"""Bounded-memory streaming battery (ISSUE 19).

The tentpole contract in unit/integration form: the memory accountant
(internals/memory.py) and its watermark resolution; the pure degradation
ladder + pacing transitions (parallel/protocol.py) and the anti-drift
identity pins proving the accountant, the serving gateway, and the
pacing model checker (analysis/meshcheck.py check_pacing) all drive the
SAME table objects; synthetic ``mem.pressure`` samples; the checker
clean on the real protocol and catching the seeded ``never_resume``
mutant with a minimal replayable trace; governed in-process runs that
pace a payload firehose inside the budget with exactly-once output; the
watchdog's paced-subject exemption (both directions); the governed
``_BACKLOG_CAP`` routing; and (slow) the fault-matrix pressure cells
that replay kill-and-resume and 2->3 rescale under governance.
"""

import os
import sys
import threading
import time

import pytest

import pathway_tpu as pw
from pathway_tpu.analysis import meshcheck as mc
from pathway_tpu.internals import faults
from pathway_tpu.internals import memory as mem
from pathway_tpu.internals.monitoring import ProberStats
from pathway_tpu.parallel import protocol as proto

_SCRIPTS = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "scripts"
)
if _SCRIPTS not in sys.path:
    sys.path.insert(0, _SCRIPTS)
import fault_matrix  # noqa: E402


@pytest.fixture(autouse=True)
def _isolation():
    faults.reset()
    mem.install(None)
    yield
    faults.reset()
    mem.install(None)


MB = 1024 * 1024


# -- watermark resolution ----------------------------------------------------


def test_resolve_watermarks_disabled_and_defaults():
    assert mem.resolve_watermarks({}) == (0, 0, 0)
    assert mem.resolve_watermarks({"PATHWAY_MEM_BUDGET_MB": "0"}) == (0, 0, 0)
    assert mem.resolve_watermarks(
        {"PATHWAY_MEM_BUDGET_MB": "nonsense"}
    ) == (0, 0, 0)
    low, high, budget = mem.resolve_watermarks(
        {"PATHWAY_MEM_BUDGET_MB": "100"}
    )
    assert budget == 100 * MB
    assert high == int(budget * 0.8)
    assert low == int(budget * 0.6)


def test_resolve_watermarks_inverted_band_clamped():
    low, high, _ = mem.resolve_watermarks({
        "PATHWAY_MEM_BUDGET_MB": "10",
        "PATHWAY_MEM_LOW": "0.9",
        "PATHWAY_MEM_HIGH": "0.5",
    })
    # an inverted hysteresis band would flap forever — low clamps to high
    assert low == high == int(10 * MB * 0.5)


def test_mem_knobs_registered():
    from pathway_tpu.analysis.knobs import KNOBS

    for name in (
        "PATHWAY_MEM_BUDGET_MB", "PATHWAY_MEM_HIGH", "PATHWAY_MEM_LOW",
    ):
        assert name in KNOBS, name


# -- the pure transitions ----------------------------------------------------


def test_mem_ladder_semantics():
    step = proto.mem_ladder
    # disabled: always ok, regardless of totals
    assert step(10**12, 0, 0, 0) == "ok"
    # climbing: ok below low, hysteresis between the watermarks
    assert step(10, 60, 80, 100, prev="ok") == "ok"
    assert step(70, 60, 80, 100, prev="ok") == "ok"
    assert step(85, 60, 80, 100, prev="ok") == "pacing"
    # draining: a rung holds until the total crosses LOW, then releases
    assert step(70, 60, 80, 100, prev="pacing") == "pacing"
    assert step(59, 60, 80, 100, prev="pacing") == "ok"
    # recovery walks down one rung at a time, never teleports
    assert step(85, 60, 80, 100, prev="brownout") == "brownout"
    # over budget: brownout now, abort only after the streak
    assert step(101, 60, 80, 100, prev="pacing", over_streak=0) == "brownout"
    assert step(
        101, 60, 80, 100, prev="brownout", over_streak=3, abort_streak=4
    ) == "abort"
    # abort is sticky — only a post-restore reset clears it
    assert step(0, 60, 80, 100, prev="abort") == "abort"
    assert proto.MEM_LADDER == ("ok", "pacing", "brownout", "abort")


def test_pace_decide_and_resume_semantics():
    # ladder off ok pauses unconditionally
    assert proto.pace_decide("pacing")
    assert proto.pace_decide("brownout", 0, 0)
    assert not proto.pace_decide("ok")
    # row-bound pacing: backlog at/over the pause bound pauses
    assert proto.pace_decide("ok", backlog_rows=10, pause_rows=10)
    assert not proto.pace_decide("ok", backlog_rows=9, pause_rows=10)
    # resume needs BOTH: ladder ok and backlog drained to the bound
    assert proto.pace_resume("ok")
    assert proto.pace_resume("ok", backlog_rows=3, resume_rows=5)
    assert not proto.pace_resume("ok", backlog_rows=6, resume_rows=5)
    assert not proto.pace_resume("pacing")
    assert not proto.pace_resume("brownout", backlog_rows=0, resume_rows=5)


def test_pace_retry_after_semantics():
    # no backlog -> the default; dead drain -> the long clamp, never "now"
    assert proto.pace_retry_after(0, 5.0) == 1.0
    assert proto.pace_retry_after(10, 0.0) == 600.0
    assert proto.pace_retry_after(10, 2.0) == 5.0
    assert proto.pace_retry_after(1, 100.0) == 1.0   # clamped up
    assert proto.pace_retry_after(10**9, 1.0) == 600.0  # clamped down


# -- the accountant ----------------------------------------------------------


def _acct(budget_mb=100, **extra):
    env = {"PATHWAY_MEM_BUDGET_MB": str(budget_mb), **extra}
    return mem.MemoryAccountant(environ=env)


def test_accountant_rejects_unknown_component():
    acct = _acct()
    with pytest.raises(ValueError, match="unknown memory component"):
        acct.set_component("gpu_scratch", 123)
    for name in mem.COMPONENTS:
        acct.set_component(name, 1)
    assert acct.total() == len(mem.COMPONENTS)


def test_accountant_sample_steps_ladder_with_hysteresis():
    acct = _acct(budget_mb=100)
    assert acct.enabled
    assert acct.sample() == "ok"
    acct.set_component("connector_backlog", 85 * MB)
    assert acct.sample() == "pacing"
    # drain into the hysteresis band: the rung holds
    acct.set_component("connector_backlog", 70 * MB)
    assert acct.sample() == "pacing"
    # under low: releases
    acct.set_component("connector_backlog", 10 * MB)
    assert acct.sample() == "ok"
    assert acct.peak_bytes == 85 * MB


def test_accountant_abort_streak_and_reset():
    acct = mem.MemoryAccountant(
        environ={"PATHWAY_MEM_BUDGET_MB": "100"}, abort_streak=2
    )
    acct.set_component("store", 101 * MB)
    assert acct.sample() == "brownout"
    assert acct.sample() == "abort"
    # sticky: even a drained total stays abort
    acct.set_component("store", 0)
    assert acct.sample() == "abort"
    # the post-restore reset is the only exit
    acct.reset()
    assert acct.state == "ok"
    assert acct.sample() == "ok"


def test_accountant_disabled_never_leaves_ok():
    acct = mem.MemoryAccountant(environ={})
    assert not acct.enabled
    acct.set_component("store", 10**15)
    assert acct.sample() == "ok"


def test_synthetic_pressure_sample_via_fault_plan():
    """A mem.pressure ``raise`` rule is CAUGHT by the accountant and read
    as an at-high-watermark sample — the deterministic pressure episode
    the pacing checker's traces and fault_matrix --pressure replay."""
    acct = _acct(budget_mb=100)
    faults.install_plan({
        "seed": 7,
        "rules": [{
            "point": "mem.pressure", "phase": "sample",
            "hits": [2], "action": "raise",
        }],
    })
    try:
        assert acct.sample() == "ok"          # hit 1: clean
        assert acct.sample() == "pacing"      # hit 2: synthetic pressure
        assert acct.pressure_injections == 1
        assert acct.peak_bytes >= acct.high_bytes
    finally:
        faults.clear_plan()
    # the real total (0) is under the low watermark: the next clean
    # sample releases the episode
    assert acct.sample() == "ok"


def test_ladder_state_reads_installed_accountant():
    assert mem.ladder_state() == "ok"  # nothing installed
    acct = _acct()
    acct.state = "brownout"
    mem.install(acct)
    assert mem.ladder_state() == "brownout"
    mem.install(None)
    assert mem.ladder_state() == "ok"


def test_mem_pressure_fault_point_registered():
    assert "mem.pressure" in faults.POINTS


# -- anti-drift identity pins ------------------------------------------------


def test_engine_and_checker_bind_the_table_objects():
    """The accountant, the serving gateway's Retry-After, and the pacing
    model checker must all drive the SAME protocol objects — the
    anti-drift pin that keeps model and engine from diverging."""
    acct = _acct()
    assert acct._ladder is proto.TRANSITIONS["mem_ladder"]
    assert acct._pace_decide is proto.TRANSITIONS["pace_decide"]
    assert acct._pace_resume is proto.TRANSITIONS["pace_resume"]
    assert proto.TRANSITIONS["mem_ladder"] is proto.mem_ladder
    assert proto.TRANSITIONS["pace_decide"] is proto.pace_decide
    assert proto.TRANSITIONS["pace_resume"] is proto.pace_resume
    assert proto.TRANSITIONS["pace_retry_after"] is proto.pace_retry_after
    trans = mc.get_pace_transitions()
    assert trans.mem_ladder is proto.mem_ladder
    assert trans.pace_decide is proto.pace_decide
    assert trans.pace_resume is proto.pace_resume


def test_pace_mutants_are_named_and_unknown_rejected():
    assert "never_resume" in mc.PACE_MUTANT_NAMES
    mutant = mc.get_pace_transitions(mutate="never_resume")
    assert mutant.pace_resume is not proto.pace_resume
    with pytest.raises(ValueError):
        mc.get_pace_transitions(mutate="definitely_not_a_mutant")


# -- metrics / dashboard -----------------------------------------------------


def test_metrics_render_mem_gauges_and_paused_counters():
    stats = ProberStats()
    stats.on_ingest("firehose", 1)
    stats.set_mem_pressure(
        "pacing", 42 * MB, 80 * MB, 100 * MB,
        {"connector_backlog": 40 * MB, "store": 2 * MB}, 3,
    )
    stats.on_connector_paused("firehose")
    stats.on_connector_paced("firehose", 1.5)
    text = stats.render_openmetrics()
    assert "mem_pressure_state 1" in text  # MEM_LADDER.index("pacing")
    assert "mem_budget_bytes" in text
    assert 'mem_component_bytes{component="connector_backlog"}' in text
    assert "mem_pressure_injections_total 3" in text
    assert 'connector_paused{connector="firehose"} 1' in text
    assert "connector_paused_seconds_total" in text
    from rich.console import Console

    from pathway_tpu.internals.monitoring import render_dashboard

    console = Console(record=True, width=120)
    console.print(render_dashboard(stats))
    dash = console.export_text()
    assert "memory ladder" in dash
    assert "pacing" in dash
    stats.on_connector_resumed("firehose", 0.5)
    text2 = stats.render_openmetrics()
    assert 'connector_paused{connector="firehose"} 0' in text2


# -- the pacing model checker ------------------------------------------------


def test_pacing_checker_clean_on_real_protocol():
    report = mc.check_pacing(mc.PaceCheckConfig())
    assert report.ok, [v.kind for v in report.violations]
    assert report.complete
    assert report.states > 100
    assert report.pauses_explored > 0  # pacing actually engaged in the model


def test_pacing_checker_catches_never_resume_with_replayable_trace():
    report = mc.check_pacing(
        mc.PaceCheckConfig(mutate="never_resume")
    )
    assert not report.ok
    v = report.violations[0]
    assert v.kind == "pace-deadlock"
    assert v.trace, "minimal trace must be non-empty"
    d = v.to_dict()
    assert d["pressure"] is True
    plan = d["fault_plan"]
    assert plan and plan["rules"], "trace must be replayable as a plan"
    assert all(r["point"] == "mem.pressure" for r in plan["rules"])
    assert all(r["phase"] == "sample" for r in plan["rules"])
    # render() is the human side of the same trace
    assert "pace-deadlock" in report.render()


# -- governed in-process runs ------------------------------------------------


class _S(pw.Schema):
    k: int
    v: int
    pad: str


class _Firehose(pw.io.python.ConnectorSubject):
    """Unthrottled payload source: without pacing it queues its whole
    payload volume ahead of a slow sink."""

    def __init__(self, n, pad=4096):
        super().__init__()
        self.pos = 0
        self.n = n
        self.pad = "x" * pad

    def run(self):
        while self.pos < self.n:
            i = self.pos
            self.next(k=i, v=i * 7, pad=self.pad)
            self.pos = i + 1
            if self.pos % 8 == 0:
                self.commit()

    def snapshot_state(self):
        return dict(pos=self.pos)

    def seek(self, state):
        self.pos = state["pos"]


class _Watch:
    """Side-thread view of the installed accountant (the object outlives
    its uninstall, and injections/peak are monotonic, so nothing is
    missed)."""

    def __init__(self):
        self.held = None
        self.paced_seen = False
        self.peak = 0
        self.enabled_seen = None
        self._stop = threading.Event()
        self._t = threading.Thread(target=self._poll, daemon=True)

    def _read(self, acct):
        self.peak = max(self.peak, acct.peak_bytes)
        if acct.state != "ok":
            self.paced_seen = True

    def _poll(self):
        while not self._stop.is_set():
            acct = mem.current()
            if acct is not None:
                if self.held is None:
                    self.held = acct
                    self.enabled_seen = acct.enabled
                self._read(acct)
            time.sleep(0.002)

    def __enter__(self):
        self._t.start()
        return self

    def __exit__(self, *exc):
        self._stop.set()
        self._t.join(timeout=2)
        if self.held is not None:
            self._read(self.held)
        return False


def _paced_pipeline(n, sink_sleep_s):
    src = _Firehose(n)
    rows = pw.io.python.read(
        src, schema=_S, autocommit_duration_ms=25, name="firehose"
    )
    counts = rows.groupby(pw.this.k).reduce(
        k=pw.this.k, c=pw.reducers.count(), s=pw.reducers.sum(pw.this.v)
    )
    seen = {}

    def on_change(key, row, time_, diff):
        if sink_sleep_s:
            time.sleep(sink_sleep_s)
        if diff > 0:
            seen[row["k"]] = (row["c"], row["s"])

    pw.io.subscribe(counts, on_change=on_change)
    return seen


def test_governed_run_paces_firehose_inside_budget(monkeypatch):
    """The end-to-end tentpole in-process: a 1 MB budget against ~1.3 MB
    of payload traffic and a slow sink — pacing must engage, the
    accounted peak must stay under budget, and delivery must remain
    exactly-once (zero drops, zero degradations)."""
    monkeypatch.setenv("PATHWAY_MEM_BUDGET_MB", "1")
    n = 300
    seen = _paced_pipeline(n, sink_sleep_s=0.002)
    log_rows = []
    pw.io.subscribe(
        pw.global_error_log(),
        on_change=lambda key, row, t, diff: log_rows.append(row["message"]),
    )
    with _Watch() as watch:
        pw.run()
    assert watch.enabled_seen is True
    assert watch.paced_seen, "pacing never engaged"
    assert watch.peak < MB, f"accounted peak {watch.peak} breached budget"
    assert seen == {k: (1, k * 7) for k in range(n)}
    # governed pacing, NOT the at-least-once escape
    assert not any("at-least-once" in m for m in log_rows)
    # the accountant was retired with the run
    assert mem.current() is None


def test_ungoverned_run_is_legacy(monkeypatch):
    monkeypatch.delenv("PATHWAY_MEM_BUDGET_MB", raising=False)
    n = 60
    # a small sink delay keeps the run up long enough for the watcher to
    # observe the installed-but-disabled accountant deterministically
    seen = _paced_pipeline(n, sink_sleep_s=0.002)
    with _Watch() as watch:
        pw.run()
    # an accountant installs but stays disabled: ladder pinned at ok
    assert watch.enabled_seen is False
    assert not watch.paced_seen
    assert seen == {k: (1, k * 7) for k in range(n)}


# -- watchdog x pacing (both directions) -------------------------------------


class _WatchedFirehose(_Firehose):
    _watchdog_timeout_s = 0.2


def test_watchdog_exempts_paced_subject(monkeypatch):
    """A subject parked by the governor is NOT stalled: its paced waits
    must never trip the watchdog even when the pause outlives the
    watchdog window."""
    monkeypatch.setenv("PATHWAY_MEM_BUDGET_MB", "1")
    n = 300
    src = _WatchedFirehose(n)
    rows = pw.io.python.read(
        src, schema=_S, autocommit_duration_ms=25, name="watched"
    )
    got = []
    pw.io.subscribe(
        rows,
        on_change=lambda key, row, t, diff: (
            time.sleep(0.002), got.append(row["k"]),
        ),
    )
    log_rows = []
    pw.io.subscribe(
        pw.global_error_log(),
        on_change=lambda key, row, t, diff: log_rows.append(row["message"]),
    )
    with _Watch() as watch:
        pw.run()
    assert watch.paced_seen, "pacing never engaged — vacuous exemption test"
    assert sorted(got) == list(range(n))
    assert not any("connector-stall" in m for m in log_rows), log_rows


class _SleepySrc(pw.io.python.ConnectorSubject):
    _watchdog_timeout_s = 0.15

    def run(self):
        time.sleep(0.7)
        self.next(k=1, v=7, pad="x")


def test_watchdog_still_trips_for_genuine_stall_under_governance(
    monkeypatch,
):
    """The exemption is scoped to PAUSED subjects: under an ample budget
    (never paces) a genuinely silent subject must still be flagged."""
    monkeypatch.setenv("PATHWAY_MEM_BUDGET_MB", "512")
    src = _SleepySrc()
    rows = pw.io.python.read(
        src, schema=_S, autocommit_duration_ms=10, name="sleepy"
    )
    got = []
    pw.io.subscribe(
        rows, on_change=lambda key, row, t, diff: got.append(row["k"])
    )
    log_rows = []
    pw.io.subscribe(
        pw.global_error_log(),
        on_change=lambda key, row, t, diff: log_rows.append(row["message"]),
    )
    pw.run()
    assert got == [1]
    assert any("connector-stall" in m for m in log_rows)


# -- governed _BACKLOG_CAP routing -------------------------------------------


class _NoCommitSrc(pw.io.python.ConnectorSubject):
    """Never calls commit(): non-paceable in the only sense that matters
    (pausing it could never resume) — the cap stays its escape."""

    def __init__(self, n=10):
        super().__init__()
        self.n = n

    def run(self):
        for i in range(self.n):
            self.next(k=i, v=i, pad="x")

    def snapshot_state(self):
        return {}


class _BoundaryThenBurstSrc(pw.io.python.ConnectorSubject):
    """Shows ONE commit boundary, then bursts far past the (tiny) cap:
    a paceable subject whose overload must route through pacing, never
    the at-least-once escape."""

    def __init__(self, n=32):
        super().__init__()
        self.n = n

    def run(self):
        self.next(k=0, v=0, pad="x")
        self.commit()
        for i in range(1, self.n):
            self.next(k=i, v=i, pad="x")

    def snapshot_state(self):
        return {}


def test_backlog_cap_escape_only_for_never_committing_subjects(
    monkeypatch, tmp_path,
):
    """Governed + committing: overload routes through pacing, never the
    at-least-once escape. Governed + never-committing: the cap remains
    the bounded-memory escape, error-logged and counted."""
    monkeypatch.setenv("PATHWAY_MEM_BUDGET_MB", "64")
    monkeypatch.setattr("pathway_tpu.io._connector._BACKLOG_CAP", 3)

    def run_one(src, name):
        pw.internals.parse_graph.G.clear()
        rows = pw.io.python.read(
            src, schema=_S, autocommit_duration_ms=0, name=name
        )
        pw.io.subscribe(rows, on_change=lambda *a: None)
        log_rows = []
        pw.io.subscribe(
            pw.global_error_log(),
            on_change=lambda key, row, t, diff: (
                log_rows.append(row["message"])
            ),
        )
        pw.run(
            persistence_config=pw.persistence.Config(
                backend=pw.persistence.Backend.filesystem(
                    str(tmp_path / name)
                ),
                snapshot_interval_ms=0,
            )
        )
        return log_rows

    # a subject with a proven boundary, far over the (tiny) cap: NO
    # degradation — overload routes through pacing
    log_rows = run_one(_BoundaryThenBurstSrc(32), "committing")
    assert not any("at-least-once" in m for m in log_rows), log_rows
    # a never-committing subject: the escape fires, loudly
    log_rows = run_one(_NoCommitSrc(32), "nocommit")
    assert any(
        "degrades to at-least-once" in m for m in log_rows
    ), log_rows


# -- fault-matrix pressure cells (subprocess; slow) --------------------------


@pytest.mark.slow
def test_pressure_cell_kill_and_resume_under_injection():
    """The never_resume-trace shape as a real cell: killed inside the
    sampler, resumed, spiked after resume — exactly-once throughout."""
    res = fault_matrix.run_pressure_cell(
        "inject", crash_hit=1, raise_hits=(1,), timeout=180
    )
    assert res.ok, res.detail


@pytest.mark.slow
def test_pace_mutant_trace_replays_green_as_real_cell(tmp_path):
    """The checker-to-matrix bridge: the never_resume counterexample's
    JSON replays through fault_matrix --from-trace as a live governed
    kill-and-resume cell and comes back green."""
    report = mc.check_pacing(mc.PaceCheckConfig(mutate="never_resume"))
    assert not report.ok
    path = tmp_path / "pace_trace.json"
    path.write_text(report.to_json())
    results = fault_matrix.run_trace_cells(str(path), timeout=240)
    assert results, "trace produced no replay cells"
    assert all(r.ok for r in results), [r.detail for r in results]


@pytest.mark.slow
def test_governed_rescale_2_to_3_stays_exactly_once(monkeypatch):
    """Pacing state is derived fresh per run, so a governed 2->3 rescale
    (kill mid-re-shard) must restore and finish bit-identical — the
    governance plumbing adds no new rescale state to lose."""
    monkeypatch.setenv("PATHWAY_MEM_BUDGET_MB", "64")
    res = fault_matrix.run_rescale_cell(
        "grow", 2, 3, kill_phase="restore", victim=1, hit=1, timeout=300
    )
    assert res.ok, res.detail


@pytest.mark.slow
def test_pressure_budget_cell_real_backlog():
    res = fault_matrix.run_pressure_cell("budget", timeout=180)
    assert res.ok, res.detail
