"""Native min/max reducers (native/exec.cpp C_MIN/C_MAX).

Unlike count/sum/avg these are non-abelian: the C++ store keeps an
ordered value multiset per group (retraction-correct) plus the joint
row multiset, so demoting to the Python path mid-stream rebuilds the
exact args-combo multiset the full reducers read. Pinned here:
engagement, streamed-vs-batch, native-vs-python, retraction of the
current extremum, string ordering, demotion, and snapshot roundtrip.
"""

import random

import pytest

import pathway_tpu as pw
from pathway_tpu.engine import nodes as N
from pathway_tpu.internals.graph_runner import GraphRunner


class _S(pw.Schema):
    k: int = pw.column_definition(primary_key=True)
    g: int
    v: int


class _OpsSubject(pw.io.python.ConnectorSubject):
    def __init__(self, commits):
        super().__init__()
        self.commits = commits

    def run(self):
        for commit in self.commits:
            for kind, row in commit:
                (self.next if kind == "upsert" else self.remove)(**row)
            self.commit()


def _random_ops(rng, n_keys=12, n_ops=80):
    live, ops, commit = {}, [], []
    for _ in range(n_ops):
        k = rng.randrange(n_keys)
        if k in live and rng.random() < 0.4:
            commit.append(("remove", live.pop(k)))
        else:
            if k in live:
                commit.append(("remove", live.pop(k)))
            row = {"k": k, "g": rng.randrange(3), "v": rng.randrange(50)}
            live[k] = row
            commit.append(("upsert", row))
        if rng.random() < 0.3:
            ops.append(commit)
            commit = []
    if commit:
        ops.append(commit)
    return ops, live


def _pipeline(t):
    return t.groupby(pw.this.g).reduce(
        g=pw.this.g,
        mn=pw.reducers.min(pw.this.v),
        mx=pw.reducers.max(pw.this.v),
        c=pw.reducers.count(),
        s=pw.reducers.sum(pw.this.v),
    )


def _state(capture):
    return sorted(tuple(r) for r in capture.state.rows.values())


def _run_streamed(commits):
    t = pw.io.python.read(
        _OpsSubject(commits), schema=_S, autocommit_duration_ms=None
    )
    return _state(GraphRunner().run_tables(_pipeline(t))[0])


def _run_batch(final_rows):
    pw.internals.parse_graph.G.clear()
    if final_rows:
        t = pw.debug.table_from_markdown(
            "\n".join(
                ["k | g | v"]
                + [f"{r['k']} | {r['g']} | {r['v']}" for r in final_rows.values()]
            ),
            schema=_S,
        )
    else:
        t = pw.Table.empty(k=int, g=int, v=int)
    return _state(GraphRunner().run_tables(_pipeline(t))[0])


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_minmax_streamed_matches_batch(seed):
    rng = random.Random(seed)
    commits, final = _random_ops(rng)
    assert _run_streamed(commits) == _run_batch(final)


def test_minmax_native_engaged_and_matches_python(monkeypatch):
    from pathway_tpu.native import get_pwexec

    if get_pwexec() is None:
        pytest.skip("no native toolchain")
    engaged = []
    orig = N.GroupByNode.process

    def spy(self, time, batches):
        out = orig(self, time, batches)
        engaged.append(self._store is not None)
        return out

    monkeypatch.setattr(N.GroupByNode, "process", spy)
    rng = random.Random(9)
    commits, _ = _random_ops(rng)
    native = _run_streamed(commits)
    assert engaged and all(engaged)
    monkeypatch.undo()

    pw.internals.parse_graph.G.clear()
    monkeypatch.setattr(N.GroupByNode, "_native_setup", lambda self: False)
    python = _run_streamed(commits)
    assert native == python


def test_min_retraction_of_current_extremum():
    """Retracting the minimum must resurface the runner-up (the failure
    abelian approximations of min/max cannot handle)."""

    class Sub(pw.io.python.ConnectorSubject):
        def run(self):
            self.next(k=1, g=0, v=5)
            self.next(k=2, g=0, v=9)
            self.commit()
            self.next(k=3, g=0, v=1)
            self.commit()
            self.remove(k=3, g=0, v=1)  # retract the current min
            self.commit()

    t = pw.io.python.read(Sub(), schema=_S, autocommit_duration_ms=None)
    changes = []
    pw.io.subscribe(
        _pipeline(t),
        on_change=lambda k, row, t_, d: changes.append(
            (row["mn"], row["mx"], 1 if d else -1)
        ),
    )
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    net = {}
    for mn, mx, d in changes:
        net[(mn, mx)] = net.get((mn, mx), 0) + d
    live = [k for k, c in net.items() if c > 0]
    assert live == [(5, 9)]
    # and the transient min=1 state was observed then retracted
    assert net.get((1, 9), 0) == 0 and (1, 9, 1) in changes


def test_minmax_strings():
    t = pw.debug.table_from_markdown(
        """
        g | w
        0 | pear
        0 | apple
        1 | fig
        """
    )
    r = t.groupby(pw.this.g).reduce(
        g=pw.this.g,
        first=pw.reducers.min(pw.this.w),
        last=pw.reducers.max(pw.this.w),
    )
    cap = GraphRunner().run_tables(r)[0]
    assert sorted(tuple(r) for r in cap.state.rows.values()) == [
        (0, "apple", "pear"),
        (1, "fig", "fig"),
    ]


def test_minmax_demotion_rebuilds_multiset():
    """A late Json grouping value demotes the node; the rebuilt Python
    multiset must keep min/max exact for subsequent retractions."""

    class _JS(pw.Schema):
        k: int = pw.column_definition(primary_key=True)
        g: pw.Json
        v: int

    class Sub(pw.io.python.ConnectorSubject):
        def run(self):
            # native-served while ints... never: Json group from the start
            # would fall back immediately; instead use int-like then Json
            self.next(k=1, g=pw.Json(0), v=5)
            self.next(k=2, g=pw.Json(0), v=9)
            self.commit()
            self.remove(k=1, g=pw.Json(0), v=5)
            self.commit()

    t = pw.io.python.read(Sub(), schema=_JS, autocommit_duration_ms=None)
    r = t.groupby(pw.this.g).reduce(
        mn=pw.reducers.min(pw.this.v), mx=pw.reducers.max(pw.this.v)
    )
    cap = GraphRunner().run_tables(r)[0]
    assert [tuple(r) for r in cap.state.rows.values()] == [(9, 9)]


def test_minmax_int_demotion_midstream(monkeypatch):
    """Start native (int groups), then hit the store with a batch whose
    grouping value is unserializable — the dumped joint multiset must
    reconstruct the Python ms EXACTLY so later retractions are correct."""
    from pathway_tpu.native import get_pwexec

    if get_pwexec() is None:
        pytest.skip("no native toolchain")

    class _AS(pw.Schema):
        k: int = pw.column_definition(primary_key=True)
        g: pw.Json
        v: int

    demoted = []
    orig = N.GroupByNode._migrate_to_python

    def spy(self):
        demoted.append(True)
        return orig(self)

    monkeypatch.setattr(N.GroupByNode, "_migrate_to_python", spy)

    class Sub(pw.io.python.ConnectorSubject):
        def run(self):
            # int-keyed commits ride the native store
            self.next(k=1, g=pw.Json("a"), v=3)
            self.commit()

    # Json never reaches the native path (grouping fallback on commit 1),
    # so to exercise a REAL mid-stream demotion drive the node directly.
    import pathway_tpu.native as native_mod

    ex = native_mod.get_pwexec()
    node_cls = N.GroupByNode

    class FakeScope:
        def __init__(self):
            self.runtime = type("R", (), {"current_trace": None})()
            self.nodes = []
            self.exchange_nodes = []

        def register(self, node):
            self.nodes.append(node)
            return len(self.nodes) - 1

    from pathway_tpu.internals.api import ref_scalar

    scope = FakeScope()
    node = node_cls(
        scope,
        N.SourceNode(scope),
        lambda k, row: (row[0],),
        lambda k, row: ((row[1], k, k),),
        [("full", _min_fn(), "min")],
        native_args=[lambda keys, rows: [r[1] for r in rows]],
        grouping_batch=lambda keys, rows: [(r[0],) for r in rows],
        args_batch=lambda keys, rows: [((r[1], k, k),) for k, r in zip(keys, rows)],
    )
    k1, k2, k3 = ref_scalar(1), ref_scalar(2), ref_scalar(3)
    out = node.process(0, [[(k1, (0, 7), 1), (k2, (0, 3), 1)]])
    assert [(r, d) for _, r, d in out] == [((0, 3), 1)]
    assert node._store is not None
    # unserializable grouping value -> demotion with state intact
    out = node.process(1, [[(k3, (pw.Json(5), 1), 1)]])
    assert node._store is None and demoted
    # retract the minimum of the original group on the PYTHON path: the
    # rebuilt multiset must resurface 7
    out = node.process(2, [[(k2, (0, 3), -1)]])
    pairs = sorted((r, d) for _, r, d in out)
    assert ((0, 3), -1) in pairs and ((0, 7), 1) in pairs


def _min_fn():
    from pathway_tpu.internals.reducers import _min_factory

    return _min_factory()


def test_minmax_mixed_kinds_fall_back():
    """Python min/max raises TypeError on numeric<->string comparison;
    the native path must never answer such groups differently — a batch
    that would mix kinds Falls Back in phase 1 (review repro)."""
    from pathway_tpu.native import get_pwexec

    ex = get_pwexec()
    if ex is None:
        pytest.skip("no native toolchain")
    from pathway_tpu.internals.api import ERROR, ref_scalar

    s = ex.store_new(2, ("min",))
    key_fn = lambda g: ref_scalar(*g)
    with pytest.raises(ex.Fallback):
        ex.process_batch(
            s, [("g",), ("g",)], [1, 2], ([1, "a"],), [1, 1], key_fn, ERROR
        )
    # cross-batch mixing too: numeric batch first, string batch second
    s2 = ex.store_new(2, ("min",))
    ex.process_batch(s2, [("g",)], [1], ([1],), [1], key_fn, ERROR)
    with pytest.raises(ex.Fallback):
        ex.process_batch(s2, [("h",)], [2], (["a"],), [1], key_fn, ERROR)


def test_minmax_int_float_precision_beyond_2_53():
    """int 2^53+1 vs float 2^53 must order exactly (long-double compare);
    doubles would collapse them and return the larger value (review
    repro)."""
    from pathway_tpu.native import get_pwexec

    ex = get_pwexec()
    if ex is None:
        pytest.skip("no native toolchain")
    from pathway_tpu.internals.api import ERROR, ref_scalar

    s = ex.store_new(2, ("min", "max"))
    key_fn = lambda g: ref_scalar(*g)
    big = 2**53 + 1
    out = ex.process_batch(
        s, [("g",), ("g",)], [1, 2],
        ([big, float(2**53)], [big, float(2**53)]), [1, 1], key_fn, ERROR,
    )
    row = out[-1][1]
    assert row[1] == float(2**53) and isinstance(row[1], float)
    assert row[2] == big and isinstance(row[2], int)


def test_minmax_operator_snapshot_roundtrip(tmp_path):
    """OPERATOR_PERSISTING kill/restart with a min/max groupby: the
    native store's dump must restore both the ordered state and the
    joint multiset."""
    from pathway_tpu.native import get_pwexec

    if get_pwexec() is None:
        pytest.skip("no native toolchain")
    ex = get_pwexec()
    key_fn = lambda g: g[0]
    s = ex.store_new(2, ("min", "max"))
    from pathway_tpu.internals.api import ERROR

    ex.process_batch(
        s, [("g",), ("g",), ("h",)], [101, 102, 103],
        ([4, 9, 5], [4, 9, 5]), [1, 1, 1], key_fn, ERROR,
    )
    dumped = ex.store_dump(s)
    s2 = ex.store_new(2, ("min", "max"))
    ex.store_load(s2, dumped, ERROR)
    # retract the min on the restored store: runner-up surfaces
    out = ex.process_batch(
        s2, [("g",)], [101], ([4], [4]), [-1], key_fn, ERROR
    )
    emitted = sorted((r, d) for _, r, d in out)
    assert emitted == [(("g", 4, 9), -1), (("g", 9, 9), 1)]
