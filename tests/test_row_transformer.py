"""Row transformer (legacy class API) + viz tests (reference pattern:
python/pathway/tests/test_row_transformer.py)."""

import pathway_tpu as pw
from pathway_tpu.internals.graph_runner import GraphRunner


def _rows(table):
    captures = GraphRunner().run_tables(table)
    return sorted(captures[0].state.rows.values(), key=repr)


def test_transformer_computed_attribute():
    @pw.transformer
    class doubler:
        class numbers:
            val = pw.input_attribute()

            @pw.output_attribute
            def doubled(self) -> int:
                return self.val * 2

            @pw.output_attribute
            def plus_one(self) -> int:
                return self.val + 1

    t = pw.debug.table_from_markdown(
        """
        val
        1
        2
        """
    )
    out = doubler(numbers=t).numbers
    assert _rows(out) == [(2, 2), (4, 3)]


def test_transformer_pointer_chasing():
    @pw.transformer
    class follower:
        class sources:
            target = pw.input_attribute()

            @pw.output_attribute
            def target_val(self):
                return self.transformer().values[self.target].v

        class values:
            v = pw.input_attribute()

    values = pw.debug.table_from_markdown(
        """
        v
        10
        20
        """
    )
    keys = list(
        GraphRunner().run_tables(values)[0].state.rows.keys()
    )
    sources = pw.debug.table_from_markdown(
        """
        i
        0
        1
        """
    )
    sources = sources.select(
        target=pw.apply_with_type(lambda i: keys[i], pw.Pointer, pw.this.i)
    )
    out = follower(sources=sources, values=values).sources
    got = sorted(r[0] for r in _rows(out))
    assert got == [10, 20]


def test_viz_table_to_pandas():
    from pathway_tpu.stdlib.viz import table_to_pandas

    t = pw.debug.table_from_markdown(
        """
        a | b
        1 | x
        2 | y
        """
    )
    df = table_to_pandas(t)
    assert sorted(df["a"].tolist()) == [1, 2]
    assert set(df.columns) == {"a", "b"}
