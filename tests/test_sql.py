"""pw.sql tests (reference pattern: python/pathway/tests/test_sql.py)."""

import pathway_tpu as pw
from pathway_tpu.internals.graph_runner import GraphRunner


def _rows(table):
    captures = GraphRunner().run_tables(table)
    return sorted(captures[0].state.rows.values(), key=repr)


def _t():
    return pw.debug.table_from_markdown(
        """
        a | b
        1 | 10
        2 | 20
        3 | 30
        """
    )


def test_sql_select_where():
    res = pw.sql("SELECT a, b FROM tab WHERE a > 1", tab=_t())
    assert _rows(res) == [(2, 20), (3, 30)]


def test_sql_select_star_and_exprs():
    res = pw.sql("SELECT *, a + b AS s FROM tab", tab=_t())
    assert _rows(res) == [(1, 10, 11), (2, 20, 22), (3, 30, 33)]


def test_sql_group_by():
    t = pw.debug.table_from_markdown(
        """
        k | v
        x | 1
        x | 2
        y | 5
        """
    )
    res = pw.sql(
        "SELECT k, SUM(v) AS total, COUNT(*) AS c FROM t GROUP BY k", t=t
    )
    assert _rows(res) == [("x", 3, 2), ("y", 5, 1)]


def test_sql_having():
    t = pw.debug.table_from_markdown(
        """
        k | v
        x | 1
        x | 2
        y | 5
        """
    )
    res = pw.sql(
        "SELECT k, SUM(v) AS total FROM t GROUP BY k HAVING SUM(v) > 4", t=t
    )
    assert _rows(res) == [("y", 5)]


def test_sql_join():
    left = pw.debug.table_from_markdown(
        """
        k | v
        1 | a
        2 | b
        """
    )
    right = pw.debug.table_from_markdown(
        """
        k2 | w
        1  | x
        2  | y
        """
    )
    res = pw.sql(
        "SELECT v, w FROM l JOIN r ON l.k = r.k2", l=left, r=right
    )
    assert _rows(res) == [("a", "x"), ("b", "y")]


def test_sql_union_all():
    res = pw.sql(
        "SELECT a FROM t WHERE a = 1 UNION ALL SELECT a FROM t WHERE a = 3",
        t=_t(),
    )
    assert _rows(res) == [(1,), (3,)]
