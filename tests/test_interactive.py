"""Interactive live-REPL mode (reference:
python/pathway/internals/interactive.py:222 — background run + live table
inspection, including tables first inspected AFTER the run started)."""

import subprocess
import sys
import textwrap

_PROG = textwrap.dedent(
    """
    import sys, time
    sys.path.insert(0, {repo!r})
    import jax; jax.config.update("jax_platforms", "cpu")
    import pathway_tpu as pw

    pw.enable_interactive_mode()

    class Src(pw.io.python.ConnectorSubject):
        _deletions_enabled = False
        def run(self):
            for i in range(5):
                self.next(v=i)
                self.commit()
                time.sleep(0.05)
            time.sleep(3)

    class S(pw.Schema):
        v: int

    t = pw.io.python.read(Src(), schema=S, autocommit_duration_ms=None)
    agg = t.reduce(s=pw.reducers.sum(pw.this.v))

    pre = pw.live(t)       # registered before the run
    pw.run()               # interactive: returns immediately
    time.sleep(1.0)
    post = pw.live(agg)    # attached AFTER the run started
    time.sleep(1.0)
    rows = post.snapshot()
    assert rows and rows[0]["s"] == 10, rows
    assert len(pre.snapshot()) == 5, pre.snapshot()
    assert "s" in repr(post)
    # unreachable-at-launch tables are a clear error, not a silent hang
    t2 = pw.debug.table_from_markdown("x\\n1")
    try:
        pw.live(t2)
    except RuntimeError as e:
        assert "fixed at launch" in str(e)
    else:
        raise AssertionError("expected RuntimeError for late table")
    print("INTERACTIVE_OK")
    """
)


def test_interactive_live_views(tmp_path):
    import os

    script = tmp_path / "prog.py"
    script.write_text(_PROG.format(repo=os.getcwd()))
    r = subprocess.run(
        [sys.executable, str(script)],
        capture_output=True,
        timeout=120,
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"},
    )
    assert r.returncode == 0, r.stderr.decode()[-2000:]
    assert "INTERACTIVE_OK" in r.stdout.decode()


_RERUN_PROG = textwrap.dedent(
    """
    import sys, time
    sys.path.insert(0, {repo!r})
    import jax; jax.config.update("jax_platforms", "cpu")
    import pathway_tpu as pw

    pw.enable_interactive_mode()

    def build(values):
        class Src(pw.io.python.ConnectorSubject):
            _deletions_enabled = False
            def run(self):
                for i in values:
                    self.next(v=i)
                self.commit()

        class S(pw.Schema):
            v: int

        t = pw.io.python.read(Src(), schema=S, autocommit_duration_ms=None)
        return t.reduce(s=pw.reducers.sum(pw.this.v))

    # ---- run 1: REPL builds, runs, inspects -------------------------------
    agg = build([1, 2, 3])
    h = pw.live(agg, name="agg")      # stable name: survives reruns
    pw.run()
    pw.interactive.wait(timeout=60)
    assert h.snapshot()[0]["s"] == 6, h.snapshot()
    f1 = h.frontier()
    assert f1 > 0 and h.done()

    # ---- derived pipeline over live state (LiveTable-as-Table analog) ----
    pw.interactive.reset()
    snap = h.to_table()               # handle still serves the last run
    doubled = snap.select(d=pw.this.s * 2)
    import pathway_tpu.internals.interactive as I
    rows = pw.debug.table_to_pandas(doubled)
    assert list(rows["d"]) == [12], rows

    # ---- run 2: REPL edits the program and reruns -------------------------
    pw.interactive.reset()
    agg2 = build([10, 20])
    h2 = pw.live(agg2, name="agg")    # re-registers the stable name
    pw.run()
    pw.interactive.wait(timeout=60)
    # BOTH handles see the updated table: re-subscription across reruns
    assert h2.snapshot()[0]["s"] == 30, h2.snapshot()
    assert h.snapshot()[0]["s"] == 30, h.snapshot()
    print("RERUN_OK")
    """
)


def test_interactive_rerun_resubscription(tmp_path):
    """VERDICT r4 #9: the REPL flow — run, inspect, derive from live
    state, rebuild, rerun; handles attach to the updated tables."""
    import os

    script = tmp_path / "rerun.py"
    script.write_text(_RERUN_PROG.format(repo=os.getcwd()))
    r = subprocess.run(
        [sys.executable, str(script)],
        capture_output=True,
        timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert r.returncode == 0, r.stderr.decode()[-2000:]
    assert "RERUN_OK" in r.stdout.decode()
