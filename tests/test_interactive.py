"""Interactive live-REPL mode (reference:
python/pathway/internals/interactive.py:222 — background run + live table
inspection, including tables first inspected AFTER the run started)."""

import subprocess
import sys
import textwrap

_PROG = textwrap.dedent(
    """
    import sys, time
    sys.path.insert(0, {repo!r})
    import jax; jax.config.update("jax_platforms", "cpu")
    import pathway_tpu as pw

    pw.enable_interactive_mode()

    class Src(pw.io.python.ConnectorSubject):
        _deletions_enabled = False
        def run(self):
            for i in range(5):
                self.next(v=i)
                self.commit()
                time.sleep(0.05)
            time.sleep(3)

    class S(pw.Schema):
        v: int

    t = pw.io.python.read(Src(), schema=S, autocommit_duration_ms=None)
    agg = t.reduce(s=pw.reducers.sum(pw.this.v))

    pre = pw.live(t)       # registered before the run
    pw.run()               # interactive: returns immediately
    time.sleep(1.0)
    post = pw.live(agg)    # attached AFTER the run started
    time.sleep(1.0)
    rows = post.snapshot()
    assert rows and rows[0]["s"] == 10, rows
    assert len(pre.snapshot()) == 5, pre.snapshot()
    assert "s" in repr(post)
    # unreachable-at-launch tables are a clear error, not a silent hang
    t2 = pw.debug.table_from_markdown("x\\n1")
    try:
        pw.live(t2)
    except RuntimeError as e:
        assert "fixed at launch" in str(e)
    else:
        raise AssertionError("expected RuntimeError for late table")
    print("INTERACTIVE_OK")
    """
)


def test_interactive_live_views(tmp_path):
    import os

    script = tmp_path / "prog.py"
    script.write_text(_PROG.format(repo=os.getcwd()))
    r = subprocess.run(
        [sys.executable, str(script)],
        capture_output=True,
        timeout=120,
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"},
    )
    assert r.returncode == 0, r.stderr.decode()[-2000:]
    assert "INTERACTIVE_OK" in r.stdout.decode()
