"""Full native reducer suite (VERDICT r3 #2): tuple/sorted_tuple/unique/
any/argmin/argmax/earliest/latest + sort_by groupbys on the sharded C++
executor (native/exec.cpp), with the Fallback-to-Python escape for values
it can't represent.

Oracle: the Python affected-group rediff path must produce the identical
change stream (rows, diffs, timestamp order). Reference bar: the full
Reducer enum with semigroup fast paths, src/engine/reduce.rs:22-594.
"""

from __future__ import annotations

import pickle

import pytest

import pathway_tpu as pw
from pathway_tpu.internals.api import ERROR, ref_scalar
from pathway_tpu.native import get_pwexec

pwexec = get_pwexec()
pytestmark = pytest.mark.skipif(pwexec is None, reason="no native toolchain")


class _Spy:
    """Asserts the native executor stayed engaged (no silent demotion) —
    the VERDICT's 'assert via the executor's stats/counters' criterion."""

    def __init__(self):
        import pathway_tpu.engine.nodes as nm

        self.nm = nm
        self.demotions = 0
        self.engaged = 0

    def __enter__(self):
        nm = self.nm
        self._orig_mig = nm.GroupByNode._migrate_to_python
        self._orig_setup = nm.GroupByNode._native_setup
        spy = self

        def mig(node):
            spy.demotions += 1
            return spy._orig_mig(node)

        def setup(node):
            ok = spy._orig_setup(node)
            if ok:
                spy.engaged += 1
            return ok

        nm.GroupByNode._migrate_to_python = mig
        nm.GroupByNode._native_setup = setup
        return self

    def __exit__(self, *exc):
        self.nm.GroupByNode._migrate_to_python = self._orig_mig
        self.nm.GroupByNode._native_setup = self._orig_setup


def _force_python():
    import pathway_tpu.engine.nodes as nm

    orig = nm.GroupByNode._native_setup
    nm.GroupByNode._native_setup = lambda self: False
    return lambda: setattr(nm.GroupByNode, "_native_setup", orig)


class _KVSchema(pw.Schema):
    k: int = pw.column_definition(primary_key=True)
    g: int
    v: int
    s: str
    o: int


class _Feed(pw.io.python.ConnectorSubject):
    """Insert/upsert/retract sequence over two groups across commits."""

    def run(self):
        self.next(k=1, g=1, v=5, s="b", o=9)
        self.next(k=2, g=1, v=3, s="a", o=1)
        self.next(k=5, g=2, v=1, s="z", o=3)
        self.commit()
        self.next(k=3, g=1, v=7, s="c", o=5)
        self.next(k=4, g=2, v=2, s="y", o=2)
        self.commit()
        self.remove(k=2, g=1, v=3, s="a", o=1)
        self.next(k=1, g=1, v=6, s="bb", o=9)  # pk upsert
        self.commit()
        self.remove(k=5, g=2, v=1, s="z", o=3)
        self.remove(k=4, g=2, v=2, s="y", o=2)  # group 2 dies
        self.commit()


def _normalized_events(events):
    times = sorted({e[1] for e in events})
    tmap = {t: i for i, t in enumerate(times)}
    return [(row, tmap[t], d) for row, t, d in events]


def _run_full_suite(sort_by: bool, skip_nones: bool = False):
    pw.internals.parse_graph.G.clear()
    t = pw.io.python.read(
        _Feed(), schema=_KVSchema, autocommit_duration_ms=None
    )
    gb = (
        t.groupby(pw.this.g, sort_by=pw.this.o)
        if sort_by
        else t.groupby(pw.this.g)
    )
    r = gb.reduce(
        g=pw.this.g,
        tp=pw.reducers.tuple(pw.this.v, skip_nones=skip_nones),
        st=pw.reducers.sorted_tuple(pw.this.v, skip_nones=skip_nones),
        un=pw.reducers.unique(pw.this.g),
        an=pw.reducers.any(pw.this.s),
        am=pw.reducers.argmin(pw.this.v),
        ax=pw.reducers.argmax(pw.this.v),
        el=pw.reducers.earliest(pw.this.s),
        lt=pw.reducers.latest(pw.this.s),
        n=pw.reducers.count(),
        sm=pw.reducers.sum(pw.this.v),
    )
    events = []
    pw.io.subscribe(
        r,
        on_change=lambda key, row, time, diff: events.append(
            (tuple(sorted(row.items())), time, diff)
        ),
    )
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    return _normalized_events(events)


@pytest.mark.parametrize("sort_by", [False, True])
def test_full_suite_matches_python_change_stream(sort_by):
    with _Spy() as spy:
        native = _run_full_suite(sort_by)
    assert spy.engaged >= 1, "native executor never engaged"
    assert spy.demotions == 0, "native executor silently demoted"
    restore = _force_python()
    try:
        python = _run_full_suite(sort_by)
    finally:
        restore()
    assert native == python


def test_full_suite_under_threads_4(monkeypatch):
    from pathway_tpu.internals import config as C

    monkeypatch.setattr(C.pathway_config, "threads", 4)
    with _Spy() as spy:
        native = _run_full_suite(sort_by=True)
    assert spy.engaged >= 1 and spy.demotions == 0
    restore = _force_python()
    try:
        python = _run_full_suite(sort_by=True)
    finally:
        restore()
    assert native == python


def test_skip_nones_tuple_variants():
    """tuple/sorted_tuple skip_nones drop None contributions; the plain
    variants keep them (None sorts FIRST in sorted_tuple)."""

    class S(pw.Schema):
        g: int
        v: int | None

    def run(force_python: bool):
        pw.internals.parse_graph.G.clear()
        t = pw.debug.table_from_rows(
            S, [(1, 1, 5), (2, 1, None), (3, 1, 3), (4, 2, None)]
        )
        r = t.groupby(pw.this.g).reduce(
            g=pw.this.g,
            tp=pw.reducers.tuple(pw.this.v),
            tps=pw.reducers.tuple(pw.this.v, skip_nones=True),
            st=pw.reducers.sorted_tuple(pw.this.v),
            sts=pw.reducers.sorted_tuple(pw.this.v, skip_nones=True),
        )
        from pathway_tpu.internals.graph_runner import GraphRunner

        if force_python:
            restore = _force_python()
        try:
            cap = GraphRunner().run_tables(r)[0]
        finally:
            if force_python:
                restore()
        return sorted(tuple(row) for row in cap.state.rows.values())

    with _Spy() as spy:
        native = run(False)
    assert spy.engaged >= 1 and spy.demotions == 0
    assert native == run(True)
    by_g = {row[0]: row for row in native}
    assert by_g[1][3] == (None, 3, 5)  # sorted_tuple: None first
    assert by_g[1][4] == (3, 5)        # skip_nones
    assert by_g[2][2] == ()            # all-None group, skip_nones tuple


def test_exotic_value_demotes_with_state_intact():
    """A tuple-reducer arg the serializer can't represent (a Json-like
    nested tuple) demotes the node mid-stream; results still match the
    all-Python run."""

    class S(pw.Schema):
        k: int = pw.column_definition(primary_key=True)
        g: int
        v: pw.internals.dtype.ANY

    class Feed(pw.io.python.ConnectorSubject):
        def run(self):
            self.next(k=1, g=1, v=5)
            self.next(k=2, g=1, v=3)
            self.commit()
            self.next(k=3, g=1, v=(1, 2))  # exotic: Fallback
            self.commit()
            self.remove(k=1, g=1, v=5)
            self.commit()

    def run(force_python: bool):
        pw.internals.parse_graph.G.clear()
        t = pw.io.python.read(
            Feed(), schema=S, autocommit_duration_ms=None
        )
        r = t.groupby(pw.this.g).reduce(
            g=pw.this.g,
            tp=pw.reducers.tuple(pw.this.v),
            n=pw.reducers.count(),
        )
        events = []
        pw.io.subscribe(
            r,
            on_change=lambda key, row, time, diff: events.append(
                (tuple(sorted(row.items())), time, diff)
            ),
        )
        if force_python:
            restore = _force_python()
        try:
            pw.run(monitoring_level=pw.MonitoringLevel.NONE)
        finally:
            if force_python:
                restore()
        return _normalized_events(events)

    with _Spy() as spy:
        native = run(False)
    assert spy.engaged >= 1
    assert spy.demotions == 1  # the exotic batch demoted exactly once
    assert native == run(True)


def test_error_in_ordering_reducer_raises_like_python():
    """argmin/sorted_tuple over a column containing ERROR raise the same
    engine error on both paths (Python TypeErrors comparing ERROR; the
    native path falls back so the identical error surfaces)."""

    def run(force_python: bool):
        pw.internals.parse_graph.G.clear()
        t = pw.debug.table_from_markdown(
            """
            k | v
            1 | 5
            1 | 0
            """
        )
        t2 = t.select(k=pw.this.k, v=pw.declare_type(int, 1 // pw.this.v))
        r = t2.groupby(pw.this.k).reduce(
            k=pw.this.k, st=pw.reducers.sorted_tuple(pw.this.v)
        )
        from pathway_tpu.internals.graph_runner import GraphRunner

        if force_python:
            restore = _force_python()
        try:
            with pytest.raises(Exception, match="not supported between"):
                GraphRunner().run_tables(r)
        finally:
            if force_python:
                restore()

    run(False)
    run(True)


def test_error_value_flows_through_tuple_unique_latest():
    """Non-comparing reducers treat ERROR as a value: tuple keeps it,
    unique of a 2-class group returns ERROR, earliest/latest pick by
    arrival (matches the Python-path probe pinned in round 4)."""
    pw.internals.parse_graph.G.clear()
    t = pw.debug.table_from_markdown(
        """
        k | v
        1 | 5
        1 | 0
        2 | 3
        """
    )
    t2 = t.select(k=pw.this.k, v=pw.declare_type(int, 1 // pw.this.v))
    r = t2.groupby(pw.this.k).reduce(
        k=pw.this.k,
        tp=pw.reducers.tuple(pw.this.v),
        un=pw.reducers.unique(pw.this.v),
        el=pw.reducers.earliest(pw.this.v),
        lt=pw.reducers.latest(pw.this.v),
    )
    from pathway_tpu.internals.graph_runner import GraphRunner

    with _Spy() as spy:
        cap = GraphRunner().run_tables(r)[0]
    assert spy.demotions == 0
    rows = {row[0]: tuple(row) for row in cap.state.rows.values()}
    assert rows[2] == (2, (0,), 0, 0, 0)
    k1 = rows[1]
    assert set(k1[1]) == {0, ERROR} and k1[2] is ERROR
    assert {k1[3], k1[4]} == {0, ERROR}


def test_native_snapshot_roundtrip_full_suite():
    """Dump/load preserves multiset entries WITH stamps and sort tokens:
    a reloaded store continues the change stream identically, including
    earliest/latest rankings that predate the snapshot."""
    import pathway_tpu.engine.nodes as nodes_mod

    class FakeScope:
        def __init__(self):
            self.nodes = []
            self.runtime = type(
                "R", (), {"mark_pending": lambda *a: None,
                          "current_trace": None}
            )()

        def register(self, node):
            self.nodes.append(node)
            return len(self.nodes) - 1

    def make_node():
        scope = FakeScope()
        src = nodes_mod.SourceNode(scope)
        from pathway_tpu.internals import reducers as R

        specs = [
            R.tuple(None)._reducer.engine_spec(),
            R.earliest.engine_spec(),
            R.latest.engine_spec(),
            R.argmin.engine_spec(),
        ]
        return nodes_mod.GroupByNode(
            scope, src,
            grouping_fn=lambda k, r: (r[0],),
            args_fn=lambda k, r: ((r[1], k, k),) * 4,
            reducer_specs=specs,
            grouping_batch=lambda ks, rs: [(r[0],) for r in rs],
            args_batch=lambda ks, rs: [
                ((r[1], k, k),) * 4 for k, r in zip(ks, rs)
            ],
            native_args=[lambda ks, rs: [r[1] for r in rs]] * 4,
        )

    a = make_node()
    assert a._native_ok
    a.process(2, [[(10, ("x", 7), 1), (11, ("x", 3), 1), (12, ("y", 9), 1)]])
    a.process(4, [[(13, ("x", 5), 1)]])
    state = pickle.loads(pickle.dumps(a.state_dict()))
    assert "__native__" in state

    b = make_node()
    b.load_state(state)
    # same next batch must produce the same deltas from both stores
    batch = [[(14, ("x", 1), 1), (12, ("y", 9), -1)]]
    out_a = sorted((tuple(r), d) for _, r, d in a.process(6, batch))
    out_b = sorted((tuple(r), d) for _, r, d in b.process(6, batch))
    assert out_a == out_b
    # earliest ranks a pre-snapshot entry first: stamp survived the dump
    x_after = [r for (r, d) in out_b if d > 0 and r[0] == "x"]
    assert x_after and x_after[0][2] == 7  # earliest = first-ever insert


def test_unchanged_tuple_output_emits_nothing():
    """Fingerprint suppression: a retract+insert netting to the same
    finished tuple emits no deltas (key moves, value doesn't)."""
    s = pwexec.store_new(2, ("tuple",))
    key_fn = lambda g: ref_scalar(*g)

    def pb(gvals, keys, col, diffs):
        return pwexec.process_batch(
            s, gvals, keys, (col,), diffs, key_fn, ERROR, 2, None
        )

    out = pb([("g",)] * 2, [1, 2], [5, 5], [1, 1])
    assert len(out) == 1  # initial insert
    # row 1 leaves, row 3 arrives with the same value: ("g",(5,5)) holds
    out = pb([("g",)] * 2, [1, 3], [5, 5], [-1, 1])
    assert out == []
    # a genuinely new value does emit
    out = pb([("g",)], [4], [6], [1])
    assert len(out) == 2


def test_argmin_none_mix_falls_back_like_python():
    """argmin/argmax compare (value, key) tuples, so a group mixing None
    and numeric values raises TypeError in Python; the native path must
    fall back (None is its own kind), not answer with the None row."""

    def run(force_python: bool):
        pw.internals.parse_graph.G.clear()

        class S(pw.Schema):
            g: int
            v: int | None

        t = pw.debug.table_from_rows(S, [(1, 1, None), (2, 1, 5)])
        r = t.groupby(pw.this.g).reduce(
            g=pw.this.g, am=pw.reducers.argmin(pw.this.v)
        )
        from pathway_tpu.internals.graph_runner import GraphRunner

        if force_python:
            restore = _force_python()
        try:
            with pytest.raises(Exception, match="not supported between"):
                GraphRunner().run_tables(r)
        finally:
            if force_python:
                restore()

    run(False)
    run(True)

    # all-None groups DO order (None==None ties break by key): both paths
    # answer, identically
    def run_all_none(force_python: bool):
        pw.internals.parse_graph.G.clear()

        class S(pw.Schema):
            g: int
            v: int | None

        t = pw.debug.table_from_rows(S, [(1, 1, None), (2, 1, None)])
        r = t.groupby(pw.this.g).reduce(
            g=pw.this.g, am=pw.reducers.argmin(pw.this.v)
        )
        from pathway_tpu.internals.graph_runner import GraphRunner

        if force_python:
            restore = _force_python()
        try:
            cap = GraphRunner().run_tables(r)[0]
        finally:
            if force_python:
                restore()
        return sorted(tuple(r_) for r_ in cap.state.rows.values())

    assert run_all_none(False) == run_all_none(True)


def test_sort_by_orders_native_tuple():
    s = pwexec.store_new(2, ("tuple",), 1)
    key_fn = lambda g: ref_scalar(*g)
    out = pwexec.process_batch(
        s, [("g",)] * 3, [30, 10, 20], ([300, 100, 200],), [1, 1, 1],
        key_fn, ERROR, 2, [3, 1, 2],
    )
    assert out[-1][1] == ("g", (100, 200, 300))  # ordered by sort token
    # mixed-kind sort tokens fall back (Python's sort would TypeError)
    with pytest.raises(pwexec.Fallback):
        pwexec.process_batch(
            s, [("g",)], [40], ([400],), [1], key_fn, ERROR, 4, ["zz"],
        )
