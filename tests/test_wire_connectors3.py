"""Wire-protocol connector transports, part 3: Google Drive (Drive v3
REST over urllib), Pub/Sub (topics:publish REST), PyFilesystem
(duck-typed fs protocol). Mock services verify protocol shape; fakes
stand in for PyFilesystem objects."""

import base64
import datetime
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

import pathway_tpu as pw
from pathway_tpu.internals.graph_runner import GraphRunner


def _serve(handler_cls):
    server = ThreadingHTTPServer(("127.0.0.1", 0), handler_cls)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return server, f"http://127.0.0.1:{server.server_port}"


# ------------------------------------------------------------------ gdrive


class _MockDriveHandler(BaseHTTPRequestHandler):
    # id -> entry dict; file content in `content`
    tree: dict = {}
    auth_seen: list = []

    def log_message(self, *a):
        pass

    def _send(self, payload: bytes, ctype="application/json"):
        self.send_response(200)
        self.send_header("Content-Length", str(len(payload)))
        self.send_header("Content-Type", ctype)
        self.end_headers()
        self.wfile.write(payload)

    def do_GET(self):
        from urllib.parse import parse_qs, unquote, urlsplit

        self.auth_seen.append(self.headers.get("Authorization"))
        split = urlsplit(self.path)
        q = parse_qs(split.query)
        if split.path.endswith("/files"):
            query = q.get("q", [""])[0]
            parent = query.split("'")[1]
            files = [
                e for e in self.tree.values()
                if parent in e.get("parents", [])
            ]
            self._send(json.dumps({"files": files}).encode())
            return
        fid = unquote(split.path.rsplit("/", 1)[1])
        entry = self.tree.get(fid)
        if entry is None:
            self.send_error(404)
            return
        if q.get("alt") == ["media"]:
            self._send(entry["content"].encode(), "application/octet-stream")
        else:
            self._send(json.dumps(entry).encode())


def test_gdrive_recursive_read():
    handler = type(
        "H", (_MockDriveHandler,),
        {
            "tree": {
                "root": {"id": "root", "mimeType":
                         "application/vnd.google-apps.folder"},
                "sub": {"id": "sub", "parents": ["root"],
                        "mimeType": "application/vnd.google-apps.folder"},
                "f1": {"id": "f1", "name": "a.txt", "parents": ["root"],
                       "mimeType": "text/plain",
                       "modifiedTime": "2026-01-01T00:00:00Z",
                       "content": "hello"},
                "f2": {"id": "f2", "name": "b.pdf", "parents": ["sub"],
                       "mimeType": "application/pdf",
                       "modifiedTime": "2026-01-02T00:00:00Z",
                       "content": "world"},
            },
            "auth_seen": [],
        },
    )
    server, url = _serve(handler)
    try:
        t = pw.io.gdrive.read(
            "root", mode="static", with_metadata=True,
            _credentials="test-token", _endpoint=url,
        )
        cap = GraphRunner().run_tables(t)[0]
        rows = sorted(
            (bytes(r[0]), r[1].value["name"])
            for r in cap.state.rows.values()
        )
        assert rows == [(b"hello", "a.txt"), (b"world", "b.pdf")]
        assert all(a == "Bearer test-token" for a in handler.auth_seen)
    finally:
        server.shutdown()


def test_gdrive_name_pattern_and_size_limit():
    handler = type(
        "H", (_MockDriveHandler,),
        {
            "tree": {
                "root": {"id": "root", "mimeType":
                         "application/vnd.google-apps.folder"},
                "f1": {"id": "f1", "name": "a.txt", "parents": ["root"],
                       "mimeType": "text/plain", "size": "5",
                       "modifiedTime": "t1", "content": "hello"},
                "f2": {"id": "f2", "name": "b.pdf", "parents": ["root"],
                       "mimeType": "application/pdf", "size": "99999",
                       "modifiedTime": "t2", "content": "huge"},
            },
            "auth_seen": [],
        },
    )
    server, url = _serve(handler)
    try:
        t = pw.io.gdrive.read(
            "root", mode="static", file_name_pattern=["*.txt", "*.pdf"],
            object_size_limit=100,
            _credentials="tok", _endpoint=url,
        )
        cap = GraphRunner().run_tables(t)[0]
        assert [bytes(r[0]) for r in cap.state.rows.values()] == [b"hello"]
    finally:
        server.shutdown()


# ------------------------------------------------------------------ pubsub


class _MockPubSubHandler(BaseHTTPRequestHandler):
    published: list = []

    def log_message(self, *a):
        pass

    def do_POST(self):
        n = int(self.headers.get("Content-Length", "0"))
        body = json.loads(self.rfile.read(n))
        self.published.append((self.path, body))
        payload = json.dumps(
            {"messageIds": [str(len(self.published))]}
        ).encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)


def test_pubsub_write_rest():
    handler = type("H", (_MockPubSubHandler,), {"published": []})
    server, url = _serve(handler)
    try:
        t = pw.debug.table_from_markdown("payload\nalpha\nbeta").select(
            data=pw.apply_with_type(
                lambda s: s.encode(), bytes, pw.this.payload
            )
        )
        publisher = pw.io.pubsub.RestPublisher("tok", endpoint=url)
        pw.io.pubsub.write(t, publisher, "proj", "blobs")
        pw.run(monitoring_level=pw.MonitoringLevel.NONE)
        assert len(handler.published) == 2
        path, body = handler.published[0]
        assert path.endswith("/projects/proj/topics/blobs:publish")
        datas = sorted(
            base64.b64decode(b["messages"][0]["data"]).decode()
            for _, b in handler.published
        )
        assert datas == ["alpha", "beta"]
        attrs = handler.published[0][1]["messages"][0]["attributes"]
        assert attrs["pathway_diff"] == "1" and "pathway_time" in attrs
    finally:
        server.shutdown()


def test_pubsub_rejects_multicolumn():
    t = pw.debug.table_from_markdown("a | b\n1 | 2")
    with pytest.raises(ValueError, match="columns"):
        pw.io.pubsub.write(t, pw.io.pubsub.RestPublisher("tok"), "p", "t")


# -------------------------------------------------------------- pyfilesystem


class _FakeInfo:
    def __init__(self, name, size, modified):
        self.name = name
        self.size = size
        self.modified = modified
        self.created = modified
        self.accessed = modified
        self.user = "tester"


class _FakeFS:
    """Minimal PyFilesystem-shaped object (listdir/isdir/open/getinfo)."""

    def __init__(self, files: dict):
        self.files = dict(files)  # path -> bytes

    def listdir(self, path):
        path = path.rstrip("/") or "/"
        seen = []
        for p in self.files:
            rel = p[len(path):].lstrip("/") if p.startswith(path) else None
            if rel:
                head = rel.split("/")[0]
                if head not in seen:
                    seen.append(head)
        return seen

    def isdir(self, path):
        path = path.rstrip("/")
        return any(
            p.startswith(path + "/") and p != path for p in self.files
        )

    def open(self, path, mode="rb"):
        import io

        return io.BytesIO(self.files[path])

    def getinfo(self, path, namespaces=None):
        return _FakeInfo(
            path.rsplit("/", 1)[-1],
            len(self.files[path]),
            datetime.datetime(2026, 1, 1),
        )

    def getmodified(self, path):
        return ("m", hash(self.files[path]))


def test_pyfilesystem_read_static():
    fs = _FakeFS(
        {
            "/a.txt": b"alpha",
            "/sub/b.bin": b"beta",
            "/sub/deep/c.txt": b"gamma",
        }
    )
    t = pw.io.pyfilesystem.read(fs, mode="static", with_metadata=True)
    cap = GraphRunner().run_tables(t)[0]
    rows = sorted(
        (bytes(r[0]), r[1].value["name"]) for r in cap.state.rows.values()
    )
    assert rows == [(b"alpha", "a.txt"), (b"beta", "b.bin"),
                    (b"gamma", "c.txt")]


def test_pyfilesystem_streaming_modify_and_delete():
    """Streaming semantics: a modified file RETRACTS its old row before
    re-emitting; a deleted file retracts its actual row (review repro:
    unbalanced deltas double-counted modifications and left phantom
    rows on deletion)."""
    import time as _time

    fs = _FakeFS({"/a.txt": b"v1", "/b.txt": b"keep"})
    t = pw.io.pyfilesystem.read(
        fs, mode="streaming", refresh_interval=0.1
    )
    events = []
    pw.io.subscribe(
        t,
        on_change=lambda k, row, t_, d: events.append(
            (bytes(row["data"]), 1 if d else -1)
        ),
    )

    def mutate():
        _time.sleep(0.5)
        fs.files["/a.txt"] = b"v2"      # modify
        _time.sleep(0.5)
        del fs.files["/b.txt"]          # delete
        _time.sleep(0.5)
        # end the stream by making every subsequent scan raise stop
        subj_stop[0]()

    subj_stop = []
    orig_read = pw.io.pyfilesystem._PyFsSubject.run

    # capture the subject to stop it cleanly after mutations
    def run_spy(self):
        subj_stop.append(self.on_stop)
        orig_read(self)

    pw.io.pyfilesystem._PyFsSubject.run = run_spy
    try:
        threading.Thread(target=mutate, daemon=True).start()
        pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    finally:
        pw.io.pyfilesystem._PyFsSubject.run = orig_read

    net = {}
    for data, d in events:
        net[data] = net.get(data, 0) + d
    live = sorted(k for k, c in net.items() if c > 0)
    assert live == [b"v2"], (live, events)
    assert (b"v1", -1) in events        # modification retracted old row
    assert (b"keep", -1) in events      # deletion retracted the real row
    assert all(c == 0 for k, c in net.items() if k != b"v2")
