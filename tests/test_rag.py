"""RAG pipeline tests over mocks (reference pattern:
python/pathway/xpacks/llm/tests/test_rag.py — BaseRAGQuestionAnswerer over
IdentityMockChat + deterministic embedder)."""

import pathway_tpu as pw
from pathway_tpu.internals.graph_runner import GraphRunner
from pathway_tpu.xpacks.llm.document_store import DocumentStore
from pathway_tpu.xpacks.llm.mocks import (
    DeterministicMockEmbedder,
    IdentityMockChat,
)
from pathway_tpu.xpacks.llm.question_answering import (
    AdaptiveRAGQuestionAnswerer,
    BaseRAGQuestionAnswerer,
)
from pathway_tpu.xpacks.llm.vector_store import VectorStoreServer


def _answered(table):
    captures = GraphRunner().run_tables(table)
    seen = set()
    out = []
    for key, row, _, d in captures[0].updates:
        if d > 0 and key not in seen:
            seen.add(key)
            out.append(row)
    return out


def _docs_source():
    t = pw.debug.table_from_markdown(
        """
        data | meta
        pathway is a streaming framework | a.txt
        the cat sat on the mat | b.txt
        """
    )
    return t.select(
        data=pw.this.data,
        _metadata=pw.apply_with_type(
            lambda p: pw.Json({"path": p, "modified_at": 1, "seen_at": 2}),
            pw.Json,
            pw.this.meta,
        ),
    )


def _answerer(cls=BaseRAGQuestionAnswerer, **kwargs):
    server = VectorStoreServer(
        _docs_source(), embedder=DeterministicMockEmbedder(dimension=12)
    )
    return cls(llm=IdentityMockChat(), indexer=server, **kwargs)


def test_base_rag_answer_query():
    rag = _answerer(search_topk=1)
    queries = pw.debug.table_from_markdown(
        """
        prompt
        the cat sat on the mat
        """,
        schema=BaseRAGQuestionAnswerer.AnswerQuerySchema,
    )
    res = rag.answer_query(queries)
    rows = _answered(res)
    assert len(rows) == 1
    response = rows[0][0].value["response"]
    # IdentityMockChat echoes "model,prompt"; prompt embeds the doc text
    assert response.startswith("mock,")
    assert "the cat sat on the mat" in response


def test_base_rag_summarize():
    rag = _answerer()
    queries = pw.debug.table_from_markdown(
        """
        q
        1
        """
    ).select(
        text_list=pw.apply_with_type(
            lambda q: pw.Json(["text one", "text two"]), pw.Json, pw.this.q
        )
    )
    res = rag.summarize_query(queries)
    rows = _answered(res)
    assert "text one" in rows[0][0] and "text two" in rows[0][0]


def test_adaptive_rag_answers():
    rag = _answerer(
        cls=AdaptiveRAGQuestionAnswerer,
        n_starting_documents=1,
        factor=2,
        max_iterations=2,
    )
    queries = pw.debug.table_from_markdown(
        """
        prompt
        pathway is a streaming framework
        """,
        schema=BaseRAGQuestionAnswerer.AnswerQuerySchema,
    )
    res = rag.answer_query(queries)
    rows = _answered(res)
    assert len(rows) == 1
    assert rows[0][0].value["response"].startswith("mock,")


def test_document_store_bm25():
    from pathway_tpu.stdlib.indexing import TantivyBM25Factory

    store = DocumentStore(
        _docs_source(), retriever_factory=TantivyBM25Factory()
    )
    queries = pw.debug.table_from_markdown(
        """
        query | k
        streaming framework | 1
        """,
        schema=DocumentStore.RetrieveQuerySchema,
    )
    res = store.retrieve_query(queries)
    rows = _answered(res)
    results = rows[0][0].value
    assert len(results) == 1
    assert "pathway" in results[0]["text"]
