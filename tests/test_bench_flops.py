"""Pin the bench's FLOP model against XLA's own cost analysis.

The MFU numbers in bench.py are only auditable if the analytic
forward_flops_per_token formula tracks what the compiled executable
actually computes. XLA's cost_analysis() reports the compiled HLO's flop
count; the analytic model must agree within a tolerance that covers the
bits the model deliberately omits (embeddings, layernorms, masking) and
XLA's own fusion accounting quirks.
"""

from __future__ import annotations

import numpy as np
import pytest


def test_flops_model_matches_xla_cost_analysis(monkeypatch):
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    import jax
    import jax.numpy as jnp

    from pathway_tpu.models.encoder import (
        EncoderConfig,
        SentenceEncoder,
        forward_flops_per_token,
    )

    cfg = EncoderConfig.tiny()
    enc = SentenceEncoder(cfg, batch_size=8)
    n, L = 8, 64
    ids = jnp.zeros((n, L), jnp.int32)
    mask = jnp.ones((n, L), jnp.int32)
    compiled = (
        jax.jit(lambda i, m: enc._forward(enc.params, i, m))
        .lower(ids, mask)
        .compile()
    )
    cost = compiled.cost_analysis()
    if isinstance(cost, list):  # older jax returns one entry per device
        cost = cost[0]
    xla_flops = float(cost.get("flops", 0.0))
    assert xla_flops > 0, "cost_analysis returned no flops"
    model = forward_flops_per_token(cfg, L) * n * L
    # the analytic model counts matmul cores only; XLA adds elementwise
    # ops and may fold masking — agree within 25%
    assert model == pytest.approx(xla_flops, rel=0.25), (
        model,
        xla_flops,
    )


def test_flops_model_scales_with_geometry():
    from pathway_tpu.models.encoder import (
        EncoderConfig,
        forward_flops_per_token,
    )

    small = forward_flops_per_token(EncoderConfig.bge_small(), 128)
    base = forward_flops_per_token(EncoderConfig.bge_base(), 128)
    # bge-base doubles hidden and mlp: projection terms 4x, attention 2x
    assert 3.0 < base / small < 4.5
    # longer sequences only grow the attention term
    longer = forward_flops_per_token(EncoderConfig.bge_small(), 512)
    assert small < longer < 1.5 * small
