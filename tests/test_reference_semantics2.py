"""Second reference-semantics battery: Json, schemas, dtypes, universes,
outer temporal joins, misc table ops."""

import numpy as np
import pytest

import pathway_tpu as pw
from utils import T, run_table


def _rows(t):
    return sorted(run_table(t).values(), key=repr)


def test_json_navigation():
    t = T("k\n1").select(
        j=pw.apply_with_type(
            lambda k: pw.Json({"a": {"b": [1, 2, 3]}, "s": "x"}),
            pw.Json,
            pw.this.k,
        )
    )
    res = t.select(
        b1=t.j.get("a").get("b").get(1),
        s=t.j.get("s"),
        missing=t.j.get("nope", default=42),
    )
    [(b1, s, missing)] = _rows(res)
    assert getattr(b1, "value", b1) == 2
    assert getattr(s, "value", s) == "x"
    assert getattr(missing, "value", missing) == 42


def test_json_as_conversions():
    t = T("k\n1").select(
        j=pw.apply_with_type(
            lambda k: pw.Json({"n": 7, "f": 2.5, "b": True}), pw.Json, pw.this.k
        )
    )
    res = t.select(
        n=t.j.get("n").as_int(),
        f=t.j.get("f").as_float(),
        b=t.j.get("b").as_bool(),
    )
    assert _rows(res) == [(7, 2.5, True)]


def test_schema_defaults_and_primary_key():
    class S(pw.Schema):
        k: int = pw.column_definition(primary_key=True)
        v: str = pw.column_definition(default_value="missing")

    t = pw.debug.table_from_markdown("k\n1\n2", schema=S)
    assert _rows(t.select(pw.this.v)) == [("missing",), ("missing",)]
    # primary-keyed rows share ids across equal markdown inputs
    t2 = pw.debug.table_from_markdown("k\n1\n2", schema=S)
    assert set(run_table(t)) == set(run_table(t2))


def test_schema_from_types_and_builder():
    s1 = pw.schema_from_types(a=int, b=str)
    assert s1.column_names() == ["a", "b"]
    s2 = pw.schema_builder(
        {
            "x": pw.column_definition(dtype=float),
            "y": pw.column_definition(dtype=int, primary_key=True),
        }
    )
    assert s2.primary_key_columns() == ["y"]


def test_deduplicate_acceptor():
    t = T("v\n5\n3\n9\n7")
    res = t.deduplicate(value=pw.this.v, acceptor=lambda new, cur: new > cur)
    assert [r[0] for r in _rows(res)] == [9]


def test_interval_join_outer_pads_both_sides():
    a = T("t\n1\n100")
    b = T("t | v\n2 | 7\n200 | 8")
    res = pw.temporal.interval_join_outer(
        a, b, a.t, b.t, pw.temporal.interval(-3, 3)
    ).select(lt=a.t, rv=b.v)
    assert _rows(res) == [(1, 7), (100, None), (None, 8)]


def test_window_join_left():
    a = T("t | x\n1 | p\n11 | q")
    b = T("t | y\n2 | z")
    res = pw.temporal.window_join_left(
        a, b, a.t, b.t, pw.temporal.tumbling(duration=5)
    ).select(x=a.x, y=b.y)
    assert _rows(res) == [("p", "z"), ("q", None)]


def test_from_columns_and_having():
    a = T("x\n1\n2")
    packed = pw.Table.from_columns(u=a.x, w=a.x * 10)
    assert _rows(packed) == [(1, 10), (2, 20)]

    keyed = a.with_id(a.pointer_from(a.x))
    # _having keeps rows of `keyed` whose id appears in the indexer column
    p = T("v\n2")
    picker = p.select(ptr=p.pointer_from(p.v))
    res = keyed._having(picker.ptr)
    assert _rows(res) == [(2,)]


def test_restrict_and_with_universe_of():
    base = T("k | v\n1 | a\n2 | b")
    base = base.with_id(base.pointer_from(base.k))
    sub = T("k\n1")
    sub = sub.with_id(sub.pointer_from(sub.k))
    pw.universes.promise_is_subset_of(sub, base)
    res = base.restrict(sub)
    assert _rows(res.select(pw.this.v)) == [("a",)]


def test_split_expression():
    t = T("v\n1\n5\n9")
    big, small = t.split(pw.this.v > 4)
    assert sorted(r[0] for r in _rows(big)) == [5, 9]
    assert sorted(r[0] for r in _rows(small)) == [1]


def test_cast_and_parse_strings():
    t = T("s | n\n12 | 3")
    res = t.select(
        i=t.s.str.parse_int(),
        f=pw.cast(float, t.n),
    )
    assert _rows(res) == [(12, 3.0)]


def test_ndarray_column_flow():
    t = T("k\n1\n2")
    res = t.select(
        arr=pw.apply_with_type(
            lambda k: np.ones(3) * k, np.ndarray, pw.this.k
        )
    )
    out = res.select(s=pw.apply_with_type(lambda a: float(a.sum()), float, res.arr))
    assert _rows(out) == [(3.0,), (6.0,)]


def test_groupby_instance_kwarg():
    t = T("g | i | v\na | 1 | 10\na | 2 | 20\nb | 1 | 30")
    res = t.groupby(t.g, instance=t.i).reduce(
        t.g, s=pw.reducers.sum(t.v)
    )
    assert _rows(res) == [("a", 10), ("a", 20), ("b", 30)]


def test_empty_table_ops():
    e = pw.Table.empty(a=int, b=str)
    agg = e.reduce(c=pw.reducers.count())
    res = _rows(agg)
    assert res == [] or res == [(0,)]
