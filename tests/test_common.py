"""Core DSL semantics (modelled on reference python/pathway/tests/test_common.py)."""

import pytest

import pathway_tpu as pw
from tests.utils import (
    T,
    assert_table_equality,
    assert_table_equality_wo_index,
    run_table,
)


def test_select_column():
    t = T(
        """
        | a | b
      1 | 1 | 2
      2 | 3 | 4
        """
    )
    res = t.select(c=t.a + t.b)
    expected = T(
        """
        | c
      1 | 3
      2 | 7
        """
    )
    assert_table_equality(res, expected)


def test_select_this():
    t = T(
        """
        | a  | b
      1 | 10 | 2
      2 | 30 | 4
        """
    )
    res = t.select(pw.this.a, doubled=pw.this.b * 2)
    expected = T(
        """
        | a  | doubled
      1 | 10 | 4
      2 | 30 | 8
        """
    )
    assert_table_equality(res, expected)


def test_with_columns():
    t = T(
        """
        | a | b
      1 | 1 | 2
        """
    )
    res = t.with_columns(c=pw.this.a * 100)
    expected = T(
        """
        | a | b | c
      1 | 1 | 2 | 100
        """
    )
    assert_table_equality(res, expected)


def test_filter():
    t = T(
        """
        | v
      1 | 1
      2 | 5
      3 | 10
        """
    )
    res = t.filter(t.v > 4)
    expected = T(
        """
        | v
      2 | 5
      3 | 10
        """
    )
    assert_table_equality(res, expected)


def test_filter_expressions():
    t = T(
        """
        | a | b
      1 | 1 | x
      2 | 2 | y
      3 | 3 | x
        """
    )
    res = t.filter((pw.this.b == "x") & (pw.this.a < 3))
    assert list(run_table(res).values()) == [(1, "x")]


def test_arithmetic():
    t = T(
        """
        | a | b
      1 | 7 | 2
        """
    )
    res = t.select(
        add=t.a + t.b,
        sub=t.a - t.b,
        mul=t.a * t.b,
        div=t.a / t.b,
        floordiv=t.a // t.b,
        mod=t.a % t.b,
        pow=t.a**t.b,
        neg=-t.a,
    )
    rows = list(run_table(res).values())
    assert rows == [(9, 5, 14, 3.5, 3, 1, 49, -7)]


def test_comparisons_and_bool():
    t = T(
        """
        | a | b
      1 | 1 | 2
      2 | 3 | 3
        """
    )
    res = t.select(
        lt=t.a < t.b,
        le=t.a <= t.b,
        eq=t.a == t.b,
        ne=t.a != t.b,
        both=(t.a < t.b) | (t.a == t.b),
        inv=~(t.a == t.b),
    )
    rows = sorted(run_table(res).values())
    assert rows == sorted([(True, True, False, True, True, True), (False, True, True, False, True, False)])


def test_if_else():
    t = T(
        """
        | a
      1 | 1
      2 | -2
        """
    )
    res = t.select(sign=pw.if_else(t.a >= 0, "pos", "neg"))
    assert sorted(run_table(res).values()) == [("neg",), ("pos",)]


def test_if_else_lazy_branches():
    t = T(
        """
        | a | b
      1 | 6 | 2
      2 | 6 | 0
        """
    )
    res = t.select(d=pw.if_else(t.b != 0, t.a // pw.unwrap(t.b), -1))
    assert sorted(run_table(res).values()) == [(-1,), (3,)]


def test_coalesce():
    t = T(
        """
        | a    | b
      1 | None | 5
      2 | 2    | 7
        """
    )
    res = t.select(c=pw.coalesce(t.a, t.b))
    assert sorted(run_table(res).values()) == [(2,), (5,)]


def test_is_none():
    t = T(
        """
        | a
      1 | None
      2 | 2
        """
    )
    res = t.select(none=t.a.is_none(), not_none=t.a.is_not_none())
    assert sorted(run_table(res).values()) == [(False, True), (True, False)]


def test_apply():
    t = T(
        """
        | a
      1 | 1
      2 | 2
        """
    )
    res = t.select(sq=pw.apply(lambda x: x * x, t.a))
    assert sorted(run_table(res).values()) == [(1,), (4,)]


def test_rename_without_prefix():
    t = T(
        """
        | a | b | c
      1 | 1 | 2 | 3
        """
    )
    assert run_table(t.without(t.b)) == run_table(t.select(t.a, t.c))
    r = t.rename_columns(x=t.a)
    assert r.column_names() == ["x", "b", "c"]
    p = t.with_prefix("p_")
    assert p.column_names() == ["p_a", "p_b", "p_c"]


def test_concat():
    t1 = T(
        """
        | a
      1 | 1
        """
    )
    t2 = T(
        """
        | a
      2 | 2
        """
    )
    res = t1.concat(t2)
    expected = T(
        """
        | a
      1 | 1
      2 | 2
        """
    )
    assert_table_equality(res, expected)


def test_concat_reindex():
    t1 = T(
        """
        | a
      1 | 1
        """
    )
    t2 = T(
        """
        | a
      1 | 2
        """
    )
    res = t1.concat_reindex(t2)
    assert sorted(run_table(res).values()) == [(1,), (2,)]


def test_update_rows():
    t1 = T(
        """
        | a
      1 | 1
      2 | 2
        """
    )
    t2 = T(
        """
        | a
      2 | 20
      3 | 30
        """
    )
    res = t1.update_rows(t2)
    expected = T(
        """
        | a
      1 | 1
      2 | 20
      3 | 30
        """
    )
    assert_table_equality(res, expected)


def test_update_cells():
    t1 = T(
        """
        | a | b
      1 | 1 | x
      2 | 2 | y
        """
    )
    t2 = T(
        """
        | b
      2 | z
        """
    )
    res = t1.update_cells(t2)
    expected = T(
        """
        | a | b
      1 | 1 | x
      2 | 2 | z
        """
    )
    assert_table_equality(res, expected)


def test_difference_intersect():
    t1 = T(
        """
        | a
      1 | 1
      2 | 2
        """
    )
    t2 = T(
        """
        | b
      2 | 0
        """
    )
    assert list(run_table(t1.difference(t2)).values()) == [(1,)]
    assert list(run_table(t1.intersect(t2)).values()) == [(2,)]


def test_restrict():
    t1 = T(
        """
        | a
      1 | 1
      2 | 2
      3 | 3
        """
    )
    t2 = T(
        """
        | b
      2 | 0
      3 | 0
        """
    )
    res = t1.restrict(t2)
    assert sorted(run_table(res).values()) == [(2,), (3,)]


def test_flatten():
    t = T(
        """
        | a
      1 | abc
        """
    )
    split = t.select(parts=pw.apply(lambda s: tuple(s), t.a))
    res = split.flatten(split.parts)
    assert sorted(run_table(res).values()) == [("a",), ("b",), ("c",)]


def test_with_id_from():
    t = T(
        """
        | a | b
      1 | 1 | 10
      2 | 2 | 20
        """
    )
    res = t.with_id_from(t.a)
    rows = run_table(res)
    assert sorted(rows.values()) == [(1, 10), (2, 20)]
    from pathway_tpu.internals.api import ref_scalar

    assert set(rows.keys()) == {ref_scalar(1), ref_scalar(2)}


def test_ix():
    queries = T(
        """
        | d
      1 | 10
      2 | 20
        """
    )
    data = queries.with_id_from(queries.d).select(v=pw.this.d * 7)
    target = queries.select(ptr=queries.pointer_from(queries.d))
    res = target.select(v=data.ix(target.ptr).v)
    assert sorted(run_table(res).values()) == [(70,), (140,)]


def test_groupby_sum_count():
    t = T(
        """
        | k | v
      1 | a | 1
      2 | a | 2
      3 | b | 5
        """
    )
    res = t.groupby(t.k).reduce(
        t.k,
        s=pw.reducers.sum(t.v),
        c=pw.reducers.count(),
    )
    assert sorted(run_table(res).values()) == [("a", 3, 2), ("b", 5, 1)]


def test_groupby_min_max_avg():
    t = T(
        """
        | k | v
      1 | a | 1
      2 | a | 4
      3 | b | 5
        """
    )
    res = t.groupby(pw.this.k).reduce(
        pw.this.k,
        mn=pw.reducers.min(pw.this.v),
        mx=pw.reducers.max(pw.this.v),
        av=pw.reducers.avg(pw.this.v),
    )
    assert sorted(run_table(res).values()) == [("a", 1, 4, 2.5), ("b", 5, 5, 5.0)]


def test_groupby_argmin_argmax():
    t = T(
        """
        | k | v
      1 | a | 3
      2 | a | 1
      3 | a | 2
        """
    )
    res2 = t.groupby(t.k).reduce(am=pw.reducers.argmin(t.v))
    rows = list(run_table(res2).values())
    t_rows = run_table(t)
    assert [t_rows[r[0]] for r in rows] == [("a", 1)]


def test_reduce_global():
    t = T(
        """
        | v
      1 | 1
      2 | 2
      3 | 3
        """
    )
    res = t.reduce(s=pw.reducers.sum(t.v))
    assert list(run_table(res).values()) == [(6,)]


def test_groupby_sorted_tuple():
    t = T(
        """
        | k | v
      1 | a | 3
      2 | a | 1
        """
    )
    res = t.groupby(t.k).reduce(vals=pw.reducers.sorted_tuple(t.v))
    assert list(run_table(res).values()) == [((1, 3),)]


def test_groupby_unique_any():
    t = T(
        """
        | k | u | v
      1 | a | 7 | 1
      2 | a | 7 | 2
        """
    )
    res = t.groupby(t.k).reduce(u=pw.reducers.unique(t.u))
    assert list(run_table(res).values()) == [(7,)]


def test_join_inner():
    t1 = T(
        """
        | k | a
      1 | x | 1
      2 | y | 2
        """
    )
    t2 = T(
        """
        | k | b
      1 | x | 10
      2 | z | 30
        """
    )
    res = t1.join(t2, t1.k == t2.k).select(t1.k, t1.a, t2.b)
    assert sorted(run_table(res).values()) == [("x", 1, 10)]


def test_join_left():
    t1 = T(
        """
        | k | a
      1 | x | 1
      2 | y | 2
        """
    )
    t2 = T(
        """
        | k | b
      1 | x | 10
        """
    )
    res = t1.join(t2, t1.k == t2.k, how="left").select(t1.a, t2.b)
    assert sorted(run_table(res).values(), key=repr) == [(1, 10), (2, None)]


def test_join_outer():
    t1 = T(
        """
        | k | a
      1 | x | 1
      2 | y | 2
        """
    )
    t2 = T(
        """
        | k | b
      1 | x | 10
      2 | z | 30
        """
    )
    res = t1.join(t2, t1.k == t2.k, how="outer").select(t1.a, t2.b)
    assert sorted(run_table(res).values(), key=repr) == [(1, 10), (2, None), (None, 30)]


def test_join_this_select():
    t1 = T(
        """
        | k | a
      1 | x | 1
        """
    )
    t2 = T(
        """
        | k | b
      1 | x | 10
        """
    )
    res = t1.join(t2, pw.left.k == pw.right.k).select(pw.this.k, pw.this.a, pw.this.b)
    assert list(run_table(res).values()) == [("x", 1, 10)]


def test_join_expression_keys():
    t1 = T(
        """
        | a
      1 | 2
        """
    )
    t2 = T(
        """
        | b
      1 | 4
        """
    )
    res = t1.join(t2, t1.a * 2 == t2.b).select(t1.a, t2.b)
    assert list(run_table(res).values()) == [(2, 4)]


def test_sort():
    t = T(
        """
        | v
      1 | 30
      2 | 10
      3 | 20
        """
    )
    res = t.sort(key=t.v)
    rows = run_table(res)
    t_rows = run_table(t)
    by_val = {row[0]: k for k, row in t_rows.items()}
    assert rows[by_val[10]][0] is None
    assert rows[by_val[10]][1] == by_val[20]
    assert rows[by_val[20]] == (by_val[10], by_val[30])
    assert rows[by_val[30]][1] is None


def test_deduplicate():
    t = T(
        """
        | v
      1 | 1
      2 | 2
      3 | 1
      4 | 5
        """
    )
    res = t.deduplicate(value=t.v, acceptor=lambda new, old: new > old)
    vals = list(run_table(res).values())
    assert vals == [(5,)]


def test_groupby_expression_output():
    t = T(
        """
        | k | v
      1 | a | 1
      2 | a | 2
        """
    )
    res = t.groupby(t.k).reduce(
        doubled=pw.reducers.sum(t.v) * 2,
        labeled=pw.this.k + "!",
    )
    assert list(run_table(res).values()) == [(6, "a!")]


def test_cast_and_declare():
    t = T(
        """
        | a
      1 | 1
        """
    )
    res = t.select(f=pw.cast(float, t.a), s=pw.cast(str, t.a))
    assert list(run_table(res).values()) == [(1.0, "1")]


def test_make_tuple_and_get():
    t = T(
        """
        | a | b
      1 | 1 | 2
        """
    )
    res = t.select(tup=pw.make_tuple(t.a, t.b))
    res2 = res.select(first=res.tup[0], second=res.tup.get(5, default=-1))
    assert list(run_table(res2).values()) == [(1, -1)]


def test_str_namespace():
    t = T(
        """
        | s
      1 | Hello
        """
    )
    res = t.select(
        up=t.s.str.upper(),
        low=t.s.str.lower(),
        n=t.s.str.len(),
        sw=t.s.str.startswith("He"),
    )
    assert list(run_table(res).values()) == [("HELLO", "hello", 5, True)]


def test_num_namespace():
    t = T(
        """
        | x
      1 | -3.7
        """
    )
    res = t.select(a=t.x.num.abs(), r=t.x.num.round(1))
    assert list(run_table(res).values()) == [(3.7, -3.7)]


def test_pointer_from_join():
    t1 = T(
        """
        | k | v
      1 | a | 1
        """
    )
    summary = t1.groupby(t1.k).reduce(t1.k, s=pw.reducers.sum(t1.v))
    enriched = t1.select(t1.k, t1.v, total=summary.ix(t1.pointer_from(t1.k)).s)
    assert list(run_table(enriched).values()) == [("a", 1, 1)]


def test_empty_table():
    t = pw.Table.empty(a=int)
    assert run_table(t) == {}


def test_same_universe_cross_ref():
    t1 = T(
        """
        | a
      1 | 1
      2 | 2
        """
    )
    t2 = t1.select(b=t1.a * 10)
    res = t1.select(t1.a, t2.b)
    assert sorted(run_table(res).values()) == [(1, 10), (2, 20)]
