"""ERROR-propagation battery across operator families (VERDICT r4 #6),
modeled on the reference's test_errors.py (1,450 LoC, python/pathway/
tests/test_errors.py): the ERROR poison value must flow through select/
filter/join/groupby/concat/update/ix exactly as the reference's engine
propagates Value::Error, and the recovery surfaces (fill_error,
remove_errors_from_table, global_error_log) must drain it."""

from __future__ import annotations

import pytest

import pathway_tpu as pw
from pathway_tpu.internals.api import ERROR
from pathway_tpu.internals.graph_runner import GraphRunner


def _rows(table):
    cap = GraphRunner().run_tables(table)[0]
    return sorted(map(tuple, cap.state.rows.values()), key=repr)


def _err_table():
    """k=2's q cell is ERROR (5 // 0)."""
    pw.internals.parse_graph.G.clear()
    t = pw.debug.table_from_markdown("k | a | b\n1 | 6 | 2\n2 | 5 | 0")
    return t.select(
        k=pw.this.k, q=pw.declare_type(int, pw.this.a // pw.this.b)
    )


# ----------------------------------------------------------- rowwise ops


def test_error_flows_through_select_chain():
    t = _err_table()
    out = t.select(k=pw.this.k, v=pw.this.q + 1, w=pw.this.q * 0)
    rows = _rows(out)
    assert (1, 4, 0) in rows
    # ERROR is absorbing: any arithmetic over it stays ERROR (reference:
    # test_division_by_zero — "5 // 0" row carries Error downstream)
    assert (2, ERROR, ERROR) in rows


def test_error_in_filter_condition_drops_row():
    # reference test_filter_with_error_in_condition: the undecidable row
    # is EXCLUDED from the output
    t = _err_table()
    out = t.filter(pw.this.q > 1)
    assert _rows(out) == [(1, 3)]


def test_error_in_other_column_survives_filter():
    # reference test_filter_with_error_in_other_column: rows kept by a
    # clean condition carry their poisoned cells along
    t = _err_table()
    out = t.filter(pw.this.k == 2)
    assert _rows(out) == [(2, ERROR)]


def test_fill_error_recovers_cell():
    t = _err_table()
    out = t.select(k=pw.this.k, v=pw.fill_error(pw.this.q, -1))
    assert _rows(out) == [(1, 3), (2, -1)]


def test_is_none_on_error_stays_error():
    t = _err_table()
    out = t.select(k=pw.this.k, n=pw.this.q.is_none())
    rows = dict(_rows(out))
    assert rows[1] is False
    assert rows[2] is ERROR


# --------------------------------------------------------------- joins


def test_inner_join_with_error_in_on_column():
    # reference test_inner_join_with_error_in_condition: a row whose join
    # key is ERROR matches nothing
    t = _err_table()
    pw.internals.parse_graph.G.clear()
    left = pw.debug.table_from_markdown(
        "k | a | b\n1 | 6 | 2\n2 | 5 | 0"
    ).select(k=pw.this.k, j=pw.declare_type(int, pw.this.a // pw.this.b))
    right = pw.debug.table_from_markdown("j | tag\n3 | hit\n0 | zero")
    out = left.join(right, pw.left.j == pw.right.j).select(
        k=pw.left.k, tag=pw.right.tag
    )
    assert _rows(out) == [(1, "hit")]


def test_left_join_with_error_key_pads():
    pw.internals.parse_graph.G.clear()
    left = pw.debug.table_from_markdown(
        "k | a | b\n1 | 6 | 2\n2 | 5 | 0"
    ).select(k=pw.this.k, j=pw.declare_type(int, pw.this.a // pw.this.b))
    right = pw.debug.table_from_markdown("j | tag\n3 | hit")
    out = left.join_left(right, pw.left.j == pw.right.j).select(
        k=pw.left.k, tag=pw.right.tag
    )
    # the ERROR-keyed left row matches nothing and pads with None,
    # exactly like any unmatched key (reference join semantics)
    assert _rows(out) == [(1, "hit"), (2, None)]


def test_join_error_in_payload_column_flows_through():
    t = _err_table()
    pw.internals.parse_graph.G.clear()
    left = pw.debug.table_from_markdown(
        "k | a | b\n1 | 6 | 2\n2 | 5 | 0"
    ).select(k=pw.this.k, q=pw.declare_type(int, pw.this.a // pw.this.b))
    right = pw.debug.table_from_markdown("k | tag\n1 | one\n2 | two")
    out = left.join(right, pw.left.k == pw.right.k).select(
        k=pw.left.k, q=pw.left.q, tag=pw.right.tag
    )
    assert _rows(out) == [(1, 3, "one"), (2, ERROR, "two")]


# --------------------------------------------------------------- groupby


def test_groupby_with_error_in_grouping_column_drops_row():
    # reference test_groupby_with_error_in_grouping_column: a row whose
    # GROUPING value is undecidable joins no group
    pw.internals.parse_graph.G.clear()
    t = pw.debug.table_from_markdown(
        "k | a | b | v\n1 | 1 | 1 | 10\n2 | 1 | 1 | 20\n3 | 5 | 0 | 40"
    ).select(
        g=pw.declare_type(int, pw.this.a // pw.this.b), v=pw.this.v
    )
    out = t.groupby(pw.this.g).reduce(
        g=pw.this.g, s=pw.reducers.sum(pw.this.v)
    )
    assert _rows(out) == [(1, 30)]


def test_groupby_error_in_reduced_column_poisons_sum():
    # reference test_groupby_propagate_errors: sum/min over a group
    # containing ERROR answers ERROR for that group only
    pw.internals.parse_graph.G.clear()
    t = pw.debug.table_from_markdown(
        "k | g | a | b\n1 | 1 | 6 | 2\n2 | 1 | 5 | 0\n3 | 2 | 8 | 2"
    ).select(
        g=pw.this.g, v=pw.declare_type(int, pw.this.a // pw.this.b)
    )
    out = t.groupby(pw.this.g).reduce(
        g=pw.this.g,
        s=pw.reducers.sum(pw.this.v),
        m=pw.reducers.min(pw.this.v),
        n=pw.reducers.count(),
    )
    rows = {r[0]: r for r in _rows(out)}
    assert rows[2] == (2, 4, 4, 1)
    assert rows[1][1] is ERROR and rows[1][2] is ERROR
    assert rows[1][3] == 2  # count ignores the values entirely


def test_unique_reducer_conflict_is_error():
    # reference test_unique_reducer: two distinct values -> Error cell
    pw.internals.parse_graph.G.clear()
    t = pw.debug.table_from_markdown(
        "g | v\n1 | 5\n1 | 5\n2 | 5\n2 | 6"
    )
    out = t.groupby(pw.this.g).reduce(
        g=pw.this.g, u=pw.reducers.unique(pw.this.v)
    )
    rows = {r[0]: r[1] for r in _rows(out)}
    assert rows[1] == 5
    assert rows[2] is ERROR


# ----------------------------------------------------- concat and update


def test_concat_carries_errors():
    pw.internals.parse_graph.G.clear()
    a = pw.debug.table_from_markdown("k | x | y\n1 | 6 | 2").with_id_from(
        pw.this.k
    ).select(k=pw.this.k, q=pw.declare_type(int, pw.this.x // pw.this.y))
    b = pw.debug.table_from_markdown("k | x | y\n2 | 5 | 0").with_id_from(
        pw.this.k
    ).select(k=pw.this.k, q=pw.declare_type(int, pw.this.x // pw.this.y))
    out = a.concat(b)
    assert _rows(out) == [(1, 3), (2, ERROR)]


def test_update_cells_with_error_value():
    pw.internals.parse_graph.G.clear()
    base = pw.debug.table_from_markdown("k | v\n1 | 10\n2 | 20").with_id_from(
        pw.this.k
    )
    patch = pw.debug.table_from_markdown(
        "k | a | b\n2 | 5 | 0"
    ).with_id_from(pw.this.k).select(
        k=pw.this.k, v=pw.declare_type(int, pw.this.a // pw.this.b)
    )
    out = base.update_cells(patch)
    rows = {r[0]: r[1] for r in _rows(out)}
    assert rows[1] == 10
    assert rows[2] is ERROR  # the patched cell carries the poison


# ------------------------------------------------- recovery + error log


def test_remove_errors_from_table():
    # reference test_remove_errors: rows with any ERROR cell are dropped
    t = _err_table()
    out = pw.remove_errors_from_table(t)
    assert _rows(out) == [(1, 3)]


def test_remove_errors_identity_when_clean():
    pw.internals.parse_graph.G.clear()
    t = pw.debug.table_from_markdown("k | v\n1 | 10\n2 | 20")
    out = pw.remove_errors_from_table(t)
    assert _rows(out) == [(1, 10), (2, 20)]


def test_global_error_log_records_data_errors():
    # reference test_local_logs/test_division_by_zero: the error log is a
    # TABLE carrying one row per data error with its message
    pw.internals.parse_graph.G.clear()
    t = pw.debug.table_from_markdown("k | a | b\n1 | 6 | 2\n2 | 5 | 0")
    bad = t.select(k=pw.this.k, q=pw.declare_type(int, pw.this.a // pw.this.b))
    log = pw.global_error_log()
    cap_bad, cap_log = (
        GraphRunner().run_tables(bad, log)
    )
    text = " ".join(
        str(r[0]) for r in cap_log.state.rows.values()
    )  # log rows are (message, origin)
    assert "division" in text.lower() or "zero" in text.lower()


def test_udf_exception_becomes_error():
    # reference test_udf: a raising UDF poisons its row, others flow
    pw.internals.parse_graph.G.clear()
    t = pw.debug.table_from_markdown("k | v\n1 | 4\n2 | 0")

    @pw.udf
    def flaky(x: int) -> int:
        if x == 0:
            raise ValueError("no zeros accepted")
        return x * 2

    out = t.select(k=pw.this.k, d=flaky(pw.this.v))
    rows = {r[0]: r[1] for r in _rows(out)}
    assert rows[1] == 8
    assert rows[2] is ERROR


def test_subscribe_delivers_error_rows():
    # reference test_subscribe: ERROR cells reach the sink as values
    pw.internals.parse_graph.G.clear()

    class S(pw.Schema):
        k: int = pw.column_definition(primary_key=True)
        a: int
        b: int

    class Src(pw.io.python.ConnectorSubject):
        def run(self):
            self.next(k=1, a=6, b=2)
            self.next(k=2, a=5, b=0)
            self.commit()

    t = pw.io.python.read(Src(), schema=S, autocommit_duration_ms=None)
    q = t.select(k=pw.this.k, v=pw.declare_type(int, pw.this.a // pw.this.b))
    seen = {}
    pw.io.subscribe(
        q,
        on_change=lambda key, row, time, diff: seen.__setitem__(
            row["k"], row["v"]
        ),
    )
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    assert seen[1] == 3
    assert seen[2] is ERROR


def test_error_recovers_on_retraction():
    # reference test_groupby_recovers_from_errors: retracting the
    # poisoning row heals the aggregate
    pw.internals.parse_graph.G.clear()

    class S(pw.Schema):
        k: int = pw.column_definition(primary_key=True)
        g: int
        a: int
        b: int

    class Src(pw.io.python.ConnectorSubject):
        def run(self):
            self.next(k=1, g=1, a=6, b=2)
            self.next(k=2, g=1, a=5, b=0)
            self.commit()
            self.remove(k=2, g=1, a=5, b=0)
            self.commit()

    t = pw.io.python.read(Src(), schema=S, autocommit_duration_ms=None)
    q = t.select(
        g=pw.this.g, v=pw.declare_type(int, pw.this.a // pw.this.b)
    )
    agg = q.groupby(pw.this.g).reduce(
        g=pw.this.g, s=pw.reducers.sum(pw.this.v)
    )
    states = []
    pw.io.subscribe(
        agg,
        on_change=lambda key, row, time, diff: states.append(
            (row["s"], diff > 0)
        ),
    )
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    # final state: the healed sum (3) is live
    live = [v for v, add in states if add]
    retracted = [v for v, add in states if not add]
    assert live[-1] == 3
    assert any(v is ERROR for v in retracted) or any(
        v is ERROR for v in live[:-1]
    )
