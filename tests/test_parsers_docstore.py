"""Parser depth + DocumentStore index-injection tests (reference:
xpacks/llm/parsers.py:53-400; document_store.py:32-120; test pattern:
xpacks/llm/tests/ — mock LLMs, pure parsers)."""

from __future__ import annotations

import asyncio
import json
import zlib

import pytest

import pathway_tpu as pw
from pathway_tpu.xpacks.llm.parsers import (
    ImageParser,
    PypdfParser,
    SlideParser,
    _builtin_pdf_pages,
)


def _make_pdf(pages: list[str], compress: bool = False) -> bytes:
    """Tiny single-font PDF with one content stream per page."""
    out = [b"%PDF-1.4\n"]
    for i, text in enumerate(pages):
        content = f"BT /F1 12 Tf 72 700 Td ({text}) Tj ET".encode()
        if compress:
            content = zlib.compress(content)
        out.append(
            b"%d 0 obj << /Length %d >>\nstream\n" % (10 + i, len(content))
            + content
            + b"\nendstream\nendobj\n"
        )
    out.append(b"%%EOF\n")
    return b"".join(out)


def _run_udf(udf, *args):
    fn = udf.func
    res = fn(*args)
    if asyncio.iscoroutine(res):
        return asyncio.new_event_loop().run_until_complete(res)
    return res


def test_builtin_pdf_extractor_plain_and_flate():
    pdf = _make_pdf(["Hello TPU world", "Second page"])
    assert _builtin_pdf_pages(pdf) == ["Hello TPU world\n", "Second page\n"]
    pdfz = _make_pdf(["Compressed text"], compress=True)
    assert _builtin_pdf_pages(pdfz) == ["Compressed text\n"]


def test_builtin_pdf_escapes_and_tj_arrays():
    content = rb"BT [(Hel) -120 (lo)] TJ (paren \( inside \)) Tj ET"
    pdf = (
        b"%PDF-1.4\n1 0 obj << >>\nstream\n" + content + b"\nendstream\nendobj\n"
    )
    [page] = _builtin_pdf_pages(pdf)
    assert "Hello" in page.replace("\n", "")
    assert "paren ( inside )" in page


def test_pypdf_parser_end_to_end():
    parser = PypdfParser()
    pdf = _make_pdf(["alpha beta", "gamma"])
    out = _run_udf(parser, pdf)
    assert out == [("alpha beta", {"page": 0}), ("gamma", {"page": 1})]


def test_pypdf_parser_in_document_pipeline(tmp_path):
    (tmp_path / "doc.pdf").write_bytes(_make_pdf(["indexable content"]))
    docs = pw.io.fs.read(str(tmp_path), format="binary", mode="static")
    parsed = docs.select(
        out=PypdfParser()(pw.this.data)
    ).flatten(pw.this.out)
    rows = []
    pw.io.subscribe(
        parsed, on_change=lambda key, row, t, d: rows.append(row["out"])
    )
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    assert rows == [("indexable content", {"page": 0})]


class _MockVisionChat:
    """Vision-LLM mock: records messages, answers deterministically
    (pattern: xpacks/llm/tests/mocks.py IdentityMockChat)."""

    def __init__(self):
        self.calls = []

    def func(self, messages):
        self.calls.append(messages)
        return "a description of the image"


def test_image_parser_against_vision_mock():
    llm = _MockVisionChat()
    parser = ImageParser(llm=llm, parse_prompt="What is on this slide?")
    out = _run_udf(parser, b"\x89PNG fake image bytes")
    assert out == [("a description of the image", {})]
    [messages] = llm.calls
    content = messages[0]["content"]
    assert content[0] == {"type": "text", "text": "What is on this slide?"}
    url = content[1]["image_url"]["url"]
    assert url.startswith("data:image/png;base64,")
    import base64

    assert base64.b64decode(url.split(",", 1)[1]) == b"\x89PNG fake image bytes"


def test_slide_parser_is_vision_parser():
    llm = _MockVisionChat()
    parser = SlideParser(llm=llm)
    out = _run_udf(parser, b"slide bytes")
    assert out == [("a description of the image", {})]


def test_unstructured_stays_gated():
    from pathway_tpu.xpacks.llm.parsers import ParseUnstructured

    with pytest.raises(ImportError, match="unstructured"):
        ParseUnstructured()


# -- DocumentStore with injected retrievers ---------------------------------

def _doc_table(texts):
    rows = "\n".join(texts)
    return pw.debug.table_from_markdown(
        "data\n" + rows
    )


def test_document_store_bm25_end_to_end():
    from pathway_tpu.stdlib.indexing.bm25 import TantivyBM25Factory
    from pathway_tpu.xpacks.llm.document_store import DocumentStore

    docs = _doc_table(["the quick brown fox", "lazy dogs sleep", "fox dens"])
    store = DocumentStore(
        docs, retriever_factory=TantivyBM25Factory()
    )
    queries = pw.debug.table_from_markdown(
        """
        query | k
        fox   | 2
        """,
        schema=DocumentStore.RetrieveQuerySchema,
    )
    res = store.retrieve_query(queries)
    # as-of-now answers are delivered once then forgotten (retracted), so
    # capture the first insert per key, not the final state
    from pathway_tpu.internals.graph_runner import GraphRunner

    caps = GraphRunner().run_tables(res)
    answers = {}
    for key, row, _t, d in caps[0].updates:
        if d > 0 and key not in answers and row[0].value:
            answers[key] = row[0]
    [result] = answers.values()
    texts = [hit["text"] for hit in result.value]
    assert len(texts) == 2 and all("fox" in t for t in texts)


def test_document_store_hybrid_end_to_end():
    from pathway_tpu.stdlib.indexing.bm25 import TantivyBM25Factory
    from pathway_tpu.stdlib.indexing.hybrid_index import HybridIndexFactory
    from pathway_tpu.stdlib.indexing.nearest_neighbors import (
        BruteForceKnnFactory,
    )
    from pathway_tpu.xpacks.llm.document_store import DocumentStore

    @pw.udf(deterministic=True)
    def embedder(text: str):
        # deterministic toy embedding: letter histogram
        import numpy as np

        v = np.zeros(26, dtype=np.float32)
        for ch in text.lower():
            if "a" <= ch <= "z":
                v[ord(ch) - 97] += 1.0
        return v / max(float(np.linalg.norm(v)), 1e-6)

    factory = HybridIndexFactory(
        [
            TantivyBM25Factory(),
            BruteForceKnnFactory(dimensions=26, embedder=embedder),
        ]
    )
    docs = _doc_table(["the quick brown fox", "lazy dogs sleep", "fox dens"])
    store = DocumentStore(docs, retriever_factory=factory)
    queries = pw.debug.table_from_markdown(
        """
        query | k
        fox   | 2
        """,
        schema=DocumentStore.RetrieveQuerySchema,
    )
    res = store.retrieve_query(queries)
    # as-of-now answers are delivered once then forgotten (retracted), so
    # capture the first insert per key, not the final state
    from pathway_tpu.internals.graph_runner import GraphRunner

    caps = GraphRunner().run_tables(res)
    answers = {}
    for key, row, _t, d in caps[0].updates:
        if d > 0 and key not in answers and row[0].value:
            answers[key] = row[0]
    [result] = answers.values()
    texts = [hit["text"] for hit in result.value]
    assert len(texts) == 2
    assert any("fox" in t for t in texts)


def test_vector_store_requires_exactly_one_strategy():
    from pathway_tpu.xpacks.llm.vector_store import VectorStoreServer

    docs = _doc_table(["x"])
    with pytest.raises(ValueError, match="exactly one"):
        VectorStoreServer(docs)
    with pytest.raises(ValueError, match="exactly one"):
        VectorStoreServer(
            docs, embedder=lambda t: [0.0], index_builder=lambda c: None
        )


def _positioned_pdf(rows):
    """Minimal one-page PDF with absolutely positioned text runs (Tm) —
    rows: list of [(x, y, text), ...]."""
    content = b"BT /F1 10 Tf\n"
    for x, y, text in rows:
        content += (
            f"1 0 0 1 {x} {y} Tm ({text}) Tj\n".encode()
        )
    content += b"ET"
    return (
        b"%PDF-1.4\n1 0 obj << /Length " + str(len(content)).encode()
        + b" >>\nstream\n" + content + b"\nendstream\nendobj\n%%EOF"
    )


def test_pdf_table_extraction():
    from pathway_tpu.xpacks.llm.parsers import pdf_tables

    pdf = _positioned_pdf([
        (72, 700, "Name"), (200, 700, "Qty"), (300, 700, "Price"),
        (72, 684, "apples"), (200, 684, "12"), (300, 684, "3.50"),
        (72, 668, "pears"), (200, 668, "7"), (300, 668, "4.10"),
        (72, 600, "A trailing paragraph spanning the page."),
    ])
    [table] = pdf_tables(pdf)
    assert table == [
        ["Name", "Qty", "Price"],
        ["apples", "12", "3.50"],
        ["pears", "7", "4.10"],
    ]


def test_pypdf_parser_emits_table_chunks():
    from pathway_tpu.xpacks.llm.parsers import PypdfParser

    pdf = _positioned_pdf([
        (72, 700, "City"), (220, 700, "Pop"),
        (72, 684, "Oslo"), (220, 684, "700k"),
        (72, 668, "Kyoto"), (220, 668, "1.4M"),
    ])
    parser = PypdfParser(extract_tables=True)
    out = _run_udf(parser, pdf)
    tables = [(t, m) for t, m in out if m.get("kind") == "table"]
    assert len(tables) == 1
    text, meta = tables[0]
    assert "| City | Pop |" in text and "| Kyoto | 1.4M |" in text
    # text chunks still present alongside
    assert any(m.get("kind") != "table" for _, m in out)


# -- OpenParse-parity structured parsing (VERDICT r4 #7): table-args
# strategies, vision pipeline, markdown output, processing pipelines ----


class _SpyTableChat:
    """BaseChat-shaped mock recording every message; answers tables with
    a normalized markdown echo and images with a fixed caption."""

    def __init__(self):
        self.calls = []

    def func(self, messages):
        self.calls.append(messages)
        content = messages[-1]["content"]
        texts = [c["text"] for c in content if c.get("type") == "text"]
        has_image = any(c.get("type") == "image_url" for c in content)
        if has_image:
            return "a diagram of the ingestion pipeline"
        return "LLM-TABLE:\n" + texts[0].split("\n\n", 1)[-1]


def _table_image_pdf():
    """Positioned table runs + prose + one embedded image XObject."""
    content = b"BT /F1 10 Tf\n"
    for x, y, text in [
        (72, 700, "Metric"), (220, 700, "Q1"), (320, 700, "Q2"),
        (72, 684, "revenue"), (220, 684, "10"), (320, 684, "14"),
        (72, 668, "margin"), (220, 668, "0.31"), (320, 668, "0.38"),
        (72, 560, "The quarterly report shows improving unit economics"),
        (72, 544, "across both revenue and margin in the second quarter."),
    ]:
        content += f"1 0 0 1 {x} {y} Tm ({text}) Tj\n".encode()
    content += b"ET"
    image = b"\x89PNG-fake-image-bytes-mock-chart"
    return (
        b"%PDF-1.4\n1 0 obj << /Length " + str(len(content)).encode()
        + b" >>\nstream\n" + content + b"\nendstream\nendobj\n"
        b"2 0 obj << /Subtype /Image /Width 4 /Height 4 /Length "
        + str(len(image)).encode()
        + b" >>\nstream\n" + image + b"\nendstream\nendobj\n%%EOF"
    )


def test_openparse_local_table_algorithms_emit_markdown():
    from pathway_tpu.xpacks.llm.parsers import OpenParse

    for alg in ("pymupdf", "unitable", "table-transformers"):
        parser = OpenParse(table_args={"parsing_algorithm": alg})
        chunks = _run_udf(parser, _table_image_pdf())
        tables = [c for c in chunks if c[1]["kind"] == "table"]
        assert len(tables) == 1, alg
        md = tables[0][0]
        assert "| Metric | Q1 | Q2 |" in md
        assert "| revenue | 10 | 14 |" in md
        # prose survives as text chunks
        assert any(
            "unit economics" in text
            for text, meta in chunks
            if meta["kind"] == "text"
        )


def test_openparse_llm_table_algorithm_routes_through_chat():
    from pathway_tpu.xpacks.llm.parsers import OpenParse

    chat = _SpyTableChat()
    parser = OpenParse(
        table_args={
            "parsing_algorithm": "llm",
            "llm": chat,
            "prompt": "Explain the given table in markdown format.",
        }
    )
    chunks = _run_udf(parser, _table_image_pdf())
    [table] = [c for c in chunks if c[1]["kind"] == "table"]
    assert table[0].startswith("LLM-TABLE:")
    assert "| revenue | 10 | 14 |" in table[0]
    # exactly one chat call, carrying the configured prompt
    assert len(chat.calls) == 1
    sent = chat.calls[0][-1]["content"][0]["text"]
    assert sent.startswith("Explain the given table")


def test_openparse_vision_pipeline_parses_images():
    from pathway_tpu.xpacks.llm.parsers import OpenParse

    chat = _SpyTableChat()
    parser = OpenParse(
        table_args={"parsing_algorithm": "pymupdf"},
        image_args={
            "parsing_algorithm": "llm",
            "llm": chat,
            "prompt": "Explain the given image in detail.",
        },
        parse_images=True,
    )
    chunks = _run_udf(parser, _table_image_pdf())
    [image] = [c for c in chunks if c[1]["kind"] == "image"]
    assert image[0] == "a diagram of the ingestion pipeline"
    # the vision call carried the image as a data-url
    [call] = chat.calls
    kinds = [c.get("type") for c in call[-1]["content"]]
    assert "image_url" in kinds


def test_openparse_image_args_require_llm_algorithm():
    import pytest as _pytest

    from pathway_tpu.xpacks.llm.parsers import OpenParse

    with _pytest.raises(ValueError, match="only supported with LLMs"):
        OpenParse(
            table_args={"parsing_algorithm": "pymupdf"},
            image_args={"parsing_algorithm": "ocr"},
            parse_images=True,
        )


def test_openparse_image_args_without_parse_images_warns_and_skips():
    import warnings as _warnings

    from pathway_tpu.xpacks.llm.parsers import OpenParse

    chat = _SpyTableChat()
    with _warnings.catch_warnings(record=True) as caught:
        _warnings.simplefilter("always")
        parser = OpenParse(
            table_args={"parsing_algorithm": "pymupdf"},
            image_args={"parsing_algorithm": "llm", "llm": chat},
            parse_images=False,
        )
    assert any("skipping image parsing" in str(w.message) for w in caught)
    chunks = _run_udf(parser, _table_image_pdf())
    assert not [c for c in chunks if c[1]["kind"] == "image"]


def test_openparse_processing_pipelines():
    import pytest as _pytest

    from pathway_tpu.xpacks.llm.parsers import OpenParse

    # merge_same_page: everything collapses to one chunk per page
    parser = OpenParse(
        table_args={"parsing_algorithm": "pymupdf"},
        processing_pipeline="merge_same_page",
    )
    chunks = _run_udf(parser, _table_image_pdf())
    pages = {meta["page"] for _t, meta in chunks}
    assert len(chunks) == len(pages)
    joined = chunks[0][0]
    assert "| Metric | Q1 | Q2 |" in joined and "unit economics" in joined

    # custom pipeline object with a process() hook
    class UpperPipeline:
        def process(self, nodes):
            return [dict(n, text=n["text"].upper()) for n in nodes]

    parser2 = OpenParse(
        table_args={"parsing_algorithm": "pymupdf"},
        processing_pipeline=UpperPipeline(),
    )
    chunks2 = _run_udf(parser2, _table_image_pdf())
    assert all(t == t.upper() for t, _m in chunks2)

    with _pytest.raises(ValueError, match="Invalid `processing_pipeline`"):
        OpenParse(
            table_args={"parsing_algorithm": "pymupdf"},
            processing_pipeline="bogus",
        )


def test_openparse_invalid_table_algorithm_rejected():
    import pytest as _pytest

    from pathway_tpu.xpacks.llm.parsers import OpenParse

    with _pytest.raises(ValueError, match="parsing_algorithm"):
        OpenParse(table_args={"parsing_algorithm": "magic"})


def test_simple_ingestion_pipeline_merges_and_filters():
    from pathway_tpu.xpacks.llm.openparse_utils import SimpleIngestionPipeline

    nodes = [
        {"text": "Quarterly Report", "page": 0, "kind": "text"},
        {"text": "Revenue grew steadily across the half.", "page": 0,
         "kind": "text"},
        {"text": "x", "page": 0, "kind": "text"},
        {"text": "| a | b |", "page": 0, "kind": "table"},
        {"text": "tiny", "page": 1, "kind": "text"},
    ]
    out = SimpleIngestionPipeline(min_chars=15).process(nodes)
    kinds = [n["kind"] for n in out]
    assert kinds == ["text", "table"]
    # the heading merged INTO the body paragraph
    assert out[0]["text"].startswith("Quarterly Report\n")
    assert "Revenue grew steadily" in out[0]["text"]
