"""Live monitoring dashboard + XLA profiler hook (VERDICT r2 #9;
reference: python/pathway/internals/monitoring.py rich TUI, SURVEY §5
tracing)."""

import logging
import os

import pathway_tpu as pw
from pathway_tpu.internals.monitoring import (
    ProberStats,
    _LogGraveyard,
    render_dashboard,
)


def test_dashboard_renders_connector_rows_and_latency():
    from rich.console import Console

    stats = ProberStats()
    stats.on_ingest("kafka:orders", 120)
    stats.on_ingest("kafka:orders", 80)
    stats.on_ingest("fs:docs", 7)
    stats.on_connector_finished("fs:docs")
    stats.on_output(42)

    graveyard = _LogGraveyard()
    graveyard.setFormatter(logging.Formatter("%(levelname)s %(message)s"))
    rec = logging.LogRecord(
        "pw", logging.WARNING, __file__, 1, "late data dropped", None, None
    )
    graveyard.emit(rec)

    console = Console(record=True, width=100)
    console.print(render_dashboard(stats, graveyard))
    text = console.export_text()
    # per-connector rows: name, last minibatch, last minute, total
    assert "kafka:orders" in text
    assert "80" in text and "200" in text
    assert "fs:docs" in text and "finished" in text
    # latency table + log graveyard
    assert "input" in text and "output" in text
    assert "late data dropped" in text


def test_dashboard_graveyard_ring_buffer():
    g = _LogGraveyard(capacity=5)
    g.setFormatter(logging.Formatter("%(message)s"))
    for i in range(12):
        g.emit(
            logging.LogRecord("pw", logging.INFO, __file__, 1, f"m{i}", None, None)
        )
    assert g.records == [f"m{i}" for i in range(7, 12)]


def test_run_profile_emits_jax_trace(tmp_path):
    pw.internals.parse_graph.G.clear()
    t = pw.debug.table_from_markdown(
        """
        a | b
        1 | 2
        3 | 4
        """
    )
    out = t.select(c=pw.this.a + pw.this.b)
    pw.io.subscribe(out, on_change=lambda *a: None)
    trace_dir = str(tmp_path / "trace")
    pw.run(monitoring_level=pw.MonitoringLevel.NONE, profile=trace_dir)
    produced = [
        os.path.join(r, f)
        for r, _, fs in os.walk(trace_dir)
        for f in fs
    ]
    assert produced, "profiler trace directory is empty"
    assert any(f.endswith((".xplane.pb", ".trace.json.gz", ".json.gz")) for f in produced), produced
