"""REST connector tests (reference pattern:
python/pathway/tests/test_server.py — real webserver, HTTP round trips)."""

import json
import threading
import time
import urllib.request

import pytest

import pathway_tpu as pw

_PORT = [8901]


def _next_port():
    _PORT[0] += 1
    return _PORT[0]


def _post(url, payload, timeout=10):
    req = urllib.request.Request(
        url,
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def test_rest_connector_roundtrip():
    port = _next_port()

    class QuerySchema(pw.Schema):
        value: int

    queries, response_writer = pw.io.http.rest_connector(
        host="127.0.0.1",
        port=port,
        schema=QuerySchema,
        autocommit_duration_ms=None,
        delete_completed_queries=True,
    )
    answers = queries.select(result=pw.this.value * 2)
    response_writer(answers)

    t = threading.Thread(target=pw.run, daemon=True)
    t.start()
    time.sleep(1.0)

    out = _post(f"http://127.0.0.1:{port}/", {"value": 21})
    assert out == 42
    out = _post(f"http://127.0.0.1:{port}/", {"value": 5})
    assert out == 10


def test_rest_connector_missing_field_400():
    port = _next_port()

    class QuerySchema(pw.Schema):
        value: int

    queries, response_writer = pw.io.http.rest_connector(
        host="127.0.0.1", port=port, schema=QuerySchema,
        autocommit_duration_ms=None,
    )
    response_writer(queries.select(result=pw.this.value))
    threading.Thread(target=pw.run, daemon=True).start()
    time.sleep(1.0)

    with pytest.raises(urllib.error.HTTPError) as e:
        _post(f"http://127.0.0.1:{port}/", {"wrong": 1})
    assert e.value.code == 400


def test_openapi_document_matches_routes():
    """Served openapi.json reflects registered routes, schema-derived
    request bodies and GET parameters (reference: _server.py:126)."""
    port = _next_port()

    class QA(pw.Schema):
        query: str
        k: int = pw.column_definition(default_value=3)

    server = pw.io.http.PathwayWebserver(host="127.0.0.1", port=port)
    queries, response_writer = pw.io.http.rest_connector(
        webserver=server,
        route="/v1/answer",
        schema=QA,
        methods=("GET", "POST"),
        autocommit_duration_ms=None,
        documentation=pw.io.http.EndpointDocumentation(
            summary="Answer a question", tags=["rag"]
        ),
    )
    response_writer(queries.select(result=pw.this.query))

    t = threading.Thread(target=pw.run, daemon=True)
    t.start()
    time.sleep(1.0)

    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/openapi.json", timeout=10
    ) as resp:
        doc = json.loads(resp.read().decode())

    assert doc["openapi"].startswith("3.")
    assert set(doc["paths"].keys()) == {"/v1/answer"}
    ops = doc["paths"]["/v1/answer"]
    assert set(ops.keys()) == {"get", "post"}
    assert ops["post"]["summary"] == "Answer a question"
    assert ops["post"]["tags"] == ["rag"]
    body = ops["post"]["requestBody"]["content"]["application/json"]["schema"]
    assert body["properties"]["query"] == {"type": "string"}
    assert body["properties"]["k"]["type"] == "integer"
    assert body["properties"]["k"]["default"] == 3
    assert body["required"] == ["query"]  # k has a default
    params = {p["name"]: p for p in ops["get"]["parameters"]}
    assert params["query"]["required"] is True
    assert params["k"]["required"] is False
    # /_schema serves the same document
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/_schema", timeout=10
    ) as resp:
        assert json.loads(resp.read().decode()) == doc


def test_request_type_validation_400():
    port = _next_port()

    class S(pw.Schema):
        value: int

    queries, response_writer = pw.io.http.rest_connector(
        host="127.0.0.1", port=port, schema=S,
        autocommit_duration_ms=None, delete_completed_queries=True,
    )
    response_writer(queries.select(result=pw.this.value * 2))
    t = threading.Thread(target=pw.run, daemon=True)
    t.start()
    time.sleep(1.0)

    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/",
        data=json.dumps({"value": "not-an-int"}).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(req, timeout=10)
    assert e.value.code == 400
    assert "integer" in json.loads(e.value.read().decode())["error"]
    # valid request still works
    assert _post(f"http://127.0.0.1:{port}/", {"value": 4}) == 8
