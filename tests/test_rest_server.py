"""REST connector tests (reference pattern:
python/pathway/tests/test_server.py — real webserver, HTTP round trips)."""

import json
import threading
import time
import urllib.request

import pytest

import pathway_tpu as pw

_PORT = [8901]


def _next_port():
    _PORT[0] += 1
    return _PORT[0]


def _post(url, payload, timeout=10):
    req = urllib.request.Request(
        url,
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def test_rest_connector_roundtrip():
    port = _next_port()

    class QuerySchema(pw.Schema):
        value: int

    queries, response_writer = pw.io.http.rest_connector(
        host="127.0.0.1",
        port=port,
        schema=QuerySchema,
        autocommit_duration_ms=None,
        delete_completed_queries=True,
    )
    answers = queries.select(result=pw.this.value * 2)
    response_writer(answers)

    t = threading.Thread(target=pw.run, daemon=True)
    t.start()
    time.sleep(1.0)

    out = _post(f"http://127.0.0.1:{port}/", {"value": 21})
    assert out == 42
    out = _post(f"http://127.0.0.1:{port}/", {"value": 5})
    assert out == 10


def test_rest_connector_missing_field_400():
    port = _next_port()

    class QuerySchema(pw.Schema):
        value: int

    queries, response_writer = pw.io.http.rest_connector(
        host="127.0.0.1", port=port, schema=QuerySchema,
        autocommit_duration_ms=None,
    )
    response_writer(queries.select(result=pw.this.value))
    threading.Thread(target=pw.run, daemon=True).start()
    time.sleep(1.0)

    with pytest.raises(urllib.error.HTTPError) as e:
        _post(f"http://127.0.0.1:{port}/", {"wrong": 1})
    assert e.value.code == 400
