"""Mesh sharding tests on the virtual 8-device CPU mesh (conftest env)."""

import numpy as np
import pytest

import jax

from pathway_tpu.models.encoder import EncoderConfig
from pathway_tpu.parallel import (
    ShardedKnnIndex,
    best_factorization,
    create_train_state,
    make_mesh,
    make_sharded_train_step,
)


def test_best_factorization():
    assert best_factorization(8) == (4, 2)
    assert best_factorization(1) == (1, 1)
    dp, tp = best_factorization(6)
    assert dp * tp == 6


def test_make_mesh_covers_devices():
    mesh = make_mesh(8)
    assert mesh.devices.size == 8
    assert set(mesh.axis_names) == {"dp", "tp"}


def test_sharded_knn_matches_single_shard():
    mesh = make_mesh(8, axes=("dp",), shape=(8,))
    rng = np.random.default_rng(0)
    db = rng.normal(size=(500, 16)).astype(np.float32)
    queries = rng.normal(size=(5, 16)).astype(np.float32)

    idx = ShardedKnnIndex(16, mesh, metric="cos")
    idx.add(list(range(500)), db)
    got = idx.search(queries, k=3)

    from pathway_tpu.ops import KnnShard

    ref = KnnShard(16, "cos")
    ref.add(list(range(500)), db)
    want = ref.search(queries, k=3)
    for g, w in zip(got, want):
        assert [k for k, _ in g] == [k for k, _ in w]
        np.testing.assert_allclose(
            [s for _, s in g], [s for _, s in w], rtol=1e-5
        )


def test_sharded_knn_remove_and_grow():
    mesh = make_mesh(8, axes=("dp",), shape=(8,))
    rng = np.random.default_rng(1)
    db = rng.normal(size=(3000, 8)).astype(np.float32)  # forces growth
    idx = ShardedKnnIndex(8, mesh, metric="cos")
    idx.add(list(range(3000)), db)
    assert idx.capacity >= 3000 and idx.capacity % 8 == 0
    idx.remove([42])
    res = idx.search(db[42][None, :], k=1)
    assert res[0][0][0] != 42


def test_sharded_train_step_runs_and_reduces_loss():
    mesh = make_mesh(8)  # (dp=4, tp=2)
    cfg = EncoderConfig.tiny()
    state, model, tx = create_train_state(cfg, mesh, learning_rate=1e-2)
    step = make_sharded_train_step(model, tx, mesh)
    rng = np.random.default_rng(0)
    batch = {
        "q_ids": rng.integers(3, cfg.vocab_size, size=(8, 16)).astype(np.int32),
        "q_mask": np.ones((8, 16), np.int32),
        "d_ids": rng.integers(3, cfg.vocab_size, size=(8, 16)).astype(np.int32),
        "d_mask": np.ones((8, 16), np.int32),
    }
    state, loss0 = step(state, batch)
    losses = [float(loss0)]
    for _ in range(5):
        state, loss = step(state, batch)
        losses.append(float(loss))
    assert int(state.step) == 6
    assert losses[-1] < losses[0]  # optimizing the same batch must descend
