"""Supervised connectors + deterministic fault injection.

Tier-1-safe battery: seeded fault plans (internals/faults.py), in-place
supervised restart with exactly-once rescan (io/_connector.py), permanent-
failure demotion through runtime.report_connector_error, the watchdog, the
_BACKLOG_CAP degradation surfacing, retry_on classification
(udfs/retries.py), and the subprocess kill-and-resume matrix
(scripts/fault_matrix.py). All schedules are seeded/deterministic and no
sleep exceeds ~1s."""

import asyncio
import json
import os
import sys
import time
from collections import Counter

import pytest

import pathway_tpu as pw
from pathway_tpu.internals import faults
from pathway_tpu.internals.monitoring import ProberStats
from pathway_tpu.io import SupervisorPolicy
from pathway_tpu.udfs import RetryPolicy

_SCRIPTS = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "scripts"
)
if _SCRIPTS not in sys.path:
    sys.path.insert(0, _SCRIPTS)
import fault_matrix  # noqa: E402


@pytest.fixture(autouse=True)
def _fault_isolation():
    faults.reset()
    yield
    faults.reset()


def _fast_policy(max_restarts=2, retry_on=None):
    return SupervisorPolicy(
        max_restarts=max_restarts,
        backoff=RetryPolicy(
            max_retries=max_restarts, initial_delay_ms=5, jitter_ms=0
        ),
        retry_on=retry_on,
    )


# ---------------------------------------------------------- fault plans


def test_fault_plan_fires_at_listed_hits():
    plan = faults.FaultPlan(
        [{"point": "connector.read", "hits": [2, 4]}], seed=1
    )
    faults.install_plan(plan)
    fired = []
    for i in range(1, 6):
        try:
            faults.fault_point("connector.read")
        except faults.InjectedFault as exc:
            fired.append((i, exc.hit, exc.retryable))
    assert fired == [(2, 2, True), (4, 4, True)]
    assert plan.hit_counts() == {"connector.read": 5}


def test_fault_plan_every_and_max_fires():
    faults.install_plan(
        {"rules": [{"point": "runtime.step", "every": 3, "max_fires": 2}]}
    )
    fired = []
    for i in range(1, 13):
        try:
            faults.fault_point("runtime.step")
        except faults.InjectedFault:
            fired.append(i)
    assert fired == [3, 6]  # capped at two fires


def test_fault_plan_points_are_independent_counters():
    faults.install_plan({"rules": [{"point": "connector.flush", "hits": [1]}]})
    faults.fault_point("connector.read")  # other point: no fire, own count
    with pytest.raises(faults.InjectedFault):
        faults.fault_point("connector.flush")


def test_fault_plan_prob_is_seed_deterministic():
    def pattern(seed):
        plan = faults.FaultPlan(
            [{"point": "runtime.step", "prob": 0.3, "max_fires": 1000}],
            seed=seed,
        )
        faults.install_plan(plan)
        out = []
        for i in range(60):
            try:
                faults.fault_point("runtime.step")
                out.append(0)
            except faults.InjectedFault:
                out.append(1)
        return out

    a, b = pattern(42), pattern(42)
    assert a == b
    assert 0 < sum(a) < 60  # actually probabilistic, not all-or-nothing


def test_fault_plan_env_roundtrip(monkeypatch):
    spec = {"rules": [{"point": "connector.read", "hits": [1],
                       "retryable": False}]}
    monkeypatch.setenv("PATHWAY_FAULT_PLAN", json.dumps(spec))
    faults.reset()
    with pytest.raises(faults.InjectedFault) as ei:
        faults.fault_point("connector.read")
    assert ei.value.retryable is False
    # clear_plan pins "no plan" even though the env var is still set
    faults.clear_plan()
    faults.fault_point("connector.read")


def test_fault_plan_rejects_unknown_action():
    with pytest.raises(ValueError):
        faults.FaultRule("connector.read", action="explode")


def test_fault_plan_rejects_unknown_point():
    # a typo'd point would otherwise never fire and pass tests vacuously
    with pytest.raises(ValueError, match="unknown injection point"):
        faults.FaultPlan([{"point": "connecter.read", "hits": [1]}])


def test_fault_plan_phase_scoped_counters():
    """A rule with a phase counts hits on the (point, phase) counter, so
    its schedule is independent of how other phases interleave."""
    faults.install_plan(
        {"rules": [
            {"point": "mesh.rank_kill", "phase": "wave_send", "hits": [2]},
        ]}
    )
    fired = []
    # interleave phases: wave_send hits are 1, 2 — the rule fires on the
    # SECOND wave_send even though it is the fourth overall hit
    for i, phase in enumerate(
        ["restore", "wave_send", "post_snapshot", "wave_send", "wave_send"]
    ):
        try:
            faults.fault_point("mesh.rank_kill", phase=phase)
        except faults.InjectedFault as exc:
            fired.append((i, phase, exc.hit))
    assert fired == [(3, "wave_send", 2)]
    counts = faults.active_plan().hit_counts()
    assert counts["mesh.rank_kill"] == 5
    assert counts["mesh.rank_kill#wave_send"] == 3


def test_fault_plan_phaseless_rule_ignores_phase_context():
    faults.install_plan({"rules": [{"point": "mesh.rank_kill", "hits": [2]}]})
    faults.fault_point("mesh.rank_kill", phase="restore")
    with pytest.raises(faults.InjectedFault):
        faults.fault_point("mesh.rank_kill", phase="wave_send")


def test_fault_plan_rank_scoped_rule():
    """One shared PATHWAY_FAULT_PLAN can name its victim rank: the rule
    only fires in the process whose config process_id matches."""
    from pathway_tpu.internals.config import (
        pop_config_overlay,
        push_config_overlay,
    )

    faults.install_plan(
        {"rules": [{"point": "mesh.send", "rank": 1}]}  # every hit, rank 1
    )
    faults.fault_point("mesh.send")  # this process is rank 0: no fire
    tok = push_config_overlay(process_id=1)
    try:
        with pytest.raises(faults.InjectedFault):
            faults.fault_point("mesh.send")
    finally:
        pop_config_overlay(tok)


# ------------------------------------------------------- RetryPolicy


def test_retry_policy_sync_invoke_and_schedule():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise TimeoutError("transient")
        return 42

    pol = RetryPolicy(max_retries=3, initial_delay_ms=1, jitter_ms=0)
    assert pol.invoke_sync(flaky) == 42
    assert len(calls) == 3
    # deterministic, capped exponential schedule with seeded jitter
    import random

    pol2 = RetryPolicy(
        max_retries=5, initial_delay_ms=100, backoff_factor=2.0,
        jitter_ms=0, max_delay_ms=250, rng=random.Random(0),
    )
    assert [pol2.delay_s(a) for a in range(4)] == [0.1, 0.2, 0.25, 0.25]


def test_retry_policy_honors_retryable_attribute_by_default():
    pol = RetryPolicy(max_retries=5)
    fatal = faults.InjectedFault("p", 1, retryable=False)
    assert not pol.should_retry(fatal, 0)
    assert pol.should_retry(RuntimeError("x"), 0)


def test_retry_policy_retry_on_fails_fast():
    calls = []

    def auth_error():
        calls.append(1)
        raise PermissionError("bad credentials")

    pol = RetryPolicy(
        max_retries=5, initial_delay_ms=1, jitter_ms=0,
        retry_on=lambda exc: not isinstance(exc, PermissionError),
    )
    with pytest.raises(PermissionError):
        pol.invoke_sync(auth_error)
    assert len(calls) == 1


def test_async_strategies_retry_on():
    # fail fast on non-retryable classification
    strat = pw.udfs.ExponentialBackoffRetryStrategy(
        max_retries=3, initial_delay=1, jitter_ms=0,
        retry_on=lambda exc: isinstance(exc, TimeoutError),
    )
    calls = []

    async def auth_boom():
        calls.append(1)
        raise ValueError("schema mismatch")

    with pytest.raises(ValueError):
        asyncio.run(strat.invoke(auth_boom))
    assert len(calls) == 1

    # retryable classification still retries
    tries = []

    async def flaky():
        tries.append(1)
        if len(tries) < 3:
            raise TimeoutError("transient")
        return "ok"

    assert asyncio.run(strat.invoke(flaky)) == "ok"
    assert len(tries) == 3

    # default preserves the historical retry-everything behavior
    legacy = pw.udfs.FixedDelayRetryStrategy(max_retries=2, delay_ms=1)
    again = []

    async def always():
        again.append(1)
        raise ValueError("still broken")

    with pytest.raises(ValueError):
        asyncio.run(legacy.invoke(always))
    assert len(again) == 3  # 1 + 2 retries, ValueError retried by default


# ------------------------------------------- in-place supervised restart


class _S(pw.Schema):
    k: int


class _SPk(pw.Schema):
    k: int = pw.column_definition(primary_key=True)


def _run_collect(subject, schema, **run_kwargs):
    rows = pw.io.python.read(
        subject, schema=schema, autocommit_duration_ms=0, name="src"
    )
    events = []
    pw.io.subscribe(
        rows,
        on_change=lambda key, row, time_, diff: events.append(
            (row["k"], 1 if diff > 0 else -1)
        ),
    )
    pw.run(**run_kwargs)
    return events


class _RescanSrc(pw.io.python.ConnectorSubject):
    """Stateful, rescannable, fails once mid-span (between commit
    boundaries) on the first attempt."""

    def __init__(self, n=9, fail_pos=5):
        super().__init__()
        self.n = n
        self.fail_pos = fail_pos
        self.pos = 0
        self.attempts = 0

    def run(self):
        self.attempts += 1
        while self.pos < self.n:
            i = self.pos
            self.next(k=i)
            self.pos = i + 1
            if self.pos % 3 == 0:
                self.commit()
            if self.attempts == 1 and self.pos == self.fail_pos:
                raise ConnectionError("transient source failure")

    def snapshot_state(self):
        return {"pos": self.pos}

    def seek(self, state):
        self.pos = state["pos"]


def test_stateful_rescan_restart_is_exactly_once_keyless():
    src = _RescanSrc()
    src._supervisor_policy = _fast_policy()
    events = _run_collect(src, _S)
    assert src.attempts == 2
    net = Counter()
    for k, d in events:
        net[k] += d
    # no loss, no double-replay: every key nets exactly one insertion
    assert dict(net) == {k: 1 for k in range(9)}
    # the mid-span rows really were re-delivered: forwarded pre-failure,
    # retracted by the supervisor, re-emitted by the rescan
    by_key = Counter(events)
    assert by_key[(4, 1)] == 2 and by_key[(4, -1)] == 1


def test_stateful_restart_before_first_commit_is_exactly_once():
    """A failure BEFORE the first commit boundary rolls back to the
    subject's captured pre-run position (there is no published state
    yet) — retract-forwarded + rescan-from-zero, no loss."""
    src = _RescanSrc(fail_pos=2)  # boundary would be at pos 3
    src._supervisor_policy = _fast_policy()
    events = _run_collect(src, _S)
    assert src.attempts == 2
    net = Counter()
    for k, d in events:
        net[k] += d
    assert dict(net) == {k: 1 for k in range(9)}


def test_raising_retry_on_callback_does_not_hang_pipeline():
    """A user retry_on callback that itself raises is a permanent
    failure, not a lost finish sentinel: the run must terminate."""
    src = _CountingSrc()
    src._supervisor_policy = _fast_policy(
        retry_on=lambda exc: exc.unknown_attribute  # AttributeError
    )
    faults.install_plan({"rules": [{"point": "connector.read", "hits": [3]}]})
    with pytest.raises(AttributeError):
        _run_collect(src, _S)


def test_stateful_rescan_restart_is_exactly_once_upsert_keys():
    src = _RescanSrc()
    src._deletions_enabled = False  # pure upserts: rescan is idempotent
    src._supervisor_policy = _fast_policy()
    events = _run_collect(src, _SPk)
    assert src.attempts == 2
    net = Counter()
    for k, d in events:
        net[k] += d
    assert dict(net) == {k: 1 for k in range(9)}


def test_upsert_restart_then_process_restart_loses_nothing(tmp_path):
    """In-place upsert rescan keeps the forwarded-but-unjournaled ledger:
    the next boundary must journal the ORIGINAL inserts too, or a later
    process restart consolidates the rescan's retract/insert pairs to
    nothing and silently drops the mid-span rows."""
    cfg = pw.persistence.Config(
        backend=pw.persistence.Backend.filesystem(str(tmp_path))
    )
    src = _RescanSrc()
    src._deletions_enabled = False
    src._supervisor_policy = _fast_policy()
    rows = pw.io.python.read(
        src, schema=_SPk, autocommit_duration_ms=0, name="ups"
    )
    pw.io.subscribe(rows, on_change=lambda *a: None)
    pw.run(persistence_config=cfg)
    assert src.attempts == 2

    # the journal's net content covers every row exactly once
    from pathway_tpu.persistence import PersistenceManager

    net = Counter()
    for _t, deltas, _s in PersistenceManager(cfg).load_journal("ups"):
        for key, row, diff in deltas:
            net[(key, tuple(row))] += diff
    assert sorted(net.values()) == [1] * 9, net

    # process restart: replay + seek reproduces the full table
    pw.internals.parse_graph.G.clear()
    src2 = _RescanSrc()
    src2._deletions_enabled = False
    rows2 = pw.io.python.read(
        src2, schema=_SPk, autocommit_duration_ms=0, name="ups"
    )
    got = []
    pw.io.subscribe(
        rows2,
        on_change=lambda key, row, t, d: got.append(
            (row["k"], 1 if d > 0 else -1)
        ),
    )
    pw.run(persistence_config=cfg)
    net2 = Counter()
    for k, d in got:
        net2[k] += d
    assert dict(net2) == {k: 1 for k in range(9)}


def test_pk_source_with_deletions_restarts_as_continuation():
    """pk sessions that may see removes are rescan-unsafe (a re-scanned
    remove would retract twice): restart continues from the subject's
    own cursor instead, which is still loss- and duplicate-free here."""
    src = _RescanSrc()  # _deletions_enabled defaults True
    src._supervisor_policy = _fast_policy()
    events = _run_collect(src, _SPk)
    assert src.attempts == 2
    net = Counter()
    for k, d in events:
        net[k] += d
    assert dict(net) == {k: 1 for k in range(9)}
    # continuation, not rescan: nothing was retracted or re-delivered
    assert all(d > 0 for _, d in events)


class _CountingSrc(pw.io.python.ConnectorSubject):
    """Stateless: keeps its own cursor, so a restart continues in place."""

    def __init__(self, n=8):
        super().__init__()
        self.n = n
        self.i = 0
        self.attempts = 0

    def run(self):
        self.attempts += 1
        while self.i < self.n:
            self.next(k=self.i)
            self.i += 1


def test_injected_transient_fault_recovers_within_budget():
    # fault plan (not subject code) injects the failure: emit hit 4 raises
    # a retryable InjectedFault out of subject.run(); the supervisor
    # restarts and the subject's own cursor resumes exactly
    faults.install_plan(
        {"rules": [{"point": "connector.read", "hits": [4]}]}
    )
    src = _CountingSrc()
    src._supervisor_policy = _fast_policy()
    events = _run_collect(src, _S)
    assert src.attempts == 2
    assert sorted(k for k, d in events if d > 0) == list(range(8))


def test_default_policy_does_not_restart_plain_stateless_subjects():
    """Re-running a non-rescannable, non-upsert subject is not provably
    duplicate-free, so without an explicit policy it keeps the historical
    fail-fast behavior."""
    src = _CountingSrc()  # no _supervisor_policy attached
    faults.install_plan({"rules": [{"point": "connector.read", "hits": [3]}]})
    with pytest.raises(faults.InjectedFault):
        _run_collect(src, _S)
    assert src.attempts == 1


class _SnapFailSrc(pw.io.python.ConnectorSubject):
    """snapshot_state itself fails transiently at the first mid-run commit
    boundary — the compensation ledger must survive the failed boundary so
    the supervised rescan stays exactly-once."""

    def __init__(self):
        super().__init__()
        self.pos = 0
        self.attempts = 0
        self.snaps = 0

    def run(self):
        self.attempts += 1
        while self.pos < 6:
            self.next(k=self.pos)
            self.pos += 1
            if self.pos == 3:
                self.commit()

    def snapshot_state(self):
        self.snaps += 1
        if self.snaps == 2:  # 1 = the supervisor's initial capture
            raise OSError("snapshot backend hiccup")
        return {"pos": self.pos}

    def seek(self, state):
        self.pos = state["pos"]


def test_snapshot_failure_mid_boundary_stays_exactly_once():
    src = _SnapFailSrc()
    src._supervisor_policy = _fast_policy()
    events = _run_collect(src, _S)
    assert src.attempts == 2
    net = Counter()
    for k, d in events:
        net[k] += d
    assert dict(net) == {k: 1 for k in range(6)}


def test_fatal_fault_classification_fails_fast():
    faults.install_plan(
        {"rules": [{"point": "connector.read", "hits": [2],
                    "retryable": False}]}
    )
    src = _CountingSrc()
    src._supervisor_policy = _fast_policy(max_restarts=3)
    with pytest.raises(faults.InjectedFault):
        _run_collect(src, _S)
    assert src.attempts == 1  # no retry for a non-retryable failure


class _DoomedSrc(pw.io.python.ConnectorSubject):
    def __init__(self):
        super().__init__()
        self.attempts = 0

    def run(self):
        self.attempts += 1
        if self.attempts == 1:
            for i in range(3):
                self.next(k=i)
            self.commit()
        raise ValueError("permanently broken source")


def test_budget_exhausted_terminate_on_error_raises():
    src = _DoomedSrc()
    src._supervisor_policy = _fast_policy(max_restarts=1)
    with pytest.raises(ValueError, match="permanently broken"):
        _run_collect(src, _S)
    assert src.attempts == 2  # initial + one restart


def test_budget_exhausted_demotes_without_abort():
    """terminate_on_error=False: the failed connector demotes to finished,
    the rows it delivered stay, and the failure lands in the error log."""
    src = _DoomedSrc()
    src._supervisor_policy = _fast_policy(max_restarts=1)
    rows = pw.io.python.read(
        src, schema=_S, autocommit_duration_ms=0, name="doomed"
    )
    got = []
    pw.io.subscribe(
        rows, on_change=lambda key, row, t, diff: got.append(row["k"])
    )
    log = pw.global_error_log()
    log_rows = []
    pw.io.subscribe(
        log,
        on_change=lambda key, row, t, diff: log_rows.append(row["message"]),
    )
    pw.run(terminate_on_error=False)  # completes: no abort
    assert sorted(got) == [0, 1, 2]
    assert src.attempts == 2
    errors = [m for m in log_rows if "failed permanently" in m]
    assert errors and "ValueError" in errors[0]
    restarts = [m for m in log_rows if "connector-restart" in m]
    assert len(restarts) == 1


class _SleepySrc(pw.io.python.ConnectorSubject):
    """Stalls (no emits, no flushes) past its watchdog window, then
    recovers — the runtime must flag the stall without killing the run."""

    _watchdog_timeout_s = 0.15

    def __init__(self):
        super().__init__()

    def run(self):
        time.sleep(0.8)
        self.next(k=1)


def test_watchdog_flags_stalled_subject():
    src = _SleepySrc()
    rows = pw.io.python.read(
        src, schema=_S, autocommit_duration_ms=10, name="sleepy"
    )
    got = []
    pw.io.subscribe(
        rows, on_change=lambda key, row, t, diff: got.append(row["k"])
    )
    log_rows = []
    pw.io.subscribe(
        pw.global_error_log(),
        on_change=lambda key, row, t, diff: log_rows.append(row["message"]),
    )
    pw.run()
    assert got == [1]  # the stall resolved; pipeline finished normally
    assert any("connector-stall" in m for m in log_rows)


class _NoCommitSrc(pw.io.python.ConnectorSubject):
    """Stateful subject that never calls commit(): its backlog overflows
    _BACKLOG_CAP and recovery degrades to at-least-once for the span."""

    def __init__(self, n=10):
        super().__init__()
        self.n = n

    def run(self):
        for i in range(self.n):
            self.next(k=i)

    def snapshot_state(self):
        return {}


def test_backlog_cap_degradation_reaches_error_log(monkeypatch):
    monkeypatch.setattr("pathway_tpu.io._connector._BACKLOG_CAP", 3)
    src = _NoCommitSrc()
    rows = pw.io.python.read(
        src, schema=_S, autocommit_duration_ms=0, name="nocommit"
    )
    got = []
    pw.io.subscribe(
        rows, on_change=lambda key, row, t, diff: got.append(row["k"])
    )
    log_rows = []
    pw.io.subscribe(
        pw.global_error_log(),
        on_change=lambda key, row, t, diff: log_rows.append(row["message"]),
    )
    pw.run(
        persistence_config=pw.persistence.Config(
            backend=pw.persistence.Backend.memory()
        )
    )
    assert sorted(got) == list(range(10))  # data still flows
    assert any(
        "connector-degraded" in m and "at-least-once" in m for m in log_rows
    )


def _bare_conn(subject, parser):
    import types

    return types.SimpleNamespace(
        subject=subject,
        parser=parser,
        name="unit",
        node=types.SimpleNamespace(
            scope=types.SimpleNamespace(runtime=None)
        ),
    )


def test_parse_failure_is_nonretryable_and_sentinel_arrives():
    """A deterministic parse failure may have half-applied stateful parser
    sessions — it must fail fast (never rescan) AND the finish sentinel
    must still reach the queue."""
    import queue
    import threading

    from pathway_tpu.io._connector import run_connector_thread

    class _Subj(pw.io.python.ConnectorSubject):
        _autocommit_duration_ms = 0

        def run(self):
            self._emit(("row", "a"))

    def bad_parser(msg):
        raise KeyError("schema mismatch")

    conn = _bare_conn(_Subj(), bad_parser)
    q = queue.Queue()
    t = threading.Thread(
        target=run_connector_thread, args=(conn, q), daemon=True
    )
    t.start()
    t.join(5)
    assert not t.is_alive()
    assert q.get(timeout=5)[1] is None  # finish sentinel
    assert isinstance(conn.failure, KeyError)
    assert conn.failure.retryable is False  # classified as poison


def test_prologue_failure_still_enqueues_finish_sentinel():
    """Even a failure resolving the supervisor policy itself must not
    strand the main loop waiting for the sentinel."""
    import queue
    import threading

    from pathway_tpu.io._connector import run_connector_thread

    class _EvilSubject:
        @property
        def _supervisor_policy(self):
            raise RuntimeError("broken policy resolution")

        def run(self):
            raise AssertionError("never reached")

    conn = _bare_conn(_EvilSubject(), lambda m: [])
    q = queue.Queue()
    t = threading.Thread(
        target=run_connector_thread, args=(conn, q), daemon=True
    )
    t.start()
    t.join(5)
    assert not t.is_alive()
    assert q.get(timeout=5)[1] is None
    assert "broken policy" in str(conn.failure)


def test_prober_stats_health_counters_render():
    stats = ProberStats()
    stats.on_connector_restart("c1")
    stats.on_connector_restart("c1")
    stats.on_connector_error("c1")
    stats.on_connector_stall("c2")
    stats.on_connector_degraded("c1")
    stats.on_mesh_heartbeat_missed(3)
    stats.on_mesh_rank_restart()
    stats.on_mesh_rollback()
    stats.on_mesh_epoch_committed(2)
    text = stats.render_openmetrics()
    assert 'connector_restarts_total{connector="c1"} 2' in text
    assert 'connector_errors_total{connector="c1"} 1' in text
    assert 'connector_stalls_total{connector="c2"} 1' in text
    assert 'connector_degraded_total{connector="c1"} 1' in text
    assert "mesh_heartbeats_missed_total 3" in text
    assert "mesh_rank_restarts_total 1" in text
    assert "mesh_rollbacks_total 1" in text
    assert "mesh_last_committed_epoch 2" in text
    assert "restarts=2" in stats.render_text()


# ------------------------------------------------ kill-and-resume battery


_BATTERY_CELLS = [
    ("connector.read", "persist"),
    ("connector.flush", "persist"),
    ("persistence.journal_write", "persist"),
    ("persistence.journal_write.post", "persist"),
    ("persistence.checkpoint", "operator"),
    ("connector.read", "stateless"),
]


@pytest.mark.parametrize("point,mode", _BATTERY_CELLS)
def test_fault_battery_kill_and_resume(tmp_path, point, mode):
    """Seeded crash at the injection point, then resume: the final table
    must match the fault-free expectation exactly (exactly-once for the
    stateful scenario; loss-free at-least-once for the stateless one)."""
    if os.environ.get("PATHWAY_LANE_PROCESSES"):
        pytest.skip("subprocess kill timing incompatible with the lane")
    res = fault_matrix.run_cell(
        point, mode=mode, hit=2, tmp=str(tmp_path), n_rows=24
    )
    assert res.ok, f"{point}/{mode}: {res.detail}"


# ------------------------------------------------- mesh rollback recovery
#
# The 2-rank analogue of the battery above (ISSUE 4): a rank is
# hard-killed at a mesh.rank_kill phase, the SURVIVOR must detect the
# loss and abort the epoch cleanly (exit MESH_RESTART_EXIT_CODE — no
# hang, no mid-wave deadlock), and the resumed 2-rank run must restore
# the last committed distributed snapshot and produce final captures
# bit-identical to an uninterrupted run. One wave_send cell per exchange
# path rides tier-1; the full phase × victim grid is `slow` (run by
# `python scripts/fault_matrix.py --mesh --mesh-no-nb` and ci_lanes).


def _mesh_cell(tmp_path, phase, victim, hit, extra_env=None):
    if os.environ.get("PATHWAY_LANE_PROCESSES"):
        pytest.skip("real-fork mesh battery incompatible with the lane")
    res = fault_matrix.run_mesh_cell(
        phase, victim=victim, hit=hit, tmp=str(tmp_path), n_rows=40,
        extra_env=extra_env,
    )
    assert res.ok, f"{res.point}/{res.mode}: {res.detail}"


def test_mesh_kill_and_resume_wave_send_columnar(tmp_path):
    _mesh_cell(tmp_path, "wave_send", victim=1, hit=3)


def test_mesh_kill_and_resume_wave_send_tuple_path(tmp_path):
    _mesh_cell(
        tmp_path, "wave_send", victim=1, hit=3,
        extra_env={"PATHWAY_NO_NB_EXCHANGE": "1"},
    )


@pytest.mark.slow
@pytest.mark.parametrize(
    "phase,victim,hit",
    [("wave_send", 0, 3), ("post_snapshot", 1, 2), ("restore", 1, 1)],
)
def test_mesh_kill_and_resume_full_grid(tmp_path, phase, victim, hit):
    _mesh_cell(tmp_path, phase, victim, hit)


def test_mesh_supervisor_kill_and_resume_smoke(tmp_path):
    """End-to-end rollback recovery in ONE supervised invocation: a
    rank-scoped fault plan (shared env) kills rank 1 mid-wave at epoch 0;
    rank 0 detects the crash and exits MESH_RESTART_EXIT_CODE; the
    supervisor respawns both ranks at epoch 1 (fresh mesh handshake,
    fault plan stripped), they restore the committed snapshot cut, rewind
    their connectors, and finish with output bit-identical to an
    uninterrupted run. This is ci_lanes.sh lane 3."""
    if os.environ.get("PATHWAY_LANE_PROCESSES"):
        pytest.skip("real-fork mesh battery incompatible with the lane")
    from pathway_tpu.internals.faults import CRASH_EXIT_CODE
    from pathway_tpu.parallel.supervisor import (
        MESH_RESTART_EXIT_CODE,
        MeshSupervisor,
    )

    tmp = str(tmp_path)
    script = os.path.join(tmp, "mesh_scenario.py")
    with open(script, "w") as f:
        f.write(fault_matrix.MESH_SCENARIO.format(repo=fault_matrix.REPO))
    n_rows = 40
    plan = {
        "seed": 7,
        "rules": [{
            "point": "mesh.rank_kill", "phase": "wave_send", "rank": 1,
            "hits": [3], "action": "crash",
        }],
    }
    sup = MeshSupervisor(
        [sys.executable, script, os.path.join(tmp, "pstorage"),
         os.path.join(tmp, "out"), str(n_rows)],
        processes=2,
        grace_s=30,
        env={
            "PATHWAY_FAULT_PLAN": json.dumps(plan),
            "PATHWAY_MESH_OP_TIMEOUT_S": "30",
            "PATHWAY_MESH_HEARTBEAT_S": "0.5",
            "PATHWAY_MESH_PEER_TIMEOUT_S": "5",
        },
    )
    rc = sup.run()
    assert rc == 0, sup.history
    assert sup.restarts_performed == 1, sup.history
    # epoch 0: rank 1 died by injection, rank 0 requested the rollback
    assert sup.history[0][1] == CRASH_EXIT_CODE
    assert sup.history[0][0] == MESH_RESTART_EXIT_CODE
    assert sup.history[1] == [0, 0]
    with open(os.path.join(tmp, "out.r0.json")) as f:
        got = json.load(f)
    assert got == fault_matrix.expected_counts(n_rows)


def test_mesh_supervisor_budget_exhausted_fails_cleanly():
    """A deterministically failing rank set burns the restart budget and
    the supervisor reports the failure instead of looping forever."""
    prog = "import sys; sys.exit(5)"
    from pathway_tpu.parallel.supervisor import MeshSupervisor

    sup = MeshSupervisor(
        [sys.executable, "-c", prog], processes=2, max_restarts=1,
        grace_s=2,
    )
    assert sup.run() == 5
    assert sup.restarts_performed == 1
    assert len(sup.history) == 2


def test_mesh_supervisor_bumps_epoch_and_strips_fault_plan():
    """Respawned epochs see PATHWAY_MESH_EPOCH=N and (by default) no
    PATHWAY_FAULT_PLAN — an injected crash behaves like the transient
    fault it models instead of recurring forever."""
    prog = (
        "import os, sys;"
        "sys.exit(27 if os.environ.get('PATHWAY_FAULT_PLAN')"
        " and os.environ['PATHWAY_PROCESS_ID'] == '1' else"
        " int(os.environ['PATHWAY_MESH_EPOCH']) - 1)"
    )
    from pathway_tpu.parallel.supervisor import MeshSupervisor

    sup = MeshSupervisor(
        [sys.executable, "-c", prog], processes=2, grace_s=2,
        env={"PATHWAY_FAULT_PLAN": '{"rules": []}'},
    )
    # epoch 0: rank 1 exits 27 (plan present); epoch 1: plan stripped,
    # both ranks exit int(epoch)-1 = 0
    assert sup.run() == 0
    assert sup.epoch == 1
    assert sup.restarts_performed == 1


def test_operator_snapshot_prune_retains_last_two_tags():
    """The snapshot prune keeps the just-committed AND the previously
    committed tag: a peer crashing between its restore-read of the
    marker and this prune must still find the snapshot it was loading
    (ISSUE 4 prune-race fix)."""
    cfg = pw.persistence.Config(backend=pw.persistence.Backend.memory())
    from pathway_tpu.persistence import PersistenceManager

    mgr = PersistenceManager(cfg)
    for tag in (3, 5, 8):
        mgr.save_operator_snapshot(
            [], {}, [], key=f"operator_snapshot/r0/{tag}"
        )
    mgr.backend.write("operator_snapshot/r0/not-a-tag", b"x")
    mgr.prune_operator_snapshots("operator_snapshot/r0/", {8, 5})
    assert mgr.list_keys("operator_snapshot/r0/") == [
        "operator_snapshot/r0/5",
        "operator_snapshot/r0/8",
        "operator_snapshot/r0/not-a-tag",  # foreign keys untouched
    ]
