"""Window operator battery — transliteration of the reference's windows
test corpus to this DSL (reference: python/pathway/tests/temporal/
test_windows.py — tumbling/sliding/session assignment, origins, floats,
datetimes, intervals_over, argument validation). Expectations are computed
by in-test oracles or written out by hand from the window definitions:

* tumbling(duration, origin): half-open [start, start+duration) aligned to
  origin (default 0);
* sliding(hop, duration, origin): every window [origin + k*hop, +duration)
  that contains the event;
* session(max_gap): events whose consecutive gap is < max_gap merge;
  window start/end are the min/max event times of the merged run;
* intervals_over(at, lower_bound, upper_bound): one window per `at` row
  collecting events with at+lower <= t <= at+upper.
"""

from __future__ import annotations

import math

import pytest

import pathway_tpu as pw
from pathway_tpu.internals.graph_runner import GraphRunner


def _rows(table):
    captures = GraphRunner().run_tables(table)
    return sorted(
        captures[0].state.rows.values(),
        key=lambda r: tuple((v is None, v) for v in r),
    )


def _markdown_of(cols, rows):
    lines = [" | ".join(cols)]
    for r in rows:
        lines.append(" | ".join("" if v is None else str(v) for v in r))
    return "\n".join(lines)


def _table_of(cols, rows):
    return pw.debug.table_from_markdown(_markdown_of(cols, rows))


# ---------------------------------------------------------------------------
# oracles


def tumbling_oracle(times, duration, origin=0):
    """[(start, end, [times...])] for every non-empty window."""
    byw = {}
    for t in times:
        k = math.floor((t - origin) / duration)
        start = origin + k * duration
        byw.setdefault((start, start + duration), []).append(t)
    return byw


def sliding_oracle(times, hop, duration, origin=0):
    byw = {}
    for t in times:
        # windows [origin + k*hop, +duration) containing t
        k_max = math.floor((t - origin) / hop)
        k = k_max
        while origin + k * hop + duration > t:
            start = origin + k * hop
            if start <= t:
                byw.setdefault((start, start + duration), []).append(t)
            k -= 1
    return byw


def session_oracle(times, max_gap):
    runs = []
    for t in sorted(times):
        if runs and t - runs[-1][-1] < max_gap:
            runs[-1].append(t)
        else:
            runs.append([t])
    return {(r[0], r[-1]): r for r in runs}


# ---------------------------------------------------------------------------
# tumbling


def test_tumbling_counts_and_edges():
    # events on exact boundaries land in the window they OPEN (half-open)
    times = [0, 4, 5, 9, 10, 14, 15]
    t = _table_of(["t"], [(x,) for x in times])
    res = t.windowby(t.t, window=pw.temporal.tumbling(duration=5)).reduce(
        start=pw.this._pw_window_start,
        end=pw.this._pw_window_end,
        c=pw.reducers.count(),
    )
    oracle = tumbling_oracle(times, 5)
    assert _rows(res) == sorted(
        (s, e, len(ts)) for (s, e), ts in oracle.items()
    )
    # boundary event 5 is in [5,10), not [0,5)
    assert (0, 5, 2) in _rows(res) and (5, 10, 2) in _rows(res)


def test_tumbling_negative_times():
    times = [-7, -5, -1, 0, 3]
    t = _table_of(["t"], [(x,) for x in times])
    res = t.windowby(t.t, window=pw.temporal.tumbling(duration=5)).reduce(
        start=pw.this._pw_window_start, c=pw.reducers.count()
    )
    assert _rows(res) == [(-10, 1), (-5, 2), (0, 2)]


def test_tumbling_origin_shifts_grid_and_drops_pre_origin():
    # reference semantics (test_windows.py:618): the grid starts AT the
    # origin; events before it belong to no window
    times = [1, 2, 3, 7, 8]
    t = _table_of(["t"], [(x,) for x in times])
    res = t.windowby(
        t.t, window=pw.temporal.tumbling(duration=5, origin=2)
    ).reduce(start=pw.this._pw_window_start, c=pw.reducers.count())
    assert _rows(res) == [(2, 2), (7, 2)]  # event t=1 dropped


def test_tumbling_float_durations():
    times = [0.0, 0.49, 0.5, 1.2, 1.49]
    t = _table_of(["t"], [(x,) for x in times])
    res = t.windowby(t.t, window=pw.temporal.tumbling(duration=0.5)).reduce(
        start=pw.this._pw_window_start, c=pw.reducers.count()
    )
    assert _rows(res) == [(0.0, 2), (0.5, 1), (1.0, 2)]


def test_tumbling_instance_partitions():
    rows = [("a", 1), ("a", 6), ("b", 1), ("b", 2), ("c", 11)]
    t = _table_of(["k", "t"], rows)
    res = t.windowby(
        t.t, window=pw.temporal.tumbling(duration=5), instance=t.k
    ).reduce(
        k=pw.this._pw_instance,
        start=pw.this._pw_window_start,
        c=pw.reducers.count(),
    )
    assert _rows(res) == [
        ("a", 0, 1),
        ("a", 5, 1),
        ("b", 0, 2),
        ("c", 10, 1),
    ]


def test_tumbling_with_other_reducers():
    rows = [(1, 10), (2, 20), (3, 30), (7, 70)]
    t = _table_of(["t", "v"], rows)
    res = t.windowby(t.t, window=pw.temporal.tumbling(duration=5)).reduce(
        start=pw.this._pw_window_start,
        s=pw.reducers.sum(pw.this.v),
        mx=pw.reducers.max(pw.this.v),
        mn=pw.reducers.min(pw.this.v),
        a=pw.reducers.avg(pw.this.v),
    )
    assert _rows(res) == [(0, 60, 30, 10, 20.0), (5, 70, 70, 70, 70.0)]


def test_tumbling_window_cols_available_in_this():
    t = _table_of(["t"], [(3,)])
    res = t.windowby(t.t, window=pw.temporal.tumbling(duration=4)).reduce(
        both=pw.this._pw_window_end - pw.this._pw_window_start,
    )
    assert _rows(res) == [(4,)]


# ---------------------------------------------------------------------------
# sliding


def test_sliding_overlapping_windows():
    times = [0, 1, 2, 3, 4, 5, 6]
    t = _table_of(["t"], [(x,) for x in times])
    res = t.windowby(
        t.t, window=pw.temporal.sliding(hop=2, duration=4)
    ).reduce(
        start=pw.this._pw_window_start,
        end=pw.this._pw_window_end,
        c=pw.reducers.count(),
    )
    oracle = sliding_oracle(times, 2, 4)
    assert _rows(res) == sorted(
        (s, e, len(ts)) for (s, e), ts in oracle.items()
    )


def test_sliding_larger_hop_skips_events():
    # hop > duration: gaps — events between windows appear in none
    times = [0, 1, 2, 3, 4, 5, 6, 7, 8, 9]
    t = _table_of(["t"], [(x,) for x in times])
    res = t.windowby(
        t.t, window=pw.temporal.sliding(hop=4, duration=2)
    ).reduce(start=pw.this._pw_window_start, c=pw.reducers.count())
    oracle = sliding_oracle(times, 4, 2)
    assert _rows(res) == sorted((s, len(ts)) for (s, _e), ts in oracle.items())
    # events 2, 3 fall between [0,2) and [4,6): never reduced
    covered = {t for ts in oracle.values() for t in ts}
    assert 2 not in covered and 3 not in covered


def test_sliding_origin():
    times = [1, 3, 5]
    t = _table_of(["t"], [(x,) for x in times])
    res = t.windowby(
        t.t, window=pw.temporal.sliding(hop=2, duration=2, origin=1)
    ).reduce(start=pw.this._pw_window_start, c=pw.reducers.count())
    assert _rows(res) == [(1, 1), (3, 1), (5, 1)]


def test_sliding_ratio():
    # ratio=k is sugar for duration = k * hop
    times = [0, 1, 2, 3]
    t = _table_of(["t"], [(x,) for x in times])
    r1 = t.windowby(
        t.t, window=pw.temporal.sliding(hop=2, ratio=2)
    ).reduce(start=pw.this._pw_window_start, c=pw.reducers.count())
    t2 = _table_of(["t"], [(x,) for x in times])
    r2 = t2.windowby(
        t2.t, window=pw.temporal.sliding(hop=2, duration=4)
    ).reduce(start=pw.this._pw_window_start, c=pw.reducers.count())
    assert _rows(r1) == _rows(r2)


def test_sliding_floats():
    times = [0.3, 0.7, 1.1]
    t = _table_of(["t"], [(x,) for x in times])
    res = t.windowby(
        t.t, window=pw.temporal.sliding(hop=0.5, duration=1.0)
    ).reduce(start=pw.this._pw_window_start, c=pw.reducers.count())
    oracle = sliding_oracle(times, 0.5, 1.0)
    got = _rows(res)
    want = sorted((s, len(ts)) for (s, _e), ts in oracle.items())
    assert len(got) == len(want)
    for (gs, gc), (ws, wc) in zip(got, want):
        assert gs == pytest.approx(ws) and gc == wc


def test_sliding_instance_and_value_reducers():
    rows = [("x", 0, 1), ("x", 1, 2), ("y", 1, 4)]
    t = _table_of(["k", "t", "v"], rows)
    res = t.windowby(
        t.t, window=pw.temporal.sliding(hop=1, duration=2), instance=t.k
    ).reduce(
        k=pw.this._pw_instance,
        start=pw.this._pw_window_start,
        s=pw.reducers.sum(pw.this.v),
    )
    assert _rows(res) == [
        ("x", -1, 1),
        ("x", 0, 3),
        ("x", 1, 2),
        ("y", 0, 4),
        ("y", 1, 4),
    ]


# ---------------------------------------------------------------------------
# session


def test_session_gap_strictness():
    # gaps strictly smaller than max_gap merge; equal gaps split
    times = [1.0, 1.1, 1.2, 3.0, 3.4, 3.5]
    t = _table_of(["t"], [(x,) for x in times])
    res = t.windowby(
        t.t, window=pw.temporal.session(max_gap=0.15)
    ).reduce(
        mn=pw.reducers.min(pw.this.t),
        c=pw.reducers.count(),
    )
    got = _rows(res)
    want = sorted(
        (min(run), len(run))
        for run in session_oracle(times, 0.15).values()
    )
    assert len(got) == len(want)
    for (gm, gc), (wm, wc) in zip(got, want):
        assert gm == pytest.approx(wm) and gc == wc
    # 3.0 alone (gap to 3.4 is 0.4 >= 0.15)
    assert any(gc == 1 and abs(gm - 3.0) < 1e-9 for gm, gc in got)


def test_session_single_event_windows():
    times = [0, 10, 20]
    t = _table_of(["t"], [(x,) for x in times])
    res = t.windowby(t.t, window=pw.temporal.session(max_gap=5)).reduce(
        start=pw.this._pw_window_start,
        end=pw.this._pw_window_end,
        c=pw.reducers.count(),
    )
    assert _rows(res) == [(0, 0, 1), (10, 10, 1), (20, 20, 1)]


def test_session_chain_merging_transitive():
    # each consecutive gap below max_gap: one long session even though
    # first-to-last exceeds the gap many times over
    times = [0, 4, 8, 12, 16]
    t = _table_of(["t"], [(x,) for x in times])
    res = t.windowby(t.t, window=pw.temporal.session(max_gap=5)).reduce(
        start=pw.this._pw_window_start,
        end=pw.this._pw_window_end,
        c=pw.reducers.count(),
    )
    assert _rows(res) == [(0, 16, 5)]


def test_session_predicate():
    # custom merge predicate instead of max_gap
    times = [1, 2, 3, 10, 11]
    t = _table_of(["t"], [(x,) for x in times])
    res = t.windowby(
        t.t,
        window=pw.temporal.session(predicate=lambda cur, nxt: nxt - cur <= 1),
    ).reduce(start=pw.this._pw_window_start, c=pw.reducers.count())
    assert _rows(res) == [(1, 3), (10, 2)]


def test_session_instances_do_not_merge_across():
    rows = [("a", 1), ("a", 2), ("b", 2), ("b", 3)]
    t = _table_of(["k", "t"], rows)
    res = t.windowby(
        t.t, window=pw.temporal.session(max_gap=5), instance=t.k
    ).reduce(
        k=pw.this._pw_instance,
        start=pw.this._pw_window_start,
        c=pw.reducers.count(),
    )
    assert _rows(res) == [("a", 1, 2), ("b", 2, 2)]


def test_session_duplicate_times():
    times = [1, 1, 1, 5, 5]
    t = _table_of(["t"], [(x,) for x in times])
    res = t.windowby(t.t, window=pw.temporal.session(max_gap=2)).reduce(
        start=pw.this._pw_window_start, c=pw.reducers.count()
    )
    assert _rows(res) == [(1, 3), (5, 2)]


# ---------------------------------------------------------------------------
# intervals_over


def test_intervals_over_basic():
    data_rows = [(1, 10), (2, 20), (3, 30), (7, 70), (8, 80)]
    t = _table_of(["t", "v"], data_rows)
    probes = _table_of(["at"], [(2,), (5,), (8,)])
    res = t.windowby(
        t.t,
        window=pw.temporal.intervals_over(
            at=probes.at, lower_bound=-2, upper_bound=1
        ),
    ).reduce(
        at=pw.this._pw_window_location,
        s=pw.reducers.sum(pw.this.v),
    )
    # at=2: t in [0,3] -> 10+20+30; at=5: t in [3,6] -> 30; at=8: [6,9] -> 150
    assert _rows(res) == [(2, 60), (5, 30), (8, 150)]


def test_intervals_over_outer_keeps_empty_probes():
    t = _table_of(["t", "v"], [(1, 10)])
    probes = _table_of(["at"], [(1,), (100,)])
    res = t.windowby(
        t.t,
        window=pw.temporal.intervals_over(
            at=probes.at, lower_bound=-1, upper_bound=1, is_outer=True
        ),
    ).reduce(
        at=pw.this._pw_window_location,
        c=pw.reducers.count(),
    )
    got = _rows(res)
    # outer: probe 100 appears with an empty window
    assert (1, 1) in got
    assert any(r[0] == 100 for r in got)


def test_intervals_over_inner_drops_empty_probes():
    t = _table_of(["t", "v"], [(1, 10)])
    probes = _table_of(["at"], [(1,), (100,)])
    res = t.windowby(
        t.t,
        window=pw.temporal.intervals_over(
            at=probes.at, lower_bound=-1, upper_bound=1, is_outer=False
        ),
    ).reduce(
        at=pw.this._pw_window_location,
        c=pw.reducers.count(),
    )
    assert _rows(res) == [(1, 1)]


def test_intervals_over_same_table():
    # probing a table against itself: each row sees its neighborhood
    times = [0, 2, 4, 6]
    t = _table_of(["t"], [(x,) for x in times])
    res = t.windowby(
        t.t,
        window=pw.temporal.intervals_over(
            at=t.t, lower_bound=-2, upper_bound=2
        ),
    ).reduce(
        at=pw.this._pw_window_location,
        c=pw.reducers.count(),
    )
    assert _rows(res) == [(0, 2), (2, 3), (4, 3), (6, 2)]


def test_intervals_over_tuple_collection():
    t = _table_of(["t", "v"], [(1, 5), (2, 6), (3, 7)])
    probes = _table_of(["at"], [(2,)])
    res = t.windowby(
        t.t,
        window=pw.temporal.intervals_over(
            at=probes.at, lower_bound=-1, upper_bound=1
        ),
    ).reduce(
        at=pw.this._pw_window_location,
        vs=pw.reducers.sorted_tuple(pw.this.v),
    )
    assert _rows(res) == [(2, (5, 6, 7))]


# ---------------------------------------------------------------------------
# argument validation


def test_tumbling_duration_required_positive():
    with pytest.raises(ValueError):
        pw.temporal.tumbling(duration=0)
    with pytest.raises(ValueError):
        pw.temporal.tumbling(duration=-3)
    with pytest.raises(ValueError):
        pw.temporal.sliding(hop=0, duration=1)


def test_sliding_requires_duration_or_ratio():
    with pytest.raises((ValueError, TypeError)):
        pw.temporal.sliding(hop=2)


def test_sliding_rejects_duration_and_ratio_together():
    with pytest.raises((ValueError, TypeError)):
        pw.temporal.sliding(hop=2, duration=4, ratio=2)


def test_session_requires_exactly_one_of_gap_predicate():
    with pytest.raises((ValueError, TypeError)):
        pw.temporal.session()
    with pytest.raises((ValueError, TypeError)):
        pw.temporal.session(max_gap=1, predicate=lambda a, b: True)


# ---------------------------------------------------------------------------
# seeded oracle sweeps — the "automatic" battery


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_tumbling_oracle_sweep(seed):
    import random

    rng = random.Random(seed)
    times = [rng.randint(-50, 50) for _ in range(60)]
    t = _table_of(["t"], [(x,) for x in times])
    res = t.windowby(t.t, window=pw.temporal.tumbling(duration=7)).reduce(
        start=pw.this._pw_window_start, c=pw.reducers.count()
    )
    oracle = tumbling_oracle(times, 7)
    assert _rows(res) == sorted(
        (s, len(ts)) for (s, _e), ts in oracle.items()
    )


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_sliding_oracle_sweep(seed):
    import random

    rng = random.Random(seed)
    times = [rng.randint(-30, 30) for _ in range(40)]
    t = _table_of(["t"], [(x,) for x in times])
    res = t.windowby(
        t.t, window=pw.temporal.sliding(hop=3, duration=8)
    ).reduce(start=pw.this._pw_window_start, c=pw.reducers.count())
    oracle = sliding_oracle(times, 3, 8)
    assert _rows(res) == sorted(
        (s, len(ts)) for (s, _e), ts in oracle.items()
    )


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_session_oracle_sweep(seed):
    import random

    rng = random.Random(seed)
    times = sorted({rng.randint(0, 200) for _ in range(50)})
    t = _table_of(["t"], [(x,) for x in times])
    res = t.windowby(t.t, window=pw.temporal.session(max_gap=4)).reduce(
        start=pw.this._pw_window_start,
        end=pw.this._pw_window_end,
        c=pw.reducers.count(),
    )
    oracle = session_oracle(times, 4)
    assert _rows(res) == sorted(
        (s, e, len(ts)) for (s, e), ts in oracle.items()
    )
