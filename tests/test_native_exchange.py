"""Columnar exchange battery: the multi-rank analogue of the fused
chain (ISSUE 3).

Pins:
* shard parity — exec.cpp ``shard_partition_nb`` mints the exact shard
  ids of procgroup ``stable_shard`` (tuple keys, by-id keys, every
  columnar dtype), so columnar and tuple routing interoperate;
* wire codecs — nb_encode/nb_decode and deltas_encode/deltas_decode
  round-trip bit-exactly, reject truncated frames, and fall back to
  pickle for object cells;
* end-to-end bit-identity — 2-rank wordcount/join/groupby results equal
  the single-rank run on BOTH the columnar path and the
  ``PATHWAY_NO_NB_EXCHANGE=1`` tuple path, object-column batches
  degrade gracefully, and the comms counters show columnar batches
  flowing and empty all-to-all legs elided;
* mesh hygiene — the PATHWAY_MESH_MAX_FRAME_MB receiver cap turns a
  corrupt length prefix into a clean ConnectionError.
"""

import json
import os
import socket
import subprocess
import sys
import tempfile
import threading

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _pwexec():
    from pathway_tpu.native import get_pwexec

    return get_pwexec()


def _free_port_base(n: int = 4) -> int:
    for _ in range(50):
        probe = socket.socket()
        probe.bind(("127.0.0.1", 0))
        base = probe.getsockname()[1]
        probe.close()
        held = []
        try:
            for i in range(n):
                s = socket.socket()
                s.bind(("127.0.0.1", base + i))
                held.append(s)
            return base
        except OSError:
            continue
        finally:
            for s in held:
                s.close()
    raise RuntimeError("no consecutive free port range found")


# ---------------------------------------------------------------------------
# shard parity + codecs (no subprocesses)
# ---------------------------------------------------------------------------


def test_stable_shard_many_matches_scalar():
    from pathway_tpu.internals.api import Pointer
    from pathway_tpu.parallel.procgroup import stable_shard, stable_shard_many

    values = [
        ("word",),
        ("a", 1),
        (None,),
        (1.5, True),
        Pointer(2**100 + 17),
        ("",),
        (-(2**63),),
    ]
    for world in (1, 2, 3, 7):
        assert stable_shard_many(values, world) == [
            stable_shard(v, world) for v in values
        ]


def _mixed_nb():
    ex = _pwexec()
    if ex is None or not hasattr(ex, "shard_partition_nb"):
        pytest.skip("native toolchain unavailable")
    from pathway_tpu.internals.api import Pointer

    msgs = [
        {
            "a": f"word{i % 7}" * (1 + i % 3),
            "b": i * 3 - 50,
            "c": float(i) * 1.5,
            "d": None if i % 3 else (i % 2 == 0),
        }
        for i in range(257)
    ]
    msgs.append({"a": "", "b": -(2**63), "c": -0.0, "d": False})
    msgs.append({"a": "x" * 300, "b": 2**63 - 1, "c": 1e308, "d": None})
    nb, _seq = ex.parse_upserts_nb(
        msgs, 0, ("a", "b", "c", "d"), (None, None, None, None),
        1234567890123456789012345678901234567, 0, Pointer,
    )
    assert nb is not None and len(nb) == len(msgs)
    return ex, nb


def test_shard_partition_nb_parity_with_stable_shard():
    ex, nb = _mixed_nb()
    from pathway_tpu.parallel.procgroup import stable_shard

    mat = nb.materialize()
    for world in (2, 3, 5):
        for kidx in [(0,), (1, 2), (0, 1, 2, 3), (3,)]:
            parts = ex.shard_partition_nb(nb, kidx, world)
            assert len(parts) == world
            expect: list[list] = [[] for _ in range(world)]
            for k, row, d in mat:
                pk = tuple(row[i] for i in kidx)
                expect[stable_shard(pk, world)].append((int(k), row, d))
            got = [
                [(int(k), row, d) for k, row, d in p.materialize()]
                for p in parts
            ]
            assert got == expect, (world, kidx)


def test_shard_partition_nb_by_id_parity():
    ex, nb = _mixed_nb()
    from pathway_tpu.parallel.procgroup import stable_shard

    mat = nb.materialize()
    for world in (2, 4):
        parts = ex.shard_partition_nb(nb, None, world)
        expect: list[list] = [[] for _ in range(world)]
        for k, row, d in mat:
            expect[stable_shard(k, world)].append((int(k), d))
        got = [
            [(int(k), d) for k, _r, d in p.materialize()] for p in parts
        ]
        assert got == expect


def test_nb_codec_roundtrip_and_truncation():
    ex, nb = _mixed_nb()
    from pathway_tpu.internals.api import Pointer

    enc = ex.nb_encode(nb)
    dec = ex.nb_decode(enc, Pointer)
    assert dec.materialize() == nb.materialize()
    # empty batch round-trips too (the elided-slice degenerate case)
    empty = ex.shard_partition_nb(nb, (0,), 10_000)
    empty = next(p for p in empty if len(p) == 0)
    assert ex.nb_decode(ex.nb_encode(empty), Pointer).materialize() == []
    for cut in (0, 4, 11, len(enc) // 2, len(enc) - 1):
        with pytest.raises(ValueError):
            ex.nb_decode(enc[:cut], Pointer)


def test_nb_concat_matches_materialized_union():
    ex, nb = _mixed_nb()
    parts = ex.shard_partition_nb(nb, (0,), 3)
    cat = ex.nb_concat(list(parts))
    merged = []
    for p in parts:
        merged.extend(p.materialize())
    assert cat.materialize() == merged
    assert len(cat) == len(nb)


def test_deltas_codec_roundtrip_and_object_fallback():
    ex = _pwexec()
    if ex is None or not hasattr(ex, "deltas_encode"):
        pytest.skip("native toolchain unavailable")
    from pathway_tpu.internals.api import Pointer

    deltas = [
        (
            Pointer(2**100 + i),
            (f"w{i % 5}", i - 30, 1.5 * i, None, i % 2 == 0),
            (-1) ** i * (1 + i % 3),
        )
        for i in range(400)
    ]
    enc = ex.deltas_encode(deltas)
    assert enc is not None
    assert ex.deltas_decode(enc, Pointer) == deltas
    assert ex.deltas_decode(ex.deltas_encode([]), Pointer) == []
    # object cells -> None (the caller pickles instead)
    assert ex.deltas_encode([(Pointer(1), ((1, 2),), 1)]) is None
    assert ex.deltas_encode([(Pointer(1), (b"bytes",), 1)]) is None
    with pytest.raises(ValueError):
        ex.deltas_decode(enc[: len(enc) - 3], Pointer)


# ---------------------------------------------------------------------------
# mesh frame-size cap
# ---------------------------------------------------------------------------


def _mesh_pair(port):
    from pathway_tpu.parallel.procgroup import ProcessGroup

    holder = {}
    errs = []

    def mk1():
        try:
            holder[1] = ProcessGroup(1, 2, port)
        except Exception as exc:  # pragma: no cover - surfaced below
            errs.append(exc)

    t = threading.Thread(target=mk1, daemon=True)
    t.start()
    holder[0] = ProcessGroup(0, 2, port)
    t.join(30)
    assert not errs, errs
    return holder[0], holder[1]


def test_collective_timeout_names_peer_and_tag(monkeypatch):
    """PATHWAY_MESH_OP_TIMEOUT_S bounds every collective: a recv blocked
    on a silent-but-connected peer raises a ConnectionError naming the
    peer rank and the pending tag instead of hanging forever."""
    monkeypatch.setenv("PATHWAY_MESH_OP_TIMEOUT_S", "0.4")
    monkeypatch.setenv("PATHWAY_MESH_HEARTBEAT_S", "0.1")
    pg0, pg1 = _mesh_pair(_free_port_base(2))
    from pathway_tpu.parallel.procgroup import MeshTimeout

    try:
        with pytest.raises(MeshTimeout, match=r"peer 1.*\('xw', 99\)"):
            pg0.recv(1, ("xw", 99))
        # gather0 on rank 0 recvs from every peer: same bounded deadline
        with pytest.raises(ConnectionError, match="PATHWAY_MESH_OP_TIMEOUT_S"):
            pg0.gather0(("g", 1), None)
    finally:
        pg0.close()
        pg1.close()


def test_op_timeout_zero_disables_deadline(monkeypatch):
    monkeypatch.setenv("PATHWAY_MESH_OP_TIMEOUT_S", "0")
    monkeypatch.setenv("PATHWAY_MESH_HEARTBEAT_S", "0.05")
    monkeypatch.setenv("PATHWAY_MESH_PEER_TIMEOUT_S", "30")
    pg0, pg1 = _mesh_pair(_free_port_base(2))
    try:
        # no deadline: a late frame is simply delivered
        t = threading.Timer(0.5, lambda: pg1.send(0, "late", 42))
        t.start()
        assert pg0.recv(1, "late") == 42
        t.join()
    finally:
        pg0.close()
        pg1.close()


def test_orderly_goodbye_distinguished_from_crash():
    """close() ships a goodbye frame: a peer that finds the link gone can
    tell clean shutdown (MeshPeerGone) from a crash (MeshPeerFailure)."""
    import socket as _socket

    from pathway_tpu.parallel.procgroup import MeshPeerFailure, MeshPeerGone

    pg0, pg1 = _mesh_pair(_free_port_base(2))
    try:
        pg1.close()
        with pytest.raises(MeshPeerGone, match="orderly goodbye"):
            pg0.recv(1, "after-bye")
    finally:
        pg0.close()
    # crash: the link dies with NO goodbye
    pg0, pg1 = _mesh_pair(_free_port_base(2))
    try:
        for s in pg1._socks.values():
            s.shutdown(_socket.SHUT_RDWR)  # simulated hard death
        with pytest.raises(MeshPeerFailure, match="without a goodbye"):
            pg0.recv(1, "dead")
    finally:
        pg0.close()
        pg1.close()


def test_heartbeat_silence_detected_before_op_timeout(monkeypatch):
    """A silent peer with a DEAD transport (partitioned host: no frames,
    no heartbeats, no kernel ACKs) is declared failed after
    PATHWAY_MESH_PEER_TIMEOUT_S — much sooner than the collective
    deadline — and the miss lands on the stats counter. The transport
    probe is forced False here: a same-process test pair keeps its TCP
    link ESTABLISHED, which since ISSUE 9 means 'busy, not dead'
    (pinned separately below)."""
    monkeypatch.setenv("PATHWAY_MESH_OP_TIMEOUT_S", "30")
    monkeypatch.setenv("PATHWAY_MESH_HEARTBEAT_S", "0.05")
    monkeypatch.setenv("PATHWAY_MESH_PEER_TIMEOUT_S", "0.3")
    from pathway_tpu.internals.monitoring import ProberStats
    from pathway_tpu.parallel.procgroup import MeshPeerFailure

    pg0, pg1 = _mesh_pair(_free_port_base(2))
    pg0.stats = ProberStats()
    try:
        pg1._hb_stop.set()  # peer alive but silent: stops heartbeating
        pg0._transport_alive = lambda peer: False  # ...and unreachable
        import time as _t

        start = _t.monotonic()
        with pytest.raises(MeshPeerFailure, match="no frame or heartbeat"):
            pg0.recv(1, "silent")
        assert _t.monotonic() - start < 5  # far below the 30s op deadline
        assert pg0.stats.mesh_heartbeats_missed >= 1
    finally:
        pg0.close()
        pg1.close()


def test_busy_rank_with_live_transport_not_falsely_failed(monkeypatch):
    """The ISSUE 9 heartbeat-starvation regression: a healthy-but-busy
    peer (long GIL-held native dispatch / fused device call — its
    Python threads can't beat, but its kernel still ACKs) must NOT be
    declared MeshPeerFailure by the liveness window. The frame it sends
    once it comes back is received normally."""
    monkeypatch.setenv("PATHWAY_MESH_OP_TIMEOUT_S", "30")
    monkeypatch.setenv("PATHWAY_MESH_HEARTBEAT_S", "0.05")
    monkeypatch.setenv("PATHWAY_MESH_PEER_TIMEOUT_S", "0.3")
    pg0, pg1 = _mesh_pair(_free_port_base(2))
    try:
        pg1._hb_stop.set()  # models GIL starvation: no beats go out
        # the loopback pair's transport IS genuinely alive (ESTABLISHED,
        # ACKs flowing) — exactly the busy-rank shape; sanity-check the
        # real TCP_INFO probe agrees before relying on it
        assert pg0._transport_alive(1) is True

        import time as _t

        def late_send():
            _t.sleep(1.0)  # 3x the liveness window
            pg1.send(0, "busy", {"ok": 1})

        t = threading.Thread(target=late_send, daemon=True)
        t.start()
        got = pg0.recv(1, "busy")  # must wait through the busy period
        assert got == {"ok": 1}
        t.join(5)
    finally:
        pg0.close()
        pg1.close()


def test_peer_liveness_transport_alive_verdicts():
    """The extended protocol decision: transport_alive only matters past
    the idle window, and never overrides goodbye/disabled semantics."""
    from pathway_tpu.parallel import protocol as proto

    assert proto.peer_liveness(99.0, 10.0, False) == "failed"
    assert proto.peer_liveness(99.0, 10.0, False, transport_alive=True) == "alive"
    assert proto.peer_liveness(5.0, 10.0, False, transport_alive=False) == "alive"
    assert proto.peer_liveness(99.0, 10.0, True, transport_alive=False) == "alive"
    assert proto.peer_liveness(99.0, 0.0, False, transport_alive=False) == "alive"


def test_bind_listener_retries_through_transient_occupancy():
    """ISSUE 9 satellite: a respawned rank whose port is briefly held by
    the dying epoch's listener must wait it out in place (every rank
    must keep first_port + r), not burn a rollback restart; a port held
    past the retry window still raises."""
    import socket as _socket

    from pathway_tpu.parallel.procgroup import _bind_listener

    blocker = _socket.socket()
    blocker.bind(("127.0.0.1", 0))
    port = blocker.getsockname()[1]
    blocker.listen(1)

    def release():
        import time as _t

        _t.sleep(0.4)
        blocker.close()

    t = threading.Thread(target=release, daemon=True)
    t.start()
    s = _bind_listener("127.0.0.1", port, retry_s=3.0)
    try:
        assert s.getsockname()[1] == port
    finally:
        s.close()
        t.join(5)
    # and a port that never frees fails loudly within the bound
    blocker2 = _socket.socket()
    blocker2.bind(("127.0.0.1", 0))
    port2 = blocker2.getsockname()[1]
    blocker2.listen(1)
    try:
        with pytest.raises(OSError):
            _bind_listener("127.0.0.1", port2, retry_s=0.3)
    finally:
        blocker2.close()


def test_free_port_base_avoids_occupied_port():
    """The supervisor's port probe must skip a range containing a port
    another live socket owns (deliberately occupied here) instead of
    assuming the base is free."""
    import socket as _socket

    from pathway_tpu.parallel.supervisor import _free_port_base

    base = _free_port_base(2)
    # occupy base (simulating a racing process) and re-probe: the new
    # range must not include the occupied port
    holder = _socket.socket()
    holder.setsockopt(_socket.SOL_SOCKET, _socket.SO_REUSEADDR, 1)
    holder.bind(("127.0.0.1", base))
    holder.listen(1)
    try:
        for _ in range(8):
            nb = _free_port_base(2)
            assert base not in (nb, nb + 1)
    finally:
        holder.close()


def test_epoch_mismatch_rejected_at_handshake():
    """A rank surviving from a rolled-back epoch cannot join the
    recovered mesh: the handshake binds PATHWAY_MESH_EPOCH."""
    from pathway_tpu.parallel.procgroup import ProcessGroup

    port = _free_port_base(2)
    errs = []

    def mk1():
        try:
            ProcessGroup(1, 2, port, epoch=1, timeout=3)
        except Exception as exc:
            errs.append(exc)

    t = threading.Thread(target=mk1, daemon=True)
    t.start()
    with pytest.raises(TimeoutError):
        ProcessGroup(0, 2, port, epoch=0, timeout=3)
    t.join(15)
    assert errs and isinstance(errs[0], ConnectionError)
    assert "EPOCH" in str(errs[0])


def test_drain_discards_inflight_frames():
    """The epoch-abort path drops queued frames of the dead epoch
    instead of delivering them to the engine."""
    pg0, pg1 = _mesh_pair(_free_port_base(2))
    try:
        pg0.send(1, "t1", {"a": 1})
        pg0.send(1, "t2", {"a": 2})
        # wait until the receiver thread queued both
        import time as _t

        deadline = _t.monotonic() + 5
        while pg1._queues[0].qsize() < 2 and _t.monotonic() < deadline:
            _t.sleep(0.01)
        assert pg1.drain() == 2
        assert pg1._queues[0].qsize() == 0
    finally:
        pg0.close()
        pg1.close()


def test_frame_size_cap_raises_clean_connection_error(monkeypatch):
    monkeypatch.setenv("PATHWAY_MESH_MAX_FRAME_MB", "0.01")  # ~10 KB
    pg0, pg1 = _mesh_pair(_free_port_base(2))
    try:
        pg0.send(1, "big", b"x" * 200_000)
        with pytest.raises(ConnectionError, match="PATHWAY_MESH_MAX_FRAME_MB"):
            pg1.recv(0, "big")
    finally:
        pg0.close()
        pg1.close()


def test_corrupt_length_prefix_refused(monkeypatch):
    monkeypatch.delenv("PATHWAY_MESH_MAX_FRAME_MB", raising=False)
    pg0, pg1 = _mesh_pair(_free_port_base(2))
    try:
        import struct

        # a corrupt 2^62-byte length prefix must NOT be allocated
        pg0._socks[1].sendall(struct.pack("<Q", 1 << 62))
        with pytest.raises(ConnectionError, match="cap"):
            pg1.recv(0, "never")
    finally:
        pg0.close()
        pg1.close()


def test_exchange_frame_roundtrip_through_mesh():
    ex = _pwexec()
    if ex is None or not hasattr(ex, "nb_encode"):
        pytest.skip("native toolchain unavailable")
    from pathway_tpu.internals.api import Pointer

    _ex, nb = _mixed_nb()
    deltas = [(Pointer(7), ("a", 1), -1), (Pointer(8), ("b", 2), 1)]
    pg0, pg1 = _mesh_pair(_free_port_base(2))
    try:
        tag = ("xw", 42, 1)
        pg0.send_exchange(
            pg0.rank + 1, tag,
            [(5, nb), (9, deltas), (11, [(Pointer(1), ((1, 2),), 1)])],
        )
        items = pg1.recv(0, tag)
        assert [nid for nid, _ in items] == [5, 9, 11]
        assert items[0][1].materialize() == nb.materialize()
        assert items[1][1] == deltas
        assert items[2][1] == [(Pointer(1), ((1, 2),), 1)]
        # empty coalesced frame (pure presence header) round-trips
        pg1.send_exchange(0, ("xw", 43, 1), [])
        assert pg0.recv(1, ("xw", 43, 1)) == []
    finally:
        pg0.close()
        pg1.close()


# ---------------------------------------------------------------------------
# wire-codec robustness battery (ISSUE 7): corrupted PWX2/PWHB/PWBY
# frames into the procgroup receiver. Contract: every mutation produces
# a clean ConnectionError / dead-peer sentinel — never a hang, a crash,
# or a silently mis-decoded frame. (Data-plane payload bytes carry no
# checksum — that is TCP's job — so the battery corrupts the frame
# STRUCTURE: length prefixes, magics, header lengths, pickled headers,
# segment size tables, truncations.)
# ---------------------------------------------------------------------------

import pickle
import struct as _struct

_LEN8 = _struct.Struct("<Q")


def _raw_frame(pg, peer, payload: bytes, declared_len: int | None = None):
    """Ship raw bytes to `peer` with a length prefix, bypassing the send
    path — the receiver-side hardening is the thing under test."""
    n = len(payload) if declared_len is None else declared_len
    pg._socks[peer].sendall(_LEN8.pack(n) + payload)


def _pwx2_payload(tag=("xw", 1, 1), entries=None, meta=None) -> bytes:
    """A valid v2 exchange frame built from pickled (kind 1) segments —
    no native toolchain needed, same framing as send_exchange
    (PWX2 | u32 head_len | u32 crc32(head+blobs) | head | blobs).
    ``meta`` overrides the (node_id, kind, size) table — used to build
    validly-checksummed frames whose size table lies."""
    import zlib

    entries = entries if entries is not None else [
        (5, [(i, (f"w{i}", i), 1) for i in range(20)]),
        (9, [(99, ("x", -1), -1)]),
    ]
    blobs = []
    real_meta = []
    for nid, deltas in entries:
        blob = pickle.dumps(list(deltas), protocol=pickle.HIGHEST_PROTOCOL)
        real_meta.append((nid, 1, len(blob)))
        blobs.append(blob)
    head = pickle.dumps(
        (tag, meta if meta is not None else real_meta),
        protocol=pickle.HIGHEST_PROTOCOL,
    )
    crc = zlib.crc32(head)
    for blob in blobs:
        crc = zlib.crc32(blob, crc)
    return b"".join(
        [b"PWX2", _struct.pack("<II", len(head), crc), head, *blobs]
    )


def _recv_outcome(pg, peer, tag, timeout_guard=20.0):
    """recv() under a wall-clock guard: returns ('ok', obj) or
    ('error', exc). A hang fails the test via the guard."""
    import time as _t

    start = _t.monotonic()
    try:
        obj = pg.recv(peer, tag)
        out = ("ok", obj)
    except (ConnectionError, RuntimeError) as exc:
        out = ("error", exc)
    assert _t.monotonic() - start < timeout_guard, "receiver hung"
    return out


def test_fuzz_pwx2_bitflips_rejected_by_crc(monkeypatch):
    """Bit flips ANYWHERE in a v2 frame (magic, header length, crc
    field, pickled header, segment bytes): the frame CRC must reject
    every one of them with a clean ConnectionError — this battery is
    what forced the checksum into the format: without it, a flipped
    bit inside the pickled node-id table decoded 'successfully' to a
    different exchange id (slice silently merged into the wrong
    boundary)."""
    import random

    monkeypatch.setenv("PATHWAY_MESH_OP_TIMEOUT_S", "10")
    rng = random.Random(0xC0DEC)
    payload = _pwx2_payload()
    # control: the unflipped frame decodes exactly
    pg0, pg1 = _mesh_pair(_free_port_base(2))
    try:
        _raw_frame(pg0, 1, payload)
        kind, got = _recv_outcome(pg1, 0, ("xw", 1, 1))
        assert kind == "ok"
        assert [nid for nid, _ in got] == [5, 9]
    finally:
        pg0.close()
        pg1.close()
    positions = [0, 1, 4, 5, 8, 11] + [
        rng.randrange(12, len(payload)) for _ in range(14)
    ]
    for pos in positions:
        flipped = bytearray(payload)
        flipped[pos] ^= 1 << rng.randrange(8)
        pg0, pg1 = _mesh_pair(_free_port_base(2))
        try:
            _raw_frame(pg0, 1, bytes(flipped))
            kind, got = _recv_outcome(pg1, 0, ("xw", 1, 1))
            assert kind == "error", (
                f"flip at byte {pos} decoded silently: {got!r}"
            )
            assert isinstance(got, ConnectionError), (pos, got)
        finally:
            pg0.close()
            pg1.close()


def test_fuzz_pwx2_truncations(monkeypatch):
    """Truncated v2 frames: cut mid-magic, mid-header, mid-segment. A
    self-consistent truncation (prefix matches the short payload) must
    poison the link cleanly; an EOF mid-frame must land as the
    dead-peer sentinel."""
    monkeypatch.setenv("PATHWAY_MESH_OP_TIMEOUT_S", "10")
    payload = _pwx2_payload()
    (hlen,) = _struct.unpack_from("<I", payload, 4)
    cuts = [2, 4, 6, 4 + 4 + hlen - 1, 4 + 4 + hlen, len(payload) - 3]
    for cut in cuts:
        pg0, pg1 = _mesh_pair(_free_port_base(2))
        try:
            _raw_frame(pg0, 1, payload[:cut])
            kind, got = _recv_outcome(pg1, 0, ("xw", 1, 1))
            assert kind == "error", f"cut at {cut} decoded silently"
            assert isinstance(got, ConnectionError)
        finally:
            pg0.close()
            pg1.close()
    # EOF mid-frame: prefix declares the full frame, bytes stop short
    from pathway_tpu.parallel.procgroup import MeshPeerFailure

    pg0, pg1 = _mesh_pair(_free_port_base(2))
    try:
        _raw_frame(pg0, 1, payload[: len(payload) // 2],
                   declared_len=len(payload))
        for s in pg0._socks.values():
            s.shutdown(socket.SHUT_RDWR)
        with pytest.raises(MeshPeerFailure):
            pg1.recv(0, ("xw", 1, 1))
    finally:
        pg0.close()
        pg1.close()


def test_fuzz_corrupt_segment_size_table(monkeypatch):
    """A VALIDLY-CHECKSUMMED v2 header whose size table lies about the
    shipped blobs (short, long, huge, zero) — the buggy/hostile-sender
    case the CRC cannot catch — must fail the link cleanly: never index
    out of the frame or mis-attribute bytes silently."""
    monkeypatch.setenv("PATHWAY_MESH_OP_TIMEOUT_S", "10")
    tag = ("xw", 2, 1)
    entries = [(5, [(1, ("a",), 1)])]
    blob_len = len(
        pickle.dumps(entries[0][1], protocol=pickle.HIGHEST_PROTOCOL)
    )
    for bad_size in (blob_len - 1, blob_len + 7, 2**31, 0):
        # the meta= override keeps the CRC valid (computed over the real
        # blobs) while the size table lies — past the checksum gate, the
        # segment bounds check / segment decode must reject it
        payload = _pwx2_payload(
            tag=tag, entries=entries, meta=[(5, 1, bad_size)]
        )
        pg0, pg1 = _mesh_pair(_free_port_base(2))
        try:
            _raw_frame(pg0, 1, payload)
            kind, got = _recv_outcome(pg1, 0, tag)
            if kind == "ok":
                pytest.fail(
                    f"size={bad_size} delivered {got!r} silently"
                )
            assert isinstance(got, ConnectionError)
            assert "checksum" not in str(got), (
                "lying size table must fail on the segment guards, not "
                "the checksum — the test frame's CRC is valid"
            )
        finally:
            pg0.close()
            pg1.close()


def test_fuzz_corrupt_control_frames(monkeypatch):
    """Near-miss PWHB/PWBY magics and corrupt length prefixes: anything
    that is not exactly a control magic must either fail the link
    cleanly or be a valid frame — a flipped heartbeat must never be
    silently treated as one (or worse, queued as data)."""
    monkeypatch.setenv("PATHWAY_MESH_OP_TIMEOUT_S", "10")
    for bad in (b"PWHX", b"pwhb", b"PWB\x00", b"PWBYX", b"\x00\x00\x00\x00"):
        pg0, pg1 = _mesh_pair(_free_port_base(2))
        try:
            _raw_frame(pg0, 1, bad)
            kind, got = _recv_outcome(pg1, 0, "never")
            assert kind == "error", f"{bad!r} was accepted as {got!r}"
            assert isinstance(got, ConnectionError)
        finally:
            pg0.close()
            pg1.close()
    # genuine control frames keep the link healthy: a heartbeat then a
    # goodbye then real data — data still arrives, then the goodbye
    # classification fires
    from pathway_tpu.parallel.procgroup import MeshPeerGone

    pg0, pg1 = _mesh_pair(_free_port_base(2))
    try:
        _raw_frame(pg0, 1, b"PWHB")
        pg0.send(1, "t", 42)
        assert pg1.recv(0, "t") == 42
        _raw_frame(pg0, 1, b"PWBY")
        for s in pg0._socks.values():
            s.shutdown(socket.SHUT_RDWR)
        with pytest.raises(MeshPeerGone):
            pg1.recv(0, "after")
    finally:
        pg0.close()
        pg1.close()


def test_fuzz_native_codec_blobs():
    """nb/deltas wire codecs under structural corruption: truncations at
    every region boundary and seeded bit flips in the header region must
    raise ValueError — and any flip that does decode must not change the
    row count (no silent length mis-decode). Never a crash."""
    import random

    ex, nb = _mixed_nb()
    if not hasattr(ex, "deltas_encode"):
        pytest.skip("native toolchain unavailable")
    from pathway_tpu.internals.api import Pointer

    rng = random.Random(0xFEED)
    for enc, dec, n_rows in (
        (ex.nb_encode(nb), lambda b: ex.nb_decode(b, Pointer), len(nb)),
        (
            ex.deltas_encode(
                [(Pointer(i), (f"w{i}", i, 1.5 * i, None), 1)
                 for i in range(64)]
            ),
            lambda b: ex.deltas_decode(b, Pointer),
            64,
        ),
    ):
        assert enc is not None
        for cut in sorted({0, 1, 7, 8, 15, len(enc) // 3, len(enc) - 1}):
            with pytest.raises(ValueError):
                dec(enc[:cut])
        header = min(64, len(enc))
        for _ in range(24):
            pos = rng.randrange(header)
            flipped = bytearray(enc)
            flipped[pos] ^= 1 << rng.randrange(8)
            try:
                out = dec(bytes(flipped))
            except (ValueError, OverflowError, MemoryError):
                continue  # clean structural rejection
            got_n = len(out)
            assert got_n == n_rows, (
                f"header flip at byte {pos} silently changed the row "
                f"count: {got_n} != {n_rows}"
            )


# ---------------------------------------------------------------------------
# fast wire (ISSUE 13): codec negotiation, compressed-frame fuzzing, and
# the per-peer sender threads. Contract for corruption: CRC first (a
# damaged wire image is rejected before any decompressor runs), then
# codec errors (a validly-checksummed but undecodable compressed stream
# can only mean a buggy sender) — both must poison the link with a
# clean MeshPeerFailure, never a partial decode.
# ---------------------------------------------------------------------------

import zlib as _zlib


def test_codec_negotiation_units():
    from pathway_tpu.parallel import procgroup as pgm

    assert pgm.local_codec_mask("off") == 0
    assert pgm.local_codec_mask("zlib") == pgm._CODEC_BIT["zlib"]
    # auto always includes stdlib zlib, whatever else is importable
    assert pgm.local_codec_mask("auto") & pgm._CODEC_BIT["zlib"]
    # a forced-but-unimportable codec advertises nothing (honest off)
    if not pgm.codec_available("lz4"):
        assert pgm.local_codec_mask("lz4") == 0
    assert pgm.negotiate_codec(0, 7) is None
    assert pgm.negotiate_codec(1, 1) == "zlib"
    assert pgm.negotiate_codec(7, 1) == "zlib"  # common = zlib only
    assert pgm.negotiate_codec(7, 7) in ("zstd", "lz4", "zlib")
    # preference order: zstd > lz4 > zlib on the common set
    assert pgm.negotiate_codec(5, 5) == "zstd"
    assert pgm.negotiate_codec(3, 3) == "lz4"


def test_compress_blob_roundtrip_and_bomb_guard():
    from pathway_tpu.parallel import procgroup as pgm

    blob = b"columnar frame bytes " * 400
    wire = pgm._compress_blob("zlib", blob)
    assert len(wire) < len(blob)
    assert pgm._decompress_blob(1, wire, 1 << 20) == blob
    # output bound: a zip bomb (or lying sender) is refused, not
    # allocated — the same cap as PATHWAY_MESH_MAX_FRAME_MB
    with pytest.raises(ValueError, match="exceeds"):
        pgm._decompress_blob(1, wire, 100)
    # truncated compressed stream: clean codec error, no partial output
    with pytest.raises(Exception):
        pgm._decompress_blob(1, wire[: len(wire) // 2], 1 << 20)
    with pytest.raises(ValueError, match="unknown wire codec id"):
        pgm._decompress_blob(9, wire, 1 << 20)


def test_wire_entropy_probe():
    ex = _pwexec()
    if ex is None or not hasattr(ex, "wire_entropy"):
        pytest.skip("native toolchain unavailable")
    assert ex.wire_entropy(b"\x00" * 50_000) == 0.0
    assert ex.wire_entropy(b"abcd" * 10_000) < 3.0
    import random as _r

    rng = _r.Random(7)
    rnd = bytes(rng.randrange(256) for _ in range(100_000))
    assert ex.wire_entropy(rnd) > 7.5  # ~8 bits/byte for uniform bytes


def _pwx2_compressed_payload(tag=("xw", 1, 1), corrupt_stream=False):
    """A v2 frame with one zlib-compressed pickled segment, built like
    _frame_send (4-tuple segment table). ``corrupt_stream`` damages the
    COMPRESSED bytes and then recomputes the CRC over the damaged wire
    image — a validly-checksummed frame whose codec stream is broken,
    the exact case that must fail on the codec, not the checksum."""
    deltas = [(i, (f"word{i % 7}", i), 1) for i in range(200)]
    raw = pickle.dumps(deltas, protocol=pickle.HIGHEST_PROTOCOL)
    wire = _zlib.compress(raw, 1)
    if corrupt_stream:
        w = bytearray(wire)
        w[len(w) // 2] ^= 0xFF
        wire = bytes(w)
    meta = [(5, 1, len(wire), 1)]  # kind 1 (pickle), codec 1 (zlib)
    head = pickle.dumps((tag, meta), protocol=pickle.HIGHEST_PROTOCOL)
    crc = _zlib.crc32(head)
    crc = _zlib.crc32(wire, crc)
    return (
        b"".join([b"PWX2", _struct.pack("<II", len(head), crc), head, wire]),
        deltas,
    )


def test_fuzz_compressed_frame_bitflips_rejected_by_crc(monkeypatch):
    """Bit flips ANYWHERE in a compressed v2 frame — including inside
    the compressed blob — are rejected by the frame CRC before any
    decompressor touches the bytes."""
    import random

    monkeypatch.setenv("PATHWAY_MESH_OP_TIMEOUT_S", "10")
    rng = random.Random(0xD1)
    payload, deltas = _pwx2_compressed_payload()
    # control: the unflipped compressed frame decodes exactly
    pg0, pg1 = _mesh_pair(_free_port_base(2))
    try:
        _raw_frame(pg0, 1, payload)
        kind, got = _recv_outcome(pg1, 0, ("xw", 1, 1))
        assert kind == "ok"
        assert got == [(5, deltas)]
    finally:
        pg0.close()
        pg1.close()
    hlen = _struct.unpack_from("<I", payload, 4)[0]
    blob_start = 4 + 8 + hlen
    positions = [0, 5, 9, blob_start - 2] + [
        rng.randrange(blob_start, len(payload)) for _ in range(10)
    ]
    for pos in positions:
        flipped = bytearray(payload)
        flipped[pos] ^= 1 << rng.randrange(8)
        pg0, pg1 = _mesh_pair(_free_port_base(2))
        try:
            _raw_frame(pg0, 1, bytes(flipped))
            kind, got = _recv_outcome(pg1, 0, ("xw", 1, 1))
            assert kind == "error", (
                f"flip at byte {pos} decoded silently: {got!r}"
            )
            assert isinstance(got, ConnectionError), (pos, got)
        finally:
            pg0.close()
            pg1.close()


def test_fuzz_corrupt_codec_stream_fails_on_codec_not_crc(monkeypatch):
    """A validly-checksummed frame whose COMPRESSED stream is damaged
    (buggy sender — the CRC cannot catch it because it was computed
    over the damaged bytes): the codec error must surface as a clean
    MeshPeerFailure, never a partial decode."""
    monkeypatch.setenv("PATHWAY_MESH_OP_TIMEOUT_S", "10")
    payload, _ = _pwx2_compressed_payload(corrupt_stream=True)
    pg0, pg1 = _mesh_pair(_free_port_base(2))
    try:
        _raw_frame(pg0, 1, payload)
        kind, got = _recv_outcome(pg1, 0, ("xw", 1, 1))
        assert kind == "error", f"corrupt codec stream decoded: {got!r}"
        assert isinstance(got, ConnectionError)
        assert "checksum" not in str(got), (
            "codec-stream damage must fail in the codec, not the CRC — "
            "this frame's CRC is valid by construction"
        )
    finally:
        pg0.close()
        pg1.close()


def test_fuzz_truncated_codec_stream_with_valid_crc(monkeypatch):
    """Segment table + CRC consistent, but the compressed stream is a
    truncated prefix (stream never reaches EOF): the inflate-side
    completeness check must reject it cleanly."""
    monkeypatch.setenv("PATHWAY_MESH_OP_TIMEOUT_S", "10")
    deltas = [(i, (f"w{i}", i), 1) for i in range(300)]
    raw = pickle.dumps(deltas, protocol=pickle.HIGHEST_PROTOCOL)
    wire = _zlib.compress(raw, 1)[: 40]  # truncated stream
    meta = [(5, 1, len(wire), 1)]
    head = pickle.dumps((("xw", 1, 1), meta), protocol=pickle.HIGHEST_PROTOCOL)
    crc = _zlib.crc32(head)
    crc = _zlib.crc32(wire, crc)
    payload = b"".join(
        [b"PWX2", _struct.pack("<II", len(head), crc), head, wire]
    )
    pg0, pg1 = _mesh_pair(_free_port_base(2))
    try:
        _raw_frame(pg0, 1, payload)
        kind, got = _recv_outcome(pg1, 0, ("xw", 1, 1))
        assert kind == "error"
        assert isinstance(got, ConnectionError)
    finally:
        pg0.close()
        pg1.close()


def _wait_stats(pg, timeout_s: float = 2.0) -> None:
    """Sender-thread byte accounting lands just after the socket write
    a recv observed — poll briefly (no-op on the synchronous path)."""
    import time as _t

    deadline = _t.monotonic() + timeout_s
    while _t.monotonic() < deadline:
        if pg.stats is None or pg.stats.exchange_wire_bytes:
            return
        _t.sleep(0.01)


def test_compress_min_bytes_floor_ships_raw(monkeypatch):
    """Blobs under PATHWAY_MESH_COMPRESS_MIN_BYTES skip the codec: the
    negotiated link stays compressed-capable, but raw == wire for tiny
    frames."""
    monkeypatch.setenv("PATHWAY_MESH_COMPRESSION", "zlib")
    monkeypatch.setenv("PATHWAY_MESH_COMPRESS_MIN_BYTES", "1000000000")
    from pathway_tpu.internals.monitoring import ProberStats

    pg0, pg1 = _mesh_pair(_free_port_base(2))
    pg0.stats = ProberStats()
    try:
        assert pg0._peer_codec.get(1) == "zlib"
        deltas = [(i, (f"word{i % 5}", i), 1) for i in range(500)]
        pg0.send_exchange(1, ("xw", 9, 1), [(5, deltas)])
        assert pg1.recv(0, ("xw", 9, 1)) == [(5, deltas)]
        _wait_stats(pg0)
        assert pg0.stats.exchange_raw_bytes > 0
        assert pg0.stats.exchange_raw_bytes == pg0.stats.exchange_wire_bytes
    finally:
        pg0.close()
        pg1.close()


def test_compression_counters_and_roundtrip(monkeypatch):
    """Forced zlib on a compressible frame: wire < raw on the sender's
    counters (per-total and per-peer), receiver decodes bit-exactly."""
    monkeypatch.setenv("PATHWAY_MESH_COMPRESSION", "zlib")
    monkeypatch.setenv("PATHWAY_MESH_COMPRESS_MIN_BYTES", "64")
    from pathway_tpu.internals.monitoring import ProberStats

    pg0, pg1 = _mesh_pair(_free_port_base(2))
    pg0.stats = ProberStats()
    try:
        deltas = [(i, (f"word{i % 5}" * 3, i), 1) for i in range(2000)]
        pg0.send_exchange(1, ("xw", 7, 1), [(5, deltas)])
        assert pg1.recv(0, ("xw", 7, 1)) == [(5, deltas)]
        _wait_stats(pg0)
        st = pg0.stats
        assert 0 < st.exchange_wire_bytes < st.exchange_raw_bytes
        assert st.exchange_comp_peer[1][1] < st.exchange_comp_peer[1][0]
        # the OpenMetrics families render
        text = st.render_openmetrics()
        assert "exchange_uncompressed_bytes_total" in text
        assert 'exchange_peer_compressed_bytes_total{peer="1"}' in text
    finally:
        pg0.close()
        pg1.close()


def test_auto_engagement_policy(monkeypatch):
    """`auto` means compress when it cannot cost wall-clock: a starved
    loopback mesh (synchronous sends — no spare cores) ships raw even
    though the link NEGOTIATED a codec; arming the sender threads
    (codec off the critical path) engages it. Forced codecs always
    engage."""
    from pathway_tpu.internals.monitoring import ProberStats

    deltas = [(i, (f"word{i % 5}" * 3, i), 1) for i in range(2000)]
    monkeypatch.setenv("PATHWAY_MESH_COMPRESSION", "auto")
    monkeypatch.setenv("PATHWAY_MESH_COMPRESS_MIN_BYTES", "64")
    # starved loopback: sync sends -> auto disengages, link still capable
    monkeypatch.setenv("PATHWAY_MESH_SEND_QUEUE", "0")
    pg0, pg1 = _mesh_pair(_free_port_base(2))
    pg0.stats = ProberStats()
    try:
        assert pg0._peer_codec.get(1) is not None  # negotiated
        assert not pg0._auto_engage
        pg0.send_exchange(1, ("xw", 1, 1), [(5, deltas)])
        assert pg1.recv(0, ("xw", 1, 1)) == [(5, deltas)]
        assert pg0.stats.exchange_raw_bytes == pg0.stats.exchange_wire_bytes
    finally:
        pg0.close()
        pg1.close()
    # sender threads armed: the codec rides them, auto engages
    monkeypatch.setenv("PATHWAY_MESH_SEND_QUEUE", "4")
    pg0, pg1 = _mesh_pair(_free_port_base(2))
    pg0.stats = ProberStats()
    try:
        assert pg0._auto_engage
        pg0.send_exchange(1, ("xw", 2, 1), [(5, deltas)])
        assert pg1.recv(0, ("xw", 2, 1)) == [(5, deltas)]
        # the sender thread's accounting lands just after the socket
        # write the recv observed — poll briefly
        import time as _t

        for _ in range(200):
            if pg0.stats.exchange_wire_bytes:
                break
            _t.sleep(0.01)
        assert (
            0
            < pg0.stats.exchange_wire_bytes
            < pg0.stats.exchange_raw_bytes
        )
    finally:
        pg0.close()
        pg1.close()


def test_relay_codec_targets_route_destination(monkeypatch):
    """Tree-gather frames are relayed verbatim toward rank 0, so their
    segments may only use a codec the route DESTINATION advertised —
    a next hop that happens to support zlib must not get zlib bytes a
    codec-less root cannot inflate (mixed deployments degrade per
    path, never decode-error at the root)."""
    from pathway_tpu.internals.monitoring import ProberStats
    from pathway_tpu.parallel import procgroup as pgm

    monkeypatch.setenv("PATHWAY_MESH_COMPRESSION", "zlib")
    monkeypatch.setenv("PATHWAY_MESH_COMPRESS_MIN_BYTES", "64")
    deltas = [(i, (f"word{i % 5}" * 3, i), 1) for i in range(2000)]
    pg0, pg1 = _mesh_pair(_free_port_base(2))
    pg0.stats = ProberStats()
    try:
        # stand-in for a world-4 leaf: the direct link (peer 1, the
        # tree parent) negotiated zlib, but the ROUTE destination
        # (rank 0, known through the full mesh) advertised nothing
        pg0._peer_mask[0] = 0
        pg0.send_exchange(
            1, ("xwr", 7, 1), [(5, deltas)], None, route_dest=0
        )
        got = pg1.recv(0, ("xwr", 7, 1))
        # relay-tagged frames arrive as raw wire segments
        assert all(isinstance(p, pgm.RawSegment) for _n, p in got)
        assert all(p.enc == 0 for _n, p in got)  # shipped raw
        _wait_stats(pg0)
        assert pg0.stats.exchange_raw_bytes == pg0.stats.exchange_wire_bytes
        # a zlib-capable destination gets compressed segments
        pg0._peer_mask[0] = pgm._CODEC_BIT["zlib"]
        pg0.send_exchange(
            1, ("xwr", 8, 1), [(5, deltas)], None, route_dest=0
        )
        got = pg1.recv(0, ("xwr", 8, 1))
        assert all(p.enc == pgm.CODEC_ID["zlib"] for _n, p in got)
    finally:
        pg0.close()
        pg1.close()


def test_sender_thread_preserves_per_peer_order(monkeypatch):
    """Control and exchange frames to one peer ride ONE sender queue:
    interleaved sends arrive in exactly the enqueue order."""
    monkeypatch.setenv("PATHWAY_MESH_SEND_QUEUE", "4")
    pg0, pg1 = _mesh_pair(_free_port_base(2))
    try:
        assert 1 in pg0._sendqs  # sender thread armed
        for i in range(30):
            if i % 2:
                pg0.send(1, ("ctl", i), {"i": i})
            else:
                pg0.send_exchange(
                    1, ("xw", i, 1), [(5, [(i, ("x", i), 1)])]
                )
        for i in range(30):
            if i % 2:
                assert pg1.recv(0, ("ctl", i)) == {"i": i}
            else:
                assert pg1.recv(0, ("xw", i, 1)) == [(5, [(i, ("x", i), 1)])]
    finally:
        pg0.close()
        pg1.close()


def test_send_queue_zero_is_synchronous(monkeypatch):
    """PATHWAY_MESH_SEND_QUEUE=0: legacy inline sends — send_exchange
    returns the shipped byte count and no sender threads exist."""
    monkeypatch.setenv("PATHWAY_MESH_SEND_QUEUE", "0")
    pg0, pg1 = _mesh_pair(_free_port_base(2))
    try:
        assert not pg0._sendqs and not pg0._send_threads
        n = pg0.send_exchange(1, ("xw", 1, 1), [(5, [(1, ("a",), 1)])])
        assert n > 0
        assert pg1.recv(0, ("xw", 1, 1)) == [(5, [(1, ("a",), 1)])]
    finally:
        pg0.close()
        pg1.close()


def test_sender_thread_failure_surfaces_as_mesh_peer_failure(monkeypatch):
    """A send-side link death on the sender thread poisons the peer:
    blocked recvs wake with the reason and later sends re-raise it
    synchronously instead of queueing into a dead link."""
    monkeypatch.setenv("PATHWAY_MESH_OP_TIMEOUT_S", "15")
    monkeypatch.setenv("PATHWAY_MESH_HEARTBEAT_S", "0")
    monkeypatch.setenv("PATHWAY_MESH_SEND_QUEUE", "4")
    from pathway_tpu.parallel.procgroup import MeshPeerFailure

    pg0, pg1 = _mesh_pair(_free_port_base(2))
    try:
        # hard-kill the transport under pg0's feet
        for s in pg0._socks.values():
            s.shutdown(socket.SHUT_RDWR)
        import time as _t

        with pytest.raises((MeshPeerFailure, ConnectionError)):
            # the sender thread hits EPIPE asynchronously; keep sending
            # until the recorded error re-raises synchronously
            for i in range(500):
                pg0.send(1, ("t", i), b"x" * 65536)
                _t.sleep(0.005)
        err = pg0._send_errs.get(1)
        assert err is not None and "sender thread" in err
        with pytest.raises(MeshPeerFailure):
            pg0.recv(1, "never")
    finally:
        pg0.close()
        pg1.close()


# ---------------------------------------------------------------------------
# end-to-end: 2-rank vs single-rank bit identity
# ---------------------------------------------------------------------------

_BATTERY = """
import json, os, sys
sys.path.insert(0, {repo!r})
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import pathway_tpu as pw
from pathway_tpu.engine.runtime import Runtime

_insts = []
_orig_init = Runtime.__init__
def _spy_init(self, *a, **k):
    _orig_init(self, *a, **k)
    _insts.append(self)
Runtime.__init__ = _spy_init

rank = int(os.environ.get("PATHWAY_PROCESS_ID", "0"))
P = int(os.environ.get("PATHWAY_PROCESSES", "1"))

words = ["alpha", "beta", "gamma", "delta", "epsilon", "zeta", "eta"]
N = 700
rows = [
    {{"data": words[(i * 7) % len(words)], "v": i}}
    for i in range(rank, N, P)
]

class Src(pw.io.python.ConnectorSubject):
    _deletions_enabled = False
    _distributed_partitioned = True
    def run(self):
        for s in range(0, len(rows), 100):
            self.next_batch(rows[s : s + 100])
            self.commit()

class S(pw.Schema):
    data: str
    v: int

t = pw.io.python.read(Src(), schema=S, autocommit_duration_ms=3_600_000)
counts = t.groupby(pw.this.data).reduce(
    word=pw.this.data, c=pw.reducers.count(), s=pw.reducers.sum(pw.this.v)
)

rrows = [{{"j": w, "w": (i + 1) * 10}} for i, w in enumerate(words[:5])]
class RSrc(pw.io.python.ConnectorSubject):
    _deletions_enabled = False
    def run(self):
        self.next_batch(rrows)
        self.commit()

class R(pw.Schema):
    j: str
    w: int

rt = pw.io.python.read(RSrc(), schema=R, autocommit_duration_ms=3_600_000)
joined = t.join(rt, pw.left.data == pw.right.j).select(
    d=pw.left.data, v=pw.left.v, w=pw.right.w
)
jagg = joined.groupby(pw.this.d).reduce(
    d=pw.this.d, sv=pw.reducers.sum(pw.this.v),
    sw=pw.reducers.sum(pw.this.w), c=pw.reducers.count(),
)

state = {{"counts": {{}}, "jagg": {{}}}}
def collector(name):
    def on_change(key, row, time_, is_add):
        if is_add:
            state[name][int(key)] = row
        else:
            state[name].pop(int(key), None)
    return on_change

pw.io.subscribe(counts, on_change=collector("counts"))
pw.io.subscribe(jagg, on_change=collector("jagg"))
pw.run(monitoring_level=pw.MonitoringLevel.NONE)

rt_main = _insts[0]
xn = rt_main.scope.exchange_nodes
st = rt_main.stats
print(json.dumps({{
    "rank": rank,
    "counts": sorted([sorted(r.items()) for r in state["counts"].values()]),
    "jagg": sorted([sorted(r.items()) for r in state["jagg"].values()]),
    "nb_batches": sum(x._nb_batches for x in xn),
    "tuple_fallbacks": sum(x._fallbacks for x in xn),
    "frames": st.exchange_frames,
    "bytes": st.exchange_bytes,
    "raw_bytes": st.exchange_raw_bytes,
    "wire_bytes": st.exchange_wire_bytes,
    "elided": st.exchange_empty_elided,
    "comms_s": st.exchange_comms_s,
}}))
"""

_OBJECT_COLUMN = """
import json, os, sys
sys.path.insert(0, {repo!r})
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import pathway_tpu as pw
from pathway_tpu.engine.runtime import Runtime

_insts = []
_orig_init = Runtime.__init__
def _spy_init(self, *a, **k):
    _orig_init(self, *a, **k)
    _insts.append(self)
Runtime.__init__ = _spy_init

rank = int(os.environ.get("PATHWAY_PROCESS_ID", "0"))

# tuple-valued column: ineligible for the columnar parser AND for the
# typed delta codec -> the exchange must take pickled tuple slices
rows = [
    (i, (f"k{{i % 5}}", i, ("tag", i % 3)))
    for i in range(300)
]
t = pw.debug.table_from_rows(
    pw.schema_from_types(k=str, v=int, meta=tuple), [r[1] for r in rows]
)
agg = t.groupby(pw.this.k).reduce(
    k=pw.this.k, s=pw.reducers.sum(pw.this.v), c=pw.reducers.count()
)
state = {{}}
def on_change(key, row, time_, is_add):
    if is_add:
        state[int(key)] = row
    else:
        state.pop(int(key), None)
pw.io.subscribe(agg, on_change=on_change)
pw.run(monitoring_level=pw.MonitoringLevel.NONE)
rt_main = _insts[0]
xn = rt_main.scope.exchange_nodes
print(json.dumps({{
    "rank": rank,
    "agg": sorted([sorted(r.items()) for r in state.values()]),
    "nb_batches": sum(x._nb_batches for x in xn),
}}))
"""


def _spawn_ranks(program: str, workdir: str, processes: int, extra_env=None):
    port = _free_port_base()
    procs = []
    for rank in range(processes):
        env = dict(os.environ)
        env.update(
            PATHWAY_PROCESSES=str(processes),
            PATHWAY_PROCESS_ID=str(rank),
            PATHWAY_FIRST_PORT=str(port),
            JAX_PLATFORMS="cpu",
            PYTHONPATH=REPO,
        )
        env.pop("PATHWAY_LANE_PROCESSES", None)
        env.update(extra_env or {})
        procs.append(
            subprocess.Popen(
                [sys.executable, program],
                env=env,
                cwd=workdir,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
            )
        )
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=180)
            assert p.returncode == 0, (
                f"rank failed rc={p.returncode}\nstderr:{err.decode()[-2000:]}"
            )
            outs.append(json.loads(out.decode().strip().splitlines()[-1]))
    finally:
        for q in procs:
            if q.poll() is None:
                q.kill()
                q.communicate()
    return outs


def _run_battery(tmpdir, processes, extra_env=None, program=_BATTERY):
    prog = os.path.join(tmpdir, f"prog_{processes}_{len(extra_env or {})}.py")
    with open(prog, "w") as f:
        f.write(program.format(repo=REPO))
    return _spawn_ranks(prog, tmpdir, processes, extra_env)


@pytest.fixture(scope="module")
def battery_results():
    """One single-rank ground-truth run + the 2-rank columnar and
    forced-tuple runs, shared across the assertions below. The default
    2-rank run rides PATHWAY_MESH_COMPRESSION's default (auto — which
    engages the codec only where it cannot cost wall-clock, so on a
    multi-core CI host these pins double as compression-on parity;
    ``compression_battery_results`` pins the forced-on case
    everywhere)."""
    with tempfile.TemporaryDirectory() as td:
        single = _run_battery(td, 1)[0]
        columnar = _run_battery(td, 2)
        no_nb = _run_battery(td, 2, {"PATHWAY_NO_NB_EXCHANGE": "1"})
        yield single, columnar, no_nb


@pytest.fixture(scope="module")
def compression_battery_results():
    """2-rank parity runs under every compression posture the satellite
    pins: off, forced zlib (always available), and the auto default
    covered by ``battery_results`` (ISSUE 13)."""
    with tempfile.TemporaryDirectory() as td:
        single = _run_battery(td, 1)[0]
        off = _run_battery(td, 2, {"PATHWAY_MESH_COMPRESSION": "off"})
        forced = _run_battery(
            td, 2,
            {
                "PATHWAY_MESH_COMPRESSION": "zlib",
                "PATHWAY_MESH_COMPRESS_MIN_BYTES": "64",
            },
        )
        yield single, off, forced


def test_two_rank_compression_off_parity_and_honest_counters(
    compression_battery_results,
):
    single, off, _forced = compression_battery_results
    rank0 = next(r for r in off if r["rank"] == 0)
    assert rank0["counts"] == single["counts"]
    assert rank0["jagg"] == single["jagg"]
    # off must be HONEST off: raw and wire totals advance in lockstep
    for r in off:
        assert r["raw_bytes"] == r["wire_bytes"]


def test_two_rank_forced_zlib_parity_and_ratio(
    compression_battery_results,
):
    single, _off, forced = compression_battery_results
    rank0 = next(r for r in forced if r["rank"] == 0)
    assert rank0["counts"] == single["counts"]
    assert rank0["jagg"] == single["jagg"]
    # typed columnar wordcount/join frames are compressible: the run's
    # aggregate ratio must exceed 1 (wire < raw)
    total_raw = sum(r["raw_bytes"] for r in forced)
    total_wire = sum(r["wire_bytes"] for r in forced)
    assert 0 < total_wire < total_raw, (total_raw, total_wire)


def test_two_rank_auto_compression_never_inflates(battery_results):
    _single, columnar, _no_nb = battery_results
    # auto (the default): wire bytes never exceed raw bytes — the
    # per-blob "ship raw unless the codec shrank it" guarantee
    for r in columnar:
        assert r["wire_bytes"] <= r["raw_bytes"]


def test_two_rank_columnar_bit_identical(battery_results):
    single, columnar, _no_nb = battery_results
    rank0 = next(r for r in columnar if r["rank"] == 0)
    assert rank0["counts"] == single["counts"]
    assert rank0["jagg"] == single["jagg"]


def test_two_rank_columnar_path_actually_columnar(battery_results):
    _single, columnar, _no_nb = battery_results
    # source batches are NB-parsed, so hash boundaries must run columnar
    assert sum(r["nb_batches"] for r in columnar) > 0
    assert all(r["frames"] > 0 and r["bytes"] > 0 for r in columnar)


def test_two_rank_empty_all_to_alls_elided(battery_results):
    _single, columnar, _no_nb = battery_results
    # pure-gather waves + contributor masks: every run elides legs
    assert sum(r["elided"] for r in columnar) > 0
    assert all(r["comms_s"] > 0 for r in columnar)


def test_two_rank_no_nb_env_parity(battery_results):
    single, _columnar, no_nb = battery_results
    rank0 = next(r for r in no_nb if r["rank"] == 0)
    assert rank0["counts"] == single["counts"]
    assert rank0["jagg"] == single["jagg"]
    # the env var must force the tuple path end-to-end
    assert all(r["nb_batches"] == 0 for r in no_nb)
    assert sum(r["tuple_fallbacks"] for r in no_nb) > 0


def test_two_rank_object_column_fallback():
    with tempfile.TemporaryDirectory() as td:
        single = _run_battery(td, 1, program=_OBJECT_COLUMN)[0]
        two = _run_battery(td, 2, program=_OBJECT_COLUMN)
        rank0 = next(r for r in two if r["rank"] == 0)
        assert rank0["agg"] == single["agg"]
        # tuple-valued rows can never ride the columnar path
        assert all(r["nb_batches"] == 0 for r in two)


_SMOKE = """
import json, os, sys
sys.path.insert(0, {repo!r})
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import pathway_tpu as pw

rank = int(os.environ.get("PATHWAY_PROCESS_ID", "0"))
P = int(os.environ.get("PATHWAY_PROCESSES", "1"))
rows = [{{"data": f"w{{i % 3}}"}} for i in range(rank, 90, P)]

class Src(pw.io.python.ConnectorSubject):
    _deletions_enabled = False
    _distributed_partitioned = True
    def run(self):
        self.next_batch(rows)
        self.commit()

class S(pw.Schema):
    data: str

t = pw.io.python.read(Src(), schema=S, autocommit_duration_ms=3_600_000)
counts = t.groupby(pw.this.data).reduce(
    word=pw.this.data, c=pw.reducers.count()
)
state = {{}}
def on_change(key, row, time_, is_add):
    if is_add:
        state[int(key)] = row
    else:
        state.pop(int(key), None)
pw.io.subscribe(counts, on_change=on_change)
pw.run(monitoring_level=pw.MonitoringLevel.NONE)
print(json.dumps({{"rank": rank,
                  "counts": sorted((r["word"], r["c"]) for r in state.values())}}))
"""


def test_tree_gather_4rank_bit_identical_to_flat():
    """Real 4-process mesh, gather legs routed over the fanout-2
    reduction tree (the world-4 auto default) vs forced flat: outputs
    bit-identical — interior-rank relays lose nothing (the live half
    of the drop_relay model-checker pin, ISSUE 13)."""
    with tempfile.TemporaryDirectory() as td:
        prog = os.path.join(td, "tree_smoke.py")
        with open(prog, "w") as f:
            f.write(_SMOKE.format(repo=REPO))
        tree = _spawn_ranks(
            prog, td, 4, {"PATHWAY_MESH_TREE_FANOUT": "2"}
        )
        flat = _spawn_ranks(
            prog, td, 4, {"PATHWAY_MESH_TREE_FANOUT": "off"}
        )
        t0 = next(r for r in tree if r["rank"] == 0)
        f0 = next(r for r in flat if r["rank"] == 0)
        assert t0["counts"] == f0["counts"]
        assert t0["counts"] == [["w0", 30], ["w1", 30], ["w2", 30]]


def test_exchange_smoke_2rank():
    """Real 2-process columnar exchange smoke (ci_lanes.sh lane 2 runs
    exactly this test after the emulated-lane battery)."""
    with tempfile.TemporaryDirectory() as td:
        prog = os.path.join(td, "smoke.py")
        with open(prog, "w") as f:
            f.write(_SMOKE.format(repo=REPO))
        outs = _spawn_ranks(prog, td, 2)
        rank0 = next(r for r in outs if r["rank"] == 0)
        assert rank0["counts"] == [["w0", 30], ["w1", 30], ["w2", 30]]
