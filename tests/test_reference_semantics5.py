"""Fifth reference-semantics battery: reducer breadth (tuple families,
earliest/latest, unique/any under retraction), asof_now one-shot joins,
numeric/datetime edge semantics, and global-reduce lifecycle — behaviors
the reference pins in python/pathway/tests/test_reducers.py,
test_asof_now_join.py and test_expressions.py."""

import datetime

import pytest

import pathway_tpu as pw
from pathway_tpu.internals.graph_runner import GraphRunner


def _rows(table):
    cap = GraphRunner().run_tables(table)[0]
    return sorted(
        (tuple(r) for r in cap.state.rows.values()), key=repr
    )


def _md(txt, schema=None):
    return pw.debug.table_from_markdown(txt, schema=schema)


# ------------------------------------------------------------- reducers


def test_tuple_reducer_families():
    t = _md(
        """
        g | v
        0 | 3
        0 | 1
        0 | 2
        1 | 9
        """
    )
    r = t.groupby(pw.this.g).reduce(
        g=pw.this.g,
        st=pw.reducers.sorted_tuple(pw.this.v),
        nd=pw.reducers.ndarray(pw.this.v),
    )
    rows = {row[0]: row[1:] for row in _rows(r)}
    assert rows[0][0] == (1, 2, 3)
    assert sorted(rows[0][1].tolist()) == [1, 2, 3]
    assert rows[1][0] == (9,)


def test_tuple_reducer_skip_nones():
    t = _md(
        """
        g | v
        0 | 3
        0 |
        0 | 1
        """
    )
    r = t.groupby(pw.this.g).reduce(
        with_none=pw.reducers.sorted_tuple(pw.this.v),
        without=pw.reducers.sorted_tuple(pw.this.v, skip_nones=True),
    )
    ((with_none, without),) = _rows(r)
    assert without == (1, 3)
    # None sorts first (reference: test_common.py test_tuple_reducer pins
    # sorted_tuple without skip_nones as (None, -1, 1))
    assert with_none == (None, 1, 3)


def test_earliest_latest_follow_processing_order():
    class S(pw.Schema):
        k: int = pw.column_definition(primary_key=True)
        v: str

    class Sub(pw.io.python.ConnectorSubject):
        def run(self):
            self.next(k=1, v="first")
            self.commit()
            self.next(k=2, v="second")
            self.commit()
            self.next(k=3, v="third")
            self.commit()

    t = pw.io.python.read(Sub(), schema=S, autocommit_duration_ms=None)
    r = t.reduce(
        e=pw.reducers.earliest(pw.this.v), l=pw.reducers.latest(pw.this.v)
    )
    cap = GraphRunner().run_tables(r)[0]
    ((e, l),) = [tuple(row) for row in cap.state.rows.values()]
    # earliest/latest order by engine timestamp of arrival
    assert e == "first" and l == "third"


def test_unique_reducer_allows_duplicates_of_same_value():
    t = _md(
        """
        g | v
        0 | 7
        0 | 7
        1 | 5
        """
    )
    r = t.groupby(pw.this.g).reduce(g=pw.this.g, u=pw.reducers.unique(pw.this.v))
    assert _rows(r) == [(0, 7), (1, 5)]


def test_any_reducer_returns_some_member():
    t = _md(
        """
        g | v
        0 | 7
        0 | 9
        """
    )
    r = t.groupby(pw.this.g).reduce(a=pw.reducers.any(pw.this.v))
    ((a,),) = _rows(r)
    assert a in (7, 9)


def test_global_reduce_empties_to_no_rows():
    """Retracting every input row must retract the global aggregate row
    (reference: reduce over an emptied table yields an empty table)."""

    class S(pw.Schema):
        k: int = pw.column_definition(primary_key=True)
        v: int

    class Sub(pw.io.python.ConnectorSubject):
        def run(self):
            self.next(k=1, v=5)
            self.next(k=2, v=6)
            self.commit()
            self.remove(k=1, v=5)
            self.remove(k=2, v=6)
            self.commit()

    t = pw.io.python.read(Sub(), schema=S, autocommit_duration_ms=None)
    r = t.reduce(s=pw.reducers.sum(pw.this.v), c=pw.reducers.count())
    cap = GraphRunner().run_tables(r)[0]
    assert list(cap.state.rows.values()) == []


def test_min_max_on_bools_and_mixed_int_float():
    t = _md(
        """
        g | b | x
        0 | True | 1
        0 | False | 2
        """,
        schema=pw.schema_from_types(g=int, b=bool, x=int),
    )
    r = t.groupby(pw.this.g).reduce(
        mn=pw.reducers.min(pw.this.b), mx=pw.reducers.max(pw.this.b)
    )
    ((mn, mx),) = _rows(r)
    assert mn == False and mx == True  # noqa: E712 — bool ordering


# ----------------------------------------------------------- asof_now


def test_asof_now_join_answers_are_frozen():
    """A left row is answered against the right state AT ARRIVAL and the
    answer never revises when the right side later changes (reference:
    _asof_now_join semantics)."""

    class L(pw.Schema):
        k: int = pw.column_definition(primary_key=True)
        j: int

    class R(pw.Schema):
        j: int = pw.column_definition(primary_key=True)
        w: str

    events = []
    # commit-order gates instead of sleeps: runtime commits get their
    # timestamps in queue-arrival order, so "commit() returned before the
    # peer's next commit()" IS the ordering guarantee — robust on a
    # loaded 1-core CI box where sleep races flake
    import threading

    r_loaded = threading.Event()
    l_first_done = threading.Event()
    r_updated = threading.Event()

    class LSub(pw.io.python.ConnectorSubject):
        def run(self):
            r_loaded.wait(timeout=30)  # right side loads first
            self.next(k=1, j=1)
            self.commit()
            l_first_done.set()
            r_updated.wait(timeout=30)  # right side then CHANGES
            self.next(k=2, j=1)
            self.commit()

    class RSub(pw.io.python.ConnectorSubject):
        def run(self):
            self.next(j=1, w="old")
            self.commit()
            r_loaded.set()
            l_first_done.wait(timeout=30)
            self.remove(j=1, w="old")
            self.next(j=1, w="new")
            self.commit()
            r_updated.set()

    lt = pw.io.python.read(LSub(), schema=L, autocommit_duration_ms=None)
    rt = pw.io.python.read(RSub(), schema=R, autocommit_duration_ms=None)
    j = lt.asof_now_join(rt, pw.left.j == pw.right.j).select(
        k=pw.left.k, w=pw.right.w
    )
    pw.io.subscribe(
        j, on_change=lambda key, row, t_, d: events.append(
            (row["k"], row["w"], 1 if d else -1)
        )
    )
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    net = {}
    for k, w, d in events:
        net[(k, w)] = net.get((k, w), 0) + d
    live = sorted(kw for kw, c in net.items() if c > 0)
    # k=1 keeps its frozen "old" answer; k=2 sees the updated state
    assert live == [(1, "old"), (2, "new")], (live, events)


# ------------------------------------------------- numeric / datetime


def test_integer_division_and_modulo_semantics():
    t = _md(
        """
        a | b
        7 | 2
        -7 | 2
        """
    )
    r = t.select(
        fdiv=pw.this.a // pw.this.b,
        tdiv=pw.this.a / pw.this.b,
        mod=pw.this.a % pw.this.b,
    )
    rows = _rows(r)
    assert (-4, -3.5, 1) in rows  # Python floor semantics on negatives
    assert (3, 3.5, 1) in rows


def test_datetime_arithmetic_and_duration():
    fmt = "%Y-%m-%d %H:%M:%S"
    t = _md(
        """
        a | b
        2026-01-02 03:04:05 | 2026-01-01 00:00:00
        """,
        schema=pw.schema_from_types(a=str, b=str),
    )
    r = t.select(
        a=pw.this.a.dt.strptime(fmt),
        b=pw.this.b.dt.strptime(fmt),
    ).select(
        delta_hours=(pw.this.a - pw.this.b).dt.hours(),
        shifted=pw.this.b + pw.Duration(days=1),
    )
    ((hours, shifted),) = _rows(r)
    assert hours == 27
    assert shifted == datetime.datetime(2026, 1, 2)


def test_string_edges():
    t = _md(
        """
        s
        hello_world
        """
    )
    r = t.select(
        up=pw.this.s.str.upper(),
        found=pw.this.s.str.find("world"),
        missing=pw.this.s.str.find("zzz"),
        sliced=pw.this.s.str.slice(0, 5),
        replaced=pw.this.s.str.replace("_", " "),
    )
    assert _rows(r) == [("HELLO_WORLD", 6, -1, "hello", "hello world")]


def test_optional_none_propagation_in_arithmetic():
    t = _md(
        """
        a | b
        1 | 2
        3 |
        """,
        schema=pw.schema_from_types(
            a=int, b=pw.internals.dtype.Optional(int)
        ),
    )
    r = t.select(s=pw.this.a + pw.fill_error(pw.coalesce(pw.this.b, 0), 0))
    assert sorted(_rows(r)) == [(3,), (3,)]


def test_pointer_column_roundtrip_and_ix():
    t = _md(
        """
        k | v
        1 | a
        2 | b
        """
    )
    withptr = t.select(pw.this.v, ptr=pw.this.id)
    looked = withptr.select(orig=t.ix(withptr.ptr).v)
    assert sorted(_rows(looked)) == [("a",), ("b",)]
