"""Fused QueryEngine correctness vs the two-step encode+search path."""

import numpy as np

from pathway_tpu.models import EncoderConfig, SentenceEncoder
from pathway_tpu.ops import KnnShard, QueryEngine


def test_query_engine_matches_two_step():
    enc = SentenceEncoder(EncoderConfig.tiny(), batch_size=16)
    docs = [
        "the cat sat on the mat",
        "dogs are loyal pets",
        "quantum computing with qubits",
        "a feline rested on a rug",
    ]
    embs = enc.encode(docs)
    shard = KnnShard(enc.embed_dim, "cos")
    shard.add(list(range(len(docs))), embs)

    engine = QueryEngine(enc, shard, k=2)
    queries = ["cat on a mat", "qubit computer"]
    fused = engine.query(queries)

    q_emb = enc.encode(queries)
    two_step = shard.search(q_emb, 2)

    for f, t in zip(fused, two_step):
        assert [k for k, _ in f] == [k for k, _ in t]
        np.testing.assert_allclose(
            [s for _, s in f], [s for _, s in t], rtol=1e-3, atol=1e-3
        )


def test_query_engine_empty_index():
    enc = SentenceEncoder(EncoderConfig.tiny())
    shard = KnnShard(enc.embed_dim, "cos")
    engine = QueryEngine(enc, shard, k=3)
    assert engine.query(["anything"]) == [[]]
