"""Fused QueryEngine correctness vs the two-step encode+search path."""

import numpy as np

from pathway_tpu.models import EncoderConfig, SentenceEncoder
from pathway_tpu.ops import KnnShard, QueryEngine


def test_query_engine_matches_two_step():
    enc = SentenceEncoder(EncoderConfig.tiny(), batch_size=16)
    docs = [
        "the cat sat on the mat",
        "dogs are loyal pets",
        "quantum computing with qubits",
        "a feline rested on a rug",
    ]
    embs = enc.encode(docs)
    shard = KnnShard(enc.embed_dim, "cos")
    shard.add(list(range(len(docs))), embs)

    engine = QueryEngine(enc, shard, k=2)
    queries = ["cat on a mat", "qubit computer"]
    fused = engine.query(queries)

    q_emb = enc.encode(queries)
    two_step = shard.search(q_emb, 2)

    for f, t in zip(fused, two_step):
        assert [k for k, _ in f] == [k for k, _ in t]
        np.testing.assert_allclose(
            [s for _, s in f], [s for _, s in t], rtol=1e-3, atol=1e-3
        )


def test_query_engine_empty_index():
    enc = SentenceEncoder(EncoderConfig.tiny())
    shard = KnnShard(enc.embed_dim, "cos")
    engine = QueryEngine(enc, shard, k=3)
    assert engine.query(["anything"]) == [[]]


def test_two_buffer_readback_past_packed_cap():
    """Shards at capacity >= 1<<24 exceed f32 slot-id packing; the engine
    switches to the two-buffer (vals, i32 idx) path and still answers
    exactly (VERDICT r4 #8: works at a 20M-capacity shard; the packed
    path stays in use below the cap)."""
    from pathway_tpu.models.encoder import EncoderConfig

    enc = SentenceEncoder(
        EncoderConfig(vocab_size=128, hidden=8, layers=1, heads=2, mlp=16,
                      max_len=16),
        batch_size=4,
    )
    shard = KnnShard(enc.embed_dim, "cos", capacity=20_000_000)
    assert shard.capacity >= (1 << 24)
    docs = ["alpha beta", "gamma delta", "epsilon zeta"]
    embs = enc.encode(docs)
    # place one doc at a slot ABOVE the f32-exact range to prove i32
    # indices survive the readback
    hi_slot = (1 << 24) + 12345
    shard.key_to_slot["hi"] = hi_slot
    shard.slot_to_key[hi_slot] = "hi"
    shard.free_slots.remove(hi_slot)
    import jax.numpy as jnp
    from pathway_tpu.ops.knn import _write_slots

    shard.vectors, shard.valid, shard.sq_norms = _write_slots(
        shard.vectors, shard.valid, shard.sq_norms,
        jnp.asarray([hi_slot]), jnp.asarray(embs[:1]),
        jnp.ones((1,), bool), normalize=True,
    )
    shard.add(["a", "b"], embs[1:])

    engine = QueryEngine(enc, shard, k=2)
    hits = engine.query([docs[0]])[0]
    assert hits and hits[0][0] == "hi"  # exact hi slot round-tripped
    ticket = engine.dispatch([docs[0]])
    assert ticket[2] is False  # two-buffer path engaged

    # below the cap the packed path stays in use
    small = KnnShard(enc.embed_dim, "cos", capacity=1024)
    small.add(["x"], embs[:1])
    engine_small = QueryEngine(enc, small, k=2)
    assert engine_small.dispatch([docs[0]])[2] is True


def test_update_while_serving_consistency():
    """Concurrent add/remove churn against in-flight fused queries: no
    torn snapshots, no donated-buffer crashes (shard.lock serializes
    write vs read+launch), and every answer maps to a key that existed."""
    import threading

    enc = SentenceEncoder(EncoderConfig.tiny(), batch_size=8)
    shard = KnnShard(enc.embed_dim, "cos", capacity=4096)
    rng = np.random.default_rng(3)
    base = rng.normal(size=(256, enc.embed_dim)).astype(np.float32)
    shard.add(list(range(256)), base)
    engine = QueryEngine(enc, shard, k=4)
    engine.query(["warm"])

    stop = threading.Event()
    errors = []

    def updater():
        nk = 1000
        try:
            while not stop.is_set():
                vecs = rng.normal(size=(32, enc.embed_dim)).astype(
                    np.float32
                )
                keys = list(range(nk, nk + 32))
                shard.add(keys, vecs)
                nk += 32
                shard.remove(keys[:16])
        except Exception as exc:  # pragma: no cover
            errors.append(exc)

    def querier():
        try:
            for i in range(30):
                hits = engine.query([f"query number {i}"])[0]
                for key, score in hits:
                    assert isinstance(key, int)
                    assert np.isfinite(score)
        except Exception as exc:  # pragma: no cover
            errors.append(exc)

    ut = threading.Thread(target=updater)
    qs = [threading.Thread(target=querier) for _ in range(3)]
    ut.start()
    for q in qs:
        q.start()
    for q in qs:
        q.join(timeout=120)
    stop.set()
    ut.join(timeout=30)
    assert not errors, errors


def test_slot_reuse_between_dispatch_and_finish_drops_hit():
    """A slot freed (and reused by a new key) after dispatch must not map
    the in-flight score to the NEW key: the remove-epoch guard drops it
    (removed-row semantics)."""
    enc = SentenceEncoder(EncoderConfig.tiny(), batch_size=4)
    shard = KnnShard(enc.embed_dim, "cos", capacity=64)
    embs = enc.encode(["only document here", "another unrelated text"])
    shard.add(["old", "other"], embs)
    engine = QueryEngine(enc, shard, k=1)
    engine.query(["warm"])

    ticket = engine.dispatch(["only document here"])
    old_slot = shard.key_to_slot["old"]
    shard.remove(["old"])
    shard.add(["new"], embs[1:])  # free list reuses the freed slot
    assert shard.key_to_slot["new"] == old_slot  # reuse actually happened
    hits = engine.finish(ticket)[0]
    assert all(k != "new" for k, _ in hits), hits

    # a fresh query resolves against the updated mapping
    hits2 = engine.query(["another unrelated text"])[0]
    assert hits2 and hits2[0][0] in ("new", "other")
