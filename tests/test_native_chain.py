"""NativeBatch fused-chain tests — the zero-interpreter steady state.

The reference's hot loop runs every operator natively with no interpreter
dispatch (reference: src/engine/dataflow.rs:5595-5650 `step_or_park` on the
timely substrate). Our equivalent is the columnar NativeBatch: the C parser
(exec.cpp parse_upserts_nb) hands the group-by executor
(exec.cpp process_batch_nb) a C-owned batch, and no per-row Python object
exists between ingest and reducer state. These tests pin:

* the chain actually engages on the wordcount shape (spy counter — no
  silent demotion);
* results are bit-identical to the Python/tuple paths across value types;
* every boundary degrades gracefully (non-columnar values, non-abelian
  reducers, persistence journaling, non-native consumers).
"""

from __future__ import annotations

from collections import Counter

import pytest

import pathway_tpu as pw
from pathway_tpu.native import get_pwexec

pytestmark = pytest.mark.skipif(
    get_pwexec() is None or not hasattr(get_pwexec(), "parse_upserts_nb"),
    reason="native toolchain unavailable",
)


def _spy_nb_batches(monkeypatch):
    """Patch GroupByNode.process to record per-node nb-batch counts."""
    import pathway_tpu.engine.nodes as N

    counts: list[int] = []
    orig = N.GroupByNode.process

    def process(self, time, batches):
        out = orig(self, time, batches)
        counts.append(getattr(self, "_nb_batches", 0))
        return out

    monkeypatch.setattr(N.GroupByNode, "process", process)
    return counts


def _run_wordcount(rows, autocommit=3_600_000, persistence_config=None):
    pw.internals.parse_graph.G.clear()

    class Source(pw.io.python.ConnectorSubject):
        _deletions_enabled = False

        def run(self):
            self.next_batch(rows)
            self.commit()

    class S(pw.Schema):
        data: str

    t = pw.io.python.read(
        Source(), schema=S, autocommit_duration_ms=autocommit
    )
    counts = t.groupby(pw.this.data).reduce(
        word=pw.this.data, c=pw.reducers.count()
    )
    live = {}

    def on_change(key, row, time_, diff):
        if diff:
            live[key] = row
        else:
            live.pop(key, None)

    pw.io.subscribe(counts, on_change=on_change)
    pw.run(
        monitoring_level=pw.MonitoringLevel.NONE,
        persistence_config=persistence_config,
    )
    return {r["word"]: r["c"] for r in live.values()}


def test_wordcount_chain_engages_and_counts(monkeypatch):
    import os

    nb_counts = _spy_nb_batches(monkeypatch)
    rows = [{"data": f"w{i % 37}"} for i in range(5_000)]
    got = _run_wordcount(rows)
    want = Counter(r["data"] for r in rows)
    assert got == dict(want)
    # the spy proves the fused chain ran — no silent demotion to the
    # tuple path on the flagship shape. In the emulated multi-rank lane
    # an ExchangeNode feeds the groupby materialized batches, so the nb
    # path legitimately does not engage there.
    if not os.environ.get("PATHWAY_LANE_PROCESSES"):
        assert max(nb_counts, default=0) >= 1


def test_chain_sum_avg_mixed_numerics():
    pw.internals.parse_graph.G.clear()
    rows = [
        {"k": f"g{i % 5}", "v": [1, 2.5, None, 3, -7][i % 5]}
        for i in range(1_000)
    ]

    class Source(pw.io.python.ConnectorSubject):
        _deletions_enabled = False

        def run(self):
            self.next_batch(rows)
            self.commit()

    class S(pw.Schema):
        k: str
        v: float | None

    t = pw.io.python.read(Source(), schema=S, autocommit_duration_ms=None)
    out = t.groupby(pw.this.k).reduce(
        k=pw.this.k,
        n=pw.reducers.count(),
        s=pw.reducers.sum(pw.this.v),
        a=pw.reducers.avg(pw.this.v),
    )
    res = pw.debug.table_to_pandas(out)
    by_k = {r["k"]: r for _, r in res.iterrows()}
    for g in range(5):
        vals = [r["v"] for r in rows if r["k"] == f"g{g}" and r["v"] is not None]
        row = by_k[f"g{g}"]
        assert row["n"] == 200
        if vals:
            assert row["s"] == pytest.approx(sum(vals))
            assert row["a"] == pytest.approx(sum(vals) / len(vals))


def test_non_abelian_reducer_falls_back_correctly(monkeypatch):
    """min() makes the store non-abelian: the nb branch must not engage,
    the materialized path must give exact results."""
    nb_counts = _spy_nb_batches(monkeypatch)
    pw.internals.parse_graph.G.clear()
    rows = [{"k": f"g{i % 3}", "v": (i * 17) % 101} for i in range(300)]

    class Source(pw.io.python.ConnectorSubject):
        _deletions_enabled = False

        def run(self):
            self.next_batch(rows)
            self.commit()

    class S(pw.Schema):
        k: str
        v: int

    t = pw.io.python.read(Source(), schema=S, autocommit_duration_ms=None)
    out = t.groupby(pw.this.k).reduce(
        k=pw.this.k, lo=pw.reducers.min(pw.this.v)
    )
    res = pw.debug.table_to_pandas(out)
    by_k = {r["k"]: r["lo"] for _, r in res.iterrows()}
    for g in range(3):
        assert by_k[f"g{g}"] == min(
            r["v"] for r in rows if r["k"] == f"g{g}"
        )
    assert max(nb_counts, default=0) == 0


def test_non_columnar_values_fall_back():
    """bytes values are outside the columnar set: parse returns the tuple
    path and results stay exact."""
    pw.internals.parse_graph.G.clear()
    rows = [{"k": f"g{i % 3}", "b": bytes([i % 7])} for i in range(100)]

    class Source(pw.io.python.ConnectorSubject):
        _deletions_enabled = False

        def run(self):
            self.next_batch(rows)
            self.commit()

    class S(pw.Schema):
        k: str
        b: bytes

    t = pw.io.python.read(Source(), schema=S, autocommit_duration_ms=None)
    out = t.groupby(pw.this.k).reduce(k=pw.this.k, n=pw.reducers.count())
    res = pw.debug.table_to_pandas(out)
    assert {r["k"]: r["n"] for _, r in res.iterrows()} == dict(
        Counter(r["k"] for r in rows)
    )


def test_bool_and_none_types_survive_materialization():
    """A bool column rides the columnar batch (NB_BOOL) and must come back
    as real bools through a non-native consumer (filter → UDF)."""
    pw.internals.parse_graph.G.clear()
    rows = [{"f": i % 2 == 0, "x": i if i % 3 else None} for i in range(50)]

    class Source(pw.io.python.ConnectorSubject):
        _deletions_enabled = False

        def run(self):
            self.next_batch(rows)
            self.commit()

    class S(pw.Schema):
        f: bool
        x: int | None

    t = pw.io.python.read(Source(), schema=S, autocommit_duration_ms=None)

    @pw.udf
    def typename(v) -> str:
        return type(v).__name__

    out = t.select(tf=typename(pw.this.f), tx=typename(pw.this.x))
    res = pw.debug.table_to_pandas(out)
    assert set(res["tf"]) == {"bool"}
    assert set(res["tx"]) == {"int", "NoneType"}


def test_chain_with_persistence_journal(tmp_path):
    """Stateless subjects journal write-ahead: a NativeBatch flush must
    land picklable (key, row, diff) rows in the journal and replay them
    on restart without double-counting."""
    backend = pw.persistence.Backend.filesystem(str(tmp_path))
    cfg = pw.persistence.Config(backend)
    rows = [{"data": f"w{i % 7}"} for i in range(200)]
    got1 = _run_wordcount(rows, persistence_config=cfg)
    assert got1 == dict(Counter(r["data"] for r in rows))
    # second run: journal replays the first run's rows, then the source
    # re-emits (stateless subject) — counts double exactly
    got2 = _run_wordcount(rows, persistence_config=cfg)
    assert got2 == {w: 2 * c for w, c in Counter(r["data"] for r in rows).items()}


def test_stateful_subject_commit_without_persistence_forwards_rows():
    """Regression (r5 review): a stateful subject (defines snapshot_state)
    running WITHOUT persistence must still forward its commit()-flushed
    batch to the engine — the journal-row emptiness must not be read as
    'nothing happened'."""
    pw.internals.parse_graph.G.clear()

    class Stateful(pw.io.python.ConnectorSubject):
        _deletions_enabled = False

        def run(self):
            self.next_batch([{"data": f"w{i % 3}"} for i in range(30)])
            self.commit()

        def snapshot_state(self):
            return {"pos": 30}

        def seek(self, state):
            pass

    class S(pw.Schema):
        data: str

    t = pw.io.python.read(
        Stateful(), schema=S, autocommit_duration_ms=None
    )
    out = t.groupby(pw.this.data).reduce(
        word=pw.this.data, c=pw.reducers.count()
    )
    res = pw.debug.table_to_pandas(out)
    assert {r["word"]: r["c"] for _, r in res.iterrows()} == {
        "w0": 10, "w1": 10, "w2": 10
    }


def test_nb_parse_and_groupby_unit():
    """Direct unit drive of the C entry points: parse → materialize parity
    and groupby output vs the tuple path on the same store codes."""
    from pathway_tpu.internals.api import ERROR, Pointer, ref_scalar

    ex = get_pwexec()
    msgs = [
        {"k": f"g{i % 4}", "v": float(i), "flag": i % 2 == 0, "x": None}
        for i in range(64)
    ]
    cols = ("k", "v", "flag", "x")
    res = ex.parse_upserts_nb(
        msgs, 0, cols, (None,) * 4, int(ref_scalar("unit")), 0, Pointer
    )
    assert res is not None
    nb, seq = res
    assert seq == 64 and len(nb) == 64
    mat = nb.materialize()
    assert mat[5][1] == ("g1", 5.0, False, None)
    assert mat[5][2] == 1 and isinstance(mat[5][0], Pointer)
    # distinct keys, monotone seq
    assert len({d[0] for d in mat}) == 64

    store = ex.store_new(2, ("count", "sum"), 0)
    out = ex.process_batch_nb(
        store, nb, (0,), (None, 1), lambda g: ref_scalar(*g), ERROR, 2
    )
    got = {r[0]: (r[1], r[2]) for _, r, d in out if d > 0}
    want_cnt = Counter(m["k"] for m in msgs)
    for k, (n, s) in got.items():
        assert n == want_cnt[k]
        assert s == sum(m["v"] for m in msgs if m["k"] == k)
