"""Behavior-matrix battery — the remaining keep/remove × delay × buffer
combinations from the reference's stream corpus (reference:
python/pathway/tests/temporal/test_windows_stream.py:291-392 — the
parametrized battery over common_behavior(delay, cutoff, keep_results)
— plus interval-join forgetting with instances and asof-join
delay/cutoff, test_interval_joins_stream.py:100, test_asof_joins_stream.py).

The driver commits deterministic rounds; assertions cover both the final
state and the presence/absence of withdrawal events — which is the whole
point of keep_results."""

from __future__ import annotations

import pytest

import pathway_tpu as pw


def run_sliding_stream(commits, behavior, hop=2, duration=4):
    pw.internals.parse_graph.G.clear()

    class Events(pw.io.python.ConnectorSubject):
        _deletions_enabled = False

        def run(self):
            for batch in commits:
                for t in batch:
                    self.next(t=t)
                self.commit()

    class S(pw.Schema):
        t: int

    events_t = pw.io.python.read(
        Events(), schema=S, autocommit_duration_ms=None
    )
    res = events_t.windowby(
        events_t.t,
        window=pw.temporal.sliding(hop=hop, duration=duration),
        behavior=behavior,
    ).reduce(
        start=pw.this._pw_window_start,
        c=pw.reducers.count(),
        hi=pw.reducers.max(pw.this.t),
    )
    updates: list[tuple] = []
    pw.io.subscribe(
        res,
        on_change=lambda key, row, time, add: updates.append(
            (row["start"], row["c"], row["hi"], add)
        ),
    )
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    return updates


def live_windows(updates):
    live: dict = {}
    for start, c, hi, add in updates:
        if add:
            live[(start, c, hi)] = live.get((start, c, hi), 0) + 1
        else:
            live[(start, c, hi)] = live.get((start, c, hi), 0) - 1
    return sorted(k for k, n in live.items() if n > 0)


COMMITS = [[1], [2], [3], [6], [12], [4]]
# sliding(hop=2, duration=4) over times 1,2,3,6,12 (+ late 4):
#   window -2: {1}      window 0: {1,2,3}  window 2: {2,3}
#   window 4 gains {6}  window 6: {6}      window 10: {12}  window 12: {12}
# the late t=4 (19 behind the 12-watermark) belongs to windows 2 and 4.


def test_keep_results_sliding():
    updates = run_sliding_stream(
        COMMITS, pw.temporal.common_behavior(cutoff=2, keep_results=True)
    )
    got = live_windows(updates)
    # late t=4 was dropped (cutoff) but closed windows KEPT their results
    assert ((-2), 1, 1) in got
    assert (0, 3, 3) in got
    assert (2, 2, 3) in got  # without the late event it would gain t=4
    assert (10, 1, 12) in got and (12, 1, 12) in got


def test_remove_results_sliding():
    updates = run_sliding_stream(
        COMMITS, pw.temporal.common_behavior(cutoff=2, keep_results=False)
    )
    got = live_windows(updates)
    # windows far behind the watermark were WITHDRAWN from the output
    assert not any(s in (-2, 0) for s, _c, _hi in got)
    # but they did exist at some point (insert followed by retraction)
    assert any(s == 0 and add for s, _c, _hi, add in updates)
    assert any(s == 0 and not add for s, _c, _hi, add in updates)
    # the newest windows survive
    assert any(s == 12 for s, _c, _hi in got)


def test_non_zero_delay_keep_results_sliding():
    updates = run_sliding_stream(
        COMMITS,
        pw.temporal.common_behavior(delay=2, cutoff=2, keep_results=True),
    )
    got = live_windows(updates)
    assert (0, 3, 3) in got
    # delay batched the first three commits: window 0 must never have
    # appeared with c=1
    assert not any(s == 0 and c == 1 for s, c, _hi, add in updates if add)


def test_non_zero_delay_remove_results_sliding():
    updates = run_sliding_stream(
        COMMITS,
        pw.temporal.common_behavior(delay=2, cutoff=2, keep_results=False),
    )
    got = live_windows(updates)
    assert not any(s in (-2, 0) for s, _c, _hi in got)
    assert any(s == 12 for s, _c, _hi in got)


def test_high_delay_high_buffer_keep_results():
    # delay larger than the whole stream: everything flushes at close,
    # each window exactly once, with its final value
    updates = run_sliding_stream(
        COMMITS,
        pw.temporal.common_behavior(
            delay=100, cutoff=100, keep_results=True
        ),
    )
    assert all(add for *_x, add in updates)
    got = live_windows(updates)
    # with an enormous cutoff the late t=4 IS accepted: window 2 = {2,3,4}
    assert (2, 3, 4) in got
    assert (4, 2, 6) in got


def test_zero_cutoff_drops_everything_behind_watermark():
    updates = run_sliding_stream(
        [[10], [1]],
        pw.temporal.common_behavior(cutoff=0, keep_results=True),
    )
    got = live_windows(updates)
    # t=1 is behind the 10-watermark with zero tolerance: its windows
    # must not exist
    assert all(s >= 8 for s, _c, _hi in got)


# ---------------------------------------------------------------------------
# interval join forgetting with instances


def test_interval_join_stream_forget_with_instance():
    pw.internals.parse_graph.G.clear()
    import threading

    # event ping-pong instead of sleeps: commit() enqueues synchronously,
    # so gate order IS engine timestamp order even on a loaded box
    l0 = threading.Event()
    r0 = threading.Event()
    l1 = threading.Event()
    r1 = threading.Event()

    class Left(pw.io.python.ConnectorSubject):
        _deletions_enabled = False

        def run(self):
            self.next(k="a", t=0)
            self.next(k="b", t=0)
            self.commit()
            l0.set()
            r0.wait(timeout=30)
            self.next(k="a", t=100)
            self.commit()
            l1.set()
            r1.wait(timeout=30)
            # late rows for both instances: must find their right
            # partners already forgotten
            self.next(k="a", t=1)
            self.next(k="b", t=1)
            self.commit()

    class Right(pw.io.python.ConnectorSubject):
        _deletions_enabled = False

        def run(self):
            l0.wait(timeout=30)
            self.next(k="a", t=0)
            self.next(k="b", t=0)
            self.commit()
            r0.set()
            l1.wait(timeout=30)
            self.next(k="a", t=100)
            self.commit()
            r1.set()

    class S(pw.Schema):
        k: str
        t: int

    lt = pw.io.python.read(Left(), schema=S, autocommit_duration_ms=None)
    rt = pw.io.python.read(Right(), schema=S, autocommit_duration_ms=None)
    res = pw.temporal.interval_join(
        lt, rt, lt.t, rt.t, pw.temporal.interval(-2, 2), lt.k == rt.k,
        behavior=pw.temporal.common_behavior(cutoff=10, keep_results=True),
    ).select(k=lt.k, lt_=lt.t, rt_=rt.t)
    got = []
    pw.io.subscribe(
        res,
        on_change=lambda key, row, time, add: got.append(
            (row["k"], row["lt_"], row["rt_"], add)
        ),
    )
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    live = {(k, l, r) for k, l, r, a in got if a}
    assert ("a", 0, 0) in live and ("b", 0, 0) in live
    assert ("a", 100, 100) in live
    # per-instance forgetting: the late t=1 rows of BOTH instances miss
    assert ("a", 1, 0) not in live and ("b", 1, 0) not in live


# ---------------------------------------------------------------------------
# asof join under behaviors


def _run_asof_stream(l_rounds, r_rounds, behavior):
    """L commits first, then the R rounds in order — gated on events, not
    sleeps (commit() enqueues synchronously: gate order == timestamps)."""
    pw.internals.parse_graph.G.clear()
    import threading

    sched: list[tuple[str, int]] = []
    for i in range(max(len(l_rounds), len(r_rounds))):
        if i < len(l_rounds):
            sched.append(("L", i))
        if i < len(r_rounds):
            sched.append(("R", i))
    pos = {si: p for p, si in enumerate(sched)}
    turn = [0]
    cv = threading.Condition()

    def gate(side, i):
        with cv:
            cv.wait_for(lambda: turn[0] == pos[(side, i)], timeout=30)

    def done():
        with cv:
            turn[0] += 1
            cv.notify_all()

    class Left(pw.io.python.ConnectorSubject):
        _deletions_enabled = False

        def run(self):
            for i, batch in enumerate(l_rounds):
                gate("L", i)
                for t, v in batch:
                    self.next(t=t, v=v)
                self.commit()
                done()

    class Right(pw.io.python.ConnectorSubject):
        _deletions_enabled = False

        def run(self):
            for i, batch in enumerate(r_rounds):
                gate("R", i)
                for t, v in batch:
                    self.next(t=t, v=v)
                self.commit()
                done()

    class S(pw.Schema):
        t: int
        v: int

    lt = pw.io.python.read(Left(), schema=S, autocommit_duration_ms=None)
    rt = pw.io.python.read(Right(), schema=S, autocommit_duration_ms=None)
    res = pw.temporal.asof_join(
        lt, rt, lt.t, rt.t, how="left", behavior=behavior
    ).select(lt_=lt.t, lv=lt.v, rv=rt.v)
    got = []
    pw.io.subscribe(
        res,
        on_change=lambda key, row, time, add: got.append(
            (row["lt_"], row["lv"], row["rv"], add)
        ),
    )
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    return got


def test_asof_stream_without_behavior_revises():
    got = _run_asof_stream(
        [[(10, 1)]],
        [[(5, 50)], [(8, 80)]],
        None,
    )
    live = {}
    for lt_, lv, rv, add in got:
        if add:
            live[(lt_, lv)] = rv
        elif live.get((lt_, lv)) == rv:
            del live[(lt_, lv)]
    assert live == {(10, 1): 80}
    assert (10, 1, 50, False) in got  # the earlier answer was revised


def test_asof_stream_cutoff_freezes_old_answers():
    # reference semantics (temporal_behavior applied per side,
    # time_column.rs — each gate watermarks over ITS OWN input): a right
    # row far behind the RIGHT side's own watermark is dropped and must
    # not revise earlier answers
    got = _run_asof_stream(
        [[(10, 1)]],
        [[(5, 50)], [(300, 99)], [(8, 80)]],  # 8 is 292 late on its side
        pw.temporal.common_behavior(cutoff=20, keep_results=True),
    )
    live = {}
    for lt_, lv, rv, add in got:
        if add:
            live[(lt_, lv)] = rv
        elif live.get((lt_, lv)) == rv:
            del live[(lt_, lv)]
    # backward-asof for t=10 considers rt<=10: the on-time 5 answers it;
    # the late 8 (threshold 28 << watermark 300) is ignored
    assert live[(10, 1)] == 50


def test_asof_stream_in_cutoff_late_row_still_revises():
    # the counterpart: a late-but-within-cutoff right row DOES revise
    got = _run_asof_stream(
        [[(10, 1)]],
        [[(5, 50)], [(12, 99)], [(8, 80)]],  # 8 is 4 late, cutoff 20
        pw.temporal.common_behavior(cutoff=20, keep_results=True),
    )
    live = {}
    for lt_, lv, rv, add in got:
        if add:
            live[(lt_, lv)] = rv
        elif live.get((lt_, lv)) == rv:
            del live[(lt_, lv)]
    assert live[(10, 1)] == 80


# ---------------------------------------------------------------------------
# mixed reducers through windows in streaming mode


def test_windowed_mixed_reducers_stream_consistency():
    pw.internals.parse_graph.G.clear()

    class Events(pw.io.python.ConnectorSubject):
        _deletions_enabled = False

        def run(self):
            for t, v in [(1, 5), (2, 9), (3, 1), (6, 4), (7, 2)]:
                self.next(t=t, v=v)
                self.commit()

    class S(pw.Schema):
        t: int
        v: int

    events_t = pw.io.python.read(
        Events(), schema=S, autocommit_duration_ms=None
    )
    res = events_t.windowby(
        events_t.t, window=pw.temporal.tumbling(duration=5)
    ).reduce(
        start=pw.this._pw_window_start,
        n=pw.reducers.count(),
        lo=pw.reducers.min(pw.this.v),
        hi=pw.reducers.max(pw.this.v),
        s=pw.reducers.sum(pw.this.v),
        vs=pw.reducers.sorted_tuple(pw.this.v),
    )
    live = {}
    pw.io.subscribe(
        res,
        on_change=lambda key, row, time, add: (
            live.__setitem__(key, row) if add else live.pop(key, None)
        ),
    )
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    by_start = {r["start"]: r for r in live.values()}
    assert by_start[0] == {
        "start": 0, "n": 3, "lo": 1, "hi": 9, "s": 15, "vs": (1, 5, 9)
    }
    assert by_start[5] == {
        "start": 5, "n": 2, "lo": 2, "hi": 4, "s": 6, "vs": (2, 4)
    }


@pytest.mark.parametrize("keep", [True, False])
def test_exactly_once_vs_common_equivalence_final_counts(keep):
    """exactly_once is sugar for (delay=end-aligned, cutoff) — final
    counts of surviving windows agree with a keep_results common
    behavior of the same cutoff."""
    updates_eo = run_sliding_stream(
        [[1], [2], [9]],
        pw.temporal.exactly_once_behavior(),
        hop=4,
        duration=4,
    )
    finals_eo = {
        (s, c) for s, c, _hi, add in updates_eo if add
    }
    updates_cb = run_sliding_stream(
        [[1], [2], [9]],
        pw.temporal.common_behavior(delay=4, cutoff=4, keep_results=keep),
        hop=4,
        duration=4,
    )
    live_cb = {(s, c) for s, c, _hi in live_windows(updates_cb)}
    # window [0,4) finalized at c=2 under both
    assert (0, 2) in finals_eo
    if keep:
        assert (0, 2) in live_cb
