"""Regression tests for the second review batch (round 1)."""

import numpy as np

import pathway_tpu as pw
from pathway_tpu.internals.graph_runner import GraphRunner


def _rows(table):
    captures = GraphRunner().run_tables(table)
    return sorted(captures[0].state.rows.values())


def test_filter_accepts_numpy_bool():
    t = pw.debug.table_from_markdown(
        """
        v
        1
        2
        3
        """
    )

    @pw.udf(deterministic=True)
    def np_gt(v: int) -> bool:
        return np.bool_(v > 1)

    out = t.filter(np_gt(pw.this.v))
    assert _rows(out) == [(2,), (3,)]


def test_if_else_accepts_numpy_bool():
    t = pw.debug.table_from_markdown(
        """
        v
        1
        5
        """
    )

    @pw.udf(deterministic=True)
    def np_big(v: int) -> bool:
        return np.bool_(v > 3)

    out = t.select(r=pw.if_else(np_big(pw.this.v), pw.this.v * 10, 0))
    assert _rows(out) == [(0,), (50,)]


def test_upsert_retracts_previous_row():
    class Subject(pw.io.python.ConnectorSubject):
        def run(self):
            self.next(k=1, v=10)
            self.commit()
            self.next(k=1, v=20)
            self.commit()

    class S(pw.Schema):
        k: int = pw.column_definition(primary_key=True)
        v: int

    t = pw.io.python.read(Subject(), schema=S)
    agg = t.reduce(c=pw.reducers.count(), s=pw.reducers.sum(pw.this.v))
    res = {}
    pw.io.subscribe(
        agg,
        on_change=lambda key, row, time, is_addition: res.update(
            {"last": (row["c"], row["s"], is_addition)}
        ),
    )
    pw.run()
    assert res["last"] == (1, 20, True)  # not double-counted


def test_nondeterministic_udf_in_reducer_args():
    calls = [0]

    @pw.udf  # deterministic=False by default
    def tag(v: int) -> int:
        calls[0] += 1
        return calls[0] * 100

    class Subject(pw.io.python.ConnectorSubject):
        def run(self):
            self.next(k=1, v=1)
            self.next(k=2, v=2)
            self.commit()
            self.remove(k=1, v=1)
            self.commit()

    class S(pw.Schema):
        k: int = pw.column_definition(primary_key=True)
        v: int

    t = pw.io.python.read(Subject(), schema=S)
    agg = t.reduce(s=pw.reducers.sum(tag(pw.this.v)))
    final = {}
    pw.io.subscribe(
        agg,
        on_change=lambda key, row, time, is_addition: final.update(
            {"s": row["s"]} if is_addition else {}
        ),
    )
    pw.run()
    # after retraction of row k=1, the sum must equal the surviving row's
    # original tag (its first-computed value), not a recomputed one
    assert final["s"] == 200


def test_memoized_rowwise_with_ndarray_column():
    @pw.udf
    def vec(v: int) -> np.ndarray:
        return np.asarray([v, v], dtype=np.float32)

    class Subject(pw.io.python.ConnectorSubject):
        def run(self):
            self.next(k=1, v=7)
            self.commit()
            self.remove(k=1, v=7)
            self.commit()

    class S(pw.Schema):
        k: int = pw.column_definition(primary_key=True)
        v: int

    t = pw.io.python.read(Subject(), schema=S)
    sel = t.select(pw.this.k, e=vec(pw.this.v))
    events = []
    pw.io.subscribe(
        sel,
        on_change=lambda key, row, time, is_addition: events.append(is_addition),
    )
    pw.run()  # must not raise "truth value of an array is ambiguous"
    assert events == [True, False]


def test_join_id_from_pointer_column_values():
    t1 = pw.debug.table_from_markdown(
        """
        a
        1
        2
        """
    )
    t1 = t1.with_columns(p=t1.pointer_from(t1.a))
    t2 = pw.debug.table_from_markdown(
        """
        b
        1
        2
        """
    )
    joined = t1.join(t2, t1.a == t2.b, id=t1.p).select(pw.this.a)
    captures = GraphRunner().run_tables(joined)
    keys = set(captures[0].state.rows.keys())
    from pathway_tpu.internals.api import ref_scalar

    assert keys == {ref_scalar(1), ref_scalar(2)}


def test_groupby_id_kwarg_sets_output_ids():
    t = pw.debug.table_from_markdown(
        """
        a | v
        1 | 10
        1 | 20
        2 | 30
        """
    )
    t = t.with_columns(p=t.pointer_from(t.a))
    agg = t.groupby(id=pw.this.p).reduce(s=pw.reducers.sum(pw.this.v))
    captures = GraphRunner().run_tables(agg)
    from pathway_tpu.internals.api import ref_scalar

    got = {k: row for k, row in captures[0].state.rows.items()}
    assert got == {ref_scalar(1): (30,), ref_scalar(2): (30,)}


def test_join_rejects_unknown_kwargs():
    t1 = pw.debug.table_from_markdown("a\n1")
    t2 = pw.debug.table_from_markdown("b\n1")
    try:
        t1.join(t2, t1.a == t2.b, bogus=True)
    except TypeError:
        pass
    else:
        raise AssertionError("expected TypeError for unknown join kwarg")


def test_fs_remove_with_duplicate_content(tmp_path):
    # two files with identical content; deleting one must retract ITS row
    d = tmp_path / "docs"
    d.mkdir()
    (d / "a.txt").write_text("same\n")
    (d / "b.txt").write_text("same\n")

    import threading

    t = pw.io.fs.read(
        str(d), format="plaintext", mode="streaming",
        autocommit_duration_ms=10, refresh_interval=0.05,
    )
    counts = t.reduce(c=pw.reducers.count())
    seen = []
    done = threading.Event()

    def on_change(key, row, time, is_addition):
        if is_addition:
            seen.append(row["c"])
            if row["c"] == 2:
                (d / "a.txt").unlink()
            if row["c"] == 1 and 2 in seen:
                done.set()

    pw.io.subscribe(counts, on_change=on_change)

    def stop_later():
        done.wait(timeout=10)
        t._source  # keep ref

    runner = threading.Thread(target=pw.run, daemon=True)
    runner.start()
    assert done.wait(timeout=10), f"never saw count drop back to 1; saw {seen}"


def test_safe_unpickler_rejects_arbitrary_classes(tmp_path):
    """Journal/subject-state loads must not resolve arbitrary classes
    (ADVICE r1: pickle in the persistence path is an RCE surface)."""
    import pickle

    import pytest

    import pathway_tpu as pw
    from pathway_tpu.persistence import PersistenceManager, _safe_loads

    # plain engine values round-trip
    from pathway_tpu.internals.api import Json, ref_scalar

    payload = (ref_scalar("x"), ("a", 1, 2.5, None, b"b"), Json({"k": 1}))
    assert _safe_loads(pickle.dumps(payload)) == payload

    class Evil:
        def __reduce__(self):
            import os

            return (os.system, ("true",))

    cfg = pw.persistence.Config(
        backend=pw.persistence.Backend.filesystem(str(tmp_path))
    )
    mgr = PersistenceManager(cfg)
    mgr.backend.write("subject_state/c1", pickle.dumps(Evil()))
    with pytest.raises(pickle.UnpicklingError, match="refuses"):
        mgr.load_subject_state("c1")


def test_gradual_broadcast_threshold_retraction():
    """A retraction-only update to the threshold table clears the
    broadcast; retract+insert in one commit lands on the inserted row
    (ADVICE r1: stale triplet stayed active forever)."""
    import pathway_tpu as pw

    class Vals(pw.Schema):
        v: int

    class Thr(pw.Schema):
        lower: int
        value: int
        upper: int

    class ValSub(pw.io.python.ConnectorSubject):
        def run(self):
            for v in (1, 2):
                self.next(v=v)
            self.commit()

    class ThrSub(pw.io.python.ConnectorSubject):
        def run(self):
            self.next(lower=0, value=100, upper=100)
            self.commit()
            import time

            time.sleep(0.3)
            self.remove(lower=0, value=100, upper=100)
            self.commit()

    vals = pw.io.python.read(ValSub(), schema=Vals, autocommit_duration_ms=None)
    thr = pw.io.python.read(ThrSub(), schema=Thr, autocommit_duration_ms=None)
    out = vals._gradual_broadcast(thr, thr.lower, thr.value, thr.upper)
    log = []
    pw.io.subscribe(
        out, on_change=lambda key, row, t, d: log.append((row["apx_value"], d))
    )
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    inserts = [a for a, d in log if d]
    deletes = [a for a, d in log if not d]
    # the threshold retraction retracted every broadcast row, final state
    # is empty
    assert len(inserts) == len(deletes) > 0


def test_sharded_knn_k_beyond_shard_capacity():
    """k larger than one shard's capacity is honored from the merged
    global top-k (ADVICE r1: silent per-shard cap under-returned)."""
    import numpy as np
    import pytest

    import jax
    from pathway_tpu.parallel import ShardedKnnIndex, make_mesh

    if len(jax.devices()) < 4:
        pytest.skip("needs the virtual CPU mesh")
    mesh = make_mesh(4, axes=("dp",), shape=(4,))
    idx = ShardedKnnIndex(8, mesh, metric="cos")
    local_cap = idx.local_cap
    n = local_cap * 2  # spans multiple shards
    rng = np.random.default_rng(0)
    vecs = rng.normal(size=(n, 8)).astype(np.float32)
    idx.add(list(range(n)), vecs)
    k = local_cap + 4
    hits = idx.search(vecs[:1], k=k)
    assert len(hits[0]) == k  # not capped at local_cap


def test_safe_unpickler_blocks_builtins_eval(tmp_path):
    """builtins is name-allowlisted: eval/exec/__import__ must not resolve
    even though list/dict do."""
    import pickle

    import pytest

    from pathway_tpu.persistence import _SafeUnpickler, _safe_loads

    class EvalBomb:
        def __reduce__(self):
            return (eval, ("1+1",))

    with pytest.raises(pickle.UnpicklingError, match="refuses"):
        _safe_loads(pickle.dumps(EvalBomb()))
    # benign builtin containers still pass
    assert _safe_loads(pickle.dumps({"a": [1, (2, 3)], "b": {4, 5}})) == {
        "a": [1, (2, 3)], "b": {4, 5}
    }
