"""Fourth reference-semantics battery: joins with ERROR values, universe
promises, sort/prev-next edge cases, intervals_over behaviors (reference
Tier-1 pattern: python/pathway/tests/ — markdown tables, static run,
captured equality)."""

import pytest

import pathway_tpu as pw
from pathway_tpu.internals.api import ERROR
from utils import T, run_table


def _rows(t):
    return sorted(run_table(t).values(), key=repr)


# -- joins with errors ------------------------------------------------------

def test_join_key_error_row_drops_to_error_log():
    t = T("a | b\n1 | 2\n0 | 3")
    # division by zero poisons the join key for row 2
    left = t.select(k=1 // pw.this.a, b=pw.this.b)
    right = T("k | w\n1 | x")
    joined = left.join(right, left.k == right.k).select(left.b, right.w)
    rows = _rows(joined)
    # the poisoned row does not match anything; the valid one joins
    assert rows == [(2, "x")]


def test_error_propagates_through_select_arithmetic():
    t = T("a\n1\n0")
    r = t.select(v=1 // pw.this.a + 1)
    vals = {v for (v,) in _rows(r)}
    assert 2 in vals and any(v is ERROR for v in vals)


def test_error_in_groupby_key_isolated():
    t = T("a | v\n1 | 10\n0 | 20\n1 | 5")
    g = t.select(k=1 // pw.this.a, v=pw.this.v)
    r = g.groupby(pw.this.k).reduce(k=pw.this.k, s=pw.reducers.sum(pw.this.v))
    rows = {k if k is ERROR else k: s for k, s in _rows(r)}
    assert rows.get(1) == 15  # valid rows unaffected by the poisoned one


def test_if_else_with_error_condition():
    t = T("a\n1\n0")
    r = t.select(v=pw.if_else(pw.this.a > 0, pw.this.a, -1))
    assert sorted(v for (v,) in _rows(r)) == [-1, 1]


def test_fill_error_with_coalesce_keeps_rows():
    t = T("a\n2\n0")
    r = t.select(v=pw.fill_error(1 // pw.this.a, -1))
    assert sorted(v for (v,) in _rows(r)) == [-1, 0]


def test_outer_join_none_fill_on_no_match():
    left = T("k | v\n1 | a\n2 | b")
    right = T("k | w\n2 | x\n3 | y")
    j = left.join_outer(right, left.k == right.k).select(
        lv=left.v, rw=right.w
    )
    assert _rows(j) == sorted(
        [("a", None), ("b", "x"), (None, "y")], key=repr
    )


def test_join_left_duplicate_right_keys_multiplies():
    left = T("k | v\n1 | a")
    right = T("k | w\n1 | x\n1 | y")
    j = left.join_left(right, left.k == right.k).select(left.v, right.w)
    assert _rows(j) == [("a", "x"), ("a", "y")]


# -- universe promises ------------------------------------------------------

def test_promise_subset_enables_restrict():
    big = T("k | v\n1 | a\n2 | b\n3 | c")
    small = big.filter(pw.this.k <= 2)
    r = big.restrict(small)
    assert len(_rows(r)) == 2


def test_promise_are_equal_enables_with_universe_of():
    a = T("k | v\n1 | a\n2 | b")
    b = T("w\nx\ny")
    # same row count but unrelated universes: promise equality first
    pw.universes.promise_are_equal(a, b)
    c = b.with_universe_of(a)
    assert len(_rows(c)) == 2


def test_promise_pairwise_disjoint_registers_with_solver():
    from pathway_tpu.internals.universe import SOLVER

    a = T("v\n1")
    b = T("v\n2")
    c = T("v\n3")
    pw.universes.promise_are_pairwise_disjoint(a, b, c)
    assert SOLVER.query_are_disjoint(a._universe, b._universe)
    assert SOLVER.query_are_disjoint(b._universe, c._universe)
    assert not SOLVER.query_are_disjoint(a._universe, a._universe)


def test_promise_disjoint_on_equal_universes_raises():
    a = T("v\n1")
    with pytest.raises(ValueError, match="equal universes"):
        pw.universes.promise_are_pairwise_disjoint(a, a)


def test_subsets_of_disjoint_universes_are_disjoint():
    from pathway_tpu.internals.universe import SOLVER

    a = T("k | v\n1 | 1\n2 | 2")
    b = T("k | v\n3 | 3")
    pw.universes.promise_are_pairwise_disjoint(a, b)
    sub_a = a.filter(pw.this.k == 1)
    assert SOLVER.query_are_disjoint(sub_a._universe, b._universe)


def test_wrong_disjoint_promise_verified_at_runtime():
    # identical position-minted ids actually collide; the promise is wrong
    a = T("v\n1")
    b = T("v\n2")
    pw.universes.promise_are_pairwise_disjoint(a, b)
    both = pw.Table.concat(a, b)
    with pytest.raises(Exception):
        _rows(both)


# -- sort / prev-next edge cases -------------------------------------------

def test_sort_single_row_has_no_neighbors():
    t = T("v\n5")
    s = t.sort(pw.this.v)
    [(prev, nxt)] = _rows(s)
    assert prev is None and nxt is None


def test_sort_chain_walks_in_order():
    t = T("v\n30\n10\n20")
    s = t.sort(pw.this.v)
    enriched = t.with_columns(prev=s.prev, next=s.next)
    rows = run_table(enriched)
    by_v = {v: (p, n) for v, p, n in rows.values()}
    assert by_v[10][0] is None and by_v[30][1] is None
    # middle element links both ways
    assert by_v[20][0] is not None and by_v[20][1] is not None


def test_sort_with_instance_partitions():
    t = T("g | v\na | 1\na | 2\nb | 3")
    s = t.sort(pw.this.v, instance=pw.this.g)
    enriched = t.with_columns(prev=s.prev, next=s.next)
    by = {(g, v): (p, n) for g, v, p, n in run_table(enriched).values()}
    # b's single row is alone in its instance
    assert by[("b", 3)] == (None, None)
    assert by[("a", 1)][1] is not None and by[("a", 2)][0] is not None


def test_sort_ties_are_deterministic():
    t = T("v\n1\n1\n1")
    s = t.sort(pw.this.v)
    rows = list(run_table(s).values())
    n_first = sum(1 for p, n in rows if p is None)
    n_last = sum(1 for p, n in rows if n is None)
    assert n_first == 1 and n_last == 1  # a single linear chain


def test_diff_over_sorted_column():
    t = T("t | v\n1 | 10\n2 | 15\n3 | 12")
    from pathway_tpu.stdlib.ordered import diff as _diff
    d = _diff(t, t.t, pw.this.v)
    vals = sorted(
        v for row in run_table(d).values() for v in [row[-1]] if v is not None
    )
    assert vals == [-3, 5]


# -- intervals_over behaviors ----------------------------------------------

def test_intervals_over_accepts_common_behavior():
    t = T("t | v\n1 | 10\n3 | 20\n5 | 30")
    r = pw.temporal.windowby(
        t, t.t,
        window=pw.temporal.intervals_over(
            at=t.t, lower_bound=-2, upper_bound=0
        ),
        behavior=pw.temporal.common_behavior(cutoff=100),
    ).reduce(end=pw.this._pw_window_end, s=pw.reducers.sum(pw.this.v))
    assert sorted(run_table(r).values()) == [(1, 10), (3, 30), (5, 50)]


def test_intervals_over_behavior_cutoff_streaming():
    """Late rows beyond the cutoff are ignored; timely rows are not."""
    t = pw.debug.table_from_markdown(
        """
        t  | v  | _time
        1  | 10 | 2
        3  | 20 | 4
        7  | 40 | 6
        1  | 99 | 20
        """
    )
    r = pw.temporal.windowby(
        t, t.t,
        window=pw.temporal.intervals_over(
            at=t.t, lower_bound=-2, upper_bound=0
        ),
        behavior=pw.temporal.common_behavior(cutoff=2),
    ).reduce(end=pw.this._pw_window_end, s=pw.reducers.sum(pw.this.v))
    out = dict(sorted(run_table(r).values()))
    # the t=7 row advanced the watermark to 7, past windows 1 and 3's
    # cutoffs (end + 2), so the late v=99 row was ignored by both
    assert out[3] == 30
    assert out.get(1, 10) == 10
    assert out[7] == 40


def test_intervals_over_rejects_non_common_behavior():
    t = T("t | v\n1 | 1")
    with pytest.raises(NotImplementedError):
        pw.temporal.windowby(
            t, t.t,
            window=pw.temporal.intervals_over(
                at=t.t, lower_bound=-1, upper_bound=0
            ),
            behavior=pw.temporal.exactly_once_behavior(),
        ).reduce(s=pw.reducers.sum(pw.this.v))


# -- misc reference edge cases ---------------------------------------------

def test_groupby_after_filter_retracts_cleanly():
    t = T("k | v\n1 | 5\n1 | 7\n2 | 9")
    f = t.filter(pw.this.v > 5)
    r = f.groupby(pw.this.k).reduce(k=pw.this.k, s=pw.reducers.sum(pw.this.v))
    assert _rows(r) == [(1, 7), (2, 9)]


def test_flatten_empty_sequences_drop_rows():
    t = T("k\n1\n2").select(
        k=pw.this.k,
        xs=pw.if_else(pw.this.k == 1, pw.make_tuple(10, 20), pw.make_tuple()),
    )
    f = t.flatten(pw.this.xs)
    assert sorted(x for _, x in _rows(f)) == [10, 20]


def test_update_cells_only_touches_matching_rows():
    base = T("k | v | w\n1 | a | p\n2 | b | q")
    base = base.with_id(base.pointer_from(base.k))
    upd = T("k | v\n2 | B")
    upd = upd.with_id(upd.pointer_from(upd.k))
    pw.universes.promise_is_subset_of(upd, base)
    r = base.update_cells(upd)
    assert sorted(_rows(r)) == [(1, "a", "p"), (2, "B", "q")]


def test_ix_missing_key_raises_without_optional():
    t = T("k | v\n1 | a")
    t = t.with_id(t.pointer_from(t.k))
    keys = T("k\n1\n9")
    with pytest.raises(Exception):
        _rows(keys.select(v=t.ix(t.pointer_from(keys.k)).v))


def test_ix_optional_fills_none():
    t = T("k | v\n1 | a")
    t = t.with_id(t.pointer_from(t.k))
    keys = T("k\n1\n9")
    r = keys.select(
        v=t.ix(t.pointer_from(keys.k), optional=True).v
    )
    assert sorted(_rows(r), key=repr) == [("a",), (None,)]


def test_groupby_sort_by_orders_tuple_reducer():
    t = T("k | v | o\n1 | a | 3\n1 | b | 1\n1 | c | 2")
    r = t.groupby(pw.this.k, sort_by=pw.this.o).reduce(
        k=pw.this.k, xs=pw.reducers.tuple(pw.this.v)
    )
    assert _rows(r) == [(1, ("b", "c", "a"))]


def test_deduplicate_keeps_latest_accepted():
    t = T("v | _time\n1 | 2\n5 | 4\n3 | 6")
    r = pw.stateful.deduplicate(
        t, value=pw.this.v, acceptor=lambda new, old: new > old
    )
    vals = [row[0] for row in run_table(r).values()]
    assert vals == [5]


def test_mixed_stateful_and_plain_reducers():
    """Stateful reducers compose freely with plain ones in a single
    reduce() (reference: src/engine/reduce.rs:22 — Stateful is just
    another Reducer variant)."""
    t = pw.debug.table_from_markdown(
        """
        g | v
        a | 1
        a | 2
        b | 5
        a | 3
        """
    )
    concat = pw.reducers.stateful_many(
        lambda state, rows: (state or "")
        + "".join(str(a[0]) for a, d in rows if d > 0)
    )
    out = t.groupby(pw.this.g).reduce(
        g=pw.this.g,
        total=pw.reducers.sum(pw.this.v),
        n=pw.reducers.count(),
        seen=concat(pw.this.v),
    )
    rows = sorted(_rows(out))
    assert rows == [("a", 6, 3, "123"), ("b", 5, 1, "5")]


def test_two_stateful_reducers_in_one_reduce():
    t = pw.debug.table_from_markdown(
        """
        g | v
        a | 1
        a | 4
        b | 2
        """
    )
    acc_sum = pw.reducers.stateful_many(
        lambda s, rows: (s or 0) + sum(a[0] * d for a, d in rows)
    )
    acc_max = pw.reducers.stateful_many(
        lambda s, rows: max(
            [a[0] for a, d in rows if d > 0] + ([s] if s is not None else [])
        )
    )
    out = t.groupby(pw.this.g).reduce(
        g=pw.this.g, s=acc_sum(pw.this.v), m=acc_max(pw.this.v)
    )
    assert sorted(_rows(out)) == [("a", 5, 4), ("b", 2, 2)]
