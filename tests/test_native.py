"""Native C++ component tests (BM25 + HNSW via ctypes)."""

import numpy as np
import pytest

from pathway_tpu.native import NativeBm25, NativeHnsw, available

pytestmark = pytest.mark.skipif(
    not available(), reason="no C++ toolchain available"
)


def test_native_bm25_ranking_and_removal():
    bm = NativeBm25()
    bm.add(1, "the quick brown fox")
    bm.add(2, "a lazy dog sleeps")
    bm.add(3, "the dog chases the fox quickly fox")
    res = bm.search("fox", 3)
    assert [k for k, _ in res][:2] == [3, 1] or res[0][0] in (1, 3)
    assert all(s > 0 for _, s in res)
    bm.remove(3)
    res = bm.search("fox", 3)
    assert [k for k, _ in res] == [1]
    # update: re-adding replaces content
    bm.add(1, "completely different words")
    assert bm.search("fox", 3) == []


def test_native_hnsw_recall():
    rng = np.random.default_rng(0)
    dim = 16
    vecs = rng.normal(size=(500, dim)).astype(np.float32)
    h = NativeHnsw(dim, "cos", M=16, ef_build=128, ef_search=96)
    for i, v in enumerate(vecs):
        h.add(i, v)
    # recall@1 against exact cos search
    norm = vecs / np.linalg.norm(vecs, axis=1, keepdims=True)
    hits = 0
    for qi in range(50):
        exact = int(np.argmax(norm @ norm[qi]))
        got = h.search(vecs[qi], 1)
        hits += got[0][0] == exact
    assert hits >= 45  # >=90% recall@1 on easy data


def test_native_hnsw_remove_and_upsert():
    h = NativeHnsw(4, "cos")
    eye = np.eye(4, dtype=np.float32)
    for i in range(4):
        h.add(i, eye[i])
    assert h.search(eye[2], 1)[0][0] == 2
    h.remove(2)
    assert h.search(eye[2], 1)[0][0] != 2
    h.add(2, eye[2])  # resurrect
    assert h.search(eye[2], 1)[0][0] == 2
    assert len(h) == 4


def test_usearch_knn_uses_native(monkeypatch):
    import pathway_tpu as pw
    from pathway_tpu.stdlib.indexing import UsearchKnn
    from pathway_tpu.stdlib.indexing.nearest_neighbors import _HnswAdapter

    docs = pw.debug.table_from_markdown(
        """
        name
        a
        b
        """
    )
    vecs = {"a": (1.0, 0.0), "b": (0.0, 1.0)}
    docs = docs.with_columns(
        emb=pw.apply_with_type(lambda n: vecs[n], tuple, pw.this.name)
    )
    inner = UsearchKnn(data_column=docs.emb, dimensions=2, metric="cos")
    adapter = inner.make_adapter()
    assert isinstance(adapter, _HnswAdapter)

    queries = pw.debug.table_from_markdown(
        """
        q
        1
        """
    ).with_columns(emb=pw.apply_with_type(lambda q: (0.9, 0.1), tuple, pw.this.q))
    res = inner.query(queries.emb, number_of_matches=1)
    from pathway_tpu.internals.graph_runner import GraphRunner

    captures = GraphRunner().run_tables(
        res.select(reply=res["_pw_index_reply"])
    )
    rows = list(captures[0].state.rows.values())
    reply = rows[0][0]
    assert len(reply) == 1
    # matched id resolves to doc 'a'
    docs_capture = GraphRunner().run_tables(docs.select(pw.this.name))


def test_fastpath_consolidate_and_value_bytes():
    from pathway_tpu.native import get_fastpath

    fp = get_fastpath()
    if fp is None:
        pytest.skip("no toolchain")
    out = fp.consolidate(
        [(1, ("a",), 1), (1, ("a",), 2), (2, ("b",), 1), (1, ("a",), -3)]
    )
    assert out == [(2, ("b",), 1)]
    # ndarray rows freeze to the same stand-ins as the python impl
    from pathway_tpu.engine.stream import freeze_row

    row = (np.array([1.0, 2.0]), "x")
    assert fp.freeze_rows([row])[0] == freeze_row(row)
    # byte-identical serialization vs the python reference impl
    from pathway_tpu.internals.api import _concat_lp, _value_to_bytes

    for args in [
        ("a", 1, 2.5, None, True, b"z"),
        ("a\x1eSb",),
        ("a", "b"),
        (("nested", 1), 7),
    ]:
        want = _concat_lp([_value_to_bytes(a) for a in args])
        assert fp.value_bytes(args) == want


def test_binop_differential_fuzz():
    """fast_binop must agree with the Python operator on EVERY value mix
    (review r4 pinned: float // underflow, int/int / correct rounding,
    -0.0 modulo, subclasses, bigints, div-zero)."""
    import operator
    import random
    import warnings

    import numpy as np

    from pathway_tpu.internals.api import ERROR
    from pathway_tpu.native import get_fastpath

    fp = get_fastpath()
    if fp is None or not hasattr(fp, "binop"):
        import pytest

        pytest.skip("no native toolchain")

    ops = [
        (0, operator.add), (1, operator.sub), (2, operator.mul),
        (3, operator.truediv), (4, operator.floordiv), (5, operator.mod),
        (6, operator.lt), (7, operator.le), (8, operator.gt),
        (9, operator.ge), (10, operator.eq), (11, operator.ne),
        (12, operator.and_), (13, operator.or_), (14, operator.xor),
    ]
    rng = random.Random(7)
    pool = [
        0, 1, -1, 2, 7, -7, 100, 2**52, 2**53, 2**53 + 1, 2**62,
        -(2**62), 2**70, -(2**70), 0.0, -0.0, 1.5, -7.5, 1e300,
        -1e-300, 2.0, float("inf"), True, False, None, "x", "y",
        np.float64(2.5), np.int64(3), -4.0,
    ]
    for code, op in ops:
        lv = [rng.choice(pool) for _ in range(400)]
        rv = [rng.choice(pool) for _ in range(400)]
        with np.errstate(all="ignore"), warnings.catch_warnings():
            warnings.simplefilter("ignore")  # numpy scalar overflow in
            # the C path's per-element python fallback (same warns the
            # pure-python loop emits)
            out, errs = fp.binop(list(lv), list(rv), code, ERROR, op)
        for i, (a, b) in enumerate(zip(lv, rv)):
            try:
                with np.errstate(all="ignore"), warnings.catch_warnings():
                    warnings.simplefilter("ignore")  # numpy scalar overflow
                    want = op(a, b)
            except Exception:
                want = ERROR
            got = out[i]
            if got is ERROR or want is ERROR:
                assert got is want, (op, a, b, got, want)
            elif isinstance(want, float) and want != want:  # NaN
                assert got != got, (op, a, b, got, want)
            else:
                assert got == want and type(got) is type(want), (
                    op, a, b, got, want,
                )
                if isinstance(want, float):
                    # bit-exact incl. -0.0 and 1-ulp rounding
                    import struct

                    assert struct.pack("d", got) == struct.pack(
                        "d", want
                    ), (op, a, b, got.hex(), want.hex())
        # error positions line up with ERROR cells from real raises
        for i, _msg in errs:
            assert out[i] is ERROR
