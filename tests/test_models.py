"""Model smoke tests (tiny geometry, CPU backend)."""

import numpy as np

from pathway_tpu.models import (
    CrossEncoder,
    EncoderConfig,
    HashTokenizer,
    SentenceEncoder,
)


def test_hash_tokenizer_deterministic():
    tok = HashTokenizer(vocab_size=1000)
    ids1, mask1 = tok(["hello world", "a much longer sentence with morewordsthanusual"])
    ids2, _ = tok(["hello world", "a much longer sentence with morewordsthanusual"])
    np.testing.assert_array_equal(ids1, ids2)
    assert mask1[0].sum() == 4  # CLS hello world SEP
    assert (ids1 < 1000).all() and (ids1 >= 0).all()


def test_sentence_encoder_shapes_and_norm():
    enc = SentenceEncoder(EncoderConfig.tiny(), batch_size=16)
    out = enc.encode(["short", "a somewhat longer text here", "third"])
    assert out.shape == (3, 64)
    np.testing.assert_allclose(np.linalg.norm(out, axis=-1), 1.0, rtol=1e-4)
    # deterministic across calls and batch-size-independent
    again = enc.encode(["a somewhat longer text here"])
    np.testing.assert_allclose(out[1], again[0], atol=2e-2)


def test_sentence_encoder_empty():
    enc = SentenceEncoder(EncoderConfig.tiny())
    assert enc.encode([]).shape == (0, 64)


def test_cross_encoder_scores():
    ce = CrossEncoder(EncoderConfig.tiny(), batch_size=8)
    scores = ce.score([("query", "relevant doc"), ("query", "other doc text")])
    assert scores.shape == (2,)
    assert np.isfinite(scores).all()
    again = ce.score([("query", "relevant doc")])
    np.testing.assert_allclose(scores[0], again[0], atol=2e-2)
