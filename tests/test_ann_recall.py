"""ANN quality proof (VERDICT r2 #8): recall@10 of the f16-quantized
native HNSW against the exact brute-force oracle (reference bar: usearch
f16, src/external_integration/usearch_integration.rs:20-120)."""

import numpy as np
import pytest


def _hnsw():
    from pathway_tpu.native import NativeHnsw, available

    if not available():
        pytest.skip("no native toolchain")
    return NativeHnsw


def _recall_at_k(index, vectors, queries, k: int) -> float:
    # exact oracle: full cosine scores (vectors pre-normalized)
    sims = queries @ vectors.T
    truth = np.argsort(-sims, axis=1)[:, :k]
    hit = 0
    for qi, q in enumerate(queries):
        got = {key for key, _ in index.search(q, k)}
        hit += len(got & set(truth[qi].tolist()))
    return hit / (len(queries) * k)


def test_hnsw_recall_at_10_cosine():
    NativeHnsw = _hnsw()
    rng = np.random.default_rng(7)
    n, dim = 20_000, 64
    # clustered data — the hard case for naive neighbor selection
    centers = rng.normal(size=(32, dim)).astype(np.float32) * 3.0
    assign = rng.integers(0, 32, size=n)
    vectors = centers[assign] + rng.normal(size=(n, dim)).astype(np.float32)
    vectors /= np.linalg.norm(vectors, axis=1, keepdims=True)

    index = NativeHnsw(dim, "cos", M=16, ef_build=128, ef_search=96)
    for i in range(n):
        index.add(i, vectors[i])
    assert len(index) == n

    queries = vectors[rng.integers(0, n, size=100)] + 0.05 * rng.normal(
        size=(100, dim)
    ).astype(np.float32)
    queries = (queries / np.linalg.norm(queries, axis=1, keepdims=True)).astype(
        np.float32
    )
    recall = _recall_at_k(index, vectors, queries, k=10)
    assert recall >= 0.95, f"recall@10 = {recall:.3f}"


def test_hnsw_f16_quantization_roundtrip():
    """f16 storage must preserve scores to half precision: top-1 self
    queries return the row itself with cosine ~1."""
    NativeHnsw = _hnsw()
    rng = np.random.default_rng(3)
    dim = 32
    vecs = rng.normal(size=(500, dim)).astype(np.float32)
    vecs /= np.linalg.norm(vecs, axis=1, keepdims=True)
    index = NativeHnsw(dim, "cos")
    for i, v in enumerate(vecs):
        index.add(i, v)
    for i in (0, 123, 499):
        [(key, score)] = index.search(vecs[i], 1)
        assert key == i
        assert score == pytest.approx(1.0, abs=2e-3)  # f16 rounding


def test_hnsw_remove_keeps_recall():
    NativeHnsw = _hnsw()
    rng = np.random.default_rng(11)
    dim = 32
    vecs = rng.normal(size=(2000, dim)).astype(np.float32)
    vecs /= np.linalg.norm(vecs, axis=1, keepdims=True)
    index = NativeHnsw(dim, "cos")
    for i, v in enumerate(vecs):
        index.add(i, v)
    for i in range(0, 2000, 2):  # delete every even key
        index.remove(i)
    assert len(index) == 1000
    hits = index.search(vecs[101], 5)
    assert all(k % 2 == 1 for k, _ in hits)
    assert hits[0][0] == 101
