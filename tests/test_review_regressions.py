"""Regression tests for review-confirmed bugs (round 1 code review)."""

import numpy as np

import pathway_tpu as pw
from pathway_tpu.internals.api import _value_to_bytes, ref_scalar


def test_ref_scalar_injective_separators():
    assert ref_scalar("a\x1eSb") != ref_scalar("a", "b")
    assert ref_scalar(("a", "b")) != ref_scalar("a\x1fSb")
    assert ref_scalar("a", "b") != ref_scalar("ab")


def test_value_to_bytes_ndarray_shape():
    a = np.array([1.0, 2.0])
    b = np.array([[1.0], [2.0]])
    assert _value_to_bytes(a) != _value_to_bytes(b)
    assert _value_to_bytes(a) != _value_to_bytes(a.astype(np.float32))


def test_outer_join_unified_key_column():
    t1 = pw.debug.table_from_markdown(
        """
        k | a
        1 | 10
        2 | 20
        """
    )
    t2 = pw.debug.table_from_markdown(
        """
        k | b
        2 | 200
        3 | 300
        """
    )
    res = t1.join(t2, t1.k == t2.k, how="outer").select(pw.this.k)
    captures = pw.internals.graph_runner.GraphRunner().run_tables(res)
    ks = sorted(row[0] for row in captures[0].state.rows.values())
    assert ks == [1, 2, 3]  # right-only row must carry k=3, not None


def test_nondeterministic_udf_retraction_replays_memo():
    """A non-deterministic UDF's output must be retracted with the SAME value
    it originally produced (reference: consistent-deletions semantics)."""
    calls = [0]

    @pw.udf(deterministic=False)
    def tag(v: int) -> int:
        calls[0] += 1
        return calls[0]

    class Subject(pw.io.python.ConnectorSubject):
        def run(self):
            self.next(k=1, v=10)
            self.commit()
            self.remove(k=1, v=10)
            self.commit()

    class S(pw.Schema):
        k: int = pw.column_definition(primary_key=True)
        v: int

    t = pw.io.python.read(Subject(), schema=S)
    tagged = t.select(pw.this.k, tag=tag(pw.this.v))
    events = []
    pw.io.subscribe(
        tagged,
        on_change=lambda key, row, time, is_addition: events.append(
            (row["tag"], is_addition)
        ),
    )
    pw.run()
    # the insert and its retraction must carry the same tag value
    assert len(events) == 2
    assert events[0][0] == events[1][0]
    assert events[0][1] is True and events[1][1] is False
