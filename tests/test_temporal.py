"""Temporal suite tests (reference pattern: python/pathway/tests/temporal/
— static tables + event-time columns, windowby/reduce compared to oracle;
streaming behavior tests use _time-style deterministic replay)."""

import pathway_tpu as pw
from pathway_tpu.internals.graph_runner import GraphRunner


def _rows(table):
    captures = GraphRunner().run_tables(table)
    return sorted(captures[0].state.rows.values())


def test_tumbling_window():
    t = pw.debug.table_from_markdown(
        """
        k | t
        a | 1
        a | 3
        a | 6
        b | 11
        """
    )
    res = t.windowby(
        t.t, window=pw.temporal.tumbling(duration=5), instance=t.k
    ).reduce(
        k=pw.this._pw_instance,
        start=pw.this._pw_window_start,
        end=pw.this._pw_window_end,
        c=pw.reducers.count(),
    )
    assert _rows(res) == [
        ("a", 0, 5, 2),
        ("a", 5, 10, 1),
        ("b", 10, 15, 1),
    ]


def test_sliding_window():
    t = pw.debug.table_from_markdown(
        """
        t
        3
        """
    )
    res = t.windowby(
        t.t, window=pw.temporal.sliding(hop=2, duration=4)
    ).reduce(
        start=pw.this._pw_window_start,
        end=pw.this._pw_window_end,
        c=pw.reducers.count(),
    )
    assert _rows(res) == [(0, 4, 1), (2, 6, 1)]


def test_session_window():
    t = pw.debug.table_from_markdown(
        """
        k | t
        a | 1
        a | 2
        a | 10
        a | 11
        b | 3
        """
    )
    res = t.windowby(
        t.t, window=pw.temporal.session(max_gap=5), instance=t.k
    ).reduce(
        k=pw.this._pw_instance,
        start=pw.this._pw_window_start,
        end=pw.this._pw_window_end,
        c=pw.reducers.count(),
    )
    assert _rows(res) == [
        ("a", 1, 2, 2),
        ("a", 10, 11, 2),
        ("b", 3, 3, 1),
    ]


def test_interval_join_inner():
    t1 = pw.debug.table_from_markdown(
        """
        k | t
        a | 10
        a | 20
        """
    )
    t2 = pw.debug.table_from_markdown(
        """
        k | t | v
        a | 11 | 100
        a | 15 | 200
        a | 25 | 300
        """
    )
    res = pw.temporal.interval_join(
        t1, t2, t1.t, t2.t, pw.temporal.interval(-2, 2), t1.k == t2.k
    ).select(lt=t1.t, rt=t2.t, v=t2.v)
    assert _rows(res) == [(10, 11, 100)]


def test_interval_join_left_padding():
    t1 = pw.debug.table_from_markdown(
        """
        t
        10
        50
        """
    )
    t2 = pw.debug.table_from_markdown(
        """
        t | v
        11 | 100
        """
    )
    res = pw.temporal.interval_join_left(
        t1, t2, t1.t, t2.t, pw.temporal.interval(-2, 2)
    ).select(lt=t1.t, v=t2.v)
    assert _rows(res) == [(10, 100), (50, None)]


def test_asof_join_backward():
    trades = pw.debug.table_from_markdown(
        """
        sym | t | px
        A   | 10 | 1
        A   | 20 | 2
        """
    )
    quotes = pw.debug.table_from_markdown(
        """
        sym | t | bid
        A   | 8  | 95
        A   | 15 | 96
        A   | 30 | 99
        """
    )
    res = pw.temporal.asof_join(
        trades, quotes, trades.t, quotes.t, trades.sym == quotes.sym
    ).select(t=trades.t, px=trades.px, bid=quotes.bid)
    assert _rows(res) == [(10, 1, 95), (20, 2, 96)]


def test_asof_now_join_not_revised():
    """Left rows answered against right state at arrival; later right
    updates must NOT revise past answers."""
    import threading

    gate = threading.Event()

    class Rates(pw.io.python.ConnectorSubject):
        def run(self):
            self.next(cur="usd", rate=100)
            self.commit()
            gate.wait(timeout=5)
            self.next(cur="usd", rate=200)
            self.commit()

    class Queries(pw.io.python.ConnectorSubject):
        def run(self):
            import time

            time.sleep(0.3)
            self.next(qid=1, cur="usd")
            self.commit()
            time.sleep(0.2)
            gate.set()

    class RS(pw.Schema):
        cur: str = pw.column_definition(primary_key=True)
        rate: int

    class QS(pw.Schema):
        qid: int = pw.column_definition(primary_key=True)
        cur: str

    rates = pw.io.python.read(Rates(), schema=RS, autocommit_duration_ms=None)
    queries = pw.io.python.read(Queries(), schema=QS, autocommit_duration_ms=None)
    res = pw.temporal.asof_now_join(
        queries, rates, queries.cur == rates.cur
    ).select(qid=queries.qid, rate=rates.rate)
    events = []
    pw.io.subscribe(
        res,
        on_change=lambda key, row, time, is_addition: events.append(
            (row["rate"], is_addition)
        ),
    )
    pw.run()
    assert events == [(100, True)]  # answered once, never revised


def test_window_join():
    t1 = pw.debug.table_from_markdown(
        """
        t | a
        1 | x
        7 | y
        """
    )
    t2 = pw.debug.table_from_markdown(
        """
        t | b
        2 | p
        8 | q
        """
    )
    res = pw.temporal.window_join(
        t1, t2, t1.t, t2.t, pw.temporal.tumbling(duration=5)
    ).select(a=t1.a, b=t2.b)
    assert _rows(res) == [("x", "p"), ("y", "q")]


def test_exactly_once_behavior_streaming():
    """With exactly_once behavior, each window emits one final result when
    the watermark passes window end (+shift) — no intermediate updates."""

    class Events(pw.io.python.ConnectorSubject):
        def run(self):
            self.next(t=1)
            self.commit()
            self.next(t=2)
            self.commit()
            self.next(t=7)  # advances watermark past window [0, 5)
            self.commit()

    class S(pw.Schema):
        t: int

    events_t = pw.io.python.read(Events(), schema=S, autocommit_duration_ms=None)
    res = events_t.windowby(
        events_t.t,
        window=pw.temporal.tumbling(duration=5),
        behavior=pw.temporal.exactly_once_behavior(),
    ).reduce(
        start=pw.this._pw_window_start,
        c=pw.reducers.count(),
    )
    updates = []
    pw.io.subscribe(
        res,
        on_change=lambda key, row, time, is_addition: updates.append(
            (row["start"], row["c"], is_addition)
        ),
    )
    pw.run()
    # window [0,5) must appear exactly once, with final count 2, after its
    # end passed; no (.., 1, True) intermediate for that window
    w0 = [u for u in updates if u[0] == 0]
    assert w0 == [(0, 2, True)]
