"""Third reference-semantics battery: join/universe/concat edge cases,
temporal behaviors under streaming, debug round-trips."""

import pandas as pd
import pytest

import pathway_tpu as pw
from utils import T, run_table


def _rows(t):
    return sorted(run_table(t).values(), key=repr)


def test_join_id_from_left_preserves_universe():
    left = T("k | v\n1 | a\n2 | b")
    left = left.with_id(left.pointer_from(left.k))
    right = T("k | w\n1 | x\n2 | y")
    joined = left.join(right, left.k == right.k, id=left.id).select(
        left.v, right.w
    )
    assert set(run_table(joined)) == set(run_table(left))


def test_concat_disjoint_and_same_schema():
    a = T("k | v\n1 | 1")
    a = a.with_id(a.pointer_from(a.k))
    b = T("k | v\n2 | 2")
    b = b.with_id(b.pointer_from(b.k))
    both = pw.Table.concat(a, b)
    assert sorted(r[1] for r in _rows(both)) == [1, 2]


def test_concat_colliding_ids_raises():
    # markdown tables mint ids from row position: index-0 rows collide
    a = T("v\n1")
    b = T("v\n2")
    both = pw.Table.concat(a, b)
    with pytest.raises(Exception, match="concat_reindex"):
        _rows(both)


def test_concat_reindex_allows_key_collisions():
    a = T("v\n7")
    b = T("v\n7")  # same content -> same minted keys
    both = pw.Table.concat_reindex(a, b)
    assert sorted(r[0] for r in _rows(both)) == [7, 7]


def test_with_id_from():
    t = T("a | b\n1 | x\n2 | y")
    res = t.with_id_from(t.a)
    from pathway_tpu.internals.api import ref_scalar

    keys = set(run_table(res))
    assert keys == {ref_scalar(1), ref_scalar(2)}


def test_table_from_pandas_roundtrip():
    df = pd.DataFrame({"a": [1, 2], "b": ["x", "y"]})
    t = pw.debug.table_from_pandas(df)
    out = pw.debug.table_to_pandas(t, include_id=False)
    assert sorted(out["a"].tolist()) == [1, 2]
    assert set(out.columns) == {"a", "b"}


def test_compute_and_print_smoke(capsys):
    t = T("a\n1")
    pw.debug.compute_and_print(t, include_id=False)
    out = capsys.readouterr().out
    assert "a" in out and "1" in out


def test_streaming_buffer_delay_behavior():
    """delay buffers window output until watermark passes start+delay."""

    class Events(pw.io.python.ConnectorSubject):
        def run(self):
            self.next(t=1)
            self.commit()
            self.next(t=2)
            self.commit()
            self.next(t=9)
            self.commit()

    class S(pw.Schema):
        t: int

    events = pw.io.python.read(Events(), schema=S, autocommit_duration_ms=None)
    res = events.windowby(
        events.t,
        window=pw.temporal.tumbling(duration=4),
        behavior=pw.temporal.common_behavior(delay=6),
    ).reduce(start=pw.this._pw_window_start, c=pw.reducers.count())
    updates = []
    pw.io.subscribe(
        res,
        on_change=lambda key, row, time, is_addition: updates.append(
            (row["start"], row["c"], is_addition)
        ),
    )
    pw.run()
    # window [0,4): rows released only once watermark >= 0+6 (t=9 arrival);
    # the count arrives as ONE final value, no intermediate c=1
    w0 = [u for u in updates if u[0] == 0]
    assert w0 == [(0, 2, True)]


def test_inactivity_columns_shape():
    # inactivity_detection wires utc_now; just validate the declaration
    # shape without running the infinite stream
    t = T("ts\n100")
    inact, resumed = pw.temporal.inactivity_detection(t.ts, 1000)
    assert inact.column_names() == ["inactive_since"]
    assert resumed.column_names() == ["resumed_at"]
    pw.internals.parse_graph.G.clear()


def test_unpack_col():
    t = T("k\n1").select(tup=pw.make_tuple(7, "x"))
    from pathway_tpu.stdlib.utils import unpack_col

    res = unpack_col(t.tup, "a", "b")
    assert _rows(res) == [(7, "x")]


def test_argmax_rows_filter():
    t = T("g | v\na | 1\na | 5\nb | 3")
    from pathway_tpu.stdlib.utils.filtering import argmax_rows

    res = argmax_rows(t, t.g, what=t.v)
    assert _rows(res.select(pw.this.g, pw.this.v)) == [("a", 5), ("b", 3)]


def test_sql_distinct():
    t = T("a\n1\n1\n2")
    res = pw.sql("SELECT DISTINCT a FROM t", t=t)
    assert _rows(res) == [(1,), (2,)]


def test_coalesce_all_none():
    t = T("k | a | b\n1 | |")
    res = t.select(c=pw.coalesce(t.a, t.b))
    assert _rows(res) == [(None,)]
