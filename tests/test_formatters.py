"""Output formatter suite tests (reference: data_format.rs formatters;
Tier-3 pattern test_dsv.rs / test_bson.rs)."""

from __future__ import annotations

import json
import struct

import pytest

from pathway_tpu.internals.api import Json, ref_scalar
from pathway_tpu.io._formats import (
    BsonFormatter,
    DsvFormatter,
    JsonLinesFormatter,
    NullFormatter,
    PsqlSnapshotFormatter,
    PsqlUpdatesFormatter,
    SingleColumnFormatter,
    bson_document,
)

KEY = ref_scalar("k")


def test_jsonlines_formatter():
    f = JsonLinesFormatter(["a", "b"])
    ctx = f.format(KEY, (1, "x"), 42, 1)
    [line] = ctx.payloads
    assert json.loads(line) == {"a": 1, "b": "x", "time": 42, "diff": 1}
    assert ctx.key == KEY and ctx.diff == 1


def test_dsv_formatter_quoting():
    f = DsvFormatter(["a", "b"])
    assert f.header() == b"a,b,time,diff\n"
    [line] = f.format(KEY, ('has,comma', 'has"quote'), 2, -1).payloads
    assert line == b'"has,comma","has""quote",2,-1\n'
    [line2] = f.format(KEY, (None, 5), 2, 1).payloads
    assert line2 == b",5,2,1\n"


def test_single_column_formatter_bytes_passthrough():
    f = SingleColumnFormatter(1)
    assert f.format(KEY, ("x", b"\x00\x01"), 0, 1).payloads == [b"\x00\x01"]
    assert f.format(KEY, ("x", 7), 0, 1).payloads == [b"7"]


def test_psql_updates_formatter():
    f = PsqlUpdatesFormatter("t", ["a", "b"])
    [stmt] = f.format(KEY, (1, "o'brien"), 6, 1).payloads
    assert stmt == (
        b'INSERT INTO "t" ("a","b","time","diff") '
        b"VALUES (1,'o''brien',6,1);\n"
    )


def test_psql_snapshot_formatter_upsert_and_delete():
    f = PsqlSnapshotFormatter("t", ["a"], ["a", "b"])
    [up] = f.format(KEY, (1, "x"), 6, 1).payloads
    assert up == (
        b'INSERT INTO "t" ("a","b") VALUES (1,\'x\') '
        b'ON CONFLICT ("a") DO UPDATE SET "b"=\'x\';\n'
    )
    [de] = f.format(KEY, (1, "x"), 8, -1).payloads
    assert de == b'DELETE FROM "t" WHERE "a"=1;\n'
    with pytest.raises(ValueError, match="primary key"):
        PsqlSnapshotFormatter("t", ["missing"], ["a"])


def test_bson_document_known_bytes():
    # {"a": 1} per bsonspec.org: 0c000000 10 'a' 00 01000000 00
    assert bson_document({"a": 1}) == bytes.fromhex("0c0000001061000100000000")
    # string element: 4(len)+1(type)+2("s\0")+4(strlen)+3("hi\0")+1 = 15
    assert bson_document({"s": "hi"}) == bytes.fromhex(
        "0f000000" + "02" + "7300" + "03000000" + "686900" + "00"
    )


def test_bson_formatter_roundtrip_structure():
    f = BsonFormatter(["a", "s", "flag", "j"])
    [doc] = f.format(
        KEY, (2**40, "txt", True, Json({"n": [1, 2]})), 4, 1
    ).payloads
    # well-formed: length prefix matches, trailing NUL
    (length,) = struct.unpack("<i", doc[:4])
    assert length == len(doc) and doc[-1] == 0
    # int64 marker for the big int, embedded doc for Json, array for list
    assert b"\x12a\x00" in doc
    assert b"\x03j\x00" in doc
    assert b"\x040\x00" in doc or b"\x04n\x00" in doc
    assert b"\x08flag\x00\x01" in doc


def test_null_formatter():
    assert NullFormatter().format(KEY, (1,), 0, 1).payloads == []


def test_live_view_diff_driven():
    """pw.viz LiveView tracks the update stream, not snapshots
    (VERDICT r1 weak #8: viz was snapshot-grade)."""
    import pathway_tpu as pw
    from pathway_tpu.stdlib.viz import show

    class Subj(pw.io.python.ConnectorSubject):
        def run(self):
            self.next(w="a")
            self.next(w="b")
            self.commit()
            self.remove(w="a")
            self.commit()

    class S(pw.Schema):
        w: str

    t = pw.io.python.read(Subj(), schema=S, autocommit_duration_ms=None)
    view = show(t, live=True)
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    rows = view.snapshot()
    assert [r["w"] for r in rows] == ["b"]  # retraction applied
    assert "<table>" in view.to_html() and "b" in repr(view)


def test_safe_unpickler_blocks_numpy_runstring():
    """numpy is name-allowlisted: testing._private.utils.runstring (an exec
    wrapper) must not resolve, while ndarray pickles still do."""
    import pickle

    import numpy as np
    import pytest

    from pathway_tpu.persistence import _safe_loads

    arr = np.asarray([1.5, 2.5], dtype=np.float32)
    out = _safe_loads(pickle.dumps(arr))
    assert (out == arr).all()
    assert _safe_loads(pickle.dumps(np.float64(3.5))) == 3.5

    class Bomb:
        def __reduce__(self):
            from numpy.testing._private.utils import runstring

            return (runstring, ("x = 1", {}))

    with pytest.raises(pickle.UnpicklingError, match="refuses"):
        _safe_loads(pickle.dumps(Bomb()))


def test_pdf_interleaved_tj_order():
    from pathway_tpu.xpacks.llm.parsers import _builtin_pdf_pages

    content = rb"BT (A) Tj [(B)] TJ (C) Tj ET"
    pdf = b"%PDF-1.4\n1 0 obj << >>\nstream\n" + content + b"\nendstream\n"
    [page] = _builtin_pdf_pages(pdf)
    assert page.replace("\n", "") == "ABC"


def test_sql_literal_nonfinite_floats():
    from pathway_tpu.io._formats import _sql_literal

    assert _sql_literal(float("nan")) == "'NaN'::float8"
    assert _sql_literal(float("inf")) == "'Infinity'::float8"
    assert _sql_literal(float("-inf")) == "'-Infinity'::float8"


def test_live_view_html_escaped():
    from pathway_tpu.stdlib.viz import LiveView

    class T:
        @staticmethod
        def column_names():
            return ["v"]

    view = LiveView.__new__(LiveView)
    view.columns = ["v"]
    view._rows = {1: {"v": "<script>alert(1)</script>"}}
    import threading

    view._lock = threading.Lock()
    html = view.to_html()
    assert "<script>" not in html and "&lt;script&gt;" in html


def test_live_view_sse_streaming_push(tmp_path):
    """serve_live_view pushes a Server-Sent-Events frame per table diff —
    true streaming, no client polling (reference analog:
    stdlib/viz/table_viz.py:165 Bokeh/Panel streams)."""
    import http.client
    import json as _json
    import threading
    import time

    import pathway_tpu as pw
    from pathway_tpu.stdlib.viz import LiveView, serve_live_view

    pw.internals.parse_graph.G.clear()

    gate = threading.Event()

    class Src(pw.io.python.ConnectorSubject):
        _deletions_enabled = False

        def run(self):
            self.next(v=1)
            self.commit()
            gate.wait(timeout=10)
            self.next(v=2)
            self.commit()

    class S(pw.Schema):
        v: int

    t = pw.io.python.read(Src(), schema=S, autocommit_duration_ms=None)
    view = LiveView(t)
    host, port = serve_live_view(view)

    frames = []
    ready = threading.Event()

    def client():
        conn = http.client.HTTPConnection(host, port, timeout=15)
        conn.request("GET", "/stream")
        resp = conn.getresponse()
        buf = b""
        while len(frames) < 3:
            chunk = resp.read1(65536)
            if not chunk:
                break
            buf += chunk
            while b"\n\n" in buf:
                frame, buf = buf.split(b"\n\n", 1)
                if frame.startswith(b"data: "):
                    frames.append(_json.loads(frame[6:].decode()))
                    ready.set()
        conn.close()

    ct = threading.Thread(target=client, daemon=True)
    ct.start()
    assert ready.wait(timeout=10)  # initial frame delivered pre-run

    runner = threading.Thread(
        target=lambda: pw.run(monitoring_level=pw.MonitoringLevel.NONE),
        daemon=True,
    )
    runner.start()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and len(frames) < 2:
        time.sleep(0.05)
    assert len(frames) >= 2, frames  # pushed on the first diff
    gate.set()  # second row flows -> another push
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and len(frames) < 3:
        time.sleep(0.05)
    assert len(frames) >= 3, frames
    assert "<table>" in frames[-1]["html"]
