"""Multi-process relational plane: end-to-end over real processes.

Spawns PATHWAY_PROCESSES ranks (subprocesses) running the same program:
fs sources shard files across ranks (stable path hash), ExchangeNodes
hash-route rows at groupby/join boundaries over the TCP mesh, the rank-0
clock master assigns global timestamps, and outputs gather to rank 0.
The merged result must equal the single-process run.

Reference: N timely workers + exchange pacts + per-worker partitioned
reads (src/engine/dataflow.rs:5506-5650, connectors/data_storage.rs:692).
"""

import json
import os
import socket
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port_base(n: int = 4) -> int:
    """Find a base with n consecutive free ports (all bound, then
    released) so rank listeners don't collide with in-use ports."""
    for _ in range(50):
        probe = socket.socket()
        probe.bind(("127.0.0.1", 0))
        base = probe.getsockname()[1]
        probe.close()
        socks = []
        try:
            for i in range(n):
                s = socket.socket()
                s.bind(("127.0.0.1", base + i))
                socks.append(s)
            return base
        except OSError:
            continue
        finally:
            for s in socks:
                s.close()
    raise RuntimeError("no consecutive free port range found")


def _spawn(program: str, workdir: str, processes: int, timeout: int = 120):
    port = _free_port_base()
    procs = []
    for rank in range(processes):
        env = dict(os.environ)
        env.update(
            PATHWAY_PROCESSES=str(processes),
            PATHWAY_PROCESS_ID=str(rank),
            PATHWAY_FIRST_PORT=str(port),
            JAX_PLATFORMS="cpu",
            PYTHONPATH=REPO,
        )
        procs.append(
            subprocess.Popen(
                [sys.executable, program],
                env=env,
                cwd=workdir,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
            )
        )
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append((p.returncode, out.decode(), err.decode()))
    for rc, out, err in outs:
        assert rc == 0, f"rank failed rc={rc}\nstdout:{out}\nstderr:{err}"
    return outs


def _run_single(program: str, workdir: str):
    env = dict(os.environ)
    env.update(
        PATHWAY_PROCESSES="1", JAX_PLATFORMS="cpu", PYTHONPATH=REPO
    )
    r = subprocess.run(
        [sys.executable, program],
        env=env,
        cwd=workdir,
        capture_output=True,
        timeout=120,
    )
    assert r.returncode == 0, r.stderr.decode()


WORDCOUNT = """
import pathway_tpu as pw

class S(pw.Schema):
    word: str

t = pw.io.jsonlines.read("in", schema=S, mode="static")
counts = t.groupby(pw.this.word).reduce(
    word=pw.this.word, c=pw.reducers.count()
)
pw.io.jsonlines.write(counts, "out_{suffix}.jsonl")
pw.run(monitoring_level=pw.MonitoringLevel.NONE)
"""

JOIN_PIPELINE = """
import pathway_tpu as pw

class L(pw.Schema):
    k: int
    j: int
    v: int

class R(pw.Schema):
    k: int
    j: int
    w: str

lt = pw.io.jsonlines.read("inl", schema=L, mode="static")
rt = pw.io.jsonlines.read("inr", schema=R, mode="static")
out = lt.join(rt, pw.left.j == pw.right.j).select(
    v=pw.left.v, w=pw.right.w
)
agg = out.groupby(pw.this.w).reduce(
    w=pw.this.w, s=pw.reducers.sum(pw.this.v), c=pw.reducers.count()
)
pw.io.jsonlines.write(agg, "out_{suffix}.jsonl")
pw.run(monitoring_level=pw.MonitoringLevel.NONE)
"""


def _read_rows(path, drop=("time", "diff", "id")):
    rows = []
    if not os.path.exists(path):
        return rows
    with open(path) as f:
        for line in f:
            if line.strip():
                d = json.loads(line)
                for key in drop:
                    d.pop(key, None)
                rows.append(tuple(sorted(d.items())))
    return sorted(rows)


@pytest.mark.parametrize("processes", [2, 3])
def test_multiprocess_wordcount(tmp_path, processes):
    os.makedirs(tmp_path / "in")
    words = ["alpha", "beta", "gamma", "delta", "epsilon"]
    n = 0
    for f in range(6):  # several files so path-sharding spreads ranks
        with open(tmp_path / "in" / f"part{f}.jsonl", "w") as fh:
            for i in range(40):
                fh.write(json.dumps({"word": words[(i * 7 + f) % len(words)]}) + "\n")
                n += 1

    prog = tmp_path / "prog_multi.py"
    prog.write_text(WORDCOUNT.format(suffix="multi"))
    _spawn(str(prog), str(tmp_path), processes)

    prog1 = tmp_path / "prog_single.py"
    prog1.write_text(WORDCOUNT.format(suffix="single"))
    _run_single(str(prog1), str(tmp_path))

    multi = _read_rows(tmp_path / "out_multi.jsonl")
    single = _read_rows(tmp_path / "out_single.jsonl")
    assert multi == single and multi, (multi, single)


ITERATE_GRAPHS = """
import pathway_tpu as pw

class E(pw.Schema):
    un: str
    vn: str
    dist: float

edge_names = pw.io.jsonlines.read("in_edges", schema=E, mode="static")
verts = edge_names.select(name=pw.this.un).concat_reindex(
    edge_names.select(name=pw.this.vn)
).groupby(pw.this.name).reduce(name=pw.this.name)
verts = verts.with_id(verts.pointer_from(verts.name)).with_columns(
    is_source=pw.this.name == "a"
)
edges = edge_names.select(
    u=verts.pointer_from(edge_names.un),
    v=verts.pointer_from(edge_names.vn),
    dist=edge_names.dist,
)
# bellman_ford + pagerank both run on pw.iterate fixpoints; under
# PATHWAY_PROCESSES>1 the iterate inputs gather to rank 0 and the
# converged output re-shards through the downstream exchanges
bf = pw.graphs.bellman_ford(verts, edges)
vnames = verts.select(pw.this.name)
res = vnames.join(
    bf, vnames.id == bf.v
).select(name=pw.left.name, d=pw.right.dist_from_source)
pw.io.jsonlines.write(res, "out_bf_{suffix}.jsonl")

pr = pw.graphs.pagerank(edges.select(u=pw.this.u, v=pw.this.v), steps=4)
ranked = pr.groupby().reduce(total=pw.reducers.sum(pw.this.rank))
pw.io.jsonlines.write(ranked, "out_pr_{suffix}.jsonl")
pw.run(monitoring_level=pw.MonitoringLevel.NONE)
"""


def test_multiprocess_iterate_graph_algorithms(tmp_path):
    """pw.iterate under PATHWAY_PROCESSES=2 (VERDICT r2 #5): bellman_ford
    and pagerank fixpoints must produce the single-process result when the
    edge files are sharded across two ranks."""
    os.makedirs(tmp_path / "in_edges")
    edges = [
        ("a", "b", 2.0), ("b", "c", 3.0), ("a", "c", 10.0),
        ("c", "d", 1.0), ("b", "d", 7.0), ("d", "e", 2.0),
    ]
    for f in range(3):  # several files so path-sharding spreads ranks
        with open(tmp_path / "in_edges" / f"e{f}.jsonl", "w") as fh:
            for i, (u, v, d) in enumerate(edges):
                if i % 3 == f:
                    fh.write(
                        json.dumps({"un": u, "vn": v, "dist": d}) + "\n"
                    )

    prog = tmp_path / "prog_multi.py"
    prog.write_text(ITERATE_GRAPHS.format(suffix="multi"))
    _spawn(str(prog), str(tmp_path), 2)

    prog1 = tmp_path / "prog_single.py"
    prog1.write_text(ITERATE_GRAPHS.format(suffix="single"))
    _run_single(str(prog1), str(tmp_path))

    bf_multi = _read_rows(tmp_path / "out_bf_multi.jsonl")
    bf_single = _read_rows(tmp_path / "out_bf_single.jsonl")
    assert bf_multi == bf_single and bf_multi, (bf_multi, bf_single)
    # shortest paths from 'a': a=0, b=2, c=5, d=6, e=8
    dists = sorted(dict(r)["d"] for r in bf_multi)
    assert dists == [0.0, 2.0, 5.0, 6.0, 8.0]
    pr_multi = _read_rows(tmp_path / "out_pr_multi.jsonl")
    pr_single = _read_rows(tmp_path / "out_pr_single.jsonl")
    assert pr_multi == pr_single and pr_multi


def test_multiprocess_join_groupby(tmp_path):
    os.makedirs(tmp_path / "inl")
    os.makedirs(tmp_path / "inr")
    for f in range(4):
        with open(tmp_path / "inl" / f"l{f}.jsonl", "w") as fh:
            for i in range(30):
                k = f * 1000 + i
                fh.write(
                    json.dumps({"k": k, "j": k % 7, "v": k % 13}) + "\n"
                )
    with open(tmp_path / "inr" / "r0.jsonl", "w") as fh:
        for j in range(7):
            fh.write(json.dumps({"k": j, "j": j, "w": f"g{j % 3}"}) + "\n")

    prog = tmp_path / "prog_multi.py"
    prog.write_text(JOIN_PIPELINE.format(suffix="multi"))
    _spawn(str(prog), str(tmp_path), 3)

    prog1 = tmp_path / "prog_single.py"
    prog1.write_text(JOIN_PIPELINE.format(suffix="single"))
    _run_single(str(prog1), str(tmp_path))

    multi = _read_rows(tmp_path / "out_multi.jsonl")
    single = _read_rows(tmp_path / "out_single.jsonl")
    assert multi == single and multi, (multi, single)


STREAMING_PIPELINE = """
import time
import pathway_tpu as pw
from pathway_tpu.internals.config import get_pathway_config

class S(pw.Schema):
    k: int = pw.column_definition(primary_key=True)
    g: int
    v: int

class RankSubject(pw.io.python.ConnectorSubject):
    # partition-aware: every rank emits ITS slice of the key space with
    # live commits, including cross-commit retractions
    _distributed_partitioned = True

    def run(self):
        c = get_pathway_config()
        base = c.process_id * 1000
        for i in range(8):
            self.next(k=base + i, g=i % 3, v=10 * c.process_id + i)
            self.commit()
            time.sleep(0.02)
        # retract half of what this rank emitted, in later rounds
        for i in range(0, 8, 2):
            self.remove(k=base + i, g=i % 3, v=10 * c.process_id + i)
            self.commit()
            time.sleep(0.02)

t = pw.io.python.read(RankSubject(), schema=S, autocommit_duration_ms=None)
agg = t.groupby(pw.this.g).reduce(
    g=pw.this.g, c=pw.reducers.count(), s=pw.reducers.sum(pw.this.v),
    mn=pw.reducers.min(pw.this.v),
)
pw.io.jsonlines.write(agg, "out_{suffix}.jsonl")
pw.run(monitoring_level=pw.MonitoringLevel.NONE)
"""


def _net_rows(path):
    """Fold the written update stream into its final net state."""
    net = {}
    if not os.path.exists(path):
        return []
    with open(path) as f:
        for line in f:
            if not line.strip():
                continue
            d = json.loads(line)
            diff = d.pop("diff")
            d.pop("time")
            key = tuple(sorted(d.items()))
            net[key] = net.get(key, 0) + diff
    return sorted(k for k, c in net.items() if c > 0)


def test_multiprocess_live_streaming_with_retractions(tmp_path):
    """Live commits arrive across BSP rounds on every rank (not just a
    static scan), with retractions spanning rounds — the lockstep
    exchange must keep groupby state exact."""
    prog = tmp_path / "prog_stream.py"
    prog.write_text(STREAMING_PIPELINE.format(suffix="multi"))
    _spawn(str(prog), str(tmp_path), 3, timeout=180)

    # the oracle is the deterministic FINAL state, computed directly:
    # ranks r in {0,1,2}, i in {1,3,5,7} survive
    expected = {}
    for r in range(3):
        for i in range(1, 8, 2):
            g = i % 3
            c, s, mn = expected.get(g, (0, 0, None))
            v = 10 * r + i
            expected[g] = (c + 1, s + v, v if mn is None else min(mn, v))
    exp_rows = sorted(
        (("c", c), ("g", g), ("mn", mn), ("s", s))
        for g, (c, s, mn) in expected.items()
    )
    got = _net_rows(tmp_path / "out_multi.jsonl")
    assert got == exp_rows, (got, exp_rows)


def test_cli_spawn_multiprocess(tmp_path):
    """`pathway spawn -n 2` launches the rank fleet (reference: cli.py
    spawn --processes)."""
    os.makedirs(tmp_path / "in")
    with open(tmp_path / "in" / "a.jsonl", "w") as fh:
        for i in range(20):
            fh.write(json.dumps({"word": f"w{i % 3}"}) + "\n")
    prog = tmp_path / "prog_cli.py"
    prog.write_text(WORDCOUNT.format(suffix="cli"))
    port = _free_port_base()
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    r = subprocess.run(
        [
            sys.executable,
            "-m",
            "pathway_tpu.cli",
            "spawn",
            "-n",
            "2",
            "--first-port",
            str(port),
            str(prog),
        ],
        env=env,
        cwd=str(tmp_path),
        capture_output=True,
        timeout=120,
    )
    assert r.returncode == 0, r.stderr.decode()
    rows = _read_rows(tmp_path / "out_cli.jsonl")
    assert rows, "no output rows from CLI spawn"
