"""Test helpers mirroring the reference Tier-1 pattern (reference:
python/pathway/tests/utils.py — T :531, assert_table_equality)."""

from __future__ import annotations

from typing import Any

import pathway_tpu as pw
from pathway_tpu.debug import table_from_markdown
from pathway_tpu.internals.graph_runner import GraphRunner

T = table_from_markdown


def _normalize(v: Any) -> Any:
    import numpy as np

    if isinstance(v, np.ndarray):
        return ("ndarray", v.shape, tuple(np.asarray(v).ravel().tolist()))
    if isinstance(v, float) and v == int(v):
        return v
    if isinstance(v, tuple):
        return tuple(_normalize(x) for x in v)
    return v


def _run_two(t1: pw.Table, t2: pw.Table):
    caps = GraphRunner().run_tables(t1, t2)
    return caps[0], caps[1]


def assert_table_equality(t1: pw.Table, t2: pw.Table) -> None:
    c1, c2 = _run_two(t1, t2)
    cols1 = t1.column_names()
    cols2 = t2.column_names()
    assert sorted(cols1) == sorted(cols2), f"columns differ: {cols1} vs {cols2}"
    order2 = [cols2.index(c) for c in cols1]
    rows1 = {k: tuple(_normalize(v) for v in row) for k, row in c1.state.rows.items()}
    rows2 = {
        k: tuple(_normalize(row[i]) for i in order2)
        for k, row in c2.state.rows.items()
    }
    assert rows1 == rows2, f"tables differ:\n{rows1}\nvs\n{rows2}"


def assert_table_equality_wo_index(t1: pw.Table, t2: pw.Table) -> None:
    c1, c2 = _run_two(t1, t2)
    cols1 = t1.column_names()
    cols2 = t2.column_names()
    assert sorted(cols1) == sorted(cols2), f"columns differ: {cols1} vs {cols2}"
    order2 = [cols2.index(c) for c in cols1]
    rows1 = sorted(
        (tuple(_normalize(v) for v in row) for row in c1.state.rows.values()),
        key=repr,
    )
    rows2 = sorted(
        (
            tuple(_normalize(row[i]) for i in order2)
            for row in c2.state.rows.values()
        ),
        key=repr,
    )
    assert rows1 == rows2, f"tables differ:\n{rows1}\nvs\n{rows2}"


# reference aliases
assert_table_equality_wo_types = assert_table_equality
assert_table_equality_wo_index_types = assert_table_equality_wo_index


def run_table(t: pw.Table) -> dict:
    [cap] = GraphRunner().run_tables(t)
    return dict(cap.state.rows)


def run_update_stream(t: pw.Table) -> list:
    [cap] = GraphRunner().run_tables(t)
    return list(cap.updates)


def wait_result_with_checker(
    checker,
    timeout: float = 30,
    *,
    target=None,
    step: float = 0.1,
):
    """Streaming-test fixture (reference: tests/utils.py:599 — run the
    pipeline on a thread and poll `checker()` until it holds or timeout).
    `target` defaults to pw.run."""
    import threading
    import time

    error: list = []

    def guarded():
        try:
            (target or pw.run)()
        except Exception as exc:  # surfaced in the final assertion
            error.append(exc)

    runner = threading.Thread(target=guarded, daemon=True)
    runner.start()
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if error:
            raise AssertionError(f"pipeline failed: {error[0]!r}") from error[0]
        try:
            if checker():
                return True
        except Exception:
            pass
        time.sleep(step)
    detail = f"; pipeline error: {error[0]!r}" if error else ""
    raise AssertionError(
        f"checker {checker!r} did not pass in {timeout}s{detail}"
    )


class FileLinesNumberChecker:
    """reference: tests/utils.py FileLinesNumberChecker."""

    def __init__(self, path, n_lines: int):
        self.path = path
        self.n_lines = n_lines

    def __call__(self) -> bool:
        try:
            with open(self.path) as f:
                return sum(1 for _ in f) >= self.n_lines
        except FileNotFoundError:
            return False


class CsvLinesNumberChecker(FileLinesNumberChecker):
    """reference: tests/utils.py CsvLinesNumberChecker (header excluded)."""

    def __call__(self) -> bool:
        try:
            with open(self.path) as f:
                return sum(1 for _ in f) - 1 >= self.n_lines
        except FileNotFoundError:
            return False
