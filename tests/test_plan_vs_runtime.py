"""Analyzer-vs-runtime agreement battery: for the bench pipelines
(wordcount, stream_join, groupby; 1-, 2- and 4-rank), ``pw.analyze``
fused/degraded verdicts must match the observed runtime fallback
counters — zero false "fused" verdicts (ISSUE 5 acceptance criterion) —
and at N>1 the Plan Doctor's mesh-verifier verdict must agree with the
real mesh's rollback/restart counters (ISSUE 7 acceptance criterion).

The 1-rank cases lower once, analyze the SAME runtime statically, run
it, then audit counters. The 2- and 4-rank cases fork a real loopback
mesh and each rank audits itself.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile

import pytest

import pathway_tpu as pw
from pathway_tpu.analysis import analyzer as pa
from pathway_tpu.analysis import bench as pb

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _nb_toolchain() -> bool:
    try:
        from pathway_tpu.native import get_pwexec

        ex = get_pwexec()
    except Exception:
        return False
    return ex is not None and hasattr(ex, "parse_upserts_nb")


needs_nb = pytest.mark.skipif(
    not _nb_toolchain(), reason="native toolchain (pwexec) unavailable"
)


def _lower_analyze_run(out_table):
    """Lower the captured graph once, analyze that runtime statically,
    then run it; returns (runtime, report, capture)."""
    from pathway_tpu.engine.runtime import Runtime
    from pathway_tpu.internals.graph_runner import GraphRunner

    g = pw.internals.parse_graph.G
    targets = [out_table._source] + g.output_operators()
    ops = g.reachable_operators(targets)
    runtime = Runtime()
    ctx = GraphRunner()._lower(ops, runtime)
    report = pa.analyze_scope(runtime)
    cap = runtime.scope.capture(ctx.engine_table(out_table))
    runtime.run()
    return runtime, report, cap


def _counters(runtime):
    from pathway_tpu.engine import nodes as N

    joins = [n for n in runtime.scope.nodes if isinstance(n, N.JoinNode)]
    groupbys = [
        n for n in runtime.scope.nodes if isinstance(n, N.GroupByNode)
    ]
    return joins, groupbys


@needs_nb
@pytest.mark.parametrize(
    "build", [pb.build_wordcount, pb.build_stream_join, pb.build_groupby],
    ids=["wordcount", "stream_join", "groupby"],
)
def test_fused_verdict_matches_zero_fallbacks_1rank(build):
    bp = build()
    runtime, report, cap = _lower_analyze_run(bp.out)
    assert report.verdict == "fused", report.render()
    # zero false fused: no fallback counter moved anywhere
    assert pa.audit_runtime(runtime, report) == []
    assert runtime.stats.nb_fallbacks == 0
    assert runtime.stats.exchange_fallbacks == 0
    # and the fused path actually ran (the verdict is not vacuous)
    joins, groupbys = _counters(runtime)
    for n in joins + groupbys:
        assert n._nb_batches > 0, f"{type(n).__name__} never ran columnar"
    assert len(cap.state.rows) > 0


@needs_nb
def test_degraded_verdict_matches_fallback_counters_1rank():
    """A groupby over an expression key: the analyzer must call it
    degraded AND the runtime must count the de-optimized batches."""
    pw.internals.parse_graph.G.clear()
    words = ["a", "b", "c"]
    rows = [{"data": words[i % 3]} for i in range(120)]

    class Src(pw.io.python.ConnectorSubject):
        _deletions_enabled = False

        def run(self):
            for s in range(0, len(rows), 40):
                self.next_batch(rows[s : s + 40])
                self.commit()

    class S(pw.Schema):
        data: str

    t = pw.io.python.read(Src(), schema=S, autocommit_duration_ms=None)
    agg = t.groupby(pw.this.data + "!").reduce(c=pw.reducers.count())
    runtime, report, cap = _lower_analyze_run(agg)
    assert report.verdict == "degraded"
    [entry] = [n for n in report.nodes if n["kind"] == "groupby"]
    assert entry["verdict"] == "degraded"
    _joins, [gb] = _counters(runtime)
    assert gb._nb_batches == 0
    assert gb._nb_fallbacks > 0  # columnar input materialized per batch
    assert runtime.stats.nb_fallbacks == gb._nb_fallbacks
    assert pa.audit_runtime(runtime, report) == []  # no FUSED node lied


@needs_nb
def test_outer_join_pad_output_not_false_fused(monkeypatch):
    """A fused-eligible LEFT join keeps its input processing columnar,
    but pad transitions (a late right row flipping liveness) emit tuple
    batches. The analyzer must NOT call the chain downstream of the join
    fused, the runtime must NOT count those batches as fallbacks, and a
    strict run must complete — no NBStrictError on a correct pipeline."""
    monkeypatch.setenv("PATHWAY_NB_STRICT", "1")
    pw.internals.parse_graph.G.clear()

    class L(pw.Schema):
        a: int
        v: int

    class R(pw.Schema):
        b: int
        w: int

    class LS(pw.io.python.ConnectorSubject):
        _deletions_enabled = False

        def run(self):
            self.next_batch([{"a": i % 5, "v": i} for i in range(40)])
            self.commit()

    class RS(pw.io.python.ConnectorSubject):
        _deletions_enabled = False

        def run(self):
            self.commit()
            # late right row: retracts the pads minted for a==2 rows
            self.next_batch([{"b": 2, "w": 20}])
            self.commit()

    lt = pw.io.python.read(LS(), schema=L, autocommit_duration_ms=None)
    rt = pw.io.python.read(RS(), schema=R, autocommit_duration_ms=None)
    out = lt.join_left(rt, lt.a == rt.b).select(
        v=pw.left.v, w=pw.right.w
    )
    runtime, report, cap = _lower_analyze_run(out)
    assert report.verdict == "degraded", report.render()
    [entry] = [n for n in report.nodes if n["kind"] == "join"]
    assert entry["verdict"] == "degraded"
    [join], _ = _counters(runtime)
    assert join.nb_decision.ok          # the join ITSELF is fused-eligible
    assert join._nb_batches > 0         # and consumed columnar input
    assert join._nb_fallbacks == 0
    assert runtime.stats.exchange_fallbacks == 0
    assert pa.audit_runtime(runtime, report) == []
    assert len(cap.state.rows) == 40    # 32 padded + 8 matched


@needs_nb
def test_forced_tuple_env_matches_degraded_verdict(monkeypatch):
    monkeypatch.setenv("PATHWAY_NO_NB_JOIN", "1")
    bp = pb.build_stream_join()
    runtime, report, cap = _lower_analyze_run(bp.out)
    assert report.verdict == "degraded"
    [entry] = [n for n in report.nodes if n["kind"] == "join"]
    assert any("PATHWAY_NO_NB_JOIN" in r for r in entry["reasons"])
    joins, _ = _counters(runtime)
    assert joins[0]._nb_batches == 0
    assert joins[0]._nb_fallbacks > 0
    assert pa.audit_runtime(runtime, report) == []


# -- egress verdicts vs runtime counters (ISSUE 14 satellite) -------------


def _egress_pipeline(consumer: str):
    """stream_join variant whose OUTPUT chain is statically columnar,
    terminated by the requested consumer kind."""
    pw.internals.parse_graph.G.clear()

    class L(pw.Schema):
        k: int = pw.column_definition(primary_key=True)
        j: int
        v: int

    class R(pw.Schema):
        k: int = pw.column_definition(primary_key=True)
        j: int
        w: int

    lrows = [{"k": i, "j": i % 9, "v": i} for i in range(180)]
    rrows = [{"k": i, "j": i % 9, "w": i} for i in range(18)]

    class LS(pw.io.python.ConnectorSubject):
        _deletions_enabled = False

        def run(self):
            for s in range(0, len(lrows), 60):
                self.next_batch(lrows[s : s + 60])
                self.commit()

    class RS(pw.io.python.ConnectorSubject):
        _deletions_enabled = False

        def run(self):
            self.next_batch(rrows)
            self.commit()

    lt = pw.io.python.read(LS(), schema=L, autocommit_duration_ms=None)
    rt = pw.io.python.read(RS(), schema=R, autocommit_duration_ms=None)
    out = lt.join(rt, pw.left.j == pw.right.j).select(
        v=pw.left.v, w=pw.right.w
    )
    if consumer == "arrow":
        pw.io.subscribe(
            out, on_batch=lambda *a: None, batch_format="arrow"
        )
    elif consumer == "rows_batch":
        pw.io.subscribe(out, on_batch=lambda *a: None)
    else:
        pw.io.subscribe(out, on_change=lambda *a: None)
    return out


@needs_nb
@pytest.mark.parametrize(
    "consumer,expect",
    [
        ("arrow", "fused"),
        ("rows_batch", "row-expanding"),
        ("on_change", "row-expanding"),
    ],
)
def test_egress_verdict_matches_runtime_counters(consumer, expect):
    """The Plan Doctor's egress verdict must be corroborated by the
    runtime's capture counters (the plan-vs-reality contract extended
    to sinks): fused egress ⇔ arrow batches delivered + zero rows
    expanded at the sink; row-expanding egress ⇔ the expansion counter
    moves and ``sink.row-expanding`` names the consumer."""
    pytest.importorskip("pyarrow")
    out = _egress_pipeline(consumer)
    runtime, report, cap = _lower_analyze_run(out)
    sink_diags = [
        d for d in report.diagnostics if d.code == "sink.row-expanding"
    ]
    # the scratch capture node added by the harness is itself an
    # arrow-capable egress; only the subscriber's OutputNode may fire
    if expect == "fused":
        assert not sink_diags, [d.message for d in sink_diags]
        assert runtime.stats.capture_arrow_batches > 0
        assert runtime.stats.capture_rows_expanded == 0
    else:
        assert len(sink_diags) == 1, [d.message for d in sink_diags]
        assert "arrow" in (sink_diags[0].hint or "")
        assert runtime.stats.capture_rows_expanded > 0
        assert runtime.stats.capture_arrow_batches == 0


@needs_nb
def test_egress_verdict_degraded_chain_not_blamed_on_sink():
    """A tuple chain (groupby output) feeding a rows consumer: the sink
    is NOT the de-optimization — no columnar batches exist to expand,
    so the capture counters stay flat and the sink.row-expanding
    message (per-row on_change hint) carries the upstream context."""
    pytest.importorskip("pyarrow")
    bp = pb.build_wordcount()
    runtime, report, cap = _lower_analyze_run(bp.out)
    assert runtime.stats.capture_rows_expanded == 0
    assert runtime.stats.capture_arrow_batches == 0
    sink_diags = [
        d for d in report.diagnostics if d.code == "sink.row-expanding"
    ]
    assert len(sink_diags) == 1
    assert "not columnar" in sink_diags[0].message


@needs_nb
def test_egress_forced_off_flips_fused_to_row_expanding(monkeypatch):
    pytest.importorskip("pyarrow")
    monkeypatch.setenv("PATHWAY_NO_NB_CAPTURE", "1")
    out = _egress_pipeline("arrow")
    runtime, report, cap = _lower_analyze_run(out)
    sink_diags = [
        d for d in report.diagnostics if d.code == "sink.row-expanding"
    ]
    assert sink_diags and any(
        "NO_NB_CAPTURE" in d.message for d in sink_diags
    )
    assert runtime.stats.capture_arrow_batches == 0
    assert runtime.stats.capture_rows_expanded > 0


# -- 2-rank real-fork agreement ------------------------------------------

_RANK_PROGRAM = """
import json, os, sys
sys.path.insert(0, {repo!r})
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import pathway_tpu as pw
import pathway_tpu.engine.runtime as rt_mod
from pathway_tpu.analysis import analyzer as pa
from pathway_tpu.engine import nodes as N

_insts = []
_orig = rt_mod.Runtime.__init__
def _spy(self, *a, **k):
    _orig(self, *a, **k)
    _insts.append(self)
rt_mod.Runtime.__init__ = _spy

rank = int(os.environ.get("PATHWAY_PROCESS_ID", "0"))
P = int(os.environ.get("PATHWAY_PROCESSES", "1"))
words = [f"w{{i}}" for i in range(5)]
rows = [
    {{"data": words[i % 5], "v": i % 50}} for i in range(rank, 300, P)
]

class Src(pw.io.python.ConnectorSubject):
    _deletions_enabled = False
    _distributed_partitioned = True
    def run(self):
        for s in range(0, len(rows), 50):
            self.next_batch(rows[s : s + 50])
            self.commit()

class S(pw.Schema):
    data: str
    v: int

t = pw.io.python.read(Src(), schema=S, autocommit_duration_ms=3_600_000)
counts = t.groupby(pw.this.data).reduce(
    word=pw.this.data, c=pw.reducers.count(), s=pw.reducers.sum(pw.this.v)
)
rrows = [{{"j": w, "m": i + 1}} for i, w in enumerate(words)]
class RSrc(pw.io.python.ConnectorSubject):
    _deletions_enabled = False
    def run(self):
        self.next_batch(rrows)
        self.commit()
class R(pw.Schema):
    j: str
    m: int
rt = pw.io.python.read(RSrc(), schema=R, autocommit_duration_ms=3_600_000)
joined = t.join(rt, pw.left.data == pw.right.j).select(
    d=pw.left.data, v=pw.left.v, m=pw.right.m
)
state = {{}}
pw.io.subscribe(counts, on_change=lambda *a: None)
pw.io.subscribe(joined, on_change=lambda *a: None)
pw.run(monitoring_level=pw.MonitoringLevel.NONE)

runtime = _insts[0]
report = pa.analyze_scope(runtime)
problems = pa.audit_runtime(runtime, report)
joins = [n for n in runtime.scope.nodes if isinstance(n, N.JoinNode)]
gbs = [n for n in runtime.scope.nodes if isinstance(n, N.GroupByNode)]
xs = runtime.scope.exchange_nodes
mesh_diags = [d.code for d in report.diagnostics
              if d.code.startswith("mesh.")]
print(json.dumps({{
    "rank": rank,
    "verdict": report.verdict,
    "problems": problems,
    "nb_fallbacks": runtime.stats.nb_fallbacks,
    "exchange_fallbacks": runtime.stats.exchange_fallbacks,
    "join_nb_batches": sum(n._nb_batches for n in joins),
    "gb_nb_batches": sum(n._nb_batches for n in gbs),
    "x_nb_batches": sum(x._nb_batches for x in xs),
    "n_exchanges": len(xs),
    "mesh_diags": mesh_diags,
    "mesh_rollbacks": runtime.stats.mesh_rollbacks,
    "mesh_heartbeats_missed": runtime.stats.mesh_heartbeats_missed,
    "mesh_rank_restarts": runtime.stats.mesh_rank_restarts,
}}))
"""


def _free_port_base(n: int = 4) -> int:
    import socket

    for _ in range(50):
        probe = socket.socket()
        probe.bind(("127.0.0.1", 0))
        base = probe.getsockname()[1]
        probe.close()
        socks = []
        try:
            for i in range(n):
                s = socket.socket()
                s.bind(("127.0.0.1", base + i))
                socks.append(s)
            return base
        except OSError:
            continue
        finally:
            for s in socks:
                s.close()
    raise RuntimeError("no consecutive free port range found")


@needs_nb
@pytest.mark.parametrize("world", [2, 4], ids=["2rank", "4rank"])
def test_fused_verdict_matches_zero_fallbacks_multirank(world):
    """Analyzer-vs-runtime agreement on a REAL N-rank mesh: the program
    carries wordcount (counts) and stream_join (joined). Every rank
    audits its own fallback counters against the static verdicts AND —
    at N>1 — the Plan Doctor's distributed-safety pass (the mesh
    verifier over this plan's exchange topology) must report verified,
    in agreement with the real run's mesh counters: zero rollbacks,
    zero restarts (ISSUE 7 acceptance: doctor verdicts at 4 ranks agree
    with a real 4-rank run)."""
    with tempfile.TemporaryDirectory() as td:
        prog = os.path.join(td, "prog.py")
        with open(prog, "w") as f:
            f.write(_RANK_PROGRAM.format(repo=REPO))
        port = _free_port_base(world)
        procs = []
        for rank in range(world):
            env = dict(os.environ)
            env.pop("PATHWAY_LANE_PROCESSES", None)
            env.update(
                PATHWAY_PROCESSES=str(world),
                PATHWAY_PROCESS_ID=str(rank),
                PATHWAY_FIRST_PORT=str(port),
                JAX_PLATFORMS="cpu",
                PYTHONPATH=REPO,
                PATHWAY_MESHCHECK_ROUNDS="1",  # keep the doctor pass lean
            )
            procs.append(
                subprocess.Popen(
                    [sys.executable, prog], env=env, cwd=td,
                    stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                )
            )
        outs = []
        try:
            for p in procs:
                out, err = p.communicate(timeout=240)
                assert p.returncode == 0, err.decode()[-2000:]
                outs.append(json.loads(out.decode().strip().splitlines()[-1]))
        finally:
            for q in procs:
                if q.poll() is None:
                    q.kill()
                    q.communicate()
        for r in outs:
            assert r["verdict"] == "fused", r
            assert r["problems"] == [], r
            assert r["nb_fallbacks"] == 0, r
            assert r["exchange_fallbacks"] == 0, r
            assert r["n_exchanges"] > 0
            # the mesh verifier's verdict, computed per rank over the
            # SAME lowered plan, agrees with what the real mesh did:
            # verified <-> no rollback, no restart, no missed heartbeat
            assert r["mesh_diags"] == ["mesh.verified"], r
            assert r["mesh_rollbacks"] == 0, r
            assert r["mesh_rank_restarts"] == 0, r
            assert r["mesh_heartbeats_missed"] == 0, r
        # the fused multi-rank chain actually carried columnar batches
        assert sum(r["x_nb_batches"] for r in outs) > 0
        assert sum(r["gb_nb_batches"] for r in outs) > 0
        assert sum(r["join_nb_batches"] for r in outs) > 0


# -- device-plan predicted vs measured recompiles (ISSUE 20) ----------------
# Zero false "device-clean": the Doctor's static shape-bucket set,
# enumerated through the SAME bucket functions the dispatch sites pad
# with, must agree EXACTLY with the runtime's device_recompiles_total /
# device_site_recompiles_total counters when the runtime is driven with
# the declared batches.

def _jax_available() -> bool:
    try:
        import jax  # noqa: F401

        return True
    except Exception:
        return False


needs_jax = pytest.mark.skipif(
    not _jax_available(), reason="jax unavailable"
)


@needs_jax
def test_device_plan_predicts_fused_ingest_recompiles_exactly():
    import numpy as np  # noqa: F401

    from pathway_tpu.analysis.device_plan import (
        WorkloadSpec,
        join_profile,
        simulate_ingest_buckets,
    )
    from pathway_tpu.internals.device import PLANE
    from pathway_tpu.internals.monitoring import ProberStats
    from pathway_tpu.models.encoder import EncoderConfig, SentenceEncoder
    from pathway_tpu.ops.ingest import IngestPipeline
    from pathway_tpu.ops.knn import KnnShard

    cfg = EncoderConfig.tiny()
    enc = SentenceEncoder(cfg)
    shard = KnnShard(cfg.hidden, capacity=128)
    pipe = IngestPipeline(enc, shard, stage_h2d=False)
    word = "retrieval"
    batches = [
        [" ".join([word] * 3)] * 4,          # small batch, short seqs
        [" ".join([word] * 3)] * 4,          # same shape: no new bucket
        [" ".join([word] * 20)] * 4,         # longer seq bucket
        [" ".join([word] * 3)] * 12,         # bigger batch bucket
    ]
    # the declared workload: (rows, raw token length) per batch, read
    # off the same tokenizer the pipeline stages with
    declared = []
    for texts in batches:
        ids, _ = enc.tokenizer(list(texts))
        declared.append((len(texts), ids.shape[1]))
    spec = WorkloadSpec(
        ingest_batches=tuple(declared),
        batch_cap=enc.batch_size,
        initial_capacity=shard.capacity,
    )
    predicted = simulate_ingest_buckets(spec, cfg)

    stats = ProberStats()
    PLANE.disarm()
    PLANE.arm(None, stats)
    try:
        for i, texts in enumerate(batches):
            pipe.ingest([f"k{i}-{j}" for j in range(len(texts))], texts)
    finally:
        PLANE.disarm()
    measured = stats.device_recompiles.get("ingest.fused", 0)
    assert measured == len(predicted), (
        f"predicted buckets {sorted(predicted)} vs measured "
        f"{measured} recompiles"
    )
    # the runtime's bucket keys ARE the predicted set (identity-shared
    # bucket functions, not merely equal counts)
    assert pipe._seen_buckets == predicted
    # and the --profile drift join agrees: measured == predicted is ok
    from pathway_tpu.analysis.device_plan import analyze_device_plan

    joined = join_profile(
        analyze_device_plan(workload=spec),
        {"device_recompiles": dict(stats.device_recompiles)},
    )
    assert joined.predictions["ingest.fused"]["drift"] == "ok"
    assert joined.verdict == "device-clean"


@needs_jax
def test_device_plan_predicts_knn_recompiles_exactly():
    import numpy as np

    from pathway_tpu.analysis.device_plan import (
        WorkloadSpec,
        simulate_knn_buckets,
    )
    from pathway_tpu.internals.device import PLANE
    from pathway_tpu.internals.monitoring import ProberStats
    from pathway_tpu.ops.knn import KnnShard

    write_batches = (16, 16, 48, 96)   # 48 keeps cap, 96 grows it to 256
    query_batches = (1, 3, 8)
    ks = (5, 10)
    spec = WorkloadSpec(
        write_batches=write_batches,
        query_batches=query_batches,
        ks=ks,
        initial_capacity=128,
    )
    pred_write, pred_search = simulate_knn_buckets(spec)

    shard = KnnShard(8, capacity=128)
    rng = np.random.default_rng(7)
    stats = ProberStats()
    PLANE.disarm()
    PLANE.arm(None, stats)
    try:
        seq = 0
        for b in write_batches:
            shard.add(
                [f"k{seq + j}" for j in range(b)],
                rng.normal(size=(b, 8)).astype(np.float32),
            )
            seq += b
        for q in query_batches:
            for k in ks:
                shard.search(
                    rng.normal(size=(q, 8)).astype(np.float32), k=k
                )
    finally:
        PLANE.disarm()
    assert stats.device_recompiles.get("knn.write", 0) == len(pred_write)
    assert stats.device_recompiles.get("knn.search", 0) == len(pred_search)
    # the runtime's seen-bucket keys are the predicted sets themselves
    assert shard._seen_buckets == pred_write | pred_search
    # aggregate pin: device_recompiles_total (the sum the OpenMetrics
    # endpoint renders) equals the Doctor's total prediction
    assert sum(stats.device_recompiles.values()) == (
        len(pred_write) + len(pred_search)
    )
