"""Persistence / recovery tests (reference pattern:
integration_tests/wordcount/ — run a wordcount pipeline as a subprocess
with fs persistent storage, kill it mid-stream, restart, assert
exactly-once-looking output after resume; test_recovery.py:38)."""

import json
import os
import subprocess
import sys
import textwrap

import pytest

_WORDCOUNT = textwrap.dedent(
    """
    import os, sys, threading, time
    sys.path.insert(0, {repo!r})
    import jax; jax.config.update("jax_platforms", "cpu")
    import pathway_tpu as pw

    pdir, docs_dir, out_path, kill_after = sys.argv[1:5]

    words = pw.io.fs.read(
        docs_dir, format="plaintext", mode="streaming",
        autocommit_duration_ms=10, refresh_interval=0.05,
        name="words",
    )
    counts = words.groupby(pw.this.data).reduce(
        word=pw.this.data, c=pw.reducers.count()
    )

    import json
    seen = {{}}
    if os.environ.get("WC_DURABLE_SINK") == "1" and os.path.exists(out_path):
        # operator-persistence contract: restored node state does NOT
        # re-notify sinks; sinks keep their own durable state (reference:
        # tracker.rs per-sink finalized times)
        with open(out_path) as f:
            seen = json.load(f)
    def on_change(key, row, time_, diff):
        if diff > 0:
            seen[row["word"]] = row["c"]
        elif row["word"] in seen and seen[row["word"]] == row["c"]:
            del seen[row["word"]]
        with open(out_path, "w") as f:
            json.dump(seen, f)

    pw.io.subscribe(counts, on_change=on_change)

    if float(kill_after) > 0:
        def killer():
            time.sleep(float(kill_after))
            os._exit(17)  # hard kill: no cleanup, journal must carry us
        threading.Thread(target=killer, daemon=True).start()
    else:
        def stopper():
            time.sleep(2.0)
            os._exit(0)
        threading.Thread(target=stopper, daemon=True).start()

    pw.run(
        persistence_config=pw.persistence.Config(
            backend=pw.persistence.Backend.filesystem(pdir)
        )
    )
    """
)


def _run_wordcount(tmp, kill_after: float) -> int:
    script = os.path.join(tmp, "wc.py")
    with open(script, "w") as f:
        f.write(_WORDCOUNT.format(repo=os.getcwd()))
    proc = subprocess.run(
        [
            sys.executable,
            script,
            os.path.join(tmp, "pstorage"),
            os.path.join(tmp, "docs"),
            os.path.join(tmp, "out.json"),
            str(kill_after),
        ],
        capture_output=True,
        timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    return proc.returncode


def test_wordcount_kill_and_recover(tmp_path):
    if __import__("os").environ.get("PATHWAY_LANE_PROCESSES"):
        pytest.skip("kill timing incompatible with the emulated-rank lane")
    tmp = str(tmp_path)
    docs = os.path.join(tmp, "docs")
    os.makedirs(docs)
    with open(os.path.join(docs, "f1.txt"), "w") as f:
        f.write("alpha\nbeta\nalpha\n")

    # phase 1: run and hard-kill mid-stream
    rc = _run_wordcount(tmp, kill_after=1.5)
    assert rc == 17

    # between runs: new data arrives
    with open(os.path.join(docs, "f2.txt"), "w") as f:
        f.write("alpha\ngamma\n")

    # phase 2: restart — journal replays f1, scan state skips re-reading it,
    # f2 is picked up fresh
    rc = _run_wordcount(tmp, kill_after=0)
    assert rc == 0

    with open(os.path.join(tmp, "out.json")) as f:
        counts = json.load(f)
    assert counts == {"alpha": 3, "beta": 1, "gamma": 1}


def test_persistence_backend_journal_roundtrip(tmp_path):
    import pathway_tpu as pw
    from pathway_tpu.persistence import PersistenceManager

    cfg = pw.persistence.Config(
        backend=pw.persistence.Backend.filesystem(str(tmp_path))
    )
    mgr = PersistenceManager(cfg)
    mgr.journal_batch("c1", 2, [(1, ("a",), 1)])
    mgr.journal_batch("c1", 4, [(1, ("a",), -1), (2, ("b",), 1)], {"pos": 7})
    mgr.save_subject_state("c1", {"pos": 7})

    mgr2 = PersistenceManager(cfg)
    journal = mgr2.load_journal("c1")
    assert journal == [
        (2, [(1, ("a",), 1)], None),
        (4, [(1, ("a",), -1), (2, ("b",), 1)], {"pos": 7}),
    ]
    assert mgr2.load_subject_state("c1") == {"pos": 7}


def test_torn_journal_tail_dropped(tmp_path):
    import pathway_tpu as pw
    from pathway_tpu.persistence import PersistenceManager

    cfg = pw.persistence.Config(
        backend=pw.persistence.Backend.filesystem(str(tmp_path))
    )
    mgr = PersistenceManager(cfg)
    mgr.journal_batch("c1", 2, [(1, ("a",), 1)])
    # simulate crash mid-append: garbage partial record at the tail
    mgr.backend.append("journal/c1", (999).to_bytes(8, "little") + b"par")
    journal = PersistenceManager(cfg).load_journal("c1")
    assert journal == [(2, [(1, ("a",), 1)], None)]


def test_wordcount_operator_snapshot_recover(tmp_path):
    if __import__("os").environ.get("PATHWAY_LANE_PROCESSES"):
        pytest.skip("kill timing incompatible with the emulated-rank lane")
    """Same kill/restart scenario, OPERATOR_PERSISTING mode: node states
    restore directly, no journal replay."""
    tmp = str(tmp_path)
    docs = os.path.join(tmp, "docs")
    os.makedirs(docs)
    with open(os.path.join(docs, "f1.txt"), "w") as f:
        f.write("alpha\nbeta\nalpha\n")

    script = os.path.join(tmp, "wc.py")
    with open(script, "w") as f:
        f.write(
            _WORDCOUNT.format(repo=os.getcwd()).replace(
                "backend=pw.persistence.Backend.filesystem(pdir)",
                "backend=pw.persistence.Backend.filesystem(pdir),\n"
                "            persistence_mode=\"OPERATOR_PERSISTING\"",
            )
        )

    def run(kill_after):
        return subprocess.run(
            [
                sys.executable, script,
                os.path.join(tmp, "pstorage"), docs,
                os.path.join(tmp, "out.json"), str(kill_after),
            ],
            capture_output=True, timeout=120,
            env={
                **os.environ,
                "JAX_PLATFORMS": "cpu",
                "WC_DURABLE_SINK": "1",
            },
        ).returncode

    assert run(1.5) == 17  # hard kill mid-stream
    with open(os.path.join(docs, "f2.txt"), "w") as f:
        f.write("alpha\ngamma\n")
    assert run(0) == 0

    with open(os.path.join(tmp, "out.json")) as f:
        counts = json.load(f)
    assert counts == {"alpha": 3, "beta": 1, "gamma": 1}


def test_index_adapter_snapshot_roundtrip():
    """Operator-persistence hooks on index adapters: state survives a
    snapshot/load cycle and answers stay identical."""
    import numpy as np

    from pathway_tpu.stdlib.indexing.nearest_neighbors import _KnnAdapter

    a = _KnnAdapter(4, "cos")
    rng = np.random.default_rng(0)
    vecs = rng.normal(size=(10, 4)).astype(np.float32)
    for i in range(10):
        a.add(i, vecs[i], {"i": i})
    before = a.search([(vecs[3], 2, None)])

    b = _KnnAdapter(4, "cos")
    b.load_state(a.snapshot_state())
    after = b.search([(vecs[3], 2, None)])
    assert before == after

    from pathway_tpu.stdlib.indexing.bm25 import _Bm25Adapter

    p = _Bm25Adapter()
    p.add(1, "the quick fox", None)
    p.add(2, "lazy dog", None)
    q = _Bm25Adapter()
    q.load_state(p.snapshot_state())
    assert q.search([("fox", 2, None)]) == p.search([("fox", 2, None)])


def _global_pickle(module: str, name: str) -> bytes:
    """Protocol-0 GLOBAL record: resolves module.name via find_class
    without needing the target importable in this process."""
    return f"c{module}\n{name}\n.".encode()


def test_safe_unpickler_denies_code_execution_names():
    """The allow-list is the trust boundary for journal/subject-state
    loads: builtins must be NAME-gated (eval/exec), numpy must not expose
    exec wrappers (runstring), and unknown modules never resolve."""
    import pickle

    from pathway_tpu.persistence import _safe_loads

    denied = [
        pickle.dumps(eval),  # builtins.eval by reference
        pickle.dumps(exec),
        _global_pickle("builtins", "getattr"),
        _global_pickle("builtins", "__import__"),
        # numpy is module-prefixed but name-allowlisted: the runstring
        # exec wrapper must not slip through the numpy branch
        _global_pickle("numpy.testing._private.utils", "runstring"),
        _global_pickle("numpy.f2py.diagnose", "run_command"),
        _global_pickle("os", "system"),
        _global_pickle("posix", "system"),
        _global_pickle("subprocess", "Popen"),
        _global_pickle("totally.unknown.module", "thing"),
    ]
    for payload in denied:
        with pytest.raises(pickle.UnpicklingError):
            _safe_loads(payload)


def test_safe_unpickler_allows_plain_engine_values():
    import pickle
    from collections import OrderedDict

    import numpy as np

    from pathway_tpu.persistence import _safe_loads

    values = [
        (2, [(1, ("a", 3.5, None), 1)], {"pos": 7}),
        OrderedDict(a=1),
        {frozenset({1, 2}): b"x"},
        np.int64(5),
        np.arange(4, dtype=np.float32),
    ]
    for v in values:
        out = _safe_loads(pickle.dumps(v))
        if isinstance(v, np.ndarray):
            assert (out == v).all()
        else:
            assert out == v


def test_midscan_force_flush_defers_journaling():
    """A runtime-cadence flush while a stateful subject is mid-scan must NOT
    journal rows (the subject's bookkeeping may lag them); the next
    subject-driven commit journals the backlog atomically with a state that
    claims it (ADVICE r1: snapshot race broke exactly-once)."""
    import queue
    import threading

    from pathway_tpu.io._connector import run_connector_thread

    class _Subject:
        _autocommit_duration_ms = 0  # zero window: flush per emit
        # (None would disable autocommit entirely, reference semantics)

        def __init__(self):
            self.bookkept = []
            self.mid_scan = threading.Event()
            self.resume = threading.Event()

        def _attach(self, emit, flush):
            self._emit = emit
            self._flush = flush

        def run(self):
            # emit two rows, then pause BEFORE updating bookkeeping —
            # modelling fs._scan_once between upserts and _seen/_emitted
            self._emit(("row", "a"))
            self._emit(("row", "b"))
            self.mid_scan.set()
            assert self.resume.wait(5)
            self.bookkept = ["a", "b"]
            self._flush()  # subject commit boundary

        def on_stop(self):
            pass

        def snapshot_state(self):
            return {"bookkept": list(self.bookkept)}

    class _Conn:
        pass

    import types

    subject = _Subject()
    conn = _Conn()
    conn.subject = subject
    # persistence configured -> the thread tracks the unjournaled backlog
    conn.node = types.SimpleNamespace(
        scope=types.SimpleNamespace(
            runtime=types.SimpleNamespace(persistence=object())
        )
    )
    conn.parser = lambda msg: [(msg[1], (msg[1],), 1)]
    q: "queue.Queue" = queue.Queue()
    t = threading.Thread(target=run_connector_thread, args=(conn, q), daemon=True)
    t.start()
    assert subject.mid_scan.wait(5)
    # runtime-cadence flush while the subject is mid-scan (pending is empty
    # here — per-emit flushes already forwarded the rows — so this pins that
    # force_flush never fabricates a journal entry mid-scan)
    conn.force_flush()
    entries = [q.get(timeout=5), q.get(timeout=5)]  # the two emit flushes
    subject.resume.set()
    t.join(timeout=5)
    # drain the boundary entry and the finish sentinel
    while True:
        entry = q.get(timeout=5)
        entries.append(entry)
        if entry[1] is None:
            break

    data_entries = [e for e in entries if e[1] is not None]
    # mid-scan flushes forwarded rows but journaled nothing, carried no state
    assert [d[0] for e in data_entries[:2] for d in e[1]] == ["a", "b"]
    for e in data_entries[:2]:
        assert e[2] is None and e[3] == []
    # commit boundary journaled the backlog atomically with a claiming state
    boundary = data_entries[-1]
    assert boundary[2] == {"bookkept": ["a", "b"]}
    assert [d[0] for d in boundary[3]] == ["a", "b"]
