"""ConnectorSubject.next_batch: the batch ingestion door.

A pre-batched list of row dicts must produce exactly the same final state
as the same rows pushed one next() at a time, across all three parser
regimes: keyless append-only (C fast path, one parse_upserts call per
batch message), keyless with removal tracking (Python fallback expansion),
and primary-keyed upsert sessions.

Reference behavior bar: python/pathway/io/python/__init__.py ConnectorSubject
(row-at-a-time only — batching is this framework's tpu-native addition, so
the equivalence oracle below is the spec).
"""

import pathway_tpu as pw
from pathway_tpu.internals.graph_runner import GraphRunner


def _run_counts(subject_cls, schema, rows_arg):
    pw.internals.parse_graph.G.clear()
    t = pw.io.python.read(
        subject_cls(rows_arg), schema=schema, autocommit_duration_ms=None
    )
    counts = t.groupby(pw.this.word).reduce(
        word=pw.this.word, c=pw.reducers.count()
    )
    cap = GraphRunner().run_tables(counts)[0]
    return sorted(tuple(r) for r in cap.state.rows.values())


class _WordSchema(pw.Schema):
    word: str


class _BatchSubject(pw.io.python.ConnectorSubject):
    _deletions_enabled = False

    def __init__(self, batches):
        super().__init__()
        self._batches = batches

    def run(self):
        for b in self._batches:
            self.next_batch(b)
            self.commit()


class _RowSubject(pw.io.python.ConnectorSubject):
    _deletions_enabled = False

    def __init__(self, batches):
        super().__init__()
        self._batches = batches

    def run(self):
        for b in self._batches:
            for row in b:
                self.next(**row)
            self.commit()


def test_next_batch_matches_row_at_a_time():
    words = ["alpha", "beta", "gamma", "delta"]
    batches = [
        [{"word": words[(i * 7 + s) % 4]} for i in range(25)]
        for s in range(4)
    ]
    assert _run_counts(_BatchSubject, _WordSchema, batches) == _run_counts(
        _RowSubject, _WordSchema, batches
    )


def test_next_batch_with_removal_tracking():
    """Subjects that keep deletions enabled route batch messages through the
    Python parse expansion; remove()-by-content must still retract rows
    that entered via next_batch."""

    class S(pw.io.python.ConnectorSubject):
        def __init__(self, _):
            super().__init__()

        def run(self):
            self.next_batch(
                [{"word": "keep"}, {"word": "drop"}, {"word": "keep"}]
            )
            self.commit()
            self.remove(word="drop")
            self.commit()

    out = _run_counts(S, _WordSchema, None)
    assert out == [("keep", 2)]


def test_next_batch_primary_keyed_upserts():
    """Primary-keyed subjects treat each batch row as an upsert: the last
    write per key wins, exactly as with next()."""

    class KV(pw.Schema):
        k: int = pw.column_definition(primary_key=True)
        v: int

    class S(pw.io.python.ConnectorSubject):
        def __init__(self, _):
            super().__init__()

        def run(self):
            self.next_batch([{"k": 1, "v": 10}, {"k": 2, "v": 20}])
            self.commit()
            self.next_batch([{"k": 1, "v": 11}, {"k": 3, "v": 30}])
            self.commit()

    pw.internals.parse_graph.G.clear()
    t = pw.io.python.read(S(None), schema=KV, autocommit_duration_ms=None)
    cap = GraphRunner().run_tables(t)[0]
    rows = sorted(tuple(r) for r in cap.state.rows.values())
    assert rows == [(1, 11), (2, 20), (3, 30)]


def test_next_batch_interleaves_with_next():
    """Mixed producers in one commit: batch messages and single rows land
    in arrival order within the same flush."""

    class S(pw.io.python.ConnectorSubject):
        _deletions_enabled = False

        def __init__(self, _):
            super().__init__()

        def run(self):
            self.next(word="solo")
            self.next_batch([{"word": "batch"}, {"word": "batch"}])
            self.next(word="solo")
            self.commit()

    out = _run_counts(S, _WordSchema, None)
    assert out == [("batch", 2), ("solo", 2)]


def test_next_batch_empty_noop():
    class S(pw.io.python.ConnectorSubject):
        _deletions_enabled = False

        def __init__(self, _):
            super().__init__()

        def run(self):
            self.next_batch([])
            self.next_batch([{"word": "x"}])
            self.commit()

    assert _run_counts(S, _WordSchema, None) == [("x", 1)]
