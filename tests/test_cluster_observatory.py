"""Cluster observatory battery (ISSUE 10): cluster metrics aggregation
(/metrics/cluster with rank labels + derived skew/efficiency gauges),
per-peer exchange labels, the mesh.slow straggler injection, per-segment
trace clock offsets, the 4-rank trace merge, and the wave critical-path
analyzer's straggler attribution."""

import json
import os
import socket
import time
import urllib.request

import pytest

import pathway_tpu as pw
from pathway_tpu.analysis.critical_path import (
    critical_path,
    render_critical_path,
)
from pathway_tpu.internals import faults
from pathway_tpu.internals.cluster import (
    ClusterMetricsAggregator,
    parse_openmetrics,
)
from pathway_tpu.internals.monitoring import (
    ProberStats,
    render_dashboard,
    start_http_server,
)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _wordcount(n_rows=3000, batches=6, distinct=40):
    class Source(pw.io.python.ConnectorSubject):
        _deletions_enabled = False

        def run(self):
            per = n_rows // batches
            for b in range(batches):
                self.next_batch(
                    [
                        {"data": f"w{i % distinct}"}
                        for i in range(b * per, (b + 1) * per)
                    ]
                )
                self.commit()

    class S(pw.Schema):
        data: str

    t = pw.io.python.read(Source(), schema=S, autocommit_duration_ms=None)
    counts = t.groupby(pw.this.data).reduce(
        word=pw.this.data, c=pw.reducers.count()
    )
    pw.io.subscribe(counts, on_change=lambda *a: None)


def _run_traced(tmp_path, monkeypatch, lane=None):
    path = str(tmp_path / "trace.json")
    monkeypatch.setenv("PATHWAY_TRACE", path)
    if lane is not None:
        monkeypatch.setenv("PATHWAY_LANE_PROCESSES", str(lane))
    _wordcount()
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    return path


# -- OpenMetrics parsing + cluster aggregation ----------------------------

def test_parse_openmetrics_roundtrip():
    stats = ProberStats()
    stats.on_ingest("src_a", 42)
    stats.on_exchange_frame(512, peer=1)
    stats.on_exchange_recv_wait(1, 0.25)
    stats.on_output_lag("out", 3.0)
    samples = parse_openmetrics(stats.render_openmetrics())
    by_name = {}
    for name, labels, value in samples:
        by_name.setdefault(name, []).append((labels, value))
    assert by_name["connector_rows_total"] == [({"connector": "src_a"}, 42.0)]
    assert by_name["exchange_peer_bytes_total"] == [({"peer": "1"}, 512.0)]
    assert by_name["exchange_recv_wait_seconds_total"][0][1] == pytest.approx(
        0.25
    )
    # histogram bucket lines parse (le is just a label)
    assert any("le" in lab for lab, _ in by_name["output_lag_ms_bucket"])


def _two_rank_endpoints():
    """Two live ProberStats-backed /metrics endpoints with distinct
    counters; returns (endpoints, stats list)."""
    endpoints, stats = {}, []
    for rank in range(2):
        st = ProberStats()
        st.on_ingest("src", 1000 * (rank + 1))
        st.on_exchange_frame(256 * (rank + 1), peer=1 - rank)
        # rank 1 waits 3x longer than rank 0 -> skew = 1.0s
        st.on_exchange_recv_wait(1 - rank, 0.5 + rank * 1.0)
        st.on_exchange_wave(0.2)
        st.on_idle(0.1 * (rank + 1))
        st.on_exchange_step(0.3, 0.7)
        port = _free_port()
        start_http_server(st, port)
        endpoints[rank] = f"http://127.0.0.1:{port}/metrics"
        stats.append(st)
    return endpoints, stats


def test_cluster_aggregator_merges_ranks_with_derived_gauges():
    endpoints, _stats = _two_rank_endpoints()
    agg = ClusterMetricsAggregator(
        _free_port(), endpoints, interval_s=60, baseline_rows_per_s=50.0
    )
    agg.start()
    try:
        assert agg.scrape_once() == 2
        body = agg.render_cluster()
        # per-rank relabeling: every curated family shows both ranks
        assert 'connector_rows_total{rank="0",connector="src"} 1000' in body
        assert 'connector_rows_total{rank="1",connector="src"} 2000' in body
        # the byte matrix: (rank, peer) cells
        assert 'exchange_peer_bytes_total{rank="0",peer="1"} 256' in body
        assert 'exchange_peer_bytes_total{rank="1",peer="0"} 512' in body
        # derived: skew = max-min of per-rank recv-wait = 1.0
        assert "mesh_skew_seconds 1.0" in body
        assert "cluster_ranks 2" in body
        # per-rank comms/compute/idle present
        assert 'exchange_comms_seconds_total{rank="0"}' in body
        assert 'runtime_idle_seconds_total{rank="1"}' in body
        # the view is served over HTTP on /metrics/cluster
        with urllib.request.urlopen(
            f"http://127.0.0.1:{agg.port}/metrics/cluster", timeout=5
        ) as r:
            assert r.status == 200
            assert "mesh_skew_seconds" in r.read().decode()
        # throughput + efficiency need a second scrape with progress
        _stats[0].on_ingest("src", 500)
        time.sleep(0.05)
        assert agg.scrape_once() == 2
        body = agg.render_cluster()
        assert "cluster_rows_per_s" in body
        assert "scaling_efficiency" in body
        summary = agg.summary()
        assert set(summary["ranks"]) == {0, 1}
        assert summary["skew_s"] == pytest.approx(1.0)
        assert summary["efficiency"] is not None
    finally:
        agg.stop()


def test_cluster_aggregator_rank_down_and_reresolve():
    endpoints, _stats = _two_rank_endpoints()
    dead_port = _free_port()
    agg = ClusterMetricsAggregator(
        _free_port(),
        {0: endpoints[0], 1: f"http://127.0.0.1:{dead_port}/metrics"},
        interval_s=60,
    )
    agg.start()
    try:
        assert agg.scrape_once() == 1
        body = agg.render_cluster()
        assert "cluster_ranks 1" in body
        assert "cluster_ranks_expected 2" in body
        assert 'connector_rows_total{rank="0",connector="src"}' in body
        # re-resolve onto the live endpoint (supervisor respawn path):
        # the fresh epoch is stamped and the rank scrapes again
        agg.set_endpoints(endpoints, epoch=3)
        assert agg.scrape_once() == 2
        body = agg.render_cluster()
        assert "cluster_ranks 2" in body
        assert "cluster_epoch 3" in body
        assert 'connector_rows_total{rank="1",connector="src"} 2000' in body
    finally:
        agg.stop()


def test_cluster_module_is_stdlib_filepath_loadable():
    """The supervisor loads internals/cluster.py by file path (no
    package __init__s) — same contract as protocol.py/_frontend.py."""
    from pathway_tpu.internals.cluster import load_by_path

    cls = load_by_path()
    assert cls.__name__ == "ClusterMetricsAggregator"
    assert cls is not ClusterMetricsAggregator  # independent module


def test_supervisor_hosts_cluster_aggregator(monkeypatch):
    from pathway_tpu.parallel.supervisor import MeshSupervisor

    monkeypatch.delenv("PATHWAY_CLUSTER_METRICS_PORT", raising=False)
    port = _free_port()
    sup = MeshSupervisor(["true"], processes=2, cluster_metrics=port)
    sup._start_cluster()
    try:
        assert sup.cluster is not None
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=5
        ) as r:
            assert r.status == 200
        # respawn path: endpoints re-resolve and the epoch is stamped
        sup.cluster.set_endpoints(
            sup.cluster.default_endpoints(2), epoch=1
        )
        assert "cluster_epoch 1" in sup.cluster.render_cluster()
    finally:
        sup.cluster.stop()
        sup.cluster = None


# -- per-peer exchange labels (satellite) ---------------------------------

def test_per_peer_exchange_labels_keep_unlabeled_totals():
    stats = ProberStats()
    stats.on_exchange_frame(100, peer=1)
    stats.on_exchange_frame(50, peer=2)
    stats.on_exchange_frame(7)  # legacy call: totals only
    text = stats.render_openmetrics()
    assert "exchange_frames_total 3" in text
    assert "exchange_bytes_total 157" in text
    assert 'exchange_peer_frames_total{peer="1"} 1' in text
    assert 'exchange_peer_bytes_total{peer="2"} 50' in text
    stats.on_exchange_recv_wait(1, 0.5)
    stats.on_exchange_recv_wait(1, 0.25)
    text = stats.render_openmetrics()
    assert "exchange_recv_wait_seconds_total 0.75" in text
    assert (
        'exchange_peer_recv_wait_seconds_total{peer="1"} 0.75' in text
    )


# -- mesh.slow straggler injection (satellite) ----------------------------

def test_mesh_slow_delay_rule_sleeps_and_never_raises():
    faults.install_plan(
        {
            "seed": 1,
            "rules": [
                {
                    "point": "mesh.slow",
                    "phase": "wave_send",
                    "action": "delay",
                    "delay_ms": 60,
                }
            ],
        }
    )
    try:
        t0 = time.perf_counter()
        faults.fault_point("mesh.slow", phase="wave_send")  # fires
        elapsed = time.perf_counter() - t0
        assert elapsed >= 0.05, "delay rule did not sleep"
        t0 = time.perf_counter()
        faults.fault_point("mesh.slow", phase="step")  # other phase
        assert time.perf_counter() - t0 < 0.02
    finally:
        faults.reset()


def test_mesh_slow_rank_filter(monkeypatch):
    from pathway_tpu.internals.config import (
        pop_config_overlay,
        push_config_overlay,
    )

    faults.install_plan(
        {
            "seed": 1,
            "rules": [
                {
                    "point": "mesh.slow",
                    "rank": 1,
                    "action": "delay",
                    "delay_ms": 60,
                }
            ],
        }
    )
    try:
        t0 = time.perf_counter()
        faults.fault_point("mesh.slow", phase="wave_send")  # rank 0
        assert time.perf_counter() - t0 < 0.02
        tok = push_config_overlay(processes=2, process_id=1)
        try:
            t0 = time.perf_counter()
            faults.fault_point("mesh.slow", phase="wave_send")
            assert time.perf_counter() - t0 >= 0.05
        finally:
            pop_config_overlay(tok)
    finally:
        faults.reset()


def test_mesh_slow_registered_point():
    assert "mesh.slow" in faults.POINTS
    with pytest.raises(ValueError, match="unknown fault action"):
        faults.FaultRule("mesh.slow", action="stall")


# -- per-segment clock offsets (satellite) --------------------------------

def test_clock_offset_segments_apply_per_event(tmp_path):
    from pathway_tpu.internals.flight import FlightRecorder

    rec = FlightRecorder(str(tmp_path / "t.json"), rank=1, world=2)
    # handshake sample (+1ms) anchored at mono 0; epoch commit at
    # mono 50_000 resamples to +3ms — conversion interpolates linearly
    # between the two samples and is constant outside them
    rec._offset_segments = [(0, 1_000_000)]
    rec.resample_clock_offset(3_000_000, at_ns=50_000)
    rec.note_node(0, 1, 10_000, 20_000, 5, True)
    rec.note_node(0, 2, 60_000, 70_000, 5, True)
    evs = [e for e in rec.chrome_events() if e.get("cat") == "node"]
    # at 10_000 (1/5 of the way): 1ms + (2ms * 10/50) = 1.4ms
    assert evs[0]["ts"] == pytest.approx((10_000 + 1_400_000) / 1000.0)
    # past the latest sample: the fresh offset applies unmodified
    assert evs[1]["ts"] == pytest.approx((60_000 + 3_000_000) / 1000.0)
    # interpolated conversion is monotone across the boundary
    assert evs[1]["ts"] > evs[0]["ts"]
    # out-of-order samples are dropped (list stays sorted)
    rec.resample_clock_offset(9_000_000, at_ns=40_000)
    assert rec.clock_offset_ns == 3_000_000
    doc = rec._doc()
    assert doc["offset_segments"] == [[0, 1_000_000], [50_000, 3_000_000]]
    # the property setter anchors at the sample instant: events BEFORE
    # the handshake convert with the first offset unshifted
    rec2 = FlightRecorder(str(tmp_path / "t2.json"), rank=1, world=2)
    rec2.clock_offset_ns = 5_000_000
    rec2.note_node(0, 1, 10_000, 20_000, 5, True)  # long before anchor
    ev = [e for e in rec2.chrome_events() if e.get("cat") == "node"][0]
    assert ev["ts"] == pytest.approx((10_000 + 5_000_000) / 1000.0)


# -- 4-rank trace merge (satellite) ---------------------------------------

def test_trace_four_rank_merged_and_critical_path_cli(
    tmp_path, monkeypatch
):
    from pathway_tpu.analysis.__main__ import main as cli_main
    from pathway_tpu.analysis.profile import validate_trace

    path = _run_traced(tmp_path, monkeypatch, lane=4)
    doc = json.load(open(path))
    assert validate_trace(doc) == []
    assert doc["pathway"]["merged_ranks"] == [0, 1, 2, 3]
    evs = doc["traceEvents"]
    assert {e["pid"] for e in evs} == {0, 1, 2, 3}
    # monotonic per-track timestamps survive the 4-way offset merge
    last = {}
    for e in evs:
        if e.get("ph") == "M":
            continue
        key = (e["pid"], e["tid"])
        assert e["ts"] >= last.get(key, float("-inf")) - 2e-3
        last[key] = e["ts"]
    # all partials consumed
    for rank in range(4):
        assert not os.path.exists(f"{path}.r{rank}")
    # every rank carries tsync metadata (segments recorded)
    meta = doc["pathway"]["rank_meta"]
    for rank in range(4):
        assert "clock_offset_ns" in meta[f"rank{rank}"]
        assert meta[f"rank{rank}"]["offset_segments"]
    # the critical-path CLI exits 0 on the merged result
    assert cli_main(["--critical-path", path]) == 0


# -- critical-path analyzer ------------------------------------------------

def _synthetic_trace(tmp_path):
    """Two ranks, two waves: rank 1 is slow to send (long busy), rank 0
    absorbs it as recv-wait — the canonical straggler shape."""
    def wave(pid, ts, dur, t, n):
        return {
            "name": "wave 1", "cat": "wave", "ph": "X", "pid": pid,
            "tid": 0, "ts": ts, "dur": dur, "args": {"t": t, "exchanges": n},
        }

    def mesh(pid, name, ts, dur, peer):
        return {
            "name": name, "cat": "mesh", "ph": "X", "pid": pid, "tid": 0,
            "ts": ts, "dur": dur, "args": {"peer": peer},
        }

    def node(pid, nid, ts, dur, rows):
        return {
            "name": f"GroupByNode#{nid}", "cat": "node", "ph": "X",
            "pid": pid, "tid": 0, "ts": ts, "dur": dur,
            "args": {"node": nid, "t": 1, "rows": rows, "rep": "nb"},
        }

    events = []
    for w, base in enumerate((1000.0, 9000.0)):
        t = 100 + w
        # rank 0: sends immediately, then waits ~3ms on rank 1
        events.append(wave(0, base, 3600.0, t, 1))
        events.append(mesh(0, "send→1", base + 50, 100.0, 1))
        events.append(mesh(0, "recv-wait←1", base + 200, 3200.0, 1))
        # rank 1: 3ms of pre-send work (the straggler), no waiting
        events.append(node(1, 5, base - 500, 400.0, 900))
        events.append(wave(1, base, 3500.0, t, 1))
        events.append(mesh(1, "send→0", base + 3000, 200.0, 0))
        events.append(mesh(1, "recv-wait←0", base + 3250, 50.0, 0))
    events.sort(key=lambda e: e["ts"])
    doc = {
        "traceEvents": events,
        "pathway": {
            "schema": 1,
            "merged_ranks": [0, 1],
            "nodes": {
                "5": {"label": "GroupByNode#5", "verdict": "fused"},
            },
        },
    }
    p = tmp_path / "synth.json"
    p.write_text(json.dumps(doc))
    return str(p)


def test_critical_path_synthetic_straggler(tmp_path):
    report = critical_path(_synthetic_trace(tmp_path))
    assert report["valid"], report["problems"]
    assert report["waves"] == 2
    s = report["straggler"]
    assert s["rank"] == 1 and s["waiter"] == 0
    assert s["upstream_node"]["label"] == "GroupByNode#5"
    assert s["upstream_node"]["verdict"] == "fused"
    assert "rank 1" in report["verdict"]
    # per-wave skew: rank 1 busy ~3.2ms vs rank 0 ~0.15ms, 2 waves
    assert report["mesh_skew_seconds"] == pytest.approx(0.00605, rel=0.1)
    assert report["speedup_if_balanced"] > 1.2
    # legs: rank 0's wall is dominated by recv-wait, rank 1's by compute
    legs = report["legs"]
    assert legs[0]["recv_wait_s"] > legs[0]["compute_s"]
    assert legs[1]["compute_s"] > legs[1]["recv_wait_s"]
    text = render_critical_path(report)
    assert "recv-wait matrix" in text and "rank 0 ← rank 1" in text


def test_critical_path_splits_decompress_from_decode(tmp_path):
    """Fast-wire sub-legs (ISSUE 13): decompress spans split out of the
    decode leg, and the codec byte ratio lands in the report AND the
    straggler verdict line — 'compression helped/hurt' is readable from
    --critical-path output."""
    path = _synthetic_trace(tmp_path)
    doc = json.loads(open(path).read())
    # rank 0's receiver track: one 1.2ms decode wrapping a 0.5ms
    # decompress that inflated 2000 wire bytes to 9000
    doc["traceEvents"].extend(
        [
            {
                "name": "decode←1", "cat": "mesh", "ph": "X", "pid": 0,
                "tid": 201, "ts": 3450.0, "dur": 1200.0,
                "args": {"peer": 1, "bytes": 2600},
            },
            {
                "name": "decompress←1", "cat": "mesh", "ph": "X",
                "pid": 0, "tid": 201, "ts": 3460.0, "dur": 500.0,
                "args": {"peer": 1, "bytes": 2000, "raw": 9000},
            },
        ]
    )
    open(path, "w").write(json.dumps(doc))
    report = critical_path(path)
    assert report["valid"], report["problems"]
    legs = report["legs"]
    assert legs[0]["decompress_s"] == pytest.approx(0.0005)
    # decode leg excludes the codec share (1.2ms total - 0.5ms inflate)
    assert legs[0]["decode_s"] == pytest.approx(0.0007)
    codec = report["codec"]
    assert codec["raw_bytes"] == 9000 and codec["wire_bytes"] == 2000
    assert codec["ratio"] == pytest.approx(4.5)
    assert "codec ratio 4.50x" in report["verdict"]
    text = render_critical_path(report)
    assert "decompress=0.0005" in text
    assert "9000 raw -> 2000 wire" in text


def test_critical_path_verdict_says_compression_off(tmp_path):
    """A trace without compressed segments reads an explicit
    'compression off' suffix — off must be distinguishable from
    unmeasured."""
    report = critical_path(_synthetic_trace(tmp_path))
    assert report["codec"] is None
    assert "compression off" in report["verdict"]


def test_critical_path_single_rank_trace_is_not_an_error(tmp_path, monkeypatch):
    monkeypatch.delenv("PATHWAY_LANE_PROCESSES", raising=False)
    path = _run_traced(tmp_path, monkeypatch)
    report = critical_path(path)
    assert report["valid"]
    assert report["waves"] == 0
    assert "no exchange waves" in report["verdict"]
    from pathway_tpu.analysis.__main__ import main as cli_main

    assert cli_main(["--critical-path", path]) == 0


def test_critical_path_names_injected_slow_rank(tmp_path, monkeypatch):
    """The acceptance pin (ISSUE 10): a mesh.slow-delayed rank must be
    named by the analyzer's straggler attribution — here over the
    emulated 2-rank lane; scripts/cluster_smoke.py pins the real-fork
    4-rank version in CI."""
    faults.install_plan(
        {
            "seed": 7,
            "rules": [
                {
                    "point": "mesh.slow",
                    "phase": "wave_send",
                    "rank": 1,
                    "action": "delay",
                    "delay_ms": 25,
                }
            ],
        }
    )
    try:
        path = _run_traced(tmp_path, monkeypatch, lane=2)
    finally:
        faults.reset()
    report = critical_path(path)
    assert report["valid"], report["problems"]
    assert report["waves"] > 0
    s = report["straggler"]
    assert s is not None and s["rank"] == 1, report["verdict"]
    assert "rank 1" in report["verdict"]
    assert report["mesh_skew_seconds"] > 0.02
    assert report["speedup_if_balanced"] > 1.0
    # the un-delayed rank's wait leg dominates its compute leg
    legs = report["legs"]
    assert legs[0]["recv_wait_s"] > legs[0]["compute_s"]


# -- dashboard cluster section --------------------------------------------

def test_dashboard_renders_cluster_section():
    from rich.console import Console

    class FakeAgg:
        def summary(self):
            return {
                "ranks": {
                    0: {"rows": 1000, "comms_s": 0.5, "compute_s": 1.5,
                        "idle_s": 0.2, "recv_wait_s": 0.4},
                    1: {"rows": 900, "comms_s": 0.6, "compute_s": 1.4,
                        "idle_s": 0.1, "recv_wait_s": 0.1},
                },
                "skew_s": 0.3,
                "rows_per_s": 123456.0,
                "efficiency": 0.87,
            }

    stats = ProberStats()
    stats.on_ingest("src", 10)
    stats.cluster = FakeAgg()
    console = Console(record=True, width=120)
    console.print(render_dashboard(stats))
    text = console.export_text()
    assert "cluster" in text
    assert "recv-wait" in text
    assert "skew 0.300s" in text
    assert "efficiency 0.87" in text


def test_dashboard_survives_broken_cluster_handle():
    from rich.console import Console

    class Broken:
        def summary(self):
            raise RuntimeError("scrape thread died")

    stats = ProberStats()
    stats.on_ingest("src", 10)
    stats.cluster = Broken()
    console = Console(record=True, width=120)
    console.print(render_dashboard(stats))  # must not raise
    assert "src" in console.export_text()
