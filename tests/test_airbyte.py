"""pw.io.airbyte end-to-end without docker (VERDICT r2 #7): a declarative
YAML-manifest source over live HTTP and an executable source speaking the
real Airbyte protocol (reference: third_party/airbyte_serverless/
executable_runner.py; io/airbyte/__init__.py)."""

import json
import os
import textwrap
import threading

import pytest

import pathway_tpu as pw
from pathway_tpu.internals.graph_runner import GraphRunner


@pytest.fixture(autouse=True)
def _clear_graph():
    pw.internals.parse_graph.G.clear()
    yield


# -- executable source (full Airbyte protocol over a subprocess) ----------

_FAKE_CONNECTOR = textwrap.dedent(
    """
    import argparse, json, sys

    p = argparse.ArgumentParser()
    p.add_argument("action")
    p.add_argument("--config")
    p.add_argument("--catalog")
    p.add_argument("--state")
    a = p.parse_args()

    if a.action == "spec":
        print(json.dumps({"type": "SPEC", "spec": {"title": "fake"}}))
        sys.exit(0)
    if a.action == "discover":
        print(json.dumps({"type": "CATALOG", "catalog": {"streams": [
            {"name": "users", "json_schema": {},
             "supported_sync_modes": ["full_refresh", "incremental"],
             "default_cursor_field": ["uid"]},
            {"name": "noise", "json_schema": {},
             "supported_sync_modes": ["full_refresh"]},
        ]}}))
        sys.exit(0)
    assert a.action == "read"
    catalog = json.load(open(a.catalog))
    names = [s["stream"]["name"] for s in catalog["streams"]]
    assert names == ["users"], names  # stream filter must reach the child
    config = json.load(open(a.config))
    start = 0
    if a.state:
        state = json.load(open(a.state))
        states = state.get("global", {}).get("stream_states", [])
        for entry in states:
            if entry["stream_descriptor"]["name"] == "users":
                start = entry["stream_state"].get("uid", 0)
    print(json.dumps({"type": "LOG", "log": {"message": "starting"}}))
    for uid in range(start + 1, config["n_users"] + 1):
        print(json.dumps({"type": "RECORD", "record": {
            "stream": "users", "data": {"uid": uid, "name": f"u{uid}"}}}))
    print(json.dumps({"type": "STATE", "state": {
        "type": "STREAM", "stream": {
            "stream_descriptor": {"name": "users"},
            "stream_state": {"uid": config["n_users"]}}}}))
    """
)


def _write_exec_connection(tmp_path, n_users: int) -> str:
    script = tmp_path / "fake_source.py"
    script.write_text(_FAKE_CONNECTOR)
    conn = tmp_path / "connection.yaml"
    conn.write_text(
        "source:\n"
        f"  executable: python {script}\n"
        "  config:\n"
        f"    n_users: {n_users}\n"
    )
    return str(conn)


def test_airbyte_executable_source_e2e(tmp_path):
    conn = _write_exec_connection(tmp_path, 3)
    t = pw.io.airbyte.read(conn, streams=["users"], mode="static")
    cap = GraphRunner().run_tables(t)[0]
    rows = sorted(
        row[0].value["uid"] for row in cap.state.rows.values()
    )
    assert rows == [1, 2, 3]


def test_airbyte_executable_incremental_state(tmp_path):
    """A sync carrying the recorded Airbyte STATE must only deliver new
    rows (the incremental contract the subject's snapshot/seek rides)."""
    conn = _write_exec_connection(tmp_path, 3)
    t = pw.io.airbyte.read(conn, streams=["users"], mode="static")
    cap = GraphRunner().run_tables(t)[0]
    assert len(cap.state.rows) == 3

    from pathway_tpu.io.airbyte import _construct_source

    src = _construct_source(
        {"executable": f"python {tmp_path / 'fake_source.py'}",
         "config": {"n_users": 5}},
        ["users"], None, None, str(tmp_path),
    )
    state = {
        "type": "GLOBAL",
        "global": {"stream_states": [
            {"stream_descriptor": {"name": "users"},
             "stream_state": {"uid": 3}},
        ]},
    }
    uids = [
        m["record"]["data"]["uid"]
        for m in src.extract(state)
        if m.get("type") == "RECORD"
    ]
    assert uids == [4, 5]


# -- declarative manifest source over live HTTP ---------------------------

def _start_api(items):
    """Tiny JSON API: /v1/items?offset=N&limit=M over the live item list."""
    import http.server

    class H(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            from urllib.parse import parse_qs, urlparse

            u = urlparse(self.path)
            if u.path != "/v1/items":
                self.send_response(404)
                self.end_headers()
                return
            q = parse_qs(u.query)
            assert q.get("api_key") == ["sekret"], q  # config interpolation
            offset = int(q.get("offset", ["0"])[0])
            limit = int(q.get("limit", ["3"])[0])
            body = json.dumps(
                {"data": items[offset : offset + limit]}
            ).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.end_headers()
            self.wfile.write(body)

    srv = http.server.HTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


def _manifest(port: int) -> str:
    return textwrap.dedent(
        f"""
        version: "0.1.0"
        streams:
          - name: items
            primary_key: id
            incremental_sync:
              cursor_field: id
            retriever:
              requester:
                url_base: http://127.0.0.1:{port}
                path: /v1/items
                http_method: GET
                request_parameters:
                  api_key: "{{{{ config['api_key'] }}}}"
              record_selector:
                extractor:
                  field_path: ["data"]
              paginator:
                type: OffsetIncrement
                page_size: 3
        """
    )


def test_airbyte_declarative_manifest_e2e(tmp_path):
    items = [{"id": i, "label": f"item{i}"} for i in range(1, 8)]
    srv = _start_api(items)
    try:
        manifest_path = tmp_path / "manifest.yaml"
        manifest_path.write_text(_manifest(srv.server_address[1]))
        conn = tmp_path / "connection.yaml"
        conn.write_text(
            "source:\n"
            "  manifest_path: manifest.yaml\n"
            "  config:\n"
            "    api_key: sekret\n"
        )
        t = pw.io.airbyte.read(str(conn), streams=["items"], mode="static")
        cap = GraphRunner().run_tables(t)[0]
        ids = sorted(r[0].value["id"] for r in cap.state.rows.values())
        assert ids == [1, 2, 3, 4, 5, 6, 7]  # paginated in pages of 3
    finally:
        srv.shutdown()


def test_airbyte_declarative_incremental(tmp_path):
    items = [{"id": i, "label": f"item{i}"} for i in range(1, 5)]
    srv = _start_api(items)
    try:
        from pathway_tpu.internals.yaml_loader import load_yaml
        from pathway_tpu.io._airbyte import DeclarativeAirbyteSource

        manifest = load_yaml(_manifest(srv.server_address[1]))
        src = DeclarativeAirbyteSource(
            manifest, config={"api_key": "sekret"}, streams=["items"]
        )
        msgs = list(src.extract())
        ids = [m["record"]["data"]["id"] for m in msgs if m["type"] == "RECORD"]
        assert ids == [1, 2, 3, 4]
        states = [m["state"] for m in msgs if m["type"] == "STATE"]
        assert states[-1]["stream"]["stream_state"] == {"id": 4}
        # new rows arrive; a sync carrying the state yields only them
        items.extend({"id": i, "label": f"item{i}"} for i in (5, 6))
        state = {
            "type": "GLOBAL",
            "global": {"stream_states": [
                {"stream_descriptor": {"name": "items"},
                 "stream_state": {"id": 4}},
            ]},
        }
        ids2 = [
            m["record"]["data"]["id"]
            for m in src.extract(state)
            if m["type"] == "RECORD"
        ]
        assert ids2 == [5, 6]
    finally:
        srv.shutdown()


def test_airbyte_docker_only_still_gated(tmp_path):
    conn = tmp_path / "connection.yaml"
    conn.write_text(
        "source:\n"
        "  docker_image: airbyte/source-exotic:latest\n"
        "  config: {}\n"
    )
    from pathway_tpu.io._airbyte import AirbyteSourceError

    with pytest.raises((AirbyteSourceError, RuntimeError)):
        pw.io.airbyte.read(
            str(conn), streams=["s"], mode="static", enforce_method="docker"
        )


def test_airbyte_full_refresh_streaming_mirrors_source(tmp_path):
    """Full-refresh (cursor-less) streams under streaming mode must diff
    each sync against the previous snapshot — the table mirrors the
    source instead of accumulating a duplicate copy per refresh
    (reference: io/airbyte/logic.py destination snapshot handling)."""
    items = [{"id": 1}, {"id": 2}]
    srv = _start_api(items)
    try:
        manifest = textwrap.dedent(
            f"""
            streams:
              - name: items
                retriever:
                  requester:
                    url_base: http://127.0.0.1:{srv.server_address[1]}
                    path: /v1/items
                    request_parameters:
                      api_key: sekret
                  record_selector:
                    extractor:
                      field_path: ["data"]
            """
        )
        (tmp_path / "manifest.yaml").write_text(manifest)
        conn = tmp_path / "connection.yaml"
        conn.write_text(
            "source:\n"
            "  manifest_path: manifest.yaml\n"
            "  config: {api_key: sekret}\n"
        )
        t = pw.io.airbyte.read(
            str(conn), streams=["items"], mode="streaming",
            refresh_interval_ms=150,
        )
        rows = {}
        import threading

        runner = None
        phase2 = threading.Event()
        done = threading.Event()

        def on_change(key, row, time_, add):
            if add:
                rows[key] = row["data"].value
            else:
                rows.pop(key, None)
            ids = sorted(r["id"] for r in rows.values())
            if ids == [1, 2] and not phase2.is_set():
                phase2.set()
                items.pop(0)          # source drops id=1 ...
                items.append({"id": 3})  # ... and gains id=3
            elif phase2.is_set() and ids == [2, 3]:
                done.set()

        pw.io.subscribe(t, on_change=on_change)

        import os as _os

        def _run_bg():
            # after the test tears the mock server down, the streaming
            # subject exhausts its retries and pw.run re-raises the
            # connector failure — expected here, and contained so it
            # doesn't surface as an unhandled-thread exception in a
            # later test
            try:
                pw.run(monitoring_level=pw.MonitoringLevel.NONE)
            except Exception:
                if not done.is_set():
                    raise

        runner = threading.Thread(target=_run_bg, daemon=True)
        runner.start()
        assert done.wait(timeout=15), sorted(
            r["id"] for r in rows.values()
        )
    finally:
        srv.shutdown()
        # let the retry loop exhaust and the contained raise land before
        # the next test starts (5 retries x 150 ms refresh)
        if runner is not None:
            runner.join(timeout=10)


# -- authenticators + cursor pagination (VERDICT r4 #7) --------------------

def _start_cursor_api(items, token="tok-123"):
    """JSON API with Bearer auth and cursor pagination: /v2/items returns
    {data: [...], meta: {next: <cursor>}} pages of 2; 401 without auth."""
    import http.server

    class H(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            from urllib.parse import parse_qs, urlparse

            if self.headers.get("Authorization") != f"Bearer {token}":
                self.send_response(401)
                self.end_headers()
                return
            u = urlparse(self.path)
            if u.path != "/v2/items":
                self.send_response(404)
                self.end_headers()
                return
            q = parse_qs(u.query)
            after = int(q.get("after", ["0"])[0])
            page = items[after : after + 2]
            nxt = after + 2 if after + 2 < len(items) else None
            body = json.dumps(
                {"data": page, "meta": {"next": nxt}}
            ).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.end_headers()
            self.wfile.write(body)

    srv = http.server.HTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


def test_declarative_auth_and_cursor_pagination(tmp_path):
    """An authed (Bearer) + CursorPagination manifest in the real
    declarative shape (DefaultPaginator + pagination_strategy +
    page_token_option) syncs all pages e2e through pw.io.airbyte.read."""
    items = [{"id": i} for i in range(1, 8)]  # 4 pages of <=2
    srv = _start_cursor_api(items)
    try:
        manifest = textwrap.dedent(
            f"""
            version: "0.1.0"
            streams:
              - name: items
                primary_key: id
                retriever:
                  requester:
                    url_base: http://127.0.0.1:{srv.server_port}
                    path: /v2/items
                    authenticator:
                      type: BearerAuthenticator
                      api_token: "{{{{ config['api_key'] }}}}"
                  record_selector:
                    extractor:
                      field_path: [data]
                  paginator:
                    type: DefaultPaginator
                    page_token_option:
                      type: RequestOption
                      inject_into: request_parameter
                      field_name: after
                    pagination_strategy:
                      type: CursorPagination
                      cursor_value: "{{{{ response['meta']['next'] }}}}"
                      stop_condition: "{{{{ not response['meta']['next'] }}}}"
            """
        )
        (tmp_path / "manifest.yaml").write_text(manifest)
        conn = tmp_path / "connection.yaml"
        conn.write_text(
            "source:\n"
            "  manifest_path: manifest.yaml\n"
            "  config: {api_key: tok-123}\n"
        )
        import os as _os

        cwd = _os.getcwd()
        _os.chdir(tmp_path)
        try:
            pw.internals.parse_graph.G.clear()
            t = pw.io.airbyte.read(
                str(conn), streams=["items"], mode="static"
            )
        finally:
            _os.chdir(cwd)
        cap = GraphRunner().run_tables(t)[0]
        ids = sorted(
            row[0].value["id"] for row in cap.state.rows.values()
        )
        assert ids == list(range(1, 8))
    finally:
        srv.shutdown()


def test_authenticator_forms():
    from pathway_tpu.io._airbyte import DeclarativeAirbyteSource

    src = DeclarativeAirbyteSource({"streams": []})

    def apply(auth):
        params, headers = {}, {}
        src._apply_auth(auth, params, headers)
        return params, headers

    assert apply(
        {"type": "ApiKeyAuthenticator", "header": "X-K", "api_token": "a"}
    ) == ({}, {"X-K": "a"})
    # request_option.inject_into=request_parameter routes to the query
    assert apply(
        {"type": "ApiKeyAuthenticator", "api_token": "a",
         "request_option": {"inject_into": "request_parameter",
                            "field_name": "api_key"}}
    ) == ({"api_key": "a"}, {})
    assert apply(
        {"type": "BearerAuthenticator", "api_token": "t"}
    ) == ({}, {"Authorization": "Bearer t"})
    import base64

    assert apply(
        {"type": "BasicHttpAuthenticator", "username": "u", "password": "p"}
    ) == ({}, {
        "Authorization": "Basic " + base64.b64encode(b"u:p").decode()
    })
    assert apply({"type": "NoAuth"}) == ({}, {})  # builder default: no-op
    import pytest as _pytest

    with _pytest.raises(ValueError, match="unsupported authenticator"):
        apply({"type": "OAuthAuthenticator"})


def test_cursor_template_resolution():
    from pathway_tpu.io._airbyte import DeclarativeAirbyteSource

    rt = DeclarativeAirbyteSource._resolve_template
    resp = {"meta": {"next": "abc"}, "flat": 7}
    assert rt("{{ response['meta']['next'] }}", resp, None) == "abc"
    assert rt("{{ response.meta.next }}", resp, None) == "abc"
    assert rt("{{ response['flat'] }}", resp, None) == 7
    assert rt("{{ not response['meta']['next'] }}", resp, None) is False
    assert rt("{{ not response['missing'] }}", resp, None) is True
    assert rt("{{ last_record['id'] }}", resp, {"id": 9}) == 9
    assert rt("plain", resp, None) == "plain"


# -- remote execution (generic HTTPS runner; reference remote mode runs
# on GCP Cloud Run — io/airbyte/__init__.py execution_type="remote") -----


class _MockRunner:
    """One-endpoint Airbyte runner: answers POST /extract with scripted
    JSON-line messages and records each request body."""

    def __init__(self, pages, require_token=None):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        runner = self

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self):
                n = int(self.headers.get("Content-Length", "0"))
                body = json.loads(self.rfile.read(n))
                runner.requests.append(
                    {
                        "path": self.path,
                        "auth": self.headers.get("Authorization"),
                        "body": body,
                    }
                )
                if (
                    runner.require_token is not None
                    and self.headers.get("Authorization")
                    != f"Bearer {runner.require_token}"
                ):
                    msg = b"unauthorized"
                    self.send_response(401)
                    self.send_header("Content-Length", str(len(msg)))
                    self.end_headers()
                    self.wfile.write(msg)
                    return
                page = runner.pages[min(len(runner.requests) - 1,
                                        len(runner.pages) - 1)]
                payload = "\n".join(json.dumps(m) for m in page).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/x-ndjson")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def log_message(self, *a):
                pass

        self.pages = pages
        self.require_token = require_token
        self.requests = []
        self.server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        threading.Thread(
            target=self.server.serve_forever, daemon=True
        ).start()
        self.url = f"http://127.0.0.1:{self.server.server_port}"

    def stop(self):
        self.server.shutdown()


def _remote_connection_file(tmp_path, runner_url, token=None):
    cfg = [
        "source:",
        '  docker_image: "airbyte/source-faker:0.1.4"',
        "  config:",
        "    count: 3",
        "remote_runner:",
        f"  url: {runner_url}",
    ]
    if token:
        cfg.append(f"  token: {token}")
    path = tmp_path / "remote.yaml"
    path.write_text("\n".join(cfg) + "\n")
    return str(path)


def test_airbyte_remote_execution_e2e(tmp_path):
    page = [
        {"type": "RECORD",
         "record": {"stream": "users", "data": {"id": 1, "name": "ann"}}},
        {"type": "RECORD",
         "record": {"stream": "users", "data": {"id": 2, "name": "bob"}}},
        {"type": "STATE",
         "state": {"type": "STREAM",
                   "stream": {"stream_descriptor": {"name": "users"},
                              "stream_state": {"id": 2}}}},
    ]
    runner = _MockRunner([page])
    try:
        t = pw.io.airbyte.read(
            _remote_connection_file(tmp_path, runner.url),
            streams=["users"],
            mode="static",
            execution_type="remote",
        )
        captures = GraphRunner().run_tables(t)
        rows = [
            json.loads(str(r[0])) if isinstance(r[0], str) else r[0].value
            for r in captures[0].state.rows.values()
        ]
        got_ids = sorted(r["id"] for r in rows)
        assert got_ids == [1, 2]
        # the runner received the source config and stream list
        body = runner.requests[0]["body"]
        assert body["source"]["docker_image"].startswith("airbyte/")
        assert body["streams"] == ["users"]
        assert body["state"] is None
    finally:
        runner.stop()


def test_airbyte_remote_carries_state_between_syncs(tmp_path):
    from pathway_tpu.io._airbyte import RemoteAirbyteSource

    pages = [
        [
            {"type": "RECORD",
             "record": {"stream": "s", "data": {"id": 1}}},
            {"type": "STATE",
             "state": {"type": "LEGACY", "data": {"cursor": 10}}},
        ],
        [
            {"type": "RECORD",
             "record": {"stream": "s", "data": {"id": 2}}},
        ],
    ]
    runner = _MockRunner(pages)
    try:
        src = RemoteAirbyteSource(runner.url, {"docker_image": "x"}, ["s"])
        first = list(src.extract(None))
        assert [m["type"] for m in first] == ["RECORD", "STATE"]
        list(src.extract({"cursor": 10}))
        assert runner.requests[1]["body"]["state"] == {"cursor": 10}
    finally:
        runner.stop()


def test_airbyte_remote_auth_token_and_reject(tmp_path):
    from pathway_tpu.io._airbyte import AirbyteSourceError, RemoteAirbyteSource

    page = [{"type": "RECORD", "record": {"stream": "s", "data": {}}}]
    runner = _MockRunner([page], require_token="sekrit")
    try:
        good = RemoteAirbyteSource(
            runner.url, {"docker_image": "x"}, ["s"], token="sekrit"
        )
        assert len(list(good.extract(None))) == 1
        assert runner.requests[-1]["auth"] == "Bearer sekrit"
        bad = RemoteAirbyteSource(
            runner.url, {"docker_image": "x"}, ["s"], token="wrong"
        )
        with pytest.raises(AirbyteSourceError, match="HTTP 401"):
            list(bad.extract(None))
    finally:
        runner.stop()


def test_airbyte_remote_trace_error_aborts(tmp_path):
    from pathway_tpu.io._airbyte import AirbyteSourceError, RemoteAirbyteSource

    page = [
        {"type": "TRACE",
         "trace": {"type": "ERROR", "error": {"message": "quota exceeded"}}},
    ]
    runner = _MockRunner([page])
    try:
        src = RemoteAirbyteSource(runner.url, {"docker_image": "x"}, ["s"])
        with pytest.raises(AirbyteSourceError, match="quota exceeded"):
            list(src.extract(None))
    finally:
        runner.stop()


def test_airbyte_remote_requires_runner_url(tmp_path):
    path = tmp_path / "local_only.yaml"
    path.write_text(
        'source:\n  docker_image: "airbyte/source-faker:0.1.4"\n'
        "  config:\n    count: 1\n"
    )
    with pytest.raises(ValueError, match="remote_runner_url"):
        pw.io.airbyte.read(
            str(path), streams=["s"], execution_type="remote"
        )


def test_cli_airbyte_create_source(tmp_path):
    from pathway_tpu.cli import main

    target = tmp_path / "connections" / "github"
    rc = main(
        ["airbyte", "create-source", str(target),
         "--image", "airbyte/source-github:1.0.0"]
    )
    assert rc == 0
    written = (tmp_path / "connections" / "github.yaml").read_text()
    assert "airbyte/source-github:1.0.0" in written
    assert "docker_image" in written
    # the scaffold must load through the same loader read() uses
    from pathway_tpu.io.airbyte import _load_connection

    cfg = _load_connection(str(tmp_path / "connections" / "github.yaml"))
    assert cfg["source"]["docker_image"] == "airbyte/source-github:1.0.0"
    # refusing to clobber an existing file
    rc2 = main(["airbyte", "create-source", str(target)])
    assert rc2 == 1
