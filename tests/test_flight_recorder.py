"""Flight recorder battery (ISSUE 8): trace schema, 2-rank merge,
hot-path blame, metrics-registry drift pin, /healthz, OTLP drain,
event-time lag watermarks, dashboard unification, native ring."""

import json
import os
import threading
import urllib.request

import pytest

import pathway_tpu as pw
from pathway_tpu.analysis.profile import (
    profile_trace,
    render_profile,
    validate_trace,
)
from pathway_tpu.internals.monitoring import (
    ProberStats,
    ServeMetrics,
    render_dashboard,
    start_http_server,
)


def _wordcount(n_rows=3000, batches=3, distinct=40):
    class Source(pw.io.python.ConnectorSubject):
        _deletions_enabled = False

        def run(self):
            per = n_rows // batches
            for b in range(batches):
                self.next_batch(
                    [
                        {"data": f"w{i % distinct}"}
                        for i in range(b * per, (b + 1) * per)
                    ]
                )
                self.commit()

    class S(pw.Schema):
        data: str

    t = pw.io.python.read(Source(), schema=S, autocommit_duration_ms=None)
    counts = t.groupby(pw.this.data).reduce(
        word=pw.this.data, c=pw.reducers.count()
    )
    seen = []
    pw.io.subscribe(counts, on_change=lambda *a: seen.append(1))
    return seen


def _run_traced(tmp_path, monkeypatch, name="trace.json", lane=None):
    path = str(tmp_path / name)
    monkeypatch.setenv("PATHWAY_TRACE", path)
    if lane is not None:
        monkeypatch.setenv("PATHWAY_LANE_PROCESSES", str(lane))
    _wordcount()
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    return path


# -- trace schema --------------------------------------------------------

def test_trace_single_rank_schema(tmp_path, monkeypatch):
    monkeypatch.delenv("PATHWAY_LANE_PROCESSES", raising=False)
    path = _run_traced(tmp_path, monkeypatch)
    doc = json.load(open(path))
    assert validate_trace(doc) == []
    evs = doc["traceEvents"]
    cats = {e.get("cat") for e in evs}
    assert {"node", "step", "lag"} <= cats
    # node spans carry the args the blame pass joins on
    node_evs = [e for e in evs if e.get("cat") == "node"]
    assert node_evs
    for e in node_evs:
        assert {"node", "rows", "rep"} <= set(e["args"])
        assert e["args"]["rep"] in ("nb", "tuple")
    # per-node self-times sum to <= the step-span wall time (process()
    # is the node's self-time; steps bracket all node work at a commit)
    self_us = sum(e["dur"] for e in node_evs)
    step_us = sum(e["dur"] for e in evs if e.get("cat") == "step")
    assert 0 < self_us <= step_us * 1.001
    # spans nest: every node span sits inside some step span
    steps = [
        (e["ts"], e["ts"] + e["dur"])
        for e in evs
        if e.get("cat") == "step"
    ]
    eps = 2e-3
    for e in node_evs:
        assert any(
            t0 - eps <= e["ts"] and e["ts"] + e["dur"] <= t1 + eps
            for t0, t1 in steps
        ), "node span outside every step span"
    # plan metadata is embedded for the blame join: verdicts come from
    # the SAME NBDecision objects the executor gates on
    nodes = doc["pathway"]["nodes"]
    assert any(m.get("verdict") == "fused" for m in nodes.values())
    assert any(m.get("row_expanding") for m in nodes.values())
    # event-time lag watermarks: non-negative freshness per output
    lags = [e for e in evs if e.get("cat") == "lag"]
    assert lags and all(e["args"]["lag_ms"] >= 0 for e in lags)


def test_trace_two_rank_merged(tmp_path, monkeypatch):
    path = _run_traced(tmp_path, monkeypatch, lane=2)
    doc = json.load(open(path))
    assert validate_trace(doc) == []
    assert doc["pathway"]["merged_ranks"] == [0, 1]
    evs = doc["traceEvents"]
    assert {e["pid"] for e in evs} == {0, 1}
    cats = {e.get("cat") for e in evs}
    assert {"wave", "mesh", "mark"} <= cats
    marks = {e["name"] for e in evs if e.get("cat") == "mark"}
    assert "mesh_join" in marks
    # per-track monotonic timestamps (the offset shift must not reorder
    # a rank's track) — validate_trace pins this, assert it directly too
    last = {}
    for e in evs:
        if e.get("ph") == "M":
            continue
        key = (e["pid"], e["tid"])
        assert e["ts"] >= last.get(key, float("-inf")) - 2e-3
        last[key] = e["ts"]
    # merge consumed the partials
    assert not os.path.exists(path + ".r0")
    assert not os.path.exists(path + ".r1")
    # clock offsets were sampled during the epoch's clock handshake
    meta = doc["pathway"]["rank_meta"]
    assert meta["rank0"]["clock_offset_ns"] == 0
    assert "clock_offset_ns" in meta["rank1"]


def test_no_trace_file_without_knob(tmp_path, monkeypatch):
    monkeypatch.delenv("PATHWAY_TRACE", raising=False)
    monkeypatch.delenv("PATHWAY_LANE_PROCESSES", raising=False)
    _wordcount(n_rows=200, batches=1)
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    assert list(tmp_path.iterdir()) == []


# -- hot-path blame (analysis --profile) ---------------------------------

def test_profile_names_top_node_with_verdict(tmp_path, monkeypatch):
    monkeypatch.delenv("PATHWAY_LANE_PROCESSES", raising=False)
    path = _run_traced(tmp_path, monkeypatch)
    report = profile_trace(path, top_k=3)
    assert report["valid"], report["problems"]
    assert report["top"]
    labels = {r["label"] for r in report["top"]}
    assert any("GroupByNode" in lb for lb in labels)
    verdicts = {r["label"]: r["verdict"] for r in report["top"]}
    gb = next(lb for lb in labels if "GroupByNode" in lb)
    assert verdicts[gb] == "fused"
    sink = [r for r in report["top"] if "sink" in r["verdict"]]
    assert sink, "row-expanding sink not named"
    text = render_profile(report)
    assert "top nodes by self-time" in text and "fused" in text


def test_profile_cli_exit_codes(tmp_path, monkeypatch):
    monkeypatch.delenv("PATHWAY_LANE_PROCESSES", raising=False)
    path = _run_traced(tmp_path, monkeypatch)
    from pathway_tpu.analysis.__main__ import main as cli_main

    assert cli_main(["--profile", path]) == 0
    bad = tmp_path / "bad.json"
    bad.write_text("{}")
    assert cli_main(["--profile", str(bad)]) == 2


def test_profile_flags_measured_degradation(tmp_path):
    """A node whose static verdict says fused but whose batches executed
    on the tuple path is a MEASURED degradation — the blame pass must
    say so instead of parroting the static verdict."""
    doc = {
        "traceEvents": [
            {
                "name": "GroupByNode#1", "cat": "node", "ph": "X",
                "pid": 0, "tid": 0, "ts": 10.0, "dur": 5.0,
                "args": {"node": 1, "t": 1, "rows": 10, "rep": "tuple"},
            },
        ],
        "pathway": {
            "schema": 1,
            "nodes": {"1": {"label": "GroupByNode#1", "verdict": "fused"}},
        },
    }
    p = tmp_path / "t.json"
    p.write_text(json.dumps(doc))
    report = profile_trace(str(p))
    assert "degraded at runtime" in report["top"][0]["verdict"]


# -- metrics registry drift pin ------------------------------------------

# every ProberStats on_* hook must surface in render_openmetrics() (or be
# explicitly exempted as dashboard-only state). A NEW hook without an
# entry here fails the completeness assertion — the knob-registry
# pattern applied to the metrics surface.
_PROBER_CALLS = {
    "on_ingest": ("conn_a", 5),
    "on_connector_restart": ("conn_a",),
    "on_connector_error": ("conn_a",),
    "on_connector_stall": ("conn_a",),
    "on_connector_degraded": ("conn_a",),
    # source pacing (ISSUE 19): gate engaged / live per-pass accrual /
    # episode closed — connector_paused gauge + paused_seconds counter
    "on_connector_paused": ("conn_a",),
    "on_connector_paced": ("conn_a", 1.5),
    "on_connector_resumed": ("conn_a", 0.5),
    "on_output": (3,),
    "on_output_lag": ("out_a", 5.0),
    "on_node_step": ("node_a", 0.25, 7, True),
    "on_exchange_frame": (128,),
    "on_exchange_elided": (2,),
    "on_exchange_fallback": (),
    "on_nb_fallback": (),
    "on_exchange_step": (0.1, 0.2),
    # cluster observability (ISSUE 10): per-peer recv-wait, wave
    # counters, and main-loop idle seconds
    "on_exchange_recv_wait": (1, 0.25),
    "on_exchange_wave": (0.5,),
    # fast wire (ISSUE 13): per-frame bytes before/after the wire codec
    # (exchange_{un,}compressed_bytes_total + the per-peer matrix)
    "on_exchange_compression": (1, 4096, 1024),
    "on_idle": (0.3,),
    "on_mesh_heartbeat_missed": (),
    "on_mesh_rank_restart": (),
    "on_mesh_rollback": (),
    "on_mesh_epoch_committed": (4,),
    # transactional egress (ISSUE 12): 2PC sink counters + epoch lag
    "on_sink_staged": ("sink_a",),
    "on_sink_finalized": ("sink_a", 2),
    "on_sink_aborted": ("sink_a", 1),
    "on_sink_recovered": ("sink_a", 1),
    "on_sink_epoch_lag": ("sink_a", 3),
    # columnar egress (ISSUE 14): arrow-delivered vs row-expanded rows
    # at the sinks + per-sink egress seconds
    "on_capture_arrow_batch": (7,),
    "on_capture_rows_expanded": (7,),
    "on_sink_egress_seconds": ("sink_a", 0.05),
    # device plane (ISSUE 15): per-dispatch-site device/wall seconds,
    # FLOPs, transfer bytes and queue depth — the device_* families.
    # Trailing arg (ISSUE 16): effective FLOPs (real rows only).
    "on_device_dispatch": (
        "knn.search", 0.5, 0.4, 1e9, 1e6, 4096, 2, 8e8,
    ),
    # shape-bucket churn visibility (ISSUE 16): fresh XLA compilations
    # per dispatch site — device_recompiles_total
    "on_device_recompile": ("encoder.forward",),
    # device fault domain (ISSUE 17): dispatch supervision verdicts,
    # watchdog trips, HBM-growth OOM refusals, and the epoch-aligned
    # index snapshot/restore accounting
    "on_device_dispatch_retry": ("knn.search",),
    "on_device_dispatch_failure": ("knn.search",),
    "on_device_watchdog_trip": ("knn.search",),
    "on_device_oom": ("knn.grow",),
    "on_index_restore_seconds": (1.5,),
    "on_index_snapshot_bytes": (4096,),
    "on_index_filter_error": (2,),
}
# state consumed by the dashboard/main loop, not an OpenMetrics family
_PROBER_EXEMPT = {"on_connector_finished"}

_SERVE_CALLS = {
    "on_request": (),
    "on_shed": (),
    "on_timeout": (),
    "on_latency_ms": (12.5,),
    "on_window": (4,),
    # serving-through-rollback (ISSUE 9): brownout answers and windows
    # aborted at an epoch rollback
    "on_brownout": (),
    "on_windows_aborted": (2,),
}


def test_metrics_registry_every_hook_renders():
    hooks = {
        n for n in dir(ProberStats)
        if n.startswith("on_") and callable(getattr(ProberStats, n))
    }
    assert hooks == set(_PROBER_CALLS) | _PROBER_EXEMPT, (
        "new ProberStats on_* hook: map it to a rendered OpenMetrics "
        "family in _PROBER_CALLS (or exempt it with a reason)"
    )
    for name, args in _PROBER_CALLS.items():
        stats = ProberStats()
        before = stats.render_openmetrics()
        getattr(stats, name)(*args)
        after = stats.render_openmetrics()
        assert after != before, (
            f"{name} incremented state that render_openmetrics() never "
            "surfaces — silent metrics drift"
        )
    serve_hooks = {
        n for n in dir(ServeMetrics)
        if n.startswith("on_") and callable(getattr(ServeMetrics, n))
    }
    assert serve_hooks == set(_SERVE_CALLS)
    for name, args in _SERVE_CALLS.items():
        stats = ProberStats()
        sm = ServeMetrics(route="/v1/q")
        stats.mount_serve_metrics(sm)
        before = stats.render_openmetrics()
        getattr(sm, name)(*args)
        assert stats.render_openmetrics() != before, name


def test_openmetrics_every_family_has_a_sample():
    stats = ProberStats()
    for name, args in _PROBER_CALLS.items():
        getattr(stats, name)(*args)
    sm = ServeMetrics(route="/v1/q")
    stats.mount_serve_metrics(sm)
    for name, args in _SERVE_CALLS.items():
        getattr(sm, name)(*args)
    text = stats.render_openmetrics()
    lines = text.splitlines()
    for i, line in enumerate(lines):
        if not line.startswith("# TYPE "):
            continue
        family = line.split()[2]
        rest = lines[i + 1:]
        assert any(
            ln.startswith(family) for ln in rest if not ln.startswith("#")
        ), f"family {family} declared but has no sample"
    # the new node/lag families render with their labels
    assert 'node_self_seconds_total{node="node_a"}' in text
    assert 'node_rows_total{node="node_a"} 7' in text
    assert 'output_lag_ms_bucket{output="out_a",le="5"}' in text


# -- /healthz + log silence ----------------------------------------------

def test_http_server_healthz_and_metrics():
    import socket

    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    port = probe.getsockname()[1]
    probe.close()
    stats = ProberStats()
    stats.on_ingest("c1", 3)
    start_http_server(stats, port)
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/healthz", timeout=5
    ) as r:
        assert r.status == 200
        assert r.read() == b"ok\n"
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=5
    ) as r:
        body = r.read().decode()
        assert 'connector_rows_total{connector="c1"} 3' in body


# -- OTLP flush-on-shutdown ----------------------------------------------

def test_otlp_drain_exports_buffered_spans_and_gauges():
    """Short runs must not exit with spans queued and gauges never
    pushed (the periodic thread is on a 60 s cadence): drain() pushes
    both, including the flight recorder's per-node aggregate spans."""
    import http.server

    received = []

    class Collector(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            n = int(self.headers["Content-Length"])
            received.append((self.path, json.loads(self.rfile.read(n))))
            self.send_response(200)
            self.end_headers()

        def log_message(self, *a):
            pass

    srv = http.server.HTTPServer(("127.0.0.1", 0), Collector)
    port = srv.server_address[1]
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        from pathway_tpu.internals.otlp import OtlpTelemetry

        tel = OtlpTelemetry(
            f"http://127.0.0.1:{port}", autostart_metrics=False
        )
        with tel.span("graph_runner.run"):
            pass
        t0 = 1_700_000_000_000_000_000
        tel.drain(
            node_spans=[
                {
                    "name": "node.GroupByNode#1",
                    "start_ns": t0,
                    "end_ns": t0 + 5_000_000,
                    "attrs": {"node.self_s": 0.005, "node.rows": 100},
                }
            ]
        )
    finally:
        srv.shutdown()
    paths = [p for p, _ in received]
    assert "/v1/metrics" in paths, "drain did not push gauges"
    span_names = [
        s["name"]
        for p, b in received
        if p == "/v1/traces"
        for rs in b["resourceSpans"]
        for ss in rs["scopeSpans"]
        for s in ss["spans"]
    ]
    assert "graph_runner.run" in span_names
    assert "node.GroupByNode#1" in span_names


# -- event-time lag watermarks (no recorder needed) ----------------------

def test_lag_watermark_populates_stats_without_tracing(monkeypatch):
    monkeypatch.delenv("PATHWAY_TRACE", raising=False)
    monkeypatch.delenv("PATHWAY_LANE_PROCESSES", raising=False)
    from pathway_tpu.internals.graph_runner import GraphRunner

    class Source(pw.io.python.ConnectorSubject):
        _deletions_enabled = False

        def run(self):
            self.next_batch([{"data": f"w{i % 10}"} for i in range(500)])
            self.commit()

    class S(pw.Schema):
        data: str

    t = pw.io.python.read(Source(), schema=S, autocommit_duration_ms=None)
    counts = t.groupby(pw.this.data).reduce(
        word=pw.this.data, c=pw.reducers.count()
    )
    pw.io.subscribe(counts, on_change=lambda *a: None)
    runner = GraphRunner()
    runtime = runner._make_runtime()
    ops = runner.graph.reachable_operators(runner.graph.output_operators())
    runner._lower(ops, runtime)
    runtime.run()
    assert runtime.stats.lag, "no output lag histogram recorded"
    (label, h), = list(runtime.stats.lag.items())
    assert "OutputNode" in label
    assert h.total >= 1 and h.sum >= 0.0
    text = runtime.stats.render_openmetrics()
    assert "output_lag_ms_count" in text


# -- dashboard unification ------------------------------------------------

def test_dashboard_covers_whole_pipeline():
    from rich.console import Console

    stats = ProberStats()
    stats.on_ingest("kafka:orders", 10)
    stats.on_exchange_frame(4096)
    stats.on_exchange_elided(3)
    stats.on_exchange_step(0.5, 1.5)
    stats.on_nb_fallback()
    stats.on_mesh_heartbeat_missed()
    stats.on_mesh_rollback()
    stats.on_mesh_epoch_committed(2)
    stats.on_output_lag("OutputNode#4", 12.0)
    stats.on_node_step("GroupByNode#2", 1.25, 9000, True)
    sm = ServeMetrics(route="/v1/retrieve")
    sm.on_request()
    sm.on_window(8)
    stats.mount_serve_metrics(sm)

    console = Console(record=True, width=120)
    console.print(render_dashboard(stats))
    text = console.export_text()
    assert "exchange frames/bytes" in text
    assert "nb_fallbacks" in text
    assert "mesh hb-missed/restarts/rollbacks" in text
    assert "serve /v1/retrieve" in text
    assert "event-time lag" in text
    assert "hot GroupByNode#2" in text


def test_profile_survives_malformed_node_events(tmp_path):
    """A truncated/foreign trace with a node event missing args must
    land on the documented exit-2 schema-problem path, not a KeyError
    traceback (review fix)."""
    doc = {
        "traceEvents": [
            {"name": "x", "cat": "node", "ph": "X", "pid": 0, "tid": 0,
             "ts": 1.0, "dur": 1.0},
        ],
        "pathway": {"schema": 1, "nodes": {}},
    }
    p = tmp_path / "t.json"
    p.write_text(json.dumps(doc))
    report = profile_trace(str(p))
    assert not report["valid"]
    assert any("missing node/rows/rep" in pr for pr in report["problems"])
    from pathway_tpu.analysis.__main__ import main as cli_main

    assert cli_main(["--profile", str(p)]) == 2


def test_recorder_event_cap_keeps_newest(tmp_path, monkeypatch):
    """PATHWAY_TRACE_MAX_EVENTS bounds the in-memory log of a
    long-running traced pipeline: newest events are kept, the dump
    records the capping (review fix — unbounded growth until OOM)."""
    monkeypatch.setenv("PATHWAY_TRACE_MAX_EVENTS", "10000")
    from pathway_tpu.internals.flight import FlightRecorder

    rec = FlightRecorder(str(tmp_path / "t.json"))
    assert rec.max_events == 10_000
    for i in range(25_000):
        rec.note_node(1, i, i, i + 1, 1, True)
    assert len(rec.events) == 10_000
    # newest survive
    assert rec.events[-1][2] == 24_999 and rec.events[0][2] == 15_000
    rec.dump(scope=None)
    doc = json.load(open(rec.path))
    assert doc["pathway"]["capped"] is True
    assert doc["pathway"]["event_cap"] == 10_000


# -- supervisor fallback merge -------------------------------------------

def test_supervisor_merges_leftover_partials(tmp_path, monkeypatch):
    """After a rollback the aborting epoch's partials (with their
    rollback marks) may outlive rank 0's merge — the MeshSupervisor
    re-merges them on its way out."""
    from pathway_tpu.internals.flight import FlightRecorder
    from pathway_tpu.parallel.supervisor import MeshSupervisor

    path = str(tmp_path / "t.json")
    for rank in range(2):
        rec = FlightRecorder(path, rank=rank, world=2)
        rec.note_mark("rollback", error="MeshPeerFailure('peer 1')")
        rec.dump_partial(scope=None)
    monkeypatch.setenv("PATHWAY_TRACE", path)
    sup = MeshSupervisor(["true"], processes=2)
    sup._merge_trace_fallback()
    doc = json.load(open(path))
    assert doc["pathway"]["merged_ranks"] == [0, 1]
    marks = [
        e for e in doc["traceEvents"] if e.get("name") == "rollback"
    ]
    assert len(marks) == 2
    assert not os.path.exists(path + ".r0")


# -- native ring ----------------------------------------------------------

def test_native_trace_ring_direct():
    from pathway_tpu.native import get_pwexec

    ex = get_pwexec()
    if ex is None or not hasattr(ex, "trace_ring_enable"):
        pytest.skip("native toolchain unavailable")
    try:
        ex.trace_ring_enable(2048, 4)
        from pathway_tpu.internals.api import Pointer

        nb = ex.nb_decode(ex.nb_encode(_make_nb(ex)), Pointer)
        assert len(nb) == 3
        evs = ex.trace_ring_drain()
        assert evs, "encode/decode produced no ring events"
        tags = {tag for tag, *_ in evs}
        assert {4, 5} <= tags  # nb_encode + nb_decode
        for _tag, thr, t0, t1, _rows in evs:
            assert t1 >= t0 >= 0 and thr >= 0
        assert ex.trace_ring_drain() == []  # drain resets
    finally:
        ex.trace_ring_disable()


def _make_nb(ex):
    from pathway_tpu.internals.api import Pointer

    nb = ex.parse_upserts_nb(
        [
            {"a": 1, "b": "x"},
            {"a": 2, "b": "y"},
            {"a": 3, "b": "z"},
        ],
        0,
        ("a", "b"),
        (None, None),
        12345,  # int128 key base, like io/python.py's minted key_base
        0,
        Pointer,
    )
    assert nb is not None
    return nb[0]
