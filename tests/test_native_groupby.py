"""Sharded native group-by executor (native/exec.cpp) correctness.

The C++ path must be output-identical to the Python affected-group rediff
path (same deltas modulo ordering), migrate its state losslessly when a
batch contains values it can't represent, and round-trip operator
snapshots. Reference semantics: semigroup reducers, src/engine/reduce.rs.
"""

from __future__ import annotations

import numpy as np
import pytest

import pathway_tpu as pw
from pathway_tpu.internals.api import ERROR, ref_scalar
from pathway_tpu.native import get_pwexec

pwexec = get_pwexec()


def _pb(store, gvals, valcols, diffs, key_fn, error):
    """Shim for the executor's signature: process_batch also takes the
    per-row engine keys (joint-multiset identity for min/max stores)."""
    return pwexec.process_batch(
        store, gvals, list(range(len(gvals))), valcols, diffs, key_fn, error
    )
pytestmark = pytest.mark.skipif(pwexec is None, reason="no native toolchain")


def _run_wordsum(monkeypatch, force_python: bool):
    if force_python:
        import pathway_tpu.engine.nodes as nodes_mod

        monkeypatch.setattr(
            "pathway_tpu.native.get_pwexec", lambda: None
        )
    t = pw.debug.table_from_markdown(
        """
        w     | v
        apple | 1
        pear  | 2
        apple | 3
        plum  | 5
        pear  | 2
        """
    )
    r = t.groupby(pw.this.w).reduce(
        w=pw.this.w,
        n=pw.reducers.count(),
        s=pw.reducers.sum(pw.this.v),
        a=pw.reducers.avg(pw.this.v),
    )
    rows = pw.debug.table_to_pandas(r)
    return sorted(
        (row.w, row.n, row.s, row.a) for row in rows.itertuples()
    )


def test_native_matches_python_path(monkeypatch):
    native = _run_wordsum(monkeypatch, force_python=False)
    monkeypatch.undo()
    python = _run_wordsum(monkeypatch, force_python=True)
    assert native == python == [
        ("apple", 2, 4, 2.0),
        ("pear", 2, 4, 2.0),
        ("plum", 1, 5, 5.0),
    ]


def test_executor_retraction_and_deletion():
    s = pwexec.store_new(4, ("count", "sum"))
    key_fn = lambda g: ref_scalar(*g)
    out = _pb(
        s, [("a",), ("a",)], (None, [3, 4]), [1, 1], key_fn, ERROR
    )
    assert [(r, d) for _, r, d in out] == [(("a", 2, 7), 1)]
    # retract both rows -> group dies, only the retraction is emitted
    out = _pb(
        s, [("a",), ("a",)], (None, [3, 4]), [-1, -1], key_fn, ERROR
    )
    assert [(r, d) for _, r, d in out] == [(("a", 2, 7), -1)]
    assert pwexec.store_len(s) == 0


def test_executor_none_error_and_float_promotion():
    s = pwexec.store_new(2, ("sum",))
    key_fn = lambda g: ref_scalar(*g)
    # None args don't contribute; float promotes the sum
    out = _pb(
        s, [("g",), ("g",), ("g",)], ([1, None, 2.5],), [1, 1, 1], key_fn, ERROR
    )
    assert [(r, d) for _, r, d in out] == [(("g", 3.5), 1)]
    # ERROR poisons
    out = _pb(s, [("g",)], ([ERROR],), [1], key_fn, ERROR)
    (_, row, d) = out[-1]
    assert row[1] is ERROR and d == 1
    # retracting the error heals the sum
    out = _pb(s, [("g",)], ([ERROR],), [-1], key_fn, ERROR)
    assert out[-1][1] == ("g", 3.5) and out[-1][2] == 1


def test_numeric_group_normalization():
    """True == 1 == 1.0 must land in ONE group (Python dict-key parity)."""
    s = pwexec.store_new(3, ("count",))
    key_fn = lambda g: ref_scalar(*g)
    out = _pb(
        s, [(1,), (1.0,), (True,)], (None,), [1, 1, 1], key_fn, ERROR
    )
    assert pwexec.store_len(s) == 1
    assert [(r[1], d) for _, r, d in out] == [(3, 1)]


def test_midstream_fallback_migration():
    """A batch with an unsupported grouping value demotes the node to the
    Python path with state intact."""
    import pathway_tpu.engine.nodes as nodes_mod
    from pathway_tpu.engine.stream import freeze_row

    class FakeScope:
        def __init__(self):
            self.nodes = []
            self.runtime = type(
                "R", (), {"mark_pending": lambda *a: None,
                          "current_trace": None}
            )()

        def register(self, node):
            self.nodes.append(node)
            return len(self.nodes) - 1

    scope = FakeScope()
    src = nodes_mod.SourceNode(scope)
    specs = [("abelian", lambda s, c, d: s + d, lambda s: s, 0, "count")]
    node = nodes_mod.GroupByNode(
        scope, src,
        grouping_fn=lambda k, r: (r[0],),
        args_fn=lambda k, r: ((k,),),
        reducer_specs=specs,
        grouping_batch=lambda ks, rs: [(r[0],) for r in rs],
        args_batch=lambda ks, rs: [((k,),) for k in ks],
        native_args=[None],
    )
    assert node._native_ok
    out1 = node.process(2, [[(1, ("x",), 1), (2, ("x",), 1)]])
    assert node._store is not None
    # tuple grouping value -> Fallback -> migrate, replay via Python path
    out2 = node.process(4, [[(3, (("t", 1),), 1), (4, ("x",), 1)]])
    assert node._store is None and not node._native_ok
    rows = {tuple(r): d for _, r, d in out2}
    assert rows[("x", 3)] == 1 and rows[("x", 2)] == -1
    assert rows[(("t", 1), 1)] == 1
    # python path continues from migrated state
    out3 = node.process(6, [[(5, ("x",), -1)]])
    rows3 = {tuple(r): d for _, r, d in out3}
    assert rows3[("x", 2)] == 1 and rows3[("x", 3)] == -1


def test_native_snapshot_roundtrip():
    import pathway_tpu.engine.nodes as nodes_mod

    class FakeScope:
        def __init__(self):
            self.nodes = []
            self.runtime = type(
                "R", (), {"mark_pending": lambda *a: None,
                          "current_trace": None}
            )()

        def register(self, node):
            self.nodes.append(node)
            return len(self.nodes) - 1

    def make_node(scope):
        src = nodes_mod.SourceNode(scope)
        specs = [
            ("abelian", lambda s, c, d: s + d, lambda s: s, 0, "count"),
        ]
        return nodes_mod.GroupByNode(
            scope, src,
            grouping_fn=lambda k, r: (r[0],),
            args_fn=lambda k, r: ((k,),),
            reducer_specs=specs,
            grouping_batch=lambda ks, rs: [(r[0],) for r in rs],
            args_batch=lambda ks, rs: [((k,),) for k in ks],
            native_args=[None],
        )

    import pickle

    a = make_node(FakeScope())
    a.process(2, [[(1, ("x",), 1), (2, ("y",), 1), (3, ("x",), 1)]])
    state = pickle.loads(pickle.dumps(a.state_dict()))
    assert "__native__" in state

    b = make_node(FakeScope())
    b.load_state(state)
    out = b.process(4, [[(9, ("x",), 1)]])
    rows = {tuple(r): d for _, r, d in out}
    assert rows[("x", 3)] == 1 and rows[("x", 2)] == -1


def test_bigint_sum_exact():
    """i64-overflowing accumulations stay exact (review: wrapping isum)."""
    s = pwexec.store_new(2, ("sum",))
    key_fn = lambda g: ref_scalar(*g)
    v = 2**62
    out = _pb(
        s, [("g",)] * 3, ([v, v, v],), [1, 1, 1], key_fn, ERROR
    )
    assert out[-1][1] == ("g", 3 * 2**62)
    # dump/load roundtrip preserves the big value
    d = pwexec.store_dump(s)
    s2 = pwexec.store_new(2, ("sum",))
    pwexec.store_load(s2, d)
    out = _pb(s2, [("g",)], ([1],), [1], key_fn, ERROR)
    assert out[-1][1] == ("g", 3 * 2**62 + 1)


def test_unchanged_output_emits_nothing():
    """A batch that moves state without moving the finished value emits no
    deltas (review: spurious retract/insert pairs leaked to subscribers)."""
    s = pwexec.store_new(2, ("sum", "avg"))
    key_fn = lambda g: ref_scalar(*g)
    _pb(s, [("g",)], ([5], [2.0]), [1], key_fn, ERROR)
    # value-0 row: sum unchanged; arriving avg value equals current mean
    out = _pb(s, [("g",)], ([0], [2.0]), [1], key_fn, ERROR)
    assert out == []
    # count would change though
    s2 = pwexec.store_new(2, ("count",))
    _pb(s2, [("g",)], (None,), [1], key_fn, ERROR)
    out = _pb(s2, [("g",)], (None,), [1], key_fn, ERROR)
    assert len(out) == 2


def test_same_schema_sources_distinct_keys():
    """Two keyless sources sharing a schema must mint disjoint row ids
    (review: concat of same-schema streams collided)."""
    class Subj(pw.io.python.ConnectorSubject):
        def __init__(self, word):
            super().__init__()
            self.word = word

        def run(self):
            self.next(data=self.word)
            self.commit()

    class S(pw.Schema):
        data: str

    a = pw.io.python.read(Subj("x"), schema=S, autocommit_duration_ms=None)
    b = pw.io.python.read(Subj("y"), schema=S, autocommit_duration_ms=None)
    both = a.concat(b)
    seen = []
    pw.io.subscribe(both, on_change=lambda key, row, t, d: seen.append(row["data"]))
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    assert sorted(seen) == ["x", "y"]


def test_surrogate_string_falls_back():
    """Non-UTF-8-encodable strings route to Fallback, not UnicodeEncodeError."""
    s = pwexec.store_new(2, ("count",))
    key_fn = lambda g: ref_scalar(*map(repr, g))
    with pytest.raises(pwexec.Fallback):
        _pb(s, [("\udcff",)], (None,), [1], key_fn, ERROR)
