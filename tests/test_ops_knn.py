"""Tests for the TPU KNN ops (run on CPU backend; Pallas in interpret mode).

Mirrors the reference's brute-force index behavior coverage
(/root/reference/src/external_integration/brute_force_knn_integration.rs tests
+ python/pathway/tests/test_knn.py patterns): add/remove/upsert, metrics,
top-k exactness vs numpy oracle, capacity growth.
"""

import numpy as np
import pytest

from pathway_tpu.ops import KnnShard, Metric, merge_topk


def _oracle_topk(queries, db, k, metric):
    if metric == "cos":
        qn = queries / np.linalg.norm(queries, axis=-1, keepdims=True)
        dn = db / np.linalg.norm(db, axis=-1, keepdims=True)
        scores = qn @ dn.T
    elif metric == "dot":
        scores = queries @ db.T
    else:  # l2sq (negated)
        scores = -(
            (queries**2).sum(-1)[:, None]
            - 2 * queries @ db.T
            + (db**2).sum(-1)[None, :]
        )
    order = np.argsort(-scores, axis=-1, kind="stable")[:, :k]
    return order, np.take_along_axis(scores, order, axis=-1)


@pytest.mark.parametrize("metric", ["cos", "dot", "l2sq"])
def test_knn_shard_matches_oracle(metric):
    rng = np.random.default_rng(0)
    db = rng.normal(size=(200, 16)).astype(np.float32)
    queries = rng.normal(size=(7, 16)).astype(np.float32)
    shard = KnnShard(16, metric)
    shard.add(list(range(200)), db)
    got = shard.search(queries, k=5)
    want_idx, want_scores = _oracle_topk(queries, db, 5, metric)
    for qi in range(7):
        got_keys = [key for key, _ in got[qi]]
        assert got_keys == list(want_idx[qi])
        np.testing.assert_allclose(
            [s for _, s in got[qi]], want_scores[qi], rtol=1e-4, atol=1e-4
        )


def test_knn_remove_and_upsert():
    rng = np.random.default_rng(1)
    db = rng.normal(size=(10, 8)).astype(np.float32)
    shard = KnnShard(8, Metric.DOT)
    shard.add(list(range(10)), db)
    shard.remove([3, 4])
    assert len(shard) == 8
    res = shard.search(db[3][None, :], k=10)
    assert 3 not in [key for key, _ in res[0]]
    # upsert key 5 with vector of key 3 — must return new vector's score
    shard.add([5], db[3][None, :])
    res = shard.search(db[3][None, :], k=1)
    assert res[0][0][0] == 5


def test_knn_growth_over_capacity():
    rng = np.random.default_rng(2)
    db = rng.normal(size=(1000, 4)).astype(np.float32)
    shard = KnnShard(4, "cos")
    for start in range(0, 1000, 100):
        shard.add(list(range(start, start + 100)), db[start : start + 100])
    assert shard.capacity >= 1000 and (shard.capacity & (shard.capacity - 1)) == 0
    res = shard.search(db[777][None, :], k=1)
    assert res[0][0][0] == 777


def test_knn_fewer_rows_than_k():
    shard = KnnShard(4, "dot")
    shard.add([1, 2], np.eye(4, dtype=np.float32)[:2])
    res = shard.search(np.eye(4, dtype=np.float32)[:1], k=10)
    assert [key for key, _ in res[0]][0] == 1
    assert len(res[0]) == 2


def test_merge_topk():
    import jax.numpy as jnp

    va = jnp.array([[9.0, 5.0]])
    ia = jnp.array([[0, 1]])
    vb = jnp.array([[7.0, 6.0]])
    ib = jnp.array([[10, 11]])
    v, i = merge_topk(va, ia, vb, ib, 3)
    assert list(np.asarray(v)[0]) == [9.0, 7.0, 6.0]
    assert list(np.asarray(i)[0]) == [0, 10, 11]


def test_pallas_kernel_interpret_matches_oracle():
    import jax.numpy as jnp

    from pathway_tpu.ops.pallas_knn import pallas_topk_scores

    rng = np.random.default_rng(3)
    db = rng.normal(size=(256, 8)).astype(np.float32)
    queries = rng.normal(size=(4, 8)).astype(np.float32)
    mask = np.zeros(256, np.float32)
    mask[100:110] = -np.inf  # deleted slots
    vals, idx = pallas_topk_scores(
        jnp.asarray(queries), jnp.asarray(db), jnp.asarray(mask),
        k=5, block=64, interpret=True,
    )
    db_masked = db.copy()
    scores = queries @ db_masked.T + mask[None, :]
    want_idx = np.argsort(-scores, axis=-1, kind="stable")[:, :5]
    np.testing.assert_array_equal(np.asarray(idx), want_idx)
    np.testing.assert_allclose(
        np.asarray(vals),
        np.take_along_axis(scores, want_idx, -1),
        rtol=1e-5,
    )
