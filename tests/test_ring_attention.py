"""Ring attention (parallel/ring_attention.py): exact sequence-parallel
attention over an 8-device mesh must match single-device full attention,
full and causal, including composition with a dp axis."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from pathway_tpu.parallel.ring_attention import (
    reference_attention,
    ring_attention,
)


def _mesh(shape, names):
    devs = np.array(jax.devices("cpu")[: int(np.prod(shape))]).reshape(shape)
    return Mesh(devs, names)


def _qkv(rng, b=2, h=4, s=64, d=16, dtype=jnp.float32):
    q = jnp.asarray(rng.standard_normal((b, h, s, d)), dtype)
    k = jnp.asarray(rng.standard_normal((b, h, s, d)), dtype)
    v = jnp.asarray(rng.standard_normal((b, h, s, d)), dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_reference(causal):
    if len(jax.devices("cpu")) < 8:
        pytest.skip("needs 8 virtual devices (conftest sets XLA_FLAGS)")
    mesh = _mesh((8,), ("sp",))
    rng = np.random.default_rng(0)
    q, k, v = _qkv(rng)
    out = ring_attention(q, k, v, mesh, causal=causal)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_ring_composes_with_dp():
    if len(jax.devices("cpu")) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = _mesh((2, 4), ("dp", "sp"))
    rng = np.random.default_rng(1)
    q, k, v = _qkv(rng, b=4, s=32)
    out = ring_attention(q, k, v, mesh, axis="sp", causal=True)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_ring_bf16_long_sequence():
    """Long-context shape: S=2048 sharded 8 ways in bf16 — per-device
    score blocks are (2048/8)^2 = 256^2 instead of 2048^2."""
    if len(jax.devices("cpu")) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = _mesh((8,), ("sp",))
    rng = np.random.default_rng(2)
    q, k, v = _qkv(rng, b=1, h=2, s=2048, d=32, dtype=jnp.bfloat16)
    out = ring_attention(q, k, v, mesh, causal=True)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out, dtype=np.float32),
        np.asarray(ref, dtype=np.float32),
        rtol=5e-2, atol=5e-2,
    )


def test_ring_handles_uneven_softmax_rows():
    """First causal query block attends to a single position — the
    fully-masked-row guards must not NaN."""
    if len(jax.devices("cpu")) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = _mesh((8,), ("sp",))
    rng = np.random.default_rng(3)
    q, k, v = _qkv(rng, b=1, h=1, s=8, d=4)  # one position per device
    out = ring_attention(q, k, v, mesh, causal=True)
    assert not np.isnan(np.asarray(out)).any()
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )
