"""UDF executor semantics + gradual_broadcast tests (reference pattern:
python/pathway/tests/test_udf.py — capacity/timeout/retry/cache)."""

import time

import pytest

import pathway_tpu as pw
from utils import T, run_table


def _rows(t):
    return sorted(run_table(t).values(), key=repr)


def test_async_udf_capacity_limits_concurrency():
    peak = [0]
    active = [0]

    @pw.udf(executor=pw.udfs.async_executor(capacity=2))
    async def slow(v: int) -> int:
        import asyncio

        active[0] += 1
        peak[0] = max(peak[0], active[0])
        await asyncio.sleep(0.05)
        active[0] -= 1
        return v

    t = T("v\n1\n2\n3\n4\n5\n6")
    res = t.select(r=slow(pw.this.v))
    assert sorted(r[0] for r in _rows(res)) == [1, 2, 3, 4, 5, 6]
    assert peak[0] <= 2


def test_async_udf_retry_strategy():
    attempts = [0]

    @pw.udf(
        executor=pw.udfs.async_executor(
            retry_strategy=pw.udfs.FixedDelayRetryStrategy(
                max_retries=4, delay_ms=1
            )
        )
    )
    async def flaky(v: int) -> int:
        attempts[0] += 1
        if attempts[0] < 3:
            raise RuntimeError("transient")
        return v * 10

    t = T("v\n7")
    res = t.select(r=flaky(pw.this.v))
    assert _rows(res) == [(70,)]
    assert attempts[0] == 3


def test_udf_in_memory_cache():
    calls = [0]

    @pw.udf(deterministic=True, cache_strategy=pw.udfs.InMemoryCache())
    def costly(v: int) -> int:
        calls[0] += 1
        return v + 1

    t = T("v\n1\n1\n1\n2")
    res = t.select(r=costly(pw.this.v))
    assert sorted(r[0] for r in _rows(res)) == [2, 2, 2, 3]
    assert calls[0] == 2  # one evaluation per distinct input


def test_async_udf_timeout_produces_error():
    @pw.udf(executor=pw.udfs.async_executor(timeout=0.02))
    async def too_slow(v: int) -> int:
        import asyncio

        await asyncio.sleep(1.0)
        return v

    t = T("v\n1")
    res = t.select(r=too_slow(pw.this.v))
    from pathway_tpu.internals.api import ERROR

    assert _rows(res) == [(ERROR,)]


def test_gradual_broadcast_apportions_threshold():
    rows = T("\n".join(["v"] + [str(i) for i in range(20)]))
    # value == upper: every key exposes its own apportioned point
    thresholds = T("lo | val | hi\n0.0 | 1.0 | 1.0")
    res = rows._gradual_broadcast(
        thresholds, thresholds.lo, thresholds.val, thresholds.hi
    )
    vals = [r[0] for r in _rows(res.select(pw.this.apx_value))]
    assert len(vals) == 20
    assert all(0.0 <= v <= 1.0 for v in vals)
    assert len(set(vals)) > 10  # spread across the hash space


def test_gradual_broadcast_caps_at_value():
    rows = T("v\n1\n2\n3")
    thresholds = T("lo | val | hi\n0.0 | 0.0 | 1.0")
    res = rows._gradual_broadcast(
        thresholds, thresholds.lo, thresholds.val, thresholds.hi
    )
    vals = [r[0] for r in _rows(res.select(pw.this.apx_value))]
    assert vals == [0.0, 0.0, 0.0]  # value at lower bound caps everything
