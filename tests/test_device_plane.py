"""Device observability plane battery (ISSUE 15): dispatch records from
the KNN/encoder sites, trace-schema pin (device spans carry dispatch
ids, land on their own tracks, correlate to node spans), MFU-gauge
sanity against the encoder's FLOPs model on the CPU backend, the
memory_stats-absent fallback, roofline verdict units, the --profile /
--critical-path host-bound verdicts, the Server-Timing satellite, the
run(profile=...) directory validation, and the trace-ring dropped-
events gauge."""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

import pathway_tpu as pw
from pathway_tpu.analysis.profile import (
    aggregate_device_spans,
    device_report,
    profile_trace,
    render_profile,
    validate_trace,
)
from pathway_tpu.internals import device as device_mod
from pathway_tpu.internals.device import (
    PLANE,
    memory_stats,
    peak_bandwidth,
    peak_flops,
    roofline_verdict,
)
from pathway_tpu.internals.flight import FlightRecorder
from pathway_tpu.internals.monitoring import ProberStats


@pytest.fixture(autouse=True)
def _disarmed_plane():
    """The plane is process-global — every test starts and ends
    disarmed so records can never leak across tests (or from an
    unrelated traced test running earlier in the session)."""
    PLANE.disarm()
    yield
    PLANE.disarm()


def _knn_round_trip(n=4, d=8, q=2):
    from pathway_tpu.ops.knn import KnnShard

    rng = np.random.RandomState(0)
    shard = KnnShard(d)
    shard.add([f"k{i}" for i in range(n)],
              rng.rand(n, d).astype(np.float32))
    return shard.search(rng.rand(q, d).astype(np.float32), 2)


# -- off-path discipline --------------------------------------------------

def test_plane_off_is_noop():
    assert PLANE.on is False
    assert PLANE.begin("knn.search") is None
    PLANE.end(None)  # closing a None record is free and legal
    stats = ProberStats()
    hits = _knn_round_trip()
    assert len(hits) == 2 and hits[0]
    assert stats.device_sites == {}


# -- dispatch records -----------------------------------------------------

def test_knn_dispatch_records_land_on_metrics_and_trace(tmp_path):
    stats = ProberStats()
    rec = FlightRecorder(str(tmp_path / "t.json"))
    PLANE.arm(rec, stats)
    try:
        _knn_round_trip()
    finally:
        PLANE.disarm()
    assert "knn.search" in stats.device_sites
    assert "knn.write" in stats.device_sites
    n, wall_s, dev_s, flops, bytes_acc, xfer, flops_eff, mfu_v, mfu_pad = (
        stats.device_totals()
    )
    assert n >= 2 and wall_s > 0 and flops > 0 and xfer > 0
    # effective FLOPs never exceed padded FLOPs (ISSUE 16)
    assert 0 < flops_eff <= flops
    assert 0 <= mfu_v <= mfu_pad
    # device seconds are a SHARE of wall, never more
    assert 0 <= dev_s <= wall_s
    text = stats.render_openmetrics()
    assert "device_dispatches_total " in text
    assert 'device_site_flops_total{site="knn.search"}' in text
    # trace side: device spans with dispatch ids on their own track
    rec.dump(scope=None)
    doc = json.load(open(rec.path))
    assert validate_trace(doc) == [], validate_trace(doc)
    devs = [
        e for e in doc["traceEvents"] if e.get("cat") == "device"
    ]
    assert devs
    sites = {e["name"] for e in devs}
    assert {"knn.search", "knn.write"} <= sites
    for e in devs:
        assert e["tid"] >= 400  # own track, never the engine track
        assert e["args"]["dispatch"] >= 1
        assert e["args"]["device_us"] >= 0
    # the platform stamp says what hardware produced the numbers
    plat = doc["pathway"]["platform"]
    assert plat and plat["backend"] == "cpu"
    assert plat["peak_flops"] > 0 and plat["peak_bandwidth"] > 0


def test_trace_schema_device_spans_correlate_to_node_spans(
    tmp_path, monkeypatch
):
    """Full pipeline pin: an ExternalIndexNode-driven embed+KNN run
    under PATHWAY_TRACE produces device spans that carry the enclosing
    node id, and that node's span exists on the engine track with the
    device flag in its metadata."""
    from pathway_tpu.stdlib.indexing import BruteForceKnn

    monkeypatch.setenv("PATHWAY_TRACE", str(tmp_path / "trace.json"))
    monkeypatch.delenv("PATHWAY_LANE_PROCESSES", raising=False)
    docs = pw.debug.table_from_markdown(
        """
        doc     | vec
        apple   | 1.0,0.0,0.0
        banana  | 0.9,0.1,0.0
        carrot  | 0.0,1.0,0.0
        """
    ).select(
        pw.this.doc,
        vec=pw.apply_with_type(
            lambda s: tuple(float(x) for x in s.split(",")),
            tuple, pw.this.vec,
        ),
    )
    queries = pw.debug.table_from_markdown(
        """
        qid | qvec
        q1  | 1.0,0.05,0.0
        """
    ).select(
        pw.this.qid,
        qvec=pw.apply_with_type(
            lambda s: tuple(float(x) for x in s.split(",")),
            tuple, pw.this.qvec,
        ),
    )
    index = BruteForceKnn(data_column=docs.vec, dimensions=3, metric="cos")
    res = index.query(queries.qvec, number_of_matches=2)
    pw.io.subscribe(
        res.select(pw.this.qid, ids=pw.this._pw_index_reply),
        on_change=lambda *a: None,
    )
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    doc = json.load(open(str(tmp_path / "trace.json")))
    assert validate_trace(doc) == [], validate_trace(doc)
    devs = [e for e in doc["traceEvents"] if e.get("cat") == "device"]
    assert devs, "no device spans from the embed+KNN run"
    node_spans = {
        e["args"]["node"]
        for e in doc["traceEvents"]
        if e.get("cat") == "node"
    }
    for e in devs:
        assert e["args"]["dispatch"] >= 1
        nid = e["args"]["node"]
        assert nid is not None, "engine dispatch without node context"
        assert nid in node_spans, "correlated node span missing"
    # the dispatching node is flagged device in the embedded metadata
    meta = doc["pathway"]["nodes"]
    dev_nodes = {int(k) for k, m in meta.items() if m.get("device")}
    assert dev_nodes & {e["args"]["node"] for e in devs}
    # and --profile joins a roofline verdict onto it
    report = profile_trace(str(tmp_path / "trace.json"))
    assert report["valid"], report["problems"]
    assert report["device"] is not None
    assert report["device"]["sites"]
    top_site = report["device"]["sites"][0]
    assert top_site["verdict"] in (
        "compute-bound", "bandwidth-bound", "host-bound"
    )
    joined = [r for r in report["top"] if r.get("device_verdict")]
    assert joined, "no node row carries a device verdict"
    assert "device dispatches" in render_profile(report)


# -- MFU sanity against the encoder FLOPs model ---------------------------

def test_encoder_mfu_gauge_sane_vs_flops_model():
    from pathway_tpu.models.encoder import (
        EncoderConfig,
        SentenceEncoder,
        forward_flops_per_token,
    )

    cfg = EncoderConfig.tiny()
    enc = SentenceEncoder(cfg)
    texts = ["the quick brown fox"] * 12
    enc.encode(texts)  # warm the jit cache outside the armed window
    stats = ProberStats()
    PLANE.arm(None, stats)
    try:
        enc.encode(texts)
    finally:
        PLANE.disarm()
    agg = stats.device_sites.get("encoder.forward")
    assert agg is not None and agg[0] >= 1
    # padded geometry: batch bucket 16, seq bucket 16 for these texts
    n_tok = 16 * 16
    model_flops = forward_flops_per_token(cfg, 16) * n_tok
    measured = agg[3]
    # cost_analysis (preferred) and the analytical model must agree to
    # within a small factor — the model is pinned against XLA elsewhere
    assert model_flops / 4 <= measured <= model_flops * 4, (
        measured, model_flops,
    )
    *_tot, mfu_v, mfu_pad = stats.device_totals()
    assert 0 < mfu_v < 50  # positive and not absurd on CPU
    # 12 real rows in a 16-row bucket: effective strictly below padded
    assert mfu_v < mfu_pad
    text = stats.render_openmetrics()
    assert "device_mfu" in text and "device_mfu_padded" in text


# -- memory_stats absent fallback -----------------------------------------

def test_memory_stats_absent_fallback(monkeypatch):
    # the real call on the CPU backend must already be absent-safe
    assert memory_stats() is None or isinstance(memory_stats(), dict)
    stats = ProberStats()
    PLANE.arm(None, stats)
    try:
        monkeypatch.setattr(device_mod, "memory_stats", lambda: None)
        PLANE.sample_memory()
    finally:
        PLANE.disarm()
    assert stats.device_hbm_available is False
    assert stats.device_hbm_live == 0 and stats.device_hbm_peak == 0
    text = stats.render_openmetrics()
    assert "device_hbm_stats_available 0" in text
    assert "device_hbm_peak_bytes 0" in text
    # present stats populate the gauges (peak is monotone)
    PLANE.arm(None, stats)
    try:
        monkeypatch.setattr(
            device_mod, "memory_stats",
            lambda: {"bytes_in_use": 100, "peak_bytes_in_use": 250},
        )
        PLANE.sample_memory()
    finally:
        PLANE.disarm()
    assert stats.device_hbm_live == 100
    assert stats.device_hbm_peak == 250
    assert stats.device_hbm_available is True


# -- roofline verdict units -----------------------------------------------

def test_roofline_verdict_units():
    pk_f, pk_b = 100e12, 1e12  # ridge at 100 FLOPs/byte
    # device idle while the host assembles -> host-bound
    assert roofline_verdict(1.0, 0.05, 1e12, 1e9, pk_f, pk_b) == (
        "host-bound"
    )
    # busy device, intensity above the ridge -> compute-bound
    assert roofline_verdict(1.0, 0.9, 1e12, 1e9, pk_f, pk_b) == (
        "compute-bound"
    )
    # busy device, intensity below the ridge -> bandwidth-bound
    assert roofline_verdict(1.0, 0.9, 1e10, 1e9, pk_f, pk_b) == (
        "bandwidth-bound"
    )
    # no modeled arithmetic at all: host work by definition
    assert roofline_verdict(1.0, 0.9, 0.0, 0.0, pk_f, pk_b) == (
        "host-bound"
    )
    # the knob moves the host-bound threshold
    assert roofline_verdict(
        1.0, 0.5, 1e12, 1e9, pk_f, pk_b, host_share=0.6
    ) == "host-bound"
    assert peak_flops("TPU v5 lite") == pytest.approx(197e12)
    assert peak_bandwidth("TPU v5p") == pytest.approx(2765e9)
    assert peak_flops("cpu") > 0


def test_peak_knob_overrides(monkeypatch):
    monkeypatch.setenv("PATHWAY_DEVICE_PEAK_FLOPS", "1e15")
    monkeypatch.setenv("PATHWAY_DEVICE_PEAK_GBPS", "2000")
    assert peak_flops("whatever") == pytest.approx(1e15)
    assert peak_bandwidth("whatever") == pytest.approx(2e12)


# -- --profile host-bound verdict on a synthetically starved dispatch ----

def _synthetic_device_trace(tmp_path, device_us, flops=1e9,
                            bytes_accessed=1e6):
    """One node span enclosing one device dispatch whose device share
    of the 10ms wall is `device_us`."""
    doc = {
        "traceEvents": [
            {
                "name": "ExternalIndexNode#3", "cat": "node", "ph": "X",
                "pid": 0, "tid": 0, "ts": 1000.0, "dur": 11000.0,
                "args": {"node": 3, "t": 1, "rows": 64, "rep": "tuple"},
            },
            {
                "name": "knn.search", "cat": "device", "ph": "X",
                "pid": 0, "tid": 400, "ts": 1100.0, "dur": 10000.0,
                "args": {
                    "dispatch": 1, "node": 3, "t": 1,
                    "device_us": device_us, "flops": flops,
                    "bytes_accessed": bytes_accessed,
                    "transfer_bytes": 4096, "queue_depth": 1,
                },
            },
        ],
        "pathway": {
            "schema": 1,
            "nodes": {
                "3": {
                    "label": "ExternalIndexNode#3", "device": True,
                },
            },
            "platform": {
                "backend": "cpu", "device_kind": "cpu",
                "peak_flops": 1e12, "peak_bandwidth": 1e11,
            },
        },
    }
    p = tmp_path / "dev.json"
    p.write_text(json.dumps(doc))
    return str(p)


def test_profile_emits_host_bound_on_starved_dispatch(tmp_path):
    # 0.2ms of device time inside a 10ms dispatch wall: the host was
    # assembling batches while the device idled
    path = _synthetic_device_trace(tmp_path, device_us=200.0)
    report = profile_trace(path)
    assert report["valid"], report["problems"]
    site = report["device"]["sites"][0]
    assert site["site"] == "knn.search"
    assert site["verdict"] == "host-bound"
    assert report["top"][0]["device_verdict"] == "host-bound"
    text = render_profile(report)
    assert "host-bound" in text and "knn.search" in text


def test_profile_emits_compute_bound_on_busy_dispatch(tmp_path):
    # 9.8ms device-busy of a 10ms wall, intensity 1e4 FLOPs/byte vs a
    # ridge of 10 -> compute-bound
    path = _synthetic_device_trace(
        tmp_path, device_us=9800.0, flops=1e10, bytes_accessed=1e6
    )
    report = profile_trace(path)
    site = report["device"]["sites"][0]
    assert site["verdict"] == "compute-bound"
    # same trace through the shared aggregation helper
    doc = json.load(open(path))
    agg = aggregate_device_spans(doc["traceEvents"])
    assert agg["knn.search"]["dispatches"] == 1
    assert agg["knn.search"]["nodes"] == {3: pytest.approx(0.0098)}
    dev = device_report(doc)
    assert dev["peak_flops"] == pytest.approx(1e12)  # from the trace


def test_device_span_missing_dispatch_arg_is_schema_problem(tmp_path):
    doc = {
        "traceEvents": [
            {
                "name": "knn.search", "cat": "device", "ph": "X",
                "pid": 0, "tid": 400, "ts": 1.0, "dur": 5.0,
                "args": {"node": 3},
            },
        ],
        "pathway": {"schema": 1, "nodes": {}},
    }
    problems = validate_trace(doc)
    assert any("device span missing dispatch" in p for p in problems)


def test_critical_path_device_leg_and_verdict(tmp_path):
    """The straggler's hottest node issued device dispatches: the
    report grows a per-rank device leg and the verdict says whether the
    straggler needs a kernel or a host-path fix."""
    from pathway_tpu.analysis.critical_path import (
        critical_path,
        render_critical_path,
    )

    # canonical 2-rank straggler shape (rank 1 slow), with rank 1's
    # pre-send work being a host-starved device dispatch
    def mesh(pid, name, ts, dur, peer):
        return {
            "name": name, "cat": "mesh", "ph": "X", "pid": pid,
            "tid": 0, "ts": ts, "dur": dur, "args": {"peer": peer},
        }

    events = [
        {"name": "wave 1", "cat": "wave", "ph": "X", "pid": 0, "tid": 0,
         "ts": 1000.0, "dur": 3600.0, "args": {"t": 100, "exchanges": 1}},
        mesh(0, "send→1", 1050.0, 100.0, 1),
        mesh(0, "recv-wait←1", 1200.0, 3200.0, 1),
        {"name": "ExternalIndexNode#5", "cat": "node", "ph": "X",
         "pid": 1, "tid": 0, "ts": 500.0, "dur": 400.0,
         "args": {"node": 5, "t": 100, "rows": 900, "rep": "tuple"}},
        {"name": "knn.search", "cat": "device", "ph": "X", "pid": 1,
         "tid": 400, "ts": 520.0, "dur": 350.0,
         "args": {"dispatch": 7, "node": 5, "t": 100,
                  "device_us": 20.0, "flops": 1e8,
                  "bytes_accessed": 1e6, "transfer_bytes": 512,
                  "queue_depth": 1}},
        {"name": "wave 1", "cat": "wave", "ph": "X", "pid": 1, "tid": 0,
         "ts": 1000.0, "dur": 3500.0, "args": {"t": 100, "exchanges": 1}},
        mesh(1, "send→0", 4000.0, 200.0, 0),
        mesh(1, "recv-wait←0", 4250.0, 50.0, 0),
    ]
    events.sort(key=lambda e: e["ts"])
    doc = {
        "traceEvents": events,
        "pathway": {
            "schema": 1,
            "merged_ranks": [0, 1],
            "nodes": {
                "5": {"label": "ExternalIndexNode#5", "device": True},
            },
        },
    }
    p = tmp_path / "cp.json"
    p.write_text(json.dumps(doc))
    report = critical_path(str(p))
    assert report["valid"], report["problems"]
    assert report["straggler"]["rank"] == 1
    n = report["straggler"]["upstream_node"]
    assert n["label"] == "ExternalIndexNode#5"
    assert n["device_verdict"] == "host-bound"
    assert n["device_site"] == "knn.search"
    assert "device: host-bound (knn.search)" in report["verdict"]
    assert report["legs"][1]["device_s"] == pytest.approx(20e-6)
    text = render_critical_path(report)
    assert "device=0.0000" in text or "device=" in text
    assert "device: host-bound" in text


# -- Server-Timing satellite ----------------------------------------------

_PORT = [9420]


def _next_port():
    _PORT[0] += 1
    return _PORT[0]


def test_server_timing_header(monkeypatch):
    monkeypatch.setenv("PATHWAY_SERVE_TIMING", "1")

    class S(pw.Schema):
        value: int

    port = _next_port()
    webserver = pw.io.http.PathwayWebserver(host="127.0.0.1", port=port)
    queries, writer = pw.io.http.rest_connector(
        webserver=webserver, schema=S, window_ms=20.0
    )
    writer(queries.select(result=pw.this.value * 3))
    t = threading.Thread(target=pw.run, daemon=True)
    t.start()
    time.sleep(1.0)
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/",
        data=json.dumps({"value": 7}).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=15) as resp:
        assert json.loads(resp.read().decode()) == 21
        st = resp.headers.get("Server-Timing")
    assert st, "Server-Timing header missing under PATHWAY_SERVE_TIMING=1"
    legs = {}
    for part in st.split(","):
        name, _, dur = part.strip().partition(";dur=")
        legs[name] = float(dur)
    assert set(legs) == {"queue", "window", "dispatch", "egress"}
    assert all(v >= 0.0 for v in legs.values())
    # the batch window was 20ms: the queue leg saw (some of) it, and
    # the total decomposition is in the same ballpark as the request
    assert sum(legs.values()) < 15_000


def test_no_server_timing_header_by_default(monkeypatch):
    monkeypatch.delenv("PATHWAY_SERVE_TIMING", raising=False)

    class S(pw.Schema):
        value: int

    port = _next_port()
    webserver = pw.io.http.PathwayWebserver(host="127.0.0.1", port=port)
    queries, writer = pw.io.http.rest_connector(
        webserver=webserver, schema=S
    )
    writer(queries.select(result=pw.this.value + 1))
    t = threading.Thread(target=pw.run, daemon=True)
    t.start()
    time.sleep(1.0)
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/",
        data=json.dumps({"value": 1}).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=15) as resp:
        assert json.loads(resp.read().decode()) == 2
        assert resp.headers.get("Server-Timing") is None


# -- run(profile=...) validation ------------------------------------------

def test_run_profile_bad_path_fails_loudly(tmp_path):
    blocker = tmp_path / "not_a_dir"
    blocker.write_text("in the way")
    t = pw.debug.table_from_rows(
        pw.schema_from_types(a=int), [(1,)]
    )
    pw.io.subscribe(t, on_change=lambda *a: None)
    with pytest.raises(NotADirectoryError):
        pw.run(
            profile=str(blocker),
            monitoring_level=pw.MonitoringLevel.NONE,
        )


# -- trace-ring pressure gauge --------------------------------------------

def test_trace_dropped_events_gauge_renders(tmp_path, monkeypatch):
    stats = ProberStats()
    assert "trace_dropped_events_total 0" in stats.render_openmetrics()
    stats.set_trace_dropped(17)
    assert "trace_dropped_events_total 17" in stats.render_openmetrics()
    # end to end: a capped recorder's drops land on the runtime's stats
    monkeypatch.setenv("PATHWAY_TRACE_MAX_EVENTS", "10000")
    monkeypatch.setenv(
        "PATHWAY_TRACE", str(tmp_path / "capped.json")
    )
    monkeypatch.delenv("PATHWAY_LANE_PROCESSES", raising=False)

    class Source(pw.io.python.ConnectorSubject):
        _deletions_enabled = False

        def run(self):
            for _ in range(4):
                self.next_batch(
                    [{"data": f"w{i}"} for i in range(4000)]
                )
                self.commit()

    class S(pw.Schema):
        data: str

    tbl = pw.io.python.read(
        Source(), schema=S, autocommit_duration_ms=None
    )
    pw.io.subscribe(
        tbl.select(u=pw.this.data.str.upper()),
        on_change=lambda *a: None,
    )
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    from pathway_tpu.engine.runtime import LAST_RUN_STATS

    doc = json.load(open(str(tmp_path / "capped.json")))
    if doc["pathway"]["dropped_events"]:
        assert LAST_RUN_STATS.trace_dropped_events > 0
        assert "trace_dropped_events_total" in (
            LAST_RUN_STATS.render_openmetrics()
        )


# -- dispatch-queue depth --------------------------------------------------

def test_dispatch_queue_depth_tracks_inflight():
    stats = ProberStats()
    PLANE.arm(None, stats)
    try:
        d1 = PLANE.begin("knn.search")
        d2 = PLANE.begin("encoder.forward")
        assert d2.depth == 2  # two dispatches in flight at launch
        PLANE.end(d2, None, block=False)
        PLANE.end(d1, None, block=False)
    finally:
        PLANE.disarm()
    assert stats.device_queue_depth in (1, 2)
    assert stats.device_sites["encoder.forward"][0] == 1


# -- overhead (pair-measured; excluded from tier-1) ------------------------

@pytest.mark.slow
def test_device_plane_overhead_pair_measured_under_3pct():
    """Traced-vs-untraced overhead of the device plane on the embed+KNN
    hot loop, measured as INTERLEAVED pairs (sequential blocks read
    ordering bias) — the same methodology as the PR 8 relational lanes.
    The smoke lane records the same number into BENCH_full.json."""
    from pathway_tpu.models.encoder import EncoderConfig, SentenceEncoder
    from pathway_tpu.ops.knn import KnnShard

    cfg = EncoderConfig.tiny()
    enc = SentenceEncoder(cfg)
    shard = KnnShard(cfg.hidden, capacity=1024)
    # a pass long enough (~150ms) that scheduler jitter is small
    # against the 3% bar on a loaded CI host
    texts = [
        f"document number {i} about topic {i % 7}" for i in range(256)
    ]
    keys = [f"k{j}" for j in range(len(texts))]  # static key set: the
    # shard must not grow between passes — a capacity doubling
    # recompiles the scan and the compile lands in whichever arm runs
    # first, which is ordering bias, not plane overhead

    def one_pass():
        emb = enc.encode(texts)
        shard.add(keys, emb)
        shard.search(emb[:16], 5)

    stats = ProberStats()
    # warm every jit cache AND the plane's one-time paths in BOTH arms
    one_pass()
    PLANE.arm(None, stats)
    one_pass()
    PLANE.disarm()

    def timed(armed):
        if armed:
            PLANE.arm(None, stats)
        t0 = time.perf_counter()
        one_pass()
        dt = time.perf_counter() - t0
        if armed:
            PLANE.disarm()
        return dt

    def measure(pairs):
        # median of per-pair ratios, pair order alternating: each pair
        # shares its moment's machine noise (scheduler, cache state),
        # and alternating which arm runs first cancels slow drift —
        # far more stable than comparing two independent medians
        ratios = []
        for i in range(pairs):
            if i % 2 == 0:
                on, off = timed(True), timed(False)
            else:
                off, on = timed(False), timed(True)
            ratios.append(on / off)
        return sorted(ratios)[len(ratios) // 2] - 1.0

    overhead = measure(7)
    if overhead > 0.03:  # one retry at double depth before failing
        overhead = measure(15)
    assert overhead <= 0.03, f"device-plane overhead {overhead:.2%}"
