"""Doctest-style API examples — runnable versions of the usage snippets a
user meets in the reference's public docstrings (reference:
python/pathway/internals/table.py, expression.py, reducers.py doctest
blocks; the round-4 verdict named doctest-style examples a thin area).
Each test is one self-contained example: build small tables, call ONE
API feature the way the docs show it, assert the documented result.
"""

from __future__ import annotations

import datetime

import pytest

import pathway_tpu as pw
from pathway_tpu.internals.graph_runner import GraphRunner


def _rows(table):
    captures = GraphRunner().run_tables(table)
    vals = list(captures[0].state.rows.values())
    try:
        return sorted(
            vals, key=lambda r: tuple((v is None, v) for v in r)
        )
    except TypeError:  # mixed-type columns: stable string ordering
        return sorted(
            vals, key=lambda r: tuple((v is None, str(v)) for v in r)
        )


def T(md: str):
    return pw.debug.table_from_markdown(md)


# ---------------------------------------------------------------------------
# expressions


def test_example_arithmetic_and_comparison_chain():
    t = T("a | b\n3 | 2\n10 | 5")
    res = t.select(
        s=pw.this.a + pw.this.b,
        p=pw.this.a * pw.this.b,
        q=pw.this.a // pw.this.b,
        m=pw.this.a % pw.this.b,
        gt=pw.this.a > pw.this.b * 2,
    )
    assert _rows(res) == [(5, 6, 1, 1, False), (15, 50, 2, 0, False)]


def test_example_boolean_operators_use_ampersand_pipe():
    t = T("a | b\n1 | 1\n1 | 0\n0 | 0")
    res = t.select(
        both=(pw.this.a == 1) & (pw.this.b == 1),
        either=(pw.this.a == 1) | (pw.this.b == 1),
        neither=~((pw.this.a == 1) | (pw.this.b == 1)),
    )
    assert _rows(res) == [
        (False, False, True),
        (False, True, False),
        (True, True, False),
    ]


def test_example_if_else_and_coalesce():
    t = T("v\n5\n-3\n")
    res = t.select(
        sign=pw.if_else(pw.this.v >= 0, "pos", "neg"),
    )
    assert _rows(res) == [("neg",), ("pos",)]

    t2 = pw.debug.table_from_rows(
        pw.schema_from_types(x=int | None), [(1, None), (2, 7)]
    )
    res2 = t2.select(filled=pw.coalesce(pw.this.x, 0))
    assert _rows(res2) == [(0,), (7,)]


def test_example_apply_and_apply_with_type():
    t = T("name\nann\nbob")
    res = t.select(
        shout=pw.apply(lambda s: s.upper() + "!", pw.this.name),
        n=pw.apply_with_type(len, int, pw.this.name),
    )
    assert _rows(res) == [("ANN!", 3), ("BOB!", 3)]


def test_example_cast_between_numeric_types():
    t = T("x\n1\n2")
    res = t.select(f=pw.cast(float, pw.this.x))
    assert _rows(res) == [(1.0,), (2.0,)]
    assert all(isinstance(v, float) for (v,) in _rows(res))


def test_example_str_namespace():
    t = T("s\nHello World\nfoo bar baz")
    res = t.select(
        up=pw.this.s.str.upper(),
        low=pw.this.s.str.lower(),
        n=pw.this.s.str.len(),
        parts=pw.this.s.str.split(" "),
    )
    got = _rows(res)
    assert got[0][0] == "FOO BAR BAZ"
    assert got[1][1] == "hello world"
    assert got[1][2] == 11
    assert tuple(got[0][3]) == ("foo", "bar", "baz")


def test_example_dt_namespace():
    t = pw.debug.table_from_rows(
        pw.schema_from_types(ts=pw.DateTimeNaive),
        [(1, datetime.datetime(2024, 3, 15, 14, 30, 45))],
    )
    res = t.select(
        y=pw.this.ts.dt.year(),
        mo=pw.this.ts.dt.month(),
        d=pw.this.ts.dt.day(),
        h=pw.this.ts.dt.hour(),
    )
    assert _rows(res) == [(2024, 3, 15, 14)]


def test_example_num_namespace_round_abs():
    t = T("x\n-2\n3")
    res = t.select(a=pw.this.x.num.abs())
    assert _rows(res) == [(2,), (3,)]


def test_example_make_tuple_and_indexing():
    t = T("a | b\n1 | 2")
    res = t.select(pair=pw.make_tuple(pw.this.a, pw.this.b))
    [(pair,)] = _rows(res)
    assert tuple(pair) == (1, 2)
    res2 = t.select(first=pw.make_tuple(pw.this.a, pw.this.b)[0])
    assert _rows(res2) == [(1,)]


def test_example_pointer_from_and_ix_ref():
    items = T("name | price\napple | 3\npear | 5")
    keyed = items.with_id_from(pw.this.name)
    orders = T("item\napple\npear\napple")
    res = orders.select(
        price=keyed.ix_ref(orders.item).price,
    )
    assert _rows(res) == [(3,), (3,), (5,)]


# ---------------------------------------------------------------------------
# table operations


def test_example_with_columns_keeps_existing():
    t = T("a | b\n1 | 2")
    res = t.with_columns(c=pw.this.a + pw.this.b)
    assert res.column_names() == ["a", "b", "c"]
    assert _rows(res) == [(1, 2, 3)]


def test_example_rename_and_without():
    t = T("a | b | c\n1 | 2 | 3")
    res = t.rename(x=pw.this.a).without(pw.this.b)
    assert sorted(res.column_names()) == ["c", "x"]


def test_example_filter_chaining():
    t = T("v\n1\n5\n10\n20")
    res = t.filter(pw.this.v > 3).filter(pw.this.v < 15)
    assert _rows(res) == [(5,), (10,)]


def test_example_concat_reindex():
    a = T("v\n1\n2")
    b = T("v\n3")
    res = a.concat_reindex(b)
    assert _rows(res) == [(1,), (2,), (3,)]


def test_example_update_rows():
    base = T(
        """
        k | v
        a | 1
        b | 2
        """
    ).with_id_from(pw.this.k)
    patch = T(
        """
        k | v
        b | 20
        c | 30
        """
    ).with_id_from(pw.this.k)
    res = base.update_rows(patch)
    assert sorted(r for r in _rows(res)) == [("a", 1), ("b", 20), ("c", 30)]


def test_example_groupby_reduce_multiple_reducers():
    t = T(
        """
        g | v
        x | 1
        x | 4
        y | 10
        """
    )
    res = t.groupby(pw.this.g).reduce(
        g=pw.this.g,
        n=pw.reducers.count(),
        total=pw.reducers.sum(pw.this.v),
        smallest=pw.reducers.min(pw.this.v),
        values=pw.reducers.sorted_tuple(pw.this.v),
    )
    assert _rows(res) == [("x", 2, 5, 1, (1, 4)), ("y", 1, 10, 10, (10,))]


def test_example_groupby_global_reduce():
    t = T("v\n1\n2\n3")
    res = t.reduce(total=pw.reducers.sum(pw.this.v))
    assert _rows(res) == [(6,)]


def test_example_argmin_argmax_reducers():
    t = T(
        """
        g | v | tag
        a | 3 | low
        a | 9 | high
        """
    )
    res = t.groupby(pw.this.g).reduce(
        cheapest=pw.reducers.argmin(pw.this.v),
        dearest=pw.reducers.argmax(pw.this.v),
    )
    [(lo_key, hi_key)] = _rows(res)
    assert isinstance(lo_key, pw.Pointer) and isinstance(hi_key, pw.Pointer)
    assert lo_key != hi_key


def test_example_join_select_with_left_right():
    people = T("name | city\nann | paris\nbob | rome")
    cities = T("city | country\nparis | fr\nrome | it")
    res = people.join(cities, pw.left.city == pw.right.city).select(
        pw.left.name, pw.right.country
    )
    assert _rows(res) == [("ann", "fr"), ("bob", "it")]


def test_example_join_left_keeps_unmatched():
    a = T("k | v\n1 | x\n2 | y")
    b = T("k | w\n1 | p")
    res = a.join_left(b, pw.left.k == pw.right.k).select(
        v=pw.left.v, w=pw.right.w
    )
    assert _rows(res) == [("x", "p"), ("y", None)]


def test_example_flatten():
    t = T("k\na").select(items=pw.make_tuple(1, 2, 3))
    res = t.flatten(pw.this.items)
    assert _rows(res.select(pw.this.items)) == [(1,), (2,), (3,)]


def test_example_difference_and_intersect():
    a = T("k | v\n1 | a\n2 | b\n3 | c").with_id_from(pw.this.k)
    b = T("k | w\n2 | x\n3 | y").with_id_from(pw.this.k)
    diff = a.difference(b)
    inter = a.intersect(b)
    assert _rows(diff.select(pw.this.v)) == [("a",)]
    assert _rows(inter.select(pw.this.v)) == [("b",), ("c",)]


def test_example_iterate_collatz_steps():
    # the reference's canonical iterate example shape: apply a step until
    # a fixed point
    def step(t):
        return dict(
            t=t.select(
                v=pw.if_else(
                    pw.this.v <= 1,
                    pw.this.v,
                    pw.if_else(
                        pw.this.v % 2 == 0,
                        pw.this.v // 2,
                        pw.this.v,  # odd: stop halving in this toy example
                    ),
                )
            )
        )

    t = T("v\n8\n5")
    res = pw.iterate(step, t=t).t
    assert _rows(res) == [(1,), (5,)]


def test_example_udf_decorator():
    @pw.udf
    def double(x: int) -> int:
        return 2 * x

    t = T("v\n3\n4")
    res = t.select(d=double(pw.this.v))
    assert _rows(res) == [(6,), (8,)]


def test_example_udf_with_propagate_none():
    @pw.udf(propagate_none=True)
    def fragile(x: int) -> int:
        return x + 1  # never sees None

    t = pw.debug.table_from_rows(
        pw.schema_from_types(x=int | None), [(1, 1), (2, None)]
    )
    res = t.select(y=fragile(pw.this.x))
    assert _rows(res) == [(2,), (None,)]


def test_example_schema_and_column_definition():
    class S(pw.Schema):
        key: int = pw.column_definition(primary_key=True)
        label: str = pw.column_definition(default_value="unknown")

    assert S.column_names() == ["key", "label"]
    assert S.primary_key_columns() == ["key"]
    assert S.default_values()["label"] == "unknown"


def test_example_json_column_access():
    t = pw.debug.table_from_rows(
        pw.schema_from_types(data=pw.Json),
        [(1, pw.Json({"name": "ann", "age": 30}))],
    )
    res = t.select(
        name=pw.this.data.get("name"),
        age=pw.this.data.get("age"),
    )
    [(name, age)] = _rows(res)
    name = name.value if hasattr(name, "value") else name
    age = age.value if hasattr(age, "value") else age
    assert name == "ann" and age == 30


def test_example_fill_error():
    t = T("a | b\n1 | 0\n6 | 3")
    res = t.select(q=pw.fill_error(pw.this.a // pw.this.b, -1))
    assert _rows(res) == [(-1,), (2,)]


def test_example_unwrap_optional():
    t = pw.debug.table_from_rows(
        pw.schema_from_types(x=int | None), [(1, 5)]
    )
    res = t.select(y=pw.unwrap(pw.this.x) + 1)
    assert _rows(res) == [(6,)]


def test_example_assert_table_has_schema():
    t = T("a | b\n1 | x")
    pw.assert_table_has_columns(t, ["a", "b"])
    with pytest.raises(AssertionError):
        pw.assert_table_has_columns(t, ["a", "missing"])


def test_example_groupby_id():
    t = T("v\n1\n2")
    res = t.groupby(id=t.id).reduce(v=pw.reducers.sum(pw.this.v))
    assert _rows(res) == [(1,), (2,)]


def test_example_table_from_pandas_roundtrip():
    import pandas as pd

    df = pd.DataFrame({"a": [1, 2], "b": ["x", "y"]})
    t = pw.debug.table_from_pandas(df)
    out = pw.debug.table_to_pandas(t, include_id=False)
    assert sorted(out["a"]) == [1, 2]
    assert sorted(out["b"]) == ["x", "y"]


def test_example_subscribe_sees_diffs():
    pw.internals.parse_graph.G.clear()

    class Src(pw.io.python.ConnectorSubject):
        def run(self):
            self.next(k="a", v=1)
            self.commit()
            self.next(k="a", v=2)  # upsert: retract then insert
            self.commit()

    class S(pw.Schema):
        k: str = pw.column_definition(primary_key=True)
        v: int

    t = pw.io.python.read(Src(), schema=S, autocommit_duration_ms=None)
    events = []
    pw.io.subscribe(
        t,
        on_change=lambda key, row, time, is_addition: events.append(
            (row["v"], is_addition)
        ),
    )
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    assert events == [(1, True), (1, False), (2, True)]
