"""Mesh-sharded retrieval through the full VectorStore path on the
virtual 8-device mesh (SURVEY §5: per-chip HBM shards replace the
reference's broadcast-replicated index)."""

import numpy as np

import pathway_tpu as pw
from pathway_tpu.internals.graph_runner import GraphRunner
from pathway_tpu.parallel import make_mesh
from pathway_tpu.xpacks.llm.mocks import DeterministicMockEmbedder
from pathway_tpu.xpacks.llm.vector_store import VectorStoreServer


def _answered(table):
    captures = GraphRunner().run_tables(table)
    seen = set()
    out = []
    for key, row, _, d in captures[0].updates:
        if d > 0 and key not in seen:
            seen.add(key)
            out.append(row)
    return out


def test_vector_store_with_mesh_sharded_index():
    mesh = make_mesh(8, axes=("dp",), shape=(8,))
    docs = pw.debug.table_from_markdown(
        "\n".join(
            ["data | meta"]
            + [f"document number {i} about topic {i % 7} | f{i}.txt" for i in range(40)]
        )
    ).select(
        data=pw.this.data,
        _metadata=pw.apply_with_type(
            lambda p: pw.Json({"path": p, "modified_at": 1, "seen_at": 2}),
            pw.Json,
            pw.this.meta,
        ),
    )
    server = VectorStoreServer(
        docs,
        embedder=DeterministicMockEmbedder(dimension=16),
        mesh=mesh,
    )
    queries = pw.debug.table_from_markdown(
        """
        query | k
        document number 13 about topic 6 | 3
        """,
        schema=VectorStoreServer.RetrieveQuerySchema,
    )
    res = server.retrieve_query(queries)
    rows = _answered(res)
    results = rows[0][0].value
    assert len(results) == 3
    # deterministic embedder: the exact text is its own nearest neighbor
    assert results[0]["text"] == "document number 13 about topic 6"
    assert results[0]["dist"] < 1e-5


def test_sharded_index_inner_matches_unsharded():
    from pathway_tpu.stdlib.indexing import BruteForceKnn

    mesh = make_mesh(8, axes=("dp",), shape=(8,))
    rng = np.random.default_rng(0)
    vecs = {i: tuple(rng.normal(size=6)) for i in range(50)}
    docs = pw.debug.table_from_markdown(
        "\n".join(["i"] + [str(i) for i in range(50)])
    ).select(i=pw.this.i, emb=pw.apply_with_type(lambda i: vecs[i], tuple, pw.this.i))
    queries = pw.debug.table_from_markdown("q\n1\n2").select(
        q=pw.this.q,
        emb=pw.apply_with_type(lambda q: vecs[q * 10], tuple, pw.this.q),
    )

    def replies(mesh_arg):
        pw.internals.parse_graph.G.clear()
        docs2 = pw.debug.table_from_markdown(
            "\n".join(["i"] + [str(i) for i in range(50)])
        ).select(
            i=pw.this.i, emb=pw.apply_with_type(lambda i: vecs[i], tuple, pw.this.i)
        )
        queries2 = pw.debug.table_from_markdown("q\n1\n2").select(
            q=pw.this.q,
            emb=pw.apply_with_type(lambda q: vecs[q * 10], tuple, pw.this.q),
        )
        inner = BruteForceKnn(
            data_column=docs2.emb, dimensions=6, metric="cos", mesh=mesh_arg
        )
        res = inner.query(queries2.emb, number_of_matches=3)
        captures = GraphRunner().run_tables(
            res.select(pw.this.q, reply=res["_pw_index_reply"])
        )
        out = {}
        for row in captures[0].state.rows.values():
            out[row[0]] = [mid for mid, _ in row[1]]
        return out

    sharded = replies(mesh)
    unsharded = replies(None)
    assert sharded == unsharded
