import os

# Must be set before jax initializes: tests run on a virtual 8-device CPU
# mesh so multi-chip sharding paths are exercised without TPU hardware.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Force CPU as the default backend: the environment's TPU plugin rewrites
# JAX_PLATFORMS at import time (env vars alone don't stick), so override via
# jax.config after import. Tests need the 8-device virtual mesh; set
# PATHWAY_TPU_TEST_REAL=1 to run against the real chip instead.
if os.environ.get("PATHWAY_TPU_TEST_REAL") != "1":
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")

import pytest

from pathway_tpu.internals.parse_graph import G


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long multi-process batteries excluded from the tier-1 "
        "sweep (-m 'not slow'); run by scripts/ci_lanes.sh and the "
        "fault-matrix CLI",
    )


@pytest.fixture(autouse=True)
def _clear_graph():
    G.clear()
    yield
    G.clear()
