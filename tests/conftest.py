import os

# Must be set before jax initializes: tests run on a virtual 8-device CPU
# mesh so multi-chip sharding paths are exercised without TPU hardware.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest

from pathway_tpu.internals.parse_graph import G


@pytest.fixture(autouse=True)
def _clear_graph():
    G.clear()
    yield
    G.clear()
