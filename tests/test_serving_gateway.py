"""Async batching gateway tests (io/http/_server.py): one commit per
batch window under load, bit-identical parity with the per-request path
on an out-of-order mixed-timeout workload, admission shedding with
Retry-After, timed-out-request eviction, GET coercion 400s, serve
metrics, the batched subscribe egress, and the Plan Doctor's
row-expanding-sink diagnostic.

Serving through rollback (ISSUE 9): the park/replay protocol
transitions and their exactly-once boundary (a responded request never
replays; an all-parked window commits nothing), the serving model
checker (clean protocol verifies, the ``replay_committed_window``
mutant is caught with a replayable trace), the dispatch circuit
breaker + brownout degraded answers, the epoch-survivable frontend's
park/deadline-503/draining behavior, the KeepAliveSession Retry-After
retry contract, /healthz readiness states, and the new knob/fault-point
registrations."""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

import pathway_tpu as pw
from pathway_tpu.internals.monitoring import ProberStats, ServeMetrics

_PORT = [9120]


def _next_port():
    _PORT[0] += 1
    return _PORT[0]


def _post(url, payload, timeout=15):
    req = urllib.request.Request(
        url,
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def _gateway(port, pipeline=None, **kw):
    """rest_connector echo server; returns (subject, url)."""

    class S(pw.Schema):
        value: int

    webserver = pw.io.http.PathwayWebserver(host="127.0.0.1", port=port)
    queries, writer = pw.io.http.rest_connector(
        webserver=webserver, schema=S, **kw
    )
    if pipeline is None:
        writer(queries.select(result=pw.this.value * 3))
    else:
        writer(pipeline(queries))
    subject = webserver._routes[0][2].__self__
    return subject, f"http://127.0.0.1:{port}/"


def _start_run():
    t = threading.Thread(target=pw.run, daemon=True)
    t.start()
    time.sleep(1.0)
    return t


def _fire(url, values, timeout=15):
    """Concurrent closed clients; returns {value: (status, result)}."""
    out = {}
    lock = threading.Lock()

    def client(v):
        try:
            res = _post(url, {"value": v}, timeout=timeout)
            status = 200
        except urllib.error.HTTPError as e:
            res = None
            status = e.code
        except Exception as e:  # client-side timeout etc.
            res = None
            status = repr(e)
        with lock:
            out[v] = (status, res)

    threads = [threading.Thread(target=client, args=(v,)) for v in values]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return out


def test_one_commit_per_window_under_load():
    """The pinned tentpole invariant: N concurrent requests coalesce
    into a handful of windows, each window is exactly ONE subject
    commit, and the occupancy histogram proves multi-request windows."""
    port = _next_port()
    subject, url = _gateway(
        port, window_ms=60.0, max_batch=64, workers=1
    )
    commits = [0]
    orig_commit = subject.commit

    def counting_commit():
        commits[0] += 1
        orig_commit()

    subject.commit = counting_commit
    _start_run()

    n = 48
    out = _fire(url, range(n))
    assert all(st == 200 and res == v * 3 for v, (st, res) in out.items())
    m = subject.serve_metrics
    assert m.requests == n
    # every request is accounted to exactly one window, and coalescing
    # engaged: far fewer commits than requests, occupancy sums to n
    assert m.occupancy.sum == n
    assert m.commits == m.occupancy.total == commits[0]
    assert commits[0] <= n // 4, (commits[0], n)
    # multi-request windows: at least one window carried > 2 requests
    # (buckets are cumulative edges 1,2,4,...: everything above the
    # le=2 bucket had occupancy > 2)
    assert m.occupancy.total - sum(m.occupancy.counts[:2]) >= 1
    assert m.shed == 0 and m.timeouts == 0


def test_parity_with_per_request_path_out_of_order_mixed_timeouts():
    """Batched gateway vs per-request path (window 0 / max_batch 1) on
    an out-of-order, mixed-timeout workload: clients fire concurrently
    (arrival order is scrambled vs completion order — windows group
    arbitrary subsets), and the values >= 900 are filtered out of the
    response table so their clients hit the request deadline while
    later requests already completed. Every completed response must be
    bit-identical between the two paths, and exactly the filtered
    requests 504 on both."""

    def pipeline(queries):
        return queries.filter(pw.this.value < 900).select(
            result=pw.this.value * 7 + 1
        )

    values = list(range(40)) + [900, 901]
    results = {}
    for mode, kw in (
        ("batched", dict(window_ms=25.0, max_batch=16)),
        ("per_request", dict(window_ms=0.0, max_batch=1)),
    ):
        pw.internals.parse_graph.G.clear()
        port = _next_port()
        subject, url = _gateway(
            port, pipeline=pipeline, timeout_s=1.5, **kw
        )
        _start_run()
        results[mode] = _fire(url, values)
        assert subject.serve_metrics.timeouts == 2, mode

    for v in values:
        assert results["batched"][v] == results["per_request"][v], v
        if v < 900:
            assert results["batched"][v] == (200, v * 7 + 1)
        else:
            assert results["batched"][v][0] == 504


def test_admission_shedding_503_with_retry_after():
    port = _next_port()
    subject, url = _gateway(
        port, window_ms=600.0, max_batch=1000, queue_cap=4
    )
    _start_run()

    n = 16
    headers = {}
    out = {}
    lock = threading.Lock()

    def client(v):
        req = urllib.request.Request(
            url,
            data=json.dumps({"value": v}).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=15) as resp:
                res = (200, json.loads(resp.read().decode()))
        except urllib.error.HTTPError as e:
            if e.code == 503:
                with lock:
                    headers[v] = e.headers.get("Retry-After")
            res = (e.code, None)
        with lock:
            out[v] = res

    threads = [threading.Thread(target=client, args=(v,)) for v in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    ok = [v for v, (st, _) in out.items() if st == 200]
    shed = [v for v, (st, _) in out.items() if st == 503]
    assert len(ok) + len(shed) == n
    # the 600 ms window holds admitted requests in flight, so the cap
    # must have shed the overflow — with 503, a Retry-After >= 1s, and
    # the shed counter agreeing
    assert len(shed) >= 1 and len(ok) >= 1
    assert all(h is not None and int(h) >= 1 for h in headers.values())
    assert subject.serve_metrics.shed == len(shed)
    for v in ok:
        assert out[v][1] == v * 3


def test_timed_out_requests_evicted_before_dispatch():
    """A request that times out while its window is still collecting is
    evicted: the window dispatches empty — no commit, no device work,
    no occupancy sample."""
    port = _next_port()
    subject, url = _gateway(
        port, window_ms=800.0, max_batch=1000, timeout_s=0.15
    )
    commits = [0]
    orig_commit = subject.commit

    def counting_commit():
        commits[0] += 1
        orig_commit()

    subject.commit = counting_commit
    _start_run()

    with pytest.raises(urllib.error.HTTPError) as e:
        _post(url, {"value": 1})
    assert e.value.code == 504
    assert subject.serve_metrics.timeouts == 1
    time.sleep(1.2)  # let the window timer fire and dispatch
    assert commits[0] == 0
    assert subject.serve_metrics.occupancy.total == 0


def test_get_coercion_failure_returns_400_naming_field():
    port = _next_port()

    class S(pw.Schema):
        value: int
        ratio: float = pw.column_definition(default_value=1.0)
        flag: bool = pw.column_definition(default_value=False)

    webserver = pw.io.http.PathwayWebserver(host="127.0.0.1", port=port)
    queries, writer = pw.io.http.rest_connector(
        webserver=webserver, schema=S, methods=("GET", "POST"),
        window_ms=0.0,
    )
    writer(queries.select(result=pw.this.value * 2))
    _start_run()

    base = f"http://127.0.0.1:{port}/"
    for qs, field in (
        ("value=abc", "value"),
        ("value=3&ratio=zz", "ratio"),
        ("value=3&flag=maybe", "flag"),
    ):
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(base + "?" + qs, timeout=10)
        assert e.value.code == 400
        body = json.loads(e.value.read().decode())
        assert field in body["error"]
    # valid coercions still work
    with urllib.request.urlopen(
        base + "?value=21&ratio=0.5&flag=true", timeout=10
    ) as resp:
        assert json.loads(resp.read().decode()) == 42


def test_serve_metrics_openmetrics_render():
    stats = ProberStats()
    m = ServeMetrics(route="/v1/retrieve")
    stats.mount_serve_metrics(m)
    stats.mount_serve_metrics(m)  # idempotent
    assert len(stats.serve) == 1
    for _ in range(5):
        m.on_request()
    m.on_shed()
    m.on_timeout()
    m.on_latency_ms(3.0)
    m.on_latency_ms(40.0)
    m.on_window(4)
    m.on_window(1)
    text = stats.render_openmetrics()
    assert 'serve_requests_total{route="/v1/retrieve"} 5' in text
    assert 'serve_shed_total{route="/v1/retrieve"} 1' in text
    assert 'serve_timeouts_total{route="/v1/retrieve"} 1' in text
    assert 'serve_window_commits_total{route="/v1/retrieve"} 2' in text
    assert "# TYPE serve_request_latency_ms histogram" in text
    # cumulative buckets: le="5" holds the 3ms sample, le="+Inf" both
    assert 'serve_request_latency_ms_bucket{route="/v1/retrieve",le="5"} 1' in text
    assert 'serve_request_latency_ms_bucket{route="/v1/retrieve",le="+Inf"} 2' in text
    assert 'serve_batch_occupancy_bucket{route="/v1/retrieve",le="4"} 2' in text
    assert 'serve_batch_occupancy_count{route="/v1/retrieve"} 2' in text
    assert 'serve_batch_occupancy_sum{route="/v1/retrieve"} 5' in text


def test_subscribe_on_batch_delivers_batched_changes():
    t = pw.debug.table_from_markdown(
        """
        a | b
        1 | 10
        2 | 20
        3 | 30
        """
    )
    batches = []
    rows = {}

    def on_batch(time_, changes):
        batches.append(list(changes))
        for key, row, diff in changes:
            assert diff == 1
            rows[key] = row

    pw.io.subscribe(t, on_batch=on_batch)
    pw.run()
    assert sum(len(b) for b in batches) == 3
    assert sorted((r["a"], r["b"]) for r in rows.values()) == [
        (1, 10), (2, 20), (3, 30),
    ]


def test_plan_doctor_blames_row_expanding_sink():
    t = pw.debug.table_from_markdown(
        """
        a
        1
        """
    )
    pw.io.subscribe(t, on_change=lambda *a: None)
    report = pw.analyze(t)
    sink = [d for d in report.diagnostics if d.code == "sink.row-expanding"]
    assert len(sink) == 1
    assert "on_batch" in (sink[0].hint or "")

    # the batched egress is clean
    pw.internals.parse_graph.G.clear()
    t2 = pw.debug.table_from_markdown(
        """
        a
        1
        """
    )
    pw.io.subscribe(t2, on_batch=lambda *a: None)
    report2 = pw.analyze(t2)
    assert not [
        d for d in report2.diagnostics if d.code == "sink.row-expanding"
    ]


def test_rest_response_sink_is_batched_in_plan():
    """The gateway's own response path must not trip the sink pass."""

    class S(pw.Schema):
        value: int

    webserver = pw.io.http.PathwayWebserver(host="127.0.0.1", port=_next_port())
    queries, writer = pw.io.http.rest_connector(webserver=webserver, schema=S)
    writer(queries.select(result=pw.this.value))
    report = pw.analyze(queries)
    assert not [
        d for d in report.diagnostics if d.code == "sink.row-expanding"
    ]


def _load_bench():
    import importlib.util
    import os

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "bench.py",
    )
    spec = importlib.util.spec_from_file_location("bench_mod", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# the measured round-5 tunneled curve (BENCH_full.json) the model must
# validate against: the OLD model's error GREW with load (0.04 → 0.21 →
# 0.56); the extended pipelined model must hold it flat
_ROUND5_CURVE = {
    "metric": "rag_qps_vs_clients",
    "curve": [
        {"n_clients": 32, "qps": 316.2, "mean_ms": 101.17},
        {"n_clients": 128, "qps": 1458.5, "mean_ms": 87.35},
        {"n_clients": 512, "qps": 7514.1, "mean_ms": 67.47},
    ],
    "device_capacity_qps": 5870.6,
    "device_ms_per_batch32": 5.45,
    "transport_floor_p50_ms": 94.8,
}


def test_extended_latency_model_error_flat_under_load():
    bench = _load_bench()
    model = bench.bench_latency_model(_ROUND5_CURVE)
    errs = [p["rel_err"] for p in model["validation"]]
    assert model["mean_rel_err"] <= 0.10, model["mean_rel_err"]
    # the high-load point must no longer be the worst one
    assert errs[-1] <= 0.05, errs
    assert max(errs) <= 0.15, errs
    # calibrated transport/pipeline parameters are recorded
    assert 0.0 < model["inputs"]["rho_transport_overlap_loss"] < 1.0
    assert model["inputs"]["kappa_pipelined_capacity_ratio"] >= 1.0
    # colocated prediction clears the acceptance bar: >= 5k qps/chip at
    # < 15 ms p50
    knee = model["colocated_knee"]
    assert knee["qps"] >= 5000.0 and knee["p50_ms"] < 15.0


def test_colocated_projection_entry_shape():
    bench = _load_bench()
    model = bench.bench_latency_model(_ROUND5_CURVE)
    entry = bench._colocated_projection(model, 1_000_000)
    assert entry["metric"] == "rag_colocated_qps"
    assert entry["projected"] is True and entry["colocated"] is False
    assert entry["value"] >= 5000.0 and entry["p50_ms"] < 15.0
    assert entry["n_docs"] == 1_000_000
    assert entry["vs_baseline"] >= 1.0


def test_serve_knobs_registered_and_wired(monkeypatch):
    from pathway_tpu.analysis.knobs import KNOBS, validate_environment

    for name in (
        "PATHWAY_REST_TIMEOUT_S", "PATHWAY_SERVE_WINDOW_MS",
        "PATHWAY_SERVE_MAX_BATCH", "PATHWAY_SERVE_QUEUE_CAP",
        "PATHWAY_SERVE_WORKERS",
    ):
        assert name in KNOBS
    monkeypatch.setenv("PATHWAY_REST_TIMEOUT_S", "17.5")
    monkeypatch.setenv("PATHWAY_SERVE_WINDOW_MS", "9")
    monkeypatch.setenv("PATHWAY_SERVE_MAX_BATCH", "8")
    monkeypatch.setenv("PATHWAY_SERVE_QUEUE_CAP", "99")
    monkeypatch.setenv("PATHWAY_SERVE_WORKERS", "2")
    assert validate_environment() == []

    class S(pw.Schema):
        value: int

    webserver = pw.io.http.PathwayWebserver(
        host="127.0.0.1", port=_next_port()
    )
    pw.io.http.rest_connector(webserver=webserver, schema=S)
    subject = webserver._routes[0][2].__self__
    assert subject.timeout_s == 17.5
    assert subject.window_s == pytest.approx(0.009)
    assert subject.max_batch == 8
    assert subject.queue_cap == 99
    assert subject.workers == 2

    # out-of-range serve knob is a startup rejection
    monkeypatch.setenv("PATHWAY_SERVE_MAX_BATCH", "0")
    findings = validate_environment()
    assert any(n == "PATHWAY_SERVE_MAX_BATCH" for n, _, _ in findings)


# ===========================================================================
# ISSUE 9: serving through rollback — park/replay, brownout, frontend
# ===========================================================================

def test_serve_park_replay_protocol_transitions():
    """The park/replay decisions are pure protocol transitions; pin the
    exactly-once boundary at the decision level: responded requests are
    NEVER in the park set, and the replay split honors deadlines."""
    from pathway_tpu.parallel import protocol as proto

    # a request whose response was delivered must not replay
    assert proto.serve_park([1, 2, 3], [2]) == [1, 3]
    assert proto.serve_park([1, 2], [1, 2]) == []
    replay, expired = proto.serve_replay_split(
        [5, 6, 7], 10.0, {5: 20.0, 6: 3.0, 7: 10.5}
    )
    assert replay == [5, 7] and expired == [6]
    # admission: recovering parks up to the budget, then sheds
    assert proto.serve_admit("serving", 0, 8, 0, 4) == "admit"
    assert proto.serve_admit("serving", 8, 8, 0, 4) == "shed"
    assert proto.serve_admit("recovering", 0, 8, 3, 4) == "park"
    assert proto.serve_admit("recovering", 0, 8, 4, 4) == "shed"
    assert proto.serve_admit("draining", 0, 8, 0, 4) == "shed"
    # frontend readiness states
    assert proto.serve_frontend_state(True, False) == "serving"
    assert proto.serve_frontend_state(False, False) == "recovering"
    assert proto.serve_frontend_state(True, True) == "draining"
    # Retry-After sized by observed restart time, never < 1s
    assert proto.serve_retry_after(4.2) == 5
    assert proto.serve_retry_after(0.0) == 1
    assert proto.serve_retry_after(9999.0) == 600
    # breaker: threshold opens, cooldown half-opens, 0 disables
    assert proto.breaker_decide("closed", 2, 3, 0.0, 5.0) == "closed"
    assert proto.breaker_decide("closed", 3, 3, 0.0, 5.0) == "open"
    assert proto.breaker_decide("open", 3, 3, 1.0, 5.0) == "open"
    assert proto.breaker_decide("open", 3, 3, 6.0, 5.0) == "half_open"
    assert proto.breaker_decide("closed", 99, 0, 0.0, 5.0) == "closed"


def test_serving_checker_transitions_are_the_engine_objects():
    """Anti-drift pin (the NBDecision/meshcheck pattern): the serving
    checker drives the very function objects the frontend and gateway
    execute — same-object identity, so checker and engine cannot
    diverge."""
    from pathway_tpu.analysis import meshcheck as mc
    from pathway_tpu.parallel import protocol as proto

    t = mc.get_serve_transitions()
    for name in mc.ServeTransitions.NAMES:
        assert getattr(t, name) is proto.TRANSITIONS[name], name
        assert proto.TRANSITIONS[name] is getattr(proto, name), name


def test_serving_checker_clean_protocol_verifies():
    """Exhaustive park/replay model: every interleaving of arrivals,
    window commits, responses, crashes and reattaches ends with every
    admitted request answered exactly once (incl. deadline 503s)."""
    from pathway_tpu.analysis import meshcheck as mc

    report = mc.check_serving()
    assert report.ok, report.render()
    assert report.terminals > 0 and report.rollbacks_explored > 0
    # with a deeper fault budget too (two rollbacks back-to-back)
    report2 = mc.check_serving(mc.ServeCheckConfig(fault_budget=2))
    assert report2.ok, report2.render()


def test_serving_checker_catches_replay_committed_window_mutant():
    """The exactly-once boundary, adversarially: a park set that stops
    filtering responded requests (replay_committed_window) MUST be
    caught as a double-response with a minimal, replayable trace."""
    from pathway_tpu.analysis import meshcheck as mc

    report = mc.check_serving(
        mc.ServeCheckConfig(mutate="replay_committed_window")
    )
    assert not report.ok
    v = report.violations[0]
    assert v.kind == "double-response"
    plan = v.fault_plan()
    assert plan is not None and plan["rules"], v.to_dict()
    rule = plan["rules"][0]
    assert rule["point"] == "serve.dispatch"
    assert rule["action"] == "crash"
    assert rule["phase"] in ("window", "committed")
    # the trace names the crash and the replay that answered twice
    labels = " | ".join(s["label"] for s in v.trace)
    assert "CRASH" in labels and "reattach" in labels


def test_all_parked_window_commits_nothing():
    """The backend half of parking: windows aborted on the epoch-abort
    path have every member evicted, so a racing dispatch commits
    NOTHING for them — and the abort is counted."""
    port = _next_port()
    subject, url = _gateway(port, window_ms=600.0, max_batch=1000)
    commits = [0]
    orig_commit = subject.commit

    def counting_commit():
        commits[0] += 1
        orig_commit()

    subject.commit = counting_commit
    # stage two closed windows + one collecting window directly (the
    # dispatch workers are not running: no pw.run, no requests)
    from pathway_tpu.io.http._server import _PendingRequest

    class _F:
        def done(self):
            return True

    w1 = [_PendingRequest(("k", i), {"value": i}, _F()) for i in range(3)]
    w2 = [_PendingRequest(("k", 9), {"value": 9}, _F())]
    subject._windows_q.put(w1)
    subject._windows_q.put(w2)
    subject._window = [_PendingRequest(("k", 5), {"value": 5}, _F())]

    aborted = subject.abort_windows_for_rollback()
    assert aborted == 3  # two queued + the collecting window
    assert subject.serve_metrics.windows_aborted == 3
    assert all(p.evicted for p in w1 + w2)
    assert all(p.evicted for p in subject._window)
    # idempotent: a second abort finds nothing new
    assert subject.abort_windows_for_rollback() == 0
    # a dispatch racing the abort sees only evicted members: no commit,
    # no occupancy sample
    subject._dispatch_window(w1)
    subject._dispatch_window(w2)
    assert commits[0] == 0
    assert subject.serve_metrics.occupancy.total == 0


def test_breaker_opens_on_dispatch_failures_then_brownout(monkeypatch):
    """Consecutive dispatch failures open the breaker; with
    PATHWAY_SERVE_BROWNOUT=1 and a brownout_answer hook the gateway then
    answers DEGRADED (Degraded: true header, browned_out counter)
    instead of shedding."""
    from pathway_tpu.internals import faults

    monkeypatch.setenv("PATHWAY_SERVE_BROWNOUT", "1")
    port = _next_port()

    class S(pw.Schema):
        value: int

    webserver = pw.io.http.PathwayWebserver(host="127.0.0.1", port=port)
    queries, writer = pw.io.http.rest_connector(
        webserver=webserver, schema=S, window_ms=20.0,
        brownout_answer=lambda values: values["value"] * 3,
        breaker_threshold=1, breaker_cooldown_s=300.0,
    )
    writer(queries.select(result=pw.this.value * 3))
    subject = webserver._routes[0][2].__self__
    faults.install_plan(
        {
            "seed": 1,
            "rules": [
                {
                    "point": "serve.dispatch", "phase": "window",
                    "action": "raise",
                }
            ],
        }
    )
    try:
        _start_run()
        url = f"http://127.0.0.1:{port}/"
        # first request: its window dispatch fails (injected) — the
        # client gets a terminal 500 and the breaker opens
        req = urllib.request.Request(
            url, data=json.dumps({"value": 1}).encode(),
            headers={"Content-Type": "application/json"}, method="POST",
        )
        with pytest.raises(urllib.error.HTTPError) as e1:
            urllib.request.urlopen(req, timeout=15)
        assert e1.value.code == 500
        deadline = time.monotonic() + 10
        while subject._breaker != "open":
            assert time.monotonic() < deadline, subject._breaker
            time.sleep(0.05)
        # second request: browned out — degraded answer, no dataflow
        req2 = urllib.request.Request(
            url, data=json.dumps({"value": 7}).encode(),
            headers={"Content-Type": "application/json"}, method="POST",
        )
        with urllib.request.urlopen(req2, timeout=15) as resp:
            assert resp.headers.get("Degraded") == "true"
            assert json.loads(resp.read().decode()) == 21
        assert subject.serve_metrics.browned_out == 1
        assert subject.serve_metrics.breaker_state == "open"
        # metrics render carries the new families
        from pathway_tpu.internals.monitoring import ProberStats

        stats = ProberStats()
        stats.mount_serve_metrics(subject.serve_metrics)
        text = stats.render_openmetrics()
        assert "serve_browned_out_total" in text
        assert 'serve_breaker_state{route="/"} 2' in text
    finally:
        faults.reset()


def test_breaker_shed_503_when_brownout_off(monkeypatch):
    """Breaker open without brownout: requests shed 503 + Retry-After
    (the cooldown), never hang into the failing dispatch path."""
    from pathway_tpu.internals import faults

    monkeypatch.delenv("PATHWAY_SERVE_BROWNOUT", raising=False)
    port = _next_port()
    subject, url = _gateway(
        port, window_ms=20.0, breaker_threshold=1,
        breaker_cooldown_s=300.0,
    )
    faults.install_plan(
        {
            "seed": 1,
            "rules": [
                {
                    "point": "serve.dispatch", "phase": "window",
                    "action": "raise",
                }
            ],
        }
    )
    try:
        _start_run()
        with pytest.raises(urllib.error.HTTPError):
            _post(url, {"value": 1})
        deadline = time.monotonic() + 10
        while subject._breaker != "open":
            assert time.monotonic() < deadline, subject._breaker
            time.sleep(0.05)
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(url, {"value": 2})
        assert e.value.code == 503
        assert e.value.headers.get("Retry-After") is not None
        assert subject.serve_metrics.shed >= 1
    finally:
        faults.reset()


def test_backend_port_env_rebinds_gateway_to_loopback(monkeypatch):
    """Frontend mode: PATHWAY_SERVE_BACKEND_PORT makes the gateway bind
    the loopback backend port while keeping its public identity — and
    with PATHWAY_SERVE_PUBLIC_PORT set, ONLY the webserver configured
    on the frontend's public port rewrites (a second webserver keeps
    its own port instead of colliding on the backend bind)."""
    monkeypatch.setenv("PATHWAY_SERVE_BACKEND_PORT", "9555")
    web = pw.io.http.PathwayWebserver(host="0.0.0.0", port=8080)
    assert (web.host, web.port) == ("127.0.0.1", 9555)
    assert (web.public_host, web.public_port) == ("0.0.0.0", 8080)
    monkeypatch.setenv("PATHWAY_SERVE_PUBLIC_PORT", "8080")
    web_match = pw.io.http.PathwayWebserver(host="0.0.0.0", port=8080)
    assert (web_match.host, web_match.port) == ("127.0.0.1", 9555)
    web_other = pw.io.http.PathwayWebserver(host="0.0.0.0", port=8082)
    assert (web_other.host, web_other.port) == ("0.0.0.0", 8082)
    monkeypatch.delenv("PATHWAY_SERVE_BACKEND_PORT")
    monkeypatch.delenv("PATHWAY_SERVE_PUBLIC_PORT")
    web2 = pw.io.http.PathwayWebserver(host="0.0.0.0", port=8081)
    assert (web2.host, web2.port) == ("0.0.0.0", 8081)


def test_frontend_parks_then_deadline_503_with_retry_after():
    """A request admitted while no backend epoch exists parks; when its
    deadline budget expires still parked it gets a terminal 503 with
    Retry-After — never a dropped connection. /healthz reports
    recovering (503) meanwhile."""
    from pathway_tpu.io.http import ServingFrontend

    port = _next_port()
    backend_port = _next_port()  # nothing ever listens here
    fe = ServingFrontend(
        host="127.0.0.1", port=port, backend_port=backend_port,
        timeout_s=0.8,
    ).start()
    try:
        with pytest.raises(urllib.error.HTTPError) as hz:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=5
            )
        assert hz.value.code == 503
        assert json.loads(hz.value.read().decode())["state"] == "recovering"
        t0 = time.monotonic()
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(f"http://127.0.0.1:{port}/", {"value": 1}, timeout=15)
        assert e.value.code == 503
        assert int(e.value.headers.get("Retry-After")) >= 1
        assert 0.5 < time.monotonic() - t0 < 10
        m = fe.metrics
        assert m.parked == 1 and m.deadline_expired == 1
        assert m.admitted == m.responses + m.deadline_expired + m.timeouts
        # the satellite metric families render
        text = m.render()
        for fam in (
            "serve_parked_total", "serve_replayed_total",
            "serve_deadline_expired_total",
            "serve_epoch_handoff_seconds_bucket",
        ):
            assert fam in text, fam
    finally:
        fe.stop()


def test_frontend_draining_sheds_with_retry_after():
    from pathway_tpu.io.http import ServingFrontend

    port = _next_port()
    fe = ServingFrontend(
        host="127.0.0.1", port=port, backend_port=_next_port(),
        timeout_s=5.0,
    ).start()
    try:
        fe.drain()
        time.sleep(0.2)
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(f"http://127.0.0.1:{port}/", {"value": 1}, timeout=10)
        assert e.value.code == 503
        assert e.value.headers.get("Retry-After") is not None
        assert fe.metrics.shed == 1 and fe.metrics.admitted == 0
        assert fe.state() == "draining"
    finally:
        fe.stop()


def test_keepalive_session_retries_503_honoring_retry_after():
    """Satellite: a 503 with Retry-After is the documented backpressure
    contract — with retries opted in the session honors it (bounded);
    without, it stays terminal. 503s lacking Retry-After never retry."""
    import http.server

    from pathway_tpu.io.http import HttpError, KeepAliveSession

    hits = {"n": 0, "bare": 0}

    class H(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            self.rfile.read(int(self.headers.get("Content-Length", 0)))
            if self.path == "/bare503":
                hits["bare"] += 1
                body = b'{"error": "no retry-after"}'
                self.send_response(503)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            hits["n"] += 1
            if hits["n"] <= 2:
                body = b'{"error": "overloaded"}'
                self.send_response(503)
                self.send_header("Retry-After", "0")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                body = b'42'
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        def log_message(self, *a):
            pass

    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    port = srv.server_address[1]
    try:
        # opted in: two sheds then success
        s = KeepAliveSession(f"http://127.0.0.1:{port}", retries=3)
        assert s.post("/", {}) == 42
        assert hits["n"] == 3
        # budget exhausted -> the last 503 propagates with headers
        hits["n"] = -10
        s2 = KeepAliveSession(f"http://127.0.0.1:{port}", retries=1)
        with pytest.raises(HttpError) as e:
            s2.post("/", {})
        assert e.value.code == 503
        assert e.value.headers.get("Retry-After") == "0"
        # not opted in: terminal on the first 503 (old behavior)
        hits["n"] = 0
        s3 = KeepAliveSession(f"http://127.0.0.1:{port}")
        with pytest.raises(HttpError):
            s3.post("/", {})
        assert hits["n"] == 1
        # no Retry-After -> no retry even when opted in
        s4 = KeepAliveSession(f"http://127.0.0.1:{port}", retries=5)
        with pytest.raises(HttpError):
            s4.post("/bare503", {})
        assert hits["bare"] == 1
    finally:
        srv.shutdown()


def test_rag_and_vector_clients_expose_retries():
    from pathway_tpu.xpacks.llm.question_answering import RAGClient
    from pathway_tpu.xpacks.llm.vector_store import VectorStoreClient

    c1 = VectorStoreClient(host="127.0.0.1", port=1, retries=2)
    assert c1._session.retries == 2
    c2 = RAGClient(host="127.0.0.1", port=1, retries=3)
    assert c2._session.retries == 3
    # default stays terminal-on-503
    assert VectorStoreClient(host="127.0.0.1", port=1)._session.retries == 0


def test_serve_rollback_knobs_registered(monkeypatch):
    from pathway_tpu.analysis.knobs import KNOBS, validate_environment

    for name in (
        "PATHWAY_SERVE_BROWNOUT", "PATHWAY_SERVE_BREAKER_THRESHOLD",
        "PATHWAY_SERVE_BREAKER_COOLDOWN_S", "PATHWAY_SERVE_PARK_BUDGET",
        "PATHWAY_SERVE_BACKEND_PORT",
    ):
        assert name in KNOBS, name
    monkeypatch.setenv("PATHWAY_SERVE_BROWNOUT", "1")
    monkeypatch.setenv("PATHWAY_SERVE_BREAKER_THRESHOLD", "2")
    monkeypatch.setenv("PATHWAY_SERVE_PARK_BUDGET", "64")
    monkeypatch.setenv("PATHWAY_SERVE_BACKEND_PORT", "9000")
    assert validate_environment() == []
    monkeypatch.setenv("PATHWAY_SERVE_BACKEND_PORT", "0")
    assert any(
        n == "PATHWAY_SERVE_BACKEND_PORT"
        for n, _, _ in validate_environment()
    )


def test_readyz_states_serving_draining_recovering():
    """Readiness states on the metrics server's /readyz: serving
    answers 200 ok; draining/recovering answer 503 with the state name
    so load balancers rotate away during the blip. /healthz stays an
    unconditional-200 LIVENESS probe — a 503 there during a rollback
    would make kubelet kill the pod mid-recovery."""
    import socket as _socket

    from pathway_tpu.internals.monitoring import (
        ProberStats, start_http_server,
    )

    probe = _socket.socket()
    probe.bind(("127.0.0.1", 0))
    port = probe.getsockname()[1]
    probe.close()
    stats = ProberStats()
    start_http_server(stats, port)
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/readyz", timeout=5
    ) as r:
        assert r.status == 200 and r.read() == b"ok\n"
    for state in ("draining", "recovering"):
        stats.set_health_state(state)
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/readyz", timeout=5
            )
        assert e.value.code == 503
        assert e.value.read().decode().strip() == state
        # liveness is state-independent
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=5
        ) as r:
            assert r.status == 200 and r.read() == b"ok\n"


def test_serve_fault_points_registered():
    from pathway_tpu.internals.faults import POINTS

    for p in ("serve.dispatch", "serve.park", "serve.replay"):
        assert p in POINTS, p


def test_retry_after_honesty_under_memory_ladder():
    """503s minted during a memory-ladder episode size Retry-After from
    the SAME pace_retry_after transition the pacing model checks —
    in-flight backlog over the EWMA drain rate — not the rolling-qps
    guess that reads near-zero exactly when the governor throttles."""
    import math

    from pathway_tpu.parallel import protocol as proto

    port = _next_port()
    subject, _url = _gateway(port)

    # a seeded drain rate: 2 responses/s with 7 in flight -> ceil(3.5)
    subject._done_rate_ewma = 2.0
    subject._inflight = 7
    for state in ("pacing", "brownout", "abort"):
        want = max(1, math.ceil(proto.pace_retry_after(7, 2.0)))
        assert subject._retry_after_s(state) == want == 4
    # drain rate unobserved -> the clamped long horizon, never "now"
    subject._done_rate_ewma = 0.0
    assert subject._retry_after_s("brownout") == 600
    # nothing in flight -> floor of one pending unit at the seeded rate
    subject._done_rate_ewma = 2.0
    subject._inflight = 0
    assert subject._retry_after_s("pacing") == max(
        1, math.ceil(proto.pace_retry_after(1, 2.0))
    )
    # ladder ok -> the legacy rolling-qps path is untouched
    assert subject._retry_after_s("ok") == 1
    assert subject._retry_after_s() == 1


def test_memory_brownout_sheds_503_then_recovers():
    """The serving breaker consumes the memory signal: while the
    installed accountant's ladder reads brownout/abort, requests shed
    503 with a paced Retry-After; once the ladder steps back to ok the
    same gateway serves 200s again."""
    from pathway_tpu.internals import memory as _memory

    port = _next_port()
    subject, url = _gateway(port)
    _start_run()
    try:
        assert _post(url, {"value": 5}) == 5 * 3

        acct = _memory.MemoryAccountant(
            environ={"PATHWAY_MEM_BUDGET_MB": "1"}
        )
        acct.state = "brownout"
        _memory.install(acct)
        shed_before = subject.serve_metrics.shed
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(url, {"value": 6})
        assert e.value.code == 503
        assert int(e.value.headers.get("Retry-After")) >= 1
        assert "memory pressure" in e.value.read().decode()
        assert subject.serve_metrics.shed == shed_before + 1

        acct.state = "ok"
        assert _post(url, {"value": 7}) == 7 * 3
    finally:
        _memory.install(None)
