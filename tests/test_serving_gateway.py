"""Async batching gateway tests (io/http/_server.py): one commit per
batch window under load, bit-identical parity with the per-request path
on an out-of-order mixed-timeout workload, admission shedding with
Retry-After, timed-out-request eviction, GET coercion 400s, serve
metrics, the batched subscribe egress, and the Plan Doctor's
row-expanding-sink diagnostic."""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

import pathway_tpu as pw
from pathway_tpu.internals.monitoring import ProberStats, ServeMetrics

_PORT = [9120]


def _next_port():
    _PORT[0] += 1
    return _PORT[0]


def _post(url, payload, timeout=15):
    req = urllib.request.Request(
        url,
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def _gateway(port, pipeline=None, **kw):
    """rest_connector echo server; returns (subject, url)."""

    class S(pw.Schema):
        value: int

    webserver = pw.io.http.PathwayWebserver(host="127.0.0.1", port=port)
    queries, writer = pw.io.http.rest_connector(
        webserver=webserver, schema=S, **kw
    )
    if pipeline is None:
        writer(queries.select(result=pw.this.value * 3))
    else:
        writer(pipeline(queries))
    subject = webserver._routes[0][2].__self__
    return subject, f"http://127.0.0.1:{port}/"


def _start_run():
    t = threading.Thread(target=pw.run, daemon=True)
    t.start()
    time.sleep(1.0)
    return t


def _fire(url, values, timeout=15):
    """Concurrent closed clients; returns {value: (status, result)}."""
    out = {}
    lock = threading.Lock()

    def client(v):
        try:
            res = _post(url, {"value": v}, timeout=timeout)
            status = 200
        except urllib.error.HTTPError as e:
            res = None
            status = e.code
        except Exception as e:  # client-side timeout etc.
            res = None
            status = repr(e)
        with lock:
            out[v] = (status, res)

    threads = [threading.Thread(target=client, args=(v,)) for v in values]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return out


def test_one_commit_per_window_under_load():
    """The pinned tentpole invariant: N concurrent requests coalesce
    into a handful of windows, each window is exactly ONE subject
    commit, and the occupancy histogram proves multi-request windows."""
    port = _next_port()
    subject, url = _gateway(
        port, window_ms=60.0, max_batch=64, workers=1
    )
    commits = [0]
    orig_commit = subject.commit

    def counting_commit():
        commits[0] += 1
        orig_commit()

    subject.commit = counting_commit
    _start_run()

    n = 48
    out = _fire(url, range(n))
    assert all(st == 200 and res == v * 3 for v, (st, res) in out.items())
    m = subject.serve_metrics
    assert m.requests == n
    # every request is accounted to exactly one window, and coalescing
    # engaged: far fewer commits than requests, occupancy sums to n
    assert m.occupancy.sum == n
    assert m.commits == m.occupancy.total == commits[0]
    assert commits[0] <= n // 4, (commits[0], n)
    # multi-request windows: at least one window carried > 2 requests
    # (buckets are cumulative edges 1,2,4,...: everything above the
    # le=2 bucket had occupancy > 2)
    assert m.occupancy.total - sum(m.occupancy.counts[:2]) >= 1
    assert m.shed == 0 and m.timeouts == 0


def test_parity_with_per_request_path_out_of_order_mixed_timeouts():
    """Batched gateway vs per-request path (window 0 / max_batch 1) on
    an out-of-order, mixed-timeout workload: clients fire concurrently
    (arrival order is scrambled vs completion order — windows group
    arbitrary subsets), and the values >= 900 are filtered out of the
    response table so their clients hit the request deadline while
    later requests already completed. Every completed response must be
    bit-identical between the two paths, and exactly the filtered
    requests 504 on both."""

    def pipeline(queries):
        return queries.filter(pw.this.value < 900).select(
            result=pw.this.value * 7 + 1
        )

    values = list(range(40)) + [900, 901]
    results = {}
    for mode, kw in (
        ("batched", dict(window_ms=25.0, max_batch=16)),
        ("per_request", dict(window_ms=0.0, max_batch=1)),
    ):
        pw.internals.parse_graph.G.clear()
        port = _next_port()
        subject, url = _gateway(
            port, pipeline=pipeline, timeout_s=1.5, **kw
        )
        _start_run()
        results[mode] = _fire(url, values)
        assert subject.serve_metrics.timeouts == 2, mode

    for v in values:
        assert results["batched"][v] == results["per_request"][v], v
        if v < 900:
            assert results["batched"][v] == (200, v * 7 + 1)
        else:
            assert results["batched"][v][0] == 504


def test_admission_shedding_503_with_retry_after():
    port = _next_port()
    subject, url = _gateway(
        port, window_ms=600.0, max_batch=1000, queue_cap=4
    )
    _start_run()

    n = 16
    headers = {}
    out = {}
    lock = threading.Lock()

    def client(v):
        req = urllib.request.Request(
            url,
            data=json.dumps({"value": v}).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=15) as resp:
                res = (200, json.loads(resp.read().decode()))
        except urllib.error.HTTPError as e:
            if e.code == 503:
                with lock:
                    headers[v] = e.headers.get("Retry-After")
            res = (e.code, None)
        with lock:
            out[v] = res

    threads = [threading.Thread(target=client, args=(v,)) for v in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    ok = [v for v, (st, _) in out.items() if st == 200]
    shed = [v for v, (st, _) in out.items() if st == 503]
    assert len(ok) + len(shed) == n
    # the 600 ms window holds admitted requests in flight, so the cap
    # must have shed the overflow — with 503, a Retry-After >= 1s, and
    # the shed counter agreeing
    assert len(shed) >= 1 and len(ok) >= 1
    assert all(h is not None and int(h) >= 1 for h in headers.values())
    assert subject.serve_metrics.shed == len(shed)
    for v in ok:
        assert out[v][1] == v * 3


def test_timed_out_requests_evicted_before_dispatch():
    """A request that times out while its window is still collecting is
    evicted: the window dispatches empty — no commit, no device work,
    no occupancy sample."""
    port = _next_port()
    subject, url = _gateway(
        port, window_ms=800.0, max_batch=1000, timeout_s=0.15
    )
    commits = [0]
    orig_commit = subject.commit

    def counting_commit():
        commits[0] += 1
        orig_commit()

    subject.commit = counting_commit
    _start_run()

    with pytest.raises(urllib.error.HTTPError) as e:
        _post(url, {"value": 1})
    assert e.value.code == 504
    assert subject.serve_metrics.timeouts == 1
    time.sleep(1.2)  # let the window timer fire and dispatch
    assert commits[0] == 0
    assert subject.serve_metrics.occupancy.total == 0


def test_get_coercion_failure_returns_400_naming_field():
    port = _next_port()

    class S(pw.Schema):
        value: int
        ratio: float = pw.column_definition(default_value=1.0)
        flag: bool = pw.column_definition(default_value=False)

    webserver = pw.io.http.PathwayWebserver(host="127.0.0.1", port=port)
    queries, writer = pw.io.http.rest_connector(
        webserver=webserver, schema=S, methods=("GET", "POST"),
        window_ms=0.0,
    )
    writer(queries.select(result=pw.this.value * 2))
    _start_run()

    base = f"http://127.0.0.1:{port}/"
    for qs, field in (
        ("value=abc", "value"),
        ("value=3&ratio=zz", "ratio"),
        ("value=3&flag=maybe", "flag"),
    ):
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(base + "?" + qs, timeout=10)
        assert e.value.code == 400
        body = json.loads(e.value.read().decode())
        assert field in body["error"]
    # valid coercions still work
    with urllib.request.urlopen(
        base + "?value=21&ratio=0.5&flag=true", timeout=10
    ) as resp:
        assert json.loads(resp.read().decode()) == 42


def test_serve_metrics_openmetrics_render():
    stats = ProberStats()
    m = ServeMetrics(route="/v1/retrieve")
    stats.mount_serve_metrics(m)
    stats.mount_serve_metrics(m)  # idempotent
    assert len(stats.serve) == 1
    for _ in range(5):
        m.on_request()
    m.on_shed()
    m.on_timeout()
    m.on_latency_ms(3.0)
    m.on_latency_ms(40.0)
    m.on_window(4)
    m.on_window(1)
    text = stats.render_openmetrics()
    assert 'serve_requests_total{route="/v1/retrieve"} 5' in text
    assert 'serve_shed_total{route="/v1/retrieve"} 1' in text
    assert 'serve_timeouts_total{route="/v1/retrieve"} 1' in text
    assert 'serve_window_commits_total{route="/v1/retrieve"} 2' in text
    assert "# TYPE serve_request_latency_ms histogram" in text
    # cumulative buckets: le="5" holds the 3ms sample, le="+Inf" both
    assert 'serve_request_latency_ms_bucket{route="/v1/retrieve",le="5"} 1' in text
    assert 'serve_request_latency_ms_bucket{route="/v1/retrieve",le="+Inf"} 2' in text
    assert 'serve_batch_occupancy_bucket{route="/v1/retrieve",le="4"} 2' in text
    assert 'serve_batch_occupancy_count{route="/v1/retrieve"} 2' in text
    assert 'serve_batch_occupancy_sum{route="/v1/retrieve"} 5' in text


def test_subscribe_on_batch_delivers_batched_changes():
    t = pw.debug.table_from_markdown(
        """
        a | b
        1 | 10
        2 | 20
        3 | 30
        """
    )
    batches = []
    rows = {}

    def on_batch(time_, changes):
        batches.append(list(changes))
        for key, row, diff in changes:
            assert diff == 1
            rows[key] = row

    pw.io.subscribe(t, on_batch=on_batch)
    pw.run()
    assert sum(len(b) for b in batches) == 3
    assert sorted((r["a"], r["b"]) for r in rows.values()) == [
        (1, 10), (2, 20), (3, 30),
    ]


def test_plan_doctor_blames_row_expanding_sink():
    t = pw.debug.table_from_markdown(
        """
        a
        1
        """
    )
    pw.io.subscribe(t, on_change=lambda *a: None)
    report = pw.analyze(t)
    sink = [d for d in report.diagnostics if d.code == "sink.row-expanding"]
    assert len(sink) == 1
    assert "on_batch" in (sink[0].hint or "")

    # the batched egress is clean
    pw.internals.parse_graph.G.clear()
    t2 = pw.debug.table_from_markdown(
        """
        a
        1
        """
    )
    pw.io.subscribe(t2, on_batch=lambda *a: None)
    report2 = pw.analyze(t2)
    assert not [
        d for d in report2.diagnostics if d.code == "sink.row-expanding"
    ]


def test_rest_response_sink_is_batched_in_plan():
    """The gateway's own response path must not trip the sink pass."""

    class S(pw.Schema):
        value: int

    webserver = pw.io.http.PathwayWebserver(host="127.0.0.1", port=_next_port())
    queries, writer = pw.io.http.rest_connector(webserver=webserver, schema=S)
    writer(queries.select(result=pw.this.value))
    report = pw.analyze(queries)
    assert not [
        d for d in report.diagnostics if d.code == "sink.row-expanding"
    ]


def _load_bench():
    import importlib.util
    import os

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "bench.py",
    )
    spec = importlib.util.spec_from_file_location("bench_mod", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# the measured round-5 tunneled curve (BENCH_full.json) the model must
# validate against: the OLD model's error GREW with load (0.04 → 0.21 →
# 0.56); the extended pipelined model must hold it flat
_ROUND5_CURVE = {
    "metric": "rag_qps_vs_clients",
    "curve": [
        {"n_clients": 32, "qps": 316.2, "mean_ms": 101.17},
        {"n_clients": 128, "qps": 1458.5, "mean_ms": 87.35},
        {"n_clients": 512, "qps": 7514.1, "mean_ms": 67.47},
    ],
    "device_capacity_qps": 5870.6,
    "device_ms_per_batch32": 5.45,
    "transport_floor_p50_ms": 94.8,
}


def test_extended_latency_model_error_flat_under_load():
    bench = _load_bench()
    model = bench.bench_latency_model(_ROUND5_CURVE)
    errs = [p["rel_err"] for p in model["validation"]]
    assert model["mean_rel_err"] <= 0.10, model["mean_rel_err"]
    # the high-load point must no longer be the worst one
    assert errs[-1] <= 0.05, errs
    assert max(errs) <= 0.15, errs
    # calibrated transport/pipeline parameters are recorded
    assert 0.0 < model["inputs"]["rho_transport_overlap_loss"] < 1.0
    assert model["inputs"]["kappa_pipelined_capacity_ratio"] >= 1.0
    # colocated prediction clears the acceptance bar: >= 5k qps/chip at
    # < 15 ms p50
    knee = model["colocated_knee"]
    assert knee["qps"] >= 5000.0 and knee["p50_ms"] < 15.0


def test_colocated_projection_entry_shape():
    bench = _load_bench()
    model = bench.bench_latency_model(_ROUND5_CURVE)
    entry = bench._colocated_projection(model, 1_000_000)
    assert entry["metric"] == "rag_colocated_qps"
    assert entry["projected"] is True and entry["colocated"] is False
    assert entry["value"] >= 5000.0 and entry["p50_ms"] < 15.0
    assert entry["n_docs"] == 1_000_000
    assert entry["vs_baseline"] >= 1.0


def test_serve_knobs_registered_and_wired(monkeypatch):
    from pathway_tpu.analysis.knobs import KNOBS, validate_environment

    for name in (
        "PATHWAY_REST_TIMEOUT_S", "PATHWAY_SERVE_WINDOW_MS",
        "PATHWAY_SERVE_MAX_BATCH", "PATHWAY_SERVE_QUEUE_CAP",
        "PATHWAY_SERVE_WORKERS",
    ):
        assert name in KNOBS
    monkeypatch.setenv("PATHWAY_REST_TIMEOUT_S", "17.5")
    monkeypatch.setenv("PATHWAY_SERVE_WINDOW_MS", "9")
    monkeypatch.setenv("PATHWAY_SERVE_MAX_BATCH", "8")
    monkeypatch.setenv("PATHWAY_SERVE_QUEUE_CAP", "99")
    monkeypatch.setenv("PATHWAY_SERVE_WORKERS", "2")
    assert validate_environment() == []

    class S(pw.Schema):
        value: int

    webserver = pw.io.http.PathwayWebserver(
        host="127.0.0.1", port=_next_port()
    )
    pw.io.http.rest_connector(webserver=webserver, schema=S)
    subject = webserver._routes[0][2].__self__
    assert subject.timeout_s == 17.5
    assert subject.window_s == pytest.approx(0.009)
    assert subject.max_batch == 8
    assert subject.queue_cap == 99
    assert subject.workers == 2

    # out-of-range serve knob is a startup rejection
    monkeypatch.setenv("PATHWAY_SERVE_MAX_BATCH", "0")
    findings = validate_environment()
    assert any(n == "PATHWAY_SERVE_MAX_BATCH" for n, _, _ in findings)
