"""Plan Doctor unit battery: pinned diagnostics for deliberately-broken
plans (fusion blame with node provenance), knob-registry validation,
strict mode, the JSON report shape, and the GIL lint's self-checks.

The agreement-with-runtime-counters battery lives in
tests/test_plan_vs_runtime.py.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

import pathway_tpu as pw
from pathway_tpu.analysis import analyzer as pa
from pathway_tpu.analysis import eligibility as elig
from pathway_tpu.analysis import knobs as pk

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _nb_toolchain() -> bool:
    try:
        from pathway_tpu.native import get_pwexec

        ex = get_pwexec()
    except Exception:
        return None
    return ex is not None and hasattr(ex, "parse_upserts_nb")


needs_nb = pytest.mark.skipif(
    not _nb_toolchain(), reason="native toolchain (pwexec) unavailable"
)


def _connector_pair(lcols=("a", "v"), rcols=("b", "w")):
    class L(pw.Schema):
        a: int
        v: int

    class R(pw.Schema):
        b: int
        w: int

    class LS(pw.io.python.ConnectorSubject):
        _deletions_enabled = False

        def run(self):
            self.next_batch([{"a": i, "v": i} for i in range(10)])
            self.commit()

    class RS(pw.io.python.ConnectorSubject):
        _deletions_enabled = False

        def run(self):
            self.next_batch([{"b": i, "w": i} for i in range(10)])
            self.commit()

    lt = pw.io.python.read(LS(), schema=L, autocommit_duration_ms=None)
    rt = pw.io.python.read(RS(), schema=R, autocommit_duration_ms=None)
    return lt, rt


def _source_table(extra_cols=None):
    cols = {"g": str, "v": int}
    cols.update(extra_cols or {})
    schema = pw.schema_from_types(**cols)

    class Src(pw.io.python.ConnectorSubject):
        _deletions_enabled = False

        def run(self):
            self.commit()

    return pw.io.python.read(
        Src(), schema=schema, autocommit_duration_ms=None
    )


def _diags(report, code):
    return [d for d in report.diagnostics if d.code == code]


# -- the six deliberately-broken plans (pinned blame + provenance) --------

@needs_nb
def test_broken_plan_join_id_expression():
    lt, rt = _connector_pair()
    out = lt.join(rt, lt.a == rt.b, id=lt.v).select(  # JOIN-ID-LINE
        v=pw.left.v, w=pw.right.w
    )
    report = pw.analyze(out)
    assert report.verdict == "degraded"
    [d] = _diags(report, "fusion.join")
    assert "id=" in d.message and "computed" in d.message
    assert d.where and "test_plan_doctor.py" in d.where
    assert "JOIN-ID-LINE" in d.where  # provenance = the user's join line


@needs_nb
def test_broken_plan_multi_arg_reducer():
    t = _source_table()
    agg = t.groupby(pw.this.g).reduce(
        g=pw.this.g, s=pw.reducers.sum(pw.this.v, pw.this.v)
    )
    report = pw.analyze(agg)
    assert report.verdict == "degraded"
    [d] = _diags(report, "fusion.groupby")
    assert "2 arguments" in d.message
    assert d.where and "test_plan_doctor.py" in d.where


@needs_nb
def test_broken_plan_expression_key_exchange():
    """Expression shard key at a 2-rank exchange: blame names the exact
    expression on both the exchange and the groupby."""
    t = _source_table()
    agg = t.groupby(pw.this.g + "!").reduce(c=pw.reducers.count())
    report = pw.analyze(agg, processes=2)
    assert report.verdict == "degraded"
    # the chain breaks AT the exchange (the first node the columnar flow
    # cannot pass); its blame names the exact grouping expression
    [d] = _diags(report, "fusion.exchange")
    assert "not a plain column" in d.message
    assert '.g + ' in d.message  # names the offending expression
    # downstream of the broken boundary the groupby is honestly "tuple",
    # with the same reasons recorded on its node entry
    [entry] = [n for n in report.nodes if n["kind"] == "groupby"]
    assert entry["verdict"] == "tuple"
    assert any("not a plain column" in r for r in entry["reasons"])


@needs_nb
def test_outer_join_blames_pad_transitions():
    """Fusion-blame for a fused-eligible left join names the real
    reason the chain breaks downstream: tuple pad-transition batches."""
    lt, rt = _connector_pair()
    out = lt.join_left(rt, lt.a == rt.b).select(
        v=pw.left.v, w=pw.right.w
    )
    report = pw.analyze(out)
    assert report.verdict == "degraded"
    [d] = _diags(report, "fusion.join")
    assert "pad-transition" in d.message
    assert "left join" in d.message


@needs_nb
def test_join_exchange_blame_is_per_side():
    """A join broken only on its RIGHT key: the left exchange still
    ships columnar on its own plain-column shard key, and the right
    exchange's blame names the RIGHT expression — not the whole combined
    tuple (which would misattribute the other side's expression)."""
    lt, rt = _connector_pair()
    out = lt.join(rt, lt.a == rt.b + 1).select(
        v=pw.left.v, w=pw.right.w
    )
    report = pw.analyze(out, processes=2)
    assert report.verdict == "degraded"
    lex, rex = report.by_kind("exchange")[:2]  # construction order: L, R
    assert lex["verdict"] == "fused", lex
    assert rex["verdict"] == "degraded", rex
    assert any("right join key" in r for r in rex["reasons"])
    assert not any("left join key" in r for r in rex["reasons"])
    # the JOIN carries the combined blame
    [entry] = [n for n in report.nodes if n["kind"] == "join"]
    assert any("right join key" in r for r in entry["reasons"])


@needs_nb
def test_broken_plan_object_key_source():
    """Tuple-typed column: the SOURCE has no columnar door — the plan is
    honestly 'tuple', and the source diagnostic names the column dtype."""
    t = _source_table(extra_cols={"meta": tuple})
    agg = t.groupby(pw.this.g).reduce(c=pw.reducers.count())
    report = pw.analyze(agg)
    assert report.verdict == "tuple"
    [d] = _diags(report, "fusion.source")
    assert "'meta'" in d.message and "columnar value set" in d.message


def test_broken_plan_nondeterministic_udf(monkeypatch):
    t = _source_table()
    label = pw.udf(lambda v: f"x{v}")  # pw.udf: deterministic=False
    sel = t.select(g=pw.this.g, lab=label(pw.this.v))
    agg = sel.groupby(pw.this.lab).reduce(c=pw.reducers.count())
    report = pw.analyze(agg, processes=2)
    diags = _diags(report, "replay.nondeterministic-udf")
    assert diags, report.render()
    assert "exchanged" in diags[0].message
    # and the memoized select breaks the fused chain
    assert report.verdict == "degraded" or not _nb_toolchain()


def test_nondeterministic_udf_persisted_single_rank():
    """At 1 rank nothing is exchanged, so the replay hazard exists only
    when the run persists state — pw.analyze(persistence=True) is how a
    caller says so (the scratch lowering never configures persistence)."""
    t = _source_table()
    label = pw.udf(lambda v: f"x{v}")  # pw.udf: deterministic=False
    sel = t.select(g=pw.this.g, lab=label(pw.this.v))
    assert not _diags(
        pw.analyze(sel), "replay.nondeterministic-udf"
    )  # 1 rank, no persistence: no divergence sink
    report = pw.analyze(sel, persistence=True)
    diags = _diags(report, "replay.nondeterministic-udf")
    assert diags, report.render()
    assert "persisted" in diags[0].message


def test_broken_plan_suspicious_deterministic_udf():
    import time as _time

    def stamp(v):
        return _time.time() + v

    t = _source_table()
    sel = t.select(s=pw.apply(stamp, pw.this.v))  # declared deterministic
    report = pw.analyze(sel)
    diags = _diags(report, "replay.suspicious-udf")
    assert diags, report.render()
    assert "'stamp'" in diags[0].message and "time" in diags[0].message


def test_broken_plan_unknown_env_knob(monkeypatch):
    monkeypatch.setenv("PATHWAY_THREDS", "8")  # typo'd PATHWAY_THREADS
    t = _source_table()
    report = pw.analyze(t)
    [d] = _diags(report, "knob.unknown")
    assert "PATHWAY_THREDS" in d.message
    assert d.hint and "PATHWAY_THREADS" in d.hint  # suggestion
    assert d.severity == "error"
    # PATHWAY_KNOB_CHECK=0 mirrors the runtime's escape hatch: the
    # finding is still reported but no longer gates (errors() empty, so
    # the CLI's exit-2 path and CI lanes keyed on it stay green)
    monkeypatch.setenv("PATHWAY_KNOB_CHECK", "0")
    report = pw.analyze(t)
    [d] = _diags(report, "knob.unknown")
    assert d.severity == "warning"
    assert not report.errors()


# -- knob registry --------------------------------------------------------

def test_knob_registry_covers_every_env_read():
    """Every PATHWAY_* name mentioned in the package source must be in
    the registry — a new knob without registration would be rejected at
    startup for users who set it."""
    import re

    pkg = os.path.join(REPO, "pathway_tpu")
    found = set()
    for root, _dirs, files in os.walk(pkg):
        for fn in files:
            if not fn.endswith(".py") or fn == "knobs.py":
                continue  # the registry's own docstring shows a typo
            with open(os.path.join(root, fn)) as f:
                found.update(re.findall(r"PATHWAY_[A-Z0-9_]+", f.read()))
    missing = found - set(pk.KNOBS)
    assert not missing, f"unregistered knobs: {sorted(missing)}"


def test_knob_validation_rejects_bad_values(monkeypatch):
    monkeypatch.setenv("PATHWAY_THREADS", "zero")
    findings = pk.validate_environment()
    assert any(n == "PATHWAY_THREADS" for n, _, _ in findings)
    monkeypatch.setenv("PATHWAY_THREADS", "-3")
    findings = pk.validate_environment()
    assert any("below the minimum" in p for _, p, _ in findings)
    monkeypatch.setenv("PATHWAY_THREADS", "4")
    monkeypatch.setenv("PATHWAY_SNAPSHOT_ACCESS", "recrod")
    findings = pk.validate_environment()
    assert any("one of" in p for _, p, _ in findings)


def test_runtime_rejects_unknown_knob_at_startup(monkeypatch):
    from pathway_tpu.engine.runtime import Runtime

    pk._checked = None  # drop the memo so this env snapshot re-validates
    monkeypatch.setenv("PATHWAY_NO_NB_JION", "1")  # typo'd NO_NB_JOIN
    with pytest.raises(pk.KnobError, match="PATHWAY_NO_NB_JION"):
        Runtime()
    # escape hatch downgrades to a warning
    monkeypatch.setenv("PATHWAY_KNOB_CHECK", "0")
    pk._checked = None
    Runtime()
    pk._checked = None


def test_knob_table_markdown_lists_all():
    table = pk.knob_table_markdown()
    for name in pk.KNOBS:
        assert f"`{name}`" in table


# -- strict mode + fallback counter (satellite 1) -------------------------

@needs_nb
def test_nb_strict_raises_with_blame_on_demotion(monkeypatch):
    """A fused-eligible groupby that hits a beyond-i64 reducer arg
    normally demotes silently to the Python path; PATHWAY_NB_STRICT=1
    must raise the fusion-blame diagnostic instead."""
    from pathway_tpu.internals.graph_runner import GraphRunner

    def build():
        pw.internals.parse_graph.G.clear()
        t = pw.debug.table_from_rows(
            pw.schema_from_types(g=str, v=int),
            [(0, "a", 2**70), (1, "a", 1)],
        )
        return t.groupby(pw.this.g).reduce(
            g=pw.this.g, s=pw.reducers.sum(pw.this.v)
        )

    # sanity: non-strict run completes on the tuple path
    agg = build()
    rows = list(GraphRunner().run_tables(agg)[0].state.rows.values())
    assert rows == [("a", 2**70 + 1)]

    monkeypatch.setenv("PATHWAY_NB_STRICT", "1")
    agg = build()
    with pytest.raises(elig.NBStrictError, match="GroupByNode"):
        GraphRunner().run_tables(agg)


def test_nb_strict_covers_exchange_deoptimization(monkeypatch):
    """NB_STRICT's documented contract covers EVERY fused-eligible node
    leaving the columnar path — including an exchange whose
    statically-columnar input arrives as tuple deltas (which otherwise
    only shows up as an _fallbacks increment)."""
    import types

    from pathway_tpu.engine import nodes as N

    monkeypatch.setattr(
        N._elig, "expects_native_batch", lambda node: True
    )
    # a real ExchangeNode skeleton (strict_error names type(node)), with
    # __init__ bypassed so no scope/runtime plumbing is needed
    fake = object.__new__(N.ExchangeNode)
    fake.scope = types.SimpleNamespace(
        runtime=types.SimpleNamespace(
            procgroup=types.SimpleNamespace(world=2, rank=0),
            stats=types.SimpleNamespace(
                on_exchange_fallback=lambda: None,
                on_exchange_elided=lambda n: None,
            ),
        )
    )
    fake.mode = "hash"
    fake.nb_kidx = (0,)
    fake.nb_decision = elig.NBDecision(True, ())
    fake._nb_ok = True
    fake._nb_batches = 0
    fake._fallbacks = 0
    fake.inputs = [None]
    fake.key_batch = lambda keys, rows: [(r[0],) for r in rows]
    fake.trace = None
    fake.node_id = 7
    deltas = [(1, ("a",), 1), (2, ("b",), 1)]
    # non-strict: counted as a fallback, sliced on the tuple path
    own, sends = N.ExchangeNode._slice(fake, list(deltas))
    assert fake._fallbacks == 1
    monkeypatch.setenv("PATHWAY_NB_STRICT", "1")
    with pytest.raises(elig.NBStrictError, match="ExchangeNode"):
        N.ExchangeNode._slice(fake, list(deltas))
    # but an exchange the PLAN already called tuple must not raise
    fake.nb_decision = elig.NBDecision(False, ("expression shard key",))
    N.ExchangeNode._slice(fake, list(deltas))


@needs_nb
def test_fallback_counted_once_on_demotion_not_per_batch():
    """Demotion fallback accounting: a columnar-capable source whose
    mid-stream batch carries a beyond-i64 value demotes the groupby once;
    the post-demotion columnar batches must NOT each count a fallback."""
    from pathway_tpu.engine.nodes import GroupByNode
    from pathway_tpu.internals.graph_runner import GraphRunner

    class S(pw.Schema):
        g: str
        v: int

    class Src(pw.io.python.ConnectorSubject):
        _deletions_enabled = False

        def run(self):
            self.next_batch([{"g": "a", "v": 1}] * 5)
            self.commit()
            # beyond-i64 value: the columnar parser refuses the batch
            # (tuple path) and the native store Falls Back -> demotion
            self.next_batch([{"g": "a", "v": 2**70}])
            self.commit()
            for _ in range(3):  # post-demotion batches: no re-count
                self.next_batch([{"g": "b", "v": 2}] * 4)
                self.commit()

    t = pw.io.python.read(Src(), schema=S, autocommit_duration_ms=None)
    agg = t.groupby(pw.this.g).reduce(
        g=pw.this.g, s=pw.reducers.sum(pw.this.v)
    )
    import pathway_tpu.engine.runtime as rt_mod

    insts = []
    orig = rt_mod.Runtime.__init__

    def spy(self, *a, **k):
        orig(self, *a, **k)
        insts.append(self)

    rt_mod.Runtime.__init__ = spy
    try:
        [cap] = GraphRunner().run_tables(agg)
    finally:
        rt_mod.Runtime.__init__ = orig
    rows = dict(cap.state.rows)
    assert sorted(rows.values()) == [("a", 2**70 + 5), ("b", 24)]
    rt = insts[0]
    [gb] = [n for n in rt.scope.nodes if isinstance(n, GroupByNode)]
    assert gb._fallback_demoted
    assert gb._nb_fallbacks == 1, gb._nb_fallbacks
    assert rt.stats.nb_fallbacks == 1


# -- report shape + CLI ---------------------------------------------------

@needs_nb
def test_json_report_schema():
    lt, rt = _connector_pair()
    out = lt.join(rt, lt.a == rt.b).select(v=pw.left.v, w=pw.right.w)
    report = pw.analyze(out, processes=2)
    data = json.loads(report.to_json())
    assert data["schema"] == "pathway_tpu.analysis/v1"
    assert data["verdict"] == "fused"
    assert data["processes"] == 2
    assert set(data["summary"]) == {
        "nodes", "fused_nodes", "degraded_nodes", "diagnostics",
    }
    for node in data["nodes"]:
        assert {"node_id", "node", "kind", "verdict", "reasons", "where"} <= set(node)
        assert node["verdict"] in ("fused", "degraded", "tuple")
    for d in data["diagnostics"]:
        assert d["severity"] in ("info", "warning", "error")


def test_cli_program_mode_and_gate(tmp_path):
    prog = tmp_path / "prog.py"
    prog.write_text(
        "import pathway_tpu as pw\n"
        "t = pw.debug.table_from_rows(pw.schema_from_types(a=int), [(1,)])\n"
        "out = t.select(b=pw.this.a + 1)\n"
        "pw.io.subscribe(out, on_change=lambda *a: None)\n"
        "pw.run(monitoring_level=pw.MonitoringLevel.NONE)\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    res = subprocess.run(
        [sys.executable, "-m", "pathway_tpu.analysis", "--json", str(prog)],
        capture_output=True, text=True, env=env, timeout=180,
    )
    assert res.returncode == 0, res.stderr
    data = json.loads(res.stdout)
    assert data["verdict"] == "tuple"  # static source: honest verdict
    # the gate rejects a non-fused plan
    res = subprocess.run(
        [sys.executable, "-m", "pathway_tpu.analysis", "--require-fused",
         str(prog)],
        capture_output=True, text=True, env=env, timeout=180,
    )
    assert res.returncode == 1
    assert "not fused" in res.stderr
    # flag-style args after the program path are the PROGRAM's argv
    # (argparse.REMAINDER), not doctor options to choke on
    argprog = prog.parent / "argprog.py"
    argprog.write_text(
        "import sys\n"
        "assert sys.argv[1:] == ['--limit', '5'], sys.argv\n"
        + prog.read_text()
    )
    res = subprocess.run(
        [sys.executable, "-m", "pathway_tpu.analysis", "--json",
         str(argprog), "--limit", "5"],
        capture_output=True, text=True, env=env, timeout=180,
    )
    assert res.returncode == 0, res.stderr
    assert json.loads(res.stdout)["verdict"] == "tuple"


def test_cli_diagnoses_bad_config_backed_knob(tmp_path):
    """A config-backed PATHWAY_* var that fails to parse must come back
    as the doctor's knob.invalid report (exit 2), not an import-time
    traceback — config construction is lazy exactly so the CLI can
    import the package under a broken environment."""
    prog = tmp_path / "prog.py"
    prog.write_text(
        "import pathway_tpu as pw\n"
        "t = pw.debug.table_from_rows(pw.schema_from_types(a=int), [(1,)])\n"
        "pw.io.subscribe(t, on_change=lambda *a: None)\n"
        "pw.run()\n"
    )
    env = dict(
        os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO,
        PATHWAY_PROCESSES="abc",
    )
    res = subprocess.run(
        [sys.executable, "-m", "pathway_tpu.analysis", str(prog)],
        capture_output=True, text=True, env=env, timeout=180,
    )
    assert res.returncode == 2, res.stderr
    assert "knob.invalid" in res.stderr
    assert "PATHWAY_PROCESSES" in res.stderr
    assert "Traceback" not in res.stderr


def test_cli_program_mode_sees_persistence(tmp_path):
    """The CLI observes the program's persistence_config (via the stubbed
    Runtime.__init__), so a 1-rank non-deterministic UDF feeding persisted
    state IS diagnosed — it would be invisible to a bare pw.analyze()."""
    pdir = tmp_path / "pstate"
    prog = tmp_path / "prog.py"
    prog.write_text(
        "import pathway_tpu as pw\n"
        "t = pw.debug.table_from_rows(pw.schema_from_types(a=int), [(1,)])\n"
        "lab = pw.udf(lambda v: f'x{v}')\n"
        "out = t.select(b=lab(pw.this.a))\n"
        "pw.io.subscribe(out, on_change=lambda *a: None)\n"
        "pw.run(persistence_config=pw.persistence.Config(\n"
        f"    backend=pw.persistence.Backend.filesystem({str(pdir)!r})))\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    res = subprocess.run(
        [sys.executable, "-m", "pathway_tpu.analysis", "--json", str(prog)],
        capture_output=True, text=True, env=env, timeout=180,
    )
    assert res.returncode == 0, res.stderr
    data = json.loads(res.stdout)
    replay = [
        d for d in data["diagnostics"]
        if d["code"] == "replay.nondeterministic-udf"
    ]
    assert replay, data
    assert "persisted" in replay[0]["message"]


def test_gil_lint_clean_and_detects_seeded_violations(tmp_path):
    lint = os.path.join(REPO, "scripts", "lint_gil.py")
    res = subprocess.run(
        [sys.executable, lint], capture_output=True, text=True, timeout=120,
    )
    assert res.returncode == 0, res.stdout + res.stderr
    bad = tmp_path / "bad.cpp"
    bad.write_text(
        "int f() {\n"
        "    /* phase 1: extract */\n"
        '    PyErr_SetString(PyExc_TypeError, "x");\n'
        "    /* phase 1 passed */\n"
        "    Py_BEGIN_ALLOW_THREADS\n"
        "    Py_DECREF(x);\n"
        "    Py_END_ALLOW_THREADS\n"
        "}\n"
    )
    res = subprocess.run(
        [sys.executable, lint, str(bad)],
        capture_output=True, text=True, timeout=120,
    )
    assert res.returncode == 1
    assert "Py_DECREF" in res.stdout
    assert "non-Fallback error" in res.stdout


# -- eligibility is the single source of truth ----------------------------

def test_executor_decisions_come_from_eligibility(monkeypatch):
    """The node constructors must gate their columnar paths on the SAME
    NBDecision objects the analyzer reads — flipping the decision flips
    the node flag with no second predicate to drift."""
    calls = []
    orig = elig.decide_join_nb

    def spy(**kw):
        d = orig(**kw)
        calls.append(d)
        return d

    monkeypatch.setattr(elig, "decide_join_nb", spy)
    lt, rt = _connector_pair()
    out = lt.join(rt, lt.a == rt.b).select(v=pw.left.v)
    from pathway_tpu.engine.nodes import JoinNode
    from pathway_tpu.engine.runtime import Runtime
    from pathway_tpu.internals.graph_runner import GraphRunner

    g = pw.internals.parse_graph.G
    ops = g.reachable_operators([out._source])
    runtime = Runtime()
    GraphRunner()._lower(ops, runtime)
    [jn] = [n for n in runtime.scope.nodes if isinstance(n, JoinNode)]
    assert calls and jn.nb_decision is calls[-1]
    assert jn._nb_ok == jn.nb_decision.ok
