"""Top-level API surface parity: every name the reference exports from
`import pathway as pw` must resolve on pathway_tpu (reference:
python/pathway/__init__.py __all__)."""

import pathway_tpu as pw

_REFERENCE_ALL = [
    # captured from the reference __init__ __all__ (91 names)
    "asynchronous", "udfs", "graphs", "ml", "apply", "udf", "udf_async",
    "UDF", "UDFAsync", "UDFSync", "apply_async", "apply_with_type",
    "declare_type", "cast", "GroupedTable", "iterate", "iterate_universe",
    "JoinResult", "IntervalJoinResult", "Joinable", "OuterJoinResult",
    "WindowJoinResult", "AsofJoinResult", "GroupedJoinResult", "reducers",
    "unwrap", "fill_error", "assert_table_has_columns", "universes",
    "debug", "indexing", "demo", "io", "Table", "JoinMode", "Schema",
    "Pointer", "MonitoringLevel", "Type", "this",
    "left", "right", "Json", "coalesce", "require", "if_else",
    "make_tuple", "sql", "run", "run_all", "temporal", "statistical",
    "stateful", "ordered", "viz", "window",
    "schema_from_types", "PersistenceMode", "BaseCustomAccumulator",
    "schema_builder", "column_definition", "TableSlice", "DateTimeNaive",
    "DateTimeUtc", "Duration", "SchemaProperties", "schema_from_csv",
    "schema_from_dict", "assert_table_has_schema", "table_transformer",
    "AsyncTransformer", "pandas_transformer", "persistence",
    "set_license_key", "set_monitoring_config", "join", "join_inner",
    "join_left", "join_right", "join_outer", "groupby",
    "enable_interactive_mode", "LiveTable", "global_error_log",
    "local_error_log", "ColumnExpression", "ColumnReference",
]


def test_reference_top_level_surface_resolves():
    missing = [n for n in _REFERENCE_ALL if not hasattr(pw, n)]
    assert missing == [], f"missing top-level exports: {missing}"


def test_aliases_are_sane():
    assert pw.Joinable is pw.Table
    assert pw.UDFSync is pw.UDF
    assert pw.local_error_log is not None
    t = pw.debug.table_from_markdown("a\n1")
    # free-function spellings delegate to methods
    res = pw.groupby(t, t.a).reduce(t.a)
    from utils import run_table

    assert sorted(run_table(res).values()) == [(1,)]
