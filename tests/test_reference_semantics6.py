"""Sixth reference-semantics battery: window joins, sliding-window
behaviors under streaming, Json edge navigation, unwrap/require
expression helpers, concat_reindex under streaming upserts."""

import pytest

import pathway_tpu as pw
from pathway_tpu.internals.graph_runner import GraphRunner


def _rows(table):
    cap = GraphRunner().run_tables(table)[0]
    return sorted((tuple(r) for r in cap.state.rows.values()), key=repr)


def test_window_join_inner_tumbling():
    lt = pw.debug.table_from_markdown(
        """
        t | a
        1 | x
        6 | y
        """
    )
    rt = pw.debug.table_from_markdown(
        """
        t | b
        2 | p
        3 | q
        11 | r
        """
    )
    j = pw.temporal.window_join(
        lt, rt, lt.t, rt.t, pw.temporal.tumbling(5)
    ).select(a=pw.left.a, b=pw.right.b)
    # window [0,5): x pairs with p and q; [5,10): y alone -> dropped;
    # [10,15): r alone -> dropped
    assert _rows(j) == [("x", "p"), ("x", "q")]


def test_window_join_left_pads():
    lt = pw.debug.table_from_markdown("t | a\n1 | x\n6 | y")
    rt = pw.debug.table_from_markdown("t | b\n2 | p")
    j = pw.temporal.window_join(
        lt, rt, lt.t, rt.t, pw.temporal.tumbling(5), how="left"
    ).select(a=pw.left.a, b=pw.right.b)
    assert _rows(j) == [("x", "p"), ("y", None)]


def test_json_edges():
    t = pw.debug.table_from_rows(
        pw.schema_from_types(j=pw.Json),
        [
            (1, pw.Json({"a": {"b": [10, 20, 30]}, "n": None})),
        ],
    )
    r = t.select(
        deep=pw.this.j["a"]["b"][1].as_int(),
        # reference pins NO negative wraparound: [-1] is out of bounds
        # (test_json_get_array_index_out_of_bounds)
        neg=pw.this.j["a"]["b"][-1].as_int(),
        missing=pw.this.j["zzz"]["deep"].as_int(),
        null_field=pw.this.j["n"].as_int(),
        dflt=pw.this.j.get("zzz", pw.Json(7)).as_int(),
    )
    assert _rows(r) == [(20, None, None, None, 7)]


def test_unwrap_and_require():
    t = pw.debug.table_from_markdown(
        """
        a | b
        1 | 2
        3 |
        """,
        schema=pw.schema_from_types(
            a=int, b=pw.internals.dtype.Optional(int)
        ),
    )
    ok = t.filter(pw.this.b.is_not_none()).select(v=pw.unwrap(pw.this.b))
    assert _rows(ok) == [(2,)]
    # require: None in any argument poisons the result to None
    r = t.select(v=pw.require(pw.this.a + 1, pw.this.b))
    assert _rows(r) == [(2,), (None,)]


def test_concat_reindex_streaming_upserts():
    class S(pw.Schema):
        k: int = pw.column_definition(primary_key=True)
        v: str

    class A(pw.io.python.ConnectorSubject):
        def run(self):
            self.next(k=1, v="a1")
            self.commit()
            self.remove(k=1, v="a1")
            self.next(k=1, v="a2")
            self.commit()

    class B(pw.io.python.ConnectorSubject):
        def run(self):
            self.next(k=1, v="b1")  # same key as stream A on purpose
            self.commit()

    ta = pw.io.python.read(A(), schema=S, autocommit_duration_ms=None)
    tb = pw.io.python.read(B(), schema=S, autocommit_duration_ms=None)
    both = ta.concat_reindex(tb)
    cap = GraphRunner().run_tables(both)[0]
    vals = sorted(r[1] for r in cap.state.rows.values())
    assert vals == ["a2", "b1"]


def test_sliding_window_count_stream():
    class S(pw.Schema):
        t: int
        v: int

    class Sub(pw.io.python.ConnectorSubject):
        _deletions_enabled = False

        def run(self):
            for tt in [1, 2, 6, 7, 12]:
                self.next(t=tt, v=1)
            self.commit()

    src = pw.io.python.read(Sub(), schema=S, autocommit_duration_ms=None)
    w = src.windowby(
        pw.this.t, window=pw.temporal.sliding(duration=10, hop=5)
    ).reduce(
        start=pw.this._pw_window_start,
        c=pw.reducers.count(),
    )
    cap = GraphRunner().run_tables(w)[0]
    got = sorted(tuple(r) for r in cap.state.rows.values())
    # windows: [-5,5): t=1,2 -> 2; [0,10): 1,2,6,7 -> 4; [5,15): 6,7,12 -> 3;
    # [10,20): 12 -> 1
    assert got == [(-5, 2), (0, 4), (5, 3), (10, 1)]


def test_groupby_instance_join_shapes():
    t = pw.debug.table_from_markdown(
        """
        g | i | v
        a | 0 | 1
        a | 0 | 2
        a | 1 | 3
        b | 0 | 4
        """
    )
    r = t.groupby(pw.this.g, instance=pw.this.i).reduce(
        g=pw.this.g, i=pw.this.i, s=pw.reducers.sum(pw.this.v)
    )
    assert _rows(r) == [("a", 0, 3), ("a", 1, 3), ("b", 0, 4)]
