"""Temporal join battery — transliteration of the reference's interval/
asof/window join corpora to this DSL (reference: python/pathway/tests/
temporal/test_interval_joins.py, test_asof_joins.py, test_window_joins.py).
Expectations come from in-test oracles over the published semantics:

* interval_join(a, b, ta, tb, interval(lo, up)): match iff
  lo <= tb - ta <= up (both bounds inclusive); left/right/outer modes pad
  unmatched rows with None;
* asof_join backward: each left row takes the latest right row with
  t_right <= t_left (forward: earliest with t_right >= t_left; nearest:
  closest by |Δt|, ties broken backward);
* window_join: rows join iff assigned a common tumbling/sliding window.
"""

from __future__ import annotations

import random

import pytest

import pathway_tpu as pw
from pathway_tpu.internals.graph_runner import GraphRunner


def _rows(table):
    captures = GraphRunner().run_tables(table)
    return sorted(
        captures[0].state.rows.values(),
        key=lambda r: tuple((v is None, v) for v in r),
    )


def _markdown_of(cols, rows):
    lines = [" | ".join(cols)]
    for r in rows:
        lines.append(" | ".join("" if v is None else str(v) for v in r))
    return "\n".join(lines)


def _table_of(cols, rows):
    return pw.debug.table_from_markdown(_markdown_of(cols, rows))


# ---------------------------------------------------------------------------
# interval join oracle


def interval_oracle(lts, rts, lo, up, how):
    """Oracle over (tag, time) rows: [(lt, rt)] pairs with None padding."""
    out = []
    matched_r = set()
    for i, lt in enumerate(lts):
        hit = False
        for j, rt in enumerate(rts):
            if lo <= rt - lt <= up:
                out.append((lt, rt))
                matched_r.add(j)
                hit = True
        if not hit and how in ("left", "outer"):
            out.append((lt, None))
    if how in ("right", "outer"):
        for j, rt in enumerate(rts):
            if j not in matched_r:
                out.append((None, rt))
    return sorted(out, key=lambda r: tuple((v is None, v) for v in r))


MODES = ["inner", "left", "right", "outer"]


@pytest.mark.parametrize("how", MODES)
def test_interval_join_modes_against_oracle(how):
    lts = [-1, 0, 2, 3, 4, 10]
    rts = [0, 2, 3, 5, 11]
    t1 = _table_of(["t"], [(x,) for x in lts])
    t2 = _table_of(["t"], [(x,) for x in rts])
    res = pw.temporal.interval_join(
        t1, t2, t1.t, t2.t, pw.temporal.interval(0, 0), how=how
    ).select(lt=t1.t, rt=t2.t)
    assert _rows(res) == interval_oracle(lts, rts, 0, 0, how)


@pytest.mark.parametrize("how", MODES)
def test_interval_join_shifted_empty_interval(how):
    # interval(2, 2): exact equality shifted by two
    lts = [-1, 0, 2, 3, 4, 10]
    rts = [0, 2, 3, 5, 11]
    t1 = _table_of(["t"], [(x,) for x in lts])
    t2 = _table_of(["t"], [(x,) for x in rts])
    res = pw.temporal.interval_join(
        t1, t2, t1.t, t2.t, pw.temporal.interval(2, 2), how=how
    ).select(lt=t1.t, rt=t2.t)
    assert _rows(res) == interval_oracle(lts, rts, 2, 2, how)


@pytest.mark.parametrize("bounds", [(-3, -1), (1, 3), (-2, 5)])
def test_interval_join_non_symmetric_bounds(bounds):
    lo, up = bounds
    lts = [0, 5, 10, 15]
    rts = [1, 4, 7, 12, 16]
    t1 = _table_of(["t"], [(x,) for x in lts])
    t2 = _table_of(["t"], [(x,) for x in rts])
    res = pw.temporal.interval_join(
        t1, t2, t1.t, t2.t, pw.temporal.interval(lo, up)
    ).select(lt=t1.t, rt=t2.t)
    assert _rows(res) == interval_oracle(lts, rts, lo, up, "inner")


def test_interval_join_bounds_inclusive_both_ends():
    t1 = _table_of(["t"], [(10,)])
    t2 = _table_of(["t"], [(8,), (12,), (7,), (13,)])
    res = pw.temporal.interval_join(
        t1, t2, t1.t, t2.t, pw.temporal.interval(-2, 2)
    ).select(rt=t2.t)
    assert _rows(res) == [(8,), (12,)]


def test_interval_join_inverted_interval_raises():
    t1 = _table_of(["t"], [(1,)])
    t2 = _table_of(["t"], [(1,)])
    with pytest.raises((ValueError, TypeError)):
        pw.temporal.interval_join(
            t1, t2, t1.t, t2.t, pw.temporal.interval(3, -3)
        ).select(lt=t1.t)
        GraphRunner().run_tables(
            pw.temporal.interval_join(
                t1, t2, t1.t, t2.t, pw.temporal.interval(3, -3)
            ).select(lt=t1.t)
        )


@pytest.mark.parametrize("how", MODES)
def test_interval_join_sharded_on_key(how):
    lrows = [("a", 0), ("a", 5), ("b", 0), ("c", 2)]
    rrows = [("a", 1), ("b", 0), ("b", 6), ("d", 0)]
    t1 = _table_of(["k", "t"], lrows)
    t2 = _table_of(["k", "t"], rrows)
    res = pw.temporal.interval_join(
        t1, t2, t1.t, t2.t, pw.temporal.interval(-1, 1), t1.k == t2.k,
        how=how,
    ).select(lk=t1.k, lt=t1.t, rk=t2.k, rt=t2.t)

    def oracle():
        out = []
        matched_r = set()
        for lk, lt in lrows:
            hit = False
            for j, (rk, rt) in enumerate(rrows):
                if lk == rk and -1 <= rt - lt <= 1:
                    out.append((lk, lt, rk, rt))
                    matched_r.add(j)
                    hit = True
            if not hit and how in ("left", "outer"):
                out.append((lk, lt, None, None))
        if how in ("right", "outer"):
            for j, (rk, rt) in enumerate(rrows):
                if j not in matched_r:
                    out.append((None, None, rk, rt))
        return sorted(out, key=lambda r: tuple((v is None, v) for v in r))

    assert _rows(res) == oracle()


def test_interval_join_multiple_equality_keys():
    lrows = [("a", 1, 0), ("a", 2, 0), ("b", 1, 0)]
    rrows = [("a", 1, 0), ("a", 2, 5), ("b", 2, 0)]
    t1 = _table_of(["k", "g", "t"], lrows)
    t2 = _table_of(["k", "g", "t"], rrows)
    res = pw.temporal.interval_join(
        t1, t2, t1.t, t2.t, pw.temporal.interval(-1, 1),
        t1.k == t2.k, t1.g == t2.g,
    ).select(k=t1.k, g=t1.g)
    assert _rows(res) == [("a", 1)]


def test_interval_join_float_bounds():
    lts = [0.0, 1.0, 2.5]
    rts = [0.4, 1.6, 2.4]
    t1 = _table_of(["t"], [(x,) for x in lts])
    t2 = _table_of(["t"], [(x,) for x in rts])
    res = pw.temporal.interval_join(
        t1, t2, t1.t, t2.t, pw.temporal.interval(-0.5, 0.5)
    ).select(lt=t1.t, rt=t2.t)
    assert _rows(res) == interval_oracle(lts, rts, -0.5, 0.5, "inner")


def test_interval_join_select_expressions():
    # select can compute over both sides, not just project
    t1 = _table_of(["t", "v"], [(0, 10), (5, 20)])
    t2 = _table_of(["t", "w"], [(1, 1), (6, 2)])
    res = pw.temporal.interval_join(
        t1, t2, t1.t, t2.t, pw.temporal.interval(0, 2)
    ).select(sum_=t1.v + t2.w, dt=t2.t - t1.t)
    assert _rows(res) == [(11, 1), (22, 1)]


def test_interval_join_outer_pad_coalesce():
    t1 = _table_of(["t", "v"], [(0, 10), (50, 99)])
    t2 = _table_of(["t", "w"], [(1, 7)])
    res = pw.temporal.interval_join_left(
        t1, t2, t1.t, t2.t, pw.temporal.interval(-2, 2)
    ).select(v=t1.v, w=pw.coalesce(t2.w, -1))
    assert _rows(res) == [(10, 7), (99, -1)]


def test_interval_join_duplicate_times_multiply():
    # two identical left rows x two identical right matches = 4 pairs
    t1 = _table_of(["t", "side"], [(0, "l1"), (0, "l2")])
    t2 = _table_of(["t", "side"], [(0, "r1"), (0, "r2")])
    res = pw.temporal.interval_join(
        t1, t2, t1.t, t2.t, pw.temporal.interval(0, 0)
    ).select(a=t1.side, b=t2.side)
    assert _rows(res) == [
        ("l1", "r1"),
        ("l1", "r2"),
        ("l2", "r1"),
        ("l2", "r2"),
    ]


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_interval_join_oracle_sweep(seed):
    rng = random.Random(seed)
    lts = [rng.randint(-20, 20) for _ in range(25)]
    rts = [rng.randint(-20, 20) for _ in range(25)]
    lo = rng.randint(-5, 0)
    up = rng.randint(0, 5)
    how = MODES[seed % 4]
    t1 = _table_of(["t"], [(x,) for x in lts])
    t2 = _table_of(["t"], [(x,) for x in rts])
    res = pw.temporal.interval_join(
        t1, t2, t1.t, t2.t, pw.temporal.interval(lo, up), how=how
    ).select(lt=t1.t, rt=t2.t)
    assert _rows(res) == interval_oracle(lts, rts, lo, up, how)


# ---------------------------------------------------------------------------
# asof join oracle


def asof_oracle(lrows, rrows, direction, how):
    """Oracle over (key, time, payload) rows. Returns
    [(lt, lv, rt_or_None, rv_or_None)] per left row (left/outer modes),
    plus unmatched right rows for right/outer."""
    out = []
    used_right = set()
    for lk, lt, lv in lrows:
        cands = [
            (j, rt, rv)
            for j, (rk, rt, rv) in enumerate(rrows)
            if rk == lk
            and (
                (direction == "backward" and rt <= lt)
                or (direction == "forward" and rt >= lt)
                or direction == "nearest"
            )
        ]
        if direction == "backward":
            cands.sort(key=lambda c: c[1])
            pick = cands[-1] if cands else None
        elif direction == "forward":
            cands.sort(key=lambda c: c[1])
            pick = cands[0] if cands else None
        else:  # nearest: min |dt|, ties backward (rt <= lt preferred)
            pick = None
            if cands:
                pick = min(
                    cands, key=lambda c: (abs(c[1] - lt), c[1] > lt, c[1])
                )
        if pick is not None:
            out.append((lt, lv, pick[1], pick[2]))
            used_right.add(pick[0])
        elif how in ("left", "outer"):
            out.append((lt, lv, None, None))
    if how in ("right", "outer"):
        for j, (rk, rt, rv) in enumerate(rrows):
            if j not in used_right:
                out.append((None, None, rt, rv))
    return sorted(out, key=lambda r: tuple((v is None, v) for v in r))


def test_asof_backward_basic():
    lrows = [("A", 10, 1), ("A", 20, 2), ("A", 5, 3)]
    rrows = [("A", 8, 95), ("A", 15, 96), ("A", 30, 99)]
    t1 = _table_of(["k", "t", "v"], lrows)
    t2 = _table_of(["k", "t", "v"], rrows)
    res = pw.temporal.asof_join(
        t1, t2, t1.t, t2.t, t1.k == t2.k, how="inner"
    ).select(lt=t1.t, lv=t1.v, rt=t2.t, rv=t2.v)
    assert _rows(res) == asof_oracle(lrows, rrows, "backward", "inner")


def test_asof_backward_left_pads():
    lrows = [("A", 5, 1), ("A", 10, 2)]
    rrows = [("A", 8, 95)]
    t1 = _table_of(["k", "t", "v"], lrows)
    t2 = _table_of(["k", "t", "v"], rrows)
    res = pw.temporal.asof_join_left(
        t1, t2, t1.t, t2.t, t1.k == t2.k
    ).select(lt=t1.t, lv=t1.v, rt=t2.t, rv=t2.v)
    assert _rows(res) == asof_oracle(lrows, rrows, "backward", "left")


def test_asof_forward():
    lrows = [("A", 10, 1), ("A", 29, 2)]
    rrows = [("A", 8, 95), ("A", 15, 96), ("A", 30, 99)]
    t1 = _table_of(["k", "t", "v"], lrows)
    t2 = _table_of(["k", "t", "v"], rrows)
    res = pw.temporal.asof_join(
        t1, t2, t1.t, t2.t, t1.k == t2.k,
        how="inner", direction=pw.temporal.Direction.FORWARD,
    ).select(lt=t1.t, lv=t1.v, rt=t2.t, rv=t2.v)
    assert _rows(res) == asof_oracle(lrows, rrows, "forward", "inner")


def test_asof_nearest():
    lrows = [("A", 10, 1), ("A", 21, 2)]
    rrows = [("A", 7, 95), ("A", 12, 96), ("A", 40, 99)]
    t1 = _table_of(["k", "t", "v"], lrows)
    t2 = _table_of(["k", "t", "v"], rrows)
    res = pw.temporal.asof_join(
        t1, t2, t1.t, t2.t, t1.k == t2.k,
        how="inner", direction=pw.temporal.Direction.NEAREST,
    ).select(lt=t1.t, lv=t1.v, rt=t2.t, rv=t2.v)
    assert _rows(res) == asof_oracle(lrows, rrows, "nearest", "inner")


def test_asof_exact_tie_goes_backward_match():
    # right row exactly at left time matches in backward mode
    lrows = [("A", 10, 1)]
    rrows = [("A", 10, 7)]
    t1 = _table_of(["k", "t", "v"], lrows)
    t2 = _table_of(["k", "t", "v"], rrows)
    res = pw.temporal.asof_join(
        t1, t2, t1.t, t2.t, t1.k == t2.k, how="inner"
    ).select(rv=t2.v)
    assert _rows(res) == [(7,)]


def test_asof_defaults_fill_unmatched():
    lrows = [("A", 5, 1)]
    rrows = [("A", 8, 95)]
    t1 = _table_of(["k", "t", "v"], lrows)
    t2 = _table_of(["k", "t", "v"], rrows)
    joined = pw.temporal.asof_join(
        t1, t2, t1.t, t2.t, t1.k == t2.k,
        how="left", defaults={t2.v: -1},
    ).select(lv=t1.v, rv=t2.v)
    assert _rows(joined) == [(1, -1)]


def test_asof_multiple_keys_partition():
    lrows = [("A", 10, 1), ("B", 10, 2), ("C", 10, 3)]
    rrows = [("A", 9, 91), ("B", 8, 92)]
    t1 = _table_of(["k", "t", "v"], lrows)
    t2 = _table_of(["k", "t", "v"], rrows)
    res = pw.temporal.asof_join_left(
        t1, t2, t1.t, t2.t, t1.k == t2.k
    ).select(k=t1.k, rv=t2.v)
    assert _rows(res) == [("A", 91), ("B", 92), ("C", None)]


@pytest.mark.parametrize(
    "direction",
    [
        pw.temporal.Direction.BACKWARD,
        pw.temporal.Direction.FORWARD,
        pw.temporal.Direction.NEAREST,
    ],
)
@pytest.mark.parametrize("seed", [0, 1])
def test_asof_oracle_sweep(direction, seed):
    rng = random.Random(seed * 7 + 1)
    keys = ["a", "b"]
    lrows = [
        (rng.choice(keys), rng.randint(0, 40), i) for i in range(20)
    ]
    # distinct right times per key: nearest-tie semantics stay unambiguous
    rrows = []
    used = set()
    for i in range(20):
        k = rng.choice(keys)
        t = rng.randint(0, 40)
        if (k, t) in used:
            continue
        used.add((k, t))
        rrows.append((k, t, 100 + i))
    dname = direction.name.lower()
    t1 = _table_of(["k", "t", "v"], lrows)
    t2 = _table_of(["k", "t", "v"], rrows)
    res = pw.temporal.asof_join(
        t1, t2, t1.t, t2.t, t1.k == t2.k, how="inner", direction=direction
    ).select(lt=t1.t, lv=t1.v, rt=t2.t, rv=t2.v)
    want = asof_oracle(lrows, rrows, dname, "inner")
    got = _rows(res)
    if dname != "nearest":
        assert got == want
    else:
        # nearest ties between equal |dt| right rows may pick either side
        # when both exist; compare pair counts and distances
        assert len(got) == len(want)
        for (glt, _gv, grt, _grv), (wlt, _wv, wrt, _wrv) in zip(
            sorted(got), sorted(want)
        ):
            assert glt == wlt and abs(grt - glt) == abs(wrt - wlt)


# ---------------------------------------------------------------------------
# window join


def window_pairs_oracle(lts, rts, hop, duration, how):
    def windows(t):
        k_hi = (t - 0) // hop
        out = []
        k = k_hi
        while k * hop + duration > t:
            if k * hop <= t:
                out.append(k)
            k -= 1
        return out

    out = []
    matched_r = set()
    for lt in lts:
        hit = False
        for j, rt in enumerate(rts):
            common = set(windows(lt)) & set(windows(rt))
            for _w in common:
                out.append((lt, rt))
                matched_r.add(j)
                hit = True
        if not hit and how in ("left", "outer"):
            out.append((lt, None))
    if how in ("right", "outer"):
        for j, rt in enumerate(rts):
            if j not in matched_r:
                out.append((None, rt))
    return sorted(out, key=lambda r: tuple((v is None, v) for v in r))


@pytest.mark.parametrize("how", MODES)
def test_window_join_tumbling_modes(how):
    lts = [1, 4, 7, 12]
    rts = [2, 8, 9, 20]
    t1 = _table_of(["t"], [(x,) for x in lts])
    t2 = _table_of(["t"], [(x,) for x in rts])
    res = pw.temporal.window_join(
        t1, t2, t1.t, t2.t, pw.temporal.tumbling(duration=5), how=how
    ).select(lt=t1.t, rt=t2.t)
    assert _rows(res) == window_pairs_oracle(lts, rts, 5, 5, how)


def test_window_join_sliding_multi_window_pairs():
    # sliding windows overlap: a pair sharing TWO windows appears twice
    t1 = _table_of(["t"], [(2,)])
    t2 = _table_of(["t"], [(3,)])
    res = pw.temporal.window_join(
        t1, t2, t1.t, t2.t, pw.temporal.sliding(hop=2, duration=4)
    ).select(lt=t1.t, rt=t2.t)
    assert _rows(res) == window_pairs_oracle([2], [3], 2, 4, "inner")
    assert len(_rows(res)) == 2


def test_window_join_with_equality_key():
    lrows = [("a", 1), ("b", 1)]
    rrows = [("a", 2), ("c", 2)]
    t1 = _table_of(["k", "t"], lrows)
    t2 = _table_of(["k", "t"], rrows)
    res = pw.temporal.window_join(
        t1, t2, t1.t, t2.t, pw.temporal.tumbling(duration=5),
        t1.k == t2.k,
    ).select(k=t1.k)
    assert _rows(res) == [("a",)]


def test_window_join_left_pads_unmatched():
    t1 = _table_of(["t", "v"], [(1, 10), (11, 20)])
    t2 = _table_of(["t", "w"], [(2, 7)])
    res = pw.temporal.window_join_left(
        t1, t2, t1.t, t2.t, pw.temporal.tumbling(duration=5)
    ).select(v=t1.v, w=t2.w)
    assert _rows(res) == [(10, 7), (20, None)]


def test_window_join_select_expressions():
    t1 = _table_of(["t", "v"], [(1, 10)])
    t2 = _table_of(["t", "w"], [(2, 7)])
    res = pw.temporal.window_join(
        t1, t2, t1.t, t2.t, pw.temporal.tumbling(duration=5)
    ).select(s=t1.v + t2.w)
    assert _rows(res) == [(17,)]


@pytest.mark.parametrize("seed", [0, 1])
def test_window_join_oracle_sweep(seed):
    rng = random.Random(seed + 11)
    lts = [rng.randint(0, 30) for _ in range(15)]
    rts = [rng.randint(0, 30) for _ in range(15)]
    how = MODES[seed % 4]
    t1 = _table_of(["t"], [(x,) for x in lts])
    t2 = _table_of(["t"], [(x,) for x in rts])
    res = pw.temporal.window_join(
        t1, t2, t1.t, t2.t, pw.temporal.tumbling(duration=4), how=how
    ).select(lt=t1.t, rt=t2.t)
    assert _rows(res) == window_pairs_oracle(lts, rts, 4, 4, how)


# ---------------------------------------------------------------------------
# typing / validation


def test_interval_join_rejects_mismatched_time_types():
    t1 = _table_of(["t"], [(1,)])
    t2 = _table_of(["s"], [("x",)])
    with pytest.raises((TypeError, ValueError, Exception)):
        r = pw.temporal.interval_join(
            t1, t2, t1.t, t2.s, pw.temporal.interval(-1, 1)
        ).select(lt=t1.t)
        GraphRunner().run_tables(r)


def test_no_extra_columns_leak_through_select():
    t1 = _table_of(["t", "v"], [(0, 1)])
    t2 = _table_of(["t", "w"], [(0, 2)])
    res = pw.temporal.interval_join(
        t1, t2, t1.t, t2.t, pw.temporal.interval(0, 0)
    ).select(v=t1.v)
    cols = set(res.column_names())
    assert cols == {"v"}


# ---------------------------------------------------------------------------
# session window join (reference: test_window_joins.py:406-740 — sessions
# built over the UNION of both sides' times; all left rows in a session
# join all right rows in it)


def session_join_oracle(lrows, rrows, max_gap, how, keyed=False):
    """Oracle over (key, t, v) rows. Sessions merge the union of both
    sides' times per key with gap < max_gap (strict, matching
    session(max_gap)); output pairs (lv, rv) with None padding."""
    from collections import defaultdict

    groups = defaultdict(list)
    for i, (k, t, v) in enumerate(lrows):
        groups[k if keyed else None].append(("L", t, i))
    for j, (k, t, v) in enumerate(rrows):
        groups[k if keyed else None].append(("R", t, j))
    out = []
    matched_l, matched_r = set(), set()
    for _k, events in groups.items():
        events.sort(key=lambda e: (e[1], e[0], e[2]))
        sessions = []
        for e in events:
            if sessions and e[1] - sessions[-1][-1][1] < max_gap:
                sessions[-1].append(e)
            else:
                sessions.append([e])
        for sess in sessions:
            ls = [e[2] for e in sess if e[0] == "L"]
            rs = [e[2] for e in sess if e[0] == "R"]
            for li in ls:
                for rj in rs:
                    out.append((lrows[li][2], rrows[rj][2]))
                    matched_l.add(li)
                    matched_r.add(rj)
    if how in ("left", "outer"):
        for i in range(len(lrows)):
            if i not in matched_l:
                out.append((lrows[i][2], None))
    if how in ("right", "outer"):
        for j in range(len(rrows)):
            if j not in matched_r:
                out.append((None, rrows[j][2]))
    return sorted(out, key=lambda r: tuple((v is None, v) for v in r))


@pytest.mark.parametrize("how", MODES)
@pytest.mark.parametrize("max_gap", [2, 3])
def test_session_window_join_time_only(how, max_gap):
    # the reference's canonical session-join scenario shape: two streams
    # whose union times chain into sessions of varying extent
    lrows = [(None, 0, 1), (None, 5, 2), (None, 10, 3), (None, 15, 4),
             (None, 17, 5)]
    rrows = [(None, -3, 1), (None, 2, 2), (None, 3, 3), (None, 6, 4),
             (None, 16, 5)]
    t1 = _table_of(["t", "v"], [(t, v) for _k, t, v in lrows])
    t2 = _table_of(["t", "v"], [(t, v) for _k, t, v in rrows])
    res = pw.temporal.window_join(
        t1, t2, t1.t, t2.t, pw.temporal.session(max_gap=max_gap), how=how
    ).select(a=t1.v, b=t2.v)
    assert _rows(res) == session_join_oracle(lrows, rrows, max_gap, how)


@pytest.mark.parametrize("how", MODES)
def test_session_window_join_sharded(how):
    lrows = [("a", 0, 1), ("a", 2, 2), ("b", 0, 3), ("c", 9, 4)]
    rrows = [("a", 1, 1), ("b", 7, 2), ("c", 10, 3), ("d", 0, 4)]
    t1 = _table_of(["k", "t", "v"], lrows)
    t2 = _table_of(["k", "t", "v"], rrows)
    res = pw.temporal.window_join(
        t1, t2, t1.t, t2.t, pw.temporal.session(max_gap=3),
        t1.k == t2.k, how=how,
    ).select(a=t1.v, b=t2.v)
    assert _rows(res) == session_join_oracle(
        lrows, rrows, 3, how, keyed=True
    )


def test_session_window_join_predicate():
    t1 = _table_of(["t", "v"], [(0, 1), (10, 2)])
    t2 = _table_of(["t", "v"], [(1, 5), (12, 6), (30, 7)])
    res = pw.temporal.window_join(
        t1, t2, t1.t, t2.t,
        pw.temporal.session(predicate=lambda a, b: b - a <= 2),
    ).select(a=t1.v, b=t2.v)
    assert _rows(res) == [(1, 5), (2, 6)]


def test_session_window_join_whole_chain_merges():
    # alternating sides chaining one long session: full cross product
    lrows = [(None, 0, 1), (None, 2, 2)]
    rrows = [(None, 1, 8), (None, 3, 9)]
    t1 = _table_of(["t", "v"], [(t, v) for _k, t, v in lrows])
    t2 = _table_of(["t", "v"], [(t, v) for _k, t, v in rrows])
    res = pw.temporal.window_join(
        t1, t2, t1.t, t2.t, pw.temporal.session(max_gap=2)
    ).select(a=t1.v, b=t2.v)
    assert _rows(res) == [(1, 8), (1, 9), (2, 8), (2, 9)]


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_session_window_join_oracle_sweep(seed):
    rng = random.Random(seed + 23)
    keys = ["a", "b"]
    lrows = [
        (rng.choice(keys), rng.randint(0, 40), 100 + i)
        for i in range(12)
    ]
    rrows = [
        (rng.choice(keys), rng.randint(0, 40), 200 + i)
        for i in range(12)
    ]
    how = MODES[seed % 4]
    t1 = _table_of(["k", "t", "v"], lrows)
    t2 = _table_of(["k", "t", "v"], rrows)
    res = pw.temporal.window_join(
        t1, t2, t1.t, t2.t, pw.temporal.session(max_gap=4),
        t1.k == t2.k, how=how,
    ).select(a=t1.v, b=t2.v)
    assert _rows(res) == session_join_oracle(
        lrows, rrows, 4, how, keyed=True
    )
