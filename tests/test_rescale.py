"""Elastic mesh (ISSUE 11): deterministic re-sharding, the rescale
model checker, the autoscaler policy, and the satellite surfaces
(frontend rescaling state, cluster world gauge).

The heavy end-to-end proofs live elsewhere: ``scripts/fault_matrix.py
--rescale`` (kill-during-rescale grid, bit-identical resumes across
world sizes), ``scripts/rescale_smoke.py`` (2→4→2 under live load,
CI lane 10) and ``python -m pathway_tpu.analysis --mesh --rescale``
(exhaustive crash-interleaving verification). This file pins the tier-1
surface: the pure transitions, the re-shard readers, and the policy.
"""

from __future__ import annotations

import os
import types

import pytest

import pathway_tpu.analysis.meshcheck as mc
import pathway_tpu.parallel.protocol as proto
from pathway_tpu.engine.stream import MultisetState, TableState
from pathway_tpu.parallel.procgroup import shard_hash, stable_shard
from pathway_tpu.persistence import reshard

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# the partition property (satellite: deterministic re-sharding)
# ---------------------------------------------------------------------------

SAMPLE_KEYS = (
    [i for i in range(40)]
    + [f"key-{i}" for i in range(20)]
    + [(i, f"v{i}") for i in range(20)]
    + [(i,) for i in range(20)]
)


@pytest.mark.parametrize("n", [1, 2, 3, 4])
@pytest.mark.parametrize("m", [1, 2, 3, 4])
def test_reshard_is_a_partition_and_roundtrips(n, m):
    """Re-partitioning a committed store's keys from N to M shards via
    the stable blake2b mint is a partition — every key lands in exactly
    one new shard — and N→M→N round-trips bit-identical. Pinned for
    N, M ∈ {1,2,3,4} in BOTH directions (the parametrization covers
    (n, m) and (m, n))."""
    # partition: each key kept by exactly one new rank
    for k in SAMPLE_KEYS:
        owners = [
            r for r in range(m)
            if proto.reshard_keep(shard_hash(k), r, m)
        ]
        assert len(owners) == 1
        assert owners[0] == stable_shard(k, m)
    assert reshard.partition_roundtrip(SAMPLE_KEYS, n, m)


def test_shard_owner_is_the_stable_shard_modulus():
    """stable_shard drives the shared shard_owner transition — the
    exact function the model checker explores and the re-shard reader
    re-buckets with."""
    for k in SAMPLE_KEYS[:20]:
        for world in (1, 2, 3, 5, 8):
            assert stable_shard(k, world) == proto.shard_owner(
                shard_hash(k), world
            )
    # frozen and raw forms hash identically (the mint freezes first),
    # so one keep filter serves python stores and native dumps alike
    from pathway_tpu.engine.stream import freeze_value

    for k in SAMPLE_KEYS[:20]:
        assert shard_hash(k) == shard_hash(freeze_value(k))


def test_transitions_identity_pins():
    """The checker's Transitions binds the exact protocol objects for
    the new rescale decisions — no second copy to drift."""
    t = mc.Transitions()
    for name in ("shard_owner", "reshard_keep", "rescale_plan"):
        assert name in mc.Transitions.NAMES
        assert getattr(t, name) is proto.TRANSITIONS[name]
        assert proto.TRANSITIONS[name] is getattr(proto, name)


def test_rescale_plan_clamps():
    assert proto.rescale_plan(2, 4) == 4
    assert proto.rescale_plan(2, 0) == 2       # invalid target holds
    assert proto.rescale_plan(2, None) == 2
    assert proto.rescale_plan(2, 9999, 1, 8) == 8
    assert proto.rescale_plan(4, 1, 2, 8) == 2  # floored at lo
    assert proto.rescale_plan(4, -3) == 4


def test_hello_accept_binds_world():
    """A dead-WORLD straggler is rejected like a dead-epoch one: its
    rank may be in range after a grow, but its slices were minted for
    a different shard count."""
    assert proto.hello_accept(0, 5, 4, 3, 5, 4)
    assert not proto.hello_accept(0, 5, 4, 3, 5, 2)   # dead world
    assert not proto.hello_accept(0, 5, 4, 3, 4, 4)   # dead epoch
    assert proto.hello_accept(0, 5, 4, 3, 5)          # legacy: no world
    assert not proto.hello_accept(0, 5, 4, 4, 5, 4)   # out of world


# ---------------------------------------------------------------------------
# re-shard readers over real state shapes
# ---------------------------------------------------------------------------


def _keep(rank, world):
    return reshard.keep_fn(rank, world)


def test_merge_and_filter_multiset_table_state():
    a, b = MultisetState(), MultisetState()
    a.apply_one("k1", ("r1",), 1)
    b.apply_one("k2", ("r2",), 2)
    merged = reshard.merge_values([a, b])
    assert set(merged.data) == {"k1", "k2"}
    # filter keeps exactly the new owner's keys
    for world in (2, 3):
        kept = [
            set(reshard.filter_value(merged, _keep(r, world)).data)
            for r in range(world)
        ]
        flat = [k for s in kept for k in s]
        assert sorted(flat) == sorted(merged.data)  # partition
    ta, tb = TableState(), TableState()
    ta.rows["x"] = (1,)
    tb.rows["y"] = (2,)
    tm = reshard.merge_values([ta, tb])
    assert set(tm.rows) == {"x", "y"}
    assert reshard.merge_values([{"a": 1}, {"b": 2}]) == {"a": 1, "b": 2}
    assert reshard.merge_values([{1, 2}, {3}]) == {1, 2, 3}


def test_groupby_reshard_state_python_form():
    from pathway_tpu.engine.nodes import GroupByNode

    keys = [(i,) for i in range(30)]
    states = []
    for r in range(3):
        groups = {
            k: [k, None, [1], 1, f"out{k}"]
            for k in keys
            if stable_shard(k, 3) == r
        }
        states.append({"groups": groups})
    self = types.SimpleNamespace(groups={})
    for rank in range(2):
        out = GroupByNode.reshard_state(self, states, _keep(rank, 2))
        assert set(out["groups"]) == {
            k for k in keys if stable_shard(k, 2) == rank
        }


def test_join_reshard_state_native_and_python():
    from pathway_tpu.engine.nodes import JoinNode

    jks = list(range(20))
    native_states = [
        {
            "__native__": [
                (jk, [("L", ("a",), 1)], [("R", ("b",), 1)])
                for jk in jks
                if stable_shard(jk, 2) == r
            ]
        }
        for r in range(2)
    ]
    self = types.SimpleNamespace(left=MultisetState(), right=MultisetState())
    self._replay_entries = lambda part: JoinNode._replay_entries(self, part)
    out = JoinNode.reshard_state(self, native_states, _keep(1, 3))
    assert set(e[0] for e in out["__native__"]) == {
        jk for jk in jks if stable_shard(jk, 3) == 1
    }
    # mixed native + python merges on the python side
    py_state = {"left": MultisetState(), "right": MultisetState()}
    py_state["left"].apply_one(99, ("K", ("row",)), 1)
    mixed = JoinNode.reshard_state(
        self, [native_states[0], py_state],
        _keep(stable_shard(99, 3), 3),
    )
    assert "__native__" not in mixed
    assert 99 in mixed["left"].data


def test_reshard_node_state_policies():
    from pathway_tpu.engine.nodes import MemoizedRowwiseNode, Node

    assert Node.RESHARD == "keyed"
    assert MemoizedRowwiseNode.RESHARD == "union"

    keyed = types.SimpleNamespace(RESHARD="keyed", RESHARD_ATTRS=None)
    states = [{"live": {k: [("r",), 1] for k in range(10) if k % 2 == r}}
              for r in range(2)]
    out = reshard.reshard_node_state(keyed, states, 0, 3)
    assert set(out["live"]) == {
        k for k in range(10) if stable_shard(k, 3) == 0
    }
    union = types.SimpleNamespace(RESHARD="union", RESHARD_ATTRS=None)
    out = reshard.reshard_node_state(union, states, 0, 3)
    assert set(out["live"]) == set(range(10))
    # refuse: non-empty un-re-shardable state names the node
    refuse = types.SimpleNamespace(RESHARD="refuse", RESHARD_ATTRS=None)
    with pytest.raises(RuntimeError, match="cannot rescale"):
        reshard.reshard_node_state(refuse, [{"heap": [1]}], 0, 2)
    assert reshard.reshard_node_state(
        refuse, [{"heap": [], "watermark": 5}], 0, 2
    ) is None


def test_reshard_subject_states_hook_and_refusal():
    snaps = [
        (None, {"src": {"done": [1, 2]}}, None),
        (None, {"src": {"done": [3]}}, None),
        (None, {"solo": {"pos": 7}}, None),
    ]

    class Hooked:
        def reshard_scan_state(self, states):
            done = sorted(set().union(*(set(s["done"]) for s in states)))
            return {"done": done}

    out = reshard.reshard_subject_states(
        ["src", "solo"], snaps, {"src": Hooked(), "solo": object()}
    )
    assert out["src"] == {"done": [1, 2, 3]}
    assert out["solo"] == {"pos": 7}  # one claiming rank: pass-through
    with pytest.raises(RuntimeError, match="reshard_scan_state"):
        reshard.reshard_subject_states(
            ["src"], snaps, {"src": object()}
        )
    # a 1->N grow: ONE old state, but the hook must still run so each
    # new rank re-filters the full old coverage for its own shard
    calls = []

    class Spy(Hooked):
        def reshard_scan_state(self, states):
            calls.append(len(states))
            return super().reshard_scan_state(states)

    out = reshard.reshard_subject_states(
        ["src"], [(None, {"src": {"done": [1, 2]}}, None)], {"src": Spy()}
    )
    assert calls == [1]
    assert out["src"] == {"done": [1, 2]}


def test_align_fingerprints_skips_exchange_nodes():
    old = ["SourceNode", "ExchangeNode", "GroupByNode", "OutputNode"]
    new = ["SourceNode", "GroupByNode", "OutputNode"]
    mapping = reshard.align_fingerprints(old, new)
    assert mapping == [0, 2, 3]
    back = reshard.align_fingerprints(new, old)
    assert back == [0, None, 1, 2]
    with pytest.raises(RuntimeError, match="graph shape"):
        reshard.align_fingerprints(old, ["SourceNode", "JoinNode"])


def test_fs_subject_reshard_scan_state(tmp_path):
    from pathway_tpu.internals.config import (
        pop_config_overlay,
        push_config_overlay,
    )
    from pathway_tpu.io.fs import _FsSubject

    root = tmp_path / "data"
    root.mkdir()
    paths = []
    for i in range(12):
        p = root / f"f{i}.txt"
        p.write_text("x")
        paths.append(str(p))
    states = []
    for r in range(3):
        mine = [p for p in paths if stable_shard(
            os.path.relpath(p, str(root)), 3) == r]
        states.append({
            "seen": {p: 1.0 for p in mine},
            "emitted": {p: [("k", ("row",))] for p in mine},
        })
    sub = _FsSubject(str(root), "plaintext", None, False, "static")
    token = push_config_overlay(processes=2, process_id=1)
    try:
        out = sub.reshard_scan_state(states)
    finally:
        pop_config_overlay(token)
    want = {
        p for p in paths
        if stable_shard(os.path.relpath(p, str(root)), 2) == 1
    }
    assert set(out["seen"]) == want
    assert set(out["emitted"]) == want


# ---------------------------------------------------------------------------
# the rescale model checker
# ---------------------------------------------------------------------------


def test_meshcheck_rescale_grow_and_shrink_clean():
    """The shipped rescale transition verifies clean over all crash
    interleavings of the rescale window — grow and shrink — and the
    verdict is not vacuous (rescale paths actually explored)."""
    for world, target in ((2, 3), (3, 2)):
        rep = mc.check(
            mc.MeshCheckConfig(
                world=world, rounds=2, fault_budget=1,
                rescale_to=target, snap_every=1,
            )
        )
        assert rep.complete
        assert rep.ok, rep.render()
        assert rep.rescales_explored > 0
        assert rep.rollbacks_explored > 0
        d = rep.to_dict()
        assert d["rescale_to"] == target
        assert d["rescales_explored"] == rep.rescales_explored


def test_meshcheck_rescale_deterministic():
    a = mc.check(mc.MeshCheckConfig(
        world=2, rounds=2, fault_budget=1, rescale_to=3, snap_every=1))
    b = mc.check(mc.MeshCheckConfig(
        world=2, rounds=2, fault_budget=1, rescale_to=3, snap_every=1))
    assert (a.states, a.transitions, a.terminals) == (
        b.states, b.transitions, b.terminals,
    )


def test_meshcheck_reshard_mutant_caught_with_replayable_trace():
    """The seeded re-shard mutant (drops one shard's committed entries
    on a world change) is caught as a lost-delta exactly-once violation
    with a minimal trace carrying the world transition — which
    fault_matrix --from-trace replays as a real rescale cell."""
    rep = mc.check(
        mc.MeshCheckConfig(
            world=2, rounds=2, fault_budget=1, rescale_to=3,
            snap_every=1, mutate="drop_reshard_shard",
        )
    )
    assert not rep.ok
    [v] = rep.violations
    assert v.kind == "exactly-once"
    assert "lost" in v.detail
    assert v.rescale == {"from": 2, "to": 3}
    assert v.to_dict()["rescale"] == {"from": 2, "to": 3}
    # the mutant only lives on the re-shard path: invisible without a
    # world change
    clean = mc.check(
        mc.MeshCheckConfig(
            world=2, rounds=2, fault_budget=1,
            mutate="drop_reshard_shard",
        )
    )
    assert clean.ok, clean.render()


def test_meshcheck_dead_world_straggler_caught():
    """A handshake that ignores epoch/world lets a pre-rescale
    straggler back in — the checker must see it under a rescale."""
    rep = mc.check(
        mc.MeshCheckConfig(
            world=2, rounds=1, fault_budget=1, rescale_to=3,
            snap_every=1, mutate="accept_dead_epoch",
        )
    )
    assert not rep.ok
    assert rep.violations[0].kind == "dead-epoch-straggler"


def test_meshcheck_base_model_unchanged():
    """The variable-world refactor must not perturb the fixed-world
    exploration: the canonical 3-rank config still exhausts cleanly
    with rollback paths explored."""
    rep = mc.check(mc.MeshCheckConfig(world=3, rounds=2, fault_budget=1))
    assert rep.complete and rep.ok, rep.render()
    assert rep.rollbacks_explored > 0
    assert rep.rescales_explored == 0


def test_meshcheck_rescale_rejects_broadcast_topologies():
    topo = (
        mc.Exchange(0, "broadcast", ()),
        mc.Exchange(1, "gather", (0,)),
    )
    with pytest.raises(ValueError, match="broadcast"):
        mc.check(
            mc.MeshCheckConfig(world=2, rounds=1, topology=topo,
                               rescale_to=3)
        )


# ---------------------------------------------------------------------------
# autoscaler policy
# ---------------------------------------------------------------------------


def _decide(**kw):
    base = dict(
        world=2, min_world=1, max_world=8,
        pressure=0.0, grow_pressure=1.0,
        efficiency=None, shrink_efficiency=0.35,
        grow_streak=0, shrink_streak=0, hysteresis=2,
        cooldown_remaining_s=0.0, budget_remaining=4,
    )
    base.update(kw)
    return proto.autoscale_decide(**base)


def test_autoscale_decide_grow_shrink_hold():
    assert _decide() == ("hold", 2)
    # pressure grows (doubling), but only past the hysteresis streak
    assert _decide(pressure=5, grow_streak=1) == ("hold", 2)
    assert _decide(pressure=5, grow_streak=2) == ("grow", 4)
    assert _decide(pressure=5, grow_streak=2, world=8) == ("hold", 8)  # cap
    # low efficiency shrinks (halving) only with zero pressure
    assert _decide(efficiency=0.1, shrink_streak=2, world=4) == ("shrink", 2)
    assert _decide(
        efficiency=0.1, shrink_streak=2, world=4, pressure=1
    ) == ("hold", 4)
    assert _decide(efficiency=0.1, shrink_streak=1, world=4) == ("hold", 4)
    assert _decide(efficiency=None, shrink_streak=9, world=4) == ("hold", 4)
    assert _decide(efficiency=0.1, shrink_streak=2, world=1) == ("hold", 1)


def test_autoscale_decide_cooldown_and_budget():
    assert _decide(
        pressure=5, grow_streak=9, cooldown_remaining_s=3.0
    ) == ("hold", 2)
    assert _decide(
        pressure=5, grow_streak=9, budget_remaining=0
    ) == ("hold", 2)


def test_autoscaler_step_bookkeeping():
    """The impure loop half: streaks accumulate, a rescale consumes
    budget and starts the cooldown, streaks reset."""
    from pathway_tpu.parallel.autoscale import (
        Autoscaler,
        AutoscaleConfig,
        Observation,
    )

    class FakeSup:
        processes = 2
        rescales = []

        def request_rescale(self, target, reason=""):
            self.rescales.append(target)
            self.processes = target
            return True

    clock = [0.0]
    sup = FakeSup()
    a = Autoscaler(
        sup,
        AutoscaleConfig(hysteresis=2, cooldown_s=10.0, budget=1),
        clock=lambda: clock[0],
    )
    assert a.step(Observation(5.0, None)) == ("hold", 2)   # streak 1
    assert a.step(Observation(5.0, None)) == ("grow", 4)   # streak 2
    assert sup.rescales == [4]
    assert a.budget_remaining == 0
    assert a.grow_streak == 0
    # budget exhausted: pressure can scream forever, the mesh holds
    for _ in range(5):
        assert a.step(Observation(50.0, None))[0] == "hold"
    # cooldown alone also holds (fresh budget, inside the window)
    a.budget_remaining = 1
    clock[0] = 5.0
    assert a.step(Observation(50.0, None))[0] == "hold"
    clock[0] = 20.0  # past cooldown; streak re-accumulates then fires
    assert a.step(Observation(50.0, None))[0] == "grow"


def test_autoscale_config_from_env(monkeypatch):
    from pathway_tpu.parallel.autoscale import AutoscaleConfig

    monkeypatch.setenv("PATHWAY_AUTOSCALE_MAX", "16")
    monkeypatch.setenv("PATHWAY_AUTOSCALE_HYSTERESIS", "5")
    c = AutoscaleConfig.from_env()
    assert c.max_world == 16 and c.hysteresis == 5
    assert "16" in c.describe()


def test_autoscale_knobs_registered():
    from pathway_tpu.analysis.knobs import KNOBS

    for name in (
        "PATHWAY_AUTOSCALE_MIN", "PATHWAY_AUTOSCALE_MAX",
        "PATHWAY_AUTOSCALE_COOLDOWN_S", "PATHWAY_AUTOSCALE_INTERVAL_S",
        "PATHWAY_AUTOSCALE_BUDGET", "PATHWAY_AUTOSCALE_GROW_PRESSURE",
        "PATHWAY_AUTOSCALE_SHRINK_EFFICIENCY",
        "PATHWAY_AUTOSCALE_HYSTERESIS",
    ):
        assert name in KNOBS, name


def test_autoscale_module_loads_by_file_path():
    """The supervisor loads autoscale.py by file path (stdlib-only):
    the module must import without the package __init__s."""
    import importlib.util

    path = os.path.join(REPO, "pathway_tpu", "parallel", "autoscale.py")
    spec = importlib.util.spec_from_file_location("_t_autoscale", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod._proto.rescale_plan(2, 4) == 4
    assert mod.AutoscaleConfig().min_world == 1


# ---------------------------------------------------------------------------
# satellites: frontend rescaling state, cluster world gauge
# ---------------------------------------------------------------------------


def test_serve_frontend_state_rescaling():
    sfs = proto.serve_frontend_state
    assert sfs(True, False, False) == "serving"
    assert sfs(False, False, False) == "recovering"
    assert sfs(False, False, True) == "rescaling"
    assert sfs(True, False, True) == "serving"   # attached = serving
    assert sfs(False, True, True) == "draining"  # draining wins
    # rescaling parks like recovering, sheds past the budget
    assert proto.serve_admit("rescaling", 0, 8, 0, 4) == "park"
    assert proto.serve_admit("rescaling", 0, 8, 4, 4) == "shed"
    assert "rescaling" in proto.SERVE_STATES


def test_frontend_split_rescale_ewma():
    """The rescale EWMA is tracked separately from the crash EWMA and
    is what sizes Retry-After while a rescale is in flight."""
    import importlib.util

    path = os.path.join(REPO, "pathway_tpu", "io", "http", "_frontend.py")
    spec = importlib.util.spec_from_file_location("_t_frontend", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    fe = mod.ServingFrontend.__new__(mod.ServingFrontend)
    fe.observed_restart_s = 2.0
    fe.observed_rescale_s = 8.0
    fe._rescaling = True
    assert fe._retry_after_s() == 8.0
    fe._rescaling = False
    assert fe._retry_after_s() == 2.0
    fe.observed_restart_s = 0.0
    assert fe._retry_after_s() == 8.0  # all we have observed


def test_cluster_world_gauge_and_departed_stale():
    from pathway_tpu.internals.cluster import ClusterMetricsAggregator

    agg = ClusterMetricsAggregator(
        9999, ClusterMetricsAggregator.default_endpoints(4)
    )
    for r in range(4):
        st = agg._ranks[r]
        st.samples = [("connector_rows_total", {}, 100.0 * (r + 1))]
        st.stale = False
    text = agg.render_cluster()
    assert "cluster_world_size 4" in text
    # shrink to 2: departed ranks retained, marked stale
    agg.set_endpoints(
        ClusterMetricsAggregator.default_endpoints(2), epoch=1
    )
    text = agg.render_cluster()
    assert "cluster_world_size 2" in text
    assert 'rank="3"' in text and 'stale="1"' in text
    assert agg._ranks[3].departed
    # departed totals are excluded from cross-rank derivations
    assert 3 not in agg._per_rank("connector_rows_total")


def test_discover_snapshot_world_from_legacy_marker(tmp_path):
    """A legacy bare marker was only ever written by an N-rank mesh:
    the single-process reader derives the true world from the
    rank-scoped snapshot keys instead of assuming world 1 (which would
    silently drop every other rank's shard)."""
    import pathway_tpu as pw
    from pathway_tpu.engine.runtime import Runtime
    from pathway_tpu.persistence import PersistenceManager

    pm = PersistenceManager(
        pw.persistence.Config(
            backend=pw.persistence.Backend.filesystem(str(tmp_path))
        )
    )
    for r in range(3):
        pm.save_operator_snapshot(
            [], {}, [], key=f"operator_snapshot/r{r}/5"
        )
    rt = Runtime.__new__(Runtime)
    rt.persistence = pm
    assert Runtime._discover_snapshot_world(rt, 5) == 3
    with pytest.raises(RuntimeError, match="no rank-scoped snapshot"):
        Runtime._discover_snapshot_world(rt, 9)


def test_marker_records_world(tmp_path):
    """The snapshot_commit marker carries (tag, world) — one atomic
    write — and legacy bare-int markers still read."""
    import pathway_tpu.persistence as pers

    pm = pers.PersistenceManager(
        pers.Config(backend=pers.Backend.filesystem(str(tmp_path)))
    )
    pm.write_marker("snapshot_commit", (7, 4))
    assert pm.read_marker("snapshot_commit") == (7, 4)
    pm.write_marker("snapshot_commit", 9)  # legacy form
    assert pm.read_marker("snapshot_commit") == 9


def test_supervisor_request_rescale_arming():
    """The supervisor's rescale arming clamps through rescale_plan and
    ignores no-op targets; the control-file poll parses targets."""
    import importlib.util

    path = os.path.join(REPO, "pathway_tpu", "parallel", "supervisor.py")
    spec = importlib.util.spec_from_file_location("_t_sup_rescale", path)
    sup_mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(sup_mod)
    sup = sup_mod.MeshSupervisor(["true"], processes=2)
    assert sup.request_rescale(4)
    assert sup._pending_rescale == 4
    sup._pending_rescale = None
    assert not sup.request_rescale(2)   # no-op
    assert not sup.request_rescale(0)   # invalid holds
    assert sup._pending_rescale is None


def test_supervisor_rescale_ctl_poll(tmp_path):
    import importlib.util

    path = os.path.join(REPO, "pathway_tpu", "parallel", "supervisor.py")
    spec = importlib.util.spec_from_file_location("_t_sup_ctl", path)
    sup_mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(sup_mod)
    ctl = tmp_path / "ctl"
    sup = sup_mod.MeshSupervisor(
        ["true"], processes=2, rescale_ctl=str(ctl)
    )
    sup._poll_rescale_ctl()      # missing file: no-op
    assert sup._pending_rescale is None
    ctl.write_text("garbage")
    sup._poll_rescale_ctl()      # unparsable: ignored until changed
    assert sup._pending_rescale is None
    ctl.write_text("3")
    sup._poll_rescale_ctl()
    assert sup._pending_rescale == 3
    sup._pending_rescale = None
    sup._poll_rescale_ctl()      # unchanged content: not re-armed
    assert sup._pending_rescale is None
