"""Native key-mint parity: fastpath.ref_scalar must be byte-identical to
the Python blake2b mint (persistence + multi-process determinism depend on
every rank minting the same keys regardless of toolchain availability).
Reference analog: src/engine/value.rs Key derivation is likewise a single
stable hash shared by every worker."""

import math
import random

import pytest

from pathway_tpu.internals.api import (
    Json,
    Pointer,
    _concat_lp,
    _hash_bytes,
    _value_to_bytes,
    ref_scalar,
)


def _py_mint(args: tuple) -> Pointer:
    return _hash_bytes(_concat_lp([_value_to_bytes(a) for a in args]))


def _fp():
    from pathway_tpu.native import get_fastpath

    fp = get_fastpath()
    if fp is None:
        pytest.skip("no native toolchain")
    return fp


def test_ref_scalar_parity_fuzz():
    fp = _fp()
    random.seed(1234)
    cases = []
    for _ in range(2000):
        n = random.randrange(0, 5)
        args = []
        for _ in range(n):
            t = random.randrange(9)
            if t == 0:
                args.append(None)
            elif t == 1:
                args.append(random.choice([True, False]))
            elif t == 2:
                args.append(random.randrange(-(2**63), 2**63))
            elif t == 3:
                args.append(random.random() * 1e6 - 5e5)
            elif t == 4:
                args.append("s" * random.randrange(3) + chr(random.randrange(32, 0x3000)))
            elif t == 5:
                args.append(bytes(random.randrange(256) for _ in range(random.randrange(4))))
            elif t == 6:
                args.append(Pointer(random.randrange(0, 2**128)))
            elif t == 7:
                args.append((random.randrange(100), "x", None))
            else:
                # beyond i64: exercises the python-fallback branch inside C
                args.append(random.randrange(-(2**200), 2**200))
        cases.append(tuple(args))
    for args in cases:
        assert fp.ref_scalar(args) == _py_mint(args), args


def test_ref_scalar_parity_edges():
    fp = _fp()
    edges = [
        (),
        (0,),
        (-1,),
        (1,),
        (255,),
        (-256,),
        (2**63 - 1,),
        (-(2**63),),
        (2**64,),
        (-(2**64),),
        (float("inf"),),
        (float("-inf"),),
        (float("nan"),),
        (0.0,),
        (-0.0,),
        ("",),
        ("\x00",),
        ("héllo",),
        (b"",),
        (b"\x00\xff",),
        ((),),
        ((1, (2, (3,))),),
        (Pointer(0),),
        (Pointer(2**128 - 1),),
        (True,),
        (False,),
        (None,),
        (Json({"a": [1, 2]}),),  # python-fallback branch
        (1, "two", 3.0, None, True, b"x", (7,)),
    ]
    for args in edges:
        assert fp.ref_scalar(args) == _py_mint(args), args
        assert type(fp.ref_scalar(args)) is Pointer


def test_public_ref_scalar_uses_consistent_mint():
    # whatever path api.ref_scalar takes, it must agree with the pure
    # python mint and handle optional=None contract
    assert ref_scalar(1, "a") == _py_mint((1, "a"))
    assert ref_scalar(1, None, optional=True) is None
    assert math.isfinite(float(int(ref_scalar("x")) % 2**32))
