"""Mesh Verifier battery (ISSUE 7): the bounded model checker of the
wave/rollback protocol, and the anti-drift pins that make its verdicts
mean something.

Pins:
* **shared transition table** — engine/runtime.py, parallel/procgroup.py
  and parallel/supervisor.py drive the SAME function objects
  (parallel/protocol.py TRANSITIONS) the checker explores: same-object
  identity, exactly like test_plan_doctor.py pins the shared NBDecision
  objects. A second implementation of any protocol decision cannot
  exist without failing here.
* **protocol self-properties** — the send/recv leg predicates mirror
  each other exhaustively (an asymmetry IS a deadlock), the commit walk
  is rank-major/stride-2/sorted, the supervisor verdict prefers root
  causes over rollback-request codes.
* **checker smoke (tier-1)** — N=3, small wave depth: the bounded state
  space is exhausted, zero violations on the shipped protocol, and two
  runs explore bit-identical state counts.
* **mutation coverage** — three deliberately broken protocol variants
  (skip the quiesce guard, accept a dead-epoch hello, drop the rollback
  retraction) are each caught with a minimal trace whose crash steps
  load as valid internals/faults.py rules (replayable via
  ``scripts/fault_matrix.py --from-trace``).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from pathway_tpu.analysis import meshcheck as mc
from pathway_tpu.parallel import protocol as proto


# ---------------------------------------------------------------------------
# anti-drift: one transition table, pinned by object identity
# ---------------------------------------------------------------------------


def test_engine_modules_drive_the_shared_protocol_module():
    """The runtime, the mesh backend and the supervisor all bind the
    SAME protocol module object the checker explores — no second copy
    of any decision exists to drift."""
    import pathway_tpu.engine.runtime as rt
    import pathway_tpu.parallel.procgroup as pg
    import pathway_tpu.parallel.supervisor as sup

    assert rt._proto is proto
    assert pg._proto is proto
    assert sup._proto is proto
    assert mc._proto is proto
    assert sup.MESH_RESTART_EXIT_CODE == proto.MESH_RESTART_EXIT_CODE == 28


def test_checker_transitions_are_the_table_objects():
    """The checker's default Transitions binds the exact function
    objects of protocol.TRANSITIONS (which are the module-level
    functions the engine calls) — flipping one flips both sides, with
    no second predicate to drift."""
    t = mc.Transitions()
    for name in mc.Transitions.NAMES:
        assert getattr(t, name) is proto.TRANSITIONS[name], name
        assert proto.TRANSITIONS[name] is getattr(proto, name), name


def test_supervisor_loads_protocol_by_file_path_outside_package():
    """scripts/fault_matrix.py loads supervisor.py by file path to stay
    import-light; the supervisor must pull protocol.py the same way and
    expose the same constants."""
    import importlib.util

    path = os.path.join(REPO, "pathway_tpu", "parallel", "supervisor.py")
    spec = importlib.util.spec_from_file_location("_t_sup", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.MESH_RESTART_EXIT_CODE == 28
    codes = [0, 137, 28]
    assert mod._proto.supervisor_decide(codes, 3, 3) == ("give_up", 137)


# ---------------------------------------------------------------------------
# protocol self-properties (unit checks of the shared table)
# ---------------------------------------------------------------------------


def test_wave_leg_predicates_mirror_exactly():
    """peer p receives from r iff r sends to p — exhaustively over
    world<=5, every rank pair, both gather modes, every contributor
    mask, AND every tree fanout (ISSUE 13: tree routes extend the
    mirror property — gather waves route child -> parent instead of
    all -> rank 0). An asymmetry here is a guaranteed rendezvous
    deadlock, which is why both sides live in one table."""
    for world in (2, 3, 5):
        for gather_only in (False, True):
            for fanout in (0, 2, 3):
                for contrib in [None] + list(range(1, 1 << world)):
                    sends = {
                        r: set(
                            proto.wave_send_targets(
                                world, r, gather_only, contrib, fanout
                            )
                        )
                        for r in range(world)
                    }
                    recvs = {
                        r: set(
                            proto.wave_recv_sources(
                                world, r, gather_only, contrib, fanout
                            )
                        )
                        for r in range(world)
                    }
                    for r in range(world):
                        for p in range(world):
                            if p == r:
                                continue
                            assert (p in sends[r]) == (r in recvs[p]), (
                                world, gather_only, contrib, fanout,
                                r, p,
                            )


def test_tree_fanout_resolution():
    """protocol.tree_fanout is the ONE resolver of
    PATHWAY_MESH_TREE_FANOUT (engine env + checker config drive it):
    auto = fanout 2 at world >= 4, off/garbage degrade safely, small
    worlds never tree (every rank is already rank 0's direct child)."""
    assert proto.tree_fanout(4, "auto") == 2
    assert proto.tree_fanout(8, None) == 2
    assert proto.tree_fanout(3, "auto") == 0
    assert proto.tree_fanout(2, "auto") == 0
    assert proto.tree_fanout(8, "off") == 0
    assert proto.tree_fanout(8, "0") == 0
    assert proto.tree_fanout(8, "3") == 3
    assert proto.tree_fanout(8, 4) == 4
    assert proto.tree_fanout(8, "1") == 0  # fanout 1 is a chain: refuse
    assert proto.tree_fanout(8, "garbage") == 2  # unparsable -> auto
    assert proto.tree_fanout(2, "2") == 0  # world 2 is already flat


def test_tree_topology_units():
    # heap layout: parent/children are mutual inverses over any world
    for world in (3, 4, 5, 8, 13):
        for fanout in (2, 3):
            for r in range(1, world):
                p = proto.tree_parent(r, fanout)
                assert 0 <= p < r
                assert r in proto.tree_children(p, world, fanout)
            # children partition 1..world-1
            seen = []
            for r in range(world):
                seen.extend(proto.tree_children(r, world, fanout))
            assert sorted(seen) == list(range(1, world))
    assert proto.tree_depth(4, 2) == 2
    assert proto.tree_depth(8, 2) == 3
    assert proto.tree_depth(16, 2) == 4
    assert proto.tree_depth(5, 4) == 1
    assert proto.tree_depth(6, 4) == 2
    assert proto.tree_depth(4, 0) == 0  # flat
    assert proto.tree_depth(1, 2) == 0


def test_tree_subtree_active_matches_descendant_set():
    """A rank's send leg exists iff its subtree holds a contributor —
    brute-force the descendant sets against the recursive predicate."""
    for world in (4, 5, 7):
        fanout = 2
        desc = {r: {r} for r in range(world)}
        for r in reversed(range(world)):
            for c in proto.tree_children(r, world, fanout):
                desc[r] |= desc[c]
        for contrib in range(1, 1 << world):
            for r in range(world):
                expect = any((contrib >> d) & 1 for d in desc[r])
                assert proto.tree_subtree_active(
                    r, world, fanout, contrib
                ) == expect, (world, contrib, r)


def test_tree_relay_concatenates_own_then_relayed():
    own = [(1, ("a",)), (2, ("b",))]
    rel = [(1, ("c",))]
    assert proto.tree_relay(own, rel) == own + rel
    assert proto.tree_relay([], rel) == rel
    assert proto.tree_relay(own, []) == own


def test_tree_gather_checker_clean_and_deterministic_world4():
    """The shipped tree transition verifies clean at world 4 (auto
    resolves fanout 2 — exactly what a real 4-rank mesh drives), and
    the exploration is deterministic."""
    cfg = mc.MeshCheckConfig(world=4, rounds=2, fault_budget=1)
    a = mc.check(cfg)
    b = mc.check(cfg)
    assert not a.violations, a.violations[:1]
    assert a.complete
    assert (a.states, a.transitions) == (b.states, b.transitions)
    # the tree is actually in the model: forcing it off explores a
    # DIFFERENT state space (flat gather legs)
    flat = mc.check(
        mc.MeshCheckConfig(
            world=4, rounds=2, fault_budget=1, tree_knob="off"
        )
    )
    assert not flat.violations
    assert flat.states != a.states


def test_drop_relay_mutant_caught_with_replayable_trace():
    """The drop_relay mutant (interior ranks forward only their own
    slices) must surface as lost deltas at world 4 — whole subtrees'
    gather output vanishes — with a minimal trace whose fault plan
    loads as real internals/faults.py rules."""
    rep = mc.check(
        mc.MeshCheckConfig(
            world=4, rounds=2, fault_budget=1, mutate="drop_relay"
        )
    )
    assert rep.violations, "drop_relay NOT caught"
    v = rep.violations[0]
    assert v.kind == "exactly-once", (v.kind, v.detail)
    assert "lost" in v.detail
    assert v.trace
    plan = v.fault_plan()
    if plan is not None:
        _validate_fault_plan(plan)


def test_drop_relay_invisible_without_interior_ranks():
    """The mutant only bites where a relay exists: world 3 (auto = no
    tree) and world 4 with the tree forced off must verify clean — the
    bug class is unreachable on flat topologies, which is exactly why
    the checker must explore the tree transition."""
    for cfg in (
        mc.MeshCheckConfig(
            world=3, rounds=2, fault_budget=1, mutate="drop_relay"
        ),
        mc.MeshCheckConfig(
            world=4, rounds=2, fault_budget=1, mutate="drop_relay",
            tree_knob="off",
        ),
    ):
        rep = mc.check(cfg)
        assert not rep.violations, (cfg.world, cfg.tree_knob)


def test_commit_plan_is_rank_major_stride2_sorted():
    plan = proto.commit_plan(100, [2, 0, 1], [[3, 3], [], [1]])
    assert plan == [(100, 3, 1), (102, 3, 1), (104, 1, 4)]
    assert all(t % 2 == 0 for t, _, _ in plan)
    assert proto.commit_time(100, 7) == 114


def test_lockstep_plan_min_time_and_contributors():
    assert proto.lockstep_plan([None, None]) is None
    plan = proto.lockstep_plan([(10, 0b01), None, (10, 0b10), (14, 0b11)])
    assert plan == (10, 0b11, 0b101)


def test_supervisor_decide_root_cause_over_restart_code():
    d = proto.supervisor_decide
    assert d([0, 0], 0, 3) == ("done", 0)
    assert d([28, 27], 0, 3) == ("rollback", 1)
    # budget exhausted: a real failing code wins over 28 (survivors
    # merely REPORTING the failure)
    assert d([28, 27], 3, 3) == ("give_up", 27)
    assert d([28, 28], 3, 3) == ("give_up", 28)


def test_hello_accept_epoch_and_rank_bounds():
    assert proto.hello_accept(0, 5, 4, 3, 5)
    assert not proto.hello_accept(0, 5, 4, 3, 4)   # dead epoch
    assert not proto.hello_accept(2, 5, 4, 1, 5)   # lower ranks dial
    assert not proto.hello_accept(0, 5, 4, 4, 5)   # out of world
    assert proto.peer_liveness(99.0, 10.0, False) == "failed"
    assert proto.peer_liveness(99.0, 10.0, True) == "alive"
    assert proto.peer_liveness(99.0, 0.0, False) == "alive"
    assert proto.classify_peer_loss(True) == "gone"
    assert proto.classify_peer_loss(False) == "crashed"


# ---------------------------------------------------------------------------
# checker smoke: the tier-1 surface of the acceptance criterion
# ---------------------------------------------------------------------------


def test_meshcheck_smoke_3rank_exhaustive_and_clean():
    """N=3, small wave depth, fault budget 1: the bounded space is
    exhausted, interleaving counts are reported, the shipped protocol
    shows zero violations, and rollback recovery paths were actually
    explored (the verdict is not vacuous)."""
    rep = mc.check(mc.MeshCheckConfig(world=3, rounds=2, fault_budget=1))
    assert rep.complete
    assert rep.ok, rep.render()
    assert rep.states > 100
    assert rep.transitions > rep.states
    assert rep.terminals > 1
    assert rep.rollbacks_explored > 0  # crashes + recoveries explored
    d = rep.to_dict()
    assert d["schema"] == "pathway_tpu.meshcheck/v1"
    assert d["ok"] and d["complete"] and d["violations"] == []


def test_meshcheck_deterministic():
    a = mc.check(mc.MeshCheckConfig(world=3, rounds=2, fault_budget=1))
    b = mc.check(mc.MeshCheckConfig(world=3, rounds=2, fault_budget=1))
    assert (a.states, a.transitions, a.terminals) == (
        b.states, b.transitions, b.terminals,
    )


def test_meshcheck_faultfree_2_and_4_ranks():
    for world in (2, 4):
        rep = mc.check(
            mc.MeshCheckConfig(
                world=world, rounds=1, fault_budget=0, straggler=False
            )
        )
        assert rep.ok, rep.render()


def test_meshcheck_state_cap_marks_incomplete():
    rep = mc.check(
        mc.MeshCheckConfig(
            world=3, rounds=2, fault_budget=1, max_states=50
        )
    )
    assert not rep.complete
    assert not rep.ok


# ---------------------------------------------------------------------------
# mutation coverage: the checker can see the bug classes it rules out
# ---------------------------------------------------------------------------


def _validate_fault_plan(plan: dict) -> None:
    """The trace's crash plan must load as real internals/faults.py
    rules — that is what makes it replayable by fault_matrix."""
    from pathway_tpu.internals import faults

    fp = faults.FaultPlan.from_spec(plan)
    assert fp.rules
    for rule in fp.rules:
        assert rule.point == "mesh.rank_kill"
        assert rule.action == "crash"
        assert rule.phase in ("wave_send", "post_snapshot", "restore")


@pytest.mark.parametrize(
    "mutant,kinds",
    [
        ("skip_quiesce", {"exactly-once", "deadlock", "wave-desync"}),
        ("accept_dead_epoch", {"dead-epoch-straggler"}),
        ("drop_rollback_retraction", {"exactly-once"}),
    ],
)
def test_mutant_caught_with_minimal_trace(mutant, kinds):
    rep = mc.check(
        mc.MeshCheckConfig(world=3, rounds=2, fault_budget=1, mutate=mutant)
    )
    assert rep.violations, f"mutant {mutant} NOT caught"
    v = rep.violations[0]
    assert v.kind in kinds, (mutant, v.kind, v.detail)
    assert v.trace, "violation carries no interleaving trace"
    plan = v.fault_plan()
    if plan is not None:
        _validate_fault_plan(plan)
    # the mutants that need a crash to surface must ship a replayable
    # plan; skip_quiesce loses deltas even fault-free
    if mutant != "skip_quiesce":
        assert plan is not None


def test_skip_quiesce_caught_without_any_fault():
    """The quiesce-guard mutant is a pure scheduling bug: it must be
    caught even with a zero fault budget (cascade deltas stranded at
    the downstream boundary = lost)."""
    rep = mc.check(
        mc.MeshCheckConfig(
            world=3, rounds=1, fault_budget=0, straggler=False,
            mutate="skip_quiesce",
        )
    )
    assert rep.violations
    assert rep.violations[0].kind == "exactly-once"
    assert "lost" in rep.violations[0].detail


def test_unknown_mutant_rejected():
    with pytest.raises(ValueError, match="unknown mutant"):
        mc.get_transitions("made_up")


# ---------------------------------------------------------------------------
# native race audit (scripts/lint_gil.py pass 3): the static half of
# the TSan lane must actually see the bug classes it claims to
# ---------------------------------------------------------------------------


_RACY_CPP = r"""
#include <thread>
#include <vector>
#include <atomic>
static long total;
static std::atomic<long> atotal;
void f(int W, std::vector<int> &shared,
       std::vector<std::vector<int>> &outs)
{
    auto work = [&](int w) {
        int local = 0;
        std::vector<int> view, scratch;      /* comma declarator list */
        for (int i = 0; i < 100; i++) {
            local += i;
            view.push_back(i);               /* lambda-local: ok */
            outs[(size_t)w].push_back(i);    /* shard slot: ok */
            atotal += i;                     /* std::atomic: ok */
            total += i;                      /* RACE: captured scalar */
            shared.push_back(i);             /* RACE: shared container */
            /* race-audit-ok: single-writer by construction (test) */
            shared[0] = i;
        }
        auto &mine = outs[(size_t)w];
        mine.push_back(local);               /* local ref: ok */
    };
    std::thread t0(work, 0);                 /* named-variable launch */
    std::vector<std::thread> threads;
    for (int w = 1; w < W; w++)
        threads.emplace_back(work, w);
    t0.join();
    for (auto &t : threads)
        t.join();
    (void)scratchless(0);
}
"""


def test_race_audit_catches_seeded_races_and_honors_escapes(tmp_path):
    """The shared-state race audit flags exactly the two seeded racing
    writes — not the lambda-local / shard-slot / atomic writes, and not
    the `race-audit-ok`-annotated one — and sees lambdas launched via
    the named-variable `std::thread t(work, 0);` form."""
    bad = tmp_path / "racy.cpp"
    bad.write_text(_RACY_CPP.replace("(void)scratchless(0);", ""))
    lint = os.path.join(REPO, "scripts", "lint_gil.py")
    res = subprocess.run(
        [sys.executable, lint, str(bad)],
        capture_output=True, text=True, timeout=120,
    )
    assert res.returncode == 1, res.stdout
    findings = [
        ln for ln in res.stdout.splitlines() if "worker lambda" in ln
    ]
    assert len(findings) == 2, res.stdout
    assert any("'total'" in f for f in findings), res.stdout
    assert any("'shared'" in f for f in findings), res.stdout
    for ok_root in ("'view'", "'outs'", "'atotal'", "'mine'", "'local'"):
        assert not any(ok_root in f for f in findings), res.stdout


def test_race_audit_clean_on_disciplined_worker(tmp_path):
    """A worker that only writes shard slots and locals passes — and a
    file with no std::thread at all skips the pass entirely."""
    good = tmp_path / "clean.cpp"
    good.write_text(
        "#include <thread>\n#include <vector>\n"
        "void f(int W, std::vector<std::vector<int>> &outs) {\n"
        "    auto work = [&](int w) {\n"
        "        auto &mine = outs[(size_t)w];\n"
        "        for (int i = 0; i < 9; i++)\n"
        "            mine.push_back(i);\n"
        "    };\n"
        "    std::vector<std::thread> threads;\n"
        "    for (int w = 0; w < W; w++)\n"
        "        threads.emplace_back(work, w);\n"
        "    for (auto &t : threads)\n"
        "        t.join();\n"
        "}\n"
    )
    lint = os.path.join(REPO, "scripts", "lint_gil.py")
    res = subprocess.run(
        [sys.executable, lint, str(good)],
        capture_output=True, text=True, timeout=120,
    )
    assert res.returncode == 0, res.stdout


# ---------------------------------------------------------------------------
# CLI + Plan Doctor integration
# ---------------------------------------------------------------------------


def _run_cli(*args):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    env.pop("PATHWAY_LANE_PROCESSES", None)
    return subprocess.run(
        [sys.executable, "-m", "pathway_tpu.analysis", *args],
        capture_output=True, text=True, timeout=300, env=env, cwd=REPO,
    )


def test_cli_mesh_smoke_and_mutant_exit_codes():
    res = _run_cli("--mesh", "--processes", "3", "--mesh-rounds", "1")
    assert res.returncode == 0, res.stderr[-1500:]
    assert "explored" in res.stdout and "states" in res.stdout
    assert "no deadlock" in res.stdout
    res = _run_cli(
        "--mesh", "--processes", "3", "--mesh-rounds", "1",
        "--mesh-mutant", "skip_quiesce", "--json",
    )
    assert res.returncode == 2, res.stdout[-500:]
    doc = json.loads(res.stdout)
    assert doc["schema"] == "pathway_tpu.meshcheck/v1"
    assert doc["violations"]


def test_doctor_mesh_pass_on_multirank_plans(monkeypatch):
    """pw.analyze(processes=4) runs the checker against the lowered
    plan's actual exchange topology and reports the distributed-safety
    verdict; PATHWAY_MESHCHECK_DOCTOR=0 disables the pass."""
    import pathway_tpu as pw

    pw.internals.parse_graph.G.clear()
    t = pw.debug.table_from_rows(
        pw.schema_from_types(data=str), [("a",), ("b",), ("a",)]
    )
    counts = t.groupby(pw.this.data).reduce(c=pw.reducers.count())
    monkeypatch.setenv("PATHWAY_MESHCHECK_ROUNDS", "1")
    rep = pw.analyze(counts, processes=4)
    mesh = [d for d in rep.diagnostics if d.code.startswith("mesh.")]
    assert len(mesh) == 1 and mesh[0].code == "mesh.verified"
    assert "4 ranks" in mesh[0].message
    assert mesh[0].severity == "info"
    # 1-rank plans never pay for the pass
    rep1 = pw.analyze(counts, processes=1)
    assert not [d for d in rep1.diagnostics if d.code.startswith("mesh.")]
    monkeypatch.setenv("PATHWAY_MESHCHECK_DOCTOR", "0")
    rep0 = pw.analyze(counts, processes=4)
    assert not [d for d in rep0.diagnostics if d.code.startswith("mesh.")]


def test_doctor_mesh_pass_reports_violations_as_errors(monkeypatch):
    """A protocol that fails the model check surfaces as an error
    diagnostic with a replayable fault plan in the hint (exercised via
    a mutant-driven check of the same plan topology)."""
    import pathway_tpu as pw
    from pathway_tpu.analysis import meshcheck

    pw.internals.parse_graph.G.clear()
    t = pw.debug.table_from_rows(
        pw.schema_from_types(data=str), [("a",), ("b",)]
    )
    counts = t.groupby(pw.this.data).reduce(c=pw.reducers.count())
    orig = meshcheck.check_runtime_mesh

    def broken(runtime, **kw):
        return orig(runtime, mutate="drop_rollback_retraction", **kw)

    monkeypatch.setattr(meshcheck, "check_runtime_mesh", broken)
    monkeypatch.setenv("PATHWAY_MESHCHECK_ROUNDS", "2")
    rep = pw.analyze(counts, processes=3)
    errs = [d for d in rep.diagnostics if d.code.startswith("mesh.")]
    assert errs and errs[0].severity == "error"
    assert errs[0].code == "mesh.exactly-once"
    assert "PATHWAY_FAULT_PLAN" in (errs[0].hint or "")


def test_topology_extraction_matches_exchange_graph():
    """check_runtime_mesh models the plan's REAL exchange nodes: modes
    and upstream relations read off the same reach masks the wave
    scheduler uses."""
    import pathway_tpu as pw
    from pathway_tpu.engine.runtime import Runtime
    from pathway_tpu.internals.config import (
        pop_config_overlay,
        push_config_overlay,
    )
    from pathway_tpu.internals.graph_runner import GraphRunner

    pw.internals.parse_graph.G.clear()
    t = pw.debug.table_from_rows(
        pw.schema_from_types(data=str), [("a",), ("b",)]
    )
    counts = t.groupby(pw.this.data).reduce(
        word=pw.this.data, c=pw.reducers.count()
    )
    pw.io.subscribe(counts, on_change=lambda *a: None)
    g = pw.internals.parse_graph.G
    ops = g.reachable_operators(g.output_operators())
    token = push_config_overlay(processes=3, process_id=0)
    try:
        runtime = Runtime(validate_env=False)
        GraphRunner(g)._lower(ops, runtime)
    finally:
        pop_config_overlay(token)
    topo = mc.topology_from_runtime(runtime)
    assert len(topo) == len(runtime.scope.exchange_nodes) > 0
    modes = {x.mode for x in topo}
    assert modes <= {"hash", "gather", "broadcast"}
    # a downstream gather must list its upstream hash boundary
    gathers = [x for x in topo if x.mode == "gather" and x.upstream]
    hashes = [x for x in topo if x.mode == "hash"]
    if gathers and hashes:
        assert any(
            h.idx in gx.upstream for gx in gathers for h in hashes
        )
