"""Randomized streaming-vs-batch consistency oracle.

The incremental engine's core guarantee: any interleaving of inserts and
retractions across commits converges to the SAME final state a one-shot
batch run produces. This fuzzes random op sequences through several
pipeline shapes and compares the streamed final state against the batch
recompute (the property differential dataflow provides by construction and
our rediff strategy must reproduce; reference Tier-2 strategy, SURVEY §4).
"""

import random

import pytest

import pathway_tpu as pw
from pathway_tpu.internals.graph_runner import GraphRunner


def _random_ops(rng, n_keys=8, n_ops=60):
    """Upsert/remove sequence over a small key space, grouped into commits."""
    live = {}
    ops = []
    commit = []
    for _ in range(n_ops):
        k = rng.randrange(n_keys)
        if k in live and rng.random() < 0.4:
            commit.append(("remove", k, live.pop(k)))
        else:
            v = rng.randrange(100)
            if k in live:
                commit.append(("remove", k, live.pop(k)))
            live[k] = v
            commit.append(("upsert", k, v))
        if rng.random() < 0.3:
            ops.append(commit)
            commit = []
    if commit:
        ops.append(commit)
    return ops, live


class _OpsSubject(pw.io.python.ConnectorSubject):
    def __init__(self, commits):
        super().__init__()
        self.commits = commits

    def run(self):
        for commit in self.commits:
            for kind, k, v in commit:
                if kind == "upsert":
                    self.next(k=k, v=v)
                else:
                    self.remove(k=k, v=v)
            self.commit()


class _Schema(pw.Schema):
    k: int = pw.column_definition(primary_key=True)
    v: int


PIPELINES = {
    "groupby_sum": lambda t: t.groupby(pw.this.k % 3).reduce(
        g=pw.this.k % 3, s=pw.reducers.sum(pw.this.v), c=pw.reducers.count()
    ),
    "filter_select": lambda t: t.filter(pw.this.v > 20).select(
        pw.this.k, d=pw.this.v * 2
    ),
    "self_join": lambda t: t.join(
        t.copy(), pw.left.k % 2 == pw.right.k % 2
    ).select(a=pw.left.v, b=pw.right.v),
    "minmax": lambda t: t.reduce(
        mn=pw.reducers.min(pw.this.v),
        mx=pw.reducers.max(pw.this.v),
        tup=pw.reducers.sorted_tuple(pw.this.v),
    ),
}


@pytest.mark.parametrize("pipeline", sorted(PIPELINES))
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_streaming_matches_batch(pipeline, seed):
    rng = random.Random(seed)
    commits, final_rows = _random_ops(rng)

    # streamed: ops arrive commit by commit with retractions
    t = pw.io.python.read(
        _OpsSubject(commits), schema=_Schema, autocommit_duration_ms=None
    )
    streamed = PIPELINES[pipeline](t)
    streamed_capture = GraphRunner().run_tables(streamed)[0]
    streamed_state = {
        k: row for k, row in streamed_capture.state.rows.items()
    }

    # batch: only the final rows, one static commit
    pw.internals.parse_graph.G.clear()
    if final_rows:
        batch_t = pw.debug.table_from_markdown(
            "\n".join(
                ["k | v"] + [f"{k} | {v}" for k, v in final_rows.items()]
            ),
            schema=_Schema,
        )
    else:
        batch_t = pw.Table.empty(k=int, v=int)
    batch = PIPELINES[pipeline](batch_t)
    batch_capture = GraphRunner().run_tables(batch)[0]
    batch_state = {k: row for k, row in batch_capture.state.rows.items()}

    assert streamed_state == batch_state, (
        f"{pipeline} seed={seed}: streamed {streamed_state} != "
        f"batch {batch_state}"
    )
