"""Numerical parity: Flax TransformerEncoder vs torch HF BertModel.

The reference's SentenceTransformerEmbedder runs real HF checkpoints in
torch (/root/reference/python/pathway/xpacks/llm/embedders.py:270-329). Our
loader (pathway_tpu/models/hf_loader.py) must map any BERT-family state dict
onto the Flax encoder with no numerical drift. This environment has zero
egress and no cached checkpoint, so the oracle is a locally constructed,
seeded torch `BertModel` with the exact bge-small-en-v1.5 geometry — the
weight-mapping and forward-pass math being verified are identical to what a
real checkpoint exercises.
"""

from __future__ import annotations

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax.numpy as jnp

from pathway_tpu.models.encoder import EncoderConfig, TransformerEncoder
from pathway_tpu.models.hf_loader import bert_state_dict_to_flax, config_from_hf
from pathway_tpu.models.tokenizer import wordpiece_tokenizer

SENTENCES = [
    "the quick brown fox jumps over the lazy dog",
    "a streaming dataflow framework for real time analytics",
    "tensor processing units multiply matrices in systolic arrays",
    "incremental computation maintains results under insertions and deletions",
    "the embedding model maps each sentence to a dense vector",
    "nearest neighbor search retrieves the most similar documents",
    "checkpointing allows the pipeline to resume after failures",
    "windows group events by time for aggregation",
] * 4  # 32 sentences


def _bge_small_torch(seed: int = 0):
    cfg = transformers.BertConfig(
        vocab_size=30522,
        hidden_size=384,
        num_hidden_layers=12,
        num_attention_heads=12,
        intermediate_size=1536,
        max_position_embeddings=512,
    )
    torch.manual_seed(seed)
    model = transformers.BertModel(cfg)
    model.eval()
    return cfg, model


def _torch_sentence_embed(model, ids, mask):
    """HF BertModel + mean-pool + L2 normalize (bge pooling) in torch."""
    with torch.no_grad():
        out = model(
            input_ids=torch.from_numpy(ids).long(),
            attention_mask=torch.from_numpy(mask).long(),
        ).last_hidden_state
        m = torch.from_numpy(mask).unsqueeze(-1).float()
        pooled = (out * m).sum(1) / m.sum(1).clamp(min=1.0)
        pooled = torch.nn.functional.normalize(pooled, dim=-1)
    return pooled.numpy()


def test_bge_small_parity_cosine():
    hf_cfg, torch_model = _bge_small_torch()
    config = config_from_hf(hf_cfg)
    # f32 activations for an exact comparison (flagship runs bf16 on TPU)
    config = EncoderConfig(
        vocab_size=config.vocab_size,
        hidden=config.hidden,
        layers=config.layers,
        heads=config.heads,
        mlp=config.mlp,
        max_len=config.max_len,
        dtype=jnp.float32,
    )
    params = bert_state_dict_to_flax(torch_model.state_dict(), config)

    tok = wordpiece_tokenizer(max_length=64)
    ids, mask = tok(SENTENCES)

    ours = np.asarray(
        TransformerEncoder(config).apply(
            {"params": params}, jnp.asarray(ids), jnp.asarray(mask)
        )
    )
    theirs = _torch_sentence_embed(torch_model, ids, mask)

    cos = np.sum(ours * theirs, axis=-1)  # both L2-normalized
    assert cos.shape == (len(SENTENCES),)
    assert np.all(cos >= 0.999), f"min cosine {cos.min()}"
    # embeddings are unit-norm
    np.testing.assert_allclose(np.linalg.norm(ours, axis=-1), 1.0, atol=1e-5)


def test_loader_roundtrip_shapes():
    hf_cfg, torch_model = _bge_small_torch(seed=1)
    config = config_from_hf(hf_cfg)
    params = bert_state_dict_to_flax(torch_model.state_dict(), config)
    assert params["tok_embed"]["embedding"].shape == (30522, 384)
    assert params["type_embed"]["embedding"].shape == (2, 384)
    assert params["block_0"]["attention"]["query"]["kernel"].shape == (384, 12, 32)
    assert params["block_11"]["attention"]["out"]["kernel"].shape == (12, 32, 384)
    # init-tree compatibility: converted params drop into the module's own tree
    import jax

    model = TransformerEncoder(config)
    ref = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32),
                     jnp.ones((1, 8), jnp.int32))["params"]
    ref_shapes = jax.tree.map(lambda a: a.shape, ref)
    got_shapes = jax.tree.map(lambda a: a.shape, params)
    assert ref_shapes == got_shapes


def test_wordpiece_tokenizer_real():
    tok = wordpiece_tokenizer(max_length=32)
    ids, mask = tok(["streaming dataflow computation", "the the the"])
    assert ids.shape == mask.shape and ids.shape[0] == 2
    # CLS/SEP framing and no UNK explosion on plain English
    assert ids[0, 0] == 2 and ids[0][mask[0].sum() - 1] == 3
    unk_rate = float(np.mean(ids[mask.astype(bool)] == 1))
    assert unk_rate < 0.05


def test_wordpiece_matches_hf_fast_tokenizer():
    """Our memoized WordPiece must be token-identical to BertTokenizerFast
    over the same vocab — normalization, punctuation splitting, greedy
    longest-match, truncation included."""
    from pathway_tpu.models.tokenizer import _VOCAB_ASSET
    from pathway_tpu.models.wordpiece import WordPieceTokenizer

    hf = wordpiece_tokenizer(max_length=16)
    ours = WordPieceTokenizer(_VOCAB_ASSET, max_length=16)

    cases = [
        "The quick brown fox jumps over the lazy dog.",
        "hello,world!  multiple   spaces\tand\ttabs",
        "CamelCase UPPERCASE lowercase MiXeD",
        "numbers 12345 and hyphen-ated words",
        "accented: café naïve résumé Zürich",
        "punctuation!!! ... (parens) [brackets] {braces} a+b=c",
        "a",
        "",
        "supercalifragilisticexpialidocious antidisestablishmentarianism",
        "unicode: 你好 world — em-dash and 'quotes'",
        "very long sentence " * 20,  # exercises truncation mid-word
        "trailing space ",
        "\n\nleading newlines",
        "x" * 150,  # beyond max_input_chars_per_word -> [UNK]
    ]
    for text in cases:
        ids_hf, mask_hf = hf([text])
        ids_us, mask_us = ours([text])
        assert ids_hf.tolist() == ids_us.tolist(), f"ids diverge on {text!r}"
        assert mask_hf.tolist() == mask_us.tolist(), f"mask diverges on {text!r}"

    # batch padding parity
    batch = cases[:6]
    ids_hf, mask_hf = hf(batch)
    ids_us, mask_us = ours(batch)
    assert ids_hf.tolist() == ids_us.tolist()
    assert mask_hf.tolist() == mask_us.tolist()


def test_wordpiece_memo_speed():
    """The memoized path must beat the HF fast tokenizer on repeated-word
    corpora (single-core streaming hot path)."""
    import time

    from pathway_tpu.models.tokenizer import _VOCAB_ASSET
    from pathway_tpu.models.wordpiece import WordPieceTokenizer

    ours = WordPieceTokenizer(_VOCAB_ASSET, max_length=512)
    rng = np.random.default_rng(0)
    with open(_VOCAB_ASSET, encoding="utf-8") as f:
        words = [w for w in (l.strip() for l in f) if w.isalpha() and len(w) > 2][:5000]
    docs = [
        " ".join(words[j] for j in rng.integers(0, len(words), size=90))
        for _ in range(512)
    ]
    ours(docs[:64])  # warm the memo
    t0 = time.perf_counter()
    ours(docs)
    dt = time.perf_counter() - t0
    assert len(docs) / dt > 4000, f"memoized WordPiece too slow: {len(docs)/dt:.0f} docs/s"
