"""Columnar egress battery (ISSUE 14): rows-vs-arrow bit-identical
parity for every egress surface — fs/csv, jsonlines, Delta and
``pw.io.subscribe(batch_format="arrow")`` — over mixed-dtype,
object-column and retraction workloads at 1 and 2 (emulated-lane)
ranks, with ``PATHWAY_NO_NB_CAPTURE=1`` forcing the row path; plus unit
coverage of the Arrow C-data-interface export itself
(``exec.cpp nb_export_arrow`` / ``capture_collect_nb``), the Python
fallback builder (``io/_arrow.py``), the CaptureNode columnar reader
and the egress eligibility verdicts.

Output files carry wall-clock commit timestamps, so "bit-identical" is
asserted modulo a dense-rank normalization of the ``time`` column (the
grouping structure must still agree — same rows in the same commits)."""

from __future__ import annotations

import csv as _csv
import glob
import json
import os
import subprocess
import sys
import tempfile

import pytest

import pathway_tpu as pw

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _toolchain() -> bool:
    try:
        from pathway_tpu.native import get_pwexec

        ex = get_pwexec()
    except Exception:
        return False
    return ex is not None and hasattr(ex, "nb_export_arrow")


def _pyarrow():
    try:
        import pyarrow as pa

        return pa
    except Exception:
        return None


needs_arrow = pytest.mark.skipif(
    not _toolchain() or _pyarrow() is None,
    reason="needs pwexec toolchain + pyarrow",
)


def _ex():
    from pathway_tpu.native import get_pwexec

    return get_pwexec()


def _mk_nb(msgs, cols):
    ex = _ex()
    out = ex.parse_upserts_nb(
        msgs, 0, tuple(cols), (None,) * len(cols), 1 << 64, 0, None
    )
    assert out is not None
    return out[0]


# -- unit: the C export ----------------------------------------------------

_DTYPE_CASES = {
    "int": [1, 2, -7, 2 ** 62],
    "float": [1.5, -0.25, 0.0, 1e300],
    "str": ["a", "", "héllo wörld", "x" * 500],
    "bool": [True, False, True, False],
    "int_nulls": [1, None, 3, None],
    "float_nulls": [None, 2.5, None, -1.0],
    "str_nulls": ["a", None, "", None],
    "bool_nulls": [None, True, None, False],
    "all_null": [None, None, None, None],
}


@needs_arrow
@pytest.mark.parametrize("case", sorted(_DTYPE_CASES), ids=sorted(_DTYPE_CASES))
def test_nb_export_parity_vs_materialize(case):
    """Every value that comes back from the Arrow export must be the
    value the row path (materialize) would have produced — type
    identity included (1 stays int, 1.0 stays float, True stays bool)."""
    from pathway_tpu.io._arrow import nb_to_arrow

    vals = _DTYPE_CASES[case]
    nb = _mk_nb([{"a": v, "tag": i} for i, v in enumerate(vals)], ("a", "tag"))
    rb = nb_to_arrow(nb, ("a", "tag"), include_diff=True)
    assert rb is not None
    got = rb.column(0).to_pylist()
    want = [row[0] for _k, row, _d in nb.materialize()]
    assert got == want
    for g, w in zip(got, want):
        assert type(g) is type(w)
    assert rb.column(rb.schema.get_field_index("diff")).to_pylist() == [1] * len(vals)


@needs_arrow
def test_nb_export_mixed_tag_column_falls_back():
    """A column mixing value tags (int next to str) cannot type as one
    Arrow column — the export returns None and the caller row-expands
    (counted, never an error)."""
    nb = _mk_nb([{"a": 1}, {"a": "x"}], ("a",))
    assert _ex().nb_export_arrow(nb, ("a",), 0, 0) is None
    # int next to float is mixed too: silent promotion would diverge
    # from the row path's type identity
    nb2 = _mk_nb([{"a": 1}, {"a": 2.5}], ("a",))
    assert _ex().nb_export_arrow(nb2, ("a",), 0, 0) is None


@needs_arrow
def test_nb_export_key_bytes_roundtrip():
    from pathway_tpu.io._arrow import key_from_bytes, nb_to_arrow

    nb = _mk_nb([{"a": i} for i in range(5)], ("a",))
    rb = nb_to_arrow(nb, ("a",), include_key=True)
    keys = [
        key_from_bytes(b)
        for b in rb.column(rb.schema.get_field_index("_key")).to_pylist()
    ]
    assert keys == [int(k) for k, _r, _d in nb.materialize()]


@needs_arrow
def test_capture_collect_nb_appends_time_column():
    nb1 = _mk_nb([{"a": 1}, {"a": 2}], ("a",))
    nb2 = _mk_nb([{"a": 3}], ("a",))
    merged = _ex().capture_collect_nb([(nb1, 7), (nb2, 9)])
    assert len(merged) == 3 and merged.width() == 2
    mat = merged.materialize()
    assert [row for _k, row, _d in mat] == [(1, 7), (2, 7), (3, 9)]


@needs_arrow
def test_capture_collect_nb_rejects_bad_input():
    nb1 = _mk_nb([{"a": 1}], ("a",))
    nb2 = _mk_nb([{"a": 1, "b": 2}], ("a", "b"))
    with pytest.raises(ValueError):
        _ex().capture_collect_nb([])
    with pytest.raises(ValueError):
        _ex().capture_collect_nb([(nb1, 1), (nb2, 2)])
    with pytest.raises(TypeError):
        _ex().capture_collect_nb([("not a batch", 1)])


# -- unit: the Python fallback builder ------------------------------------

@needs_arrow
def test_deltas_to_arrow_matches_c_export():
    """The two builders must produce the same logical batch for the
    same data — the tuple-fallback leg of an arrow subscriber cannot
    diverge from the zero-copy leg."""
    from pathway_tpu.io._arrow import deltas_to_arrow, nb_to_arrow

    msgs = [
        {"a": 1, "s": "x", "f": 1.5, "b": True, "o": None},
        {"a": None, "s": "", "f": -2.0, "b": False, "o": None},
    ]
    cols = ("a", "s", "f", "b", "o")
    nb = _mk_nb(msgs, cols)
    rb_c = nb_to_arrow(nb, cols, include_key=True, include_diff=True)
    deltas = [(k, row, d) for k, row, d in nb.materialize()]
    rb_py = deltas_to_arrow(deltas, cols, include_key=True)
    assert rb_c.schema.names == rb_py.schema.names
    assert rb_c.to_pydict() == rb_py.to_pydict()


@needs_arrow
def test_deltas_to_arrow_pickles_objects_and_roundtrips():
    from pathway_tpu.io._arrow import (
        deltas_to_arrow,
        is_pickled_field,
        unpickle_columns,
    )

    deltas = [
        (1, (("t", 1), 5), 1),
        (2, (None, 6), -1),
        (3, ({"k": [1, 2]}, 7), 1),
    ]
    rb = deltas_to_arrow(deltas, ("obj", "v"), include_key=False)
    f = rb.schema.field("obj")
    assert is_pickled_field(f)
    restored = unpickle_columns(rb)
    assert restored == {"obj": [("t", 1), None, {"k": [1, 2]}]}
    assert rb.column(rb.schema.get_field_index("v")).to_pylist() == [5, 6, 7]
    assert rb.column(rb.schema.get_field_index("diff")).to_pylist() == [1, -1, 1]


@needs_arrow
def test_deltas_to_arrow_pickle_veto_returns_none():
    from pathway_tpu.io._arrow import deltas_to_arrow

    deltas = [(1, ((1, 2),), 1)]
    assert deltas_to_arrow(deltas, ("o",), pickle_objects=False) is None
    # mixed numeric column: pickles rather than silently promoting
    rb = deltas_to_arrow([(1, (1,), 1), (2, (2.5,), 1)], ("n",))
    from pathway_tpu.io._arrow import unpickle_columns

    vals = unpickle_columns(rb)["n"]
    assert vals == [1, 2.5] and type(vals[0]) is int


@needs_arrow
def test_deltas_to_arrow_big_int_pickles():
    from pathway_tpu.io._arrow import deltas_to_arrow, unpickle_columns

    big = 2 ** 70
    rb = deltas_to_arrow([(1, (big,), 1)], ("n",))
    assert unpickle_columns(rb)["n"] == [big]


@needs_arrow
def test_record_batch_rows_adapter():
    from pathway_tpu.io._arrow import deltas_to_arrow, record_batch_rows

    deltas = [(1, (1, "a"), 1), (2, (2, "b"), -1)]
    rb = deltas_to_arrow(deltas, ("v", "s"), include_key=True)
    assert list(record_batch_rows(rb, ("v", "s"))) == [
        ((1, "a"), 1), ((2, "b"), -1),
    ]


# -- unit: CaptureNode columnar reader ------------------------------------

def _run_capture(rows, schema_cls):
    from pathway_tpu.internals.graph_runner import GraphRunner

    pw.internals.parse_graph.G.clear()

    class Src(pw.io.python.ConnectorSubject):
        _deletions_enabled = False

        def run(self):
            half = len(rows) // 2
            self.next_batch(rows[:half])
            self.commit()
            self.next_batch(rows[half:])
            self.commit()

    t = pw.io.python.read(Src(), schema=schema_cls, autocommit_duration_ms=None)
    return GraphRunner().run_tables(t)[0]


class _S(pw.Schema):
    k: int = pw.column_definition(primary_key=True)
    w: str
    v: float


_ROWS = [{"k": i, "w": f"w{i % 3}", "v": i * 0.5} for i in range(40)]


@needs_arrow
def test_capture_arrow_table_matches_state():
    cap = _run_capture(_ROWS, _S)
    tbl = cap.arrow_table(cols=["k", "w", "v"])
    assert tbl is not None
    got = sorted(
        zip(tbl.column("k").to_pylist(), tbl.column("w").to_pylist(),
            tbl.column("v").to_pylist())
    )
    # non-consuming: the row-expanding readers still work afterwards
    want = sorted(tuple(r) for r in cap.state.rows.values())
    assert got == want
    assert len(tbl.column("time").to_pylist()) == len(_ROWS)
    assert set(tbl.column("diff").to_pylist()) == {1}


@needs_arrow
def test_capture_arrow_table_none_after_expansion():
    cap = _run_capture(_ROWS, _S)
    _ = cap.state.rows  # reader expanded the pending chunks
    assert cap.arrow_table(cols=["k", "w", "v"]) is None


@needs_arrow
def test_capture_arrow_table_counters(monkeypatch):
    cap = _run_capture(_ROWS, _S)
    stats = cap.scope.runtime.stats
    before = stats.capture_arrow_rows
    assert cap.arrow_table(cols=["k", "w", "v"]) is not None
    assert stats.capture_arrow_rows == before + len(_ROWS)
    # forced off: the reader declines and the row path still works
    monkeypatch.setenv("PATHWAY_NO_NB_CAPTURE", "1")
    cap2 = _run_capture(_ROWS, _S)
    assert cap2.arrow_table(cols=["k", "w", "v"]) is None
    assert len(cap2.state.rows) == len(_ROWS)


@needs_arrow
def test_capture_arrow_table_cached_no_double_count():
    """Re-reading the capture neither redoes the C merge nor inflates
    the arrow counters the fused-egress audit pins."""
    cap = _run_capture(_ROWS, _S)
    stats = cap.scope.runtime.stats
    t1 = cap.arrow_table(cols=["k", "w", "v"])
    after = stats.capture_arrow_rows
    t2 = cap.arrow_table(cols=["k", "w", "v"])
    assert t2 is t1
    assert stats.capture_arrow_rows == after


@needs_arrow
def test_capture_arrow_table_name_width_mismatch():
    cap = _run_capture(_ROWS, _S)
    with pytest.raises(ValueError):
        cap.arrow_table(cols=["just_one"])


# -- unit: egress eligibility verdicts ------------------------------------

@needs_arrow
def test_sink_consumer_columnar_verdicts():
    from pathway_tpu.analysis import eligibility as elig
    from pathway_tpu.engine import nodes as N
    from pathway_tpu.internals.graph_runner import GraphRunner
    from pathway_tpu.engine.runtime import Runtime

    insts = []
    orig = Runtime.__init__

    def spy(self, *a, **k):
        orig(self, *a, **k)
        insts.append(self)

    Runtime.__init__ = spy
    try:
        pw.internals.parse_graph.G.clear()

        class Src(pw.io.python.ConnectorSubject):
            _deletions_enabled = False

            def run(self):
                self.next_batch([{"k": 1, "w": "a", "v": 0.5}])
                self.commit()

        t = pw.io.python.read(Src(), schema=_S, autocommit_duration_ms=None)
        pw.io.subscribe(t, on_batch=lambda *a: None, batch_format="arrow")
        pw.io.subscribe(t, on_batch=lambda *a: None)  # rows mode
        pw.io.subscribe(t, on_change=lambda *a: None)
        pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    finally:
        Runtime.__init__ = orig
    runtime = insts[0]
    outs = [n for n in runtime.scope.nodes if isinstance(n, N.OutputNode)]
    arrow_node = next(n for n in outs if n._on_batch_arrow is not None)
    rows_node = next(
        n for n in outs
        if n._on_batch is not None and n._on_batch_arrow is None
    )
    change_node = next(n for n in outs if n._on_change is not None)
    assert elig.sink_consumer_columnar(arrow_node).ok
    assert elig.sink_egress_decision(arrow_node).ok
    dec = elig.sink_consumer_columnar(rows_node)
    assert not dec.ok and any("rows-mode" in r for r in dec.reasons)
    dec = elig.sink_consumer_columnar(change_node)
    assert not dec.ok and any("on_change" in r for r in dec.reasons)
    # the runtime counters agree with the verdicts: the arrow node's
    # deliveries never expanded, the rows/change nodes' did
    assert runtime.stats.capture_arrow_batches > 0
    assert runtime.stats.capture_rows_expanded > 0


@needs_arrow
def test_sink_verdict_honest_without_pyarrow(monkeypatch):
    """A declared Arrow consumer on a host that cannot export must NOT
    read as fused — the runtime would row-expand every delivery there,
    and NB_STRICT must not fire (the plan says rows, so rows is not a
    demotion)."""
    from pathway_tpu.analysis import eligibility as elig
    from pathway_tpu.engine import nodes as N

    cap = _run_capture(_ROWS, _S)  # any runtime with an egress node
    node = N.OutputNode(
        cap.scope, cap.inputs[0],
        on_batch=lambda *a: None,
        on_batch_arrow=lambda *a: None,
        arrow_cols=("k", "w", "v"),
    )
    assert elig.sink_consumer_columnar(node).ok
    import pathway_tpu.io._arrow as A

    monkeypatch.setattr(A, "arrow_capable", lambda: False)
    dec = elig.sink_consumer_columnar(node)
    assert not dec.ok and any("pyarrow" in r for r in dec.reasons)


@needs_arrow
def test_probe_output_node_not_row_expanding():
    """A callback-free probe OutputNode (neutered non-writer rank) never
    materializes its batches — it must not read as row-expanding nor
    fire a sink diagnostic."""
    from pathway_tpu.analysis import eligibility as elig
    from pathway_tpu.engine import nodes as N

    cap = _run_capture(_ROWS, _S)
    probe = N.OutputNode(cap.scope, cap.inputs[0], on_end=lambda: None)
    assert elig.sink_consumer_columnar(probe).ok
    assert not elig.sink_row_expands(probe)
    assert elig.sink_egress_verdict(probe) in ("fused", "degraded")


@needs_arrow
def test_sink_decision_honors_forced_off(monkeypatch):
    from pathway_tpu.analysis import eligibility as elig

    monkeypatch.setenv("PATHWAY_NO_NB_CAPTURE", "1")
    cap = _run_capture(_ROWS, _S)
    dec = elig.sink_consumer_columnar(cap)
    assert not dec.ok and any("NO_NB_CAPTURE" in r for r in dec.reasons)


def test_subscribe_arrow_validates_arguments():
    pw.internals.parse_graph.G.clear()
    t = pw.debug.table_from_markdown(
        """
        a
        1
        """
    )
    with pytest.raises(ValueError):
        pw.io.subscribe(t, on_batch=lambda *a: None, batch_format="nope")
    with pytest.raises(ValueError):
        pw.io.subscribe(t, batch_format="arrow")


def test_no_nb_capture_knob_registered():
    from pathway_tpu.analysis.knobs import KNOBS

    assert "PATHWAY_NO_NB_CAPTURE" in KNOBS
    assert KNOBS["PATHWAY_NO_NB_CAPTURE"].type == "bool"


@needs_arrow
def test_egress_metrics_render():
    from pathway_tpu.internals.monitoring import ProberStats

    st = ProberStats()
    st.on_capture_arrow_batch(10)
    st.on_capture_rows_expanded(3)
    st.on_sink_egress_seconds("fs:out.csv", 0.25)
    text = st.render_openmetrics()
    assert "capture_arrow_batches_total 1" in text
    assert "capture_arrow_rows_total 10" in text
    assert "capture_rows_expanded_total 3" in text
    assert 'sink_egress_seconds_total{sink="fs:out.csv"} 0.25' in text


# -- end-to-end parity battery ---------------------------------------------
#
# One subprocess per (workload, world, forced) cell runs EVERY egress
# surface at once: csv + jsonlines + Delta writers plus an arrow-mode
# subscriber whose batches are re-serialized through record_batch_rows.
# The parametrized tests below compare the arrow-vs-forced-row outputs
# per sink (session-cached: 12 subprocess runs total).

_PROGRAM = """
import json, os, sys
sys.path.insert(0, {repo!r})
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import pathway_tpu as pw

workload = {workload!r}
outdir = {outdir!r}

if workload == "mixed":
    class S(pw.Schema):
        k: int = pw.column_definition(primary_key=True)
        s: str
        f: float
        b: bool
        o: str | None
    rows = [
        {{"k": i, "s": f"s{{i % 7}}", "f": i * 0.75, "b": i % 2 == 0,
          "o": None if i % 3 == 0 else f"o{{i}}"}}
        for i in range(120)
    ]
    class Src(pw.io.python.ConnectorSubject):
        _deletions_enabled = False
        def run(self):
            for s in range(0, len(rows), 40):
                self.next_batch(rows[s:s + 40])
                self.commit()
    t = pw.io.python.read(Src(), schema=S, autocommit_duration_ms=None)
    cols = ["k", "s", "f", "b", "o"]
elif workload == "object":
    S = pw.schema_from_types(k=int, meta=tuple, v=int)
    rows = [
        {{"k": i, "meta": ("tag", i % 3, (i,)), "v": i}} for i in range(90)
    ]
    class Src(pw.io.python.ConnectorSubject):
        _deletions_enabled = False
        def run(self):
            for s in range(0, len(rows), 30):
                self.next_batch(rows[s:s + 30])
                self.commit()
    t = pw.io.python.read(Src(), schema=S, autocommit_duration_ms=None)
    cols = ["k", "meta", "v"]
else:  # retraction
    class S(pw.Schema):
        k: int = pw.column_definition(primary_key=True)
        w: str
        v: int
    rows = [{{"k": i, "w": f"w{{i % 5}}", "v": i}} for i in range(80)]
    from pathway_tpu.internals.api import ref_scalar
    class Src(pw.io.python.ConnectorSubject):
        def run(self):
            self.next_batch(rows[:40]); self.commit()
            self.next_batch(rows[40:]); self.commit()
            for r in rows[::10]:
                self._remove(ref_scalar(r["k"]), r)
            self.commit()
    t = pw.io.python.read(Src(), schema=S, autocommit_duration_ms=None)
    cols = ["k", "w", "v"]

pw.io.csv.write(t, os.path.join(outdir, "out.csv"))
pw.io.jsonlines.write(t, os.path.join(outdir, "out.jsonl"))
if workload != "object":
    # the Delta writer requires arrow-representable dtypes on BOTH
    # paths (pa.table inference refuses tuples) — excluded, not a
    # parity asymmetry
    pw.io.deltalake.write(
        t, os.path.join(outdir, "lake"), min_commit_frequency=None
    )
sub = []
def on_batch(time_, rb):
    from pathway_tpu.io._arrow import record_batch_rows
    for row, d in record_batch_rows(rb, cols):
        sub.append([repr(row), d, time_])
pw.io.subscribe(t, on_batch=on_batch, batch_format="arrow")
pw.run(monitoring_level=pw.MonitoringLevel.NONE)

times = sorted({{s[2] for s in sub}})
rank = {{t_: i for i, t_ in enumerate(times)}}
sub = sorted([s[0], s[1], rank[s[2]]] for s in sub)
from pathway_tpu.engine import runtime as R
st = R.LAST_RUN_STATS
with open(os.path.join(outdir, "result.json"), "w") as f:
    json.dump({{
        "subscribe": sub,
        "arrow_batches": st.capture_arrow_batches,
        "rows_expanded": st.capture_rows_expanded,
        "nb_fallbacks": st.nb_fallbacks,
    }}, f)
"""

_CELLS = {}


def _run_cell(workload: str, world: int, forced: bool, tmp_root: str) -> dict:
    key = (workload, world, forced)
    if key in _CELLS:
        return _CELLS[key]
    outdir = os.path.join(
        tmp_root, f"{workload}-w{world}-{'rows' if forced else 'arrow'}"
    )
    os.makedirs(outdir, exist_ok=True)
    prog = os.path.join(outdir, "prog.py")
    with open(prog, "w") as f:
        f.write(_PROGRAM.format(repo=REPO, workload=workload, outdir=outdir))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PATHWAY_NO_NB_CAPTURE", None)
    env.pop("PATHWAY_LANE_PROCESSES", None)
    if forced:
        env["PATHWAY_NO_NB_CAPTURE"] = "1"
    if world > 1:
        env["PATHWAY_LANE_PROCESSES"] = str(world)
    r = subprocess.run(
        [sys.executable, prog], env=env, capture_output=True, text=True,
        timeout=300,
    )
    assert r.returncode == 0, r.stderr[-4000:]
    with open(os.path.join(outdir, "result.json")) as f:
        res = json.load(f)
    res["outdir"] = outdir
    _CELLS[key] = res
    return res


@pytest.fixture(scope="module")
def cell_root():
    with tempfile.TemporaryDirectory() as td:
        yield td
        _CELLS.clear()


def _norm_csv(path):
    with open(path) as f:
        rdr = list(_csv.reader(f))
    hdr, rows = rdr[0], rdr[1:]
    ti = hdr.index("time")
    times = sorted({r[ti] for r in rows})
    rank = {t: i for i, t in enumerate(times)}
    return hdr, sorted(
        [r[:ti] + [rank[r[ti]]] + r[ti + 1:] for r in rows], key=str
    )


def _norm_jsonl(path):
    rows = [json.loads(ln) for ln in open(path) if ln.strip()]
    times = sorted({r["time"] for r in rows})
    rank = {t: i for i, t in enumerate(times)}
    for r in rows:
        r["time"] = rank[r["time"]]
    return sorted(rows, key=lambda r: json.dumps(r, sort_keys=True))


def _norm_lake(lakedir):
    import pyarrow.parquet as pq

    rows = []
    for p in glob.glob(os.path.join(lakedir, "*.parquet")):
        rows.extend(pq.read_table(p, use_threads=False).to_pylist())
    times = sorted({r["time"] for r in rows})
    rank = {t: i for i, t in enumerate(times)}
    for r in rows:
        r["time"] = rank[r["time"]]
    return sorted(rows, key=lambda r: json.dumps(r, sort_keys=True))


_WORKLOADS = ("mixed", "object", "retraction")
_WORLDS = (1, 2)


@needs_arrow
@pytest.mark.parametrize("world", _WORLDS, ids=["1rank", "2rank"])
@pytest.mark.parametrize("workload", _WORKLOADS)
@pytest.mark.parametrize("sink", ["csv", "jsonlines", "delta", "subscribe"])
def test_rows_vs_arrow_parity(sink, workload, world, cell_root):
    if sink == "delta" and workload == "object":
        pytest.skip("Delta writer refuses object dtypes on both paths")
    arrow = _run_cell(workload, world, False, cell_root)
    rows = _run_cell(workload, world, True, cell_root)
    if sink == "csv":
        a = _norm_csv(os.path.join(arrow["outdir"], "out.csv"))
        b = _norm_csv(os.path.join(rows["outdir"], "out.csv"))
    elif sink == "jsonlines":
        a = _norm_jsonl(os.path.join(arrow["outdir"], "out.jsonl"))
        b = _norm_jsonl(os.path.join(rows["outdir"], "out.jsonl"))
    elif sink == "delta":
        a = _norm_lake(os.path.join(arrow["outdir"], "lake"))
        b = _norm_lake(os.path.join(rows["outdir"], "lake"))
        assert a, "empty lake"
    else:
        a = arrow["subscribe"]
        b = rows["subscribe"]
        assert a, "empty subscription"
    assert a == b


@needs_arrow
@pytest.mark.parametrize("world", _WORLDS, ids=["1rank", "2rank"])
@pytest.mark.parametrize("workload", _WORKLOADS)
def test_counters_match_path(workload, world, cell_root):
    """The egress counters tell the truth about which path ran: the
    arrow run of a columnar workload delivers arrow batches and never
    expands; the forced-row run expands and never delivers arrow.
    Object/retraction workloads are tuple chains — no columnar batches
    exist at the sink, so BOTH paths leave arrow at zero (the Python
    fallback builder is not 'columnar egress', it is the graceful
    conversion of an already-row-expanded delivery)."""
    arrow = _run_cell(workload, world, False, cell_root)
    rows = _run_cell(workload, world, True, cell_root)
    assert rows["arrow_batches"] == 0
    if workload == "mixed":
        assert arrow["arrow_batches"] > 0
        assert arrow["rows_expanded"] == 0
        assert rows["rows_expanded"] > 0
        # forcing the egress knob must not create upstream fallbacks
        assert rows["nb_fallbacks"] == arrow["nb_fallbacks"]
    else:
        assert arrow["arrow_batches"] == 0
