/* Sharded native group-by executor — the multi-worker relational engine
 * core (reference: N timely workers each owning a key shard with exchange
 * at groupby boundaries, src/engine/dataflow.rs:5538, dataflow/shard.rs;
 * semigroup reducers, src/engine/reduce.rs:40).
 *
 * Model: a GroupStore holds W shard-local hash maps (W = PATHWAY_THREADS).
 * Each delta batch is processed in three phases:
 *   1. extract (GIL): grouping values are serialized to injective byte
 *      keys, reducer args to tagged scalars, diffs to i64. Unsupported
 *      values raise Fallback — the node migrates to the Python path.
 *   2. apply (GIL RELEASED): rows are partitioned by hash(key) % W and W
 *      threads update their shard maps independently — the in-process
 *      equivalent of the reference's exchange + per-worker state. This is
 *      where multi-core scaling happens.
 *   3. emit (GIL): new groups get their output Pointer minted by the
 *      Python key_fn (once per group lifetime); before/after reducer
 *      values that changed become retract/insert delta pairs.
 *
 * Reducers: the abelian set — count / sum (int-exact, float-promoting,
 * ERROR-poisoning, None-skipping) / avg — plus ordered min/max (value
 * multiset per group) and the multiset-valued suite — tuple /
 * sorted_tuple (+skip_nones variants) / unique / any / argmin / argmax /
 * earliest / latest — with optional groupby sort_by ordering (reference:
 * src/engine/reduce.rs:22-594). Multiset-valued ("fp") reducers detect
 * output changes via GIL-free finished-value fingerprints in phase 2 and
 * build Python values only for changed groups in phase 3.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <map>
#include <memory>
#include <string>
#include <mutex>
#include <thread>
#include <unordered_map>
#include <vector>

#include "pw_blake2b.h"

/* Heterogeneous unordered_map lookup (string_view probe into a
 * string-keyed map) is a C++20 library feature that libstdc++ only ships
 * from GCC 11; on older toolchains fall back to materializing the probe
 * key so the extension still builds (g++ 10 is the floor some images
 * carry). */
#if defined(__cpp_lib_generic_unordered_lookup)
#define PW_SV_FIND(map_, sv_) (map_).find(sv_)
#else
#define PW_SV_FIND(map_, sv_) (map_).find(std::string(sv_))
#endif

namespace {

PyObject *FallbackError = nullptr;

/* ---- tagged scalar for reducer args ---------------------------------- */

enum ValTag : uint8_t { V_NONE, V_ERR, V_INT, V_FLT, V_STR };

struct Val {
    ValTag tag;
    int64_t i;
    double f;
    const char *sptr;   /* V_STR: UTF-8 view into the batch object */
    Py_ssize_t slen;
    PyObject *obj;      /* borrowed original (joint-multiset storage) */
};

/* ordered value for min/max multisets and the sorted_tuple ordering:
 * None sorts first (reference: Value::None is the smallest Value,
 * value.rs:208), numerics compare numerically (ints exactly against
 * ints; mixed int/float via double, tag-broken so 5 and 5.0 stay
 * distinct adjacent entries); strings sort after numerics by code point
 * (UTF-8 byte order) */
struct MVal {
    uint8_t tag = V_NONE; /* V_NONE / V_ERR / V_INT / V_FLT / V_STR */
    int64_t i = 0;
    double f = 0.0;
    std::string s;

    /* None=0 < numeric=1 < string=2 (V_ERR never enters an ordering:
     * codes that would compare it fall back to the Python path, which
     * raises the same TypeError the reference's semantics demand) */
    int rank() const
    {
        if (tag == V_NONE || tag == V_ERR)
            return 0;
        return tag == V_STR ? 2 : 1;
    }

    bool operator<(const MVal &o) const {
        const int ra = rank(), rb = o.rank();
        if (ra != rb)
            return ra < rb;
        if (ra == 0)
            return false; /* Nones tie */
        const bool anum = ra == 1;
        if (!anum)
            return s < o.s;
        if (tag == V_INT && o.tag == V_INT)
            return i < o.i;
        /* exact mixed int/float ordering: x86-64 long double carries a
         * 64-bit mantissa, so every int64 converts losslessly (doubles
         * would misorder |int| > 2^53 against nearby floats) */
        const long double a = tag == V_INT ? (long double)i : (long double)f;
        const long double b =
            o.tag == V_INT ? (long double)o.i : (long double)o.f;
        if (a != b)
            return a < b;
        return tag < o.tag; /* 5 (int) before 5.0 (float), stable */
    }
    bool num_equal(const MVal &o) const {
        const int ra = rank(), rb = o.rank();
        if (ra != rb)
            return false;
        if (ra == 0)
            return tag == o.tag; /* None==None, ERROR==ERROR */
        if (ra == 2)
            return s == o.s;
        const long double a = tag == V_INT ? (long double)i : (long double)f;
        const long double b =
            o.tag == V_INT ? (long double)o.i : (long double)o.f;
        return a == b;
    }
};

inline MVal mval_of(const Val &v)
{
    MVal m;
    m.tag = v.tag;
    if (v.tag == V_INT)
        m.i = v.i;
    else if (v.tag == V_FLT)
        m.f = v.f;
    else if (v.tag == V_STR)
        m.s.assign(v.sptr, (size_t)v.slen);
    return m;
}

/* serialize an MVal with the SAME numeric normalization as ser_value
 * (integral floats and bools collapse onto ints), so fingerprint
 * equality coincides with Python tuple equality — the condition under
 * which the Python path's consolidate() cancels a retract/insert pair */
inline void mval_ser(std::string &out, const MVal &m)
{
    switch (m.tag) {
    case V_NONE:
        out.push_back('\x01');
        return;
    case V_ERR:
        out.push_back('\x02');
        return;
    case V_INT: {
        out.push_back('I');
        out.append(reinterpret_cast<const char *>(&m.i), 8);
        return;
    }
    case V_FLT: {
        double d = m.f;
        if (d == (double)(int64_t)d && d >= -9.2e18 && d <= 9.2e18) {
            int64_t i = (int64_t)d;
            out.push_back('I');
            out.append(reinterpret_cast<const char *>(&i), 8);
            return;
        }
        out.push_back('F');
        out.append(reinterpret_cast<const char *>(&d), 8);
        return;
    }
    case V_STR: {
        uint32_t len = (uint32_t)m.s.size();
        out.push_back('S');
        out.append(reinterpret_cast<const char *>(&len), 4);
        out.append(m.s);
        return;
    }
    }
}

/* ---- per-spec reducer state ------------------------------------------ */

enum Code : uint8_t {
    C_COUNT,
    C_SUM,
    C_AVG,
    C_MIN,
    C_MAX,
    /* multiset-valued reducers (reference: reduce.rs:22-594 Tuple/
     * SortedTuple/Unique/ArgMin/ArgMax/Earliest/Latest/Any): finished
     * values are recomputed from the group's joint row multiset at emit
     * time; change detection runs on GIL-free fingerprints in phase 2 */
    C_ARGMIN,
    C_ARGMAX,
    C_UNIQUE,
    C_ANY,
    C_TUPLE,
    C_TUPLE_SN, /* skip_nones variant */
    C_STUPLE,
    C_STUPLE_SN,
    C_EARLIEST,
    C_LATEST,
};

/* codes whose finished value lives in the joint multiset (fp = they use
 * the fingerprint machinery rather than FinSnap scalar images) */
inline bool is_fp(uint8_t c) { return c >= C_ARGMIN; }
/* codes that ORDER arg values — mixed numeric/string args (or an ERROR
 * arg) would raise TypeError in Python; they fall back instead */
inline bool orders_args(uint8_t c)
{
    return c == C_MIN || c == C_MAX || c == C_ARGMIN || c == C_ARGMAX ||
           c == C_STUPLE || c == C_STUPLE_SN;
}
/* fp codes whose comparisons reject ERROR args (Python raises); min/max
 * instead count ERROR contributions and poison the output */
inline bool rejects_error(uint8_t c)
{
    return c == C_ARGMIN || c == C_ARGMAX || c == C_STUPLE ||
           c == C_STUPLE_SN;
}
/* codes whose comparisons include None values (argmin/argmax compare
 * (value, key) tuples, so None is a kind of its own — see SpecKind) */
inline bool compares_none(uint8_t c) { return c == C_ARGMIN || c == C_ARGMAX; }

/* order-preserving 16-byte big-endian image of a row key (Pointer
 * subclasses int, always a non-negative 128-bit value). Shared by
 * process_batch phase 1 and store_load. */
bool key_ord_of(PyObject *key, std::string &out)
{
    if (PyLong_Check(key)) {
        unsigned char buf[16];
#if PY_VERSION_HEX >= 0x030D0000
        if (_PyLong_AsByteArray((PyLongObject *)key, buf, 16, 0, 0, 0) == 0) {
#else
        if (_PyLong_AsByteArray((PyLongObject *)key, buf, 16, 0, 0) == 0) {
#endif
            out.assign(reinterpret_cast<char *>(buf), 16);
            return true;
        }
        PyErr_Clear();
    }
    /* non-int or >128-bit key: slow path via int.to_bytes for parity */
    PyObject *kb = PyObject_CallMethod(key, "to_bytes", "is", 16, "big");
    if (kb == nullptr || !PyBytes_Check(kb)) {
        Py_XDECREF(kb);
        PyErr_Clear();
        return false;
    }
    out.assign(PyBytes_AS_STRING(kb), (size_t)PyBytes_GET_SIZE(kb));
    Py_DECREF(kb);
    return true;
}

struct SState {
    int64_t cnt = 0;     /* numeric contributions (sum/avg) or row count */
    __int128 isum = 0;   /* exact for any i64 args at any realistic count */
    double fsum = 0.0;
    bool isfloat = false;
    int64_t err = 0;
    std::map<MVal, int64_t> mm; /* min/max: ordered value multiset */
};

/* cheap before-image of a spec's FINISHED value (capturing full SState
 * would copy the min/max map per touched group per batch) */
struct FinSnap {
    int64_t cnt = 0;
    __int128 isum = 0;
    double fsum = 0.0;
    bool isfloat = false;
    int64_t err = 0;
    bool mm_empty = true;
    MVal best; /* min or max, by code */
};

inline FinSnap snap_of(uint8_t code, const SState &s)
{
    FinSnap out;
    out.cnt = s.cnt;
    out.isum = s.isum;
    out.fsum = s.fsum;
    out.isfloat = s.isfloat;
    out.err = s.err;
    out.mm_empty = s.mm.empty();
    if (!s.mm.empty())
        out.best = code == C_MAX ? s.mm.rbegin()->first : s.mm.begin()->first;
    return out;
}

/* joint row multiset entry (kept when any min/max or fp spec exists):
 * mirrors the Python path's args-combo multiset so demotion can rebuild
 * it exactly — (key, per-spec arg value, count[, stamp, order]).
 * key_ord / mvals / order_mv are GIL-free comparable copies used by the
 * fp codes' phase-2 fingerprints and emit-time orderings. */
struct MsEntry {
    PyObject *key;                /* owned via deferred incref */
    std::vector<PyObject *> vals; /* owned; slot per spec (NULL if argless) */
    int64_t count;
    std::string key_ord;          /* 16-byte big-endian row key (fp codes) */
    std::vector<MVal> mvals;      /* per-spec comparable copy (fp codes) */
    int64_t st_t = 0, st_i = 0;   /* creation stamp: (engine time, row idx) */
    PyObject *order_obj = nullptr; /* owned: sort_by token (when has_order) */
    MVal order_mv;                /* comparable copy of order_obj */
};

struct Group {
    int64_t total = 0;       /* multiset row count of the group */
    PyObject *gvals = nullptr;   /* owned: grouping-values tuple */
    PyObject *out_key = nullptr; /* owned: output Pointer (minted lazily) */
    std::vector<SState> st;
    std::unordered_map<std::string, MsEntry> ms; /* only when has_ms */
};

/* transparent hashing lets the NativeBatch fused path probe the group
 * map with string_views into its key arena — no per-row std::string */
struct SvHash {
    using is_transparent = void;
    size_t operator()(std::string_view s) const
    {
        return std::hash<std::string_view>{}(s);
    }
    size_t operator()(const std::string &s) const
    {
        return std::hash<std::string_view>{}(s);
    }
};

struct Shard {
    std::unordered_map<std::string, Group, SvHash, std::equal_to<>> groups;
};

/* K_NONE participates only in argmin/argmax kind tracking: Python
 * compares the VALUES there ((None, key) < (5, key) raises TypeError on
 * the mixed case, while all-None groups order by key), so None is a
 * third kind that must not mix with numerics or strings. min/max skip
 * None args entirely and sorted_tuple maps None below every value, so
 * neither tracks it. */
enum SpecKind : uint8_t { K_UNSET = 0, K_NUM = 1, K_STR = 2, K_NONE = 3 };

/* per-phase wall-time accumulators: extract/emit hold the GIL, apply
 * runs GIL-free over shard threads — the share of `apply` bounds the
 * multi-core speedup available, and recording it makes thread-scaling
 * headroom auditable from a 1-core box (r4 verdict weak #5) */
struct PhaseStats {
    double extract_s = 0.0; /* GIL held */
    double apply_s = 0.0;   /* GIL released, shard-parallel */
    double emit_s = 0.0;    /* GIL held */
    int64_t batches = 0;
    int64_t rows = 0;
};


PhaseStats g_phases; /* process-wide totals (all stores) — read by the
                        bench via phase_stats()/phase_stats_reset() */

struct GroupStore; /* fwd: phase_add defined after the store type */

inline double _since(std::chrono::steady_clock::time_point t0)
{
    return std::chrono::duration<double>(std::chrono::steady_clock::now() -
                                         t0)
        .count();
}

struct GroupStore {
    int n_shards;
    bool has_ms = false;
    bool has_fp = false;    /* any multiset-valued (fp) spec */
    bool has_order = false; /* groupby sort_by: an order column rides in */
    PhaseStats phases;
    std::vector<uint8_t> codes;
    /* per ordering spec: the value kind seen so far. Python raises
     * TypeError on numeric<->string comparison (min/max/argmin/argmax/
     * sorted_tuple args, and the sort_by column); rather than diverge (or
     * crash after demotion), a batch that would mix kinds anywhere in the
     * store Falls Back in phase 1 — store-level granularity is coarser
     * than Python's per-group check, which only means we fall back early,
     * never that we answer differently. */
    std::vector<uint8_t> kinds;
    uint8_t order_kind = K_UNSET; /* kind of the sort_by column */
    std::vector<Shard> shards;
};

inline void phase_add(GroupStore *s, double PhaseStats::*field,
                      std::chrono::steady_clock::time_point t0)
{
    const double dt = _since(t0);
    s->phases.*field += dt;
    g_phases.*field += dt;
}

inline void phase_count(GroupStore *s, int64_t n)
{
    s->phases.batches += 1;
    g_phases.batches += 1;
    s->phases.rows += n;
    g_phases.rows += n;
}

/* ---- flight-recorder ring (internals/flight.py) ----------------------
 * Nanosecond batch timers from the GIL-free regions: each event is a
 * fixed-size record written into a preallocated per-thread ring buffer
 * with NO Python C-API calls (scripts/lint_gil.py clean) and no locks.
 * Slot 0 belongs to whichever thread owns the region entry (the
 * interpreter thread for serial applies, procgroup receiver threads for
 * nb_decode); slots 1..N belong to shard workers (worker index + 1).
 * The Python flight recorder enables the ring via trace_ring_enable()
 * and drains it between engine steps via trace_ring_drain(); disabled
 * (the default), the hot paths pay one relaxed atomic load. */
enum TraceTag : uint16_t {
    T_GB_APPLY = 1,   /* group-by apply (tuple + nb) */
    T_JOIN_APPLY = 2, /* delta-join apply (tuple + nb) */
    T_SHARD_PART = 3, /* columnar exchange partition */
    T_NB_ENCODE = 4,  /* wire encode */
    T_NB_DECODE = 5,  /* wire decode (receiver threads) */
    T_NB_CONCAT = 6,  /* arena-rebased exchange merge */
    T_ARROW_EXPORT = 7, /* columnar egress: Arrow record-batch export */
};

struct TraceEv {
    uint64_t t0;
    uint64_t t1;
    int64_t rows;
    uint16_t tag;
    uint16_t thr;
};

#define PW_TRACE_RINGS 65 /* slot 0 = region-entry thread, 1..64 workers */

struct TraceRing {
    std::vector<TraceEv> ev; /* preallocated at enable time */
    std::atomic<uint64_t> w{0};
    uint64_t drained = 0; /* reader-only watermark (GIL-held drains) */
};

std::atomic<int> g_trace_on{0};
TraceRing g_trace_rings[PW_TRACE_RINGS];

inline bool trace_on()
{
    return g_trace_on.load(std::memory_order_relaxed) != 0;
}

inline uint64_t trace_now_ns()
{
    /* steady_clock is CLOCK_MONOTONIC on this toolchain — the same
     * timebase as Python's time.perf_counter_ns(), so ring events line
     * up with the engine-side spans without translation */
    return (uint64_t)std::chrono::duration_cast<std::chrono::nanoseconds>(
               std::chrono::steady_clock::now().time_since_epoch())
        .count();
}

/* GIL-free safe: chrono + atomics only. `thr` is the shard worker index
 * (-1 = the thread that owns the region entry). Writers share a ring
 * only through the atomic write index, so records never interleave; a
 * wrap overwrites the oldest event (ring size = the capacity passed to
 * trace_ring_enable). Lifetime contract: disable only clears the armed
 * flag — ring storage is NEVER freed while the process may still have
 * writers in flight (procgroup receiver threads decode frames
 * asynchronously to engine steps), so a note racing a disable lands in
 * still-allocated memory and is simply never drained. The only
 * remaining unsynchronized overlap is a reader scanning a slot that a
 * writer wraps onto mid-drain, which needs a full ring of writes
 * within one drain loop; it corrupts at most that one diagnostic
 * record (durations are clamped >= 0 downstream). */
inline void trace_note(uint16_t tag, int thr, uint64_t t0, uint64_t t1,
                       int64_t rows)
{
    if (!g_trace_on.load(std::memory_order_acquire))
        return;
    TraceRing &r = g_trace_rings[(size_t)((thr + 1) % PW_TRACE_RINGS)];
    const size_t cap = r.ev.size();
    if (cap == 0)
        return;
    const uint64_t i = r.w.fetch_add(1, std::memory_order_relaxed);
    TraceEv &e = r.ev[(size_t)(i % cap)];
    e.t0 = t0;
    e.t1 = t1;
    e.rows = rows;
    e.tag = tag;
    e.thr = (uint16_t)(thr + 1);
}

void release_ms(Group &g)
{
    for (auto &kv : g.ms) {
        Py_XDECREF(kv.second.key);
        Py_XDECREF(kv.second.order_obj);
        for (PyObject *v : kv.second.vals)
            Py_XDECREF(v);
    }
    g.ms.clear();
}

void store_destructor(PyObject *capsule)
{
    auto *s = static_cast<GroupStore *>(
        PyCapsule_GetPointer(capsule, "pwexec.GroupStore"));
    if (s == nullptr)
        return;
    for (auto &sh : s->shards)
        for (auto &kv : sh.groups) {
            Py_XDECREF(kv.second.gvals);
            Py_XDECREF(kv.second.out_key);
            release_ms(kv.second);
        }
    delete s;
}

GroupStore *get_store(PyObject *capsule)
{
    return static_cast<GroupStore *>(
        PyCapsule_GetPointer(capsule, "pwexec.GroupStore"));
}

/* ---- injective serialization of grouping tuples ----------------------
 * Internal to the store (output keys come from the Python key_fn), so the
 * format only needs injectivity, not parity with api._value_to_bytes. */

bool ser_value(std::string &out, PyObject *v)
{
    if (v == Py_None) {
        out.push_back('\x01');
        return true;
    }
    /* numeric normalization: Python dict keys make True == 1 == 1.0 the
     * same group, so bools and integral floats serialize as ints */
    if (PyBool_Check(v)) {
        int64_t i = v == Py_True ? 1 : 0;
        out.push_back('I');
        out.append(reinterpret_cast<char *>(&i), 8);
        return true;
    }
    if (PyFloat_Check(v)) {
        double d = PyFloat_AS_DOUBLE(v);
        if (d == (double)(int64_t)d && d >= -9.2e18 && d <= 9.2e18) {
            int64_t i = (int64_t)d;
            out.push_back('I');
            out.append(reinterpret_cast<char *>(&i), 8);
            return true;
        }
        out.push_back('F');
        out.append(reinterpret_cast<char *>(&d), 8);
        return true;
    }
    if (PyLong_Check(v)) {
        int overflow = 0;
        int64_t i = PyLong_AsLongLongAndOverflow(v, &overflow);
        if (!overflow) {
            out.push_back('I');
            out.append(reinterpret_cast<char *>(&i), 8);
            return true;
        }
        /* 128-bit Pointers and big ints: hex digest via Python */
        PyObject *hex = PyNumber_ToBase(v, 16);
        if (hex == nullptr)
            return false;
        Py_ssize_t n;
        const char *s = PyUnicode_AsUTF8AndSize(hex, &n);
        if (s == nullptr) {
            Py_DECREF(hex);
            return false;
        }
        uint32_t len = (uint32_t)n;
        out.push_back('H');
        out.append(reinterpret_cast<char *>(&len), 4);
        out.append(s, n);
        Py_DECREF(hex);
        return true;
    }
    if (PyUnicode_Check(v)) {
        Py_ssize_t n;
        const char *s = PyUnicode_AsUTF8AndSize(v, &n);
        if (s == nullptr)
            return false;
        uint32_t len = (uint32_t)n;
        out.push_back('S');
        out.append(reinterpret_cast<char *>(&len), 4);
        out.append(s, n);
        return true;
    }
    if (PyBytes_Check(v)) {
        uint32_t len = (uint32_t)PyBytes_GET_SIZE(v);
        out.push_back('Y');
        out.append(reinterpret_cast<char *>(&len), 4);
        out.append(PyBytes_AS_STRING(v), len);
        return true;
    }
    return false; /* tuples/arrays/Json etc.: Python path */
}

bool ser_gvals(std::string &out, PyObject *gvals)
{
    if (!PyTuple_Check(gvals))
        return false;
    Py_ssize_t n = PyTuple_GET_SIZE(gvals);
    uint32_t un = (uint32_t)n;
    out.append(reinterpret_cast<char *>(&un), 4);
    for (Py_ssize_t i = 0; i < n; i++)
        if (!ser_value(out, PyTuple_GET_ITEM(gvals, i)))
            return false;
    return true;
}

/* blake2b-128: shared single implementation (native/pw_blake2b.h) —
 * the GIL-free key mint for the fused join/parse paths, byte-identical
 * to hashlib.blake2b(digest_size=16) and to fastpath.c's ref_scalar. */

/* ---- native key minting (ref_scalar parity, GIL-free) ----------------
 * api._value_to_bytes layout for the values the fused paths mint from:
 *   None    -> "\x00"
 *   Pointer -> "P" + 16-byte LE
 * wrapped in the length-prefixed tuple concat of api._concat_lp. The
 * fused join emits ref_scalar(lk, rk) pair keys without a Python frame:
 * serialize the two sides, blake2b-128, read little-endian. */

inline void pw_put_u32le(std::string &out, uint32_t v)
{
    char b[4] = {(char)(v & 0xff), (char)((v >> 8) & 0xff),
                 (char)((v >> 16) & 0xff), (char)((v >> 24) & 0xff)};
    out.append(b, 4);
}

inline unsigned __int128 mint_pair_key128(bool l_some, unsigned __int128 lk,
                                          bool r_some, unsigned __int128 rk)
{
    unsigned char buf[4 + 4 + 17 + 4 + 17];
    size_t off = 0;
    auto put_u32 = [&](uint32_t v) {
        buf[off++] = (unsigned char)(v & 0xff);
        buf[off++] = (unsigned char)((v >> 8) & 0xff);
        buf[off++] = (unsigned char)((v >> 16) & 0xff);
        buf[off++] = (unsigned char)((v >> 24) & 0xff);
    };
    auto put_side = [&](bool some, unsigned __int128 k) {
        if (!some) {
            put_u32(1);
            buf[off++] = 0; /* None */
            return;
        }
        put_u32(17);
        buf[off++] = 'P';
        for (int i = 0; i < 16; i++)
            buf[off++] = (unsigned char)((k >> (8 * i)) & 0xff);
    };
    put_u32(2);
    put_side(l_some, lk);
    put_side(r_some, rk);
    unsigned char dg[16];
    pw_b2b_digest16(dg, buf, off);
    unsigned __int128 out;
    memcpy(&out, dg, 16);
    return out;
}

/* ser_value parity for a 128-bit row key: values below 2^63 take the
 * int64 'I' branch; larger ones match the 'H' + PyNumber_ToBase(v, 16)
 * branch ("0x" + minimal lowercase hex) byte for byte, so entries stored
 * by the nb path land in exactly the map slots the tuple path probes. */
inline void ser_key128(std::string &out, unsigned __int128 k)
{
    if (k < ((unsigned __int128)1 << 63)) {
        int64_t v = (int64_t)k;
        out.push_back('I');
        out.append(reinterpret_cast<const char *>(&v), 8);
        return;
    }
    char hex[36];
    uint64_t hi = (uint64_t)(k >> 64), lo = (uint64_t)k;
    int n;
    if (hi != 0)
        n = snprintf(hex, sizeof(hex), "0x%llx%016llx",
                     (unsigned long long)hi, (unsigned long long)lo);
    else
        n = snprintf(hex, sizeof(hex), "0x%llx", (unsigned long long)lo);
    out.push_back('H');
    pw_put_u32le(out, (uint32_t)n);
    out.append(hex, (size_t)n);
}

/* ---- packed row cells (faithful columnar storage) --------------------
 * The fused join keeps nb-fed store entries as C-owned packed cells
 * instead of per-row Python tuples: tag byte + payload per cell, the
 * same tag set the NativeBatch carries (so bool/int and 5.0/5 identity
 * survives round-trips, unlike the normalized ser_value form). */

enum NbTag : uint8_t {
    NB_NONE = 0,
    NB_INT = 1,
    NB_FLT = 2,
    NB_STR = 3,
    NB_BOOL = 4,
};

/* one packed cell -> new Python value (GIL); advances p */
inline PyObject *packed_cell_to_py(const char *&p)
{
    uint8_t tag = (uint8_t)*p++;
    switch (tag) {
    case NB_NONE:
        Py_RETURN_NONE;
    case NB_BOOL: {
        int64_t w;
        memcpy(&w, p, 8);
        p += 8;
        if (w)
            Py_RETURN_TRUE;
        Py_RETURN_FALSE;
    }
    case NB_INT: {
        int64_t w;
        memcpy(&w, p, 8);
        p += 8;
        return PyLong_FromLongLong((long long)w);
    }
    case NB_FLT: {
        double d;
        memcpy(&d, p, 8);
        p += 8;
        return PyFloat_FromDouble(d);
    }
    default: { /* NB_STR */
        uint32_t len;
        memcpy(&len, p, 4);
        p += 4;
        const char *s = p;
        p += len;
        return PyUnicode_FromStringAndSize(s, (Py_ssize_t)len);
    }
    }
}

/* advance p over one packed cell without materializing it */
inline void packed_skip_cell(const char *&p)
{
    uint8_t tag = (uint8_t)*p++;
    switch (tag) {
    case NB_NONE:
        return;
    case NB_STR: {
        uint32_t len;
        memcpy(&len, p, 4);
        p += 4 + len;
        return;
    }
    default:
        p += 8;
        return;
    }
}

/* packed cells -> new row tuple (GIL) */
inline PyObject *packed_row_to_py(const std::string &cells, int width)
{
    PyObject *row = PyTuple_New(width);
    if (row == nullptr)
        return nullptr;
    const char *p = cells.data();
    for (int j = 0; j < width; j++) {
        PyObject *v = packed_cell_to_py(p);
        if (v == nullptr) {
            Py_DECREF(row);
            return nullptr;
        }
        PyTuple_SET_ITEM(row, j, v);
    }
    return row;
}

inline bool nb_int128_of(PyObject *v, unsigned __int128 *out)
{
    if (!PyLong_Check(v))
        return false;
    unsigned char buf[16];
#if PY_VERSION_HEX >= 0x030D0000
    if (_PyLong_AsByteArray((PyLongObject *)v, buf, 16, 1, 0, 0) != 0) {
#else
    if (_PyLong_AsByteArray((PyLongObject *)v, buf, 16, 1, 0) != 0) {
#endif
        PyErr_Clear();
        return false;
    }
    memcpy(out, buf, 16);
    return true;
}

/* materialize a 128-bit key into a Pointer (GIL) */
inline PyObject *pointer_from_u128(PyObject *ptr_type, unsigned __int128 k)
{
    unsigned char buf[16];
    memcpy(buf, &k, 16);
    PyObject *raw = _PyLong_FromByteArray(buf, 16, 1, 0);
    if (raw == nullptr || ptr_type == nullptr || ptr_type == Py_None)
        return raw;
    PyObject *key = PyObject_CallOneArg(ptr_type, raw);
    Py_DECREF(raw);
    return key;
}

/* ---- reducer math ----------------------------------------------------- */

inline void apply_spec(uint8_t code, SState &s, const Val &v, int64_t diff)
{
    switch (code) {
    case C_COUNT:
        s.cnt += diff;
        break;
    case C_SUM:
    case C_AVG:
        switch (v.tag) {
        case V_NONE:
            break;
        case V_ERR:
            s.err += diff;
            break;
        case V_INT:
            s.isum += (__int128)v.i * (__int128)diff;
            s.cnt += diff;
            break;
        case V_FLT:
            s.fsum += v.f * (double)diff;
            s.isfloat = true;
            s.cnt += diff;
            break;
        default:
            break;
        }
        break;
    case C_MIN:
    case C_MAX:
        if (v.tag == V_NONE)
            break;
        if (v.tag == V_ERR) {
            s.err += diff;
            break;
        }
        {
            auto it = s.mm.emplace(mval_of(v), 0).first;
            it->second += diff;
            if (it->second == 0)
                s.mm.erase(it);
        }
        break;
    }
}

/* exact Python int from __int128 (rare >i64 path goes via decimal text) */
PyObject *pylong_from_i128(__int128 v)
{
    if (v >= INT64_MIN && v <= INT64_MAX)
        return PyLong_FromLongLong((int64_t)v);
    char buf[48];
    char *p = buf + sizeof(buf);
    *--p = '\0';
    bool neg = v < 0;
    unsigned __int128 u = neg ? (unsigned __int128)(-v) : (unsigned __int128)v;
    do {
        *--p = (char)('0' + (int)(u % 10));
        u /= 10;
    } while (u != 0);
    if (neg)
        *--p = '-';
    return PyLong_FromString(p, nullptr, 10);
}

/* finish: build the Python value for one spec snapshot (GIL held).
 * FinSnap is the uniform finished-image of a spec — snap_of(current
 * state) produces the after-image, Affected carries the before-image. */
PyObject *finish_snap(uint8_t code, const FinSnap &s, PyObject *error_obj)
{
    switch (code) {
    case C_COUNT:
        return PyLong_FromLongLong(s.cnt);
    case C_SUM:
        if (s.err > 0) {
            Py_INCREF(error_obj);
            return error_obj;
        }
        if (s.cnt <= 0)
            Py_RETURN_NONE;
        if (s.isfloat)
            return PyFloat_FromDouble(s.fsum + (double)s.isum);
        return pylong_from_i128(s.isum);
    case C_AVG:
        if (s.err > 0) {
            Py_INCREF(error_obj);
            return error_obj;
        }
        if (s.cnt <= 0)
            Py_RETURN_NONE;
        return PyFloat_FromDouble((s.fsum + (double)s.isum) / (double)s.cnt);
    case C_MIN:
    case C_MAX:
        if (s.err > 0) {
            Py_INCREF(error_obj);
            return error_obj;
        }
        if (s.mm_empty)
            Py_RETURN_NONE;
        if (s.best.tag == V_INT)
            return PyLong_FromLongLong(s.best.i);
        if (s.best.tag == V_FLT)
            return PyFloat_FromDouble(s.best.f);
        return PyUnicode_FromStringAndSize(
            s.best.s.data(), (Py_ssize_t)s.best.s.size());
    }
    Py_RETURN_NONE;
}

/* semantic equality of FINISHED values (not raw state): a batch that moves
 * the state without moving the output (e.g. a None/0-contributing row)
 * must emit nothing — the Python path's consolidate() would cancel the
 * retract/insert pair and downstream subscribers never see it */
inline bool finish_equal(uint8_t code, const FinSnap &a, const FinSnap &b)
{
    switch (code) {
    case C_COUNT:
        return a.cnt == b.cnt;
    case C_SUM: {
        bool aerr = a.err > 0, berr = b.err > 0;
        if (aerr || berr)
            return aerr && berr;
        bool anone = a.cnt <= 0, bnone = b.cnt <= 0;
        if (anone || bnone)
            return anone && bnone;
        if (!a.isfloat && !b.isfloat)
            return a.isum == b.isum;
        /* numeric equality across int/float, matching Python 5 == 5.0 */
        return a.fsum + (double)a.isum == b.fsum + (double)b.isum;
    }
    case C_AVG: {
        bool aerr = a.err > 0, berr = b.err > 0;
        if (aerr || berr)
            return aerr && berr;
        bool anone = a.cnt <= 0, bnone = b.cnt <= 0;
        if (anone || bnone)
            return anone && bnone;
        return (a.fsum + (double)a.isum) / (double)a.cnt ==
               (b.fsum + (double)b.isum) / (double)b.cnt;
    }
    case C_MIN:
    case C_MAX: {
        bool aerr = a.err > 0, berr = b.err > 0;
        if (aerr || berr)
            return aerr && berr;
        if (a.mm_empty || b.mm_empty)
            return a.mm_empty && b.mm_empty;
        return a.best.num_equal(b.best);
    }
    }
    return false;
}

/* ---- fp codes: fingerprints (GIL-free) + emit values (GIL) ------------
 *
 * The finished value of a multiset-valued reducer is a function of the
 * group's joint row multiset. Change detection must coincide with Python
 * tuple equality of the OUTPUT (the condition under which the Python
 * path's consolidate() cancels the retract/insert pair), so phase 2
 * computes a fingerprint of the finished value — not of the multiset —
 * from the entries' GIL-free MVal copies, and phase 3 only builds Python
 * values for groups whose fingerprint moved. */

/* ordering helpers over borrowed MsEntry pointers */
inline bool tuple_less(const MsEntry *a, const MsEntry *b, bool has_order)
{
    if (has_order) {
        if (a->order_mv < b->order_mv)
            return true;
        if (b->order_mv < a->order_mv)
            return false;
    }
    return a->key_ord < b->key_ord;
}

inline bool stamp_less(const MsEntry *a, const MsEntry *b)
{
    if (a->st_t != b->st_t)
        return a->st_t < b->st_t;
    if (a->st_i != b->st_i)
        return a->st_i < b->st_i;
    return a->key_ord < b->key_ord;
}

/* choose the entry a single-valued fp code resolves to; nullptr when the
 * multiset is empty. `entries` may be in any order. */
const MsEntry *fp_choose(uint8_t code, bool has_order,
                         const std::vector<const MsEntry *> &entries,
                         size_t sidx)
{
    const MsEntry *best = nullptr;
    for (const MsEntry *e : entries) {
        if (best == nullptr) {
            best = e;
            continue;
        }
        switch (code) {
        case C_ANY: /* min by (order token, key) — order==key w/o sort_by */
            if (tuple_less(e, best, has_order))
                best = e;
            break;
        case C_ARGMIN: /* min by (value, key) */
            if (e->mvals[sidx] < best->mvals[sidx] ||
                (!(best->mvals[sidx] < e->mvals[sidx]) &&
                 e->key_ord < best->key_ord))
                best = e;
            break;
        case C_ARGMAX: /* max by value, ties -> SMALLEST key */
            if (best->mvals[sidx] < e->mvals[sidx] ||
                (!(e->mvals[sidx] < best->mvals[sidx]) &&
                 e->key_ord < best->key_ord))
                best = e;
            break;
        case C_EARLIEST:
            if (stamp_less(e, best))
                best = e;
            break;
        case C_LATEST:
            if (stamp_less(best, e))
                best = e;
            break;
        }
    }
    return best;
}

/* fingerprint of one spec's finished value over `entries` (borrowed,
 * any order; count<=0 entries exist but contribute nothing to tuple
 * expansions, exactly like Python's [v] * negative_count) */
void fp_fingerprint(std::string &out, uint8_t code, bool has_order,
                    const std::vector<const MsEntry *> &entries, size_t sidx,
                    std::vector<const MsEntry *> &scratch)
{
    out.clear();
    switch (code) {
    case C_TUPLE:
    case C_TUPLE_SN:
    case C_STUPLE:
    case C_STUPLE_SN: {
        const bool skip_none = code == C_TUPLE_SN || code == C_STUPLE_SN;
        const bool by_value = code == C_STUPLE || code == C_STUPLE_SN;
        scratch.clear();
        for (const MsEntry *e : entries)
            if (e->count > 0 &&
                !(skip_none && e->mvals[sidx].tag == V_NONE))
                scratch.push_back(e);
        if (by_value)
            std::sort(scratch.begin(), scratch.end(),
                      [&](const MsEntry *a, const MsEntry *b) {
                          if (a->mvals[sidx] < b->mvals[sidx])
                              return true;
                          if (b->mvals[sidx] < a->mvals[sidx])
                              return false;
                          return a->key_ord < b->key_ord;
                      });
        else
            std::sort(scratch.begin(), scratch.end(),
                      [&](const MsEntry *a, const MsEntry *b) {
                          return tuple_less(a, b, has_order);
                      });
        /* runs of numerically-equal adjacent values merge (5 then 5.0
         * yields the same Python tuple under == as 5 then 5) */
        int64_t run_count = 0;
        std::string cur, prev;
        for (const MsEntry *e : scratch) {
            cur.clear();
            mval_ser(cur, e->mvals[sidx]);
            if (run_count > 0 && cur == prev) {
                run_count += e->count;
            } else {
                if (run_count > 0) {
                    out.append(prev);
                    out.append(reinterpret_cast<char *>(&run_count), 8);
                }
                prev = cur;
                run_count = e->count;
            }
        }
        if (run_count > 0) {
            out.append(prev);
            out.append(reinterpret_cast<char *>(&run_count), 8);
        }
        return;
    }
    case C_UNIQUE: {
        /* distinct under Python value equality (5 == 5.0 == True fold
         * via mval_ser normalization); >1 class -> ERROR */
        std::string first;
        bool have = false, multi = false;
        for (const MsEntry *e : entries) {
            std::string c;
            mval_ser(c, e->mvals[sidx]);
            if (!have) {
                first = c;
                have = true;
            } else if (c != first) {
                multi = true;
                break;
            }
        }
        out.push_back(multi ? 'E' : 'U');
        if (!multi && have)
            out.append(first);
        return;
    }
    case C_ANY:
    case C_EARLIEST:
    case C_LATEST: {
        const MsEntry *b = fp_choose(code, has_order, entries, sidx);
        if (b != nullptr)
            mval_ser(out, b->mvals[sidx]);
        return;
    }
    case C_ARGMIN:
    case C_ARGMAX: {
        const MsEntry *b = fp_choose(code, has_order, entries, sidx);
        if (b != nullptr)
            out.append(b->key_ord);
        return;
    }
    }
}

/* build the Python finished value for one fp spec (GIL held). Entries
 * are borrowed; their PyObjects are alive (phase-3 decrefs run last). */
PyObject *fp_value(uint8_t code, bool has_order,
                   const std::vector<const MsEntry *> &entries, size_t sidx,
                   PyObject *error_obj)
{
    switch (code) {
    case C_TUPLE:
    case C_TUPLE_SN:
    case C_STUPLE:
    case C_STUPLE_SN: {
        const bool skip_none = code == C_TUPLE_SN || code == C_STUPLE_SN;
        const bool by_value = code == C_STUPLE || code == C_STUPLE_SN;
        std::vector<const MsEntry *> live;
        for (const MsEntry *e : entries)
            if (e->count > 0 &&
                !(skip_none && e->mvals[sidx].tag == V_NONE))
                live.push_back(e);
        if (by_value)
            std::sort(live.begin(), live.end(),
                      [&](const MsEntry *a, const MsEntry *b) {
                          if (a->mvals[sidx] < b->mvals[sidx])
                              return true;
                          if (b->mvals[sidx] < a->mvals[sidx])
                              return false;
                          return a->key_ord < b->key_ord;
                      });
        else
            std::sort(live.begin(), live.end(),
                      [&](const MsEntry *a, const MsEntry *b) {
                          return tuple_less(a, b, has_order);
                      });
        int64_t total = 0;
        for (const MsEntry *e : live)
            total += e->count;
        PyObject *tup = PyTuple_New((Py_ssize_t)total);
        if (tup == nullptr)
            return nullptr;
        Py_ssize_t at = 0;
        for (const MsEntry *e : live) {
            PyObject *v = e->vals[sidx] ? e->vals[sidx] : Py_None;
            for (int64_t c = 0; c < e->count; c++) {
                Py_INCREF(v);
                PyTuple_SET_ITEM(tup, at++, v);
            }
        }
        return tup;
    }
    case C_UNIQUE: {
        std::string first, cur;
        const MsEntry *rep = nullptr;
        for (const MsEntry *e : entries) {
            cur.clear();
            mval_ser(cur, e->mvals[sidx]);
            if (rep == nullptr) {
                first = cur;
                rep = e;
            } else if (cur != first) {
                Py_INCREF(error_obj);
                return error_obj;
            } else if (e->key_ord < rep->key_ord) {
                rep = e; /* deterministic representative */
            }
        }
        if (rep == nullptr)
            Py_RETURN_NONE;
        PyObject *v = rep->vals[sidx] ? rep->vals[sidx] : Py_None;
        Py_INCREF(v);
        return v;
    }
    case C_ANY:
    case C_EARLIEST:
    case C_LATEST: {
        const MsEntry *b = fp_choose(code, has_order, entries, sidx);
        if (b == nullptr)
            Py_RETURN_NONE;
        PyObject *v = b->vals[sidx] ? b->vals[sidx] : Py_None;
        Py_INCREF(v);
        return v;
    }
    case C_ARGMIN:
    case C_ARGMAX: {
        const MsEntry *b = fp_choose(code, has_order, entries, sidx);
        if (b == nullptr)
            Py_RETURN_NONE;
        Py_INCREF(b->key);
        return b->key;
    }
    }
    Py_RETURN_NONE;
}

/* ---- store_new(n_shards, codes_tuple[, has_order]) -------------------- */

PyObject *store_new(PyObject *, PyObject *args)
{
    int n_shards;
    PyObject *codes;
    int has_order = 0;
    if (!PyArg_ParseTuple(args, "iO|i", &n_shards, &codes, &has_order))
        return nullptr;
    if (n_shards < 1)
        n_shards = 1;
    auto *s = new GroupStore();
    s->n_shards = n_shards;
    s->has_order = has_order != 0;
    s->shards.resize(n_shards);
    static const struct {
        const char *name;
        uint8_t code;
    } kCodes[] = {
        {"count", C_COUNT},       {"sum", C_SUM},
        {"avg", C_AVG},           {"min", C_MIN},
        {"max", C_MAX},           {"argmin", C_ARGMIN},
        {"argmax", C_ARGMAX},     {"unique", C_UNIQUE},
        {"any", C_ANY},           {"tuple", C_TUPLE},
        {"tuple_sn", C_TUPLE_SN}, {"sorted_tuple", C_STUPLE},
        {"sorted_tuple_sn", C_STUPLE_SN},
        {"earliest", C_EARLIEST}, {"latest", C_LATEST},
    };
    Py_ssize_t nc = PySequence_Size(codes);
    for (Py_ssize_t i = 0; i < nc; i++) {
        PyObject *c = PySequence_GetItem(codes, i);
        const char *cs = PyUnicode_AsUTF8(c);
        int found = -1;
        if (cs != nullptr)
            for (size_t j = 0; j < sizeof(kCodes) / sizeof(kCodes[0]); j++)
                if (strcmp(cs, kCodes[j].name) == 0) {
                    found = (int)j;
                    break;
                }
        if (found < 0) {
            Py_XDECREF(c);
            delete s;
            PyErr_SetString(PyExc_ValueError, "unknown reducer code");
            return nullptr;
        }
        uint8_t code = kCodes[found].code;
        if (code == C_MIN || code == C_MAX || is_fp(code))
            s->has_ms = true;
        if (is_fp(code))
            s->has_fp = true;
        s->codes.push_back(code);
        s->kinds.push_back(K_UNSET);
        Py_DECREF(c);
    }
    return PyCapsule_New(s, "pwexec.GroupStore", store_destructor);
}

PyObject *store_len(PyObject *, PyObject *arg)
{
    GroupStore *s = get_store(arg);
    if (s == nullptr)
        return nullptr;
    int64_t n = 0;
    for (auto &sh : s->shards)
        n += (int64_t)sh.groups.size();
    return PyLong_FromLongLong(n);
}

/* -- store_nbytes(store) ------------------------------------------------
 * GIL-free byte probe for the memory accountant (internals/memory.py;
 * ISSUE 19): container capacities + amortized node overhead + a flat
 * per-owned-object charge. An ESTIMATE, not malloc truth — the
 * accountant steps watermarks, it does not bill. The walk only reads
 * pointers and container shapes (NULL-compares, no C-API, no
 * refcounts), so it runs released like the shard apply phase and the
 * lint_gil.py sweep covers the region like every other. */

static const int64_t kNodeEst = 48; /* map node + bucket slot, amortized */
static const int64_t kObjEst = 64;  /* flat charge per owned heap object */

PyObject *store_nbytes(PyObject *, PyObject *arg)
{
    GroupStore *s = get_store(arg);
    if (s == nullptr)
        return nullptr;
    int64_t n = 0;
    Py_BEGIN_ALLOW_THREADS
    n += (int64_t)sizeof(GroupStore);
    n += (int64_t)(s->codes.capacity() + s->kinds.capacity());
    for (auto &sh : s->shards) {
        n += (int64_t)sizeof(Shard);
        n += (int64_t)sh.groups.bucket_count() * (int64_t)sizeof(void *);
        for (auto &kv : sh.groups) {
            const Group &g = kv.second;
            n += kNodeEst + (int64_t)kv.first.capacity();
            n += (int64_t)sizeof(Group);
            if (g.gvals != nullptr)
                n += kObjEst;
            if (g.out_key != nullptr)
                n += kObjEst;
            n += (int64_t)(g.st.capacity() * sizeof(SState));
            for (const auto &st : g.st)
                n += (int64_t)st.mm.size() *
                     (kNodeEst + (int64_t)sizeof(MVal) +
                      (int64_t)sizeof(int64_t));
            n += (int64_t)g.ms.bucket_count() * (int64_t)sizeof(void *);
            for (const auto &me : g.ms) {
                const MsEntry &e = me.second;
                n += kNodeEst + (int64_t)me.first.capacity();
                n += (int64_t)sizeof(MsEntry);
                n += (int64_t)e.key_ord.capacity();
                n += (int64_t)(e.vals.capacity() * sizeof(void *));
                n += (int64_t)(e.mvals.capacity() * sizeof(MVal));
                if (e.key != nullptr)
                    n += kObjEst;
                for (auto *v : e.vals)
                    if (v != nullptr)
                        n += kObjEst;
                if (e.order_obj != nullptr)
                    n += kObjEst;
            }
        }
    }
    Py_END_ALLOW_THREADS
    return PyLong_FromLongLong(n);
}

/* -- process_batch(store, gvals_list, keys, valcols, diffs, key_fn,
 *                  error[, time, ordercol]) ----------------------------- */

struct RowExtract {
    uint32_t shard;
    std::string key;
    std::string ms_key;    /* has_ms: ser(row key) + ser(arg vals) */
    PyObject *row_key;     /* borrowed */
    int64_t diff;
    std::vector<Val> vals; /* one per spec */
    std::string key_ord;   /* fp codes: 16-byte big-endian row key */
    PyObject *order_obj = nullptr; /* borrowed: sort_by token */
    MVal order_mv;
    bool skip = false;     /* ERROR in grouping values: row skipped */
};

struct Affected {
    Group *g;
    std::string key;      /* for erase */
    int32_t first_row;    /* gvals source for groups created this batch */
    int64_t before_total;
    std::vector<FinSnap> before;
    bool created;
    /* fp codes: borrowed snapshot of the pre-batch multiset (objects
     * stay alive through emit — phase-3 decrefs run last) + per-spec
     * finished-value fingerprints computed GIL-free in phase 2 */
    std::vector<MsEntry> ms_before;
    std::vector<std::string> fp_before, fp_after;
};

PyObject *process_batch(PyObject *, PyObject *args)
{
    PyObject *capsule, *gvals_list, *keys_list, *valcols, *diffs, *key_fn,
        *error_obj;
    long long batch_time = 0;
    PyObject *ordercol = Py_None;
    PyObject *skipped_out = Py_None;
    if (!PyArg_ParseTuple(args, "OOOOOOO|LOO", &capsule, &gvals_list,
                          &keys_list, &valcols, &diffs, &key_fn, &error_obj,
                          &batch_time, &ordercol, &skipped_out))
        return nullptr;
    GroupStore *store = get_store(capsule);
    if (store == nullptr)
        return nullptr;
    const int W = store->n_shards;
    const size_t n_specs = store->codes.size();
    const bool has_ms = store->has_ms;
    const bool has_fp = store->has_fp;
    const bool has_order = store->has_order;
    if (has_order &&
        (!PyList_Check(ordercol) ||
         PyList_Size(ordercol) != PyList_Size(gvals_list))) {
        PyErr_SetString(PyExc_TypeError,
                        "process_batch: order column length mismatch");
        return nullptr;
    }

    Py_ssize_t n = PyList_Size(gvals_list);
    if (n < 0)
        return nullptr;
    /* Validate list shapes up front: phase 1 indexes keys/diffs/valcols
     * with unchecked PyList_GET_ITEM, so a drifting Python caller must be
     * rejected here rather than read out of bounds in C. */
    if (!PyList_Check(keys_list) || PyList_Size(keys_list) != n ||
        !PyList_Check(diffs) || PyList_Size(diffs) != n ||
        !PyTuple_Check(valcols) ||
        PyTuple_Size(valcols) != (Py_ssize_t)n_specs) {
        PyErr_SetString(PyExc_TypeError,
                        "process_batch: keys/diffs must be lists of the "
                        "gvals length and valcols a tuple of one column "
                        "per spec");
        return nullptr;
    }
    for (size_t sidx = 0; sidx < n_specs; sidx++) {
        PyObject *col = PyTuple_GET_ITEM(valcols, (Py_ssize_t)sidx);
        if (col != Py_None &&
            (!PyList_Check(col) || PyList_Size(col) != n)) {
            PyErr_SetString(PyExc_TypeError,
                            "process_batch: value column length mismatch");
            return nullptr;
        }
    }

    /* phase 1: extract (GIL held) — no state is mutated, so Fallback here
     * leaves the store untouched and the Python path can replay the batch */
    auto _t0 = std::chrono::steady_clock::now();
    std::vector<RowExtract> rows(n);
    std::vector<uint8_t> kinds = store->kinds; /* committed after phase 1 */
    uint8_t order_kind = store->order_kind;
    SvHash hasher; /* one hasher everywhere: shard placement must agree across the nb and tuple paths */
    for (Py_ssize_t i = 0; i < n; i++) {
        RowExtract &r = rows[i];
        PyObject *gv = PyList_GET_ITEM(gvals_list, i);
        if (!ser_gvals(r.key, gv)) {
            PyErr_Clear();
            /* ERROR in a grouping value: the row joins no group — it is
             * skipped and reported for the error log (reference:
             * test_errors.py "Error value encountered in grouping
             * columns"). Any other serialization failure (exotic
             * values, surrogate-escaped strings) routes to the Python
             * path, which handles those values. */
            bool has_err = false;
            if (error_obj != nullptr && PyTuple_Check(gv))
                for (Py_ssize_t j = 0; j < PyTuple_GET_SIZE(gv); j++)
                    if (PyTuple_GET_ITEM(gv, j) == error_obj) {
                        has_err = true;
                        break;
                    }
            if (has_err) {
                r.skip = true;
                if (skipped_out != Py_None &&
                    PyList_Append(skipped_out,
                                  PyList_GET_ITEM(keys_list, i)) < 0)
                    return nullptr;
                continue;
            }
            PyErr_SetString(FallbackError, "unsupported grouping value");
            return nullptr;
        }
        r.shard = (uint32_t)(hasher(r.key) % (size_t)W);
        r.row_key = PyList_GET_ITEM(keys_list, i);
        PyObject *d = PyList_GET_ITEM(diffs, i);
        int overflow = 0;
        r.diff = PyLong_AsLongLongAndOverflow(d, &overflow);
        if (overflow || (r.diff == -1 && PyErr_Occurred())) {
            if (!PyErr_Occurred())
                PyErr_SetString(FallbackError, "diff overflow");
            return nullptr;
        }
        r.vals.resize(n_specs);
        for (size_t sidx = 0; sidx < n_specs; sidx++) {
            Val &v = r.vals[sidx];
            const uint8_t code = store->codes[sidx];
            /* codes whose value lands in the joint multiset accept the
             * full scalar set (strings included); sum/avg stay numeric */
            const bool stores_val =
                code == C_MIN || code == C_MAX || is_fp(code);
            PyObject *col = PyTuple_GET_ITEM(valcols, (Py_ssize_t)sidx);
            v.obj = nullptr;
            if (col == Py_None || code == C_COUNT) {
                v.tag = V_NONE;
                continue;
            }
            PyObject *item = PyList_GET_ITEM(col, i);
            v.obj = item;
            if (item == Py_None) {
                v.tag = V_NONE;
            } else if (item == error_obj) {
                if (rejects_error(code)) {
                    /* Python raises TypeError comparing ERROR — route to
                     * the Python path so the same error surfaces */
                    PyErr_SetString(FallbackError,
                                    "ERROR arg in ordering reducer");
                    return nullptr;
                }
                v.tag = V_ERR;
            } else if (PyFloat_Check(item)) {
                v.tag = V_FLT;
                v.f = PyFloat_AS_DOUBLE(item);
            } else if (PyBool_Check(item)) {
                /* bool compares as int in Python min/max and sums */
                v.tag = V_INT;
                v.i = item == Py_True ? 1 : 0;
            } else if (PyLong_Check(item)) {
                int ovf = 0;
                v.i = PyLong_AsLongLongAndOverflow(item, &ovf);
                if (ovf) {
                    PyErr_SetString(FallbackError, "arg beyond i64");
                    return nullptr;
                }
                v.tag = V_INT;
            } else if (stores_val && PyUnicode_Check(item)) {
                v.sptr = PyUnicode_AsUTF8AndSize(item, &v.slen);
                if (v.sptr == nullptr) {
                    PyErr_Clear();
                    PyErr_SetString(FallbackError, "non-UTF8 string arg");
                    return nullptr;
                }
                v.tag = V_STR;
            } else {
                PyErr_SetString(FallbackError, "unsupported reducer arg");
                return nullptr;
            }
            if (orders_args(code) &&
                (v.tag == V_INT || v.tag == V_FLT || v.tag == V_STR ||
                 (v.tag == V_NONE && compares_none(code)))) {
                const uint8_t k = v.tag == V_NONE  ? K_NONE
                                  : v.tag == V_STR ? K_STR
                                                   : K_NUM;
                if (kinds[sidx] != K_UNSET && kinds[sidx] != k) {
                    /* Python TypeErrors on mixed-kind comparisons — route
                     * the whole node to the Python path for parity */
                    PyErr_SetString(FallbackError,
                                    "mixed-kind ordering args");
                    return nullptr;
                }
                kinds[sidx] = k;
            }
        }
        if (has_order) {
            PyObject *item = PyList_GET_ITEM(ordercol, i);
            r.order_obj = item;
            MVal &m = r.order_mv;
            if (PyFloat_Check(item)) {
                m.tag = V_FLT;
                m.f = PyFloat_AS_DOUBLE(item);
            } else if (PyBool_Check(item)) {
                m.tag = V_INT;
                m.i = item == Py_True ? 1 : 0;
            } else if (PyLong_Check(item)) {
                int ovf = 0;
                m.i = PyLong_AsLongLongAndOverflow(item, &ovf);
                if (ovf) {
                    PyErr_SetString(FallbackError, "sort_by beyond i64");
                    return nullptr;
                }
                m.tag = V_INT;
            } else if (PyUnicode_Check(item)) {
                Py_ssize_t sl;
                const char *sp = PyUnicode_AsUTF8AndSize(item, &sl);
                if (sp == nullptr) {
                    PyErr_Clear();
                    PyErr_SetString(FallbackError, "non-UTF8 sort_by");
                    return nullptr;
                }
                m.tag = V_STR;
                m.s.assign(sp, (size_t)sl);
            } else {
                /* None/ERROR/exotic sort keys raise in Python's sort */
                PyErr_SetString(FallbackError, "unsupported sort_by value");
                return nullptr;
            }
            const uint8_t k = m.tag == V_STR ? K_STR : K_NUM;
            if (order_kind != K_UNSET && order_kind != k) {
                PyErr_SetString(FallbackError,
                                "mixed numeric/string sort_by values");
                return nullptr;
            }
            order_kind = k;
        }
        if (has_ms) {
            if (!ser_value(r.ms_key, r.row_key)) {
                PyErr_Clear();
                PyErr_SetString(FallbackError, "unsupported row key");
                return nullptr;
            }
            for (size_t sidx = 0; sidx < n_specs; sidx++) {
                Val &v = r.vals[sidx];
                if (v.obj == nullptr) {
                    r.ms_key.push_back('\x00');
                } else if (!ser_value(r.ms_key, v.obj)) {
                    if (v.obj == error_obj) {
                        r.ms_key.push_back('\x02'); /* ERROR sentinel */
                    } else {
                        PyErr_Clear();
                        PyErr_SetString(FallbackError,
                                        "unsupported reducer arg");
                        return nullptr;
                    }
                }
            }
            if (has_order) {
                /* same row re-fed with a different sort token must be a
                 * distinct multiset entry (Python keys combos on the
                 * order token too) */
                mval_ser(r.ms_key, r.order_mv);
            }
            if (has_fp && !key_ord_of(r.row_key, r.key_ord)) {
                PyErr_SetString(FallbackError, "row key not 128-bit");
                return nullptr;
            }
        }
    }

    store->kinds = kinds; /* phase 1 passed: no Fallback beyond here */
    store->order_kind = order_kind;
    phase_add(store, &PhaseStats::extract_s, _t0);
    phase_count(store, (int64_t)n);
    auto _t1 = std::chrono::steady_clock::now();

    /* phase 2: apply (GIL released) — shard-partitioned parallel update.
     * Refcounts are never touched here: creations/erasures of joint-
     * multiset entries record intents applied in phase 3. */
    std::vector<std::vector<Affected>> affected((size_t)W);
    std::vector<std::vector<PyObject *>> to_incref((size_t)W);
    std::vector<std::vector<PyObject *>> to_decref((size_t)W);
    {
        std::vector<std::vector<int32_t>> shard_rows((size_t)W);
        for (Py_ssize_t i = 0; i < n; i++)
            if (!rows[i].skip)
                shard_rows[rows[i].shard].push_back((int32_t)i);

        auto work = [&](int w) {
            Shard &sh = store->shards[(size_t)w];
            auto &aff = affected[(size_t)w];
            auto &incs = to_incref[(size_t)w];
            auto &decs = to_decref[(size_t)w];
            std::unordered_map<std::string, size_t> touched;
            for (int32_t ri : shard_rows[(size_t)w]) {
                RowExtract &r = rows[(size_t)ri];
                auto it = sh.groups.find(r.key);
                bool created = false;
                if (it == sh.groups.end()) {
                    it = sh.groups.emplace(r.key, Group{}).first;
                    it->second.st.resize(n_specs);
                    created = true;
                }
                Group &g = it->second;
                auto t = touched.find(r.key);
                if (t == touched.end()) {
                    touched.emplace(r.key, aff.size());
                    Affected a;
                    a.g = &g;
                    a.key = r.key;
                    a.first_row = ri;
                    a.before_total = created ? 0 : g.total;
                    a.created = created;
                    a.before.reserve(n_specs);
                    for (size_t sidx = 0; sidx < n_specs; sidx++)
                        a.before.push_back(
                            snap_of(store->codes[sidx], g.st[sidx]));
                    if (has_fp) {
                        /* borrowed pre-batch multiset image: objects stay
                         * alive through emit (phase-3 decrefs run last) */
                        a.ms_before.reserve(g.ms.size());
                        for (auto &kv : g.ms)
                            a.ms_before.push_back(kv.second);
                    }
                    aff.push_back(std::move(a));
                }
                g.total += r.diff;
                for (size_t sidx = 0; sidx < n_specs; sidx++)
                    apply_spec(store->codes[sidx], g.st[sidx], r.vals[sidx],
                               r.diff);
                if (has_ms) {
                    auto mit = g.ms.find(r.ms_key);
                    if (mit == g.ms.end()) {
                        MsEntry e;
                        e.key = r.row_key;
                        e.count = r.diff;
                        incs.push_back(r.row_key);
                        e.vals.reserve(n_specs);
                        for (size_t sidx = 0; sidx < n_specs; sidx++) {
                            PyObject *o = rows[(size_t)ri].vals[sidx].obj;
                            e.vals.push_back(o);
                            if (o != nullptr)
                                incs.push_back(o);
                        }
                        if (has_fp) {
                            e.key_ord = r.key_ord;
                            e.st_t = (int64_t)batch_time;
                            e.st_i = (int64_t)ri;
                            e.mvals.reserve(n_specs);
                            for (size_t sidx = 0; sidx < n_specs; sidx++)
                                e.mvals.push_back(
                                    mval_of(rows[(size_t)ri].vals[sidx]));
                        }
                        if (has_order) {
                            e.order_obj = r.order_obj;
                            e.order_mv = r.order_mv;
                            if (e.order_obj != nullptr)
                                incs.push_back(e.order_obj);
                        }
                        g.ms.emplace(r.ms_key, std::move(e));
                    } else {
                        mit->second.count += r.diff;
                        if (mit->second.count == 0) {
                            decs.push_back(mit->second.key);
                            if (mit->second.order_obj != nullptr)
                                decs.push_back(mit->second.order_obj);
                            for (PyObject *o : mit->second.vals)
                                if (o != nullptr)
                                    decs.push_back(o);
                            g.ms.erase(mit);
                        }
                    }
                }
            }
            if (has_fp) {
                /* finished-value fingerprints, before and after, for every
                 * fp spec of every touched group — GIL-free */
                std::vector<const MsEntry *> view, scratch;
                for (Affected &a : aff) {
                    a.fp_before.resize(n_specs);
                    a.fp_after.resize(n_specs);
                    Group &g = *a.g;
                    for (size_t sidx = 0; sidx < n_specs; sidx++) {
                        const uint8_t code = store->codes[sidx];
                        if (!is_fp(code))
                            continue;
                        view.clear();
                        for (const MsEntry &e : a.ms_before)
                            view.push_back(&e);
                        fp_fingerprint(a.fp_before[sidx], code, has_order,
                                       view, sidx, scratch);
                        view.clear();
                        for (auto &kv : g.ms)
                            view.push_back(&kv.second);
                        fp_fingerprint(a.fp_after[sidx], code, has_order,
                                       view, sidx, scratch);
                    }
                }
            }
        };

        Py_BEGIN_ALLOW_THREADS
        const uint64_t _tr0 = trace_on() ? trace_now_ns() : 0;
        if (W > 1 && n >= 2048) {
            std::vector<std::thread> threads;
            threads.reserve((size_t)W);
            for (int w = 0; w < W; w++)
                threads.emplace_back(
                    [&work](int ww) {
                        const uint64_t t0 =
                            trace_on() ? trace_now_ns() : 0;
                        work(ww);
                        if (t0)
                            trace_note(T_GB_APPLY, ww, t0,
                                       trace_now_ns(), -1);
                    },
                    w);
            for (auto &t : threads)
                t.join();
        } else {
            for (int w = 0; w < W; w++)
                work(w);
        }
        if (_tr0)
            trace_note(T_GB_APPLY, -1, _tr0, trace_now_ns(), (int64_t)n);
        Py_END_ALLOW_THREADS
    }

    phase_add(store, &PhaseStats::apply_s, _t1);
    auto _t2 = std::chrono::steady_clock::now();

    /* phase 3: refcount intents first, then emit (GIL held) */
    for (int w = 0; w < W; w++)
        for (PyObject *p : to_incref[(size_t)w])
            Py_INCREF(p);

    PyObject *out = PyList_New(0);
    bool failed = out == nullptr;
    for (int w = 0; w < W && !failed; w++) {
        for (Affected &a : affected[(size_t)w]) {
            Group &g = *a.g;
            /* mint gvals/out_key refs for groups created this batch.
             * out_key is minted into a local and committed together with
             * gvals only on success (and re-minted when a previous batch
             * failed mid-mint) — a key_fn exception must not leave a
             * group with gvals set and a null out_key that a later
             * batch's emit would Py_INCREF. */
            if (g.out_key == nullptr) {
                PyObject *gv = g.gvals != nullptr
                                   ? g.gvals
                                   : PyList_GET_ITEM(gvals_list, a.first_row);
                PyObject *ok = PyObject_CallOneArg(key_fn, gv);
                if (ok == nullptr) {
                    failed = true;
                    break;
                }
                if (g.gvals == nullptr) {
                    Py_INCREF(gv);
                    g.gvals = gv;
                }
                g.out_key = ok;
            }
            bool before_live = a.before_total > 0;
            bool after_live = g.total > 0;
            bool changed = before_live != after_live;
            std::vector<FinSnap> after;
            if (after_live) {
                after.reserve(n_specs);
                for (size_t sidx = 0; sidx < n_specs; sidx++)
                    after.push_back(snap_of(store->codes[sidx], g.st[sidx]));
            }
            if (!changed && after_live) {
                for (size_t sidx = 0; sidx < n_specs && !changed; sidx++) {
                    const uint8_t code = store->codes[sidx];
                    changed = is_fp(code)
                                  ? a.fp_before[sidx] != a.fp_after[sidx]
                                  : !finish_equal(code, a.before[sidx],
                                                  after[sidx]);
                }
            }
            if (changed) {
                Py_ssize_t ng = PyTuple_GET_SIZE(g.gvals);
                /* entry views for fp specs: before from the borrowed
                 * snapshot, after from the live multiset */
                std::vector<const MsEntry *> before_view, after_view;
                if (has_fp) {
                    for (const MsEntry &e : a.ms_before)
                        before_view.push_back(&e);
                    for (auto &kv : g.ms)
                        after_view.push_back(&kv.second);
                }
                auto emit = [&](const std::vector<FinSnap> &st,
                                const std::vector<const MsEntry *> &view,
                                long dir) -> int {
                    PyObject *row =
                        PyTuple_New(ng + (Py_ssize_t)n_specs);
                    if (row == nullptr)
                        return -1;
                    for (Py_ssize_t j = 0; j < ng; j++) {
                        PyObject *x = PyTuple_GET_ITEM(g.gvals, j);
                        Py_INCREF(x);
                        PyTuple_SET_ITEM(row, j, x);
                    }
                    for (size_t sidx = 0; sidx < n_specs; sidx++) {
                        const uint8_t code = store->codes[sidx];
                        PyObject *v =
                            is_fp(code)
                                ? fp_value(code, has_order, view, sidx,
                                           error_obj)
                                : finish_snap(code, st[sidx], error_obj);
                        if (v == nullptr) {
                            Py_DECREF(row);
                            return -1;
                        }
                        PyTuple_SET_ITEM(row, ng + (Py_ssize_t)sidx, v);
                    }
                    PyObject *delta = Py_BuildValue("(OOl)", g.out_key, row,
                                                    dir);
                    Py_DECREF(row);
                    if (delta == nullptr)
                        return -1;
                    int rc = PyList_Append(out, delta);
                    Py_DECREF(delta);
                    return rc;
                };
                if (before_live && emit(a.before, before_view, -1) < 0) {
                    failed = true;
                    break;
                }
                if (after_live && emit(after, after_view, 1) < 0) {
                    failed = true;
                    break;
                }
            }
            if (g.total == 0 && g.ms.empty()) {
                /* fully retracted group: release refs and erase */
                Py_XDECREF(g.gvals);
                Py_XDECREF(g.out_key);
                store->shards[(size_t)w].groups.erase(a.key);
            }
        }
    }

    for (int w = 0; w < W; w++)
        for (PyObject *p : to_decref[(size_t)w])
            Py_DECREF(p);
    phase_add(store, &PhaseStats::emit_s, _t2);
    if (failed) {
        Py_XDECREF(out);
        return nullptr;
    }
    return out;
}

/* ---- dump/load for operator snapshots and Python-path migration -------
 * Entry: (gvals, out_key, total, states[, ms_entries]) — ms_entries
 * present iff the store tracks the joint row multiset (min/max or fp
 * specs): [(row_key, (val_or_None per spec), count, (st_t, st_i),
 * order_or_None)] — the stamp preserves earliest/latest processing-time
 * ranking and `order` the sort_by token. Legacy 3-tuple entries load
 * with stamp (0,0) and no order. min/max mm state is NOT dumped — load
 * rebuilds it from ms_entries. */

PyObject *store_dump(PyObject *, PyObject *arg)
{
    GroupStore *s = get_store(arg);
    if (s == nullptr)
        return nullptr;
    PyObject *out = PyList_New(0);
    if (out == nullptr)
        return nullptr;
    for (auto &sh : s->shards) {
        for (auto &kv : sh.groups) {
            Group &g = kv.second;
            PyObject *states = PyList_New((Py_ssize_t)g.st.size());
            if (states == nullptr) {
                Py_DECREF(out);
                return nullptr;
            }
            for (size_t i = 0; i < g.st.size(); i++) {
                SState &st = g.st[i];
                PyObject *isum = pylong_from_i128(st.isum);
                if (isum == nullptr) {
                    Py_DECREF(states);
                    Py_DECREF(out);
                    return nullptr;
                }
                PyObject *t = Py_BuildValue(
                    "(LNdOL)", (long long)st.cnt, isum, st.fsum,
                    st.isfloat ? Py_True : Py_False, (long long)st.err);
                if (t == nullptr) {
                    Py_DECREF(states);
                    Py_DECREF(out);
                    return nullptr;
                }
                PyList_SET_ITEM(states, (Py_ssize_t)i, t);
            }
            PyObject *entry;
            if (s->has_ms) {
                PyObject *ms = PyList_New(0);
                bool ok = ms != nullptr;
                for (auto &me : g.ms) {
                    if (!ok)
                        break;
                    const MsEntry &e = me.second;
                    PyObject *vals =
                        PyTuple_New((Py_ssize_t)e.vals.size());
                    if (vals == nullptr) {
                        ok = false;
                        break;
                    }
                    for (size_t j = 0; j < e.vals.size(); j++) {
                        PyObject *v = e.vals[j] ? e.vals[j] : Py_None;
                        Py_INCREF(v);
                        PyTuple_SET_ITEM(vals, (Py_ssize_t)j, v);
                    }
                    PyObject *t = Py_BuildValue(
                        "(ONL(LL)O)", e.key, vals, (long long)e.count,
                        (long long)e.st_t, (long long)e.st_i,
                        e.order_obj ? e.order_obj : Py_None);
                    if (t == nullptr || PyList_Append(ms, t) < 0) {
                        Py_XDECREF(t);
                        ok = false;
                        break;
                    }
                    Py_DECREF(t);
                }
                if (!ok) {
                    Py_XDECREF(ms);
                    Py_DECREF(states);
                    Py_DECREF(out);
                    return nullptr;
                }
                entry = Py_BuildValue(
                    "(OOLON)", g.gvals ? g.gvals : Py_None,
                    g.out_key ? g.out_key : Py_None, (long long)g.total,
                    states, ms);
            } else {
                entry = Py_BuildValue(
                    "(OOLO)", g.gvals ? g.gvals : Py_None,
                    g.out_key ? g.out_key : Py_None, (long long)g.total,
                    states);
            }
            Py_DECREF(states);
            if (entry == nullptr || PyList_Append(out, entry) < 0) {
                Py_XDECREF(entry);
                Py_DECREF(out);
                return nullptr;
            }
            Py_DECREF(entry);
        }
    }
    return out;
}

PyObject *store_load(PyObject *, PyObject *args)
{
    PyObject *capsule, *entries, *error_obj = nullptr;
    if (!PyArg_ParseTuple(args, "OO|O", &capsule, &entries, &error_obj))
        return nullptr;
    GroupStore *s = get_store(capsule);
    if (s == nullptr)
        return nullptr;
    SvHash hasher; /* one hasher everywhere: shard placement must agree across the nb and tuple paths */
    Py_ssize_t n = PyList_Size(entries);
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *entry = PyList_GET_ITEM(entries, i);
        PyObject *gvals, *out_key, *states, *ms_list = nullptr;
        long long total;
        if (PyTuple_Check(entry) && PyTuple_GET_SIZE(entry) == 5) {
            if (!PyArg_ParseTuple(entry, "OOLOO", &gvals, &out_key, &total,
                                  &states, &ms_list))
                return nullptr;
        } else if (!PyArg_ParseTuple(entry, "OOLO", &gvals, &out_key,
                                     &total, &states))
            return nullptr;
        if (s->has_ms && ms_list == nullptr) {
            PyErr_SetString(FallbackError,
                            "snapshot lacks the joint multiset this "
                            "min/max store needs");
            return nullptr;
        }
        std::string key;
        if (!ser_gvals(key, gvals)) {
            if (!PyErr_Occurred())
                PyErr_SetString(FallbackError,
                                "unsupported grouping value in snapshot");
            return nullptr;
        }
        Shard &sh = s->shards[hasher(key) % (size_t)s->n_shards];
        Group &g = sh.groups[key];
        g.total = total;
        Py_INCREF(gvals);
        g.gvals = gvals;
        Py_INCREF(out_key);
        g.out_key = out_key;
        Py_ssize_t ns = PyList_Size(states);
        g.st.resize((size_t)ns);
        for (Py_ssize_t j = 0; j < ns; j++) {
            long long cnt, err;
            double fsum;
            PyObject *isum_obj, *isfloat;
            if (!PyArg_ParseTuple(PyList_GET_ITEM(states, j), "LOdOL", &cnt,
                                  &isum_obj, &fsum, &isfloat, &err))
                return nullptr;
            SState &st = g.st[(size_t)j];
            st.cnt = cnt;
            int ovf = 0;
            long long i64 = PyLong_AsLongLongAndOverflow(isum_obj, &ovf);
            if (!ovf) {
                st.isum = i64;
            } else {
                /* >i64 snapshot value: parse the decimal text into i128 */
                PyObject *txt = PyObject_Str(isum_obj);
                if (txt == nullptr)
                    return nullptr;
                const char *p = PyUnicode_AsUTF8(txt);
                bool neg = *p == '-';
                if (neg)
                    p++;
                __int128 acc = 0;
                for (; *p >= '0' && *p <= '9'; p++)
                    acc = acc * 10 + (*p - '0');
                st.isum = neg ? -acc : acc;
                Py_DECREF(txt);
            }
            st.fsum = fsum;
            st.isfloat = isfloat == Py_True;
            st.err = err;
        }
        if (s->has_ms && ms_list != nullptr) {
            /* rebuild the joint multiset AND every min/max spec's ordered
             * state from the dumped entries (min/max err comes from the
             * entries too — clear the state-dump copy to avoid doubling) */
            for (size_t sidx = 0; sidx < s->codes.size(); sidx++)
                if (s->codes[sidx] == C_MIN || s->codes[sidx] == C_MAX) {
                    g.st[sidx].err = 0;
                    g.st[sidx].mm.clear();
                }
            Py_ssize_t nm = PyList_Size(ms_list);
            for (Py_ssize_t j = 0; j < nm; j++) {
                PyObject *row_key, *vals, *stamp = nullptr,
                                          *order = nullptr;
                long long count;
                PyObject *ms_entry = PyList_GET_ITEM(ms_list, j);
                if (PyTuple_Check(ms_entry) &&
                    PyTuple_GET_SIZE(ms_entry) == 5) {
                    if (!PyArg_ParseTuple(ms_entry, "OOLOO", &row_key,
                                          &vals, &count, &stamp, &order))
                        return nullptr;
                    if (order == Py_None)
                        order = nullptr;
                } else if (!PyArg_ParseTuple(ms_entry, "OOL", &row_key,
                                             &vals, &count))
                    return nullptr; /* legacy 3-tuple snapshot */
                if (s->has_order && order == nullptr) {
                    PyErr_SetString(FallbackError,
                                    "snapshot lacks the sort_by tokens "
                                    "this store needs");
                    return nullptr;
                }
                /* pass 1: serialize the entry key (no refcounts yet) */
                std::string mkey;
                if (!ser_value(mkey, row_key)) {
                    if (!PyErr_Occurred())
                        PyErr_SetString(FallbackError,
                                        "unsupported row key in snapshot");
                    return nullptr;
                }
                std::vector<PyObject *> raw_vals;
                for (size_t sidx = 0; sidx < s->codes.size(); sidx++) {
                    PyObject *v =
                        PyTuple_GET_ITEM(vals, (Py_ssize_t)sidx);
                    if (s->codes[sidx] == C_COUNT) { /* argless: None */
                        mkey.push_back('\x00');
                        raw_vals.push_back(nullptr);
                        continue;
                    }
                    raw_vals.push_back(v);
                    if (!ser_value(mkey, v)) {
                        if (error_obj != nullptr && v == error_obj) {
                            PyErr_Clear();
                            mkey.push_back('\x02');
                        } else {
                            if (!PyErr_Occurred())
                                PyErr_SetString(
                                    FallbackError,
                                    "unsupported reducer arg in snapshot");
                            return nullptr;
                        }
                    }
                }
                /* pass 1.5: extract Vals exactly like process_batch phase
                 * 1 (incl. overflow/encoding/kind checks) for every spec
                 * that stores values — BEFORE any state mutates, so a
                 * Fallback here leaves the store loadable by Python */
                std::vector<Val> vvs(s->codes.size());
                MVal order_mv;
                for (size_t sidx = 0; sidx < s->codes.size(); sidx++) {
                    const uint8_t code = s->codes[sidx];
                    const bool stores_val =
                        code == C_MIN || code == C_MAX || is_fp(code);
                    if (!stores_val)
                        continue;
                    PyObject *v = raw_vals[sidx];
                    Val &vv = vvs[sidx];
                    vv.obj = v;
                    if (v == nullptr || v == Py_None) {
                        vv.tag = V_NONE;
                    } else if (error_obj != nullptr && v == error_obj) {
                        if (rejects_error(code)) {
                            PyErr_SetString(
                                FallbackError,
                                "ERROR arg in ordering-reducer snapshot");
                            return nullptr;
                        }
                        vv.tag = V_ERR;
                    } else if (PyFloat_Check(v)) {
                        vv.tag = V_FLT;
                        vv.f = PyFloat_AS_DOUBLE(v);
                    } else if (PyBool_Check(v)) {
                        vv.tag = V_INT;
                        vv.i = v == Py_True ? 1 : 0;
                    } else if (PyLong_Check(v)) {
                        int ovf = 0;
                        vv.i = PyLong_AsLongLongAndOverflow(v, &ovf);
                        if (ovf) {
                            PyErr_SetString(FallbackError,
                                            "snapshot arg beyond i64");
                            return nullptr;
                        }
                        vv.tag = V_INT;
                    } else if (PyUnicode_Check(v)) {
                        vv.sptr = PyUnicode_AsUTF8AndSize(v, &vv.slen);
                        if (vv.sptr == nullptr) {
                            PyErr_Clear();
                            PyErr_SetString(FallbackError,
                                            "non-UTF8 snapshot arg");
                            return nullptr;
                        }
                        vv.tag = V_STR;
                    } else {
                        PyErr_SetString(FallbackError,
                                        "unsupported snapshot arg");
                        return nullptr;
                    }
                    if (orders_args(code) &&
                        (vv.tag == V_INT || vv.tag == V_FLT ||
                         vv.tag == V_STR ||
                         (vv.tag == V_NONE && compares_none(code)))) {
                        const uint8_t k = vv.tag == V_NONE  ? K_NONE
                                          : vv.tag == V_STR ? K_STR
                                                            : K_NUM;
                        if (s->kinds[sidx] != K_UNSET &&
                            s->kinds[sidx] != k) {
                            PyErr_SetString(
                                FallbackError,
                                "mixed-kind ordering snapshot");
                            return nullptr;
                        }
                        s->kinds[sidx] = k;
                    }
                }
                if (s->has_order) {
                    if (PyFloat_Check(order)) {
                        order_mv.tag = V_FLT;
                        order_mv.f = PyFloat_AS_DOUBLE(order);
                    } else if (PyBool_Check(order)) {
                        order_mv.tag = V_INT;
                        order_mv.i = order == Py_True ? 1 : 0;
                    } else if (PyLong_Check(order)) {
                        int ovf = 0;
                        order_mv.i =
                            PyLong_AsLongLongAndOverflow(order, &ovf);
                        if (ovf) {
                            PyErr_SetString(FallbackError,
                                            "snapshot sort_by beyond i64");
                            return nullptr;
                        }
                        order_mv.tag = V_INT;
                    } else if (PyUnicode_Check(order)) {
                        Py_ssize_t sl;
                        const char *sp =
                            PyUnicode_AsUTF8AndSize(order, &sl);
                        if (sp == nullptr) {
                            PyErr_Clear();
                            PyErr_SetString(FallbackError,
                                            "non-UTF8 snapshot sort_by");
                            return nullptr;
                        }
                        order_mv.tag = V_STR;
                        order_mv.s.assign(sp, (size_t)sl);
                    } else {
                        PyErr_SetString(FallbackError,
                                        "unsupported snapshot sort_by");
                        return nullptr;
                    }
                    const uint8_t k =
                        order_mv.tag == V_STR ? K_STR : K_NUM;
                    if (s->order_kind != K_UNSET && s->order_kind != k) {
                        PyErr_SetString(
                            FallbackError,
                            "mixed numeric/string sort_by snapshot");
                        return nullptr;
                    }
                    s->order_kind = k;
                    mval_ser(mkey, order_mv);
                }
                /* pass 2: merge-or-insert, then fold into min/max state */
                auto found = g.ms.find(mkey);
                if (found != g.ms.end()) {
                    found->second.count += count;
                } else {
                    MsEntry e;
                    e.key = row_key;
                    e.count = count;
                    Py_INCREF(row_key);
                    for (PyObject *v : raw_vals) {
                        e.vals.push_back(v);
                        if (v != nullptr)
                            Py_INCREF(v);
                    }
                    if (s->has_fp) {
                        if (!key_ord_of(row_key, e.key_ord)) {
                            PyErr_SetString(FallbackError,
                                            "snapshot row key not 128-bit");
                            /* e's refs were taken above: release them */
                            Py_DECREF(row_key);
                            for (PyObject *v : raw_vals)
                                if (v != nullptr)
                                    Py_DECREF(v);
                            return nullptr;
                        }
                        e.mvals.reserve(s->codes.size());
                        for (size_t sidx = 0; sidx < s->codes.size();
                             sidx++)
                            e.mvals.push_back(mval_of(vvs[sidx]));
                        if (stamp != nullptr && PyTuple_Check(stamp) &&
                            PyTuple_GET_SIZE(stamp) == 2) {
                            e.st_t = PyLong_AsLongLong(
                                PyTuple_GET_ITEM(stamp, 0));
                            e.st_i = PyLong_AsLongLong(
                                PyTuple_GET_ITEM(stamp, 1));
                            if (PyErr_Occurred())
                                PyErr_Clear();
                        }
                    }
                    if (s->has_order) {
                        e.order_obj = order;
                        Py_INCREF(order);
                        e.order_mv = order_mv;
                    }
                    g.ms.emplace(std::move(mkey), std::move(e));
                }
                for (size_t sidx = 0; sidx < s->codes.size(); sidx++) {
                    const uint8_t code = s->codes[sidx];
                    if (code != C_MIN && code != C_MAX)
                        continue;
                    apply_spec(code, g.st[sidx], vvs[sidx], count);
                }
            }
        }
    }
    Py_RETURN_NONE;
}

/* ====================================================================== *
 *  Sharded native DELTA-JOIN executor (reference: dataflow.rs join impl
 *  over differential arrangements — join_core computes ΔL⋈R + L'⋈ΔR).
 *
 *  Unlike the Python JoinNode (whole-group rediff: O(|L|·|R|) per touched
 *  join key), this computes the output delta directly:
 *      Δ(L⋈R) = ΔL ⋈ R_old  +  L_new ⋈ ΔR
 *  plus pad-row transitions for left/right/outer joins, so work is
 *  proportional to the OUTPUT change. Shards partition join keys across
 *  PATHWAY_THREADS; the apply phase runs with the GIL released.
 *
 *  Ref-count protocol: phase 2 (no GIL) never touches refcounts — it
 *  records to_incref (objects newly stored) and to_decref (objects whose
 *  store entries died). Phase 3 (GIL) INCREFs first, builds the output
 *  deltas (which borrow from either the store or the still-alive batch
 *  lists), and DECREFs last.
 *
 *  Two entry points share ONE store: join_batch (Python delta lists in,
 *  delta lists out) and join_batch_nb (columnar NativeBatch in, and —
 *  in the steady streaming state — NativeBatch out). Entries carry a
 *  tuple rep, a native packed-cell rep, or both; jk/entry serialization
 *  is byte-identical across the two paths so a store may be fed by any
 *  mix of them.
 *
 *  Replay invariant (both entry points): NO Fallback beyond phase 1.
 *  Phase 1 mutates nothing, so a Fallback there replays safely on the
 *  other path; an error after phase 1 leaves the batch half-applied and
 *  the caller must demote the node rather than replay the batch.
 * ====================================================================== */

/* One (key, row) multiset entry on a join side. Two representations:
 *  - tuple rep: `key`/`row` own Python objects (tuple-path inserts);
 *  - native rep: `key128` + `cells` hold a C-owned packed image of the
 *    row (NativeBatch-path inserts) — no Python object exists for the
 *    entry until the tuple path, a dump, or a demotion needs one.
 * An entry has at least one rep; emissions use whichever is present and
 * the fused emit stays columnar only while every touched entry carries
 * the native rep. `cells` is shared so emit records survive the entry
 * being erased mid-batch (retraction storms over nb-fed groups). */
struct JEntry {
    PyObject *key = nullptr;  /* owned (incref'd via to_incref in phase 3) */
    PyObject *row = nullptr;  /* owned */
    unsigned __int128 key128 = 0;
    std::shared_ptr<const std::string> cells;
    int64_t count = 0;
    uint64_t seq = 0; /* per-group insertion order: cross-product emits
                       * must not follow unordered_map bucket order —
                       * same-output-key emits (id= fanout joins) would
                       * pick an encoding/timing-dependent winner */
};

struct JGroup {
    PyObject *jk = nullptr; /* owned: join-key tuple (for dump/migration);
                             * nullptr for nb-created groups — jk_cells
                             * then holds the packed key columns */
    std::string jk_cells;
    std::unordered_map<std::string, JEntry> left, right;
    uint64_t next_seq = 0;
};

/* one side's live entries in insertion (seq) order — the order the pure
 * Python MultisetState (insertion-ordered dict) emits, so native and
 * demoted paths stay bit-identical even under duplicate output keys.
 * The sort is per affected group per batch; callers skip the call
 * entirely when no delta consumes the side, and the 0/1-entry case
 * (unique join keys, the common shape) pays no sort at all. */
inline void jside_ordered(std::unordered_map<std::string, JEntry> &side,
                          std::vector<const JEntry *> &out)
{
    out.clear();
    out.reserve(side.size());
    for (auto &e : side)
        out.push_back(&e.second);
    if (out.size() > 1)
        std::sort(out.begin(), out.end(),
                  [](const JEntry *a, const JEntry *b) {
                      return a->seq < b->seq;
                  });
}

struct JShard {
    std::unordered_map<std::string, JGroup> groups;
};

enum JType : uint8_t { J_INNER = 0, J_LEFT = 1, J_RIGHT = 2, J_OUTER = 3 };
enum IdMode : uint8_t {
    ID_PAIR = 0,
    ID_FROM_LEFT = 1,
    ID_FROM_RIGHT = 2,
    ID_LEFT_FN = 3,
    ID_RIGHT_FN = 4,
};

struct JoinStore {
    int n_shards;
    uint8_t jt;
    uint8_t id_mode;
    int lwidth, rwidth;
    PyObject *ptr_type = nullptr; /* owned: Pointer class — set by the nb
                                   * path; materializes native entries */
    PhaseStats phases;
    std::vector<JShard> shards;
};

PhaseStats g_join_phases; /* process-wide join totals (all stores) */

inline void jphase_add(JoinStore *s, double PhaseStats::*field,
                       std::chrono::steady_clock::time_point t0)
{
    const double dt = _since(t0);
    s->phases.*field += dt;
    g_join_phases.*field += dt;
}

void join_store_destructor(PyObject *capsule)
{
    auto *s = static_cast<JoinStore *>(
        PyCapsule_GetPointer(capsule, "pwexec.JoinStore"));
    if (s == nullptr)
        return;
    for (auto &sh : s->shards)
        for (auto &kv : sh.groups) {
            Py_XDECREF(kv.second.jk);
            for (auto &e : kv.second.left) {
                Py_XDECREF(e.second.key);
                Py_XDECREF(e.second.row);
            }
            for (auto &e : kv.second.right) {
                Py_XDECREF(e.second.key);
                Py_XDECREF(e.second.row);
            }
        }
    Py_XDECREF(s->ptr_type);
    delete s;
}

JoinStore *get_join_store(PyObject *capsule)
{
    return static_cast<JoinStore *>(
        PyCapsule_GetPointer(capsule, "pwexec.JoinStore"));
}

PyObject *join_store_new(PyObject *, PyObject *args)
{
    int n_shards, jt, id_mode, lwidth, rwidth;
    if (!PyArg_ParseTuple(args, "iiiii", &n_shards, &jt, &id_mode, &lwidth,
                          &rwidth))
        return nullptr;
    if (n_shards < 1)
        n_shards = 1;
    auto *s = new JoinStore();
    s->n_shards = n_shards;
    s->jt = (uint8_t)jt;
    s->id_mode = (uint8_t)id_mode;
    s->lwidth = lwidth;
    s->rwidth = rwidth;
    s->shards.resize(n_shards);
    return PyCapsule_New(s, "pwexec.JoinStore", join_store_destructor);
}

PyObject *join_store_len(PyObject *, PyObject *arg)
{
    JoinStore *s = get_join_store(arg);
    if (s == nullptr)
        return nullptr;
    int64_t n = 0;
    for (auto &sh : s->shards)
        n += (int64_t)sh.groups.size();
    return PyLong_FromLongLong(n);
}

/* -- join_store_nbytes(store) -------------------------------------------
 * the join-side twin of store_nbytes (same estimate discipline, same
 * GIL-free walk: pointer NULL-compares and container shapes only). */
PyObject *join_store_nbytes(PyObject *, PyObject *arg)
{
    JoinStore *s = get_join_store(arg);
    if (s == nullptr)
        return nullptr;
    int64_t n = 0;
    Py_BEGIN_ALLOW_THREADS
    n += (int64_t)sizeof(JoinStore);
    for (auto &sh : s->shards) {
        n += (int64_t)sizeof(JShard);
        n += (int64_t)sh.groups.bucket_count() * (int64_t)sizeof(void *);
        for (auto &kv : sh.groups) {
            const JGroup &g = kv.second;
            n += kNodeEst + (int64_t)kv.first.capacity();
            n += (int64_t)sizeof(JGroup) + (int64_t)g.jk_cells.capacity();
            if (g.jk != nullptr)
                n += kObjEst;
            const std::unordered_map<std::string, JEntry> *sides[2] = {
                &g.left, &g.right};
            for (const auto *side : sides) {
                n += (int64_t)side->bucket_count() *
                     (int64_t)sizeof(void *);
                for (const auto &ev : *side) {
                    const JEntry &e = ev.second;
                    n += kNodeEst + (int64_t)ev.first.capacity();
                    n += (int64_t)sizeof(JEntry);
                    if (e.key != nullptr)
                        n += kObjEst;
                    if (e.row != nullptr)
                        n += kObjEst;
                    if (e.cells)
                        n += (int64_t)e.cells->capacity();
                }
            }
        }
    }
    Py_END_ALLOW_THREADS
    return PyLong_FromLongLong(n);
}

/* extracted input row for one side */
struct JRowX {
    uint32_t shard;
    std::string jk_bytes;
    std::string entry_bytes; /* ser(key) + ser(row tuple) */
    PyObject *jk;            /* borrowed from batch list */
    PyObject *key;           /* borrowed */
    PyObject *row;           /* borrowed */
    int64_t diff;
};

/* one side of an output instruction: pad-with-Nones, a borrowed Python
 * (key, row) pair, or a native (key128, packed cells) image. `cells` is
 * a shared_ptr copy so the record survives its store entry being erased
 * later in the batch (Python refs survive via the deferred-decref
 * protocol instead). */
enum JRefKind : uint8_t { JR_PAD = 0, JR_PY = 1, JR_NATIVE = 2 };

struct JRef {
    PyObject *k = nullptr, *row = nullptr; /* borrowed (protocol above) */
    unsigned __int128 key128 = 0;
    std::shared_ptr<const std::string> cells;
    uint8_t kind = JR_PAD;
};

inline JRef jref_of_entry(const JEntry &e)
{
    JRef r;
    if (e.cells) {
        r.kind = JR_NATIVE;
        r.key128 = e.key128;
        r.cells = e.cells;
    } else {
        r.kind = JR_PY;
        r.k = e.key;
        r.row = e.row;
    }
    return r;
}

struct JEmit {
    JRef l, r;
    int64_t d;
};

bool ser_entry(std::string &out, PyObject *key, PyObject *row)
{
    if (!ser_value(out, key))
        return false;
    return ser_gvals(out, row);
}

bool extract_side(PyObject *jks, PyObject *keys, PyObject *rows,
                  PyObject *diffs, int W, std::vector<JRowX> &out)
{
    Py_ssize_t n = PyList_Size(jks);
    if (n < 0)
        return false;
    out.resize((size_t)n);
    SvHash hasher; /* one hasher everywhere: shard placement must agree across the nb and tuple paths */
    for (Py_ssize_t i = 0; i < n; i++) {
        JRowX &r = out[(size_t)i];
        r.jk = PyList_GET_ITEM(jks, i);
        r.key = PyList_GET_ITEM(keys, i);
        r.row = PyList_GET_ITEM(rows, i);
        if (!ser_gvals(r.jk_bytes, r.jk) ||
            !ser_entry(r.entry_bytes, r.key, r.row)) {
            PyErr_Clear();
            PyErr_SetString(FallbackError, "unsupported join value");
            return false;
        }
        r.shard = (uint32_t)(hasher(r.jk_bytes) % (size_t)W);
        PyObject *d = PyList_GET_ITEM(diffs, i);
        int overflow = 0;
        r.diff = PyLong_AsLongLongAndOverflow(d, &overflow);
        if (overflow || (r.diff == -1 && PyErr_Occurred())) {
            if (!PyErr_Occurred())
                PyErr_SetString(FallbackError, "diff overflow");
            return false;
        }
    }
    return true;
}

/* per-shard scratch produced by the parallel apply phase */
struct JShardOut {
    std::vector<JEmit> emits;
    std::vector<PyObject *> to_incref;
    std::vector<PyObject *> to_decref;
    bool dup_bump = false; /* positive bump of a live (key,row) entry */
};

/* apply one side's delta rows to a side map; records refcount intents */
inline void japply(std::unordered_map<std::string, JEntry> &side,
                   const JRowX &r, JShardOut &o, uint64_t &next_seq)
{
    auto it = side.find(r.entry_bytes);
    if (it == side.end()) {
        JEntry e;
        e.key = r.key;
        e.row = r.row;
        e.count = r.diff;
        e.seq = next_seq++;
        side.emplace(r.entry_bytes, std::move(e));
        o.to_incref.push_back(r.key);
        o.to_incref.push_back(r.row);
    } else {
        /* multiplicity bump of an already-live (key, row): the only way
         * one output pair can be emitted twice in a batch (dL x R_old
         * and L_new x dR hitting the same 4-tuple) — disqualifies the
         * caller's net-form shortcut */
        if (it->second.count > 0 && r.diff > 0)
            o.dup_bump = true;
        it->second.count += r.diff;
        if (it->second.count == 0) {
            if (it->second.key != nullptr) {
                o.to_decref.push_back(it->second.key);
                o.to_decref.push_back(it->second.row);
            }
            side.erase(it);
        }
    }
}

/* fill row slots [base, base+width) from one side ref (GIL) */
inline int fill_row_side(PyObject *row, int base, int width, const JRef &ref)
{
    if (ref.kind == JR_NATIVE) {
        const char *p = ref.cells->data();
        for (int j = 0; j < width; j++) {
            PyObject *v = packed_cell_to_py(p);
            if (v == nullptr)
                return -1;
            PyTuple_SET_ITEM(row, base + j, v);
        }
        return 0;
    }
    for (int j = 0; j < width; j++) {
        PyObject *v =
            ref.kind == JR_PY ? PyTuple_GET_ITEM(ref.row, j) : Py_None;
        Py_INCREF(v);
        PyTuple_SET_ITEM(row, base + j, v);
    }
    return 0;
}

/* side key as a NEW reference: Pointer, or None for pads (GIL) */
inline PyObject *jref_key_py(const JRef &ref, PyObject *ptr_type)
{
    if (ref.kind == JR_PY) {
        Py_INCREF(ref.k);
        return ref.k;
    }
    if (ref.kind == JR_NATIVE)
        return pointer_from_u128(ptr_type, ref.key128);
    Py_RETURN_NONE;
}

/* Materialize the shard emit records into [(okey, row, d), ...] (GIL).
 * pair_key_fn == nullptr mints ref_scalar(lk, rk) natively (blake2b
 * parity) — the join_batch_nb path; join_batch passes its Python fn so
 * direct callers with custom key fns keep their semantics. The JRef
 * protocol keeps every referenced object/cell image alive until the
 * caller runs its deferred decrefs AFTER this returns. */
PyObject *jemit_tuples(JoinStore *store, std::vector<JShardOut> &outs,
                       PyObject *pair_key_fn, PyObject *id_fn)
{
    PyObject *out = PyList_New(0);
    bool failed = out == nullptr;
    const int lw = store->lwidth, rw = store->rwidth;
    for (auto &o : outs) {
        if (failed)
            break;
        for (JEmit &e : o.emits) {
            if (e.d == 0)
                continue;
            PyObject *row = PyTuple_New(lw + rw);
            if (row == nullptr) {
                failed = true;
                break;
            }
            if (fill_row_side(row, 0, lw, e.l) < 0 ||
                fill_row_side(row, lw, rw, e.r) < 0) {
                Py_DECREF(row);
                failed = true;
                break;
            }
            PyObject *okey = nullptr;
            switch (store->id_mode) {
            case ID_LEFT_FN:
                if (e.l.kind == JR_PAD) {
                    PyErr_SetString(
                        PyExc_ValueError,
                        "join id= references the left side but an "
                        "outer/right join produced a row with no left match");
                    failed = true;
                } else {
                    /* id fns disqualify the nb path, so the side is
                     * tuple-rep here by construction */
                    PyObject *stack[2] = {e.l.k, e.l.row};
                    okey = PyObject_Vectorcall(id_fn, stack, 2, nullptr);
                }
                break;
            case ID_RIGHT_FN:
                if (e.r.kind == JR_PAD) {
                    PyErr_SetString(
                        PyExc_ValueError,
                        "join id= references the right side but an "
                        "outer/left join produced a row with no right match");
                    failed = true;
                } else {
                    PyObject *stack[2] = {e.r.k, e.r.row};
                    okey = PyObject_Vectorcall(id_fn, stack, 2, nullptr);
                }
                break;
            case ID_FROM_LEFT:
                if (e.l.kind != JR_PAD) {
                    okey = jref_key_py(e.l, store->ptr_type);
                    break;
                }
                goto pair_key;
            case ID_FROM_RIGHT:
                if (e.r.kind != JR_PAD) {
                    okey = jref_key_py(e.r, store->ptr_type);
                    break;
                }
                goto pair_key;
            default:
            pair_key:
                if (pair_key_fn != nullptr) {
                    /* vectorcall for the per-output-row key mint: at join
                     * fanouts this call count equals the OUTPUT size */
                    PyObject *lk = jref_key_py(e.l, store->ptr_type);
                    PyObject *rk =
                        lk != nullptr ? jref_key_py(e.r, store->ptr_type)
                                      : nullptr;
                    if (lk == nullptr || rk == nullptr) {
                        Py_XDECREF(lk);
                        failed = true;
                        break;
                    }
                    PyObject *stack[2] = {lk, rk};
                    okey = PyObject_Vectorcall(pair_key_fn, stack, 2,
                                               nullptr);
                    Py_DECREF(lk);
                    Py_DECREF(rk);
                } else {
                    /* native ref_scalar(lk, rk) mint (blake2b parity);
                     * tuple-rep sides surface their 128-bit key value */
                    unsigned __int128 lk128 = e.l.key128;
                    unsigned __int128 rk128 = e.r.key128;
                    bool ok = e.l.kind != JR_PY || nb_int128_of(e.l.k, &lk128);
                    ok = ok &&
                         (e.r.kind != JR_PY || nb_int128_of(e.r.k, &rk128));
                    if (!ok) {
                        PyErr_SetString(PyExc_TypeError,
                                        "join key is not a 128-bit int");
                        /* okey stays null: the shared cleanup below owns
                         * the row decref (exactly once) */
                        break;
                    }
                    okey = pointer_from_u128(
                        store->ptr_type,
                        mint_pair_key128(e.l.kind != JR_PAD, lk128,
                                         e.r.kind != JR_PAD, rk128));
                }
            }
            if (okey == nullptr) {
                Py_DECREF(row);
                failed = true;
                break;
            }
            PyObject *delta = PyTuple_New(3);
            PyObject *dobj = delta ? PyLong_FromLongLong(e.d) : nullptr;
            if (delta == nullptr || dobj == nullptr) {
                Py_XDECREF(delta);
                Py_DECREF(okey);
                Py_DECREF(row);
                failed = true;
                break;
            }
            PyTuple_SET_ITEM(delta, 0, okey);
            PyTuple_SET_ITEM(delta, 1, row);
            PyTuple_SET_ITEM(delta, 2, dobj);
            if (PyList_Append(out, delta) < 0) {
                Py_DECREF(delta);
                failed = true;
                break;
            }
            Py_DECREF(delta);
        }
    }
    if (failed) {
        Py_XDECREF(out);
        return nullptr;
    }
    return out;
}

PyObject *join_batch(PyObject *, PyObject *args)
{
    PyObject *capsule;
    PyObject *ljks, *lkeys, *lrows, *ldiffs;
    PyObject *rjks, *rkeys, *rrows, *rdiffs;
    PyObject *pair_key_fn, *id_fn;
    if (!PyArg_ParseTuple(args, "OOOOOOOOOOO", &capsule, &ljks, &lkeys,
                          &lrows, &ldiffs, &rjks, &rkeys, &rrows, &rdiffs,
                          &pair_key_fn, &id_fn))
        return nullptr;
    JoinStore *store = get_join_store(capsule);
    if (store == nullptr)
        return nullptr;
    const int W = store->n_shards;
    const bool lpads = store->jt == J_LEFT || store->jt == J_OUTER;
    const bool rpads = store->jt == J_RIGHT || store->jt == J_OUTER;

    /* phase 1: extract (GIL held; no state mutated — Fallback replayable) */
    auto _t0 = std::chrono::steady_clock::now();
    std::vector<JRowX> lx, rx;
    if (!extract_side(ljks, lkeys, lrows, ldiffs, W, lx) ||
        !extract_side(rjks, rkeys, rrows, rdiffs, W, rx))
        return nullptr;
    jphase_add(store, &PhaseStats::extract_s, _t0);
    store->phases.batches += 1;
    g_join_phases.batches += 1;
    store->phases.rows += (int64_t)(lx.size() + rx.size());
    g_join_phases.rows += (int64_t)(lx.size() + rx.size());
    auto _t1 = std::chrono::steady_clock::now();

    /* phase 2: apply + delta emission (GIL released) */
    std::vector<JShardOut> outs((size_t)W);
    {
        struct Aff {
            std::vector<int32_t> l, r;
        };
        std::vector<std::unordered_map<std::string, Aff>> touched((size_t)W);
        std::vector<std::vector<const std::string *>> order((size_t)W);
        for (size_t i = 0; i < lx.size(); i++) {
            auto &t = touched[lx[i].shard];
            auto it = t.find(lx[i].jk_bytes);
            if (it == t.end()) {
                it = t.emplace(lx[i].jk_bytes, Aff{}).first;
                order[lx[i].shard].push_back(&it->first);
            }
            it->second.l.push_back((int32_t)i);
        }
        for (size_t i = 0; i < rx.size(); i++) {
            auto &t = touched[rx[i].shard];
            auto it = t.find(rx[i].jk_bytes);
            if (it == t.end()) {
                it = t.emplace(rx[i].jk_bytes, Aff{}).first;
                order[rx[i].shard].push_back(&it->first);
            }
            it->second.r.push_back((int32_t)i);
        }

        auto work = [&](int w) {
            JShard &sh = store->shards[(size_t)w];
            JShardOut &o = outs[(size_t)w];
            std::vector<const JEntry *> ord; /* seq-ordered side view */
            for (const std::string *jkb : order[(size_t)w]) {
                Aff &aff = touched[(size_t)w][*jkb];
                auto git = sh.groups.find(*jkb);
                if (git == sh.groups.end()) {
                    git = sh.groups.emplace(*jkb, JGroup{}).first;
                    /* mint the group's jk ref from the first delta row */
                    PyObject *jk = aff.l.empty() ? rx[(size_t)aff.r[0]].jk
                                                 : lx[(size_t)aff.l[0]].jk;
                    git->second.jk = jk;
                    o.to_incref.push_back(jk);
                }
                JGroup &g = git->second;
                const bool llive0 = !g.left.empty();
                const bool rlive0 = !g.right.empty();
                JRef pad;

                /* ΔL × R_old */
                if (!aff.l.empty())
                    jside_ordered(g.right, ord);
                for (int32_t li : aff.l) {
                    const JRowX &dl = lx[(size_t)li];
                    JRef dref;
                    dref.kind = JR_PY;
                    dref.k = dl.key;
                    dref.row = dl.row;
                    for (const JEntry *e : ord)
                        o.emits.push_back(
                            JEmit{dref, jref_of_entry(*e),
                                  dl.diff * e->count});
                    if (lpads && !rlive0)
                        o.emits.push_back(JEmit{dref, pad, dl.diff});
                }
                for (int32_t li : aff.l)
                    japply(g.left, lx[(size_t)li], o, g.next_seq);

                /* L_new × ΔR */
                if (!aff.r.empty())
                    jside_ordered(g.left, ord);
                for (int32_t ri : aff.r) {
                    const JRowX &dr = rx[(size_t)ri];
                    JRef dref;
                    dref.kind = JR_PY;
                    dref.k = dr.key;
                    dref.row = dr.row;
                    for (const JEntry *e : ord)
                        o.emits.push_back(
                            JEmit{jref_of_entry(*e), dref,
                                  e->count * dr.diff});
                    if (rpads && !llive0)
                        o.emits.push_back(JEmit{pad, dref, dr.diff});
                }
                for (int32_t ri : aff.r)
                    japply(g.right, rx[(size_t)ri], o, g.next_seq);

                /* pad transitions: tracked pads now reflect (L1 vs Rlive0)
                 * and (R1 vs Llive0); correct for liveness flips */
                const bool llive1 = !g.left.empty();
                const bool rlive1 = !g.right.empty();
                if (lpads && rlive0 != rlive1) {
                    const int64_t sign = rlive1 ? -1 : 1;
                    /* right liveness can only flip via ΔR, so the L_new
                     * × ΔR block already left ord == ordered g.left
                     * (g.left untouched since); re-sort only if not */
                    if (aff.r.empty())
                        jside_ordered(g.left, ord);
                    for (const JEntry *e : ord)
                        o.emits.push_back(JEmit{jref_of_entry(*e), pad,
                                                sign * e->count});
                }
                if (rpads && llive0 != llive1) {
                    const int64_t sign = llive1 ? -1 : 1;
                    jside_ordered(g.right, ord);
                    for (const JEntry *e : ord)
                        o.emits.push_back(JEmit{pad, jref_of_entry(*e),
                                                sign * e->count});
                }
                if (g.left.empty() && g.right.empty()) {
                    if (g.jk != nullptr)
                        o.to_decref.push_back(g.jk);
                    sh.groups.erase(git);
                }
            }
        };

        size_t total = lx.size() + rx.size();
        Py_BEGIN_ALLOW_THREADS
        const uint64_t _tr0 = trace_on() ? trace_now_ns() : 0;
        if (W > 1 && total >= 2048) {
            std::vector<std::thread> threads;
            threads.reserve((size_t)W);
            for (int w = 0; w < W; w++)
                threads.emplace_back(
                    [&work](int ww) {
                        const uint64_t t0 =
                            trace_on() ? trace_now_ns() : 0;
                        work(ww);
                        if (t0)
                            trace_note(T_JOIN_APPLY, ww, t0,
                                       trace_now_ns(), -1);
                    },
                    w);
            for (auto &t : threads)
                t.join();
        } else {
            for (int w = 0; w < W; w++)
                work(w);
        }
        if (_tr0)
            trace_note(T_JOIN_APPLY, -1, _tr0, trace_now_ns(),
                       (int64_t)total);
        Py_END_ALLOW_THREADS
    }
    jphase_add(store, &PhaseStats::apply_s, _t1);
    auto _t2 = std::chrono::steady_clock::now();

    /* phase 3: refcounts + output materialization (GIL held) */
    for (auto &o : outs)
        for (PyObject *p : o.to_incref)
            Py_INCREF(p);

    PyObject *out = jemit_tuples(store, outs, pair_key_fn, id_fn);

    for (auto &o : outs)
        for (PyObject *p : o.to_decref)
            Py_DECREF(p);
    if (out == nullptr)
        return nullptr;
    jphase_add(store, &PhaseStats::emit_s, _t2);
    bool dup = false;
    for (auto &o : outs)
        dup = dup || o.dup_bump;
    PyObject *res = Py_BuildValue("(OO)", out, dup ? Py_True : Py_False);
    Py_DECREF(out);
    return res;
}

/* dump: [(jk, [(key,row,count) left], [(key,row,count) right])] —
 * native-rep entries (and nb-created group keys) materialize here, so
 * snapshots and Python-path demotion see ordinary picklable tuples
 * regardless of which path fed the store. */
PyObject *join_store_dump(PyObject *, PyObject *arg)
{
    JoinStore *s = get_join_store(arg);
    if (s == nullptr)
        return nullptr;
    PyObject *out = PyList_New(0);
    if (out == nullptr)
        return nullptr;
    auto dump_side = [s](std::unordered_map<std::string, JEntry> &side,
                         int width) -> PyObject * {
        PyObject *lst = PyList_New(0);
        if (lst == nullptr)
            return nullptr;
        /* insertion (seq) order: the Python MultisetState dicts this
         * feeds are insertion-ordered, and emission order after a
         * demotion must match what the native path produced */
        std::vector<const JEntry *> ord;
        jside_ordered(side, ord);
        for (const JEntry *ep : ord) {
            const JEntry &entry = *ep;
            PyObject *t;
            if (entry.cells) {
                PyObject *key =
                    pointer_from_u128(s->ptr_type, entry.key128);
                if (key == nullptr) {
                    Py_DECREF(lst);
                    return nullptr;
                }
                PyObject *row = packed_row_to_py(*entry.cells, width);
                if (row == nullptr) {
                    Py_DECREF(key);
                    Py_DECREF(lst);
                    return nullptr;
                }
                t = Py_BuildValue("(NNL)", key, row,
                                  (long long)entry.count);
            } else {
                t = Py_BuildValue("(OOL)", entry.key, entry.row,
                                  (long long)entry.count);
            }
            if (t == nullptr || PyList_Append(lst, t) < 0) {
                Py_XDECREF(t);
                Py_DECREF(lst);
                return nullptr;
            }
            Py_DECREF(t);
        }
        return lst;
    };
    for (auto &sh : s->shards) {
        for (auto &kv : sh.groups) {
            PyObject *jk = kv.second.jk;
            PyObject *jk_new = nullptr;
            if (jk == nullptr) {
                /* nb-created group: rebuild the join-key tuple from its
                 * packed key cells */
                const std::string &kc = kv.second.jk_cells;
                Py_ssize_t nk = 0;
                {
                    const char *p = kc.data();
                    const char *end = p + kc.size();
                    while (p < end) {
                        packed_skip_cell(p);
                        nk++;
                    }
                }
                jk_new = PyTuple_New(nk);
                if (jk_new == nullptr) {
                    Py_DECREF(out);
                    return nullptr;
                }
                const char *p = kc.data();
                for (Py_ssize_t j = 0; j < nk; j++) {
                    PyObject *v = packed_cell_to_py(p);
                    if (v == nullptr) {
                        Py_DECREF(jk_new);
                        Py_DECREF(out);
                        return nullptr;
                    }
                    PyTuple_SET_ITEM(jk_new, j, v);
                }
                jk = jk_new;
            }
            PyObject *l = dump_side(kv.second.left, s->lwidth);
            PyObject *r =
                l != nullptr ? dump_side(kv.second.right, s->rwidth)
                             : nullptr;
            PyObject *entry =
                r != nullptr ? Py_BuildValue("(ONN)", jk, l, r) : nullptr;
            Py_XDECREF(jk_new);
            if (entry == nullptr || PyList_Append(out, entry) < 0) {
                if (entry == nullptr && l != nullptr && r == nullptr)
                    Py_DECREF(l);
                Py_XDECREF(entry);
                Py_DECREF(out);
                return nullptr;
            }
            Py_DECREF(entry);
        }
    }
    return out;
}

PyObject *join_store_load(PyObject *, PyObject *args)
{
    PyObject *capsule, *entries;
    if (!PyArg_ParseTuple(args, "OO", &capsule, &entries))
        return nullptr;
    JoinStore *s = get_join_store(capsule);
    if (s == nullptr)
        return nullptr;
    SvHash hasher; /* one hasher everywhere: shard placement must agree across the nb and tuple paths */
    Py_ssize_t n = PyList_Size(entries);
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *entry = PyList_GET_ITEM(entries, i);
        PyObject *jk, *lside, *rside;
        if (!PyArg_ParseTuple(entry, "OOO", &jk, &lside, &rside))
            return nullptr;
        std::string jkb;
        if (!ser_gvals(jkb, jk)) {
            if (!PyErr_Occurred())
                PyErr_SetString(FallbackError,
                                "unsupported join value in snapshot");
            return nullptr;
        }
        JShard &sh = s->shards[hasher(jkb) % (size_t)s->n_shards];
        JGroup &g = sh.groups[jkb];
        if (g.jk == nullptr) {
            Py_INCREF(jk);
            g.jk = jk;
        }
        auto load_side =
            [](PyObject *lst, std::unordered_map<std::string, JEntry> &side,
               uint64_t &next_seq) -> bool {
            Py_ssize_t m = PyList_Size(lst);
            if (m < 0)
                return false;
            for (Py_ssize_t j = 0; j < m; j++) {
                PyObject *key, *row;
                long long count;
                if (!PyArg_ParseTuple(PyList_GET_ITEM(lst, j), "OOL", &key,
                                      &row, &count))
                    return false;
                std::string eb;
                if (!ser_entry(eb, key, row)) {
                    if (!PyErr_Occurred())
                        PyErr_SetString(FallbackError,
                                        "unsupported join value in snapshot");
                    return false;
                }
                JEntry ne;
                ne.key = key;
                ne.row = row;
                ne.count = count;
                ne.seq = next_seq++; /* dump order IS insertion order */
                auto ins = side.emplace(eb, std::move(ne));
                if (ins.second) {
                    Py_INCREF(key);
                    Py_INCREF(row);
                } else {
                    /* re-load into a non-empty store: merge counts */
                    ins.first->second.count += count;
                }
            }
            return true;
        };
        if (!load_side(lside, g.left, g.next_seq) ||
            !load_side(rside, g.right, g.next_seq))
            return nullptr;
    }
    Py_RETURN_NONE;
}

/* ---- WordPiece batch tokenizer --------------------------------------
 * The streaming-ingest hot loop (models/wordpiece.py): whitespace split,
 * per-word memo lookup and sequence assembly run in C; memo MISSES call
 * back into the Python tokenizer's exact `_word_ids` (normalization +
 * punctuation split + greedy longest-match), so token output is
 * byte-identical to the pure path. Texts containing non-ASCII bytes
 * return None (the Python path handles them — str.split() whitespace
 * semantics differ beyond ASCII). */

struct WpStore {
    std::unordered_map<std::string, std::vector<int32_t>> memo;
    size_t cap;
    std::mutex mu; /* serializes concurrent batch calls: the memo-hit
                    * phase runs with the GIL RELEASED, so the GIL no
                    * longer guards the map */
};

void wp_capsule_destructor(PyObject *capsule)
{
    delete static_cast<WpStore *>(
        PyCapsule_GetPointer(capsule, "pwexec.wp"));
}

WpStore *get_wp(PyObject *capsule)
{
    return static_cast<WpStore *>(PyCapsule_GetPointer(capsule, "pwexec.wp"));
}

PyObject *wp_new(PyObject *, PyObject *args)
{
    long long cap = 1000000;
    if (!PyArg_ParseTuple(args, "|L", &cap))
        return nullptr;
    auto *st = new WpStore();
    st->cap = (size_t)cap;
    return PyCapsule_New(st, "pwexec.wp", wp_capsule_destructor);
}

PyObject *wp_len(PyObject *, PyObject *capsule)
{
    WpStore *st = get_wp(capsule);
    if (st == nullptr)
        return nullptr;
    return PyLong_FromSsize_t((Py_ssize_t)st->memo.size());
}

inline bool wp_is_ws(unsigned char c)
{
    /* str.split() whitespace within ASCII: space, \t-\r, \x1c-\x1f */
    return c == ' ' || (c >= 0x09 && c <= 0x0d) || (c >= 0x1c && c <= 0x1f);
}

/* wp_tokenize(store, texts, budget, cls, sep, fallback) ->
 *   list of bytes (int32 token ids incl. cls/sep, truncated) | None
 *   (None = text has non-ASCII bytes: caller uses the Python path) */
PyObject *wp_tokenize(PyObject *, PyObject *args)
{
    PyObject *capsule, *texts, *fallback;
    long long budget, cls_id, sep_id;
    if (!PyArg_ParseTuple(args, "OOLLLO", &capsule, &texts, &budget,
                          &cls_id, &sep_id, &fallback))
        return nullptr;
    WpStore *st = get_wp(capsule);
    if (st == nullptr)
        return nullptr;
    PyObject *seq = PySequence_Fast(texts, "wp_tokenize expects a sequence");
    if (seq == nullptr)
        return nullptr;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    PyObject *out = PyList_New(n);
    if (out == nullptr) {
        Py_DECREF(seq);
        return nullptr;
    }
    /* phase A (GIL held): pin the UTF-8 views */
    std::vector<const char *> tptr((size_t)n);
    std::vector<Py_ssize_t> tlen((size_t)n);
    for (Py_ssize_t i = 0; i < n; i++) {
        tptr[(size_t)i] = PyUnicode_AsUTF8AndSize(
            PySequence_Fast_GET_ITEM(seq, i), &tlen[(size_t)i]);
        if (tptr[(size_t)i] == nullptr) {
            Py_DECREF(out);
            Py_DECREF(seq);
            return nullptr;
        }
    }
    /* phase B (GIL RELEASED): memo-only tokenization. After warmup every
     * word hits the memo and this is the whole batch — the tokenize-ahead
     * thread genuinely overlaps device dispatch on multi-core hosts.
     * Texts with a miss or non-ASCII bytes are deferred to phase C. */
    std::vector<int32_t> flat;
    flat.reserve((size_t)n * 128);
    std::vector<size_t> fstart((size_t)n + 1, 0);
    std::vector<uint8_t> deferred((size_t)n, 0);
    std::vector<uint8_t> non_ascii((size_t)n, 0);
    {
        /* lock ordering: NEVER wait on the store mutex while holding the
         * GIL (another thread may hold the mutex and need the GIL for its
         * fallback phase). The mutex is taken inside the allow-threads
         * region; phase C reacquires the GIL while still holding it. */
        std::unique_lock<std::mutex> guard(st->mu, std::defer_lock);
        Py_BEGIN_ALLOW_THREADS
        guard.lock();
        std::string word;
        for (Py_ssize_t i = 0; i < n; i++) {
            const char *t = tptr[(size_t)i];
            const Py_ssize_t len = tlen[(size_t)i];
            bool ascii = true;
            for (Py_ssize_t j = 0; j < len; j++)
                if ((unsigned char)t[j] >= 0x80) {
                    ascii = false;
                    break;
                }
            fstart[(size_t)i] = flat.size();
            if (!ascii) {
                non_ascii[(size_t)i] = 1;
                continue;
            }
            const size_t base = flat.size();
            flat.push_back((int32_t)cls_id);
            Py_ssize_t j = 0;
            bool missed = false;
            while (j < len) {
                while (j < len && wp_is_ws((unsigned char)t[j]))
                    j++;
                Py_ssize_t ws = j;
                while (j < len && !wp_is_ws((unsigned char)t[j]))
                    j++;
                if (j == ws)
                    break;
                if ((long long)(flat.size() - base) - 1 >= budget)
                    break;
                word.assign(t + ws, (size_t)(j - ws));
                auto it = st->memo.find(word);
                if (it == st->memo.end()) {
                    missed = true;
                    break;
                }
                flat.insert(flat.end(), it->second.begin(),
                            it->second.end());
            }
            if (missed) {
                deferred[(size_t)i] = 1;
                flat.resize(base);
                continue;
            }
            if ((long long)(flat.size() - base) > budget + 1)
                flat.resize(base + (size_t)(budget + 1));
            flat.push_back((int32_t)sep_id);
        }
        fstart[(size_t)n] = flat.size();
        Py_END_ALLOW_THREADS
        /* phase C (GIL held, store still locked): texts with misses run
         * the fallback-calling loop; non-ASCII texts yield None */
        std::vector<int32_t> ids;
        std::string word;
        for (Py_ssize_t i = 0; i < n; i++) {
            if (non_ascii[(size_t)i]) {
                Py_INCREF(Py_None);
                PyList_SET_ITEM(out, i, Py_None);
                continue;
            }
            if (!deferred[(size_t)i]) {
                /* fstart is monotone: deferred/non-ascii texts occupy an
                 * empty span (their flat writes were rolled back) */
                const size_t lo = fstart[(size_t)i];
                const size_t hi = fstart[(size_t)i + 1];
                PyObject *b = PyBytes_FromStringAndSize(
                    reinterpret_cast<const char *>(flat.data() + lo),
                    (Py_ssize_t)((hi - lo) * sizeof(int32_t)));
                if (b == nullptr)
                    goto fail;
                PyList_SET_ITEM(out, i, b);
                continue;
            }
            const char *t = tptr[(size_t)i];
            const Py_ssize_t len = tlen[(size_t)i];
            ids.clear();
            ids.push_back((int32_t)cls_id);
            Py_ssize_t j = 0;
            while (j < len) {
                while (j < len && wp_is_ws((unsigned char)t[j]))
                    j++;
                Py_ssize_t ws = j;
                while (j < len && !wp_is_ws((unsigned char)t[j]))
                    j++;
                if (j == ws)
                    break;
                if ((long long)ids.size() - 1 >= budget)
                    break;
                word.assign(t + ws, (size_t)(j - ws));
                auto it = st->memo.find(word);
                if (it == st->memo.end()) {
                    /* memo miss: exact Python tokenization of this word */
                    PyObject *w = PyUnicode_FromStringAndSize(
                        t + ws, j - ws);
                    if (w == nullptr)
                        goto fail;
                    PyObject *res = PyObject_CallOneArg(fallback, w);
                    Py_DECREF(w);
                    if (res == nullptr)
                        goto fail;
                    PyObject *rseq = PySequence_Fast(
                        res, "fallback must return a sequence");
                    Py_DECREF(res);
                    if (rseq == nullptr)
                        goto fail;
                    std::vector<int32_t> wids;
                    Py_ssize_t m = PySequence_Fast_GET_SIZE(rseq);
                    wids.reserve((size_t)m);
                    for (Py_ssize_t q = 0; q < m; q++) {
                        long v = PyLong_AsLong(
                            PySequence_Fast_GET_ITEM(rseq, q));
                        if (v == -1 && PyErr_Occurred()) {
                            Py_DECREF(rseq);
                            goto fail;
                        }
                        wids.push_back((int32_t)v);
                    }
                    Py_DECREF(rseq);
                    if (st->memo.size() < st->cap)
                        it = st->memo.emplace(word, std::move(wids)).first;
                    else {
                        ids.insert(ids.end(), wids.begin(), wids.end());
                        continue;
                    }
                }
                ids.insert(ids.end(), it->second.begin(), it->second.end());
            }
            if ((long long)ids.size() > budget + 1)
                ids.resize((size_t)(budget + 1));
            ids.push_back((int32_t)sep_id);
            PyObject *b = PyBytes_FromStringAndSize(
                reinterpret_cast<const char *>(ids.data()),
                (Py_ssize_t)(ids.size() * sizeof(int32_t)));
            if (b == nullptr)
                goto fail;
            PyList_SET_ITEM(out, i, b);
        }
    }
    Py_DECREF(seq);
    return out;
fail:
    Py_DECREF(out);
    Py_DECREF(seq);
    return nullptr;
}

/* wp_tokenize_padded(store, texts, budget, cls, sep, pad, fallback) ->
 *   (ids_bytes, mask_bytes, n, longest) — one padded int32 buffer pair
 *   for the whole batch — or None when any text has non-ASCII bytes
 *   (caller falls back to the per-row route). */
PyObject *wp_tokenize_padded(PyObject *, PyObject *args)
{
    PyObject *capsule, *texts, *fallback;
    long long budget, cls_id, sep_id, pad_id;
    if (!PyArg_ParseTuple(args, "OOLLLLO", &capsule, &texts, &budget,
                          &cls_id, &sep_id, &pad_id, &fallback))
        return nullptr;
    /* reuse wp_tokenize for the per-text id vectors */
    PyObject *sub_args = Py_BuildValue(
        "(OOLLLO)", capsule, texts, budget, cls_id, sep_id, fallback);
    if (sub_args == nullptr)
        return nullptr;
    PyObject *rows = wp_tokenize(nullptr, sub_args);
    Py_DECREF(sub_args);
    if (rows == nullptr)
        return nullptr;
    Py_ssize_t n = PyList_GET_SIZE(rows);
    Py_ssize_t longest = 1;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *r = PyList_GET_ITEM(rows, i);
        if (r == Py_None) {
            Py_DECREF(rows);
            Py_RETURN_NONE;
        }
        Py_ssize_t m = PyBytes_GET_SIZE(r) / (Py_ssize_t)sizeof(int32_t);
        if (m > longest)
            longest = m;
    }
    PyObject *ids_b = PyBytes_FromStringAndSize(
        nullptr, n * longest * (Py_ssize_t)sizeof(int32_t));
    PyObject *mask_b = PyBytes_FromStringAndSize(
        nullptr, n * longest * (Py_ssize_t)sizeof(int32_t));
    if (ids_b == nullptr || mask_b == nullptr) {
        Py_XDECREF(ids_b);
        Py_XDECREF(mask_b);
        Py_DECREF(rows);
        return nullptr;
    }
    auto *ids = reinterpret_cast<int32_t *>(PyBytes_AS_STRING(ids_b));
    auto *mask = reinterpret_cast<int32_t *>(PyBytes_AS_STRING(mask_b));
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *r = PyList_GET_ITEM(rows, i);
        const auto *src =
            reinterpret_cast<const int32_t *>(PyBytes_AS_STRING(r));
        Py_ssize_t m = PyBytes_GET_SIZE(r) / (Py_ssize_t)sizeof(int32_t);
        int32_t *row_ids = ids + i * longest;
        int32_t *row_mask = mask + i * longest;
        for (Py_ssize_t j = 0; j < m; j++) {
            row_ids[j] = src[j];
            row_mask[j] = 1;
        }
        for (Py_ssize_t j = m; j < longest; j++) {
            row_ids[j] = (int32_t)pad_id;
            row_mask[j] = 0;
        }
    }
    Py_DECREF(rows);
    PyObject *out = Py_BuildValue("(OOnn)", ids_b, mask_b, n, longest);
    Py_DECREF(ids_b);
    Py_DECREF(mask_b);
    return out;
}

/* ==== NativeBatch: columnar zero-Python delta batch ====================
 *
 * The reference's steady-state hot loop is entirely native — every
 * operator runs under worker.step_or_park with no interpreter dispatch
 * (reference: src/engine/dataflow.rs:5595-5650 on the timely substrate).
 * The NativeBatch is this engine's equivalent: a C-owned columnar image
 * of one insert-only delta batch (tags + unboxed scalars + string arena,
 * 128-bit keys) produced directly by the connector parser and consumed
 * directly by the sharded group-by executor, so a parse→groupby chain
 * moves rows from ingest to reducer state without materializing ONE
 * per-row Python object. Non-native consumers see a normal sequence:
 * len()/iteration/indexing materialize (once, cached) into the familiar
 * [(key, row, +1), ...] form and the batch degrades gracefully at any
 * chain boundary (UDFs, temporal gates, exchanges, journals). */

/* NbTag lives up top (packed-cell helpers reuse it for the join store's
 * columnar entries). */

struct NbCol {
    std::vector<uint8_t> tag;
    /* int value, double bits, or arena byte offset (by tag) */
    std::vector<int64_t> word;
    std::vector<uint32_t> len; /* NB_STR: byte length */
    std::string arena;
};

typedef struct {
    PyObject_HEAD
    Py_ssize_t n;
    int width;
    std::vector<unsigned __int128> *keys;
    std::vector<NbCol> *cols;
    PyObject *ptr_type; /* owned: Pointer class for materialization */
    PyObject *mat;      /* owned: cached materialized delta list */
} NativeBatchObject;

extern PyTypeObject NativeBatchType; /* defined after the slot fns */

void nb_dealloc(PyObject *self)
{
    auto *nb = reinterpret_cast<NativeBatchObject *>(self);
    delete nb->keys;
    delete nb->cols;
    Py_XDECREF(nb->ptr_type);
    Py_XDECREF(nb->mat);
    Py_TYPE(self)->tp_free(self);
}

NativeBatchObject *nb_alloc(int width, PyObject *ptr_type)
{
    auto *nb = PyObject_New(NativeBatchObject, &NativeBatchType);
    if (nb == nullptr)
        return nullptr;
    nb->n = 0;
    nb->width = width;
    nb->keys = new std::vector<unsigned __int128>();
    nb->cols = new std::vector<NbCol>((size_t)width);
    Py_XINCREF(ptr_type);
    nb->ptr_type = ptr_type;
    nb->mat = nullptr;
    return nb;
}

Py_ssize_t nb_length(PyObject *self)
{
    return reinterpret_cast<NativeBatchObject *>(self)->n;
}

/* one cell -> new Python value */
PyObject *nb_cell_to_py(const NbCol &c, Py_ssize_t i)
{
    switch (c.tag[(size_t)i]) {
    case NB_NONE:
        Py_RETURN_NONE;
    case NB_BOOL:
        if (c.word[(size_t)i])
            Py_RETURN_TRUE;
        Py_RETURN_FALSE;
    case NB_INT:
        return PyLong_FromLongLong((long long)c.word[(size_t)i]);
    case NB_FLT: {
        double d;
        int64_t w = c.word[(size_t)i];
        memcpy(&d, &w, 8);
        return PyFloat_FromDouble(d);
    }
    default: /* NB_STR */
        return PyUnicode_FromStringAndSize(
            c.arena.data() + (size_t)c.word[(size_t)i],
            (Py_ssize_t)c.len[(size_t)i]);
    }
}

PyObject *nb_key_to_py(const NativeBatchObject *nb, Py_ssize_t i)
{
    unsigned char buf[16];
    unsigned __int128 k = (*nb->keys)[(size_t)i];
    memcpy(buf, &k, 16); /* little-endian on every supported target */
    PyObject *raw = _PyLong_FromByteArray(buf, 16, 1, 0);
    if (raw == nullptr)
        return nullptr;
    if (nb->ptr_type == nullptr || nb->ptr_type == Py_None)
        return raw;
    PyObject *key = PyObject_CallOneArg(nb->ptr_type, raw);
    Py_DECREF(raw);
    return key;
}

/* materialize() -> [(Pointer, row_tuple, 1), ...], cached. */
PyObject *nb_materialize_impl(NativeBatchObject *nb)
{
    if (nb->mat != nullptr) {
        Py_INCREF(nb->mat);
        return nb->mat;
    }
    PyObject *out = PyList_New(nb->n);
    if (out == nullptr)
        return nullptr;
    PyObject *one = PyLong_FromLong(1);
    for (Py_ssize_t i = 0; i < nb->n; i++) {
        PyObject *key = nb_key_to_py(nb, i);
        if (key == nullptr)
            goto fail;
        PyObject *row = PyTuple_New(nb->width);
        if (row == nullptr) {
            Py_DECREF(key);
            goto fail;
        }
        for (int c = 0; c < nb->width; c++) {
            PyObject *v = nb_cell_to_py((*nb->cols)[(size_t)c], i);
            if (v == nullptr) {
                Py_DECREF(key);
                Py_DECREF(row);
                goto fail;
            }
            PyTuple_SET_ITEM(row, c, v);
        }
        PyObject *t = PyTuple_New(3);
        if (t == nullptr) {
            Py_DECREF(key);
            Py_DECREF(row);
            goto fail;
        }
        PyTuple_SET_ITEM(t, 0, key);
        PyTuple_SET_ITEM(t, 1, row);
        Py_INCREF(one);
        PyTuple_SET_ITEM(t, 2, one);
        PyList_SET_ITEM(out, i, t);
    }
    Py_DECREF(one);
    nb->mat = out;
    Py_INCREF(out);
    return out;
fail:
    Py_DECREF(one);
    Py_DECREF(out);
    return nullptr;
}

PyObject *nb_materialize(PyObject *self, PyObject *)
{
    return nb_materialize_impl(reinterpret_cast<NativeBatchObject *>(self));
}

PyObject *nb_item(PyObject *self, Py_ssize_t i)
{
    auto *nb = reinterpret_cast<NativeBatchObject *>(self);
    if (i < 0 || i >= nb->n) {
        PyErr_SetString(PyExc_IndexError, "NativeBatch index out of range");
        return nullptr;
    }
    PyObject *mat = nb_materialize_impl(nb);
    if (mat == nullptr)
        return nullptr;
    PyObject *item = PyList_GET_ITEM(mat, i);
    Py_INCREF(item);
    Py_DECREF(mat);
    return item;
}

PyObject *nb_iter(PyObject *self)
{
    PyObject *mat =
        nb_materialize_impl(reinterpret_cast<NativeBatchObject *>(self));
    if (mat == nullptr)
        return nullptr;
    PyObject *it = PyObject_GetIter(mat);
    Py_DECREF(mat);
    return it;
}

PyObject *nb_width(PyObject *self, PyObject *)
{
    return PyLong_FromLong(
        reinterpret_cast<NativeBatchObject *>(self)->width);
}

PyMethodDef nb_methods[] = {
    {"materialize", nb_materialize, METH_NOARGS,
     "materialize() -> [(key, row, 1), ...] (cached)"},
    {"width", nb_width, METH_NOARGS,
     "width() -> number of value columns (no materialization)"},
    {nullptr, nullptr, 0, nullptr},
};

PySequenceMethods nb_as_sequence = {
    nb_length,  /* sq_length */
    nullptr,    /* sq_concat */
    nullptr,    /* sq_repeat */
    nb_item,    /* sq_item */
    nullptr, nullptr, nullptr, nullptr, nullptr, nullptr,
};

PyTypeObject NativeBatchType = {
    PyVarObject_HEAD_INIT(nullptr, 0)
    "pwexec.NativeBatch",            /* tp_name */
    sizeof(NativeBatchObject),       /* tp_basicsize */
    0,                               /* tp_itemsize */
    nb_dealloc,                      /* tp_dealloc */
    0,                               /* tp_vectorcall_offset */
    nullptr,                         /* tp_getattr */
    nullptr,                         /* tp_setattr */
    nullptr,                         /* tp_as_async */
    nullptr,                         /* tp_repr */
    nullptr,                         /* tp_as_number */
    &nb_as_sequence,                 /* tp_as_sequence */
    nullptr,                         /* tp_as_mapping */
    nullptr,                         /* tp_hash */
    nullptr,                         /* tp_call */
    nullptr,                         /* tp_str */
    nullptr,                         /* tp_getattro */
    nullptr,                         /* tp_setattro */
    nullptr,                         /* tp_as_buffer */
    Py_TPFLAGS_DEFAULT,              /* tp_flags */
    "Columnar zero-Python delta batch (insert-only, net form).",
    nullptr,                         /* tp_traverse */
    nullptr,                         /* tp_clear */
    nullptr,                         /* tp_richcompare */
    0,                               /* tp_weaklistoffset */
    nb_iter,                         /* tp_iter */
    nullptr,                         /* tp_iternext */
    nb_methods,                      /* tp_methods */
};

/* value conversion helpers for parse ---------------------------------- */

/* convert one Python value into cell `i` of `c`; false = unsupported
 * type (caller falls back to the tuple parser — NOT an error).
 * EXACT type checks only: int/float/str subclasses (IntEnum, Pointer,
 * tagged strings) must keep their identity through the engine, which
 * only the object-preserving tuple path provides. */
bool nb_put(NbCol &c, PyObject *v)
{
    if (v == Py_None) {
        c.tag.push_back(NB_NONE);
        c.word.push_back(0);
        c.len.push_back(0);
        return true;
    }
    if (PyBool_Check(v)) { /* bool is final: no subclass concern */
        c.tag.push_back(NB_BOOL);
        c.word.push_back(v == Py_True ? 1 : 0);
        c.len.push_back(0);
        return true;
    }
    if (PyLong_CheckExact(v)) {
        int ovf = 0;
        long long i = PyLong_AsLongLongAndOverflow(v, &ovf);
        if (ovf)
            return false; /* beyond i64 */
        c.tag.push_back(NB_INT);
        c.word.push_back((int64_t)i);
        c.len.push_back(0);
        return true;
    }
    if (PyFloat_CheckExact(v)) {
        double d = PyFloat_AS_DOUBLE(v);
        int64_t w;
        memcpy(&w, &d, 8);
        c.tag.push_back(NB_FLT);
        c.word.push_back(w);
        c.len.push_back(0);
        return true;
    }
    if (PyUnicode_CheckExact(v)) {
        Py_ssize_t sl;
        const char *sp = PyUnicode_AsUTF8AndSize(v, &sl);
        if (sp == nullptr) {
            PyErr_Clear();
            return false; /* surrogate-escaped: tuple path handles it */
        }
        c.tag.push_back(NB_STR);
        c.word.push_back((int64_t)c.arena.size());
        c.len.push_back((uint32_t)sl);
        c.arena.append(sp, (size_t)sl);
        return true;
    }
    return false; /* bytes/tuples/ndarrays/Json/subclasses: tuple path */
}

/* parse_upserts_nb(msgs, start, cols, defaults, key_base, seq0, ptr_type)
 *   Columnar variant of fastpath.parse_upserts: builds a NativeBatch
 *   instead of per-row Python tuples. Keys are (key_base + seq) mod
 *   2^128 — identical to the tuple parser's (key_base + seq) & _KEY_MASK.
 *   Returns (NativeBatch, new_seq), or None when any value's type is
 *   outside the columnar set (caller re-parses via the tuple path). */
PyObject *parse_upserts_nb(PyObject *, PyObject *args)
{
    PyObject *msgs, *cols, *defaults, *key_base_obj, *ptr_type;
    Py_ssize_t start;
    long long seq0;
    if (!PyArg_ParseTuple(args, "OnO!O!OLO", &msgs, &start, &PyTuple_Type,
                          &cols, &PyTuple_Type, &defaults, &key_base_obj,
                          &seq0, &ptr_type))
        return nullptr;
    unsigned __int128 base;
    if (!nb_int128_of(key_base_obj, &base))
        Py_RETURN_NONE;
    PyObject *seq = PySequence_Fast(msgs, "parse_upserts_nb: sequence");
    if (seq == nullptr)
        return nullptr;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    Py_ssize_t w = PyTuple_GET_SIZE(cols);
    if (PyTuple_GET_SIZE(defaults) != w) {
        Py_DECREF(seq);
        PyErr_SetString(PyExc_ValueError, "parse_upserts_nb: widths");
        return nullptr;
    }
    NativeBatchObject *nb = nb_alloc((int)w, ptr_type);
    if (nb == nullptr) {
        Py_DECREF(seq);
        return nullptr;
    }
    Py_ssize_t nrows = n - start;
    nb->keys->reserve((size_t)nrows);
    for (Py_ssize_t c = 0; c < w; c++) {
        (*nb->cols)[(size_t)c].tag.reserve((size_t)nrows);
        (*nb->cols)[(size_t)c].word.reserve((size_t)nrows);
        (*nb->cols)[(size_t)c].len.reserve((size_t)nrows);
    }
    unsigned long long sq = (unsigned long long)seq0;
    for (Py_ssize_t i = start; i < n; i++) {
        PyObject *values = PySequence_Fast_GET_ITEM(seq, i);
        if (!PyDict_Check(values))
            goto fallback;
        for (Py_ssize_t c = 0; c < w; c++) {
            PyObject *v = PyDict_GetItemWithError(
                values, PyTuple_GET_ITEM(cols, c));
            if (v == nullptr) {
                if (PyErr_Occurred())
                    PyErr_Clear();
                v = PyTuple_GET_ITEM(defaults, c);
            }
            if (!nb_put((*nb->cols)[(size_t)c], v))
                goto fallback;
        }
        sq += 1;
        nb->keys->push_back(base + (unsigned __int128)sq);
    }
    /* column lengths can differ mid-row on fallback only, never here */
    nb->n = (Py_ssize_t)nb->keys->size();
    Py_DECREF(seq);
    {
        PyObject *res =
            Py_BuildValue("(OL)", (PyObject *)nb, (long long)sq);
        Py_DECREF(nb);
        return res;
    }
fallback:
    Py_DECREF(nb);
    Py_DECREF(seq);
    Py_RETURN_NONE;
}

/* ser_cell: mirror ser_value's normalization (bools and integral floats
 * collapse onto ints) so a store fed nb batches and tuple batches lands
 * identical rows in identical groups */
inline void nb_ser_cell(std::string &out, const NbCol &c, Py_ssize_t i)
{
    switch (c.tag[(size_t)i]) {
    case NB_NONE:
        out.push_back('\x01');
        return;
    case NB_BOOL:
    case NB_INT: {
        int64_t v = c.word[(size_t)i];
        out.push_back('I');
        out.append(reinterpret_cast<const char *>(&v), 8);
        return;
    }
    case NB_FLT: {
        double d;
        int64_t w = c.word[(size_t)i];
        memcpy(&d, &w, 8);
        if (d == (double)(int64_t)d && d >= -9.2e18 && d <= 9.2e18) {
            int64_t iv = (int64_t)d;
            out.push_back('I');
            out.append(reinterpret_cast<const char *>(&iv), 8);
            return;
        }
        out.push_back('F');
        out.append(reinterpret_cast<const char *>(&d), 8);
        return;
    }
    default: { /* NB_STR */
        uint32_t len = c.len[(size_t)i];
        out.push_back('S');
        out.append(reinterpret_cast<const char *>(&len), 4);
        out.append(c.arena.data() + (size_t)c.word[(size_t)i], len);
        return;
    }
    }
}

/* ---- columnar pack/append helpers (fused join + pk parse) ------------ */

/* faithful packed copy of one nb cell (tag + payload) */
inline void pack_cell_from_nb(std::string &out, const NbCol &c, Py_ssize_t i)
{
    uint8_t tag = c.tag[(size_t)i];
    out.push_back((char)tag);
    switch (tag) {
    case NB_NONE:
        return;
    case NB_STR: {
        uint32_t len = c.len[(size_t)i];
        out.append(reinterpret_cast<const char *>(&len), 4);
        out.append(c.arena.data() + (size_t)c.word[(size_t)i], len);
        return;
    }
    default:
        out.append(reinterpret_cast<const char *>(&c.word[(size_t)i]), 8);
        return;
    }
}

/* append one packed row image (or width Nones when cells == nullptr)
 * into output columns [base, base+width) — GIL-free */
inline void append_packed_cells(std::vector<NbCol> &cols, int base,
                                int width, const std::string *cells)
{
    if (cells == nullptr) {
        for (int j = 0; j < width; j++) {
            NbCol &c = cols[(size_t)(base + j)];
            c.tag.push_back(NB_NONE);
            c.word.push_back(0);
            c.len.push_back(0);
        }
        return;
    }
    const char *p = cells->data();
    for (int j = 0; j < width; j++) {
        NbCol &c = cols[(size_t)(base + j)];
        uint8_t tag = (uint8_t)*p++;
        switch (tag) {
        case NB_NONE:
            c.tag.push_back(NB_NONE);
            c.word.push_back(0);
            c.len.push_back(0);
            break;
        case NB_STR: {
            uint32_t len;
            memcpy(&len, p, 4);
            p += 4;
            c.tag.push_back(NB_STR);
            c.word.push_back((int64_t)c.arena.size());
            c.len.push_back(len);
            c.arena.append(p, len);
            p += len;
            break;
        }
        default: {
            int64_t w;
            memcpy(&w, p, 8);
            p += 8;
            c.tag.push_back(tag);
            c.word.push_back(w);
            c.len.push_back(0);
            break;
        }
        }
    }
}

/* concatenate one column into another (arena offsets re-based) */
inline void nbcol_append(NbCol &dst, const NbCol &src)
{
    const int64_t base = (int64_t)dst.arena.size();
    size_t n0 = dst.tag.size();
    dst.tag.insert(dst.tag.end(), src.tag.begin(), src.tag.end());
    dst.word.insert(dst.word.end(), src.word.begin(), src.word.end());
    dst.len.insert(dst.len.end(), src.len.begin(), src.len.end());
    dst.arena.append(src.arena);
    for (size_t i = n0; i < dst.tag.size(); i++)
        if (dst.tag[i] == NB_STR)
            dst.word[i] += base;
}

/* ==== join_batch_nb: the fused join step ===============================
 *
 * One C call takes a columnar NativeBatch (either or both sides) through
 * the delta join with zero per-row Python objects: join keys and entry
 * identities serialize straight from the columns (byte-identical to the
 * tuple path, so nb- and tuple-fed batches share one store), apply runs
 * GIL-free and shard-parallel, and when every output row is a +1 over
 * native-rep entries the OUTPUT is built as a NativeBatch too — pair
 * keys minted by the in-process blake2b (ref_scalar parity) — so a
 * downstream fused consumer (exprs/filter projection, group-by, capture)
 * stays in C. Anything the columnar form cannot express (multiplicity
 * bumps, pad-transition retractions, tuple-rep store entries) falls back
 * to materialized (key, row, diff) output for THAT batch only.
 *
 * Replay invariant (mirrors process_batch/join_batch): no Fallback
 * beyond phase 1. Phase 1 mutates nothing, so a Fallback there is
 * replayable via the tuple path; any later error leaves the batch
 * half-applied and the CALLER must demote the node instead of replaying
 * (JoinNode._poison_demote). */

/* extracted nb row for one side */
struct JRowNb {
    uint32_t shard;
    uint32_t row; /* index into the source nb */
    std::string jk_bytes;
    std::string entry_bytes;
    std::shared_ptr<const std::string> cells;
    unsigned __int128 key128;
};

inline void japply_nb(std::unordered_map<std::string, JEntry> &side,
                      const JRowNb &r, JShardOut &o, uint64_t &next_seq)
{
    auto it = side.find(r.entry_bytes);
    if (it == side.end()) {
        JEntry e;
        e.key128 = r.key128;
        e.cells = r.cells;
        e.count = 1;
        e.seq = next_seq++;
        side.emplace(r.entry_bytes, std::move(e));
    } else {
        if (it->second.count > 0)
            o.dup_bump = true; /* nb deltas are always +1 */
        it->second.count += 1;
    }
}

bool extract_side_nb(NativeBatchObject *nb, const std::vector<int> &kidx,
                     int W, std::vector<JRowNb> &out)
{
    if (nb == nullptr)
        return true;
    const Py_ssize_t n = nb->n;
    out.resize((size_t)n);
    SvHash hasher; /* one hasher everywhere: shard placement must agree
                      across the nb and tuple paths */
    const int width = nb->width;
    for (Py_ssize_t i = 0; i < n; i++) {
        JRowNb &r = out[(size_t)i];
        r.row = (uint32_t)i;
        r.key128 = (*nb->keys)[(size_t)i];
        uint32_t nk = (uint32_t)kidx.size();
        r.jk_bytes.append(reinterpret_cast<const char *>(&nk), 4);
        for (int j : kidx)
            nb_ser_cell(r.jk_bytes, (*nb->cols)[(size_t)j], i);
        ser_key128(r.entry_bytes, r.key128);
        uint32_t uw = (uint32_t)width;
        r.entry_bytes.append(reinterpret_cast<const char *>(&uw), 4);
        for (int c = 0; c < width; c++)
            nb_ser_cell(r.entry_bytes, (*nb->cols)[(size_t)c], i);
        auto cells = std::make_shared<std::string>();
        cells->reserve((size_t)width * 9);
        for (int c = 0; c < width; c++)
            pack_cell_from_nb(*cells, (*nb->cols)[(size_t)c], i);
        r.cells = std::move(cells);
        r.shard = (uint32_t)(hasher(r.jk_bytes) % (size_t)W);
    }
    return true;
}

/* join_batch_nb(store, lnb_or_None, rnb_or_None, lkidx, rkidx, ptr_type)
 * -> NativeBatch (fully fused) | (deltas_list, dup_bump) */
PyObject *join_batch_nb(PyObject *, PyObject *args)
{
    PyObject *capsule, *lnb_obj, *rnb_obj, *lkidx_t, *rkidx_t, *ptr_type;
    if (!PyArg_ParseTuple(args, "OOOO!O!O", &capsule, &lnb_obj, &rnb_obj,
                          &PyTuple_Type, &lkidx_t, &PyTuple_Type, &rkidx_t,
                          &ptr_type))
        return nullptr;
    JoinStore *store = get_join_store(capsule);
    if (store == nullptr)
        return nullptr;
    if (store->id_mode == ID_LEFT_FN || store->id_mode == ID_RIGHT_FN) {
        /* per-row Python id fns cannot run in the fused path; nothing is
         * mutated yet, so this Fallback is replayable via the tuple path */
        PyErr_SetString(FallbackError, "nb join path with id= fn");
        return nullptr;
    }
    NativeBatchObject *lnb =
        lnb_obj == Py_None ? nullptr
                           : reinterpret_cast<NativeBatchObject *>(lnb_obj);
    NativeBatchObject *rnb =
        rnb_obj == Py_None ? nullptr
                           : reinterpret_cast<NativeBatchObject *>(rnb_obj);
    if ((lnb_obj != Py_None && Py_TYPE(lnb_obj) != &NativeBatchType) ||
        (rnb_obj != Py_None && Py_TYPE(rnb_obj) != &NativeBatchType)) {
        PyErr_SetString(PyExc_TypeError, "join_batch_nb: NativeBatch sides");
        return nullptr;
    }
    if ((lnb != nullptr && lnb->width != store->lwidth) ||
        (rnb != nullptr && rnb->width != store->rwidth)) {
        PyErr_SetString(PyExc_ValueError, "join_batch_nb: width mismatch");
        return nullptr;
    }
    auto idx_vec = [](PyObject *t, int width,
                      std::vector<int> &out) -> bool {
        Py_ssize_t n = PyTuple_GET_SIZE(t);
        out.resize((size_t)n);
        for (Py_ssize_t j = 0; j < n; j++) {
            long v = PyLong_AsLong(PyTuple_GET_ITEM(t, j));
            if (v < 0 || v >= width) {
                PyErr_SetString(PyExc_ValueError, "join_batch_nb: key idx");
                return false;
            }
            out[(size_t)j] = (int)v;
        }
        return true;
    };
    std::vector<int> lkidx, rkidx;
    if (!idx_vec(lkidx_t, store->lwidth, lkidx) ||
        !idx_vec(rkidx_t, store->rwidth, rkidx))
        return nullptr;
    if (store->ptr_type == nullptr && ptr_type != Py_None) {
        Py_INCREF(ptr_type);
        store->ptr_type = ptr_type;
    }
    const int W = store->n_shards;
    const bool lpads = store->jt == J_LEFT || store->jt == J_OUTER;
    const bool rpads = store->jt == J_RIGHT || store->jt == J_OUTER;

    /* phase 1: extract — pure C over the columnar images (GIL held; no
     * state mutated, so failures up to here are replayable) */
    auto _t0 = std::chrono::steady_clock::now();
    std::vector<JRowNb> lx, rx;
    if (!extract_side_nb(lnb, lkidx, W, lx) ||
        !extract_side_nb(rnb, rkidx, W, rx))
        return nullptr;
    jphase_add(store, &PhaseStats::extract_s, _t0);
    store->phases.batches += 1;
    g_join_phases.batches += 1;
    store->phases.rows += (int64_t)(lx.size() + rx.size());
    g_join_phases.rows += (int64_t)(lx.size() + rx.size());
    auto _t1 = std::chrono::steady_clock::now();

    /* phase 2: apply + delta emission + (when fusable) columnar output
     * build, all GIL-free and shard-parallel */
    std::vector<JShardOut> outs((size_t)W);
    struct NbShardOut {
        std::vector<unsigned __int128> keys;
        std::vector<NbCol> cols;
        bool fusable = true;
    };
    std::vector<NbShardOut> nbouts((size_t)W);
    const int lw = store->lwidth, rw = store->rwidth;
    bool fuse_all = true;
    {
        struct Aff {
            std::vector<int32_t> l, r;
        };
        std::vector<std::unordered_map<std::string, Aff>> touched((size_t)W);
        std::vector<std::vector<const std::string *>> order((size_t)W);
        for (size_t i = 0; i < lx.size(); i++) {
            auto &t = touched[lx[i].shard];
            auto it = t.find(lx[i].jk_bytes);
            if (it == t.end()) {
                it = t.emplace(lx[i].jk_bytes, Aff{}).first;
                order[lx[i].shard].push_back(&it->first);
            }
            it->second.l.push_back((int32_t)i);
        }
        for (size_t i = 0; i < rx.size(); i++) {
            auto &t = touched[rx[i].shard];
            auto it = t.find(rx[i].jk_bytes);
            if (it == t.end()) {
                it = t.emplace(rx[i].jk_bytes, Aff{}).first;
                order[rx[i].shard].push_back(&it->first);
            }
            it->second.r.push_back((int32_t)i);
        }

        auto work = [&](int w) {
            JShard &sh = store->shards[(size_t)w];
            JShardOut &o = outs[(size_t)w];
            std::vector<const JEntry *> ord; /* seq-ordered side view */
            for (const std::string *jkb : order[(size_t)w]) {
                Aff &aff = touched[(size_t)w][*jkb];
                auto git = sh.groups.find(*jkb);
                if (git == sh.groups.end()) {
                    git = sh.groups.emplace(*jkb, JGroup{}).first;
                    /* nb-created group: pack the key columns so dump /
                     * demotion can rebuild the join-key tuple */
                    JGroup &ng = git->second;
                    if (!aff.l.empty()) {
                        const JRowNb &r0 = lx[(size_t)aff.l[0]];
                        for (int j : lkidx)
                            pack_cell_from_nb(ng.jk_cells,
                                              (*lnb->cols)[(size_t)j],
                                              (Py_ssize_t)r0.row);
                    } else {
                        const JRowNb &r0 = rx[(size_t)aff.r[0]];
                        for (int j : rkidx)
                            pack_cell_from_nb(ng.jk_cells,
                                              (*rnb->cols)[(size_t)j],
                                              (Py_ssize_t)r0.row);
                    }
                }
                JGroup &g = git->second;
                const bool llive0 = !g.left.empty();
                const bool rlive0 = !g.right.empty();
                JRef pad;

                /* ΔL × R_old */
                if (!aff.l.empty())
                    jside_ordered(g.right, ord);
                for (int32_t li : aff.l) {
                    const JRowNb &dl = lx[(size_t)li];
                    JRef dref;
                    dref.kind = JR_NATIVE;
                    dref.key128 = dl.key128;
                    dref.cells = dl.cells;
                    for (const JEntry *e : ord)
                        o.emits.push_back(JEmit{dref, jref_of_entry(*e),
                                                e->count});
                    if (lpads && !rlive0)
                        o.emits.push_back(JEmit{dref, pad, 1});
                }
                for (int32_t li : aff.l)
                    japply_nb(g.left, lx[(size_t)li], o, g.next_seq);

                /* L_new × ΔR */
                if (!aff.r.empty())
                    jside_ordered(g.left, ord);
                for (int32_t ri : aff.r) {
                    const JRowNb &dr = rx[(size_t)ri];
                    JRef dref;
                    dref.kind = JR_NATIVE;
                    dref.key128 = dr.key128;
                    dref.cells = dr.cells;
                    for (const JEntry *e : ord)
                        o.emits.push_back(JEmit{jref_of_entry(*e), dref,
                                                e->count});
                    if (rpads && !llive0)
                        o.emits.push_back(JEmit{pad, dref, 1});
                }
                for (int32_t ri : aff.r)
                    japply_nb(g.right, rx[(size_t)ri], o, g.next_seq);

                /* pad transitions (liveness flips) — retractions: they
                 * disqualify the columnar output but stay exact */
                const bool llive1 = !g.left.empty();
                const bool rlive1 = !g.right.empty();
                if (lpads && rlive0 != rlive1) {
                    const int64_t sign = rlive1 ? -1 : 1;
                    /* right liveness can only flip via ΔR, so the L_new
                     * × ΔR block already left ord == ordered g.left
                     * (g.left untouched since); re-sort only if not */
                    if (aff.r.empty())
                        jside_ordered(g.left, ord);
                    for (const JEntry *e : ord)
                        o.emits.push_back(JEmit{jref_of_entry(*e), pad,
                                                sign * e->count});
                }
                if (rpads && llive0 != llive1) {
                    const int64_t sign = llive1 ? -1 : 1;
                    jside_ordered(g.right, ord);
                    for (const JEntry *e : ord)
                        o.emits.push_back(JEmit{pad, jref_of_entry(*e),
                                                sign * e->count});
                }
                /* insert-only deltas can never empty a group */
            }
            NbShardOut &no = nbouts[(size_t)w];
            /* Fused output requires the NativeBatch invariant of DISTINCT
             * keys (nb_project passthrough skips the key-set re-check the
             * materialized path performs): only ID_PAIR guarantees it —
             * distinct (lk, rk) pairs mint distinct blake2b keys, and
             * dup_bump flags repeated pairs. id_from_left/right joins
             * with fanout repeat output ids, so they emit tuples. */
            if (store->id_mode != ID_PAIR)
                no.fusable = false;
            for (const JEmit &e : o.emits)
                if (!no.fusable || e.d != 1 || e.l.kind == JR_PY ||
                    e.r.kind == JR_PY || o.dup_bump) {
                    no.fusable = false;
                    break;
                }
        };
        auto build = [&](int w) {
            /* stage B: columnar output build (still GIL-free) */
            JShardOut &o = outs[(size_t)w];
            NbShardOut &no = nbouts[(size_t)w];
            no.cols.resize((size_t)(lw + rw));
            no.keys.reserve(o.emits.size());
            for (const JEmit &e : o.emits) {
                const bool l_some = e.l.kind != JR_PAD;
                const bool r_some = e.r.kind != JR_PAD;
                /* only ID_PAIR is fusable (distinct-keys invariant) */
                no.keys.push_back(mint_pair_key128(l_some, e.l.key128,
                                                   r_some, e.r.key128));
                append_packed_cells(no.cols, 0, lw,
                                    l_some ? e.l.cells.get() : nullptr);
                append_packed_cells(no.cols, lw, rw,
                                    r_some ? e.r.cells.get() : nullptr);
            }
        };

        size_t total = lx.size() + rx.size();
        Py_BEGIN_ALLOW_THREADS
        const uint64_t _tr0 = trace_on() ? trace_now_ns() : 0;
        const bool threaded = W > 1 && total >= 2048;
        if (threaded) {
            std::vector<std::thread> threads;
            threads.reserve((size_t)W);
            for (int w = 0; w < W; w++)
                threads.emplace_back(
                    [&work](int ww) {
                        const uint64_t t0 =
                            trace_on() ? trace_now_ns() : 0;
                        work(ww);
                        if (t0)
                            trace_note(T_JOIN_APPLY, ww, t0,
                                       trace_now_ns(), -1);
                    },
                    w);
            for (auto &t : threads)
                t.join();
        } else {
            for (int w = 0; w < W; w++)
                work(w);
        }
        for (int w = 0; w < W; w++)
            fuse_all = fuse_all && nbouts[(size_t)w].fusable &&
                       !outs[(size_t)w].dup_bump;
        if (fuse_all) {
            if (threaded) {
                std::vector<std::thread> threads;
                threads.reserve((size_t)W);
                for (int w = 0; w < W; w++)
                    threads.emplace_back(build, w);
                for (auto &t : threads)
                    t.join();
            } else {
                for (int w = 0; w < W; w++)
                    build(w);
            }
        }
        if (_tr0)
            trace_note(T_JOIN_APPLY, -1, _tr0, trace_now_ns(),
                       (int64_t)total);
        Py_END_ALLOW_THREADS
    }
    jphase_add(store, &PhaseStats::apply_s, _t1);
    auto _t2 = std::chrono::steady_clock::now();

    /* phase 3 (GIL): assemble the output object. No refcount intents —
     * the nb path stores no Python objects. */
    if (fuse_all) {
        NativeBatchObject *nb = nb_alloc(lw + rw, store->ptr_type);
        if (nb == nullptr)
            return nullptr;
        size_t total_rows = 0;
        for (auto &no : nbouts)
            total_rows += no.keys.size();
        nb->keys->reserve(total_rows);
        for (auto &no : nbouts) {
            nb->keys->insert(nb->keys->end(), no.keys.begin(),
                             no.keys.end());
            if (no.cols.empty())
                continue;
            for (int c = 0; c < lw + rw; c++)
                nbcol_append((*nb->cols)[(size_t)c], no.cols[(size_t)c]);
        }
        nb->n = (Py_ssize_t)nb->keys->size();
        jphase_add(store, &PhaseStats::emit_s, _t2);
        return reinterpret_cast<PyObject *>(nb);
    }
    PyObject *out = jemit_tuples(store, outs, nullptr, nullptr);
    if (out == nullptr)
        return nullptr;
    jphase_add(store, &PhaseStats::emit_s, _t2);
    bool dup = false;
    for (auto &o : outs)
        dup = dup || o.dup_bump;
    PyObject *res = Py_BuildValue("(OO)", out, dup ? Py_True : Py_False);
    Py_DECREF(out);
    return res;
}

/* ==== parse_pk_upserts_nb: columnar primary-keyed upsert parse =========
 *
 * The CDC-shaped connector hot path (primary_key columns, deletions
 * disabled) kept per-row Python alive purely for the upsert session
 * bookkeeping. This variant owns the session in C — pk digest -> packed
 * row cells — and emits a NativeBatch when every row is a FRESH key, so
 * the parse → join/groupby chain stays zero-interpreter. The first
 * obstacle (re-upserted key needing a retraction, non-columnar value,
 * pk overflow) dumps the C session into the caller's live_rows dict and
 * returns None: the caller permanently falls back to the tuple pk path,
 * which then sees exactly the state it would have built itself. */

struct PkStore {
    std::unordered_map<std::string, std::string> rows;
};

void pk_store_destructor(PyObject *capsule)
{
    delete static_cast<PkStore *>(
        PyCapsule_GetPointer(capsule, "pwexec.PkStore"));
}

PyObject *pk_session_new(PyObject *, PyObject *)
{
    return PyCapsule_New(new PkStore(), "pwexec.PkStore",
                         pk_store_destructor);
}

/* value_bytes parity for pk minting (api._value_to_bytes subset over the
 * columnar value set; anything else demotes to the Python mint) */
inline bool ser_pk_value(std::string &out, PyObject *v)
{
    if (v == Py_None) {
        out.push_back('\0');
        return true;
    }
    if (PyBool_Check(v)) {
        out.push_back('B');
        out.push_back(v == Py_True ? '\x01' : '\x00');
        return true;
    }
    if (PyLong_CheckExact(v)) {
        int ovf = 0;
        long long sv = PyLong_AsLongLongAndOverflow(v, &ovf);
        if (ovf || (sv == -1 && PyErr_Occurred())) {
            PyErr_Clear();
            return false;
        }
        uint64_t uv = sv < 0 ? (uint64_t)0 - (uint64_t)sv : (uint64_t)sv;
        int bl = 0;
        while (bl < 64 && (uv >> bl))
            bl++;
        int nbytes = (bl + 8) / 8 + 1;
        out.push_back('I');
        uint64_t tw = (uint64_t)sv;
        for (int i = 0; i < nbytes; i++)
            out.push_back((char)(i < 8 ? (tw >> (8 * i)) & 0xff
                                       : (sv < 0 ? 0xff : 0x00)));
        return true;
    }
    if (PyFloat_CheckExact(v)) {
        double d = PyFloat_AS_DOUBLE(v);
        out.push_back('F');
        out.append(reinterpret_cast<const char *>(&d), 8);
        return true;
    }
    if (PyUnicode_CheckExact(v)) {
        Py_ssize_t n;
        const char *s = PyUnicode_AsUTF8AndSize(v, &n);
        if (s == nullptr) {
            PyErr_Clear();
            return false;
        }
        out.push_back('S');
        out.append(s, (size_t)n);
        return true;
    }
    return false;
}

/* dump the C session into live_rows (Pointer -> row tuple); empties the
 * store. Shared by the demotion path and the caller's explicit demote
 * (a flush carrying non-upsert messages). */
bool pk_dump_into(PkStore *store, PyObject *live_rows, PyObject *ptr_type,
                  Py_ssize_t width)
{
    for (auto &kv : store->rows) {
        unsigned __int128 k;
        memcpy(&k, kv.first.data(), 16);
        PyObject *key = pointer_from_u128(ptr_type, k);
        if (key == nullptr)
            return false;
        PyObject *row = packed_row_to_py(kv.second, (int)width);
        if (row == nullptr) {
            Py_DECREF(key);
            return false;
        }
        int rc = PyDict_SetItem(live_rows, key, row);
        Py_DECREF(key);
        Py_DECREF(row);
        if (rc < 0)
            return false;
    }
    store->rows.clear();
    return true;
}

PyObject *pk_session_dump(PyObject *, PyObject *args)
{
    PyObject *capsule, *live_rows, *ptr_type;
    long long width;
    if (!PyArg_ParseTuple(args, "OO!OL", &capsule, &PyDict_Type, &live_rows,
                          &ptr_type, &width))
        return nullptr;
    auto *store = static_cast<PkStore *>(
        PyCapsule_GetPointer(capsule, "pwexec.PkStore"));
    if (store == nullptr)
        return nullptr;
    if (!pk_dump_into(store, live_rows, ptr_type, (Py_ssize_t)width))
        return nullptr;
    Py_RETURN_NONE;
}

/* parse_pk_upserts_nb(dicts, cols, defaults, pkeys, capsule, live_rows,
 *                     ptr_type) -> NativeBatch | None (demoted)  */
PyObject *parse_pk_upserts_nb(PyObject *, PyObject *args)
{
    PyObject *dicts, *cols, *defaults, *pkeys, *capsule, *live_rows,
        *ptr_type;
    if (!PyArg_ParseTuple(args, "OO!O!O!OO!O", &dicts, &PyTuple_Type, &cols,
                          &PyTuple_Type, &defaults, &PyTuple_Type, &pkeys,
                          &capsule, &PyDict_Type, &live_rows, &ptr_type))
        return nullptr;
    auto *store = static_cast<PkStore *>(
        PyCapsule_GetPointer(capsule, "pwexec.PkStore"));
    if (store == nullptr)
        return nullptr;
    PyObject *seq = PySequence_Fast(dicts, "parse_pk_upserts_nb: sequence");
    if (seq == nullptr)
        return nullptr;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    Py_ssize_t w = PyTuple_GET_SIZE(cols);
    Py_ssize_t npk = PyTuple_GET_SIZE(pkeys);
    if (PyTuple_GET_SIZE(defaults) != w) {
        Py_DECREF(seq);
        PyErr_SetString(PyExc_ValueError, "parse_pk_upserts_nb: widths");
        return nullptr;
    }
    NativeBatchObject *nb = nb_alloc((int)w, ptr_type);
    if (nb == nullptr) {
        Py_DECREF(seq);
        return nullptr;
    }
    std::vector<std::string> digests((size_t)n);
    std::vector<std::string> packed((size_t)n);
    std::unordered_map<std::string, int> batch_seen;
    std::string mintbuf;
    bool demote = false;
    for (Py_ssize_t i = 0; i < n && !demote; i++) {
        PyObject *values = PySequence_Fast_GET_ITEM(seq, i);
        if (!PyDict_Check(values)) {
            demote = true;
            break;
        }
        for (Py_ssize_t c = 0; c < w; c++) {
            PyObject *v = PyDict_GetItemWithError(
                values, PyTuple_GET_ITEM(cols, c));
            if (v == nullptr) {
                if (PyErr_Occurred())
                    PyErr_Clear();
                v = PyTuple_GET_ITEM(defaults, c);
            }
            if (!nb_put((*nb->cols)[(size_t)c], v)) {
                demote = true;
                break;
            }
        }
        if (demote)
            break;
        /* pk mint: value_bytes(pkvals) + blake2b-128 = ref_scalar parity */
        mintbuf.clear();
        pw_put_u32le(mintbuf, (uint32_t)npk);
        for (Py_ssize_t p = 0; p < npk; p++) {
            PyObject *v = PyDict_GetItemWithError(
                values, PyTuple_GET_ITEM(pkeys, p));
            if (v == nullptr) {
                /* missing pk: the tuple path raises KeyError — demote and
                 * let it do exactly that on the replayed batch */
                if (PyErr_Occurred())
                    PyErr_Clear();
                demote = true;
                break;
            }
            size_t mark = mintbuf.size();
            pw_put_u32le(mintbuf, 0);
            if (!ser_pk_value(mintbuf, v)) {
                demote = true;
                break;
            }
            uint32_t plen = (uint32_t)(mintbuf.size() - mark - 4);
            memcpy(&mintbuf[mark], &plen, 4);
        }
        if (demote)
            break;
        unsigned char dg[16];
        pw_b2b_digest16(dg, (const unsigned char *)mintbuf.data(),
                        mintbuf.size());
        std::string dkey(reinterpret_cast<const char *>(dg), 16);
        /* a key already live (in the session or earlier in this batch)
         * needs a retraction — not representable columnar: demote */
        if (store->rows.find(dkey) != store->rows.end() ||
            batch_seen.find(dkey) != batch_seen.end()) {
            demote = true;
            break;
        }
        batch_seen.emplace(dkey, 1);
        digests[(size_t)i] = std::move(dkey);
        for (Py_ssize_t c = 0; c < w; c++)
            pack_cell_from_nb(packed[(size_t)i], (*nb->cols)[(size_t)c], i);
        unsigned __int128 k;
        memcpy(&k, dg, 16);
        nb->keys->push_back(k);
    }
    Py_DECREF(seq);
    if (PyErr_Occurred()) {
        Py_DECREF(nb);
        return nullptr;
    }
    if (demote) {
        Py_DECREF(nb);
        if (!pk_dump_into(store, live_rows, ptr_type, w))
            return nullptr;
        Py_RETURN_NONE;
    }
    for (Py_ssize_t i = 0; i < n; i++)
        store->rows.emplace(std::move(digests[(size_t)i]),
                            std::move(packed[(size_t)i]));
    nb->n = (Py_ssize_t)nb->keys->size();
    return reinterpret_cast<PyObject *>(nb);
}

/* ---- nb_project(nb, idxs) -> NativeBatch -----------------------------
 * Columnar projection: the fused form of a select over plain column
 * references (keys preserved, columns copied/reordered). Keeps a
 * join/parse NativeBatch in C through the projection hop instead of
 * materializing per-row tuples at the first RowwiseNode. The kept
 * columns and key vector are value-copied — a straight memcpy that
 * profiles at ~0.5% of the fused join bench's batch cost; sharing
 * immutable columns across batch objects would save it at the price of
 * shared-ownership plumbing in NativeBatchObject, worth revisiting only
 * if wide selects ever dominate a profile. */
PyObject *nb_project(PyObject *, PyObject *args)
{
    PyObject *nb_obj, *idxs;
    if (!PyArg_ParseTuple(args, "O!O!", &NativeBatchType, &nb_obj,
                          &PyTuple_Type, &idxs))
        return nullptr;
    auto *src = reinterpret_cast<NativeBatchObject *>(nb_obj);
    Py_ssize_t w = PyTuple_GET_SIZE(idxs);
    NativeBatchObject *out = nb_alloc((int)w, src->ptr_type);
    if (out == nullptr)
        return nullptr;
    for (Py_ssize_t j = 0; j < w; j++) {
        long v = PyLong_AsLong(PyTuple_GET_ITEM(idxs, j));
        if (v < 0 || v >= src->width) {
            Py_DECREF(out);
            PyErr_SetString(PyExc_ValueError, "nb_project: idx");
            return nullptr;
        }
        (*out->cols)[(size_t)j] = (*src->cols)[(size_t)v];
    }
    *out->keys = *src->keys;
    out->n = src->n;
    return reinterpret_cast<PyObject *>(out);
}

/* ==== columnar exchange: shard/encode/decode/concat ====================
 *
 * The multi-rank analogue of the fused chain (reference: timely exchange
 * pacts are a streamed byte-level concern, dataflow.rs): an ExchangeNode
 * boundary slices a NativeBatch into per-rank columnar parts
 * (shard_partition_nb), ships them as typed columnar buffers
 * (nb_encode/nb_decode) and re-joins received parts (nb_concat) — no
 * per-row Python object exists anywhere on the path, and the downstream
 * group-by/join keeps consuming columnar. */

/* one copied cell (arena re-based) — GIL-free */
inline void nbcol_push_cell(NbCol &dst, const NbCol &src, Py_ssize_t i)
{
    uint8_t tag = src.tag[(size_t)i];
    dst.tag.push_back(tag);
    if (tag == NB_STR) {
        uint32_t len = src.len[(size_t)i];
        dst.word.push_back((int64_t)dst.arena.size());
        dst.len.push_back(len);
        dst.arena.append(src.arena.data() + (size_t)src.word[(size_t)i],
                         len);
    } else {
        dst.word.push_back(src.word[(size_t)i]);
        dst.len.push_back(0);
    }
}

/* api._value_to_bytes parity for one nb cell — the INJECTIVE key
 * serialization behind procgroup.stable_shard (NOT ser_value, whose
 * normalization collapses 5.0 onto 5: stable_shard hashes the raw
 * Python value, so the columnar shard mint must too):
 *   None  -> "\x00"
 *   bool  -> "B" + \x01/\x00
 *   int   -> "I" + to_bytes((bit_length+8)//8 + 1, little, signed)
 *   float -> "F" + 8-byte LE double
 *   str   -> "S" + utf-8 bytes                                        */
inline void vb_ser_cell(std::string &out, const NbCol &c, Py_ssize_t i)
{
    switch (c.tag[(size_t)i]) {
    case NB_NONE:
        out.push_back('\x00');
        return;
    case NB_BOOL:
        out.push_back('B');
        out.push_back(c.word[(size_t)i] ? '\x01' : '\x00');
        return;
    case NB_INT: {
        int64_t v = c.word[(size_t)i];
        out.push_back('I');
        /* two's-complement abs handles INT64_MIN */
        uint64_t a = v < 0 ? ~(uint64_t)v + 1ULL : (uint64_t)v;
        int bl = 0;
        while (bl < 64 && (a >> bl)) /* guard first: a >> 64 is UB */
            bl++;
        int nbytes = (bl + 8) / 8 + 1;
        for (int b = 0; b < nbytes; b++)
            out.push_back(
                b < 8 ? (char)(((uint64_t)v >> (8 * b)) & 0xff)
                      : (v < 0 ? '\xff' : '\x00'));
        return;
    }
    case NB_FLT: {
        /* word already holds the IEEE-754 bits; struct.pack("<d") parity */
        int64_t w = c.word[(size_t)i];
        out.push_back('F');
        out.append(reinterpret_cast<const char *>(&w), 8);
        return;
    }
    default: { /* NB_STR */
        out.push_back('S');
        out.append(c.arena.data() + (size_t)c.word[(size_t)i],
                   c.len[(size_t)i]);
        return;
    }
    }
}

/* shard_partition_nb(nb, kidx | None, world) -> [NativeBatch] * world
 *
 * Mints each row's shard id with the in-process blake2b-64 over the
 * exact stable_shard byte image — kidx a tuple of key-column indices
 * hashes the TUPLE of those values ("T" + length-prefixed cells, the
 * grouping_batch / lkey_batch pk shape); kidx None hashes the row's own
 * Pointer ("P" + 16-byte LE, the _exchange_by_id shape) — and emits
 * per-rank columnar slices without materializing one row object. The
 * hash+slice loop runs with the GIL released. */
PyObject *shard_partition_nb(PyObject *, PyObject *args)
{
    PyObject *nb_obj, *kidx_obj;
    int world;
    if (!PyArg_ParseTuple(args, "O!Oi", &NativeBatchType, &nb_obj,
                          &kidx_obj, &world))
        return nullptr;
    if (world < 1) {
        PyErr_SetString(PyExc_ValueError, "shard_partition_nb: world");
        return nullptr;
    }
    auto *nb = reinterpret_cast<NativeBatchObject *>(nb_obj);
    std::vector<int> kidx;
    bool by_id = (kidx_obj == Py_None);
    if (!by_id) {
        if (!PyTuple_Check(kidx_obj)) {
            PyErr_SetString(PyExc_TypeError,
                            "shard_partition_nb: kidx must be tuple|None");
            return nullptr;
        }
        Py_ssize_t nk = PyTuple_GET_SIZE(kidx_obj);
        for (Py_ssize_t j = 0; j < nk; j++) {
            long v = PyLong_AsLong(PyTuple_GET_ITEM(kidx_obj, j));
            if (v < 0 || v >= nb->width) {
                PyErr_SetString(PyExc_ValueError,
                                "shard_partition_nb: kidx out of range");
                return nullptr;
            }
            kidx.push_back((int)v);
        }
    }
    std::vector<NativeBatchObject *> outs((size_t)world, nullptr);
    for (int w = 0; w < world; w++) {
        outs[(size_t)w] = nb_alloc(nb->width, nb->ptr_type);
        if (outs[(size_t)w] == nullptr) {
            for (int u = 0; u < w; u++)
                Py_DECREF(outs[(size_t)u]);
            return nullptr;
        }
    }
    Py_BEGIN_ALLOW_THREADS;
    {
        const uint64_t _tr0 = trace_on() ? trace_now_ns() : 0;
        std::string kb;
        kb.reserve(64);
        for (Py_ssize_t i = 0; i < nb->n; i++) {
            kb.clear();
            if (by_id) {
                kb.push_back('P');
                unsigned __int128 k = (*nb->keys)[(size_t)i];
                kb.append(reinterpret_cast<const char *>(&k), 16);
            } else {
                kb.push_back('T');
                pw_put_u32le(kb, (uint32_t)kidx.size());
                for (int c : kidx) {
                    size_t lp = kb.size();
                    kb.append(4, '\0');
                    vb_ser_cell(kb, (*nb->cols)[(size_t)c], i);
                    uint32_t plen = (uint32_t)(kb.size() - lp - 4);
                    memcpy(&kb[lp], &plen, 4);
                }
            }
            int s = (int)(pw_b2b_digest8_u64(
                              reinterpret_cast<const unsigned char *>(
                                  kb.data()),
                              kb.size()) %
                          (uint64_t)world);
            NativeBatchObject *dst = outs[(size_t)s];
            dst->keys->push_back((*nb->keys)[(size_t)i]);
            for (int c = 0; c < nb->width; c++)
                nbcol_push_cell((*dst->cols)[(size_t)c],
                                (*nb->cols)[(size_t)c], i);
        }
        for (int w = 0; w < world; w++)
            outs[(size_t)w]->n = (Py_ssize_t)outs[(size_t)w]->keys->size();
        if (_tr0)
            trace_note(T_SHARD_PART, -1, _tr0, trace_now_ns(),
                       (int64_t)nb->n);
    }
    Py_END_ALLOW_THREADS;
    PyObject *res = PyList_New(world);
    if (res == nullptr) {
        for (int w = 0; w < world; w++)
            Py_DECREF(outs[(size_t)w]);
        return nullptr;
    }
    for (int w = 0; w < world; w++)
        PyList_SET_ITEM(res, w, (PyObject *)outs[(size_t)w]);
    return res;
}

/* ---- nb wire codec (exchange v2 typed columnar buffers) --------------
 * Layout (all little-endian):
 *   u32 version(=1) | u32 n | u32 width
 *   keys: n * 16 bytes
 *   per column:
 *     u8 has_str | tags: n bytes | words: n * 8 bytes
 *     [has_str: lens: n * 4 bytes | u64 arena_len | arena bytes]
 * Pure memcpy both ways — the wire image IS the in-memory image. */

/* memcpy with the empty case made explicit: an empty vector's data() is
 * null, and memcpy's pointer arguments are declared nonnull even for
 * zero sizes (UBSan flags the n=0 frame) */
inline void wire_put(char *&p, const void *src, size_t k)
{
    if (k)
        memcpy(p, src, k);
    p += k;
}

inline void wire_get(void *dst, const char *&p, size_t k)
{
    if (k)
        memcpy(dst, p, k);
    p += k;
}

PyObject *nb_encode(PyObject *, PyObject *args)
{
    PyObject *nb_obj;
    if (!PyArg_ParseTuple(args, "O!", &NativeBatchType, &nb_obj))
        return nullptr;
    auto *nb = reinterpret_cast<NativeBatchObject *>(nb_obj);
    size_t n = (size_t)nb->n;
    std::vector<uint8_t> has_str((size_t)nb->width, 0);
    size_t total = 12 + n * 16;
    for (int c = 0; c < nb->width; c++) {
        const NbCol &col = (*nb->cols)[(size_t)c];
        uint8_t hs = 0;
        for (size_t i = 0; i < n; i++)
            if (col.tag[i] == NB_STR) {
                hs = 1;
                break;
            }
        has_str[(size_t)c] = hs;
        total += 1 + n + n * 8 + (hs ? n * 4 + 8 + col.arena.size() : 0);
    }
    PyObject *out = PyBytes_FromStringAndSize(nullptr, (Py_ssize_t)total);
    if (out == nullptr)
        return nullptr;
    char *p = PyBytes_AS_STRING(out);
    Py_BEGIN_ALLOW_THREADS;
    {
        const uint64_t _tr0 = trace_on() ? trace_now_ns() : 0;
        auto put_u32 = [&](uint32_t v) {
            memcpy(p, &v, 4);
            p += 4;
        };
        put_u32(1u);
        put_u32((uint32_t)n);
        put_u32((uint32_t)nb->width);
        wire_put(p, nb->keys->data(), n * 16);
        for (int c = 0; c < nb->width; c++) {
            const NbCol &col = (*nb->cols)[(size_t)c];
            *p++ = (char)has_str[(size_t)c];
            wire_put(p, col.tag.data(), n);
            wire_put(p, col.word.data(), n * 8);
            if (has_str[(size_t)c]) {
                wire_put(p, col.len.data(), n * 4);
                uint64_t alen = (uint64_t)col.arena.size();
                memcpy(p, &alen, 8);
                p += 8;
                wire_put(p, col.arena.data(), col.arena.size());
            }
        }
        if (_tr0)
            trace_note(T_NB_ENCODE, -1, _tr0, trace_now_ns(),
                       (int64_t)n);
    }
    Py_END_ALLOW_THREADS;
    return out;
}

PyObject *nb_decode(PyObject *, PyObject *args)
{
    Py_buffer buf;
    PyObject *ptr_type;
    if (!PyArg_ParseTuple(args, "y*O", &buf, &ptr_type))
        return nullptr;
    const char *p = (const char *)buf.buf;
    const char *end = p + buf.len;
    NativeBatchObject *nb = nullptr;
    uint32_t ver = 0, n = 0, width = 0;
    auto need = [&](size_t k) { return (size_t)(end - p) >= k; };
    auto get_u32 = [&](uint32_t *v) {
        memcpy(v, p, 4);
        p += 4;
    };
    if (!need(12))
        goto corrupt;
    get_u32(&ver);
    get_u32(&n);
    get_u32(&width);
    if (ver != 1 || width > (1u << 16) || n > (1u << 30))
        goto corrupt;
    nb = nb_alloc((int)width, ptr_type);
    if (nb == nullptr) {
        PyBuffer_Release(&buf);
        return nullptr;
    }
    {
        bool bad = false;
        Py_BEGIN_ALLOW_THREADS;
        const uint64_t _tr0 = trace_on() ? trace_now_ns() : 0;
        do {
            if (!need((size_t)n * 16)) {
                bad = true;
                break;
            }
            nb->keys->resize(n);
            wire_get(nb->keys->data(), p, (size_t)n * 16);
            for (uint32_t c = 0; c < width && !bad; c++) {
                NbCol &col = (*nb->cols)[c];
                if (!need(1 + (size_t)n * 9)) {
                    bad = true;
                    break;
                }
                uint8_t hs = (uint8_t)*p++;
                col.tag.resize(n);
                wire_get(col.tag.data(), p, n);
                col.word.resize(n);
                wire_get(col.word.data(), p, (size_t)n * 8);
                col.len.assign(n, 0);
                if (hs) {
                    if (!need((size_t)n * 4 + 8)) {
                        bad = true;
                        break;
                    }
                    wire_get(col.len.data(), p, (size_t)n * 4);
                    uint64_t alen;
                    memcpy(&alen, p, 8);
                    p += 8;
                    if (!need(alen)) {
                        bad = true;
                        break;
                    }
                    col.arena.assign(p, alen);
                    p += alen;
                }
                /* arena bounds: every NB_STR cell must stay inside */
                for (uint32_t i = 0; i < n && !bad; i++)
                    if (col.tag[i] == NB_STR &&
                        (uint64_t)col.word[i] + col.len[i] >
                            col.arena.size())
                        bad = true;
            }
        } while (false);
        if (_tr0)
            trace_note(T_NB_DECODE, -1, _tr0, trace_now_ns(),
                       (int64_t)n);
        Py_END_ALLOW_THREADS;
        if (bad) {
            Py_DECREF(nb);
            goto corrupt;
        }
    }
    nb->n = (Py_ssize_t)n;
    PyBuffer_Release(&buf);
    return reinterpret_cast<PyObject *>(nb);
corrupt:
    PyBuffer_Release(&buf);
    PyErr_SetString(PyExc_ValueError, "nb_decode: corrupt columnar frame");
    return nullptr;
}

/* ---- delta-list wire codec (exchange v2, retraction-bearing) ---------
 * NativeBatch carries insert-only batches; exchange slices that carry
 * retractions (group-by updates gathered to rank 0, upsert sessions)
 * use this codec instead: keys + i32 diffs + the same dtype-tagged
 * column buffers. Any non-scalar cell (ndarray, Json, tuple, subclass)
 * makes encode return None and the caller falls back to pickle — the
 * "pickled segments for object columns only" rule. Layout:
 *   u32 version(=2) | u32 n | u32 width
 *   keys: n * 16 | diffs: n * 4 (i32)
 *   columns as in nb_encode */

PyObject *deltas_encode(PyObject *, PyObject *args)
{
    PyObject *lst;
    if (!PyArg_ParseTuple(args, "O", &lst))
        return nullptr;
    PyObject *seq = PySequence_Fast(lst, "deltas_encode: sequence");
    if (seq == nullptr)
        return nullptr;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    Py_ssize_t w = 0;
    if (n > 0) {
        PyObject *d0 = PySequence_Fast_GET_ITEM(seq, 0);
        if (!PyTuple_Check(d0) || PyTuple_GET_SIZE(d0) != 3 ||
            !PyTuple_Check(PyTuple_GET_ITEM(d0, 1))) {
            Py_DECREF(seq);
            Py_RETURN_NONE;
        }
        w = PyTuple_GET_SIZE(PyTuple_GET_ITEM(d0, 1));
    }
    std::vector<unsigned __int128> keys;
    std::vector<int32_t> diffs;
    std::vector<NbCol> cols((size_t)w);
    keys.reserve((size_t)n);
    diffs.reserve((size_t)n);
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *d = PySequence_Fast_GET_ITEM(seq, i);
        if (!PyTuple_Check(d) || PyTuple_GET_SIZE(d) != 3)
            goto fallback;
        PyObject *row = PyTuple_GET_ITEM(d, 1);
        if (!PyTuple_Check(row) || PyTuple_GET_SIZE(row) != w)
            goto fallback;
        unsigned __int128 k;
        if (!nb_int128_of(PyTuple_GET_ITEM(d, 0), &k))
            goto fallback;
        long diff = PyLong_AsLong(PyTuple_GET_ITEM(d, 2));
        if ((diff == -1 && PyErr_Occurred()) || diff < INT32_MIN ||
            diff > INT32_MAX) {
            PyErr_Clear();
            goto fallback;
        }
        for (Py_ssize_t c = 0; c < w; c++)
            if (!nb_put(cols[(size_t)c], PyTuple_GET_ITEM(row, c))) {
                /* roll the columns back to a consistent length */
                for (Py_ssize_t u = 0; u < w; u++) {
                    NbCol &cc = cols[(size_t)u];
                    while ((Py_ssize_t)cc.tag.size() > i) {
                        if (cc.tag.back() == NB_STR)
                            cc.arena.resize((size_t)cc.word.back());
                        cc.tag.pop_back();
                        cc.word.pop_back();
                        cc.len.pop_back();
                    }
                }
                goto fallback;
            }
        keys.push_back(k);
        diffs.push_back((int32_t)diff);
    }
    {
        Py_DECREF(seq);
        std::vector<uint8_t> has_str((size_t)w, 0);
        size_t total = 12 + (size_t)n * 20;
        for (Py_ssize_t c = 0; c < w; c++) {
            const NbCol &col = cols[(size_t)c];
            uint8_t hs = 0;
            for (size_t i = 0; i < (size_t)n; i++)
                if (col.tag[i] == NB_STR) {
                    hs = 1;
                    break;
                }
            has_str[(size_t)c] = hs;
            total += 1 + (size_t)n * 9 +
                     (hs ? (size_t)n * 4 + 8 + col.arena.size() : 0);
        }
        PyObject *out =
            PyBytes_FromStringAndSize(nullptr, (Py_ssize_t)total);
        if (out == nullptr)
            return nullptr;
        char *p = PyBytes_AS_STRING(out);
        auto put_u32 = [&](uint32_t v) {
            memcpy(p, &v, 4);
            p += 4;
        };
        put_u32(2u);
        put_u32((uint32_t)n);
        put_u32((uint32_t)w);
        wire_put(p, keys.data(), (size_t)n * 16);
        wire_put(p, diffs.data(), (size_t)n * 4);
        for (Py_ssize_t c = 0; c < w; c++) {
            const NbCol &col = cols[(size_t)c];
            *p++ = (char)has_str[(size_t)c];
            wire_put(p, col.tag.data(), (size_t)n);
            wire_put(p, col.word.data(), (size_t)n * 8);
            if (has_str[(size_t)c]) {
                wire_put(p, col.len.data(), (size_t)n * 4);
                uint64_t alen = (uint64_t)col.arena.size();
                memcpy(p, &alen, 8);
                p += 8;
                wire_put(p, col.arena.data(), col.arena.size());
            }
        }
        return out;
    }
fallback:
    Py_DECREF(seq);
    Py_RETURN_NONE;
}

/* ---- cross-frame wire intern cache (ISSUE 13) -----------------------
 * deltas_decode's per-call interning only sees recurrence WITHIN one
 * frame (retract+insert pairs: ~2x). The gather stream's vocabulary —
 * group keys, group-key strings — recurs commit after commit, so a
 * receiver thread that keeps ONE cache across its link's frames turns
 * nearly every Pointer/str mint into a dict hit. Owned by a capsule
 * (one per procgroup receiver thread); bounded: at capacity the cache
 * epoch-resets (decref all, start over) instead of growing. Touched
 * only with the GIL held (deltas_decode runs GIL-held; the capsule
 * destructor is invoked by CPython under the GIL). */

struct WireU128H {
    size_t operator()(unsigned __int128 v) const
    {
        return (size_t)(((uint64_t)v ^ (uint64_t)(v >> 64)) *
                        0x9E3779B97F4A7C15ull);
    }
};

struct InternCache {
    std::unordered_map<unsigned __int128, PyObject *, WireU128H> keys;
    std::unordered_map<std::string, PyObject *> strs;
    size_t cap;

    void clear_refs()
    {
        for (auto &kv : keys)
            Py_DECREF(kv.second);
        for (auto &kv : strs)
            Py_DECREF(kv.second);
        keys.clear();
        strs.clear();
    }
};

static void intern_cache_destroy(PyObject *capsule)
{
    auto *c = (InternCache *)PyCapsule_GetPointer(capsule, "pw_intern");
    if (c != nullptr) {
        c->clear_refs();
        delete c;
    }
}

PyObject *intern_new(PyObject *, PyObject *args)
{
    long cap = 65536;
    if (!PyArg_ParseTuple(args, "|l", &cap))
        return nullptr;
    auto *c = new InternCache();
    c->cap = cap > 0 ? (size_t)cap : 65536;
    c->keys.reserve(std::min(c->cap, (size_t)4096));
    c->strs.reserve(std::min(c->cap, (size_t)4096));
    return PyCapsule_New(c, "pw_intern", intern_cache_destroy);
}

PyObject *deltas_decode(PyObject *, PyObject *args)
{
    Py_buffer buf;
    PyObject *ptr_type;
    PyObject *intern_obj = nullptr;
    InternCache *xc = nullptr;
    if (!PyArg_ParseTuple(args, "y*O|O", &buf, &ptr_type, &intern_obj))
        return nullptr;
    if (intern_obj != nullptr && intern_obj != Py_None) {
        xc = (InternCache *)PyCapsule_GetPointer(intern_obj, "pw_intern");
        if (xc == nullptr) {
            PyBuffer_Release(&buf);
            return nullptr;
        }
    }
    const char *p = (const char *)buf.buf;
    const char *end = p + buf.len;
    uint32_t ver = 0, n = 0, width = 0;
    PyObject *out = nullptr;
    std::vector<NbCol> cols;
    const char *keys_p = nullptr, *diffs_p = nullptr;
    auto need = [&](size_t k) { return (size_t)(end - p) >= k; };
    if (!need(12))
        goto corrupt;
    memcpy(&ver, p, 4);
    memcpy(&n, p + 4, 4);
    memcpy(&width, p + 8, 4);
    p += 12;
    if (ver != 2 || width > (1u << 16) || n > (1u << 30))
        goto corrupt;
    if (!need((size_t)n * 20))
        goto corrupt;
    keys_p = p;
    p += (size_t)n * 16;
    diffs_p = p;
    p += (size_t)n * 4;
    cols.resize(width);
    for (uint32_t c = 0; c < width; c++) {
        NbCol &col = cols[c];
        if (!need(1 + (size_t)n * 9))
            goto corrupt;
        uint8_t hs = (uint8_t)*p++;
        col.tag.resize(n);
        wire_get(col.tag.data(), p, n);
        col.word.resize(n);
        wire_get(col.word.data(), p, (size_t)n * 8);
        col.len.assign(n, 0);
        if (hs) {
            if (!need((size_t)n * 4 + 8))
                goto corrupt;
            wire_get(col.len.data(), p, (size_t)n * 4);
            uint64_t alen;
            memcpy(&alen, p, 8);
            p += 8;
            if (!need(alen))
                goto corrupt;
            col.arena.assign(p, alen);
            p += alen;
        }
        for (uint32_t i = 0; i < n; i++)
            if (col.tag[i] == NB_STR &&
                (uint64_t)col.word[i] + col.len[i] > col.arena.size())
                goto corrupt;
    }
    out = PyList_New((Py_ssize_t)n);
    if (out == nullptr) {
        PyBuffer_Release(&buf);
        return nullptr;
    }
    {
        /* wire interning (ISSUE 13): retraction-bearing gather streams
         * (materialized groupby/capture output to rank 0) repeat a
         * small vocabulary — every update ships a retract+insert pair
         * for the same key, and the same group keys/strings recur
         * commit after commit. Minting a fresh Pointer (a Python int
         * subclass constructed via its type object) and a fresh
         * PyUnicode per row made deltas_decode the receiver's hottest
         * leg (~0.5M deltas/s, half of it Pointer.__new__). A per-call
         * cache keyed by the raw 16-byte key / arena slice reuses the
         * object for every recurrence — per-CALL, not global, so an
         * unbounded vocabulary cannot pin memory past its frame. Cache
         * entries hold one strong ref each, released below. */
        std::unordered_map<unsigned __int128, PyObject *, WireU128H>
            local_k;
        std::unordered_map<std::string, PyObject *> local_s;
        /* an attached cross-frame cache (capsule arg — one per
         * procgroup receiver thread) replaces the per-call maps: the
         * gather vocabulary recurs commit after commit, which a
         * per-frame cache cannot see */
        auto &kcache = xc != nullptr ? xc->keys : local_k;
        auto &scache = xc != nullptr ? xc->strs : local_s;
        /* insertion cap: a high-cardinality stream (distinct keys per
         * row — nothing recurs) must not grow 400k-entry maps it never
         * hits; past the cap the CROSS-FRAME cache epoch-resets (the
         * vocabulary changed) while the per-call cache just stops
         * inserting */
        const size_t CACHE_CAP = xc != nullptr ? xc->cap : (1u << 16);
        if (xc == nullptr) {
            kcache.reserve(std::min((size_t)n, CACHE_CAP));
            scache.reserve(std::min((size_t)n, CACHE_CAP));
        }
        /* adaptive (per-call mode only): a high-cardinality stream
         * never hits — probe a prefix and drop the caches when the
         * recurrence isn't there. The cross-frame cache skips the
         * probe: its whole point is recurrence ACROSS frames that the
         * prefix cannot see. */
        const uint32_t PROBE_ROWS = 4096;
        bool interning = xc != nullptr || n > 64;
        uint64_t khits = 0;
        bool failed = false;
        for (uint32_t i = 0; i < n && !failed; i++) {
            unsigned __int128 k;
            memcpy(&k, keys_p + (size_t)i * 16, 16);
            int32_t diff;
            memcpy(&diff, diffs_p + (size_t)i * 4, 4);
            if (xc == nullptr && interning && i == PROBE_ROWS &&
                khits < PROBE_ROWS / 8) {
                /* no recurrence in the probe window: stop paying */
                interning = false;
                for (auto &kv : kcache)
                    Py_DECREF(kv.second);
                for (auto &kv : scache)
                    Py_DECREF(kv.second);
                kcache.clear();
                scache.clear();
            }
            PyObject *key;
            auto kit = interning ? kcache.find(k) : kcache.end();
            if (kit != kcache.end()) {
                key = kit->second;
                khits++;
                Py_INCREF(key);
            } else {
                key = pointer_from_u128(ptr_type, k);
                if (key == nullptr) {
                    failed = true;
                    break;
                }
                if (interning) {
                    if (kcache.size() >= CACHE_CAP && xc != nullptr)
                        xc->clear_refs(); /* epoch reset */
                    if (kcache.size() < CACHE_CAP) {
                        Py_INCREF(key); /* the cache's ref */
                        kcache.emplace(k, key);
                    }
                }
            }
            PyObject *row = PyTuple_New((Py_ssize_t)width);
            if (row == nullptr) {
                Py_DECREF(key);
                failed = true;
                break;
            }
            for (uint32_t c = 0; c < width; c++) {
                const NbCol &col = cols[c];
                PyObject *v;
                if (col.tag[(size_t)i] == NB_STR) {
                    std::string sv(
                        col.arena.data() + (size_t)col.word[(size_t)i],
                        (size_t)col.len[(size_t)i]);
                    auto sit = interning ? scache.find(sv) : scache.end();
                    if (sit != scache.end()) {
                        v = sit->second;
                        Py_INCREF(v);
                    } else {
                        v = PyUnicode_FromStringAndSize(
                            sv.data(), (Py_ssize_t)sv.size());
                        if (v != nullptr && interning) {
                            if (scache.size() >= CACHE_CAP &&
                                xc != nullptr)
                                xc->clear_refs();
                            if (scache.size() < CACHE_CAP) {
                                Py_INCREF(v); /* the cache's ref */
                                scache.emplace(std::move(sv), v);
                            }
                        }
                    }
                } else {
                    v = nb_cell_to_py(col, (Py_ssize_t)i);
                }
                if (v == nullptr) {
                    Py_DECREF(key);
                    Py_DECREF(row);
                    failed = true;
                    break;
                }
                PyTuple_SET_ITEM(row, (Py_ssize_t)c, v);
            }
            if (failed)
                break;
            /* direct 3-tuple build: Py_BuildValue("(NNi)") re-parses
             * its format string per row — measurable at 400k rows */
            PyObject *d = PyLong_FromLong((long)diff);
            PyObject *t = d ? PyTuple_New(3) : nullptr;
            if (t == nullptr) {
                Py_XDECREF(d);
                Py_DECREF(key);
                Py_DECREF(row);
                failed = true;
                break;
            }
            PyTuple_SET_ITEM(t, 0, key);
            PyTuple_SET_ITEM(t, 1, row);
            PyTuple_SET_ITEM(t, 2, d);
            PyList_SET_ITEM(out, (Py_ssize_t)i, t);
        }
        if (xc == nullptr) {
            /* per-call mode: release the maps' refs; a cross-frame
             * cache keeps its entries for the link's next frame (the
             * capsule destructor releases them) */
            for (auto &kv : kcache)
                Py_DECREF(kv.second);
            for (auto &kv : scache)
                Py_DECREF(kv.second);
        }
        if (failed)
            goto fail;
    }
    PyBuffer_Release(&buf);
    return out;
fail:
    Py_DECREF(out);
    PyBuffer_Release(&buf);
    return nullptr;
corrupt:
    PyBuffer_Release(&buf);
    PyErr_SetString(PyExc_ValueError, "deltas_decode: corrupt frame");
    return nullptr;
}

/* nb_concat([nb, ...]) -> NativeBatch — arena-rebased column append;
 * used by the exchange merge so downstream fused consumers see ONE
 * columnar batch per timestamp regardless of how many peers fed it. */
PyObject *nb_concat(PyObject *, PyObject *args)
{
    PyObject *lst;
    if (!PyArg_ParseTuple(args, "O!", &PyList_Type, &lst))
        return nullptr;
    Py_ssize_t k = PyList_GET_SIZE(lst);
    if (k == 0) {
        PyErr_SetString(PyExc_ValueError, "nb_concat: empty list");
        return nullptr;
    }
    for (Py_ssize_t j = 0; j < k; j++)
        if (!PyObject_TypeCheck(PyList_GET_ITEM(lst, j), &NativeBatchType)) {
            PyErr_SetString(PyExc_TypeError, "nb_concat: NativeBatch list");
            return nullptr;
        }
    auto *first = reinterpret_cast<NativeBatchObject *>(PyList_GET_ITEM(lst, 0));
    for (Py_ssize_t j = 1; j < k; j++)
        if (reinterpret_cast<NativeBatchObject *>(PyList_GET_ITEM(lst, j))
                ->width != first->width) {
            PyErr_SetString(PyExc_ValueError, "nb_concat: width mismatch");
            return nullptr;
        }
    NativeBatchObject *out = nb_alloc(first->width, first->ptr_type);
    if (out == nullptr)
        return nullptr;
    /* snapshot AND pin the items with the GIL held: PyList_GET_ITEM is
     * Python API and returns borrowed refs — another thread could mutate
     * the caller's list (dropping an item's last reference) while this
     * one runs GIL-free (scripts/lint_gil.py) */
    std::vector<NativeBatchObject *> srcs((size_t)k);
    for (Py_ssize_t j = 0; j < k; j++) {
        srcs[(size_t)j] =
            reinterpret_cast<NativeBatchObject *>(PyList_GET_ITEM(lst, j));
        Py_INCREF(srcs[(size_t)j]);
    }
    Py_BEGIN_ALLOW_THREADS;
    {
        const uint64_t _tr0 = trace_on() ? trace_now_ns() : 0;
        for (Py_ssize_t j = 0; j < k; j++) {
            NativeBatchObject *src = srcs[(size_t)j];
            out->keys->insert(out->keys->end(), src->keys->begin(),
                              src->keys->end());
            for (int c = 0; c < first->width; c++)
                nbcol_append((*out->cols)[(size_t)c],
                             (*src->cols)[(size_t)c]);
        }
        out->n = (Py_ssize_t)out->keys->size();
        if (_tr0)
            trace_note(T_NB_CONCAT, -1, _tr0, trace_now_ns(),
                       (int64_t)out->n);
    }
    Py_END_ALLOW_THREADS;
    for (Py_ssize_t j = 0; j < k; j++)
        Py_DECREF(srcs[(size_t)j]);
    return reinterpret_cast<PyObject *>(out);
}

/* ---- capture_apply_nb(rows_dict, updates, nb, time) ------------------
 * Columnar capture sink expansion: one C pass takes a NativeBatch into
 * the capture's key->row dict and update history — no intermediate
 * delta-tuple list, no double traversal. nb batches are insert-only so
 * the dict op is a plain upsert. */
PyObject *capture_apply_nb(PyObject *, PyObject *args)
{
    PyObject *rows_dict, *updates, *nb_obj;
    long long time_v;
    if (!PyArg_ParseTuple(args, "O!O!O!L", &PyDict_Type, &rows_dict,
                          &PyList_Type, &updates, &NativeBatchType, &nb_obj,
                          &time_v))
        return nullptr;
    auto *nb = reinterpret_cast<NativeBatchObject *>(nb_obj);
    PyObject *tobj = PyLong_FromLongLong(time_v);
    PyObject *one = PyLong_FromLong(1);
    if (tobj == nullptr || one == nullptr) {
        Py_XDECREF(tobj);
        Py_XDECREF(one);
        return nullptr;
    }
    for (Py_ssize_t i = 0; i < nb->n; i++) {
        PyObject *key = nb_key_to_py(nb, i);
        if (key == nullptr)
            goto fail;
        PyObject *row = PyTuple_New(nb->width);
        if (row == nullptr) {
            Py_DECREF(key);
            goto fail;
        }
        for (int c = 0; c < nb->width; c++) {
            PyObject *v = nb_cell_to_py((*nb->cols)[(size_t)c], i);
            if (v == nullptr) {
                Py_DECREF(key);
                Py_DECREF(row);
                goto fail;
            }
            PyTuple_SET_ITEM(row, c, v);
        }
        if (PyDict_SetItem(rows_dict, key, row) < 0) {
            Py_DECREF(key);
            Py_DECREF(row);
            goto fail;
        }
        {
            PyObject *upd = PyTuple_Pack(4, key, row, tobj, one);
            Py_DECREF(key);
            Py_DECREF(row);
            if (upd == nullptr || PyList_Append(updates, upd) < 0) {
                Py_XDECREF(upd);
                goto fail;
            }
            Py_DECREF(upd);
        }
    }
    Py_DECREF(tobj);
    Py_DECREF(one);
    Py_RETURN_NONE;
fail:
    Py_DECREF(tobj);
    Py_DECREF(one);
    return nullptr;
}

/* ==== columnar egress: Arrow C data interface export ===================
 *
 * The zero-copy capture/export path (ISSUE 14): a NativeBatch's C-owned
 * typed column buffers are assembled into an Arrow record batch through
 * the Arrow C data interface — the stable cross-library ABI pyarrow
 * imports without copying (pa.RecordBatch._import_from_c). Buffers are
 * DONATED: the export copies the column images into buffers owned by a
 * refcounted holder that the consumer's release callbacks free, so the
 * record batch outlives the NativeBatch and the engine never sees a
 * dangling view. Assembly runs GIL-free (plain memcpy/bit-packing —
 * scripts/lint_gil.py clean) and reports on the flight-recorder ring as
 * an `arrow_export` native span.
 *
 * Column typing: a NativeBatch column exports when its non-null cells
 * share ONE tag (int64 -> "l", float64 -> "g", bool -> "b", utf8 ->
 * "u", all-null -> "n"); NB_NONE cells become Arrow nulls under a
 * validity bitmap. A mixed-tag column (int cells next to str cells —
 * only reachable through untyped object sources) makes the whole export
 * return None and the caller falls back to the row-expanding path, the
 * graceful degradation the egress counters make visible. */

#ifndef ARROW_C_DATA_INTERFACE
#define ARROW_C_DATA_INTERFACE

#define ARROW_FLAG_NULLABLE 2

struct ArrowSchema {
    const char *format;
    const char *name;
    const char *metadata;
    int64_t flags;
    int64_t n_children;
    struct ArrowSchema **children;
    struct ArrowSchema *dictionary;
    void (*release)(struct ArrowSchema *);
    void *private_data;
};

struct ArrowArray {
    int64_t length;
    int64_t null_count;
    int64_t offset;
    int64_t n_buffers;
    int64_t n_children;
    const void **buffers;
    struct ArrowArray **children;
    struct ArrowArray *dictionary;
    void (*release)(struct ArrowArray *);
    void *private_data;
};

#endif /* ARROW_C_DATA_INTERFACE */

/* Everything one export donates, freed only when BOTH the consumer's
 * schema and array copies released (pyarrow may drop them on different
 * threads at GC time — the refcount is atomic). Child structs live in
 * reserved vectors so their addresses stay stable; buffers in deques
 * for the same reason. */
struct ArrowHolder {
    std::deque<std::vector<uint8_t>> bufs;
    std::deque<std::vector<const void *>> bufptrs;
    std::deque<std::string> strs; /* column-name storage */
    std::vector<ArrowSchema> schemas;      /* children */
    std::vector<ArrowArray> arrays;        /* children */
    std::vector<ArrowSchema *> schema_children;
    std::vector<ArrowArray *> array_children;
    std::atomic<int> refs{2}; /* schema shell + array shell */
};

void arrow_holder_unref(ArrowHolder *h)
{
    if (h != nullptr && h->refs.fetch_sub(1) == 1)
        delete h;
}

/* child storage is holder-owned: releasing a child only marks it */
void pw_arrow_child_schema_release(ArrowSchema *s) { s->release = nullptr; }
void pw_arrow_child_array_release(ArrowArray *a) { a->release = nullptr; }

void pw_arrow_schema_release(ArrowSchema *s)
{
    for (int64_t i = 0; i < s->n_children; i++) {
        ArrowSchema *c = s->children[i];
        if (c != nullptr && c->release != nullptr)
            c->release(c);
    }
    s->release = nullptr;
    arrow_holder_unref((ArrowHolder *)s->private_data);
}

void pw_arrow_array_release(ArrowArray *a)
{
    for (int64_t i = 0; i < a->n_children; i++) {
        ArrowArray *c = a->children[i];
        if (c != nullptr && c->release != nullptr)
            c->release(c);
    }
    a->release = nullptr;
    arrow_holder_unref((ArrowHolder *)a->private_data);
}

/* build one exported column (GIL-free: memcpy/bit ops only).
 * `unified` is the column's single non-null tag (NB_NONE = all-null). */
void arrow_build_col(ArrowHolder *h, const NbCol &col, size_t n,
                     uint8_t unified, const char *name)
{
    auto add_buf = [&](size_t bytes) -> uint8_t * {
        h->bufs.emplace_back(bytes > 0 ? bytes : 1);
        return h->bufs.back().data();
    };
    int64_t nulls = 0;
    for (size_t i = 0; i < n; i++)
        if (col.tag[i] == NB_NONE)
            nulls++;
    const uint8_t *validity = nullptr;
    if (nulls > 0 && unified != NB_NONE) {
        uint8_t *vb = add_buf((n + 7) / 8);
        memset(vb, 0, (n + 7) / 8);
        for (size_t i = 0; i < n; i++)
            if (col.tag[i] != NB_NONE)
                vb[i >> 3] |= (uint8_t)(1u << (i & 7));
        validity = vb;
    }
    const char *fmt;
    h->bufptrs.emplace_back();
    std::vector<const void *> &bp = h->bufptrs.back();
    int64_t n_buffers;
    switch (unified) {
    case NB_NONE: /* all-null column -> Arrow null type */
        fmt = "n";
        n_buffers = 0;
        nulls = (int64_t)n;
        break;
    case NB_BOOL: {
        fmt = "b";
        uint8_t *vals = add_buf((n + 7) / 8);
        memset(vals, 0, (n + 7) / 8);
        for (size_t i = 0; i < n; i++)
            if (col.word[i])
                vals[i >> 3] |= (uint8_t)(1u << (i & 7));
        bp = {validity, vals};
        n_buffers = 2;
        break;
    }
    case NB_INT:
    case NB_FLT: {
        /* word already holds the int64 value or the double's bit
         * image — one memcpy IS the Arrow values buffer */
        fmt = unified == NB_INT ? "l" : "g";
        uint8_t *vals = add_buf(n * 8);
        if (n > 0)
            memcpy(vals, col.word.data(), n * 8);
        bp = {validity, vals};
        n_buffers = 2;
        break;
    }
    default: { /* NB_STR -> utf8 (int32 offsets + data) */
        fmt = "u";
        uint8_t *offs_b = add_buf((n + 1) * 4);
        int32_t *offs = (int32_t *)offs_b;
        size_t total = 0;
        for (size_t i = 0; i < n; i++)
            if (col.tag[i] == NB_STR)
                total += col.len[i];
        uint8_t *data = add_buf(total);
        size_t pos = 0;
        offs[0] = 0;
        for (size_t i = 0; i < n; i++) {
            if (col.tag[i] == NB_STR && col.len[i] > 0) {
                memcpy(data + pos, col.arena.data() + (size_t)col.word[i],
                       col.len[i]);
                pos += col.len[i];
            }
            offs[i + 1] = (int32_t)pos;
        }
        bp = {validity, offs_b, data};
        n_buffers = 3;
        break;
    }
    }
    h->strs.emplace_back(name);
    ArrowSchema s;
    s.format = fmt;
    s.name = h->strs.back().c_str();
    s.metadata = nullptr;
    s.flags = ARROW_FLAG_NULLABLE;
    s.n_children = 0;
    s.children = nullptr;
    s.dictionary = nullptr;
    s.release = pw_arrow_child_schema_release;
    s.private_data = nullptr;
    h->schemas.push_back(s);
    ArrowArray a;
    a.length = (int64_t)n;
    a.null_count = nulls;
    a.offset = 0;
    a.n_buffers = n_buffers;
    a.n_children = 0;
    a.buffers = bp.data();
    a.children = nullptr;
    a.dictionary = nullptr;
    a.release = pw_arrow_child_array_release;
    a.private_data = nullptr;
    h->arrays.push_back(a);
}

/* one fixed-width extra column (key bytes / constant diff) */
void arrow_build_fixed_col(ArrowHolder *h, const char *fmt,
                           const char *name, const void *data,
                           size_t bytes, size_t n)
{
    h->bufs.emplace_back(bytes > 0 ? bytes : 1);
    if (bytes > 0)
        memcpy(h->bufs.back().data(), data, bytes);
    h->bufptrs.emplace_back(
        std::vector<const void *>{nullptr, h->bufs.back().data()});
    h->strs.emplace_back(name);
    ArrowSchema s;
    s.format = fmt;
    s.name = h->strs.back().c_str();
    s.metadata = nullptr;
    s.flags = 0;
    s.n_children = 0;
    s.children = nullptr;
    s.dictionary = nullptr;
    s.release = pw_arrow_child_schema_release;
    s.private_data = nullptr;
    h->schemas.push_back(s);
    ArrowArray a;
    a.length = (int64_t)n;
    a.null_count = 0;
    a.offset = 0;
    a.n_buffers = 2;
    a.n_children = 0;
    a.buffers = h->bufptrs.back().data();
    a.children = nullptr;
    a.dictionary = nullptr;
    a.release = pw_arrow_child_array_release;
    a.private_data = nullptr;
    h->arrays.push_back(a);
}

/* nb_export_arrow(nb, names[, include_key, include_diff])
 *   -> (schema_addr, array_addr) | None
 *
 * Donating export of one NativeBatch as an Arrow struct/record batch.
 * The two addresses are malloc'd ArrowSchema/ArrowArray shells the
 * caller hands to pa.RecordBatch._import_from_c (which MOVES the
 * contents and marks the shells released) and then returns to
 * arrow_shells_free. None = a column mixes value tags (caller falls
 * back to the row path; counted, never an error). include_key adds a
 * "_key" fixed_size_binary(16) column (the engine's 128-bit row keys,
 * little-endian); include_diff a constant +1 "diff" int64 column (nb
 * batches are insert-only net form by construction). */
PyObject *nb_export_arrow(PyObject *, PyObject *args)
{
    PyObject *nb_obj, *names;
    int include_key = 0, include_diff = 0;
    if (!PyArg_ParseTuple(args, "O!O!|pp", &NativeBatchType, &nb_obj,
                          &PyTuple_Type, &names, &include_key,
                          &include_diff))
        return nullptr;
    auto *nb = reinterpret_cast<NativeBatchObject *>(nb_obj);
    if (PyTuple_GET_SIZE(names) != (Py_ssize_t)nb->width) {
        PyErr_SetString(PyExc_ValueError,
                        "nb_export_arrow: names width mismatch");
        return nullptr;
    }
    /* extract names with the GIL held — the region below is Py-free */
    std::vector<std::string> colnames((size_t)nb->width);
    for (Py_ssize_t j = 0; j < (Py_ssize_t)nb->width; j++) {
        PyObject *s = PyTuple_GET_ITEM(names, j);
        Py_ssize_t sl;
        const char *sp = PyUnicode_AsUTF8AndSize(s, &sl);
        if (sp == nullptr)
            return nullptr;
        colnames[(size_t)j].assign(sp, (size_t)sl);
    }
    const size_t n = (size_t)nb->n;
    const int width = nb->width;
    const int ncols = width + (include_key ? 1 : 0) + (include_diff ? 1 : 0);
    auto *h = new ArrowHolder();
    h->schemas.reserve((size_t)ncols);
    h->arrays.reserve((size_t)ncols);
    auto *top_s = (ArrowSchema *)malloc(sizeof(ArrowSchema));
    auto *top_a = (ArrowArray *)malloc(sizeof(ArrowArray));
    if (top_s == nullptr || top_a == nullptr) {
        free(top_s);
        free(top_a);
        delete h;
        return PyErr_NoMemory();
    }
    bool mixed = false;
    Py_BEGIN_ALLOW_THREADS;
    {
        const uint64_t _tr0 = trace_on() ? trace_now_ns() : 0;
        /* pass 1: unified tag per column (NB_NONE cells don't count).
         * String columns also sum their data bytes: utf8 exports with
         * int32 offsets, so a column past INT32_MAX data bytes takes
         * the same not-exportable verdict as a mixed-tag column (row
         * fallback) instead of silently wrapping the offsets. */
        std::vector<uint8_t> unified((size_t)width, NB_NONE);
        for (int c = 0; c < width && !mixed; c++) {
            const NbCol &col = (*nb->cols)[(size_t)c];
            uint8_t u = NB_NONE;
            uint64_t str_bytes = 0;
            for (size_t i = 0; i < n; i++) {
                const uint8_t t = col.tag[i];
                if (t == NB_NONE)
                    continue;
                if (t == NB_STR)
                    str_bytes += col.len[i];
                if (u == NB_NONE)
                    u = t;
                else if (u != t) {
                    mixed = true;
                    break;
                }
            }
            if (str_bytes > (uint64_t)INT32_MAX)
                mixed = true;
            unified[(size_t)c] = u;
        }
        if (!mixed) {
            for (int c = 0; c < width; c++)
                arrow_build_col(h, (*nb->cols)[(size_t)c], n,
                                unified[(size_t)c],
                                colnames[(size_t)c].c_str());
            if (include_key)
                arrow_build_fixed_col(h, "w:16", "_key", nb->keys->data(),
                                      n * 16, n);
            if (include_diff) {
                std::vector<int64_t> ones(n, 1);
                arrow_build_fixed_col(h, "l", "diff", ones.data(), n * 8,
                                      n);
            }
            h->schema_children.resize((size_t)ncols);
            h->array_children.resize((size_t)ncols);
            for (int c = 0; c < ncols; c++) {
                h->schema_children[(size_t)c] = &h->schemas[(size_t)c];
                h->array_children[(size_t)c] = &h->arrays[(size_t)c];
            }
            top_s->format = "+s";
            top_s->name = "";
            top_s->metadata = nullptr;
            top_s->flags = 0;
            top_s->n_children = ncols;
            top_s->children = h->schema_children.data();
            top_s->dictionary = nullptr;
            top_s->release = pw_arrow_schema_release;
            top_s->private_data = h;
            h->bufptrs.emplace_back(std::vector<const void *>{nullptr});
            top_a->length = (int64_t)n;
            top_a->null_count = 0;
            top_a->offset = 0;
            top_a->n_buffers = 1;
            top_a->n_children = ncols;
            top_a->buffers = h->bufptrs.back().data();
            top_a->children = h->array_children.data();
            top_a->dictionary = nullptr;
            top_a->release = pw_arrow_array_release;
            top_a->private_data = h;
        }
        if (_tr0)
            trace_note(T_ARROW_EXPORT, -1, _tr0, trace_now_ns(),
                       (int64_t)n);
    }
    Py_END_ALLOW_THREADS;
    if (mixed) {
        delete h;
        free(top_s);
        free(top_a);
        Py_RETURN_NONE;
    }
    return Py_BuildValue("(KK)", (unsigned long long)(uintptr_t)top_s,
                         (unsigned long long)(uintptr_t)top_a);
}

/* arrow_shells_free(schema_addr, array_addr) — return the two malloc'd
 * shells after the consumer imported (moved) them. A shell whose
 * release survived (import never ran / failed) is released here so the
 * donation can't leak. */
PyObject *arrow_shells_free(PyObject *, PyObject *args)
{
    unsigned long long s_addr, a_addr;
    if (!PyArg_ParseTuple(args, "KK", &s_addr, &a_addr))
        return nullptr;
    auto *s = (ArrowSchema *)(uintptr_t)s_addr;
    auto *a = (ArrowArray *)(uintptr_t)a_addr;
    if (a != nullptr) {
        if (a->release != nullptr)
            a->release(a);
        free(a);
    }
    if (s != nullptr) {
        if (s->release != nullptr)
            s->release(s);
        free(s);
    }
    Py_RETURN_NONE;
}

/* capture_collect_nb(chunks) -> NativeBatch
 *
 * The columnar capture collector (ISSUE 14): takes the CaptureNode's
 * pending [(NativeBatch, time), ...] chunks and returns ONE C-owned
 * NativeBatch of width+1 whose last column is each chunk's commit
 * timestamp (NB_INT) — committed output stays typed column buffers end
 * to end, ready for one nb_export_arrow, with zero per-row Python.
 * Width must agree across chunks (they come from one node's output). */
PyObject *capture_collect_nb(PyObject *, PyObject *args)
{
    PyObject *lst;
    if (!PyArg_ParseTuple(args, "O!", &PyList_Type, &lst))
        return nullptr;
    Py_ssize_t k = PyList_GET_SIZE(lst);
    if (k == 0) {
        PyErr_SetString(PyExc_ValueError, "capture_collect_nb: empty");
        return nullptr;
    }
    std::vector<NativeBatchObject *> srcs((size_t)k);
    std::vector<int64_t> times((size_t)k);
    for (Py_ssize_t j = 0; j < k; j++) {
        PyObject *item = PyList_GET_ITEM(lst, j);
        if (!PyTuple_Check(item) || PyTuple_GET_SIZE(item) != 2 ||
            !PyObject_TypeCheck(PyTuple_GET_ITEM(item, 0),
                                &NativeBatchType)) {
            PyErr_SetString(PyExc_TypeError,
                            "capture_collect_nb: [(nb, time), ...]");
            return nullptr;
        }
        long long t = PyLong_AsLongLong(PyTuple_GET_ITEM(item, 1));
        if (t == -1 && PyErr_Occurred())
            return nullptr;
        srcs[(size_t)j] = reinterpret_cast<NativeBatchObject *>(
            PyTuple_GET_ITEM(item, 0));
        times[(size_t)j] = (int64_t)t;
    }
    const int width = srcs[0]->width;
    for (Py_ssize_t j = 1; j < k; j++)
        if (srcs[(size_t)j]->width != width) {
            PyErr_SetString(PyExc_ValueError,
                            "capture_collect_nb: width mismatch");
            return nullptr;
        }
    NativeBatchObject *out = nb_alloc(width + 1, srcs[0]->ptr_type);
    if (out == nullptr)
        return nullptr;
    /* pin the sources with the GIL held (same discipline as nb_concat:
     * the caller's list could drop an item while this runs GIL-free) */
    for (Py_ssize_t j = 0; j < k; j++)
        Py_INCREF(srcs[(size_t)j]);
    Py_BEGIN_ALLOW_THREADS;
    {
        const uint64_t _tr0 = trace_on() ? trace_now_ns() : 0;
        NbCol &tc = (*out->cols)[(size_t)width];
        for (Py_ssize_t j = 0; j < k; j++) {
            NativeBatchObject *src = srcs[(size_t)j];
            out->keys->insert(out->keys->end(), src->keys->begin(),
                              src->keys->end());
            for (int c = 0; c < width; c++)
                nbcol_append((*out->cols)[(size_t)c],
                             (*src->cols)[(size_t)c]);
            const size_t nj = (size_t)src->n;
            tc.tag.insert(tc.tag.end(), nj, NB_INT);
            tc.word.insert(tc.word.end(), nj, times[(size_t)j]);
            tc.len.insert(tc.len.end(), nj, 0);
        }
        out->n = (Py_ssize_t)out->keys->size();
        if (_tr0)
            trace_note(T_ARROW_EXPORT, -1, _tr0, trace_now_ns(),
                       (int64_t)out->n);
    }
    Py_END_ALLOW_THREADS;
    for (Py_ssize_t j = 0; j < k; j++)
        Py_DECREF(srcs[(size_t)j]);
    return reinterpret_cast<PyObject *>(out);
}

/* process_batch_nb(store, nb, g_idxs, arg_idxs, key_fn, error
 *                  [, time, out_type])
 *
 * The fused chain step: one C call takes a columnar batch through
 * extract→apply→emit with zero per-row Python objects. Python appears
 * only once per NEW group (gvals tuple + key_fn output-Pointer mint) and
 * once per CHANGED group output row. Restricted to all-abelian stores
 * (count/sum/avg — no joint multiset, no sort_by); anything else raises
 * Fallback and the node materializes the batch into the general path.
 * out_type (a list subclass, e.g. ConsolidatedList) lets the caller get
 * its net-form batch type back without a post-hoc copy.
 *
 * Replay invariant (mirrors process_batch): NO Fallback beyond phase 1.
 * Phase 1 mutates nothing, so a Fallback there is safely replayed via
 * the materialized path. Any error raised AFTER phase 1 (a key_fn
 * exception in emit, memory errors) leaves the batch half-applied in
 * reducer state: the caller must treat the store as poisoned for replay
 * and demote the node (GroupByNode._poison_demote) instead of retrying
 * the batch. */
PyObject *process_batch_nb(PyObject *, PyObject *args)
{
    PyObject *capsule, *nb_obj, *g_idxs, *arg_idxs, *key_fn, *error_obj;
    /* batch_time is reserved for signature parity with process_batch —
     * the abelian-only path needs no creation stamps today */
    long long batch_time = 0;
    PyObject *out_type = nullptr;
    if (!PyArg_ParseTuple(args, "OO!OOOO|LO", &capsule, &NativeBatchType,
                          &nb_obj, &g_idxs, &arg_idxs, &key_fn, &error_obj,
                          &batch_time, &out_type))
        return nullptr;
    (void)batch_time;
    GroupStore *store = get_store(capsule);
    if (store == nullptr)
        return nullptr;
    auto *nb = reinterpret_cast<NativeBatchObject *>(nb_obj);
    const int W = store->n_shards;
    const size_t n_specs = store->codes.size();
    if (store->has_ms || store->has_order) {
        PyErr_SetString(FallbackError, "nb path is abelian-only");
        return nullptr;
    }
    if (!PyTuple_Check(g_idxs) || !PyTuple_Check(arg_idxs) ||
        PyTuple_GET_SIZE(arg_idxs) != (Py_ssize_t)n_specs) {
        PyErr_SetString(PyExc_TypeError,
                        "process_batch_nb: index tuples");
        return nullptr;
    }
    const Py_ssize_t ng = PyTuple_GET_SIZE(g_idxs);
    std::vector<int> gidx((size_t)ng);
    for (Py_ssize_t j = 0; j < ng; j++) {
        long v = PyLong_AsLong(PyTuple_GET_ITEM(g_idxs, j));
        if (v < 0 || v >= nb->width) {
            PyErr_SetString(PyExc_ValueError, "process_batch_nb: g idx");
            return nullptr;
        }
        gidx[(size_t)j] = (int)v;
    }
    std::vector<int> aidx(n_specs, -1); /* -1 = argless (count) */
    for (size_t s = 0; s < n_specs; s++) {
        PyObject *it = PyTuple_GET_ITEM(arg_idxs, (Py_ssize_t)s);
        if (it == Py_None)
            continue;
        long v = PyLong_AsLong(it);
        if (v < 0 || v >= nb->width) {
            PyErr_SetString(PyExc_ValueError, "process_batch_nb: arg idx");
            return nullptr;
        }
        aidx[s] = (int)v;
    }

    const Py_ssize_t n = nb->n;
    auto _t0 = std::chrono::steady_clock::now();
    /* flat per-row layout — no per-row heap allocations: serialized
     * group keys share one arena, reducer args share one flat Val
     * buffer (phase 1 is ~half the fused path's C time at wordcount
     * shapes; allocation-free extraction is what keeps it there) */
    struct NbRow {
        uint32_t shard;
        uint32_t koff, klen;
    };
    std::vector<NbRow> rows((size_t)n);
    std::vector<Val> valbuf((size_t)(n * (Py_ssize_t)n_specs));
    std::string keybuf;
    keybuf.reserve((size_t)n * 24);
    SvHash hasher;
    /* phase 1: extract — pure C over the columnar image */
    for (Py_ssize_t i = 0; i < n; i++) {
        NbRow &r = rows[(size_t)i];
        r.koff = (uint32_t)keybuf.size();
        uint32_t un = (uint32_t)ng;
        keybuf.append(reinterpret_cast<const char *>(&un), 4);
        for (Py_ssize_t j = 0; j < ng; j++)
            nb_ser_cell(keybuf, (*nb->cols)[(size_t)gidx[(size_t)j]], i);
        r.klen = (uint32_t)keybuf.size() - r.koff;
        r.shard = (uint32_t)(
            hasher(std::string_view(keybuf.data() + r.koff, r.klen)) %
            (size_t)W);
        Val *vals = &valbuf[(size_t)(i * (Py_ssize_t)n_specs)];
        for (size_t s = 0; s < n_specs; s++) {
            Val &v = vals[s];
            v.obj = nullptr;
            if (aidx[s] < 0 || store->codes[s] == C_COUNT) {
                v.tag = V_NONE;
                continue;
            }
            const NbCol &c = (*nb->cols)[(size_t)aidx[s]];
            switch (c.tag[(size_t)i]) {
            case NB_NONE:
                v.tag = V_NONE;
                break;
            case NB_BOOL:
            case NB_INT:
                v.tag = V_INT;
                v.i = c.word[(size_t)i];
                break;
            case NB_FLT: {
                double d;
                int64_t w = c.word[(size_t)i];
                memcpy(&d, &w, 8);
                v.tag = V_FLT;
                v.f = d;
                break;
            }
            default:
                /* string arg into sum/avg: Python raises — route the
                 * batch to the general path for identical surfacing */
                PyErr_SetString(FallbackError, "string arg in nb reducer");
                return nullptr;
            }
        }
    }

    phase_add(store, &PhaseStats::extract_s, _t0);
    phase_count(store, (int64_t)n);
    auto _t1 = std::chrono::steady_clock::now();

    /* phase 2: apply (GIL released) — shard-parallel abelian updates */
    struct NbAffected {
        Group *g;
        int32_t first_row;
        int64_t before_total;
        std::vector<FinSnap> before;
    };
    std::vector<std::vector<NbAffected>> affected((size_t)W);
    {
        std::vector<std::vector<int32_t>> shard_rows((size_t)W);
        for (Py_ssize_t i = 0; i < n; i++)
            shard_rows[rows[(size_t)i].shard].push_back((int32_t)i);
        auto work = [&](int w) {
            Shard &sh = store->shards[(size_t)w];
            auto &aff = affected[(size_t)w];
            std::unordered_map<std::string_view, size_t> touched;
            for (int32_t ri : shard_rows[(size_t)w]) {
                NbRow &r = rows[(size_t)ri];
                std::string_view kv(keybuf.data() + r.koff, r.klen);
                auto it = PW_SV_FIND(sh.groups, kv);
                bool created = false;
                if (it == sh.groups.end()) {
                    it = sh.groups.emplace(std::string(kv), Group{}).first;
                    it->second.st.resize(n_specs);
                    created = true;
                }
                Group &g = it->second;
                if (touched.find(kv) == touched.end()) {
                    touched.emplace(kv, aff.size());
                    NbAffected a;
                    a.g = &g;
                    a.first_row = ri;
                    a.before_total = created ? 0 : g.total;
                    a.before.reserve(n_specs);
                    for (size_t s = 0; s < n_specs; s++)
                        a.before.push_back(snap_of(store->codes[s], g.st[s]));
                    aff.push_back(std::move(a));
                }
                g.total += 1; /* nb batches are insert-only (+1) */
                const Val *vals =
                    &valbuf[(size_t)ri * n_specs];
                for (size_t s = 0; s < n_specs; s++)
                    apply_spec(store->codes[s], g.st[s], vals[s], 1);
            }
        };
        Py_BEGIN_ALLOW_THREADS
        const uint64_t _tr0 = trace_on() ? trace_now_ns() : 0;
        if (W > 1 && n >= 2048) {
            std::vector<std::thread> threads;
            threads.reserve((size_t)W);
            for (int w = 0; w < W; w++)
                threads.emplace_back(
                    [&work](int ww) {
                        const uint64_t t0 =
                            trace_on() ? trace_now_ns() : 0;
                        work(ww);
                        if (t0)
                            trace_note(T_GB_APPLY, ww, t0,
                                       trace_now_ns(), -1);
                    },
                    w);
            for (auto &t : threads)
                t.join();
        } else {
            for (int w = 0; w < W; w++)
                work(w);
        }
        if (_tr0)
            trace_note(T_GB_APPLY, -1, _tr0, trace_now_ns(), (int64_t)n);
        Py_END_ALLOW_THREADS
    }

    phase_add(store, &PhaseStats::apply_s, _t1);
    auto _t2 = std::chrono::steady_clock::now();

    /* phase 3: emit (GIL held) — Python only for new-group mints and
     * changed-group output rows */
    PyObject *out;
    if (out_type != nullptr && out_type != Py_None) {
        out = PyObject_CallNoArgs(out_type);
        if (out != nullptr && !PyList_Check(out)) {
            PyErr_SetString(PyExc_TypeError,
                            "process_batch_nb: out_type must be a list "
                            "subclass");
            Py_DECREF(out);
            out = nullptr;
        }
    } else {
        out = PyList_New(0);
    }
    bool failed = out == nullptr;
    for (int w = 0; w < W && !failed; w++) {
        for (NbAffected &a : affected[(size_t)w]) {
            Group &g = *a.g;
            /* mint into locals and commit gvals/out_key together only on
             * success (re-minting when a previous batch failed mid-mint):
             * a key_fn exception must never leave gvals set with a null
             * out_key for a later batch to Py_INCREF (latent segfault,
             * ADVICE r5). */
            if (g.out_key == nullptr) {
                PyObject *gv = g.gvals;
                if (gv == nullptr) {
                    gv = PyTuple_New(ng);
                    if (gv == nullptr) {
                        failed = true;
                        break;
                    }
                    bool bad = false;
                    for (Py_ssize_t j = 0; j < ng; j++) {
                        PyObject *x = nb_cell_to_py(
                            (*nb->cols)[(size_t)gidx[(size_t)j]],
                            (Py_ssize_t)a.first_row);
                        if (x == nullptr) {
                            bad = true;
                            break;
                        }
                        PyTuple_SET_ITEM(gv, j, x);
                    }
                    if (bad) {
                        Py_DECREF(gv);
                        failed = true;
                        break;
                    }
                }
                PyObject *ok = PyObject_CallOneArg(key_fn, gv);
                if (ok == nullptr) {
                    if (gv != g.gvals)
                        Py_DECREF(gv);
                    failed = true;
                    break;
                }
                g.gvals = gv;
                g.out_key = ok;
            }
            bool before_live = a.before_total > 0;
            bool after_live = g.total > 0;
            bool changed = before_live != after_live;
            std::vector<FinSnap> after;
            if (after_live) {
                after.reserve(n_specs);
                for (size_t s = 0; s < n_specs; s++)
                    after.push_back(snap_of(store->codes[s], g.st[s]));
            }
            if (!changed && after_live)
                for (size_t s = 0; s < n_specs && !changed; s++)
                    changed = !finish_equal(store->codes[s], a.before[s],
                                            after[s]);
            if (changed) {
                Py_ssize_t ngv = PyTuple_GET_SIZE(g.gvals);
                auto emit = [&](const std::vector<FinSnap> &st,
                                long dir) -> int {
                    PyObject *row = PyTuple_New(ngv + (Py_ssize_t)n_specs);
                    if (row == nullptr)
                        return -1;
                    for (Py_ssize_t j = 0; j < ngv; j++) {
                        PyObject *x = PyTuple_GET_ITEM(g.gvals, j);
                        Py_INCREF(x);
                        PyTuple_SET_ITEM(row, j, x);
                    }
                    for (size_t s = 0; s < n_specs; s++) {
                        PyObject *v = finish_snap(store->codes[s], st[s],
                                                  error_obj);
                        if (v == nullptr) {
                            Py_DECREF(row);
                            return -1;
                        }
                        PyTuple_SET_ITEM(row, ngv + (Py_ssize_t)s, v);
                    }
                    PyObject *delta = PyTuple_New(3);
                    if (delta == nullptr) {
                        Py_DECREF(row);
                        return -1;
                    }
                    Py_INCREF(g.out_key);
                    PyTuple_SET_ITEM(delta, 0, g.out_key);
                    PyTuple_SET_ITEM(delta, 1, row);
                    PyObject *d = PyLong_FromLong(dir);
                    if (d == nullptr) {
                        Py_DECREF(delta);
                        return -1;
                    }
                    PyTuple_SET_ITEM(delta, 2, d);
                    int rc = PyList_Append(out, delta);
                    Py_DECREF(delta);
                    return rc;
                };
                if (before_live && emit(a.before, -1) < 0) {
                    failed = true;
                    break;
                }
                if (after_live && emit(after, 1) < 0) {
                    failed = true;
                    break;
                }
            }
            /* insert-only batches never fully retract a group */
        }
    }
    phase_add(store, &PhaseStats::emit_s, _t2);
    if (failed) {
        Py_XDECREF(out);
        return nullptr;
    }
    return out;
}

/* ---- store_phase_stats(store) -> dict --------------------------------- */

PyObject *phase_stats(PyObject *, PyObject *)
{
    return Py_BuildValue(
        "{s:d,s:d,s:d,s:L,s:L,s:{s:d,s:d,s:d,s:L,s:L}}",
        "extract_s", g_phases.extract_s,
        "apply_s", g_phases.apply_s,
        "emit_s", g_phases.emit_s,
        "batches", (long long)g_phases.batches,
        "rows", (long long)g_phases.rows,
        "join",
        "extract_s", g_join_phases.extract_s,
        "apply_s", g_join_phases.apply_s,
        "emit_s", g_join_phases.emit_s,
        "batches", (long long)g_join_phases.batches,
        "rows", (long long)g_join_phases.rows);
}

PyObject *phase_stats_reset(PyObject *, PyObject *)
{
    g_phases = PhaseStats{};
    g_join_phases = PhaseStats{};
    Py_RETURN_NONE;
}

PyObject *store_phase_stats(PyObject *, PyObject *arg)
{
    GroupStore *s = get_store(arg);
    if (s == nullptr)
        return nullptr;
    return Py_BuildValue(
        "{s:d,s:d,s:d,s:L,s:L}",
        "extract_s", s->phases.extract_s,
        "apply_s", s->phases.apply_s,
        "emit_s", s->phases.emit_s,
        "batches", (long long)s->phases.batches,
        "rows", (long long)s->phases.rows);
}

/* ---- flight-recorder ring: enable / disable / drain ------------------ */

PyObject *trace_ring_enable(PyObject *, PyObject *args)
{
    long cap = 65536;
    long n_threads = 8;
    if (!PyArg_ParseTuple(args, "|ll", &cap, &n_threads))
        return nullptr;
    if (cap < 16)
        cap = 16;
    if (cap > (1 << 24))
        cap = 1 << 24;
    if (n_threads < 0)
        n_threads = 0;
    if (n_threads > PW_TRACE_RINGS - 1)
        n_threads = PW_TRACE_RINGS - 1;
    /* Already armed (another runtime of this process — the emulated
     * rank lane runs several per process): keep the live buffers.
     * Touching them under a concurrent writer would be a use-after-
     * free; the first armer's configuration wins for the overlap. */
    if (g_trace_on.load(std::memory_order_acquire))
        Py_RETURN_NONE;
    /* rings 0..n_threads get capacity; the rest stay empty (writes to
     * them drop — trace_note's cap==0 check). A ring is allocated
     * exactly ONCE per process: a straggler note racing the previous
     * disarm may sit between its cap read and the slot write, so any
     * reallocation here — even growth — would be a use-after-free with
     * a stale modulus. The first enable's capacity therefore sticks
     * for the process lifetime (PATHWAY_TRACE_RING_EVENTS changes need
     * a fresh process). */
    for (long k = 0; k < PW_TRACE_RINGS; k++) {
        TraceRing &r = g_trace_rings[(size_t)k];
        if (k <= n_threads && r.ev.empty())
            r.ev.assign((size_t)cap, TraceEv{});
        r.w.store(0, std::memory_order_release);
        r.drained = 0;
    }
    g_trace_on.store(1, std::memory_order_release);
    Py_RETURN_NONE;
}

PyObject *trace_ring_disable(PyObject *, PyObject *)
{
    /* flag only — NEVER free the buffers: procgroup receiver threads
     * may be mid-note (nb_decode runs asynchronously to engine steps),
     * and a clear()+shrink here would turn that into a use-after-free.
     * The storage (a few MB, only ever allocated when tracing was
     * armed) stays until the next enable resizes it. */
    g_trace_on.store(0, std::memory_order_release);
    Py_RETURN_NONE;
}

PyObject *trace_ring_drain(PyObject *, PyObject *)
{
    /* GIL-held reader: return events in [drained, wend) as (tag, thr,
     * t0_ns, t1_ns, rows) and advance the reader-only watermark — the
     * writer index is never reset, so concurrent writers (receiver
     * threads) cannot race a reader-side reset. A ring that wrapped
     * past the watermark yields only its newest `cap` events. */
    PyObject *out = PyList_New(0);
    if (out == nullptr)
        return nullptr;
    for (size_t k = 0; k < PW_TRACE_RINGS; k++) {
        TraceRing &r = g_trace_rings[k];
        const size_t cap = r.ev.size();
        if (cap == 0)
            continue;
        const uint64_t wend = r.w.load(std::memory_order_acquire);
        uint64_t start = r.drained;
        if (wend > start + cap)
            start = wend - cap;
        for (uint64_t i = start; i < wend; i++) {
            const TraceEv &e = r.ev[(size_t)(i % cap)];
            PyObject *t = Py_BuildValue(
                "(iiKKL)", (int)e.tag, (int)e.thr,
                (unsigned long long)e.t0, (unsigned long long)e.t1,
                (long long)e.rows);
            if (t == nullptr || PyList_Append(out, t) < 0) {
                Py_XDECREF(t);
                Py_DECREF(out);
                return nullptr;
            }
            Py_DECREF(t);
        }
        r.drained = wend;
    }
    return out;
}

/* ---- wire_entropy: auto-codec compressibility probe (ISSUE 13) ------
 * Sampled Shannon entropy (bits/byte) of a buffer: the fast-wire auto
 * mode skips the compressor on blobs whose byte distribution says they
 * will not shrink (random floats, pre-compressed payloads) — the probe
 * must cost microseconds where the codec would cost milliseconds.
 * Samples up to 64 KiB at an even stride, GIL-free. */

PyObject *wire_entropy(PyObject *, PyObject *args)
{
    Py_buffer buf;
    if (!PyArg_ParseTuple(args, "y*", &buf))
        return nullptr;
    double bits = 0.0;
    Py_BEGIN_ALLOW_THREADS;
    {
        const unsigned char *p = (const unsigned char *)buf.buf;
        const size_t n = (size_t)buf.len;
        const size_t max_sample = 64 * 1024;
        const size_t stride = n > max_sample ? n / max_sample : 1;
        uint64_t hist[256] = {0};
        uint64_t total = 0;
        for (size_t i = 0; i < n; i += stride) {
            hist[p[i]]++;
            total++;
        }
        if (total > 1) {
            const double inv = 1.0 / (double)total;
            for (int b = 0; b < 256; b++) {
                if (hist[b]) {
                    const double f = (double)hist[b] * inv;
                    bits -= f * std::log2(f);
                }
            }
        }
    }
    Py_END_ALLOW_THREADS;
    PyBuffer_Release(&buf);
    return PyFloat_FromDouble(bits);
}

PyMethodDef methods[] = {
    {"wp_new", wp_new, METH_VARARGS,
     "wp_new(cache_size) -> wordpiece memo capsule"},
    {"wp_tokenize_padded", wp_tokenize_padded, METH_VARARGS,
     "wp_tokenize_padded(store, texts, budget, cls, sep, pad, fallback) "
     "-> (ids_bytes, mask_bytes, n, longest) | None"},
    {"wp_len", wp_len, METH_O, "number of memoized words"},
    {"wp_tokenize", wp_tokenize, METH_VARARGS,
     "wp_tokenize(store, texts, budget, cls, sep, fallback) -> "
     "[ids_bytes|None, ...]"},
    {"store_new", store_new, METH_VARARGS,
     "store_new(n_shards, codes[, has_order]) -> capsule"},
    {"store_len", store_len, METH_O, "number of live groups"},
    {"store_nbytes", store_nbytes, METH_O,
     "estimated bytes held by a GroupStore (GIL-free walk)"},
    {"phase_stats", phase_stats, METH_NOARGS,
     "process-wide per-phase wall time (all group stores)"},
    {"phase_stats_reset", phase_stats_reset, METH_NOARGS,
     "zero the process-wide phase accumulators"},
    {"store_phase_stats", store_phase_stats, METH_O,
     "per-phase wall time {extract_s, apply_s (GIL-free), emit_s, "
     "batches, rows}"},
    {"store_dump", store_dump, METH_O,
     "picklable [(gvals, out_key, total, states)]"},
    {"store_load", store_load, METH_VARARGS, "restore a dumped store"},
    {"process_batch", process_batch, METH_VARARGS,
     "process_batch(store, gvals, keys, valcols, diffs, key_fn, error"
     "[, time, ordercol]) -> deltas"},
    {"join_store_new", join_store_new, METH_VARARGS,
     "join_store_new(n_shards, jtype, id_mode, lwidth, rwidth) -> capsule"},
    {"join_store_len", join_store_len, METH_O, "number of live join keys"},
    {"join_store_nbytes", join_store_nbytes, METH_O,
     "estimated bytes held by a JoinStore (GIL-free walk)"},
    {"join_store_dump", join_store_dump, METH_O,
     "picklable [(jk, left_entries, right_entries)]"},
    {"join_store_load", join_store_load, METH_VARARGS,
     "restore a dumped join store"},
    {"join_batch", join_batch, METH_VARARGS,
     "join_batch(store, ljks, lkeys, lrows, ldiffs, rjks, rkeys, rrows, "
     "rdiffs, pair_key_fn, id_fn) -> deltas"},
    {"join_batch_nb", join_batch_nb, METH_VARARGS,
     "join_batch_nb(store, lnb, rnb, lkidx, rkidx, ptr_type) -> "
     "NativeBatch | (deltas, dup_bump) — fused columnar delta join"},
    {"pk_session_new", pk_session_new, METH_NOARGS,
     "pk_session_new() -> C-owned primary-key upsert session"},
    {"pk_session_dump", pk_session_dump, METH_VARARGS,
     "pk_session_dump(session, live_rows, ptr_type, width) — demote the "
     "C session into the Python live-rows dict"},
    {"parse_pk_upserts_nb", parse_pk_upserts_nb, METH_VARARGS,
     "parse_pk_upserts_nb(dicts, cols, defaults, pkeys, session, "
     "live_rows, ptr_type) -> NativeBatch | None (demoted)"},
    {"shard_partition_nb", shard_partition_nb, METH_VARARGS,
     "shard_partition_nb(nb, kidx|None, world) -> [NativeBatch]*world "
     "(stable_shard-parity columnar partition, GIL-free)"},
    {"nb_encode", nb_encode, METH_VARARGS,
     "nb_encode(nb) -> bytes (exchange v2 typed columnar buffer)"},
    {"wire_entropy", wire_entropy, METH_VARARGS,
     "wire_entropy(buffer) -> sampled Shannon entropy in bits/byte "
     "(fast-wire auto-codec compressibility probe, GIL-free)"},
    {"nb_decode", nb_decode, METH_VARARGS,
     "nb_decode(buffer, ptr_type) -> NativeBatch"},
    {"nb_concat", nb_concat, METH_VARARGS,
     "nb_concat([nb, ...]) -> NativeBatch (arena-rebased append)"},
    {"deltas_encode", deltas_encode, METH_VARARGS,
     "deltas_encode(deltas) -> bytes | None (typed columnar buffer for "
     "retraction-bearing slices; None = non-scalar cells, pickle instead)"},
    {"deltas_decode", deltas_decode, METH_VARARGS,
     "deltas_decode(buffer, ptr_type[, intern]) -> [(key, row, diff), "
     "...]; intern = intern_new() capsule for cross-frame key/string "
     "reuse (one per receiver link)"},
    {"intern_new", intern_new, METH_VARARGS,
     "intern_new([capacity]) -> wire intern-cache capsule "
     "(cross-frame Pointer/str reuse for deltas_decode; epoch-resets "
     "at capacity)"},
    {"nb_project", nb_project, METH_VARARGS,
     "nb_project(nb, idxs) -> NativeBatch — columnar column projection"},
    {"capture_apply_nb", capture_apply_nb, METH_VARARGS,
     "capture_apply_nb(rows_dict, updates, nb, time) — one-pass columnar "
     "capture expansion"},
    {"capture_collect_nb", capture_collect_nb, METH_VARARGS,
     "capture_collect_nb([(nb, time), ...]) -> NativeBatch — C-owned "
     "columnar capture collector (width+1: appended int64 time column)"},
    {"nb_export_arrow", nb_export_arrow, METH_VARARGS,
     "nb_export_arrow(nb, names[, include_key, include_diff]) -> "
     "(schema_addr, array_addr) | None — donating Arrow C-data-interface "
     "export (GIL-free assembly; None = mixed-tag column, row fallback)"},
    {"arrow_shells_free", arrow_shells_free, METH_VARARGS,
     "arrow_shells_free(schema_addr, array_addr) — free the malloc'd "
     "shells after import; releases un-imported donations"},
    {"parse_upserts_nb", parse_upserts_nb, METH_VARARGS,
     "parse_upserts_nb(msgs, start, cols, defaults, key_base, seq0, ptr) "
     "-> (NativeBatch, new_seq) | None"},
    {"process_batch_nb", process_batch_nb, METH_VARARGS,
     "process_batch_nb(store, nb, g_idxs, arg_idxs, key_fn, error"
     "[, time]) -> deltas (abelian-only fused chain step)"},
    {"trace_ring_enable", trace_ring_enable, METH_VARARGS,
     "trace_ring_enable([capacity, n_threads]) — preallocate the "
     "per-thread flight-recorder rings and arm GIL-free batch timers"},
    {"trace_ring_disable", trace_ring_disable, METH_NOARGS,
     "disarm the flight-recorder rings and free their buffers"},
    {"trace_ring_drain", trace_ring_drain, METH_NOARGS,
     "trace_ring_drain() -> [(tag, thr, t0_ns, t1_ns, rows)] — drain + "
     "reset the rings (call between engine steps)"},
    {nullptr, nullptr, 0, nullptr},
};

struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT,
    "pwexec",
    "Sharded native group-by executor.",
    -1,
    methods,
};

} // namespace

PyMODINIT_FUNC PyInit_pwexec(void)
{
    PyObject *m = PyModule_Create(&moduledef);
    if (m == nullptr)
        return nullptr;
    FallbackError =
        PyErr_NewException("pwexec.Fallback", PyExc_Exception, nullptr);
    Py_INCREF(FallbackError);
    PyModule_AddObject(m, "Fallback", FallbackError);
    if (PyType_Ready(&NativeBatchType) < 0) {
        Py_DECREF(m);
        return nullptr;
    }
    Py_INCREF(&NativeBatchType);
    PyModule_AddObject(m, "NativeBatch", (PyObject *)&NativeBatchType);
    return m;
}
