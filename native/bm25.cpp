// Native BM25 full-text index — C++ core for pathway_tpu.stdlib.indexing
// (the tantivy-equivalent; reference native core:
// src/external_integration/tantivy_integration.rs). C ABI over opaque
// handles; Python side at pathway_tpu/native/__init__.py.

#include <algorithm>
#include <cctype>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct Posting {
    std::unordered_map<int64_t, int32_t> tf;  // doc -> term frequency
};

struct Bm25Index {
    double k1;
    double b;
    std::unordered_map<std::string, Posting> postings;
    std::unordered_map<int64_t, int32_t> doc_len;
    int64_t total_len = 0;

    void tokenize(const char* text, std::vector<std::string>& out) const {
        out.clear();
        std::string cur;
        for (const char* p = text; *p; ++p) {
            unsigned char c = static_cast<unsigned char>(*p);
            if (std::isalnum(c) || c == '_') {
                cur.push_back(static_cast<char>(std::tolower(c)));
            } else if (!cur.empty()) {
                out.push_back(cur);
                cur.clear();
            }
        }
        if (!cur.empty()) out.push_back(cur);
    }

    void remove_doc(int64_t key) {
        auto it = doc_len.find(key);
        if (it == doc_len.end()) return;
        total_len -= it->second;
        doc_len.erase(it);
        for (auto pit = postings.begin(); pit != postings.end();) {
            pit->second.tf.erase(key);
            if (pit->second.tf.empty()) {
                pit = postings.erase(pit);
            } else {
                ++pit;
            }
        }
    }

    void add_doc(int64_t key, const char* text) {
        remove_doc(key);
        std::vector<std::string> toks;
        tokenize(text, toks);
        doc_len[key] = static_cast<int32_t>(toks.size());
        total_len += static_cast<int64_t>(toks.size());
        for (const auto& t : toks) {
            postings[t].tf[key] += 1;
        }
    }

    // returns up to k (key, score) pairs, best first
    int64_t search(const char* query, int64_t k, int64_t* out_keys,
                   double* out_scores) const {
        if (doc_len.empty() || k <= 0) return 0;
        const double n = static_cast<double>(doc_len.size());
        const double avg_len = static_cast<double>(total_len) / n;
        std::vector<std::string> toks;
        tokenize(query, toks);
        std::unordered_map<int64_t, double> scores;
        for (const auto& t : toks) {
            auto pit = postings.find(t);
            if (pit == postings.end()) continue;
            const double df = static_cast<double>(pit->second.tf.size());
            const double idf = std::log(1.0 + (n - df + 0.5) / (df + 0.5));
            for (const auto& [key, tf] : pit->second.tf) {
                const double dl = static_cast<double>(doc_len.at(key));
                const double denom =
                    tf + k1 * (1.0 - b + b * dl / avg_len);
                scores[key] += idf * tf * (k1 + 1.0) / denom;
            }
        }
        std::vector<std::pair<double, int64_t>> ranked;
        ranked.reserve(scores.size());
        for (const auto& [key, s] : scores) ranked.emplace_back(s, key);
        std::sort(ranked.begin(), ranked.end(),
                  [](const auto& a, const auto& b2) {
                      if (a.first != b2.first) return a.first > b2.first;
                      return a.second < b2.second;
                  });
        const int64_t out_n =
            std::min<int64_t>(k, static_cast<int64_t>(ranked.size()));
        for (int64_t i = 0; i < out_n; ++i) {
            out_keys[i] = ranked[static_cast<size_t>(i)].second;
            out_scores[i] = ranked[static_cast<size_t>(i)].first;
        }
        return out_n;
    }
};

}  // namespace

extern "C" {

void* bm25_new(double k1, double b) { return new Bm25Index{k1, b}; }

void bm25_free(void* h) { delete static_cast<Bm25Index*>(h); }

void bm25_add(void* h, int64_t key, const char* text) {
    static_cast<Bm25Index*>(h)->add_doc(key, text);
}

void bm25_remove(void* h, int64_t key) {
    static_cast<Bm25Index*>(h)->remove_doc(key);
}

int64_t bm25_len(void* h) {
    return static_cast<int64_t>(static_cast<Bm25Index*>(h)->doc_len.size());
}

int64_t bm25_search(void* h, const char* query, int64_t k, int64_t* out_keys,
                    double* out_scores) {
    return static_cast<Bm25Index*>(h)->search(query, k, out_keys, out_scores);
}

}  // extern "C"
