/* blake2b-128 (RFC 7693, unkeyed) — the ONE copy of the key-mint digest
 * shared by fastpath.c (ref_scalar / pk key mint) and exec.cpp (fused
 * join pair keys, columnar pk parse). Must stay byte-identical to
 * hashlib.blake2b(data, digest_size=16): every natively minted Pointer
 * has to equal the Python-minted one bit for bit (persistence,
 * multi-process determinism, fused/tuple join-path parity — pinned by
 * tests/test_native_keys.py and tests/test_join_battery.py). Plain C so
 * both the C and C++ translation units can include it; everything is
 * static — each extension carries its own copy of the code, never of
 * the logic. */

#ifndef PW_BLAKE2B_H
#define PW_BLAKE2B_H

#include <stdint.h>
#include <string.h>

static const uint64_t pw_b2b_iv[8] = {
    0x6a09e667f3bcc908ULL, 0xbb67ae8584caa73bULL, 0x3c6ef372fe94f82bULL,
    0xa54ff53a5f1d36f1ULL, 0x510e527fade682d1ULL, 0x9b05688c2b3e6c1fULL,
    0x1f83d9abfb41bd6bULL, 0x5be0cd19137e2179ULL,
};

static const uint8_t pw_b2b_sigma[12][16] = {
    {0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15},
    {14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3},
    {11, 8, 12, 0, 5, 2, 15, 13, 10, 14, 3, 6, 7, 1, 9, 4},
    {7, 9, 3, 1, 13, 12, 11, 14, 2, 6, 5, 10, 4, 0, 15, 8},
    {9, 0, 5, 7, 2, 4, 10, 15, 14, 1, 11, 12, 6, 8, 3, 13},
    {2, 12, 6, 10, 0, 11, 8, 3, 4, 13, 7, 5, 15, 14, 1, 9},
    {12, 5, 1, 15, 14, 13, 4, 10, 0, 7, 6, 3, 9, 2, 8, 11},
    {13, 11, 7, 14, 12, 1, 3, 9, 5, 0, 15, 4, 8, 6, 2, 10},
    {6, 15, 14, 9, 11, 3, 0, 8, 12, 2, 13, 7, 1, 4, 10, 5},
    {10, 2, 8, 4, 7, 6, 1, 5, 15, 11, 9, 14, 3, 12, 13, 0},
    {0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15},
    {14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3},
};

#define PW_B2B_ROTR(x, n) (((x) >> (n)) | ((x) << (64 - (n))))

#define PW_B2B_G(a, b, c, d, x, y)            \
    do {                                      \
        v[a] = v[a] + v[b] + (x);             \
        v[d] = PW_B2B_ROTR(v[d] ^ v[a], 32);  \
        v[c] = v[c] + v[d];                   \
        v[b] = PW_B2B_ROTR(v[b] ^ v[c], 24);  \
        v[a] = v[a] + v[b] + (y);             \
        v[d] = PW_B2B_ROTR(v[d] ^ v[a], 16);  \
        v[c] = v[c] + v[d];                   \
        v[b] = PW_B2B_ROTR(v[b] ^ v[c], 63);  \
    } while (0)

static void pw_b2b_compress(uint64_t h[8], const unsigned char block[128],
                            uint64_t t, int last)
{
    uint64_t v[16], m[16];
    int i;
    for (i = 0; i < 16; i++) {
        uint64_t w = 0;
        int j;
        for (j = 7; j >= 0; j--)
            w = (w << 8) | block[i * 8 + j];
        m[i] = w;
    }
    for (i = 0; i < 8; i++)
        v[i] = h[i];
    for (i = 0; i < 8; i++)
        v[8 + i] = pw_b2b_iv[i];
    v[12] ^= t; /* low word of the offset counter; high word stays 0 for
                 * inputs < 2^64 bytes */
    if (last)
        v[14] = ~v[14];
    for (i = 0; i < 12; i++) {
        const uint8_t *s = pw_b2b_sigma[i];
        PW_B2B_G(0, 4, 8, 12, m[s[0]], m[s[1]]);
        PW_B2B_G(1, 5, 9, 13, m[s[2]], m[s[3]]);
        PW_B2B_G(2, 6, 10, 14, m[s[4]], m[s[5]]);
        PW_B2B_G(3, 7, 11, 15, m[s[6]], m[s[7]]);
        PW_B2B_G(0, 5, 10, 15, m[s[8]], m[s[9]]);
        PW_B2B_G(1, 6, 11, 12, m[s[10]], m[s[11]]);
        PW_B2B_G(2, 7, 8, 13, m[s[12]], m[s[13]]);
        PW_B2B_G(3, 4, 9, 14, m[s[14]], m[s[15]]);
    }
    for (i = 0; i < 8; i++)
        h[i] ^= v[i] ^ v[8 + i];
}

/* pw_b2b_digest16(out, data, n): blake2b-128 of data, no key */
static void pw_b2b_digest16(unsigned char out[16], const unsigned char *data,
                            size_t n)
{
    uint64_t h[8];
    int i;
    for (i = 0; i < 8; i++)
        h[i] = pw_b2b_iv[i];
    h[0] ^= 0x01010000ULL ^ 16ULL; /* param block: digest_len=16, fanout=1,
                                    * depth=1 */
    size_t off = 0;
    while (n - off > 128) {
        pw_b2b_compress(h, data + off, (uint64_t)(off + 128), 0);
        off += 128;
    }
    unsigned char last[128];
    size_t rem = n - off; /* 0..128; empty input -> one zero block */
    memset(last, 0, sizeof(last));
    if (rem > 0)
        memcpy(last, data + off, rem);
    pw_b2b_compress(h, last, (uint64_t)n, 1);
    for (i = 0; i < 16; i++)
        out[i] = (unsigned char)((h[i / 8] >> (8 * (i % 8))) & 0xff);
}

/* pw_b2b_digest8_u64(data, n): little-endian u64 of the blake2b-64
 * digest, no key — byte-identical to
 * int.from_bytes(hashlib.blake2b(data, digest_size=8).digest(), "little")
 * which backs procgroup.stable_shard. The digest length enters the
 * blake2b parameter block, so this is NOT a truncation of digest16. */
static uint64_t pw_b2b_digest8_u64(const unsigned char *data, size_t n)
{
    uint64_t h[8];
    int i;
    for (i = 0; i < 8; i++)
        h[i] = pw_b2b_iv[i];
    h[0] ^= 0x01010000ULL ^ 8ULL; /* param block: digest_len=8, fanout=1,
                                   * depth=1 */
    size_t off = 0;
    while (n - off > 128) {
        pw_b2b_compress(h, data + off, (uint64_t)(off + 128), 0);
        off += 128;
    }
    unsigned char last[128];
    size_t rem = n - off; /* 0..128; empty input -> one zero block */
    memset(last, 0, sizeof(last));
    if (rem > 0)
        memcpy(last, data + off, rem);
    pw_b2b_compress(h, last, (uint64_t)n, 1);
    return h[0];
}

#endif /* PW_BLAKE2B_H */
