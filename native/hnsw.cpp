// Native HNSW approximate-nearest-neighbor index — C++ core replacing the
// reference's usearch FFI (src/external_integration/usearch_integration.rs
// :20-120 — usearch runs f16-quantized storage by default; so does this
// index: vectors are stored as IEEE 754 half floats, halving resident
// memory, with queries decoded to f32 on the fly).
//
// Standard HNSW (Malkov & Yashunin): layered proximity graphs; greedy
// descent from the top layer, beam search (ef) at layer 0, and the
// paper's neighbor-selection heuristic (a candidate is linked only if it
// is closer to the new node than to any already-selected neighbor),
// which is what keeps recall high on clustered data.

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <queue>
#include <random>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

enum Metric : int32_t { COS = 0, L2SQ = 1, IP = 2 };

// -- IEEE 754 binary16 <-> binary32, portable bit manipulation ----------

inline uint16_t f32_to_f16(float f) {
    uint32_t x;
    std::memcpy(&x, &f, 4);
    const uint32_t sign = (x >> 16) & 0x8000u;
    x &= 0x7fffffffu;
    if (x >= 0x47800000u) {                       // overflow -> inf (or nan)
        return static_cast<uint16_t>(
            sign | (x > 0x7f800000u ? 0x7e00u : 0x7c00u));
    }
    if (x < 0x38800000u) {                        // subnormal / zero
        const float magic = 0.5f;
        float tmp;
        std::memcpy(&tmp, &x, 4);
        tmp += magic;
        uint32_t bits;
        std::memcpy(&bits, &tmp, 4);
        return static_cast<uint16_t>(sign | (bits - 0x3f000000u));
    }
    uint32_t rounded = x + 0x00000fffu + ((x >> 13) & 1u);
    return static_cast<uint16_t>(sign | ((rounded - 0x38000000u) >> 13));
}

inline float f16_to_f32(uint16_t h) {
    const uint32_t sign = static_cast<uint32_t>(h & 0x8000u) << 16;
    const uint32_t em = h & 0x7fffu;
    uint32_t x;
    if (em >= 0x7c00u) {                          // inf / nan
        x = sign | 0x7f800000u | (static_cast<uint32_t>(em & 0x3ffu) << 13);
    } else if (em == 0) {
        x = sign;
    } else if (em < 0x0400u) {                    // subnormal
        int32_t e = -1;
        uint32_t m = em;
        do {
            ++e;
            m <<= 1;
        } while ((m & 0x0400u) == 0);
        x = sign | (static_cast<uint32_t>(127 - 15 - e) << 23) |
            (static_cast<uint32_t>(m & 0x3ffu) << 13);
    } else {
        x = sign | ((static_cast<uint32_t>(em >> 10) + 112u) << 23) |
            (static_cast<uint32_t>(em & 0x3ffu) << 13);
    }
    float f;
    std::memcpy(&f, &x, 4);
    return f;
}

struct HnswIndex {
    int32_t dim;
    Metric metric;
    int32_t M;          // max neighbors per layer (2*M at layer 0)
    int32_t ef_build;
    int32_t ef_search;
    std::mt19937_64 rng{42};

    std::vector<std::vector<uint16_t>> vecs;       // slot -> f16 vector
    std::vector<int64_t> keys;                     // slot -> user key
    std::vector<bool> alive;
    std::vector<int32_t> levels;                   // slot -> top level
    // slot -> level -> neighbor slots
    std::vector<std::vector<std::vector<int32_t>>> links;
    std::unordered_map<int64_t, int32_t> key_to_slot;
    int32_t entry = -1;
    int32_t max_level = -1;
    int64_t alive_count = 0;

    // f32 query vs f16 stored
    float dist(const float* a, const uint16_t* b) const {
        float acc = 0.f;
        switch (metric) {
            case L2SQ: {
                for (int32_t i = 0; i < dim; ++i) {
                    const float d = a[i] - f16_to_f32(b[i]);
                    acc += d * d;
                }
                return acc;
            }
            case IP:
            case COS: {  // vectors pre-normalized for COS at insert/query
                for (int32_t i = 0; i < dim; ++i)
                    acc += a[i] * f16_to_f32(b[i]);
                return -acc;  // smaller = closer
            }
        }
        return acc;
    }

    void decode(int32_t slot, std::vector<float>& out) const {
        const auto& v = vecs[static_cast<size_t>(slot)];
        out.resize(static_cast<size_t>(dim));
        for (int32_t i = 0; i < dim; ++i) out[static_cast<size_t>(i)] = f16_to_f32(v[static_cast<size_t>(i)]);
    }

    int32_t random_level() {
        const double r = std::uniform_real_distribution<double>(0.0, 1.0)(rng);
        const double ml = 1.0 / std::log(std::max(2, M));
        return static_cast<int32_t>(-std::log(r + 1e-12) * ml);
    }

    // beam search on one level; returns (dist, slot) closest-first
    void search_layer(const float* q, int32_t ep, int32_t level, int32_t ef,
                      std::vector<std::pair<float, int32_t>>& out) const {
        std::priority_queue<std::pair<float, int32_t>> best;  // max-heap
        std::priority_queue<std::pair<float, int32_t>,
                            std::vector<std::pair<float, int32_t>>,
                            std::greater<>> cand;             // min-heap
        std::unordered_set<int32_t> seen;
        const float d0 = dist(q, vecs[static_cast<size_t>(ep)].data());
        best.emplace(d0, ep);
        cand.emplace(d0, ep);
        seen.insert(ep);
        while (!cand.empty()) {
            auto [dc, c] = cand.top();
            if (dc > best.top().first && static_cast<int32_t>(best.size()) >= ef)
                break;
            cand.pop();
            for (int32_t nb : links[static_cast<size_t>(c)][static_cast<size_t>(level)]) {
                if (!seen.insert(nb).second) continue;
                const float d = dist(q, vecs[static_cast<size_t>(nb)].data());
                if (static_cast<int32_t>(best.size()) < ef ||
                    d < best.top().first) {
                    best.emplace(d, nb);
                    cand.emplace(d, nb);
                    if (static_cast<int32_t>(best.size()) > ef) best.pop();
                }
            }
        }
        out.clear();
        while (!best.empty()) {
            out.push_back(best.top());
            best.pop();
        }
        std::reverse(out.begin(), out.end());  // closest first
    }

    // Malkov & Yashunin Algorithm 4: keep a candidate only if it is
    // closer to the base than to every already-kept neighbor — spreads
    // links across clusters instead of piling onto the nearest one.
    void select_heuristic(const std::vector<std::pair<float, int32_t>>& in,
                          int32_t cap,
                          std::vector<std::pair<float, int32_t>>& out) const {
        out.clear();
        std::vector<float> cand_vec;
        std::vector<float> kept_vec;
        for (const auto& [d, c] : in) {
            if (static_cast<int32_t>(out.size()) >= cap) break;
            decode(c, cand_vec);
            bool good = true;
            for (const auto& [kd, kslot] : out) {
                (void)kd;
                const float d_ck =
                    dist(cand_vec.data(), vecs[static_cast<size_t>(kslot)].data());
                if (d_ck < d) {
                    good = false;
                    break;
                }
            }
            if (good) out.emplace_back(d, c);
        }
        // backfill with closest skipped candidates if underfull
        if (static_cast<int32_t>(out.size()) < cap) {
            std::unordered_set<int32_t> have;
            for (const auto& [d, c] : out) {
                (void)d;
                have.insert(c);
            }
            for (const auto& [d, c] : in) {
                if (static_cast<int32_t>(out.size()) >= cap) break;
                if (have.insert(c).second) out.emplace_back(d, c);
            }
        }
    }

    void connect(int32_t slot, int32_t level,
                 const std::vector<std::pair<float, int32_t>>& found) {
        const int32_t cap = level == 0 ? 2 * M : M;
        std::vector<std::pair<float, int32_t>> chosen;
        select_heuristic(found, M, chosen);
        auto& my = links[static_cast<size_t>(slot)][static_cast<size_t>(level)];
        std::vector<float> nb_vec;
        for (const auto& [d, nb] : chosen) {
            (void)d;
            my.push_back(nb);
            auto& theirs =
                links[static_cast<size_t>(nb)][static_cast<size_t>(level)];
            theirs.push_back(slot);
            if (static_cast<int32_t>(theirs.size()) > cap) {
                // re-select nb's neighborhood with the same heuristic
                decode(nb, nb_vec);
                std::vector<std::pair<float, int32_t>> cands;
                cands.reserve(theirs.size());
                for (int32_t t : theirs)
                    cands.emplace_back(
                        dist(nb_vec.data(),
                             vecs[static_cast<size_t>(t)].data()),
                        t);
                std::sort(cands.begin(), cands.end());
                std::vector<std::pair<float, int32_t>> trimmed;
                select_heuristic(cands, cap, trimmed);
                theirs.clear();
                for (const auto& [td, t] : trimmed) {
                    (void)td;
                    theirs.push_back(t);
                }
            }
        }
    }

    void add(int64_t key, const float* vec_in) {
        std::vector<float> v(vec_in, vec_in + dim);
        if (metric == COS) {
            float n = 0.f;
            for (float x : v) n += x * x;
            n = std::sqrt(n);
            if (n > 0.f)
                for (auto& x : v) x /= n;
        }
        std::vector<uint16_t> h(static_cast<size_t>(dim));
        for (int32_t i = 0; i < dim; ++i)
            h[static_cast<size_t>(i)] = f32_to_f16(v[static_cast<size_t>(i)]);
        auto it = key_to_slot.find(key);
        if (it != key_to_slot.end()) {
            // upsert: replace vector in place (links stay — acceptable ANN
            // degradation, same trade usearch makes)
            const int32_t slot = it->second;
            vecs[static_cast<size_t>(slot)] = std::move(h);
            if (!alive[static_cast<size_t>(slot)]) {
                alive[static_cast<size_t>(slot)] = true;
                ++alive_count;
            }
            return;
        }
        const int32_t slot = static_cast<int32_t>(vecs.size());
        const int32_t level = random_level();
        vecs.push_back(std::move(h));
        keys.push_back(key);
        alive.push_back(true);
        levels.push_back(level);
        links.emplace_back(static_cast<size_t>(level) + 1);
        key_to_slot[key] = slot;
        ++alive_count;

        if (entry < 0) {
            entry = slot;
            max_level = level;
            return;
        }
        const float* q = v.data();  // full-precision insert query
        int32_t ep = entry;
        std::vector<std::pair<float, int32_t>> found;
        for (int32_t lv = max_level; lv > level; --lv) {
            search_layer(q, ep, lv, 1, found);
            if (!found.empty()) ep = found[0].second;
        }
        for (int32_t lv = std::min(level, max_level); lv >= 0; --lv) {
            search_layer(q, ep, lv, ef_build, found);
            connect(slot, lv, found);
            if (!found.empty()) ep = found[0].second;
        }
        if (level > max_level) {
            max_level = level;
            entry = slot;
        }
    }

    void remove(int64_t key) {
        auto it = key_to_slot.find(key);
        if (it == key_to_slot.end()) return;
        if (alive[static_cast<size_t>(it->second)]) {
            alive[static_cast<size_t>(it->second)] = false;
            --alive_count;
        }
    }

    int64_t search(const float* q_in, int64_t k, int64_t* out_keys,
                   double* out_scores) const {
        if (entry < 0 || alive_count == 0 || k <= 0) return 0;
        std::vector<float> q(q_in, q_in + dim);
        if (metric == COS) {
            float n = 0.f;
            for (float x : q) n += x * x;
            n = std::sqrt(n);
            if (n > 0.f)
                for (auto& x : q) x /= n;
        }
        int32_t ep = entry;
        std::vector<std::pair<float, int32_t>> found;
        for (int32_t lv = max_level; lv > 0; --lv) {
            search_layer(q.data(), ep, lv, 1, found);
            if (!found.empty()) ep = found[0].second;
        }
        const int32_t ef =
            std::max<int32_t>(ef_search, static_cast<int32_t>(k) * 2);
        search_layer(q.data(), ep, 0, ef, found);
        int64_t out_n = 0;
        for (const auto& [d, slot] : found) {
            if (!alive[static_cast<size_t>(slot)]) continue;
            out_keys[out_n] = keys[static_cast<size_t>(slot)];
            // -d is the similarity for cos/ip (d = -dot) and the negated
            // squared distance for l2 — larger is better in both, matching
            // the TPU brute-force score convention
            out_scores[out_n] = -static_cast<double>(d);
            ++out_n;
            if (out_n == k) break;
        }
        return out_n;
    }
};

}  // namespace

extern "C" {

void* hnsw_new(int32_t dim, int32_t metric, int32_t M, int32_t ef_build,
               int32_t ef_search) {
    auto* h = new HnswIndex();
    h->dim = dim;
    h->metric = static_cast<Metric>(metric);
    h->M = M > 0 ? M : 16;
    h->ef_build = ef_build > 0 ? ef_build : 128;
    h->ef_search = ef_search > 0 ? ef_search : 64;
    return h;
}

void hnsw_free(void* h) { delete static_cast<HnswIndex*>(h); }

void hnsw_add(void* h, int64_t key, const float* vec) {
    static_cast<HnswIndex*>(h)->add(key, vec);
}

// batched insert: one library crossing for n contiguous rows instead of
// one per document — the graph build itself is still per-row, but the
// ctypes + argument-marshalling overhead is amortized over the batch
void hnsw_add_batch(void* h, const int64_t* keys, const float* vecs,
                    int64_t n) {
    auto* idx = static_cast<HnswIndex*>(h);
    for (int64_t i = 0; i < n; ++i) {
        idx->add(keys[i], vecs + i * idx->dim);
    }
}

void hnsw_remove(void* h, int64_t key) {
    static_cast<HnswIndex*>(h)->remove(key);
}

int64_t hnsw_len(void* h) { return static_cast<HnswIndex*>(h)->alive_count; }

int64_t hnsw_search(void* h, const float* q, int64_t k, int64_t* out_keys,
                    double* out_scores) {
    return static_cast<HnswIndex*>(h)->search(q, k, out_keys, out_scores);
}

}  // extern "C"
