/* Native engine fast path — CPython C-API implementations of the
 * per-row hot loops of the incremental engine (profiling: freeze_row,
 * consolidate and key-byte building dominate the Python engine's
 * wordcount profile). The reference keeps these loops in Rust
 * (src/engine/dataflow.rs arrangements, value.rs key hashing); here they
 * are a C extension bound through pathway_tpu.native.
 *
 * Exposed functions:
 *   consolidate(deltas)        -> list[(key,row,diff)] summed, zero-dropped
 *   freeze_rows(rows)          -> list of hashable stand-ins (fast path:
 *                                 row already hashable -> returned as-is)
 *   value_bytes(args_tuple)    -> bytes — the injective length-prefixed
 *                                 serialization behind ref_scalar
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <string.h>

/* -- helpers ----------------------------------------------------------- */

static PyObject *freeze_value_py = NULL; /* python fallback for exotic values */

static PyObject *
freeze_one(PyObject *v)
{
    /* fast path: hashable scalars pass through unchanged */
    Py_hash_t h = PyObject_Hash(v);
    if (h != -1 || !PyErr_Occurred()) {
        Py_INCREF(v);
        return v;
    }
    PyErr_Clear();
    if (freeze_value_py == NULL) {
        PyObject *mod = PyImport_ImportModule("pathway_tpu.engine.stream");
        if (mod == NULL)
            return NULL;
        freeze_value_py = PyObject_GetAttrString(mod, "freeze_value");
        Py_DECREF(mod);
        if (freeze_value_py == NULL)
            return NULL;
    }
    return PyObject_CallOneArg(freeze_value_py, v);
}

static PyObject *
freeze_row_c(PyObject *row)
{
    Py_hash_t h = PyObject_Hash(row);
    if (h != -1 || !PyErr_Occurred()) {
        Py_INCREF(row);
        return row;
    }
    PyErr_Clear();
    if (!PyTuple_Check(row)) {
        return freeze_one(row);
    }
    Py_ssize_t n = PyTuple_GET_SIZE(row);
    PyObject *out = PyTuple_New(n);
    if (out == NULL)
        return NULL;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *fv = freeze_one(PyTuple_GET_ITEM(row, i));
        if (fv == NULL) {
            Py_DECREF(out);
            return NULL;
        }
        PyTuple_SET_ITEM(out, i, fv);
    }
    return out;
}

/* -- consolidate -------------------------------------------------------- */

static PyObject *
fast_consolidate(PyObject *self, PyObject *arg)
{
    PyObject *seq = PySequence_Fast(arg, "consolidate expects a sequence");
    if (seq == NULL)
        return NULL;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);

    /* ident(key, frozen_row) -> [key, row, diff] */
    PyObject *acc = PyDict_New();
    PyObject *order = PyList_New(0); /* deterministic output order */
    if (acc == NULL || order == NULL)
        goto fail;

    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *delta = PySequence_Fast_GET_ITEM(seq, i);
        if (!PyTuple_Check(delta) || PyTuple_GET_SIZE(delta) != 3) {
            PyErr_SetString(PyExc_TypeError,
                            "delta must be (key, row, diff)");
            goto fail;
        }
        PyObject *key = PyTuple_GET_ITEM(delta, 0);
        PyObject *row = PyTuple_GET_ITEM(delta, 1);
        PyObject *diff = PyTuple_GET_ITEM(delta, 2);

        PyObject *frow = freeze_row_c(row);
        if (frow == NULL)
            goto fail;
        PyObject *ident = PyTuple_Pack(2, key, frow);
        Py_DECREF(frow);
        if (ident == NULL)
            goto fail;

        PyObject *slot = PyDict_GetItemWithError(acc, ident);
        if (slot == NULL && PyErr_Occurred()) {
            Py_DECREF(ident);
            goto fail;
        }
        if (slot == NULL) {
            slot = PyList_New(3);
            if (slot == NULL) {
                Py_DECREF(ident);
                goto fail;
            }
            Py_INCREF(key);
            PyList_SET_ITEM(slot, 0, key);
            Py_INCREF(row);
            PyList_SET_ITEM(slot, 1, row);
            Py_INCREF(diff);
            PyList_SET_ITEM(slot, 2, diff);
            if (PyDict_SetItem(acc, ident, slot) < 0 ||
                PyList_Append(order, slot) < 0) {
                Py_DECREF(slot);
                Py_DECREF(ident);
                goto fail;
            }
            Py_DECREF(slot);
        } else {
            PyObject *cur = PyList_GET_ITEM(slot, 2);
            PyObject *sum = PyNumber_Add(cur, diff);
            if (sum == NULL) {
                Py_DECREF(ident);
                goto fail;
            }
            PyList_SetItem(slot, 2, sum); /* steals sum */
        }
        Py_DECREF(ident);
    }

    PyObject *result = PyList_New(0);
    if (result == NULL)
        goto fail;
    Py_ssize_t m = PyList_GET_SIZE(order);
    for (Py_ssize_t i = 0; i < m; i++) {
        PyObject *slot = PyList_GET_ITEM(order, i);
        PyObject *diff = PyList_GET_ITEM(slot, 2);
        int nz = PyObject_IsTrue(diff);
        if (nz < 0) {
            Py_DECREF(result);
            goto fail;
        }
        if (nz) {
            PyObject *t = PyTuple_Pack(3, PyList_GET_ITEM(slot, 0),
                                       PyList_GET_ITEM(slot, 1), diff);
            if (t == NULL || PyList_Append(result, t) < 0) {
                Py_XDECREF(t);
                Py_DECREF(result);
                goto fail;
            }
            Py_DECREF(t);
        }
    }
    Py_DECREF(acc);
    Py_DECREF(order);
    Py_DECREF(seq);
    return result;

fail:
    Py_XDECREF(acc);
    Py_XDECREF(order);
    Py_DECREF(seq);
    return NULL;
}

/* -- freeze_rows -------------------------------------------------------- */

static PyObject *
fast_freeze_rows(PyObject *self, PyObject *arg)
{
    PyObject *seq = PySequence_Fast(arg, "freeze_rows expects a sequence");
    if (seq == NULL)
        return NULL;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    PyObject *out = PyList_New(n);
    if (out == NULL) {
        Py_DECREF(seq);
        return NULL;
    }
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *f = freeze_row_c(PySequence_Fast_GET_ITEM(seq, i));
        if (f == NULL) {
            Py_DECREF(out);
            Py_DECREF(seq);
            return NULL;
        }
        PyList_SET_ITEM(out, i, f);
    }
    Py_DECREF(seq);
    return out;
}

/* -- value_bytes: injective serialization for ref_scalar ---------------- */

typedef struct {
    char *buf;
    Py_ssize_t len;
    Py_ssize_t cap;
} Buf;

static int
buf_ensure(Buf *b, Py_ssize_t extra)
{
    if (b->len + extra <= b->cap)
        return 0;
    Py_ssize_t ncap = b->cap * 2;
    while (ncap < b->len + extra)
        ncap *= 2;
    char *nb = PyMem_Realloc(b->buf, ncap);
    if (nb == NULL) {
        PyErr_NoMemory();
        return -1;
    }
    b->buf = nb;
    b->cap = ncap;
    return 0;
}

static int
buf_put(Buf *b, const void *data, Py_ssize_t n)
{
    if (buf_ensure(b, n) < 0)
        return -1;
    memcpy(b->buf + b->len, data, n);
    b->len += n;
    return 0;
}

static int
buf_put_u32(Buf *b, uint32_t v)
{
    /* explicit little-endian: key bytes must be identical to the Python
     * path's struct.pack('<I') on every host (api.py requires keys stable
     * across processes for persistence / multi-host determinism) */
    unsigned char le[4] = {
        (unsigned char)(v & 0xff),
        (unsigned char)((v >> 8) & 0xff),
        (unsigned char)((v >> 16) & 0xff),
        (unsigned char)((v >> 24) & 0xff),
    };
    return buf_put(b, le, 4);
}

static int
buf_put_f64_le(Buf *b, double d)
{
    /* matches struct.pack('<d'): IEEE-754 bits emitted little-endian */
    uint64_t bits;
    memcpy(&bits, &d, 8);
    unsigned char le[8];
    for (int i = 0; i < 8; i++)
        le[i] = (unsigned char)((bits >> (8 * i)) & 0xff);
    return buf_put(b, le, 8);
}

static PyObject *value_to_bytes_py = NULL; /* python fallback */

static int
serialize_value(Buf *b, PyObject *v)
{
    /* mirrors pathway_tpu.internals.api._value_to_bytes for the scalar
     * fast paths; composite/exotic values defer to the Python function */
    if (v == Py_None)
        return buf_put(b, "\x00", 1);
    if (PyBool_Check(v)) {
        char t[2] = {'B', v == Py_True ? 1 : 0};
        return buf_put(b, t, 2);
    }
    if (PyFloat_Check(v)) {
        double d = PyFloat_AS_DOUBLE(v);
        if (buf_put(b, "F", 1) < 0)
            return -1;
        return buf_put_f64_le(b, d);
    }
    if (PyUnicode_Check(v)) {
        Py_ssize_t n;
        const char *s = PyUnicode_AsUTF8AndSize(v, &n);
        if (s == NULL)
            return -1;
        if (buf_put(b, "S", 1) < 0)
            return -1;
        return buf_put(b, s, n);
    }
    if (PyBytes_Check(v)) {
        if (buf_put(b, "Y", 1) < 0)
            return -1;
        return buf_put(b, PyBytes_AS_STRING(v), PyBytes_GET_SIZE(v));
    }
    /* ints (incl. Pointer subclass) and everything else -> python impl */
    if (value_to_bytes_py == NULL) {
        PyObject *mod = PyImport_ImportModule("pathway_tpu.internals.api");
        if (mod == NULL)
            return -1;
        value_to_bytes_py = PyObject_GetAttrString(mod, "_value_to_bytes");
        Py_DECREF(mod);
        if (value_to_bytes_py == NULL)
            return -1;
    }
    PyObject *bytes = PyObject_CallOneArg(value_to_bytes_py, v);
    if (bytes == NULL)
        return -1;
    int rc = buf_put(b, PyBytes_AS_STRING(bytes), PyBytes_GET_SIZE(bytes));
    Py_DECREF(bytes);
    return rc;
}

static PyObject *
fast_value_bytes(PyObject *self, PyObject *args_tuple)
{
    if (!PyTuple_Check(args_tuple)) {
        PyErr_SetString(PyExc_TypeError, "value_bytes expects a tuple");
        return NULL;
    }
    Py_ssize_t n = PyTuple_GET_SIZE(args_tuple);
    Buf b = {PyMem_Malloc(256), 0, 256};
    if (b.buf == NULL)
        return PyErr_NoMemory();
    if (buf_put_u32(&b, (uint32_t)n) < 0)
        goto fail;
    for (Py_ssize_t i = 0; i < n; i++) {
        /* length-prefix each serialized value (injective concat) */
        Py_ssize_t mark = b.len;
        if (buf_put_u32(&b, 0) < 0)
            goto fail;
        if (serialize_value(&b, PyTuple_GET_ITEM(args_tuple, i)) < 0)
            goto fail;
        uint32_t plen = (uint32_t)(b.len - mark - 4);
        memcpy(b.buf + mark, &plen, 4);
    }
    PyObject *out = PyBytes_FromStringAndSize(b.buf, b.len);
    PyMem_Free(b.buf);
    return out;
fail:
    PyMem_Free(b.buf);
    return NULL;
}

/* -- integer int path for serialize (avoid python fallback for ints) ---- */

/* module def ------------------------------------------------------------ */

static PyMethodDef methods[] = {
    {"consolidate", fast_consolidate, METH_O,
     "Sum multiplicities of identical (key,row) pairs, drop zeros."},
    {"freeze_rows", fast_freeze_rows, METH_O,
     "Hashable stand-ins for a batch of rows."},
    {"value_bytes", fast_value_bytes, METH_O,
     "Injective length-prefixed serialization of a value tuple."},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "fastpath",
    "Native engine fast path (consolidate/freeze/key bytes).", -1, methods,
};

PyMODINIT_FUNC
PyInit_fastpath(void)
{
    return PyModule_Create(&moduledef);
}
